# Convenience targets mirroring the reference's Makefile surface
# (all / benchmarking / tune / clean — reference Makefile:1-29).  The real
# build is standard Python packaging (pyproject.toml); the native host
# engine compiles itself lazily (capital_tpu/native/__init__.py).

PY ?= python

.PHONY: all test benchmarking bench-explicit bench-small bench-blocktri \
	bench-blocktri-par bench-arrowhead bench-update bench-refine \
	bench-session tune audit lint lint-concurrency robust serve-smoke \
	serve-bench serve-replicas serve-trace native clean

all: test

test:
	$(PY) -m pytest tests/ -x -q

# the reference's `make benchmarking` builds the bench drivers; here they
# are modules — run the whole driver suite on small shapes as a smoke
benchmarking:
	$(PY) -m capital_tpu.bench suite --n 1024 --m 8192 --k 256

# explicit-path constant tracker (docs/DISTRIBUTED.md "2.33x -> parity"):
# bench the explicit cholinv schedule and its persistent tile-cyclic
# spelling, appending unified ledger rows (measured + model copy-bytes +
# audit) so the BENCH/MULTICHIP trajectories carry the closure instead of
# it living only in docs.  Smoke shapes here; the flagship row on a TPU is
# --n 16384 --devices 1 (round-4 constant: 35.4 vs 68.0 TF/s).
bench-explicit:
	$(PY) -m capital_tpu.bench cholinv --n 1024 --mode explicit \
		--validate --ledger bench_explicit.jsonl
	$(PY) -m capital_tpu.bench cholinv --n 1024 --mode explicit \
		--balance tile_cyclic_persistent --devices 4 \
		--validate --ledger bench_explicit.jsonl

tune:
	$(PY) -m capital_tpu.autotune cholinv --n 2048 --out autotune_out

# small-N latency smoke (docs/PERF.md round 7): the batched-grid posv and
# lstsq buckets in --latency mode, per-dispatch p50/p95/p99 wall_ms on the
# CPU interpret rig, one bench:latency ledger record each.  The absolute
# numbers are emulation artifacts; what this pins is that the latency
# protocol, the fused kernels, and the ledger schema all work end to end.
bench-small:
	$(PY) -m capital_tpu.bench posv --platform cpu --n 32 --batch 4 \
		--nrhs 2 --dtype float32 --latency --calls 8 \
		--small-impl pallas --validate --ledger bench_small.jsonl
	$(PY) -m capital_tpu.bench lstsq --platform cpu --n 32 --batch 4 \
		--nrhs 2 --dtype float32 --latency --calls 8 \
		--small-impl pallas --validate --ledger bench_small.jsonl

# block-tridiagonal fast-path gate (docs/PERF.md round 11): the flagship
# (nblocks=64, b=128, f32) chain vs the SAME problems assembled dense at
# n=8192, gated at >= 25x per-problem wall-clock speedup with factor AND
# solve residuals held to the dense f32 tolerance — the structural
# O(n·b³) vs O(n³) win measured, not asserted.  CPU rig: the driver
# resolves 'auto' to the xla scan off-TPU (interpret-pallas would
# measure the emulator, not the algorithm).  The second row pins the
# --latency protocol + the bench:blocktri_latency ledger seam on a
# small validated shape.
bench-blocktri:
	rm -f bench_blocktri.jsonl
	$(PY) -m capital_tpu.bench blocktri --platform cpu --dtype float32 \
		--nblocks 64 --block 128 --batch 1 --nrhs 1 --validate \
		--min-speedup 25 --ledger bench_blocktri.jsonl
	$(PY) -m capital_tpu.bench blocktri --platform cpu --dtype float32 \
		--nblocks 8 --block 16 --batch 4 --nrhs 2 --latency --calls 8 \
		--validate --ledger bench_blocktri.jsonl

# block-arrowhead fast-path gate (docs/PERF.md round 15): the flagship
# (nblocks=64, b=128, s=32, f32) bordered chain vs the SAME problems
# assembled dense at n=8224, gated at >= 10x per-problem wall-clock
# speedup — lower than bench-blocktri's 25x ON PURPOSE: the arrowhead
# pays the widened chain solve (s extra columns every sweep) plus the
# Schur completion on top of the chain factor, so its structural margin
# is real but thinner.  The driver's f64-NumPy-side factor AND solve
# residual gates are always-on (no --validate flag to forget).  The
# second row pins the --latency protocol + the bench:arrowhead_latency
# ledger seam on a small shape.
bench-arrowhead:
	rm -f bench_arrowhead.jsonl
	$(PY) -m capital_tpu.bench arrowhead --platform cpu --dtype float32 \
		--nblocks 64 --block 128 --border 32 --batch 1 --nrhs 1 \
		--min-speedup 10 --ledger bench_arrowhead.jsonl
	$(PY) -m capital_tpu.bench arrowhead --platform cpu --dtype float32 \
		--nblocks 8 --block 16 --border 4 --batch 4 --nrhs 2 \
		--latency --calls 8 --ledger bench_arrowhead.jsonl

# parallel chain factorization gate (docs/PERF.md round 13): the
# partitioned (Spike) blocktri driver A/B'd against the sequential scan
# on the same problems.  On this 1-core rig the wall-clock columns are
# informational; the GATE is the jaxpr sequential scan-depth reduction
# (192 -> 45 trips at nblocks=64, P=8: >= 4x) plus pinned residual
# parity vs the sequential impl — both properties of the compiled
# program, honest regardless of core count.  The __graft_entry__ dry run
# then certifies the partitioned path on a real 8-device mesh (one chain
# per device, batch·P interiors distributed) with its own residual gate.
bench-blocktri-par:
	rm -f bench_blocktri_par.jsonl
	$(PY) -m capital_tpu.bench blocktri --platform cpu --dtype float32 \
		--nblocks 64 --block 16 --batch 2 --nrhs 2 --impl partitioned \
		--validate --min-depth-reduction 4 \
		--ledger bench_blocktri_par.jsonl
	$(PY) __graft_entry__.py

# online factor-maintenance gate (docs/PERF.md round 12): rank-k Cholesky
# update at the flagship serve shape (n=1024, k=16) vs refactor-from-
# resident-state — the honest cache-less alternative: the server already
# holds R, so the baseline reassembles S = RᵀR + VVᵀ and refactors
# (docs/PERF.md spells out why vs client-shipped-A the ratio would be
# smaller).  Gated at >= 5x per-problem wall-clock with f64-NumPy-side
# update AND downdate residuals held to tolerance, plus a 50-request
# serve smoke with mixed chol_update/posv_cached traffic gated on
# residency hit-rate >= 0.9 and zero steady-state recompiles
# (serve/factorcache.py).  obs serve-report re-gates the ledger record's
# factor_cache block — fails loudly if no record carries it.
bench-update:
	rm -f bench_update.jsonl
	$(PY) -m capital_tpu.bench update --platform cpu --n 1024 --k 16 \
		--batch 2 --dtype float32 --iters 5 --validate \
		--min-speedup 5 --min-hit-rate 0.9 --ledger bench_update.jsonl
	$(PY) -m capital_tpu.obs serve-report bench_update.jsonl \
		--min-residency-hit-rate 0.85
# (0.85, not the driver's 0.9: the record's factor_cache block carries
# LIFETIME counters, so the engine's per-bucket warmup lookups dilute the
# steady-state 0.92 the driver gates on delta counters)

# mixed-precision iterative-refinement gate (docs/PERF.md round 14): the
# guaranteed-tier posv program (f32 factor + f64 Wilkinson sweeps) vs the
# straight f64 factor on cond ~1e5 masters.  The speedup gate is on the
# FACTOR PHASE (f32 vs f64 potrf, >= 1.5x — measured ~1.9x, this rig's
# whole f32:f64 LAPACK gap); end-to-end latency rides the record ungated
# because on CPU the sweeps price in at XLA's ~2.4 GFLOP/s skinny-RHS
# potrs and land the ratio below 1 — docs/PERF.md round 14 owns that
# honesty note.  The accuracy half IS gated: refined backward error
# <= 10x the straight f64 factor's (measured ~0.9-1.8x) and <= the
# absolute f64 tolerance, all problems converged, plus the cond-1e12
# TSQR escalation probe (ortho <= 1e-13) and the mixed-tier serve smoke
# at zero steady-state recompiles.  obs serve-report then re-gates the
# smoke's request_stats record: sweep cap and converged fraction from
# the refine block (fails loudly if no record carries one).
bench-refine:
	rm -f bench_refine.jsonl
	$(PY) -m capital_tpu.bench refine --platform cpu --n 1024 --nrhs 4 \
		--batch 4 --dtype float64 --iters 3 --validate \
		--min-speedup 1.5 --max-resid-ratio 10 \
		--ledger bench_refine.jsonl
	$(PY) -m capital_tpu.obs serve-report bench_refine.jsonl \
		--max-refine-iters 6 --min-converged-frac 0.99

# streaming-session gate (docs/SERVING.md "Streaming sessions", round 19):
# the sliding-window steady-state cycle — extend(slide) onto the resident
# chain factor + contract(slide), a pure slice — vs refactoring the whole
# nblocks window, the only move a cache-less server has.  Gated >= 5x at
# the flagship geometry (structural ~nblocks/slide = 8x; measured ~9x on
# this rig), with always-on f64-NumPy residual gates on the MARGINALIZED
# slid window (head D <- L_k L_k^T — a wrong marginalization blows the
# gate) and the bitwise replay pin (extend-replay of the truncated chain
# == the contracted factor, max |delta| exactly 0).  The 50-request mixed
# session workload (bursty arrivals, long-tail lifetimes, all three
# accuracy tiers) then gates session hit-rate >= 0.85 post-warmup and
# zero steady-state recompiles; obs serve-report re-gates the ledger's
# serve:session_stats record — fails loudly if no record carries it.
bench-session:
	rm -f bench_session.jsonl
	$(PY) -m capital_tpu.bench session --platform cpu --dtype float32 \
		--nblocks 64 --block 128 --slide 8 --batch 1 --nrhs 2 \
		--iters 5 --min-speedup 5 --min-hit-rate 0.85 \
		--ledger bench_session.jsonl
	$(PY) -m capital_tpu.obs serve-report bench_session.jsonl \
		--min-session-hit-rate 0.85 --max-reseeds 0

# model-vs-compiled drift gate on the flagship configs (docs/OBSERVABILITY.md);
# compile-only — runs in CI without a TPU (exit non-zero on drift).  The
# bench.trace step is the phase-attribution gate: it decomposes a real
# (small-shape) cholinv wall into per-phase seconds, fails if the
# unattributed bubble fraction blows the budget OR if nothing could be
# attributed at all (dead-gate protection), and re-gates the ledger record
# through obs trace-report — the same double-entry discipline as lint.
# The generous 0.995 bound absorbs CPU-interpret emulation; what it pins
# is that attribution works end to end.
audit: serve-smoke serve-bench serve-replicas serve-trace bench-blocktri \
	bench-blocktri-par bench-arrowhead bench-update bench-refine \
	bench-session lint
	$(PY) -m capital_tpu.obs audit cholinv --n 4096 --platform cpu
	$(PY) -m capital_tpu.obs audit cacqr --m 16384 --n 512 --platform cpu
	$(PY) -m capital_tpu.obs robust-gate --platform cpu
	rm -f bench_trace.jsonl
	$(PY) -m capital_tpu.bench.trace cholinv --n 768 --bc 256 \
		--dtype float32 --iters 2 --platform cpu \
		--max-bubble-frac 0.995 --ledger bench_trace.jsonl
	$(PY) -m capital_tpu.obs trace-report bench_trace.jsonl \
		--max-bubble-frac 0.995

# static analysis gate (docs/STATIC_ANALYSIS.md): the program sanitizer over
# the flagship cholinv/cacqr/serve-bucket entry points (phase coverage,
# donation, cache-key hygiene, host sync, dtype drift, collective budget)
# plus the AST source lint, each appending one lint:report ledger record
# that `obs lint-report` re-gates — compile-only, no TPU needed
lint:
	rm -f lint_report.jsonl
	$(PY) -m capital_tpu.lint program --platform cpu \
		--ledger lint_report.jsonl
	$(PY) -m capital_tpu.lint source capital_tpu \
		--fail-on warn --ledger lint_report.jsonl
	$(PY) -m capital_tpu.lint concurrency --schedules 200 \
		--ledger lint_report.jsonl
	$(PY) -m capital_tpu.obs lint-report lint_report.jsonl \
		--require-pass program --require-pass source \
		--require-pass concurrency

# concurrency sanitizer alone (docs/STATIC_ANALYSIS.md "Concurrency
# sanitizer"): the guarded-by/lock-order static pass over the serve host
# plane plus the seeded interleaving explorer (>= 4 scenarios x 200
# schedules, every lint/invariants.py identity checked after every step)
# and the seeded-fault self-check that proves the gate is alive
lint-concurrency:
	$(PY) -m capital_tpu.lint concurrency --schedules 200

# serving self-check (docs/SERVING.md): mixed-bucket CPU workload through
# the SolveEngine, one serve:request_stats ledger record, gated on 100%
# post-warmup cache hit-rate (zero steady-state recompiles) + the pinned
# per-request residual gates inside the smoke itself.  --max-p99-ms-small
# gates the small-N (batched-grid pallas) request tail; the generous bound
# absorbs CPU-interpret emulation — what it pins is that the small path ran
# and reported (the gate fails loudly if no latency_ms_small block exists).
# The SECOND smoke is the cold-start proof: same workload, same (now warm)
# persistent cache dir, --max-compiles 0 — every executable must
# deserialize from disk, zero fresh XLA compiles (serve/cache.py).
# --max-queue-wait-ms fails loudly if no record carries the queue-wait /
# device latency split (serve/stats.py)
serve-smoke:
	rm -f serve_smoke.jsonl
	rm -rf serve_cache
	$(PY) -m capital_tpu.serve smoke --platform cpu --requests 50 \
		--persist-dir serve_cache --ledger serve_smoke.jsonl
	$(PY) -m capital_tpu.serve smoke --platform cpu --requests 50 \
		--persist-dir serve_cache --max-compiles 0 \
		--ledger serve_smoke.jsonl
	$(PY) -m capital_tpu.obs serve-report serve_smoke.jsonl \
		--min-hit-rate 1.0 --max-p99-ms-small 30000 \
		--max-queue-wait-ms 30000

# continuous-vs-sync A/B (docs/SERVING.md, docs/PERF.md): the fixed-seed
# closed-loop workload through both schedulers, one request_stats record
# per mode carrying the loadgen block (QPS, speedup) and the queue-wait /
# device split, gated on occupancy + zero steady-state recompiles via
# serve-report.  No speedup gate here: on shared CI hardware the overlap
# win is real but its magnitude is noisy — the record carries it, PERF.md
# tracks it
serve-bench:
	rm -f serve_bench.jsonl
	$(PY) -m capital_tpu.serve loadgen --platform cpu --requests 160 \
		--concurrency 16 --ledger serve_bench.jsonl
	$(PY) -m capital_tpu.obs serve-report serve_bench.jsonl \
		--min-hit-rate 1.0 --min-occupancy 0.25 \
		--max-queue-wait-ms 60000

# multi-replica serving smoke (docs/SERVING.md "Multi-replica serving"):
# 2 replicas behind the router sharing one persistent cache dir.  The COLD
# run warms the shared disk tier and proves the failure paths: an induced
# replica kill (in-flight requests re-dispatched, the replacement replica
# warms from disk, not by compiling) and an induced drain + resume under
# load — gated inside the smoke on zero dropped requests and zero
# steady-state recompiles.  The WARM run re-runs drain-only with
# --max-compiles 0: every replica must deserialize its whole ladder from
# the shared dir.  serve-report --aggregate then re-gates the ledger:
# >= 2 distinct replica tags (the it-really-was-multi-replica check) and
# aggregate hit-rate 1.0 across the merged records
serve-replicas:
	rm -f serve_replicas.jsonl
	rm -rf serve_replicas_cache
	$(PY) -m capital_tpu.serve replicas --platform cpu --replicas 2 \
		--requests 48 --persist-dir serve_replicas_cache \
		--kill-one --drain-one --ledger serve_replicas.jsonl
	$(PY) -m capital_tpu.serve replicas --platform cpu --replicas 2 \
		--requests 48 --persist-dir serve_replicas_cache \
		--drain-one --max-compiles 0 --ledger serve_replicas.jsonl
	$(PY) -m capital_tpu.obs serve-report serve_replicas.jsonl \
		--aggregate --min-replicas 2 --min-hit-rate 1.0

# per-request tracing + live-window telemetry gate (docs/OBSERVABILITY.md
# "Per-request tracing and live windows"): the smoke under --trace must
# land 100% complete monotonic span chains (admit -> ... -> respond) under
# the pinned 25 ms bubble tolerance — gated in-run AND re-gated from the
# ledger by serve-report (double-entry, same discipline as lint).  The
# loadgen leg runs both schedulers with 0.2 s rolling windows and a 60 s
# deadline, gated on >= 3 serve:window records whose internal coherence
# (percentile ordering, histogram/count sums) validate_serve_window pins
# on every read.  obs timeline then proves the chrome-trace export path
# end to end — it exits non-zero on an empty or malformed trace ledger,
# so a silently-dead producer can never pass
serve-trace:
	rm -f serve_trace.jsonl serve_trace_chrome.json
	$(PY) -m capital_tpu.serve smoke --platform cpu --requests 42 \
		--trace --bubble-tol-ms 25 --ledger serve_trace.jsonl
	$(PY) -m capital_tpu.serve loadgen --platform cpu --requests 120 \
		--concurrency 8 --window-s 0.2 --min-windows 3 \
		--deadline-ms 60000 --trace --ledger serve_trace.jsonl
	$(PY) -m capital_tpu.obs serve-report serve_trace.jsonl \
		--min-trace-complete 1.0 --min-windows 3
	$(PY) -m capital_tpu.obs timeline serve_trace.jsonl \
		--chrome serve_trace_chrome.json

# breakdown detection / shifted-CholeskyQR recovery / fault-injection suite
# (docs/ROBUSTNESS.md); CPU rig — tests/conftest.py provides the 8-device
# virtual mesh and enables x64
robust:
	$(PY) -m pytest tests/test_robust.py tests/test_faultinject.py -q

native:
	$(PY) -c "from capital_tpu import native; print('native engine available:', native.available())"

clean:
	rm -rf autotune_out .pytest_cache bench_explicit.jsonl serve_smoke.jsonl \
		lint_report.jsonl bench_small.jsonl serve_bench.jsonl serve_cache \
		bench_trace.jsonl serve_replicas.jsonl serve_replicas_cache \
		bench_blocktri.jsonl bench_update.jsonl bench_refine.jsonl \
		bench_arrowhead.jsonl serve_trace.jsonl serve_trace_chrome.json \
		bench_session.jsonl
	find . -name __pycache__ -type d -exec rm -rf {} +
