"""Batched-grid small-N kernel layer tests (ISSUE 6 acceptance).

The properties pinned here, mapped to the issue's criteria:

* the ops/batched_small kernels match the vmap-over-LAPACK reference
  across bucket ladders, both uplos, f32 and bf16 (TestKernelsVsReference);
* identity-tail-padded batches (a serve flush's fill problems) produce
  exact-zero tail solutions with info == 0 (TestIdentityTail);
* fused posv/lstsq compile to ONE pallas_call per bucket batch, the split
  variant to two — asserted on the traced program (TestFusion);
* an injected NaN in one problem of a fused batch corrupts only that
  problem's info/solution: the in-program O(n^2) breakdown checks survive
  fusion (TestFaultContainment);
* the engine's small_n_impl switch routes buckets through the kernels with
  the zero-recompile invariant intact, and the stats split
  (requests_small / latency_ms_small) appears exactly when small-bucket
  traffic happened (TestEngineSmall, TestStatsSmall);
* `obs serve-report --max-p99-ms-small` gates the small tail and fails
  loudly when requested against records with no small block
  (TestServeReportSmallGate);
* tune_small runs under run_sweep with resumable checkpoints and the
  per-bucket wall_ms percentiles ride SweepResult.extra and the ledger
  (TestTuneSmall);
* the bench posv/lstsq --latency drivers emit bench:latency records
  (TestBenchSmallCLI) and the lint targets for the bucket programs pass
  the trace-side rules (TestLintTargets).

Everything runs on the conftest CPU rig: x64 is on, so the f64->vmap
dispatch rule is itself load-bearing here — tests that want the kernels
say float32 explicitly.  interpret=None resolves to interpret mode off-TPU,
so tier-1 executes the actual kernel bodies.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from capital_tpu.lint import rules as lint_rules
from capital_tpu.lint import targets as lint_targets
from capital_tpu.lint.program import sanitize
from capital_tpu.obs import __main__ as obs_main
from capital_tpu.obs import ledger
from capital_tpu.ops import batched_small
from capital_tpu.parallel.topology import Grid
from capital_tpu.serve import ServeConfig, SolveEngine, api, stats


def _spd_batch(rng, batch, n, dtype=np.float32):
    X = rng.standard_normal((batch, n, n))
    A = X @ X.transpose(0, 2, 1) / n + 3.0 * np.eye(n)
    return A.astype(dtype)


def _grid1():
    return Grid.square(c=1, devices=jax.devices()[:1])


# ---------------------------------------------------------------------------
# dispatch plumbing
# ---------------------------------------------------------------------------


class TestDispatch:
    def test_pick_block_divides(self):
        assert batched_small.pick_block(16) == 8
        assert batched_small.pick_block(12) == 4
        assert batched_small.pick_block(7) == 1

    def test_default_impl_routes_small_f32_posv_to_pallas(self):
        assert batched_small.default_impl(
            "posv", (4, 64, 64), (4, 64, 2), jnp.float32) == "pallas"

    def test_default_impl_large_n_goes_vmap(self):
        n = batched_small.SMALL_N_MAX * 2
        assert batched_small.default_impl(
            "posv", (4, n, n), (4, n, 2), jnp.float32) == "vmap"

    def test_default_impl_f64_goes_vmap(self):
        # the kernels compute f32; routing an f64 bucket through them would
        # silently downgrade precision — always LAPACK
        assert batched_small.default_impl(
            "posv", (4, 32, 32), (4, 32, 2), jnp.float64) == "vmap"

    def test_default_impl_inv_goes_vmap(self):
        assert batched_small.default_impl(
            "inv", (4, 32, 32), None, jnp.float32) == "vmap"

    def test_eligible_lstsq_rows_not_batch(self):
        # eligible() receives BATCHED (batch, m, n) shapes: the per-problem
        # VMEM need must be driven by the row count m = a_shape[-2], not the
        # bucket capacity.  interpret=False forces the hardware gate the CPU
        # rig's interpret bypass would skip.
        tall = 1 << 20  # ~256 MiB of f32 A rows: beyond any VMEM budget
        assert not batched_small.eligible(
            "lstsq", (8, tall, 64), (8, tall, 2), jnp.float32,
            interpret=False)
        # a large-capacity bucket of short problems stays eligible — the
        # batch axis rides the grid, one problem resident at a time
        assert batched_small.eligible(
            "lstsq", (65536, 64, 64), (65536, 64, 2), jnp.float32,
            interpret=False)

    def test_default_impl_tall_lstsq_goes_vmap(self):
        # a tall-m lstsq bucket passes the n <= SMALL_N_MAX check; the VMEM
        # gate must still route it to vmap under hardware resolution
        tall = 1 << 20
        assert batched_small.default_impl(
            "lstsq", (8, tall, 64), (8, tall, 2), jnp.float32,
            interpret=False) == "vmap"
        assert batched_small.default_impl(
            "lstsq", (65536, 64, 64), (65536, 64, 2), jnp.float32,
            interpret=False) == "pallas"

    def test_forced_pallas_f64_falls_back_to_vmap(self):
        # forcing impl='pallas' must not skip the dtype guard: an f64 batch
        # takes the vmap program (full precision), bit-identical to
        # impl='vmap', not an f32 kernel pass behind f64-labeled outputs
        rng = np.random.default_rng(7)
        A = jnp.asarray(_spd_batch(rng, 2, 16, dtype=np.float64))
        B = jnp.asarray(rng.standard_normal((2, 16, 2)))
        assert A.dtype == jnp.float64
        for impl in ("pallas", "pallas_split"):
            X, info = api.batched("posv", impl=impl)(A, B)
            Xr, infor = api.batched("posv", impl="vmap")(A, B)
            assert X.dtype == jnp.float64
            np.testing.assert_array_equal(np.asarray(X), np.asarray(Xr))
            np.testing.assert_array_equal(np.asarray(info),
                                          np.asarray(infor))

    def test_api_batched_rejects_unknown_impl(self):
        with pytest.raises(ValueError, match="impl"):
            api.batched("posv", impl="fortran")

    def test_engine_rejects_unknown_impl(self):
        with pytest.raises(ValueError, match="small_n_impl"):
            SolveEngine(cfg=ServeConfig(small_n_impl="fortran"))

    def test_small_n_impl_is_part_of_cache_identity(self):
        e1 = SolveEngine(cfg=ServeConfig(small_n_impl="vmap"))
        e2 = SolveEngine(cfg=ServeConfig(small_n_impl="pallas"))
        assert e1._cfg_hash != e2._cfg_hash


# ---------------------------------------------------------------------------
# kernels vs reference
# ---------------------------------------------------------------------------


class TestKernelsVsReference:
    @pytest.mark.parametrize("uplo", ["U", "L"])
    @pytest.mark.parametrize("n", [16, 32, 64])
    def test_potrf_matches_numpy(self, uplo, n):
        rng = np.random.default_rng(0)
        A = _spd_batch(rng, 3, n)
        R, info = batched_small.potrf(jnp.asarray(A), uplo=uplo)
        assert np.all(np.asarray(info) == 0)
        L_ref = np.linalg.cholesky(A.astype(np.float64))
        ref = L_ref.transpose(0, 2, 1) if uplo == "U" else L_ref
        np.testing.assert_allclose(np.asarray(R), ref, atol=2e-4, rtol=2e-4)

    @pytest.mark.parametrize("uplo,trans", [
        ("U", False), ("U", True), ("L", False), ("L", True),
    ])
    def test_trsm_matches_solve(self, uplo, trans):
        rng = np.random.default_rng(1)
        n, k = 16, 3
        T = rng.standard_normal((2, n, n)) * 0.1 + 2.0 * np.eye(n)
        T = (np.triu(T) if uplo == "U" else np.tril(T)).astype(np.float32)
        B = rng.standard_normal((2, n, k)).astype(np.float32)
        X = batched_small.trsm(
            jnp.asarray(T), jnp.asarray(B), uplo=uplo, trans=trans)
        op = T.transpose(0, 2, 1) if trans else T
        ref = np.linalg.solve(op.astype(np.float64), B.astype(np.float64))
        np.testing.assert_allclose(np.asarray(X), ref, atol=1e-4, rtol=1e-4)

    @pytest.mark.parametrize("n", [16, 32])
    def test_posv_matches_vmap_reference(self, n):
        rng = np.random.default_rng(2)
        A = _spd_batch(rng, 4, n)
        B = rng.standard_normal((4, n, 2)).astype(np.float32)
        a, b = jnp.asarray(A), jnp.asarray(B)
        X, info = batched_small.posv(a, b)
        assert np.all(np.asarray(info) == 0)
        Xv, _ = api.batched("posv", impl="vmap")(a, b)
        np.testing.assert_allclose(
            np.asarray(X), np.asarray(Xv), atol=5e-4, rtol=5e-4)

    @pytest.mark.parametrize("n", [16, 32])
    def test_lstsq_matches_numpy(self, n):
        rng = np.random.default_rng(3)
        m = 4 * n
        A = rng.standard_normal((3, m, n)).astype(np.float32)
        B = rng.standard_normal((3, m, 2)).astype(np.float32)
        X, info = batched_small.lstsq(jnp.asarray(A), jnp.asarray(B))
        assert np.all(np.asarray(info) == 0)
        for i in range(3):
            ref = np.linalg.lstsq(
                A[i].astype(np.float64), B[i].astype(np.float64), rcond=None
            )[0]
            np.testing.assert_allclose(
                np.asarray(X)[i], ref, atol=2e-3, rtol=2e-3)

    def test_posv_bf16(self):
        rng = np.random.default_rng(4)
        n = 16
        A = _spd_batch(rng, 2, n)
        B = rng.standard_normal((2, n, 1)).astype(np.float32)
        a = jnp.asarray(A, jnp.bfloat16)
        b = jnp.asarray(B, jnp.bfloat16)
        X, info = batched_small.posv(a, b)
        assert X.dtype == jnp.bfloat16
        assert np.all(np.asarray(info) == 0)
        ref = np.linalg.solve(A.astype(np.float64), B.astype(np.float64))
        err = np.max(np.abs(np.asarray(X, np.float64) - ref))
        assert err < 0.15  # bf16 storage; the kernel computes f32

    @pytest.mark.parametrize("block", [1, 2, 4, 8])
    def test_block_knob_is_correctness_neutral(self, block):
        rng = np.random.default_rng(5)
        n = 16
        A = _spd_batch(rng, 2, n)
        B = rng.standard_normal((2, n, 1)).astype(np.float32)
        X, info = batched_small.posv(
            jnp.asarray(A), jnp.asarray(B), block=block)
        assert np.all(np.asarray(info) == 0)
        ref = np.linalg.solve(A.astype(np.float64), B.astype(np.float64))
        np.testing.assert_allclose(np.asarray(X), ref, atol=5e-4, rtol=5e-4)


# ---------------------------------------------------------------------------
# identity-tail exactness (the serve flush mixture)
# ---------------------------------------------------------------------------


class TestIdentityTail:
    def test_posv_identity_tail_exact(self):
        rng = np.random.default_rng(6)
        n, batch, real = 16, 4, 2
        A = _spd_batch(rng, batch, n)
        B = rng.standard_normal((batch, n, 2)).astype(np.float32)
        A[real:] = np.eye(n, dtype=np.float32)
        B[real:] = 0.0
        X, info = batched_small.posv(jnp.asarray(A), jnp.asarray(B))
        assert np.all(np.asarray(info) == 0)
        # identity operand, zero RHS -> bitwise-zero solutions: the tail
        # problems a bucket flush pads with cost nothing and leak nothing
        assert np.all(np.asarray(X)[real:] == 0.0)

    def test_lstsq_identity_tail_exact(self):
        rng = np.random.default_rng(7)
        n, m, batch, real = 16, 64, 4, 3
        A = rng.standard_normal((batch, m, n)).astype(np.float32)
        B = rng.standard_normal((batch, m, 2)).astype(np.float32)
        A[real:] = np.eye(m, n, dtype=np.float32)
        B[real:] = 0.0
        X, info = batched_small.lstsq(jnp.asarray(A), jnp.asarray(B))
        assert np.all(np.asarray(info) == 0)
        assert np.all(np.asarray(X)[real:] == 0.0)


# ---------------------------------------------------------------------------
# fusion: one pallas_call per bucket batch
# ---------------------------------------------------------------------------


class TestFusion:
    def _shapes(self, n=16, batch=4, nrhs=2, m=None):
        dt = jnp.float32
        a = jax.ShapeDtypeStruct((batch, m or n, n), dt)
        b = jax.ShapeDtypeStruct((batch, m or n, nrhs), dt)
        return a, b

    def test_fused_posv_is_one_pallas_call(self):
        a, b = self._shapes()
        jaxpr = str(jax.make_jaxpr(api.batched("posv", impl="pallas"))(a, b))
        assert jaxpr.count("pallas_call") == 1

    def test_fused_lstsq_is_one_pallas_call(self):
        a, b = self._shapes(m=64)
        jaxpr = str(jax.make_jaxpr(api.batched("lstsq", impl="pallas"))(a, b))
        assert jaxpr.count("pallas_call") == 1

    def test_split_posv_is_two_pallas_calls(self):
        a, b = self._shapes()
        jaxpr = str(
            jax.make_jaxpr(api.batched("posv", impl="pallas_split"))(a, b))
        assert jaxpr.count("pallas_call") == 2

    def test_auto_resolves_pallas_for_small_f32(self):
        a, b = self._shapes()
        jaxpr = str(jax.make_jaxpr(api.batched("posv"))(a, b))
        assert jaxpr.count("pallas_call") == 1

    def test_auto_resolves_vmap_for_f64(self):
        dt = jnp.float64
        a = jax.ShapeDtypeStruct((4, 16, 16), dt)
        b = jax.ShapeDtypeStruct((4, 16, 2), dt)
        jaxpr = str(jax.make_jaxpr(api.batched("posv"))(a, b))
        assert jaxpr.count("pallas_call") == 0


# ---------------------------------------------------------------------------
# fault containment through fusion
# ---------------------------------------------------------------------------


class TestFaultContainment:
    def test_nan_in_one_problem_flags_only_that_info(self):
        rng = np.random.default_rng(8)
        n, batch = 16, 4
        A = _spd_batch(rng, batch, n)
        B = rng.standard_normal((batch, n, 1)).astype(np.float32)
        A[1, 3, 3] = np.nan
        X, info = batched_small.posv(jnp.asarray(A), jnp.asarray(B))
        info = np.asarray(info)
        assert info[1] != 0
        assert np.all(info[[0, 2, 3]] == 0)
        ref = np.linalg.solve(
            A[[0, 2, 3]].astype(np.float64), B[[0, 2, 3]].astype(np.float64))
        np.testing.assert_allclose(
            np.asarray(X)[[0, 2, 3]], ref, atol=5e-4, rtol=5e-4)

    def test_nan_in_one_lstsq_problem_contained(self):
        rng = np.random.default_rng(9)
        n, m, batch = 16, 64, 3
        A = rng.standard_normal((batch, m, n)).astype(np.float32)
        B = rng.standard_normal((batch, m, 1)).astype(np.float32)
        A[0, 0, 0] = np.nan
        X, info = batched_small.lstsq(jnp.asarray(A), jnp.asarray(B))
        info = np.asarray(info)
        assert info[0] != 0
        assert np.all(info[1:] == 0)
        assert np.all(np.isfinite(np.asarray(X)[1:]))


# ---------------------------------------------------------------------------
# engine integration: small_n_impl routing + zero-recompile + stats split
# ---------------------------------------------------------------------------

SMALL_CFG = ServeConfig(
    buckets=(8, 16),
    rows_buckets=(32, 64),
    nrhs_buckets=(1, 4),
    max_batch=3,
    max_delay_s=10.0,
)


class TestEngineSmall:
    def _workload(self, eng, count=9, n=16, dtype=np.float32, seed=10):
        rng = np.random.default_rng(seed)
        tickets = []
        for _ in range(count):
            A = _spd_batch(rng, 1, n, dtype)[0]
            b = rng.standard_normal((n, 1)).astype(dtype)
            tickets.append((eng.submit("posv", A, b), A, b))
        eng.drain()
        return tickets

    def test_pallas_engine_matches_reference_zero_recompiles(self):
        import dataclasses

        eng = SolveEngine(
            cfg=dataclasses.replace(SMALL_CFG, small_n_impl="pallas"))
        # warmup pass populates the AOT cache for the one bucket shape
        self._workload(eng, count=3)
        warm = eng.cache_stats()
        tickets = self._workload(eng, count=9, seed=11)
        cs = eng.cache_stats()
        assert cs["misses"] == warm["misses"]  # zero steady-state recompiles
        assert cs["hit_rate"] == 1.0 or cs["hits"] > warm["hits"]
        for t, A, b in tickets:
            r = t.result()
            assert r.ok
            ref = np.linalg.solve(A.astype(np.float64), b.astype(np.float64))
            np.testing.assert_allclose(
                np.asarray(r.x), ref, atol=5e-4, rtol=5e-4)
        snap = eng.stats.snapshot(eng.cache_stats())
        assert snap["requests_small"] == 12
        assert snap["latency_ms_small"]["p99"] > 0.0

    def test_vmap_engine_has_no_small_split(self):
        import dataclasses

        eng = SolveEngine(
            cfg=dataclasses.replace(SMALL_CFG, small_n_impl="vmap"))
        self._workload(eng, count=3)
        snap = eng.stats.snapshot(eng.cache_stats())
        assert "requests_small" not in snap
        assert "latency_ms_small" not in snap

    def test_auto_engine_routes_f64_vmap_f32_pallas(self):
        eng = SolveEngine(cfg=SMALL_CFG)  # small_n_impl="auto"
        self._workload(eng, count=3, dtype=np.float64)
        assert "requests_small" not in eng.stats.snapshot()
        self._workload(eng, count=3, dtype=np.float32, seed=12)
        snap = eng.stats.snapshot()
        assert snap["requests_small"] == 3


# ---------------------------------------------------------------------------
# stats + ledger schema
# ---------------------------------------------------------------------------


class TestStatsSmall:
    def test_snapshot_small_block_only_when_traffic(self):
        c = stats.Collector()
        c.record_request("posv", 0.01, ok=True)
        assert "latency_ms_small" not in c.snapshot()
        c.record_request("posv", 0.02, ok=True, small=True)
        snap = c.snapshot()
        assert snap["requests_small"] == 1
        assert snap["latency_ms_small"]["p50"] == 20.0

    def test_validate_accepts_small_block(self):
        c = stats.Collector()
        c.record_request("posv", 0.01, ok=True, small=True)
        assert ledger.validate_request_stats(c.snapshot()) == []

    def test_validate_rejects_malformed_small_block(self):
        c = stats.Collector()
        c.record_request("posv", 0.01, ok=True, small=True)
        snap = c.snapshot()
        snap["latency_ms_small"] = {"p50": "fast"}
        assert ledger.validate_request_stats(snap) != []
        snap = c.snapshot()
        snap["requests_small"] = True
        assert ledger.validate_request_stats(snap) != []


class TestServeReportSmallGate:
    def _emit(self, path, small_p99_s=None):
        c = stats.Collector()
        c.record_request("posv", 0.01, ok=True)
        if small_p99_s is not None:
            c.record_request("posv", small_p99_s, ok=True, small=True)
        c.emit(str(path), cache={"hits": 9, "misses": 0,
                                 "warmup_compiles": 3, "entries": 3,
                                 "hit_rate": 1.0})

    def test_small_gate_passes(self, tmp_path, capsys):
        path = tmp_path / "serve.jsonl"
        self._emit(path, small_p99_s=0.010)
        assert obs_main.main(["serve-report", str(path),
                              "--max-p99-ms-small", "100"]) == 0
        assert "small" in capsys.readouterr().out

    def test_small_gate_fails_on_slow_tail(self, tmp_path, capsys):
        path = tmp_path / "serve.jsonl"
        self._emit(path, small_p99_s=0.500)
        assert obs_main.main(["serve-report", str(path),
                              "--max-p99-ms-small", "100"]) == 1
        assert "small" in capsys.readouterr().err

    def test_small_gate_fails_loudly_when_block_missing(self, tmp_path,
                                                       capsys):
        # a gate that silently passes because the path under test never ran
        # is worse than no gate
        path = tmp_path / "serve.jsonl"
        self._emit(path, small_p99_s=None)
        assert obs_main.main(["serve-report", str(path),
                              "--max-p99-ms-small", "100"]) == 1
        assert "latency_ms_small" in capsys.readouterr().err

    def test_report_without_small_gate_still_ok(self, tmp_path):
        path = tmp_path / "serve.jsonl"
        self._emit(path, small_p99_s=None)
        assert obs_main.main(["serve-report", str(path)]) == 0


# ---------------------------------------------------------------------------
# latency autotune
# ---------------------------------------------------------------------------


class TestTuneSmall:
    def test_sweep_checkpoint_resume_and_ledger(self, tmp_path):
        from capital_tpu.autotune import sweep

        led = tmp_path / "tune.jsonl"
        kw = dict(
            batch=2, nrhs=1, dtype=jnp.float32,
            out_dir=str(tmp_path / "out"), occupancy=0.5, calls=2,
            warmup=1, checkpoint=True, impls=("vmap", "pallas"),
        )
        res = sweep.tune_small(_grid1(), "posv", 8, ledger=str(led), **kw)
        assert [r.seconds for r in res] == sorted(r.seconds for r in res)
        assert {r.config_id for r in res} == {"vmap", "pallas_b8"}
        for r in res:
            assert r.extra and set(r.extra["wall_ms"]) == {"p50", "p95",
                                                           "p99"}
            # wall_ms is rounded to 4 decimals for the ledger
            assert r.seconds == pytest.approx(
                r.extra["wall_ms"]["p99"] / 1e3, abs=1e-7)
        recs = ledger.read(str(led))
        assert len(recs) == 2
        for rec in recs:
            assert rec["kind"] == "autotune:small_posv"
            assert "wall_ms" in rec["measured"]
        # resume: both configs come from the checkpoint, extra intact
        res2 = sweep.tune_small(_grid1(), "posv", 8, **kw)
        assert {r.config_id for r in res2} == {"vmap", "pallas_b8"}
        for r in res2:
            assert r.extra and "wall_ms" in r.extra

    def test_occupancy_validated(self, tmp_path):
        from capital_tpu.autotune import sweep

        with pytest.raises(ValueError, match="occupancy"):
            sweep.tune_small(_grid1(), "posv", 8, occupancy=0.0,
                             out_dir=str(tmp_path))
        with pytest.raises(ValueError, match="op"):
            sweep.tune_small(_grid1(), "inv", 8, out_dir=str(tmp_path))


# ---------------------------------------------------------------------------
# bench drivers
# ---------------------------------------------------------------------------


class TestBenchSmallCLI:
    def test_posv_latency_driver_emits_ledger(self, tmp_path, capsys):
        from capital_tpu.bench import drivers

        led = tmp_path / "bench.jsonl"
        drivers.main([
            "posv", "--n", "8", "--batch", "2", "--nrhs", "1",
            "--dtype", "float32", "--latency", "--calls", "2",
            "--small-impl", "pallas", "--validate", "--ledger", str(led),
        ])
        out = capsys.readouterr().out
        assert "small_posv_latency" in out
        recs = ledger.read(str(led))
        assert len(recs) == 1
        assert recs[0]["kind"] == "bench:latency"
        assert set(recs[0]["measured"]["wall_ms"]) == {"p50", "p95", "p99"}

    def test_lstsq_throughput_driver(self, tmp_path, capsys):
        from capital_tpu.bench import drivers

        led = tmp_path / "bench.jsonl"
        drivers.main([
            "lstsq", "--n", "8", "--batch", "2", "--nrhs", "1",
            "--dtype", "float32", "--calls", "2",
            "--small-impl", "vmap", "--validate", "--ledger", str(led),
        ])
        assert "small_lstsq_tflops" in capsys.readouterr().out
        recs = ledger.read(str(led))
        assert len(recs) == 1
        assert recs[0]["kind"] == "bench:lstsq"


# ---------------------------------------------------------------------------
# lint targets
# ---------------------------------------------------------------------------


class TestLintTargets:
    def test_batched_small_targets_pass_trace_rules(self):
        tgts = lint_targets.batched_small_targets(
            n=16, rows=32, nrhs=2, capacity=2)
        assert len(tgts) == 3
        for t in tgts:
            assert t.flops_audited is False
            findings = sanitize(t, compile_program=False)
            errs = [f for f in findings if f.severity == lint_rules.ERROR]
            assert errs == [], [f.message for f in errs]

    def test_flagship_set_includes_batched_small(self):
        names = [t.name
                 for t in lint_targets.flagship_targets(["batched_small"])]
        assert any("small-posv" in n for n in names)
        assert any("small-lstsq" in n for n in names)
