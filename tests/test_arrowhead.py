"""Block-arrowhead Cholesky fast-path tests (ISSUE 15 acceptance).

The properties pinned here, mapped to the issue's criteria:

* posv matches the dense reference on the assembled arrowhead across
  geometry ladders, xla f64 and pallas f32, and the sequential scan and
  the partitioned Spike chain drivers produce matching answers UNDER THE
  BORDER SOLVE — the widened-chain design's whole point (TestParity);
* schur()'s corner factor reconstructs an f64 NumPy-side Schur reference
  (the bench-arrowhead factor gate's seam), assemble/pack/unpack round-
  trip, and the bordered-banded adapter solves to dense-NumPy parity on
  both band storage forms (TestParity, TestBordered);
* breakdown infos land in whole-matrix LAPACK coordinates: chain pivots
  pass through in [1, n_T], corner pivots are offset past n_T
  (docs/ROBUSTNESS.md corner-pivot note), healthy problems report 0 and
  batch neighbors stay contained (TestInfo);
* the serve pad is structure-safe: appended identity chain blocks leave
  the real solution BITWISE unchanged (chain-length padding is inert —
  the PR-10 contract extended to the bordered op), in-block / border /
  nrhs pads are tight, fill problems solve to exact zeros (TestPadding);
* the engine buckets posv_arrowhead on its three ladders with the
  zero-recompile invariant, counts it in request_stats.ops, keeps
  border_buckets in the config hash, flattens the two-part solution
  into the documented (n_T + s, k) response, and routes oversize
  geometry through the single path (TestServeArrowhead);
* bench:arrowhead ledger records validate structurally — malformed ones
  are LedgerIncompatible and a speedup row without its residual proof
  bundle is rejected (TestLedgerSeam);
* the AH::* phases are registered with executed-flop helpers and
  estimate_seconds prices refine sweeps from the serve stats feed — the
  round-15 cost-model satellite (TestTracing).

Same rig notes as test_blocktri: conftest CPU, x64 on, f32 asked for
explicitly when the pallas kernels are the point.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from capital_tpu.models import arrowhead, banded
from capital_tpu.obs import ledger
from capital_tpu.serve import ServeConfig, SolveEngine, batching
from capital_tpu.utils import tracing

# Small ladders so every executable compiles fast (the BT_CFG posture)
# plus the new border ladder.
AH_CFG = ServeConfig(
    buckets=(8, 16),
    rows_buckets=(32,),
    nrhs_buckets=(1, 4),
    max_batch=2,
    max_delay_s=10.0,
    nblocks_buckets=(2, 4),
    block_buckets=(4, 8),
    border_buckets=(2, 4),
)


def _arrow(rng, batch, nblocks, b, s, k, dtype=np.float64):
    """A well-conditioned SPD arrowhead + RHS (the driver recipe: the
    blocktri chain family, border coupling shrinking with chain length,
    corner with a 5I margin)."""
    G = rng.standard_normal((batch, nblocks, b, b))
    D = G @ G.transpose(0, 1, 3, 2) / b + 3.0 * np.eye(b)
    C = 0.3 / np.sqrt(b) * rng.standard_normal((batch, nblocks, b, b))
    C[:, 0] = 0.0
    F = 0.3 / np.sqrt(nblocks * b) * rng.standard_normal(
        (batch, nblocks, s, b))
    S0 = rng.standard_normal((batch, s, s))
    S = S0 @ S0.transpose(0, 2, 1) / s + 5.0 * np.eye(s)
    B = rng.standard_normal((batch, nblocks, b, k))
    Bs = rng.standard_normal((batch, s, k))
    return tuple(x.astype(dtype) for x in (D, C, F, S, B, Bs))


def _np_dense(D, C, F, S):
    """NumPy-side dense assembly of ONE problem's arrowhead — independent
    of arrowhead.assemble (the bench-driver discipline)."""
    nblocks, b = D.shape[0], D.shape[1]
    s = F.shape[1]
    n_t = nblocks * b
    A = np.zeros((n_t + s, n_t + s), dtype=np.float64)
    for i in range(nblocks):
        sl = slice(i * b, (i + 1) * b)
        A[sl, sl] = D[i]
        if i:
            up = slice((i - 1) * b, i * b)
            A[sl, up] = C[i]
            A[up, sl] = C[i].T
        A[n_t:, sl] = F[i]
        A[sl, n_t:] = F[i].T
    A[n_t:, n_t:] = S
    return A


def _dense_solve(D, C, F, S, B, Bs):
    """f64 flat dense reference (batch, n_T + s, k)."""
    out = []
    for j in range(D.shape[0]):
        A = _np_dense(*(np.asarray(o[j], np.float64) for o in (D, C, F, S)))
        rhs = np.concatenate(
            [np.asarray(B[j], np.float64).reshape(-1, B.shape[-1]),
             np.asarray(Bs[j], np.float64)])
        out.append(np.linalg.solve(A, rhs))
    return np.stack(out)


def _posv(D, C, F, S, B, Bs, **kw):
    return arrowhead.posv(*(jnp.asarray(o) for o in (D, C, F, S, B, Bs)),
                          **kw)


def _flat(X, Xs):
    X, Xs = np.asarray(X), np.asarray(Xs)
    return np.concatenate(
        [X.reshape(X.shape[0], -1, X.shape[-1]), Xs], axis=1)


# ---------------------------------------------------------------------------
# numerical parity: arrowhead vs dense, scan vs partitioned
# ---------------------------------------------------------------------------


class TestParity:
    @pytest.mark.parametrize("nblocks,b,s", [(2, 3, 1), (4, 4, 3),
                                             (6, 8, 5)])
    def test_posv_matches_dense_xla_f64(self, nblocks, b, s):
        rng = np.random.default_rng(50)
        ops = _arrow(rng, 2, nblocks, b, s, 2)
        X, Xs, info = _posv(*ops, impl="xla")
        assert np.all(np.asarray(info) == 0)
        ref = _dense_solve(*ops)
        assert np.abs(_flat(X, Xs) - ref).max() < 1e-11 * np.abs(ref).max()

    def test_posv_matches_dense_pallas_f32(self):
        rng = np.random.default_rng(51)
        ops = _arrow(rng, 2, 4, 8, 3, 2, dtype=np.float32)
        X, Xs, info = _posv(*ops, impl="pallas")
        assert np.all(np.asarray(info) == 0)
        ref = _dense_solve(*ops)
        assert np.abs(_flat(X, Xs) - ref).max() < 5e-5 * np.abs(ref).max()

    def test_scan_and_partitioned_match(self):
        # the acceptance criterion: the partitioned Spike chain driver
        # serves the border columns too (the ONE widened solve design),
        # and both impls land the same answers
        rng = np.random.default_rng(52)
        ops = _arrow(rng, 2, 16, 4, 3, 2)
        Xa, Xsa, ia = _posv(*ops, impl="xla")
        Xb, Xsb, ib = _posv(*ops, impl="partitioned", partitions=4,
                            partition_inner="xla")
        np.testing.assert_array_equal(np.asarray(ia), np.asarray(ib))
        a, b_ = _flat(Xa, Xsa), _flat(Xb, Xsb)
        assert np.abs(a - b_).max() < 1e-11 * np.abs(a).max()
        ref = _dense_solve(*ops)
        assert np.abs(b_ - ref).max() < 1e-11 * np.abs(ref).max()

    def test_schur_matches_numpy_reference(self):
        # the bench-arrowhead factor gate's seam: L_S·L_Sᵀ reconstructs
        # an f64 Schur complement built WITHOUT models code
        rng = np.random.default_rng(53)
        D, C, F, S, _, _ = _arrow(rng, 2, 3, 4, 3, 1)
        Zb, St, Ls, info = arrowhead.schur(
            jnp.asarray(D), jnp.asarray(C), jnp.asarray(F), jnp.asarray(S),
            impl="xla")
        assert np.all(np.asarray(info) == 0)
        for j in range(2):
            A = _np_dense(D[j], C[j], F[j], S[j])
            n_t = 12
            ref = S[j] - A[n_t:, :n_t] @ np.linalg.solve(
                A[:n_t, :n_t], A[:n_t, n_t:])
            L = np.asarray(Ls)[j]
            assert np.abs(L @ L.T - ref).max() < 1e-11

    def test_assemble_matches_numpy(self):
        rng = np.random.default_rng(54)
        D, C, F, S, _, _ = _arrow(rng, 1, 3, 2, 2, 1)
        A = arrowhead.assemble(jnp.asarray(D), jnp.asarray(C),
                               jnp.asarray(F), jnp.asarray(S))
        np.testing.assert_allclose(np.asarray(A)[0],
                                   _np_dense(D[0], C[0], F[0], S[0]),
                                   rtol=0, atol=0)

    def test_pack_unpack_roundtrip(self):
        rng = np.random.default_rng(55)
        _, _, F, S, B, Bs = _arrow(rng, 2, 3, 4, 2, 3)
        P = arrowhead.pack(jnp.asarray(F), jnp.asarray(S),
                           jnp.asarray(B), jnp.asarray(Bs))
        assert P.shape == (2, 3 * 4 + 2, 2 + 3)
        F2, S2, B2, Bs2 = arrowhead.unpack(P, 3, 4)
        for a, b_ in ((F, F2), (S, S2), (B, B2), (Bs, Bs2)):
            np.testing.assert_array_equal(a, np.asarray(b_))

    def test_unpack_rejects_bad_geometry(self):
        with pytest.raises(ValueError, match="cannot carry"):
            arrowhead.unpack(jnp.zeros((1, 10, 3)), 3, 4)


# ---------------------------------------------------------------------------
# breakdown coordinates: chain pass-through, corner offset
# ---------------------------------------------------------------------------


class TestInfo:
    def test_corner_pivot_offset_past_chain(self):
        # poison the corner only: the chain factors clean, the corner
        # cholesky fails, and the combined info lands PAST n_T in
        # whole-matrix coordinates (jax NaN-fills the failed factor, so
        # the exact corner index is the first corner diagonal — the
        # blocktri xla convention; the pinned property is the offset)
        rng = np.random.default_rng(60)
        D, C, F, S, B, Bs = _arrow(rng, 1, 3, 4, 3, 1)
        S[0] = np.diag([4.0, -50.0, 4.0])
        F[0] = 0.0
        X, Xs, info = _posv(D, C, F, S, B, Bs, impl="xla")
        n_t = 12
        assert n_t < int(info[0]) <= n_t + 3 + 1

    def test_chain_pivot_passes_through(self):
        # poison chain block 1 with a zeroed incoming coupling: the
        # arrowhead info is the blocktri info verbatim (the (0, n_T)
        # window is exact) and stays <= n_T
        rng = np.random.default_rng(61)
        D, C, F, S, B, Bs = _arrow(rng, 1, 3, 4, 3, 1)
        D[0, 1] = np.diag([1.0, 1.0, -5.0, 1.0])
        C[0, 1] = 0.0
        C[0, 2] = 0.0
        X, Xs, info = _posv(D, C, F, S, B, Bs, impl="xla")
        assert 4 < int(info[0]) <= 8

    def test_bad_problem_contained_in_batch(self):
        rng = np.random.default_rng(62)
        D, C, F, S, B, Bs = _arrow(rng, 2, 3, 4, 2, 2)
        S[1] = -np.eye(2)
        X, Xs, info = _posv(D, C, F, S, B, Bs, impl="xla")
        info = np.asarray(info)
        assert info[0] == 0 and info[1] > 12
        ref = _dense_solve(D[:1], C[:1], F[:1], S[:1], B[:1], Bs[:1])
        got = _flat(X, Xs)[:1]
        assert np.abs(got - ref).max() < 1e-11 * np.abs(ref).max()


# ---------------------------------------------------------------------------
# serve padding contract
# ---------------------------------------------------------------------------


def _bucket(nbb, bb, sb, kb, dtype="float64", cap=2):
    return batching.Bucket("posv_arrowhead", dtype, (2, nbb, bb, bb),
                           (nbb * bb + sb, sb + kb), cap)


class TestPadding:
    def test_appended_chain_blocks_are_bitwise_inert(self):
        # same b/s/k, nblocks 3 -> 4: trailing identity chain blocks with
        # ZERO border columns never feed the sweeps or the Schur
        # reduction's accumulation prefix, so the cropped solution is
        # BITWISE the unpadded one (the PR-10 chain contract extended
        # through the border solve and the completion gemms)
        rng = np.random.default_rng(63)
        D, C, F, S, B, Bs = _arrow(rng, 1, 3, 4, 2, 2)
        A = jnp.asarray(np.stack([D[0], C[0]]))
        P = arrowhead.pack(jnp.asarray(F), jnp.asarray(S),
                           jnp.asarray(B), jnp.asarray(Bs))[0]
        bucket = _bucket(4, 4, 2, 2)
        pa, pp = batching.pad_operands("posv_arrowhead", A, P, bucket)
        Fp, Sp, Bp, Bsp = arrowhead.unpack(pp[None], 4, 4)
        Xp, Xsp, ip = arrowhead.posv(pa[None, 0], pa[None, 1], Fp, Sp,
                                     Bp, Bsp, impl="xla")
        X0, Xs0, i0 = _posv(D, C, F, S, B, Bs, impl="xla")
        Xc = batching.crop("posv_arrowhead", Xp[0], A.shape, P.shape)
        np.testing.assert_array_equal(np.asarray(Xc), np.asarray(X0)[0])
        np.testing.assert_array_equal(np.asarray(Xsp)[0], np.asarray(Xs0)[0])
        # the identity tail solves to exact zeros, info stays clean
        np.testing.assert_array_equal(np.asarray(Xp)[0, 3:], 0.0)
        assert int(ip[0]) == int(i0[0]) == 0

    def test_block_border_nrhs_pad_is_tight(self):
        # b 3 -> 4, s 2 -> 4, k 1 -> 4, nblocks 3 -> 4 all at once:
        # identity embeds everywhere, the padded operand stays a valid
        # SPD arrowhead, and the cropped solution matches the dense
        # reference tightly (not bitwise: contraction lengths change)
        rng = np.random.default_rng(64)
        D, C, F, S, B, Bs = _arrow(rng, 1, 3, 3, 2, 1)
        A = jnp.asarray(np.stack([D[0], C[0]]))
        P = arrowhead.pack(jnp.asarray(F), jnp.asarray(S),
                           jnp.asarray(B), jnp.asarray(Bs))[0]
        bucket = _bucket(4, 4, 4, 4)
        pa, pp = batching.pad_operands("posv_arrowhead", A, P, bucket)
        # chain blocks completed to diag(D_i, I), appended block pure I
        np.testing.assert_array_equal(np.asarray(pa)[0, 0, 3, :],
                                      np.eye(4)[3])
        np.testing.assert_array_equal(np.asarray(pa)[0, 3], np.eye(4))
        Fp, Sp, Bp, Bsp = arrowhead.unpack(pp[None], 4, 4)
        # corner embedded as diag(S, I), border zero over all padding
        np.testing.assert_array_equal(np.asarray(Sp)[0, 2:, 2:], np.eye(2))
        np.testing.assert_array_equal(np.asarray(Sp)[0, :2, 2:], 0.0)
        np.testing.assert_array_equal(np.asarray(Fp)[0, :, 2:], 0.0)
        np.testing.assert_array_equal(np.asarray(Fp)[0, :, :, 3], 0.0)
        np.testing.assert_array_equal(np.asarray(Fp)[0, 3], 0.0)
        Xp, Xsp, ip = arrowhead.posv(pa[None, 0], pa[None, 1], Fp, Sp,
                                     Bp, Bsp, impl="xla")
        assert int(ip[0]) == 0
        Xc = batching.crop("posv_arrowhead", Xp[0], A.shape, P.shape)
        ref = _dense_solve(D, C, F, S, B, Bs)[0]
        got = np.concatenate([np.asarray(Xc).reshape(9, 1),
                              np.asarray(Xsp)[0, :2, :1]])
        np.testing.assert_allclose(got, ref, rtol=0, atol=1e-12)

    def test_fill_problem_is_identity_arrowhead(self):
        bucket = _bucket(4, 4, 2, 2)
        fa, fb = batching.fill_problem(bucket)
        np.testing.assert_array_equal(np.asarray(fa)[0],
                                      np.broadcast_to(np.eye(4), (4, 4, 4)))
        np.testing.assert_array_equal(np.asarray(fa)[1], 0.0)
        F, S, B, Bs = arrowhead.unpack(fb[None], 4, 4)
        np.testing.assert_array_equal(np.asarray(S)[0], np.eye(2))
        np.testing.assert_array_equal(np.asarray(F), 0.0)
        X, Xs, info = arrowhead.posv(fa[None, 0], fa[None, 1], F, S, B, Bs,
                                     impl="xla")
        np.testing.assert_array_equal(np.asarray(X), 0.0)
        np.testing.assert_array_equal(np.asarray(Xs), 0.0)
        assert int(info[0]) == 0


# ---------------------------------------------------------------------------
# serve engine: bucketing, zero-recompile, flat response, config hash
# ---------------------------------------------------------------------------


def _submit_ops(rng, nblocks, b, s, k):
    D, C, F, S, B, Bs = _arrow(rng, 1, nblocks, b, s, k)
    A = np.stack([D[0], C[0]])
    P = np.asarray(arrowhead.pack(jnp.asarray(F), jnp.asarray(S),
                                  jnp.asarray(B), jnp.asarray(Bs))[0])
    ref = _dense_solve(D, C, F, S, B, Bs)[0]
    return A, P, ref


class TestServeArrowhead:
    def test_engine_matches_dense_flat_response(self):
        rng = np.random.default_rng(65)
        A, P, ref = _submit_ops(rng, 2, 3, 2, 1)
        eng = SolveEngine(cfg=AH_CFG)
        r = eng.solve("posv_arrowhead", A, P)
        assert r.ok and r.batched and r.bucket is not None
        assert np.asarray(r.x).shape == (2 * 3 + 2, 1)
        np.testing.assert_allclose(np.asarray(r.x), ref, rtol=0, atol=1e-10)

    def test_same_bucket_zero_recompile(self):
        # (2, 3, 2) and (2, 4, 1) geometries land in the same
        # (2, 4, 2)-bucket: one compile, then steady-state hits
        rng = np.random.default_rng(66)
        eng = SolveEngine(cfg=AH_CFG)
        for b, s in ((3, 2), (4, 1)):
            A, P, ref = _submit_ops(rng, 2, b, s, 1)
            r = eng.solve("posv_arrowhead", A, P)
            assert r.ok
            np.testing.assert_allclose(np.asarray(r.x), ref,
                                       rtol=0, atol=1e-10)
        c = eng.cache_stats()
        assert (c["hits"], c["misses"]) == (1, 1)
        assert eng.stats.ops["posv_arrowhead"] == 2

    def test_submit_validation(self):
        eng = SolveEngine(cfg=AH_CFG)
        with pytest.raises(ValueError, match="chain pack"):
            eng.submit("posv_arrowhead", np.zeros((3, 2, 4, 4)),
                       np.zeros((10, 3)))
        with pytest.raises(ValueError, match="packed tail"):
            eng.submit("posv_arrowhead", np.zeros((2, 2, 4, 4)),
                       np.zeros((8, 3)))

    def test_border_ladder_joins_config_hash(self):
        e1 = SolveEngine(cfg=AH_CFG)
        e2 = SolveEngine(cfg=ServeConfig(
            buckets=AH_CFG.buckets, rows_buckets=AH_CFG.rows_buckets,
            nrhs_buckets=AH_CFG.nrhs_buckets, max_batch=AH_CFG.max_batch,
            max_delay_s=AH_CFG.max_delay_s,
            nblocks_buckets=AH_CFG.nblocks_buckets,
            block_buckets=AH_CFG.block_buckets,
            border_buckets=(2, 8),
        ))
        assert e1._cfg_hash != e2._cfg_hash

    def test_oversize_routes_single(self):
        # border past the ladder: unbatched single route, same flat
        # client-visible layout, still correct
        rng = np.random.default_rng(67)
        A, P, ref = _submit_ops(rng, 2, 3, 6, 1)
        eng = SolveEngine(cfg=AH_CFG)
        r = eng.solve("posv_arrowhead", A, P)
        assert r.ok and not r.batched and r.bucket is None
        assert np.asarray(r.x).shape == ref.shape
        np.testing.assert_allclose(np.asarray(r.x), ref, rtol=0, atol=1e-10)


# ---------------------------------------------------------------------------
# bordered-banded adapter (models/banded.solveh_bordered)
# ---------------------------------------------------------------------------


class TestBordered:
    def _system(self, rng, n=23, u=2, s=3, k=2):
        A = np.zeros((n, n))
        for d in range(1, u + 1):
            v = 0.3 * rng.standard_normal(n - d)
            A[np.arange(n - d) + d, np.arange(n - d)] = v
            A[np.arange(n - d), np.arange(n - d) + d] = v
        A[np.diag_indices(n)] = 4.0 + rng.random(n)
        ab = np.zeros((u + 1, n))
        for d in range(u + 1):
            ab[d, :n - d] = A[np.arange(n - d) + d, np.arange(n - d)]
        B = 0.2 * rng.standard_normal((s, n))
        S0 = rng.standard_normal((s, s))
        S = S0 @ S0.T / s + 5.0 * np.eye(s)
        rhs = rng.standard_normal((n, k))
        rhs_c = rng.standard_normal((s, k))
        full = np.block([[A, B.T], [B, S]])
        ref = np.linalg.solve(full, np.concatenate([rhs, rhs_c]))
        return ab, B, S, rhs, rhs_c, ref

    def test_matches_dense_numpy_both_forms(self):
        rng = np.random.default_rng(70)
        ab, B, S, rhs, rhs_c, ref = self._system(rng)
        u, n = ab.shape[0] - 1, ab.shape[1]
        ab_up = np.zeros_like(ab)
        for d in range(u + 1):
            ab_up[u - d, d:] = ab[d, :n - d]
        for lower, a in ((True, ab), (False, ab_up)):
            x, xs = banded.solveh_bordered(jnp.asarray(a), B, S, rhs,
                                           rhs_c, lower=lower)
            got = np.concatenate([np.asarray(x), np.asarray(xs)])
            assert np.abs(got - ref).max() < 1e-11

    def test_vector_rhs_roundtrip(self):
        rng = np.random.default_rng(71)
        ab, B, S, rhs, rhs_c, ref = self._system(rng, k=1)
        x, xs = banded.solveh_bordered(jnp.asarray(ab), B, S, rhs[:, 0],
                                       rhs_c[:, 0], lower=True)
        assert x.shape == (23,) and xs.shape == (3,)
        got = np.concatenate([np.asarray(x), np.asarray(xs)])
        assert np.abs(got - ref[:, 0]).max() < 1e-11

    def test_corner_breakdown_reports_unpadded_order(self):
        rng = np.random.default_rng(72)
        ab, B, S, rhs, rhs_c, _ = self._system(rng)
        Sbad = S.copy()
        Sbad[0, 0] = -99.0
        with pytest.raises(ValueError, match="order 24"):
            banded.solveh_bordered(jnp.asarray(ab), B, Sbad, rhs, rhs_c,
                                   lower=True)

    def test_border_shape_validated(self):
        rng = np.random.default_rng(73)
        ab, B, S, rhs, rhs_c, _ = self._system(rng)
        with pytest.raises(ValueError, match="dense rows"):
            banded.solveh_bordered(jnp.asarray(ab), B[:, :-1], S, rhs,
                                   rhs_c, lower=True)


# ---------------------------------------------------------------------------
# ledger seam: exemption-with-validation for bench:arrowhead records
# ---------------------------------------------------------------------------


def _ah_measured(**over):
    m = {"metric": "arrowhead_tflops", "value": 0.5, "nblocks": 4,
         "block": 8, "border": 2, "n": 34, "batch": 2, "nrhs": 1,
         "impl": "xla", "speedup": 12.0, "arrow_ms": 1.0, "dense_ms": 12.0,
         "factor_resid": 1e-7, "solve_resid": 1e-7}
    m.update(over)
    return m


class TestLedgerSeam:
    def test_valid_record_passes_diff(self):
        rec = ledger.record("bench:arrowhead", ledger.manifest(),
                            measured=_ah_measured())
        assert ledger.diff([rec], [rec]) == []

    def test_validate_flags_geometry_mismatch(self):
        probs = ledger.validate_arrowhead_measured(_ah_measured(n=33))
        assert any("nblocks*block+border" in p for p in probs)

    def test_malformed_record_is_incompatible(self):
        rec = ledger.record("bench:arrowhead", ledger.manifest(),
                            measured=_ah_measured(impl="cuda"))
        with pytest.raises(ledger.LedgerIncompatible, match="arrowhead"):
            ledger.diff([rec], [rec])

    def test_speedup_row_requires_residual_proof(self):
        m = _ah_measured()
        del m["factor_resid"]
        probs = ledger.validate_arrowhead_measured(m)
        assert any("factor_resid" in p for p in probs)

    def test_latency_metric_validated_without_speedup(self):
        m = _ah_measured(metric="arrowhead_latency")
        for key in ("speedup", "arrow_ms", "dense_ms", "factor_resid",
                    "solve_resid"):
            del m[key]
        assert ledger.validate_arrowhead_measured(m) == []
        rec = ledger.record("bench:arrowhead", ledger.manifest(),
                            measured=_ah_measured(metric="arrowhead_latency",
                                                  border=0))
        with pytest.raises(ledger.LedgerIncompatible, match="border"):
            ledger.diff([rec], [rec])

    def test_arrowhead_op_known_to_request_stats(self):
        assert "posv_arrowhead" in ledger._REQ_STATS_OPS


# ---------------------------------------------------------------------------
# cost model: AH phases, executed-flop pricing, refine-sweep satellite
# ---------------------------------------------------------------------------


class TestTracing:
    def test_ah_phases_registered_and_priced(self):
        ops = _arrow(np.random.default_rng(80), 1, 3, 4, 2, 1)
        with tracing.Recorder() as rec:
            X, Xs, info = _posv(*ops, impl="xla")
        assert rec.stats["AH::schur"].flops == pytest.approx(
            tracing.arrowhead_schur_flops(3, 4, 2))
        assert rec.stats["AH::border"].flops == pytest.approx(
            tracing.arrowhead_border_flops(3, 4, 2, 1))

    def test_estimate_seconds_scales_refine_sweeps(self):
        # the round-15 cost-model satellite: IR::* phases price by the
        # measured sweep count, every other phase is untouched
        rec = tracing.Recorder()
        with rec:
            with tracing.scope("IR::residual"):
                tracing.emit(flops=1e9)
            with tracing.scope("AH::schur"):
                tracing.emit(flops=1e9)
        spec = tracing.DeviceSpec("test", 100.0, 1000.0, 100.0)
        one = rec.estimate_seconds(spec, jnp.float32, refine_sweeps=1.0)
        three = rec.estimate_seconds(spec, jnp.float32, refine_sweeps=3.0)
        assert three["IR::residual"][0] == pytest.approx(
            3.0 * one["IR::residual"][0])
        assert three["AH::schur"][0] == pytest.approx(one["AH::schur"][0])

    def test_refine_sweeps_from_stats_feed(self):
        assert tracing.refine_sweeps_from_stats(None) == 1.0
        assert tracing.refine_sweeps_from_stats(
            {"iters": {"p50": 2.5}}) == 2.5
        assert tracing.refine_sweeps_from_stats(
            {"iters": {"p50": 0.0}}) == 1.0
        assert tracing.refine_sweeps_from_stats({"iters": {}}) == 1.0
