"""Rectangular (dx != dy) grid coverage — the reference's tunable topo::rect.

The reference's tall-skinny QR sweeps *grid shape* as its main tuning knob
(qr tune.cpp sweeps c over rect d x c grids, topology.h:16-65); here the mesh
shape IS that knob (Grid.rect), so every algorithm must run on a genuinely
rectangular face.  VERDICT r1 flagged that no dx != dy grid was ever
exercised; these tests close that.

On the split gram reduction (reference sweep_tune, cacqr.hpp:147-149 — a
column_contig MPI_Reduce followed by a column_alt MPI_Allreduce): on a TPU
mesh the gram is one fused psum over all axes and the split is strictly
non-better in the alpha-beta model.  For p devices and an n x n gram (ring
collectives): fused allreduce moves 2(p-1)/p * n^2 bytes in ONE collective;
a split over a contiguous group of size g then an allreduce across p/g
groups moves (g-1)/g * n^2 + 2(p/g-1)/(p/g) * n^2 bytes in TWO.  At p=8,
g=4: fused = 1.75 n^2 vs split = 0.75 + 1.0 = 1.75 n^2 — byte-equal, one
extra synchronization.  The reference splits because MPI subcommunicators
let it align stages with the network hierarchy; XLA performs that hierarchy
decomposition itself when lowering the single psum over ICI/DCN, so the
fused spelling dominates (test_gram_split_cost_model pins the arithmetic).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from capital_tpu.models import cholesky, qr
from capital_tpu.parallel import summa
from capital_tpu.parallel.summa import TrmmArgs
from capital_tpu.parallel.topology import Grid
from capital_tpu.utils import rand48, residual, tracing


@pytest.fixture(scope="module")
def grid4x2x1() -> Grid:
    return Grid.rect(4, 2, 1, devices=jax.devices("cpu"))


@pytest.fixture(scope="module")
def grid4x1x2() -> Grid:
    return Grid.rect(4, 1, 2, devices=jax.devices("cpu"))


def _put(grid, x):
    return jax.device_put(jnp.asarray(x), grid.face_sharding())


class TestSummaRect:
    @pytest.mark.parametrize("gridname", ["grid4x2x1", "grid4x1x2"])
    def test_gemm_xla(self, gridname, request):
        g = request.getfixturevalue(gridname)
        A = rand48.random(32, 48, key=1)
        B = rand48.random(48, 16, key=2)
        C = summa.gemm(g, _put(g, A), _put(g, B), mode="xla")
        np.testing.assert_allclose(np.asarray(C), A @ B, rtol=1e-12)

    def test_trmm_xla(self, grid4x2x1):
        g = grid4x2x1
        A = rand48.random(32, 32, key=3)
        B = rand48.random(32, 16, key=4)
        C = summa.trmm(g, _put(g, A), _put(g, B), TrmmArgs(side="L", uplo="U"))
        np.testing.assert_allclose(np.asarray(C), np.triu(A) @ B, rtol=1e-12)

    def test_explicit_requires_square_face(self, grid4x2x1):
        A = _put(grid4x2x1, rand48.random(32, 32, key=1))
        with pytest.raises(ValueError, match="square"):
            summa.gemm(grid4x2x1, A, A, mode="explicit")


class TestCholinvRect:
    @pytest.mark.parametrize("gridname", ["grid4x2x1", "grid4x1x2"])
    def test_factor(self, gridname, request):
        g = request.getfixturevalue(gridname)
        A = jnp.asarray(rand48.symmetric(64))
        Ag = _put(g, A)
        cfg = cholesky.CholinvConfig(base_case_dim=16, mode="xla")
        R, Rinv = jax.jit(lambda a: cholesky.factor(g, a, cfg))(Ag)
        assert float(residual.cholesky_residual(Ag, R)) < 1e-14
        assert float(residual.cholesky_inverse_residual(R, Rinv)) < 1e-13


class TestCacqrRect:
    def test_dist_regime_end_to_end(self, grid4x2x1):
        # the reference's tunable-rect QR regime: tall-skinny A on a
        # genuinely rectangular face, cholinv nested on the gram
        g = grid4x2x1
        A = _put(g, rand48.random(512, 64, key=11))
        cfg = qr.CacqrConfig(
            regime="dist",
            cholinv=cholesky.CholinvConfig(base_case_dim=16, complete_inv=True),
        )
        Q, R = jax.jit(lambda a: qr.factor(g, a, cfg))(A)
        assert float(residual.qr_orthogonality(Q)) < 1e-14
        assert float(residual.qr_residual(A, Q, R)) < 1e-13

    def test_1d_regime_rect_with_depth(self, grid4x1x2):
        g = grid4x1x2
        A = jax.device_put(
            jnp.asarray(rand48.random(512, 32, key=12)), g.rows_sharding()
        )
        Q, R = jax.jit(
            lambda a: qr.factor(g, a, qr.CacqrConfig(num_iter=2, regime="1d"))
        )(A)
        assert float(residual.qr_orthogonality(Q)) < 1e-14


def test_gram_split_cost_model():
    """The numbers behind preferring one fused gram psum over the
    reference's split reduction (module docstring): byte-equal at best,
    always one extra synchronization."""
    n, item, p = 1024, 8, 8
    bytes_gram = n * n * item
    fused = tracing._allreduce_bytes(bytes_gram, p)
    for g in (2, 4):
        # reduce over a contiguous group of size g: (g-1)/g * bytes
        reduce_stage = bytes_gram * (g - 1) / g
        allreduce_stage = tracing._allreduce_bytes(bytes_gram, p // g)
        split_total = reduce_stage + allreduce_stage
        assert split_total >= fused - 1e-9, (g, split_total, fused)
