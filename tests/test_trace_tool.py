"""Unit tests for the device-trace budget tool (capital_tpu/bench/trace.py).

The own-time sweep and phase bucketing are pure logic — testable without a
TPU by synthesizing xplane protos."""

import types

import pytest

pytest.importorskip("tensorflow.tsl.profiler.protobuf.xplane_pb2")
from tensorflow.tsl.profiler.protobuf import xplane_pb2  # noqa: E402

from capital_tpu.bench import trace  # noqa: E402


def _line(events):
    """events: [(offset_ps, duration_ps, metadata_id)]"""
    line = xplane_pb2.XLine(name="XLA Ops")
    for off, dur, mid in events:
        line.events.add(offset_ps=off, duration_ps=dur, metadata_id=mid)
    return line


class TestOwnTimes:
    def test_nested_subtraction(self):
        # while[0,100] contains a[10,30] and b[50,40]; a contains c[15,10]
        line = _line([(0, 100, 1), (10, 30, 2), (15, 10, 3), (50, 40, 4)])
        own = dict(trace._own_times(line))
        assert own == {1: 30, 2: 20, 3: 10, 4: 40}

    def test_flat_events(self):
        line = _line([(0, 10, 1), (10, 10, 2), (25, 5, 3)])
        own = dict(trace._own_times(line))
        assert own == {1: 10, 2: 10, 3: 5}

    def test_total_is_conserved(self):
        # sum of own times == duration of the outermost container
        line = _line([(0, 1000, 1), (0, 400, 2), (400, 600, 3), (450, 100, 4)])
        own = trace._own_times(line)
        assert sum(t for _, t in own) == 1000

    def test_overlap_closes_all_outlasted_ancestors(self):
        # a[0,100] contains b[10,40] contains c[20,20]; async tail
        # d[30,200] outlasts ALL of a/b/c — every stacked ancestor must be
        # closed (round-3 advisor: a single pop left the deeper ancestors
        # open, mis-attributing the overlap across phase buckets)
        line = _line([(0, 100, 1), (10, 40, 2), (20, 20, 3), (30, 200, 4)])
        own = dict(trace._own_times(line))
        # c: own 20; b: 40 - 20 = 20; a: 100 - 40 = 60; d: 200
        assert own == {1: 60, 2: 20, 3: 20, 4: 200}


class TestBucket:
    def _md(self, name, display=""):
        return xplane_pb2.XEventMetadata(name=name, display_name=display)

    def test_phase_from_op_name_wins_over_stats(self):
        # op NAME is authoritative: %CI.tmu.90 goes to CI::tmu even if the
        # stats were to mention other scopes (the round-3 mis-filing bug)
        md = self._md("%CI.tmu.90 = bf16[64,64] fusion(...)", "CI.tmu.90")
        assert trace._bucket(md, {}) == "CI::tmu"
        md2 = self._md("%CI.factor_diag.3 = f32[128,128] custom-call(...)")
        assert trace._bucket(md2, {}) == "CI::factor_diag"

    def test_kind_fallbacks(self):
        assert trace._bucket(self._md("%copy.1 = bf16[8,8] copy(%x)"), {}) == "copy"
        assert (
            trace._bucket(self._md("%fusion.2 = bf16[8,8] fusion(%x)"), {})
            == "fusion"
        )
        assert (
            trace._bucket(self._md("%custom-call.9 = f32[8,8] custom-call()"), {})
            == "custom-call"
        )
        assert trace._bucket(self._md("%add.1 = f32[8] add(%a, %b)"), {}) == "other"


class TestCriticalPlane:
    """device_budget must report the max-total device plane, not the sum
    over planes (round-3 advisor HIGH finding: on an n-device run the
    summed floor is ~n x the true per-iteration device time and flags
    honest walls as below-floor)."""

    def _space(self, plane_specs):
        """plane_specs: {plane_name: [(off, dur, mid, op_name)]}"""
        space = xplane_pb2.XSpace()
        for pname, events in plane_specs.items():
            plane = space.planes.add(name=pname)
            line = plane.lines.add(name="XLA Ops")
            for off, dur, mid, op in events:
                line.events.add(offset_ps=off, duration_ps=dur, metadata_id=mid)
                plane.event_metadata[mid].name = op
        return space

    def test_max_plane_not_sum(self):
        ps = 1_000_000  # 1 us in ps -> 1e-3 ms
        space = self._space({
            "/device:TPU:0 (pid 1)": [(0, 3 * ps, 1, "%CI.tmu.1 = f(...)")],
            "/device:TPU:1 (pid 2)": [(0, 5 * ps, 2, "%CI.trsm.1 = f(...)")],
            "/device:TPU:2 (pid 3)": [(0, 4 * ps, 3, "%copy.1 = copy(...)")],
        })
        budget = trace._critical_plane_budget([("t", space)])
        # the critical plane is TPU:1 (5 us) — its buckets alone, no sums
        assert budget == {"CI::trsm": pytest.approx(5e-3)}

    def test_non_tpu_planes_ignored(self):
        ps = 1_000_000
        space = self._space({
            "/host:CPU (pid 9)": [(0, 100 * ps, 1, "%copy.9 = copy(...)")],
            "/device:TPU:0 (pid 1)": [(0, 2 * ps, 2, "%CI.tmu.1 = f(...)")],
        })
        budget = trace._critical_plane_budget([("t", space)])
        assert budget == {"CI::tmu": pytest.approx(2e-3)}

    def test_single_plane_unchanged(self):
        ps = 1_000_000
        space = self._space({
            "/device:TPU:0 (pid 1)": [
                (0, 2 * ps, 1, "%CI.tmu.1 = f(...)"),
                (2 * ps, 1 * ps, 2, "%copy.1 = copy(...)"),
            ],
        })
        budget = trace._critical_plane_budget([("t", space)])
        assert budget == {
            "CI::tmu": pytest.approx(2e-3),
            "copy": pytest.approx(1e-3),
        }


class TestCopyFraction:
    """check_copy_fraction: the CI gate that the explicit-cholinv 'copy'
    bucket stays below a pinned fraction of device own-time — the trace
    counterpart of the collective-inventory audit.  The explicit schedule
    now rides copy-free / persistent-layout routes, so its pinned budget is
    tight: a take_triangle materialization or whole-buffer
    dynamic_update_slice creeping back in trips the gate loudly."""

    # pinned CI budget for the explicit cholinv trace (the copy-free d==1
    # route plus the persistent layout leave only band-sized residue)
    EXPLICIT_CHOLINV_COPY_BUDGET = 0.10

    def _explicit_cholinv_budget(self, copy_ms):
        # shape of a real explicit-cholinv device budget: phase buckets
        # dominate, 'copy' carries whatever the schedule materialized
        return {
            "CI::tmu": 6.0,
            "CI::trsm": 2.5,
            "CI::inv": 1.0,
            "CI::factor_diag": 0.4,
            "fusion": 0.1,
            "copy": copy_ms,
            "async (overlapped)": 50.0,  # DMA occupancy: excluded
        }

    def test_within_budget_returns_fraction(self):
        budget = self._explicit_cholinv_budget(copy_ms=0.5)
        frac = trace.check_copy_fraction(
            budget, self.EXPLICIT_CHOLINV_COPY_BUDGET, "cholinv explicit"
        )
        assert frac == pytest.approx(0.5 / 10.5)
        assert frac <= self.EXPLICIT_CHOLINV_COPY_BUDGET

    def test_regression_fails_loudly(self):
        # the pre-copy-free schedule's shape: dozens of whole-buffer
        # round-trips put 'copy' at a third of device time
        budget = self._explicit_cholinv_budget(copy_ms=5.0)
        with pytest.raises(RuntimeError, match="copy-budget regression"):
            trace.check_copy_fraction(
                budget, self.EXPLICIT_CHOLINV_COPY_BUDGET, "cholinv explicit"
            )

    def test_async_occupancy_excluded_both_sides(self):
        # async DMA occupancy overlaps compute — it must inflate neither
        # the numerator nor the denominator
        with_async = self._explicit_cholinv_budget(copy_ms=0.5)
        without = dict(with_async)
        without.pop("async (overlapped)")
        f1 = trace.check_copy_fraction(with_async, 0.1)
        f2 = trace.check_copy_fraction(without, 0.1)
        assert f1 == f2

    def test_empty_and_copyless_budgets(self):
        assert trace.check_copy_fraction({}, 0.1) == 0.0
        assert trace.check_copy_fraction({"CI::tmu": 3.0}, 0.0) == 0.0

    def test_from_synthesized_xplane(self):
        # end-to-end through the plane parser: a synthesized trace whose
        # copy share violates the pinned budget must trip the gate
        ps = 1_000_000
        space = xplane_pb2.XSpace()
        plane = space.planes.add(name="/device:TPU:0 (pid 1)")
        line = plane.lines.add(name="XLA Ops")
        for mid, (off, dur, op) in enumerate([
            (0, 6 * ps, "%CI.tmu.1 = f(...)"),
            (6 * ps, 3 * ps, "%copy.7 = bf16[8192,8192] copy(%buf)"),
        ], start=1):
            line.events.add(offset_ps=off, duration_ps=dur, metadata_id=mid)
            plane.event_metadata[mid].name = op
        budget = trace._critical_plane_budget([("t", space)])
        with pytest.raises(RuntimeError, match="copy-budget regression"):
            trace.check_copy_fraction(
                budget, self.EXPLICIT_CHOLINV_COPY_BUDGET, "cholinv explicit"
            )
        assert (
            trace.check_copy_fraction(budget, 0.5) == pytest.approx(3 / 9)
        )
