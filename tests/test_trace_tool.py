"""Unit tests for the device-trace budget tool (capital_tpu/bench/trace.py).

The own-time sweep and phase bucketing are pure logic — testable without a
TPU by synthesizing xplane protos."""

import types

import pytest

pytest.importorskip("tensorflow.tsl.profiler.protobuf.xplane_pb2")
from tensorflow.tsl.profiler.protobuf import xplane_pb2  # noqa: E402

from capital_tpu.bench import trace  # noqa: E402


def _line(events):
    """events: [(offset_ps, duration_ps, metadata_id)]"""
    line = xplane_pb2.XLine(name="XLA Ops")
    for off, dur, mid in events:
        line.events.add(offset_ps=off, duration_ps=dur, metadata_id=mid)
    return line


class TestOwnTimes:
    def test_nested_subtraction(self):
        # while[0,100] contains a[10,30] and b[50,40]; a contains c[15,10]
        line = _line([(0, 100, 1), (10, 30, 2), (15, 10, 3), (50, 40, 4)])
        own = dict(trace._own_times(line))
        assert own == {1: 30, 2: 20, 3: 10, 4: 40}

    def test_flat_events(self):
        line = _line([(0, 10, 1), (10, 10, 2), (25, 5, 3)])
        own = dict(trace._own_times(line))
        assert own == {1: 10, 2: 10, 3: 5}

    def test_total_is_conserved(self):
        # sum of own times == duration of the outermost container
        line = _line([(0, 1000, 1), (0, 400, 2), (400, 600, 3), (450, 100, 4)])
        own = trace._own_times(line)
        assert sum(t for _, t in own) == 1000


class TestBucket:
    def _md(self, name, display=""):
        return xplane_pb2.XEventMetadata(name=name, display_name=display)

    def test_phase_from_op_name_wins_over_stats(self):
        # op NAME is authoritative: %CI.tmu.90 goes to CI::tmu even if the
        # stats were to mention other scopes (the round-3 mis-filing bug)
        md = self._md("%CI.tmu.90 = bf16[64,64] fusion(...)", "CI.tmu.90")
        assert trace._bucket(md, {}) == "CI::tmu"
        md2 = self._md("%CI.factor_diag.3 = f32[128,128] custom-call(...)")
        assert trace._bucket(md2, {}) == "CI::factor_diag"

    def test_kind_fallbacks(self):
        assert trace._bucket(self._md("%copy.1 = bf16[8,8] copy(%x)"), {}) == "copy"
        assert (
            trace._bucket(self._md("%fusion.2 = bf16[8,8] fusion(%x)"), {})
            == "fusion"
        )
        assert (
            trace._bucket(self._md("%custom-call.9 = f32[8,8] custom-call()"), {})
            == "custom-call"
        )
        assert trace._bucket(self._md("%add.1 = f32[8] add(%a, %b)"), {}) == "other"
