"""M5 tests: rectri, Newton-Schulz, and distributed TRSM."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from capital_tpu.models import inverse, trsm
from capital_tpu.models.inverse import NewtonConfig, RectriConfig
from capital_tpu.models.trsm import TrsmConfig
from capital_tpu.utils import rand48, residual


def _tri(n, uplo, key=21):
    A = np.asarray(rand48.random(n, n, key=key)) + np.eye(n) * n
    return jnp.asarray(np.tril(A) if uplo == "L" else np.triu(A))


class TestRectri:
    @pytest.mark.parametrize("uplo", ["L", "U"])
    @pytest.mark.parametrize("n,bc", [(64, 16), (100, 32)])
    def test_inverse(self, grid2x2x1, uplo, n, bc):
        T = _tri(n, uplo)
        Tinv = jax.jit(
            lambda t: inverse.rectri(grid2x2x1, t, uplo, RectriConfig(base_case_dim=bc))
        )(T)
        assert residual.inverse_residual(T, Tinv) < 1e-13
        # inverse of a triangular matrix is triangular with the same uplo
        Ti = np.asarray(Tinv)
        if uplo == "L":
            np.testing.assert_allclose(Ti, np.tril(Ti), atol=1e-14)
        else:
            np.testing.assert_allclose(Ti, np.triu(Ti), atol=1e-14)

    def test_on_3d_grid(self, grid2x2x2):
        T = _tri(128, "L")
        Td = jax.device_put(T, grid2x2x2.face_sharding())
        Tinv = inverse.rectri(grid2x2x2, Td, "L", RectriConfig(base_case_dim=32))
        assert residual.inverse_residual(T, Tinv) < 1e-13

    def test_pallas_mode_single_device(self):
        # the flat-buffer recursion's in-place view writes (VERDICT r1 #8)
        from capital_tpu.parallel.topology import Grid

        g1 = Grid.square(c=1, devices=jax.devices("cpu")[:1])
        T = jax.device_put(_tri(256, "L"), g1.face_sharding())
        Tinv = jax.jit(
            lambda t: inverse.rectri(
                g1, t, "L", RectriConfig(base_case_dim=64, mode="pallas")
            )
        )(T)
        assert residual.inverse_residual(T, Tinv) < 1e-13
        Ti = np.asarray(Tinv)
        np.testing.assert_allclose(Ti, np.tril(Ti), atol=1e-14)

    def test_explicit_mode_mesh(self, grid2x2x2):
        T = jax.device_put(_tri(128, "L"), grid2x2x2.face_sharding())
        Tinv = inverse.rectri(
            grid2x2x2, T, "L", RectriConfig(base_case_dim=32, mode="explicit")
        )
        assert residual.inverse_residual(T, Tinv) < 1e-13

    def test_explicit_tile_cyclic_balance(self, grid2x2x1):
        # VERDICT r3 #5: the balanced side-L merge trmm wired into rectri —
        # same results, balanced schedule engaged on large-enough windows
        from capital_tpu.utils import tracing

        T = jax.device_put(_tri(128, "L"), grid2x2x1.face_sharding())
        cfg = RectriConfig(
            base_case_dim=32, mode="explicit",
            balance="tile_cyclic", balance_min_window=32,
        )
        with tracing.Recorder() as rec:
            Tinv = jax.jit(lambda t: inverse.rectri(grid2x2x1, t, "L", cfg))(T)
        assert residual.inverse_residual(T, Tinv) < 1e-13
        ref = inverse.rectri(
            grid2x2x1, T, "L", RectriConfig(base_case_dim=32, mode="explicit")
        )
        np.testing.assert_allclose(np.asarray(Tinv), np.asarray(ref), atol=1e-13)
        # the balanced schedule must actually ENGAGE: every merge window
        # here (64, 32 >= min_window 32) is tile-cyclic-eligible on the
        # 2x2 face, so a fallback note means the balance plumb-through
        # regressed to the block schedule
        assert "trmm::tile_cyclic_fallback" not in rec.stats, rec.stats.keys()
        assert any("RT::merge" in k for k in rec.stats), rec.stats.keys()

    def test_cross_level_assembly_pinned(self, grid2x2x1):
        """Pin the documented DECISION on the reference's rectri TODO
        (inverse.py module docstring; rectri.hpp:70-99): the cross-level
        assembly IS implemented — windowed trmms over one flat buffer on
        the full mesh, no nested-grid redistribution — so the top-level
        windows of the result must equal the closed-form block inverse
        [[L11inv, 0], [-L22inv @ L21 @ L11inv, L22inv]] computed
        independently, with the never-written upper block EXACTLY zero
        (each window is written once; nothing is masked after the fact)."""
        n, bc = 128, 32
        T = _tri(n, "L")
        Td = jax.device_put(T, grid2x2x1.face_sharding())
        Ti = np.asarray(
            jax.jit(
                lambda t: inverse.rectri(
                    grid2x2x1, t, "L", RectriConfig(base_case_dim=bc)
                )
            )(Td)
        )
        L = np.asarray(T, dtype=np.float64)
        # the bc-aligned split rule at the top level: n1 = (n//bc//2)*bc
        n1 = (n // bc // 2) * bc
        L11i = np.linalg.inv(L[:n1, :n1])
        L22i = np.linalg.inv(L[n1:, n1:])
        np.testing.assert_allclose(Ti[:n1, :n1], L11i, rtol=1e-10, atol=1e-12)
        np.testing.assert_allclose(Ti[n1:, n1:], L22i, rtol=1e-10, atol=1e-12)
        np.testing.assert_allclose(
            Ti[n1:, :n1], -L22i @ L[n1:, :n1] @ L11i, rtol=1e-10, atol=1e-11
        )
        assert np.all(Ti[:n1, n1:] == 0.0)

    def test_bad_inputs(self, grid2x2x1):
        with pytest.raises(ValueError):
            inverse.rectri(grid2x2x1, jnp.zeros((4, 6)))
        with pytest.raises(ValueError):
            inverse.rectri(grid2x2x1, jnp.eye(4), uplo="X")

    def test_batched_levels_single_device(self):
        # the single-device batched level sweep (batched trtri leaves +
        # batched dense merges; an off-by-default measured loser on TPU —
        # docs/PERF.md) is the same operator as the depth-first recursion;
        # f64 pins them together.  Eligibility is all-or-nothing on the
        # padded plan: 256/320/300/511 all pad to a bc·2^k chain (prefix
        # engages matrix-wide), while 700 pads to 768 = 24·32 (nb not a
        # power of two — prefix refuses, pure recursion even with the knob
        # set)
        from capital_tpu.parallel.topology import Grid

        g1 = Grid.square(c=1, devices=jax.devices("cpu")[:1])
        for n in (256, 320, 300, 511, 700):
            T = _tri(n, "L", key=41)
            a = inverse.rectri(
                g1, T, "L", RectriConfig(base_case_dim=32, batch_below=128)
            )
            b = inverse.rectri(
                g1, T, "L", RectriConfig(base_case_dim=32, batch_below=0)
            )
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-11, atol=1e-11
            )
            assert residual.inverse_residual(T, a) < 1e-12, n


class TestNewton:
    def test_spd_inverse(self, grid2x2x1):
        A = jnp.asarray(rand48.symmetric(64))
        X, iters = jax.jit(lambda a: inverse.newton(grid2x2x1, a, NewtonConfig()))(A)
        assert residual.inverse_residual(A, X) < 1e-11
        assert 0 < int(iters) < 60

    def test_nonsymmetric(self, grid2x2x1):
        # diagonally dominant nonsymmetric matrix
        n = 48
        A = jnp.asarray(np.asarray(rand48.random(n, n, key=3)) + np.eye(n) * n)
        X, _ = inverse.newton(grid2x2x1, A)
        assert residual.inverse_residual(A, X) < 1e-11

    def test_max_iter_bound(self, grid2x2x1):
        A = jnp.asarray(rand48.symmetric(32))
        _, iters = inverse.newton(grid2x2x1, A, NewtonConfig(max_iter=3))
        assert int(iters) == 3  # stopped by the bound, not converged


class TestTrsm:
    @pytest.mark.parametrize("side", ["L", "R"])
    @pytest.mark.parametrize("uplo", ["L", "U"])
    @pytest.mark.parametrize("trans_a", [False, True])
    def test_all_variants(self, grid2x2x1, side, uplo, trans_a):
        n, m = 64, 32
        T = _tri(n, uplo)
        Bshape = (n, m) if side == "L" else (m, n)
        B = jnp.asarray(rand48.random(*Bshape, key=22))
        X = jax.jit(
            lambda t, b: trsm.solve(
                grid2x2x1, t, b, side, uplo, trans_a, TrsmConfig(base_case_dim=16)
            )
        )(T, B)
        Tn = np.asarray(T).T if trans_a else np.asarray(T)
        got = Tn @ np.asarray(X) if side == "L" else np.asarray(X) @ Tn
        np.testing.assert_allclose(got, np.asarray(B), rtol=1e-11, atol=1e-11)

    @pytest.mark.parametrize("trans_a", [False, True])
    def test_unit_diag(self, grid2x2x1, trans_a):
        # Diag::AblasUnit parity (reference blas/engine.h:23-52): the
        # diagonal is treated as ones without being read — garbage on the
        # stored diagonal must not affect the solution
        n, m = 64, 16
        T = _tri(n, "L")
        T = T.at[jnp.arange(n), jnp.arange(n)].set(1e30)  # poison the diag
        B = jnp.asarray(rand48.random(n, m, key=27))
        X = jax.jit(
            lambda t, b: trsm.solve(
                grid2x2x1, t, b, "L", "L", trans_a,
                TrsmConfig(base_case_dim=16), unit_diag=True,
            )
        )(T, B)
        T1 = np.tril(np.asarray(T), -1) + np.eye(n)
        Tn = T1.T if trans_a else T1
        np.testing.assert_allclose(Tn @ np.asarray(X), np.asarray(B),
                                   rtol=1e-11, atol=1e-11)

    def test_odd_size_recursion(self, grid2x2x1):
        # n=100 with bc=16 once exercised uneven halving (50/50 -> 25/25...);
        # on a mesh the solve now pads to bc·2^k at the boundary so every
        # window keeps the face layout — no Grid.pin fallback warnings
        # (VERDICT r2 weak #5)
        import warnings

        T = _tri(100, "L")
        B = jnp.asarray(rand48.random(100, 8, key=23))
        with warnings.catch_warnings():
            warnings.simplefilter("error")
            X = trsm.solve(
                grid2x2x1, T, B, "L", "L", cfg=TrsmConfig(base_case_dim=16)
            )
        assert X.shape == (100, 8)
        np.testing.assert_allclose(
            np.asarray(T) @ np.asarray(X), np.asarray(B), rtol=1e-11, atol=1e-11
        )

    def test_odd_size_rectri_warning_free(self, grid2x2x1):
        # same boundary-pad contract for rectri on a mesh
        import warnings

        T = _tri(100, "L")
        with warnings.catch_warnings():
            warnings.simplefilter("error")
            Tinv = inverse.rectri(
                grid2x2x1, T, "L", RectriConfig(base_case_dim=16)
            )
        assert Tinv.shape == (100, 100)
        assert residual.inverse_residual(T, Tinv) < 1e-13

    def test_agrees_with_rectri(self, grid2x2x1):
        # X = T⁻¹ B two ways
        T = _tri(64, "L")
        B = jnp.asarray(rand48.random(64, 16, key=24))
        X1 = trsm.solve(grid2x2x1, T, B, "L", "L", cfg=TrsmConfig(base_case_dim=32))
        Tinv = inverse.rectri(grid2x2x1, T, "L", RectriConfig(base_case_dim=32))
        X2 = Tinv @ B
        np.testing.assert_allclose(np.asarray(X1), np.asarray(X2), rtol=1e-9, atol=1e-11)

    def test_bad_inputs(self, grid2x2x1):
        T = _tri(16, "L")
        with pytest.raises(ValueError):
            trsm.solve(grid2x2x1, T, jnp.zeros((8, 4)))  # shape mismatch
        with pytest.raises(ValueError):
            trsm.solve(grid2x2x1, T, jnp.zeros((16, 4)), side="X")

    @pytest.mark.parametrize("side", ["L", "R"])
    @pytest.mark.parametrize("uplo", ["L", "U"])
    def test_invert_leaf_matches_solve_leaf(self, grid2x2x1, side, uplo):
        # the diaginvert leaf (batched block inverses + gemm leaves) and the
        # substitution leaf are the same operator; f64 pins them together
        n, m = 96, 24  # 96 = 16·6: pads to 16·2^3 = 128 on the invert path
        T = _tri(n, uplo)
        Bshape = (n, m) if side == "L" else (m, n)
        B = jnp.asarray(rand48.random(*Bshape, key=31))
        Xs = [
            trsm.solve(
                grid2x2x1, T, B, side, uplo,
                cfg=TrsmConfig(base_case_dim=16, leaf=leaf),
            )
            for leaf in ("invert", "solve")
        ]
        np.testing.assert_allclose(
            np.asarray(Xs[0]), np.asarray(Xs[1]), rtol=1e-11, atol=1e-11
        )

    def test_invert_leaf_single_device_and_unit_diag(self):
        # single-device invert path pads only to the next bc multiple
        # (75 -> 80 = 5·16 — NOT bc·2^k, which would near-quadruple flops
        # at n just past a power of two) and splits block-aligned (5 -> 2+3
        # blocks, every leaf exactly bc); the batched inverse must ignore a
        # poisoned stored diagonal under unit_diag — the Diag::AblasUnit
        # contract holds leaf-for-leaf
        from capital_tpu.parallel.topology import Grid

        g1 = Grid.square(c=1, devices=jax.devices("cpu")[:1])
        n, m = 75, 8
        T = _tri(n, "L")
        Tp = T.at[jnp.arange(n), jnp.arange(n)].set(1e30)  # poison
        B = jnp.asarray(rand48.random(n, m, key=33))
        X = jax.jit(
            lambda t, b: trsm.solve(
                g1, t, b, "L", "L",
                cfg=TrsmConfig(base_case_dim=16, leaf="invert"),
                unit_diag=True,
            )
        )(Tp, B)
        T1 = np.tril(np.asarray(T), -1) + np.eye(n)
        np.testing.assert_allclose(T1 @ np.asarray(X), np.asarray(B),
                                   rtol=1e-11, atol=1e-11)

    def test_explicit_mode_mesh(self, grid2x2x2):
        # the full explicit-SUMMA schedule under the TRSM recursion on the
        # 3D mesh, diaginvert leaves included — completes the
        # mode='explicit' coverage the other three model families have
        n, m = 128, 16
        T = jax.device_put(_tri(n, "L"), grid2x2x2.face_sharding())
        B = jnp.asarray(rand48.random(n, m, key=35))
        X = trsm.solve(
            grid2x2x2, T, B, "L", "L",
            cfg=TrsmConfig(base_case_dim=32, mode="explicit"),
        )
        np.testing.assert_allclose(
            np.asarray(T) @ np.asarray(X), np.asarray(B), rtol=1e-11, atol=1e-11
        )

    def test_invert_leaf_bad_value_and_pad_economy(self):
        # leaf typos raise instead of silently taking the slow path, and the
        # single-device invert pad stays under one bc block for any n
        from capital_tpu.models.cholesky import padded_dim
        from capital_tpu.parallel.topology import Grid

        g1 = Grid.square(c=1, devices=jax.devices("cpu")[:1])
        T = _tri(32, "L")
        with pytest.raises(ValueError, match="leaf"):
            trsm.solve(g1, T, jnp.zeros((32, 4)),
                       cfg=TrsmConfig(base_case_dim=16, leaf="diaginvert"))
        # n just past a power of two: bc·2^k padding would near-double the
        # dimension (padded_dim(1040, 128) = 2048); the invert path pads to
        # the next bc multiple instead and still solves correctly
        n, bc = 1040, 128
        assert padded_dim(n, bc) == 2048 and -(-n // bc) * bc == 1152
        T = _tri(n, "L", key=29)
        B = jnp.asarray(rand48.random(n, 8, key=30))
        X = trsm.solve(g1, T, B, cfg=TrsmConfig(base_case_dim=bc, leaf="invert"))
        r = np.asarray(T) @ np.asarray(X) - np.asarray(B)
        assert np.max(np.abs(r)) < 1e-11
