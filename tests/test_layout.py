"""Layout engine tests: block/cyclic repack round-trips and packed storage.

Covers the TPU equivalents of the reference's serialize engine
(serialize.h:16-70) and block<->cyclic repack kernels (util.hpp:56-230) —
the property tests SURVEY §4 calls for.
"""

import numpy as np
import jax.numpy as jnp
import pytest

from capital_tpu.utils import layout


@pytest.mark.parametrize("dx,dy", [(1, 1), (2, 2), (2, 4), (3, 3)])
def test_block_cyclic_roundtrip(dx, dy):
    rng = np.random.default_rng(0)
    G = rng.standard_normal((dx * 5, dy * 3))
    assert np.array_equal(layout.cyclic_to_block(layout.block_to_cyclic(G, dx, dy), dx, dy), G)
    assert np.array_equal(layout.block_to_cyclic(layout.cyclic_to_block(G, dx, dy), dx, dy), G)


def test_block_to_cyclic_semantics():
    """Tile (x, y) of the blocked buffer holds rank (x,y)'s cyclic elements:
    local (k, l) = global (k*dx + x, l*dy + y) (reference structure.hpp
    distribution arithmetic)."""
    dx, dy, m, n = 2, 3, 4, 2
    G = np.arange(dx * m * dy * n, dtype=np.float64).reshape(dx * m, dy * n)
    blocked = layout.cyclic_to_block(G, dx, dy)
    for x in range(dx):
        for y in range(dy):
            tile = blocked[x * m : (x + 1) * m, y * n : (y + 1) * n]
            assert np.array_equal(tile, layout.local_cyclic_tile(G, dx, dy, x, y))


def test_local_block_tile():
    G = np.arange(36.0).reshape(6, 6)
    t = layout.local_block_tile(G, 2, 3, 1, 2)
    assert np.array_equal(t, G[3:6, 4:6])


@pytest.mark.parametrize("n", [1, 4, 7])
def test_pack_unpack_upper(n):
    rng = np.random.default_rng(1)
    A = np.triu(rng.standard_normal((n, n)))
    p = layout.pack_upper(A)
    assert p.shape == (layout.num_packed_elems(n),)
    # reference structure.h:38: column x starts at offset x(x+1)/2 and holds
    # its x+1 leading entries
    for col in range(n):
        off = col * (col + 1) // 2
        assert np.array_equal(p[off : off + col + 1], A[: col + 1, col])
    assert np.array_equal(layout.unpack_upper(p, n), A)


@pytest.mark.parametrize("n", [1, 4, 7])
def test_pack_unpack_lower(n):
    rng = np.random.default_rng(2)
    A = np.tril(rng.standard_normal((n, n)))
    p = layout.pack_lower(A)
    assert p.shape == (layout.num_packed_elems(n),)
    assert np.array_equal(layout.unpack_lower(p, n), A)


def test_pack_unpack_jax_arrays():
    A = jnp.triu(jnp.arange(16.0).reshape(4, 4))
    assert np.array_equal(layout.unpack_upper(layout.pack_upper(A), 4), np.asarray(A))
    L = jnp.tril(jnp.arange(16.0).reshape(4, 4))
    assert np.array_equal(layout.unpack_lower(layout.pack_lower(L), 4), np.asarray(L))


def test_remove_triangle():
    A = np.arange(1.0, 17.0).reshape(4, 4)
    U = layout.remove_triangle(A, "U")
    assert np.array_equal(U, np.triu(A))
    L = layout.remove_triangle(jnp.asarray(A), "L")
    assert np.array_equal(np.asarray(L), np.tril(A))


def test_get_next_power2():
    assert [layout.get_next_power2(k) for k in (1, 2, 3, 5, 8, 1000)] == [
        1, 2, 4, 8, 8, 1024,
    ]
