"""PR 14 mixed-precision iterative refinement tests: robust/refine's three
drivers, the TSQR escalation rung, and accuracy tiers as a serve
scheduling dimension.

The acceptance properties of ISSUE 14 / docs/PERF.md round 14 /
docs/SERVING.md "Accuracy tiers" are asserted directly:

* a low-precision factor plus high-precision Wilkinson sweeps reaches the
  CORRECTION dtype's backward error inside the factor envelope — the
  cond≈2e4 point where f32 sCQR3 stalls refines clean (TestRefinePosv);
* beyond the envelope the loop freezes (progress guard) and reports
  ``converged == 0`` with the measured error — loud, finite, at most one
  wasted sweep (TestRefinePosv::test_beyond_envelope_stalls_loud);
* lstsq refines via SEMI-NORMAL corrections against the gram R, blocktri
  refines against a chain factor that can be a PR 12 RESIDENT factor —
  refinement never refactors (TestRefineLstsq, TestRefineBlocktri);
* the TSQR rung recovers cond 1e12 where the gram-forming CQR family
  cannot, both standalone (recovery.tsqr_escalate) and in-graph under
  RobustConfig.tsqr, with RobustInfo.gate naming which gate a surviving
  nonzero info describes (TestTsqrEscalation);
* accuracy_tier rides the serve bucket key: per-tier executables, zero
  steady-state recompiles per warm tier, non-convergence lands as a
  failed Response (never a silent wrong answer), and non-tier ops reject
  the vocabulary loudly (TestServeTiers);
* the telemetry seam: Collector.note_refine -> snapshot refine block ->
  merge_snapshots -> validate_request_stats / validate_refine_measured ->
  ``obs serve-report --max-refine-iters/--min-converged-frac``
  (TestStatsRefineBlock, TestValidateRefineMeasured,
  TestServeReportRefineGates).

Everything runs on the conftest CPU/x64 rig; engines use tiny bucket
ladders on the vmap/LAPACK seam so every executable compiles fast.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from capital_tpu.models import blocktri, qr
from capital_tpu.models.qr import CacqrConfig
from capital_tpu.obs import __main__ as obs_main
from capital_tpu.obs import ledger
from capital_tpu.parallel.topology import Grid
from capital_tpu.robust import RobustConfig, recovery, refine
from capital_tpu.robust.config import GATE_NONE, GATE_ORTHO
from capital_tpu.serve import ServeConfig, SolveEngine, stats


def _spd_cond(rng, n, cond, batch=1):
    """(batch, n, n) f64 SPD stack with a log-spaced spectrum spanning
    exactly `cond` — the refine drivers' conditioning knob."""
    eigs = np.logspace(0.0, -np.log10(cond), n)
    A = np.empty((batch, n, n))
    for i in range(batch):
        Q, _ = np.linalg.qr(rng.standard_normal((n, n)))
        A[i] = (Q * eigs) @ Q.T
    return 0.5 * (A + A.transpose(0, 2, 1))


def _bwerr(A, X, B):
    """Worst per-problem normwise backward error, f64 NumPy side."""
    A, X, B = (np.asarray(v, np.float64) for v in (A, X, B))
    worst = 0.0
    for i in range(A.shape[0]):
        r = A[i] @ X[i] - B[i]
        denom = (np.linalg.norm(A[i]) * np.linalg.norm(X[i])
                 + np.linalg.norm(B[i]) + np.finfo(np.float64).tiny)
        worst = max(worst, float(np.linalg.norm(r) / denom))
    return worst


# One jitted entry per (driver, static-config), shared by every test
# below: a bare refine.* call re-traces its while_loop body (fresh
# closure identity per call), so routing all same-shape calls through
# these module-level wrappers is what keeps the file inside the tier-1
# wall-clock budget — tests that can share an operand shape do.
_F32_KW = dict(factor_dtype=jnp.float32, correction_dtype=jnp.float64)
_posv = jax.jit(functools.partial(refine.posv, **_F32_KW))
_posv_mi0 = jax.jit(functools.partial(refine.posv, max_iters=0, **_F32_KW))
_lstsq = jax.jit(functools.partial(refine.lstsq, **_F32_KW))
_bt = jax.jit(functools.partial(refine.posv_blocktri, impl="xla", **_F32_KW))


# --------------------------------------------------------------------------
# tier plans + tolerance (the static resolution serve hashes)
# --------------------------------------------------------------------------


class TestTierPlans:
    def test_balanced_is_identity(self):
        for dt in (jnp.bfloat16, jnp.float32, jnp.float64):
            p = refine.plan("balanced", dt)
            assert p.factor_dtype == jnp.dtype(dt)
            assert p.correction_dtype == jnp.dtype(dt)
            assert p.max_iters == 0

    def test_fast_downgrades_factor(self):
        assert refine.plan("fast", jnp.float64).factor_dtype == jnp.float32
        assert refine.plan("fast", jnp.float32).factor_dtype == jnp.bfloat16
        assert refine.plan("fast", jnp.bfloat16).factor_dtype == jnp.bfloat16
        assert refine.plan("fast", jnp.float64).max_iters == 0

    def test_guaranteed_pairs_low_factor_high_correction(self):
        p64 = refine.plan("guaranteed", jnp.float64)
        assert (p64.factor_dtype, p64.correction_dtype) == \
            (jnp.dtype(jnp.float32), jnp.dtype(jnp.float64))
        p32 = refine.plan("guaranteed", jnp.float32)
        assert (p32.factor_dtype, p32.correction_dtype) == \
            (jnp.dtype(jnp.float32), jnp.dtype(jnp.float64))
        p16 = refine.plan("guaranteed", jnp.bfloat16)
        assert (p16.factor_dtype, p16.correction_dtype) == \
            (jnp.dtype(jnp.bfloat16), jnp.dtype(jnp.float32))
        assert p64.max_iters == refine.DEFAULT_MAX_ITERS

    def test_unknown_tier_raises(self):
        with pytest.raises(ValueError, match="accuracy_tier"):
            refine.plan("turbo", jnp.float32)

    def test_tolerance_scales_with_correction_dtype(self):
        t64 = refine.tolerance(64, jnp.float64)
        t32 = refine.tolerance(64, jnp.float32)
        assert t64 == pytest.approx(0.5 * 8.0 * np.finfo(np.float64).eps)
        assert t32 / t64 == pytest.approx(
            np.finfo(np.float32).eps / np.finfo(np.float64).eps)


# --------------------------------------------------------------------------
# refine.posv — the flagship driver
# --------------------------------------------------------------------------


class TestRefinePosv:
    @pytest.mark.parametrize("cond", [1e2, 1e4, 2e4])
    def test_f32_factor_reaches_f64_grade(self, cond):
        # 2e4 is the documented f32 sCQR3 stall point (ROBUSTNESS.md):
        # comfortably inside the refinement envelope
        rng = np.random.default_rng(int(cond) % 97)
        n, k, batch = 48, 3, 2
        A = _spd_cond(rng, n, cond, batch)
        B = rng.standard_normal((batch, n, k))
        X, info, ri = _posv(jnp.asarray(A), jnp.asarray(B))
        assert not np.any(np.asarray(info))
        assert np.all(np.asarray(ri.converged) == 1)
        assert np.all(np.asarray(ri.iters) >= 1)  # f32 X0 alone is not f64
        assert X.dtype == jnp.float64
        assert _bwerr(A, X, B) < refine.tolerance(n, jnp.float64)

    def test_refined_beats_unrefined_factor(self):
        rng = np.random.default_rng(5)
        n, k, batch = 48, 3, 2
        A = _spd_cond(rng, n, 2e4, batch)
        B = rng.standard_normal((batch, n, k))
        X0, _, r0 = _posv_mi0(jnp.asarray(A), jnp.asarray(B))
        X, _, ri = _posv(jnp.asarray(A), jnp.asarray(B))
        e0, e = _bwerr(A, X0, B), _bwerr(A, X, B)
        assert np.all(np.asarray(r0.iters) == 0)
        assert np.all(np.asarray(r0.converged) == 0)  # honest: not there yet
        assert e < 1e-3 * e0  # sweeps bought >= 3 digits back

    def test_beyond_envelope_stalls_loud(self):
        # cond 1e8 > 1/u32: the f32 factor still completes (info 0) but
        # the error floors orders of magnitude above the f64 tolerance,
        # so the progress guard freezes the problem and reports it —
        # never a spin, never a silent wrong answer
        rng = np.random.default_rng(7)
        n = 16
        bad = _spd_cond(rng, n, 1e8)
        A = np.concatenate([bad, bad])  # (2, n, n): the shared-shape class
        b1 = rng.standard_normal((1, n, 2))
        B = np.concatenate([b1, b1])  # both problems ARE the probed case
        X, info, ri = _posv(jnp.asarray(A), jnp.asarray(B))
        assert not np.any(np.asarray(info))  # the factor is NOT the story
        assert np.all(np.asarray(ri.converged) == 0)
        assert np.all(np.asarray(ri.iters) <= 2)  # froze, didn't spin
        assert np.all(np.asarray(ri.resid) > refine.tolerance(
            n, jnp.float64))  # the measured error says why

    def test_per_problem_freeze_is_independent(self):
        # batch mixing a clean problem with a beyond-envelope one: the
        # clean one converges, the bad one reports, neither perturbs the
        # other (the serve batching containment property)
        rng = np.random.default_rng(9)
        n = 16
        A = np.concatenate([_spd_cond(rng, n, 1e2), _spd_cond(rng, n, 1e8)])
        B = rng.standard_normal((2, n, 2))
        X, info, ri = _posv(jnp.asarray(A), jnp.asarray(B))
        conv = np.asarray(ri.converged)
        assert conv[0] == 1 and conv[1] == 0
        assert _bwerr(A[:1], X[:1], B[:1]) < refine.tolerance(n, jnp.float64)

    def test_nan_operand_freezes_immediately(self):
        rng = np.random.default_rng(11)
        n = 16
        A = _spd_cond(rng, n, 10.0, 2)
        B = rng.standard_normal((2, n, 2))
        B[0, 0, 0] = np.nan
        X, info, ri = _posv(jnp.asarray(A), jnp.asarray(B))
        # NaN error fails every comparison: not active, never converged —
        # and the clean batch neighbor is untouched by the poisoned one
        assert int(np.asarray(ri.converged)[0]) == 0
        assert int(np.asarray(ri.iters)[0]) == 0
        assert int(np.asarray(ri.converged)[1]) == 1

    def test_jit_and_fixed_output_arity(self):
        rng = np.random.default_rng(13)
        n = 16
        A = _spd_cond(rng, n, 1e2, 2)
        B = rng.standard_normal((2, n, 2))
        X, info, ri = _posv(jnp.asarray(A), jnp.asarray(B))
        assert ri.iters.shape == (2,) and ri.resid.dtype == jnp.float32
        assert _bwerr(A, X, B) < refine.tolerance(n, jnp.float64)


class TestRefineLstsq:
    def test_semi_normal_corrections_converge(self):
        rng = np.random.default_rng(17)
        m, n, k, batch = 96, 12, 2, 2
        A = rng.standard_normal((batch, m, n))
        B = rng.standard_normal((batch, m, k))
        X, info, ri = _lstsq(jnp.asarray(A), jnp.asarray(B))
        assert not np.any(np.asarray(info))
        assert np.all(np.asarray(ri.converged) == 1)
        for i in range(batch):
            Xr, *_ = np.linalg.lstsq(A[i], B[i], rcond=None)
            assert np.linalg.norm(np.asarray(X[i]) - Xr) \
                / np.linalg.norm(Xr) < 1e-9

    def test_gram_cond_squaring_still_refines(self):
        # cond(A) = 1e3 squares to 1e6 in the gram — hopeless for a plain
        # f32 normal-equations solve, recovered by the f64 sweeps
        rng = np.random.default_rng(19)
        m, n, k, batch = 96, 12, 2, 2
        A = np.empty((batch, m, n))
        for i in range(batch):
            Q0, _ = np.linalg.qr(rng.standard_normal((m, n)))
            V, _ = np.linalg.qr(rng.standard_normal((n, n)))
            A[i] = (Q0 * np.logspace(0, -3, n)) @ V.T
        B = rng.standard_normal((batch, m, k))
        X, _, ri = _lstsq(jnp.asarray(A), jnp.asarray(B))
        assert np.all(np.asarray(ri.converged) == 1)
        for i in range(batch):
            Xr, *_ = np.linalg.lstsq(A[i], B[i], rcond=None)
            assert np.linalg.norm(np.asarray(X[i]) - Xr) \
                / np.linalg.norm(Xr) < 1e-8


class TestRefineBlocktri:
    def _chain(self, rng, nblocks, b, batch=2):
        # diag-dominant blocks (the test_update chain recipe): ‖C‖ ~ 0.1
        # against diagonal eigenvalues >= 3 keeps the CHAIN SPD
        def blk():
            G = rng.standard_normal((b, b))
            return G @ G.T / b + 3.0 * np.eye(b)

        D = np.stack([
            np.stack([blk() for _ in range(nblocks)]) for _ in range(batch)
        ])
        C = 0.1 * rng.standard_normal((batch, nblocks, b, b))
        C[:, 0] = 0.0
        return D, C

    def _dense(self, D, C):
        nblocks, b = D.shape[0], D.shape[-1]
        n = nblocks * b
        A = np.zeros((n, n))
        for i in range(nblocks):
            A[i * b:(i + 1) * b, i * b:(i + 1) * b] = D[i]
            if i:
                A[i * b:(i + 1) * b, (i - 1) * b:i * b] = C[i]
                A[(i - 1) * b:i * b, i * b:(i + 1) * b] = C[i].T
        return A

    def test_matches_dense_solve(self):
        rng = np.random.default_rng(23)
        nblocks, b, batch, k = 3, 4, 2, 2
        D, C = self._chain(rng, nblocks, b, batch)
        B = rng.standard_normal((batch, nblocks, b, k))
        X, info, ri = _bt(jnp.asarray(D), jnp.asarray(C), jnp.asarray(B))
        assert not np.any(np.asarray(info))
        assert np.all(np.asarray(ri.converged) == 1)
        for i in range(batch):
            A = self._dense(D[i], C[i])
            Xr = np.linalg.solve(A, B[i].reshape(-1, k))
            assert np.linalg.norm(
                np.asarray(X[i], np.float64).reshape(-1, k) - Xr
            ) / np.linalg.norm(Xr) < 1e-10

    def test_resident_factor_reuse_is_bitwise(self):
        # the PR 12 composition: a resident (L, Wt) factor skips the
        # refactor entirely, and since the in-driver factor would compute
        # the identical values, the refined answers agree bitwise
        rng = np.random.default_rng(29)
        nblocks, b, batch, k = 3, 4, 2, 2
        D, C = self._chain(rng, nblocks, b, batch)
        B = rng.standard_normal((batch, nblocks, b, k))
        L, Wt, finfo = blocktri.factor(
            jnp.asarray(D, jnp.float32), jnp.asarray(C, jnp.float32),
            impl="xla")
        assert not np.any(np.asarray(finfo))
        X1, i1, r1 = _bt(jnp.asarray(D), jnp.asarray(C), jnp.asarray(B))
        X2, i2, r2 = _bt(jnp.asarray(D), jnp.asarray(C), jnp.asarray(B),
                         factor=(L, Wt))
        np.testing.assert_array_equal(np.asarray(X1), np.asarray(X2))
        assert not np.any(np.asarray(i2))  # resident factors install clean
        np.testing.assert_array_equal(
            np.asarray(r1.iters), np.asarray(r2.iters))


# --------------------------------------------------------------------------
# TSQR escalation: ops/tsqr + the in-graph rung + RobustInfo.gate
# --------------------------------------------------------------------------


def _illcond(m, n, cond, dtype, seed=0):
    rng = np.random.default_rng(seed)
    Q0, _ = np.linalg.qr(rng.standard_normal((m, n)))
    V, _ = np.linalg.qr(rng.standard_normal((n, n)))
    s = np.logspace(0, -np.log10(cond), n)
    return jnp.asarray(Q0 @ np.diag(s) @ V.T, dtype=dtype)


class TestTsqrEscalation:
    def test_escalate_recovers_cond_1e12(self):
        from capital_tpu.ops import tsqr as tsqr_mod

        A = _illcond(2048, 64, 1e12, jnp.float32)
        Q, R, ortho = recovery.tsqr_escalate(A)
        assert Q.dtype == recovery.escalation_dtype(jnp.float32)
        assert float(ortho) <= 1e-13  # the bench-refine gate
        assert float(tsqr_mod.ortho_gate(Q)) == pytest.approx(
            float(ortho), rel=1e-3)
        A64 = np.asarray(A, np.float64)
        resid = np.linalg.norm(
            A64 - np.asarray(Q, np.float64) @ np.asarray(R, np.float64))
        assert resid / np.linalg.norm(A64) < 1e-6  # f32 input rounding

    def test_in_graph_rung_recovers_beyond_envelope(self):
        # the f32 cond 1e12 case is FUNDAMENTALLY beyond the shift/sCQR3
        # envelope (test_robust BEYOND_ENVELOPE): without the rung it must
        # come back with the honest-failure sentinel and gate=GATE_ORTHO;
        # with RobustConfig.tsqr the f64 rung recovers it in-graph
        g = Grid.square(c=1, devices=[jax.devices()[0]])
        M, N = 384, 48
        A = _illcond(M, N, 1e12, jnp.float32)
        cfg0 = CacqrConfig(regime="1d", robust=RobustConfig())
        _, _, ri0 = qr.factor(g, A, cfg0)
        assert int(ri0.info) == N + 2
        assert int(ri0.gate) == GATE_ORTHO

        cfg = CacqrConfig(regime="1d", robust=RobustConfig(tsqr=True))
        Q, R, ri = qr.factor(g, A, cfg)
        assert int(ri.info) == 0
        assert int(ri.gate) == GATE_NONE
        tol64 = 100.0 * N * recovery.unit_roundoff(jnp.dtype(jnp.float64))
        assert float(ri.ortho) <= tol64
        resid = np.linalg.norm(
            np.asarray(A, np.float64)
            - np.asarray(Q, np.float64) @ np.asarray(R, np.float64))
        assert resid / np.linalg.norm(np.asarray(A, np.float64)) < 1e-4

    def test_healthy_path_gate_none(self):
        g = Grid.square(c=1, devices=[jax.devices()[0]])
        A = _illcond(384, 48, 1e3, jnp.float64)
        _, _, ri = qr.factor(
            g, A, CacqrConfig(regime="1d", robust=RobustConfig(tsqr=True)))
        assert int(ri.info) == 0 and int(ri.breakdown) == 0
        assert int(ri.gate) == GATE_NONE


# --------------------------------------------------------------------------
# accuracy_tier through serve (docs/SERVING.md "Accuracy tiers")
# --------------------------------------------------------------------------


CFG = ServeConfig(
    buckets=(16,), rows_buckets=(64,), nrhs_buckets=(2,),
    max_batch=2, max_delay_s=0.0, small_n_impl="vmap",
)


@pytest.fixture(scope="module")
def engine():
    return SolveEngine(cfg=CFG)


@pytest.fixture(scope="module")
def tier_problem():
    rng = np.random.default_rng(31)
    n, nrhs = 16, 2
    G = rng.standard_normal((n, n))
    A = (G @ G.T / n + 3.0 * np.eye(n)).astype(np.float32)
    B = rng.standard_normal((n, nrhs)).astype(np.float32)
    return A, B


class TestServeTiers:
    def test_guaranteed_tier_end_to_end(self, engine, tier_problem):
        A, B = tier_problem
        r = engine.solve("posv", A, B, accuracy_tier="guaranteed")
        assert r.ok, r.error
        Xr = np.linalg.solve(np.asarray(A, np.float64), B)
        # f32 request, f64 sweeps: the answer is f32-representation-grade
        assert np.asarray(r.x).dtype == np.float32
        np.testing.assert_allclose(np.asarray(r.x), Xr, rtol=2e-6, atol=2e-6)

    def test_fast_tier_downcast_factor(self, engine, tier_problem):
        A, B = tier_problem
        r = engine.solve("posv", A, B, accuracy_tier="fast")
        assert r.ok, r.error
        assert np.asarray(r.x).dtype == np.float32  # request dtype out
        Xr = np.linalg.solve(np.asarray(A, np.float64), B)
        # bf16 factor on a cond~3 operand: coarse but correct
        assert np.linalg.norm(np.asarray(r.x) - Xr) / np.linalg.norm(Xr) < 0.1

    def test_tiers_compile_separate_buckets_then_stay_warm(
            self, engine, tier_problem):
        A, B = tier_problem
        compiles = {}
        for tier in ("balanced", "fast", "guaranteed"):
            before = engine.cache_stats()["compiles"]
            assert engine.solve("posv", A, B, accuracy_tier=tier).ok
            compiles[tier] = engine.cache_stats()["compiles"] - before
        # each tier owns its executable (first use may compile; a tier
        # warmed by an earlier test legitimately reports 0)
        warm = engine.cache_stats()["compiles"]
        for _ in range(2):
            for tier in ("balanced", "fast", "guaranteed"):
                assert engine.solve("posv", A, B, accuracy_tier=tier).ok
        assert engine.cache_stats()["compiles"] == warm  # zero recompiles

    def test_nonconvergence_is_a_failed_response(self, engine):
        rng = np.random.default_rng(37)
        A = np.asarray(_spd_cond(rng, 16, 1e8)[0], np.float32)
        B = rng.standard_normal((16, 2)).astype(np.float32)
        r = engine.solve("posv", A, B, accuracy_tier="guaranteed")
        assert not r.ok
        assert "did not converge" in r.error

    def test_non_tier_op_rejects_vocabulary(self, engine, tier_problem):
        A, _ = tier_problem
        with pytest.raises(ValueError, match="accuracy_tier"):
            engine.solve("inv", A, accuracy_tier="guaranteed")

    def test_oversize_tiered_request_fails_loud(self, engine):
        rng = np.random.default_rng(41)
        n = 64  # beyond the (16,) ladder
        G = rng.standard_normal((n, n)).astype(np.float32)
        A = (G @ G.T / n + 3.0 * np.eye(n, dtype=np.float32))
        B = rng.standard_normal((n, 2)).astype(np.float32)
        r = engine.solve("posv", A, B, accuracy_tier="guaranteed")
        assert not r.ok
        assert "no oversize route" in r.error

    def test_stats_carry_refine_block(self, engine):
        rec = engine.emit_stats()
        rs = rec["request_stats"]
        assert "refine" in rs  # guaranteed traffic happened above
        blk = rs["refine"]
        assert blk["requests"] == blk["converged"] + blk["nonconverged"]
        assert blk["nonconverged"] >= 1  # the loud-failure test landed here
        assert ledger.validate_request_stats(rs) == []

    def test_warmup_specs_accept_tier(self):
        eng = SolveEngine(cfg=CFG)
        n_compiles = eng.warmup(
            [("posv", (16, 16), (16, 2), "float32", "guaranteed")])
        assert n_compiles >= 1
        before = eng.cache_stats()["compiles"]
        rng = np.random.default_rng(43)
        G = rng.standard_normal((16, 16))
        A = (G @ G.T / 16 + 3.0 * np.eye(16)).astype(np.float32)
        B = rng.standard_normal((16, 2)).astype(np.float32)
        assert eng.solve("posv", A, B, accuracy_tier="guaranteed").ok
        assert eng.cache_stats()["compiles"] == before  # warmup covered it


class TestRouterTierPassThrough:
    def test_guaranteed_through_router(self):
        from capital_tpu.serve.replica import ThreadReplica
        from capital_tpu.serve.router import Router, RouterConfig

        import time

        router = Router(RouterConfig(policy="bucket_affinity"))
        router.add_replica(ThreadReplica("ra", CFG))
        router.add_replica(ThreadReplica("rb", CFG))
        try:
            rng = np.random.default_rng(47)
            G = rng.standard_normal((16, 16))
            A = (G @ G.T / 16 + 3.0 * np.eye(16)).astype(np.float32)
            B = rng.standard_normal((16, 2)).astype(np.float32)
            tickets = [
                router.submit("posv", A, B, accuracy_tier=t)
                for t in ("balanced", "guaranteed", "guaranteed")
            ]
            deadline = time.monotonic() + 120.0
            while not all(t.done for t in tickets):
                router.pump()
                assert time.monotonic() < deadline, "tickets never landed"
                time.sleep(1e-3)
            for t in tickets:
                res = t.result()
                assert res.ok, res.error
            Xr = np.linalg.solve(np.asarray(A, np.float64), B)
            np.testing.assert_allclose(
                np.asarray(tickets[1].result().x), Xr, rtol=2e-6, atol=2e-6)
            # the aggregate record (last) carries the merged refine block
            merged = router.emit_stats()[-1]["request_stats"]
            assert merged["refine"]["requests"] == 2
            assert merged["refine"]["converged_frac"] == 1.0
        finally:
            router.stop()


# --------------------------------------------------------------------------
# stats / obs seams
# --------------------------------------------------------------------------


class TestStatsRefineBlock:
    def test_absent_without_guaranteed_traffic(self):
        c = stats.Collector()
        c.record_request("posv", 0.01, ok=True)
        assert "refine" not in c.snapshot()

    def test_block_contents_and_nan_filter(self):
        c = stats.Collector()
        c.record_request("posv", 0.01, ok=True)
        c.note_refine(2, True, 1e-15)
        c.note_refine(3, True, 4e-15)
        c.note_refine(8, False, float("nan"))  # factor breakdown shape
        blk = c.snapshot()["refine"]
        assert blk["requests"] == 3
        assert blk["converged"] == 2 and blk["nonconverged"] == 1
        assert blk["converged_frac"] == pytest.approx(0.6667, abs=1e-4)
        assert blk["iters_max"] == 8
        # NaN resid counts as nonconverged but stays out of the max
        assert blk["resid_max"] == pytest.approx(4e-15)
        assert blk["iters"]["p50"] >= 2.0

    def test_merge_sums_counts_and_maxes_tails(self):
        c = stats.Collector()
        c.record_request("posv", 0.01, ok=True)
        c.note_refine(2, True, 1e-15)
        s1 = c.snapshot()
        c2 = stats.Collector()
        c2.record_request("posv", 0.01, ok=True)
        c2.note_refine(5, False, 3e-12)
        s2 = c2.snapshot()
        merged = stats.merge_snapshots([s1, s2])["refine"]
        assert merged["requests"] == 2
        assert merged["converged"] == 1 and merged["nonconverged"] == 1
        assert merged["converged_frac"] == pytest.approx(0.5)
        assert merged["iters_max"] == 5
        assert merged["resid_max"] == pytest.approx(3e-12)
        # replicas without guaranteed traffic don't erase the block
        c3 = stats.Collector()
        c3.record_request("posv", 0.01, ok=True)
        assert "refine" in stats.merge_snapshots([s1, c3.snapshot()])
        assert "refine" not in stats.merge_snapshots(
            [c3.snapshot(), c3.snapshot()])

    def test_validate_request_stats_refine_block(self):
        c = stats.Collector()
        c.record_request("posv", 0.01, ok=True)
        c.note_refine(2, True, 1e-15)
        good = c.snapshot()
        assert ledger.validate_request_stats(good) == []
        bad = dict(good, refine=dict(good["refine"], converged_frac=1.5))
        assert any("converged_frac" in p
                   for p in ledger.validate_request_stats(bad))
        bad = dict(good, refine=dict(good["refine"], iters_max=-1))
        assert any("iters_max" in p
                   for p in ledger.validate_request_stats(bad))


def _refine_measured(**over):
    m = {
        "metric": "refine_speedup", "value": 0.008, "unit": "TFLOP/s",
        "n": 1024, "nrhs": 4, "batch": 4,
        "factor_dtype": "float32", "correction_dtype": "float64",
        "speedup": 1.8, "refined_ms": 220.0, "baseline_ms": 130.0,
        "end_to_end_speedup": 0.59, "resid_ratio": 1.7, "iters": 3,
        "tsqr_ortho": 4.6e-16,
        "wall_ms": {"p50": 266.0, "p95": 268.0, "p99": 268.0},
        "serve_smoke": {"requests": 24, "recompiles": 0},
    }
    m.update(over)
    return m


class TestValidateRefineMeasured:
    def test_valid(self):
        assert ledger.validate_refine_measured(_refine_measured()) == []
        bare = _refine_measured()
        del bare["tsqr_ortho"], bare["serve_smoke"]
        assert ledger.validate_refine_measured(bare) == []

    @pytest.mark.parametrize("field,value,frag", [
        ("n", 0, "n must be"),
        ("factor_dtype", "", "factor_dtype"),
        ("speedup", -1.0, "speedup must be"),
        ("resid_ratio", -0.5, "resid_ratio"),
        ("iters", 2.5, "iters"),
        ("tsqr_ortho", -1e-16, "tsqr_ortho"),
        ("wall_ms", {"p50": 1.0}, "wall_ms.p9"),
        ("serve_smoke", {"requests": 24, "recompiles": -1}, "recompiles"),
    ])
    def test_invalid(self, field, value, frag):
        m = _refine_measured(**{field: value})
        assert any(frag in p for p in ledger.validate_refine_measured(m))

    def test_diff_validates_refine_records(self):
        rec = {"manifest": {"schema_version": ledger.SCHEMA_VERSION,
                            "device": "cpu"},
               "measured": _refine_measured(speedup=-1.0)}
        with pytest.raises(ledger.LedgerIncompatible, match="refine"):
            ledger.diff([rec], [rec])


class TestServeReportRefineGates:
    def _emit(self, path, iters=(2, 3), nonconv=0):
        c = stats.Collector()
        c.record_request("posv", 0.01, ok=True)
        for it in iters:
            c.note_refine(it, True, 1e-15)
        for _ in range(nonconv):
            c.note_refine(8, False, 1e-3)
        c.emit(str(path))

    def test_gates_pass(self, tmp_path, capsys):
        path = tmp_path / "serve.jsonl"
        self._emit(path)
        assert obs_main.main(["serve-report", str(path),
                              "--max-refine-iters", "6",
                              "--min-converged-frac", "0.99"]) == 0
        assert "refine requests=2" in capsys.readouterr().out

    def test_iters_gate_fails(self, tmp_path, capsys):
        path = tmp_path / "serve.jsonl"
        self._emit(path, iters=(2, 7))
        assert obs_main.main(["serve-report", str(path),
                              "--max-refine-iters", "6"]) == 1
        assert "iters_max" in capsys.readouterr().err

    def test_converged_frac_gate_fails(self, tmp_path, capsys):
        path = tmp_path / "serve.jsonl"
        self._emit(path, nonconv=1)
        assert obs_main.main(["serve-report", str(path),
                              "--min-converged-frac", "0.99"]) == 1
        assert "converged_frac" in capsys.readouterr().err

    def test_fails_loudly_when_block_missing(self, tmp_path, capsys):
        path = tmp_path / "serve.jsonl"
        c = stats.Collector()
        c.record_request("posv", 0.01, ok=True)
        c.emit(str(path))
        assert obs_main.main(["serve-report", str(path),
                              "--max-refine-iters", "6"]) == 1
        assert "no record carries a refine block" in capsys.readouterr().err


class TestLintTarget:
    def test_refine_target_registered(self):
        from capital_tpu.lint import targets

        assert "refine" in targets.TARGET_NAMES
