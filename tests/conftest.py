"""Test harness: an 8-device virtual CPU mesh + float64.

The reference exercises distributed behavior with oversubscribed
``mpirun -n 8`` on one box (SURVEY §4); the JAX equivalent is
``--xla_force_host_platform_device_count=8`` — 8 virtual CPU devices that run
real XLA collectives, so every sharding/collective path is tested without TPU
hardware.  float64 is enabled to reproduce the reference's ~1e-14 f64
residual gates (bench/cholesky/cholinv.cpp:61-66).

These env vars must be set before jax initializes, hence the top of conftest.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"  # force: the session env pins the TPU platform
# The suite assumes exactly 8 virtual devices; strip any pre-existing count.
flags = [
    f
    for f in os.environ.get("XLA_FLAGS", "").split()
    if "xla_force_host_platform_device_count" not in f
]
flags.append("--xla_force_host_platform_device_count=8")
os.environ["XLA_FLAGS"] = " ".join(flags)

import jax  # noqa: E402

# jax may already be imported (pytest plugins) with the session's TPU platform
# baked into its config defaults — override through the config API, which works
# any time before backend initialization.
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

import pytest  # noqa: E402

from capital_tpu.parallel.topology import Grid  # noqa: E402


@pytest.fixture(scope="session")
def grid2x2x2() -> Grid:
    """2x2x2 grid — the full 3D SUMMA topology (d=2, c=2)."""
    return Grid.square(c=2)


@pytest.fixture(scope="session")
def grid2x2x1() -> Grid:
    """2x2 face grid, no replication depth (d=2, c=1)."""
    return Grid.square(c=1, devices=jax.devices("cpu")[:4])


@pytest.fixture(scope="session")
def grid_flat8() -> Grid:
    """8x1x1 — the 1D tall-skinny topology."""
    return Grid.flat()


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: excluded from the tier-1 gate (pytest -m 'not slow'); "
        "covered by `make audit` targets instead",
    )
