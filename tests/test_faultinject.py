"""Fault-injection tests: deterministic corruption at phase-tagged taps,
detection + recovery of the injected breakdown, and the raise kind that
exercises the sweep containment path (docs/ROBUSTNESS.md)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from capital_tpu.models import qr
from capital_tpu.models.qr import CacqrConfig
from capital_tpu.parallel.topology import Grid
from capital_tpu.robust import RobustConfig, faultinject as fi, recovery


def _grid1():
    return Grid.square(c=1, devices=[jax.devices()[0]])


def _well(m=256, n=32, seed=7):
    rng = np.random.default_rng(seed)
    return jnp.asarray(rng.standard_normal((m, n)) / np.sqrt(m), jnp.float64)


class TestPlanMechanics:
    def test_tap_identity_without_plan(self):
        x = jnp.arange(6.0).reshape(2, 3)
        assert fi.tap(x) is x

    def test_unknown_tag_rejected(self):
        with pytest.raises(ValueError, match="not in tracing.PHASE_REGISTRY"):
            fi.Fault(tag="CQR::nope")

    def test_unknown_kind_rejected(self):
        with pytest.raises(ValueError, match="fault kind"):
            fi.Fault(tag="CQR::gram", kind="meteor")

    def test_occurrence_selection_is_deterministic(self):
        f = fi.Fault(tag="CQR::gram", kind="nan", index=1)
        x = jnp.ones((2, 2))
        with fi.active_plan(f) as plan:
            y0 = fi.tap(x, point="CQR::gram")  # occurrence 0: untouched
            y1 = fi.tap(x, point="CQR::gram")  # occurrence 1: poisoned
        assert bool(jnp.all(jnp.isfinite(y0)))
        assert not bool(jnp.all(jnp.isfinite(y1)))
        assert plan.fired == [("CQR::gram", 1)]

    def test_raise_is_a_jax_runtime_error(self):
        assert issubclass(fi.FaultInjected, jax.errors.JaxRuntimeError)
        with fi.active_plan(fi.Fault(tag="CQR::gram", kind="raise")):
            with pytest.raises(fi.FaultInjected):
                fi.tap(jnp.ones(2), point="CQR::gram")

    def test_rank_deficient_zeroes_border(self):
        x = jnp.ones((4, 4))
        with fi.active_plan(fi.Fault(tag="CQR::gram", kind="rank_deficient")):
            y = fi.tap(x, point="CQR::gram")
        assert bool(jnp.all(y[-1, :] == 0)) and bool(jnp.all(y[:, -1] == 0))
        assert bool(jnp.all(y[:-1, :-1] == 1))


class TestInjectedBreakdown:
    def test_nan_gram_detected(self):
        g = _grid1()
        A = _well()
        cfg = CacqrConfig(regime="1d", robust=RobustConfig())
        with fi.active_plan(fi.Fault(tag="CQR::gram", kind="nan")) as plan:
            Q, R, ri = qr.factor(g, A, cfg)
        assert plan.fired and plan.fired[0] == ("CQR::gram", 0)
        assert int(ri.breakdown) > 0  # the poisoned gram broke the factor

    def test_rank_deficient_gram_recovers(self):
        # a singular-but-finite gram is exactly the shifted-retry case:
        # the shift restores positive-definiteness and sCQR3 polishes
        g = _grid1()
        A = _well()
        n = A.shape[1]
        cfg = CacqrConfig(regime="1d", robust=RobustConfig())
        with fi.active_plan(
            fi.Fault(tag="CQR::gram", kind="rank_deficient")
        ) as plan:
            Q, R, ri = qr.factor(g, A, cfg)
        assert plan.fired == [("CQR::gram", 0)]
        assert int(ri.breakdown) > 0
        assert int(ri.shifted) > 0
        assert bool(jnp.all(jnp.isfinite(Q)))
        # note the CONTRACT here: info reports honestly — the corrupted
        # gram no longer describes A, so we assert finiteness + flags, not
        # orthogonality of Q against the uncorrupted A
        assert int(ri.info) in (0, n + 2)

    def test_without_robust_nan_propagates(self):
        g = _grid1()
        A = _well()
        cfg = CacqrConfig(regime="1d")
        with fi.active_plan(fi.Fault(tag="CQR::gram", kind="nan")):
            Q, R = qr.factor(g, A, cfg)
        assert not bool(jnp.all(jnp.isfinite(Q)))  # the baseline failure

    def test_plan_scopes_cleanly(self):
        # after the context exits, factorization is pristine again
        g = _grid1()
        A = _well()
        cfg = CacqrConfig(regime="1d", robust=RobustConfig())
        with fi.active_plan(fi.Fault(tag="CQR::gram", kind="nan")):
            qr.factor(g, A, cfg)
        Q, R, ri = qr.factor(g, A, cfg)
        assert int(ri.breakdown) == 0 and int(ri.info) == 0


class TestContainmentPath:
    def test_injected_raise_contained_by_run_guarded(self):
        from capital_tpu.bench import harness

        calls = {"n": 0}

        def flaky():
            calls["n"] += 1
            if calls["n"] == 1:
                raise fi.FaultInjected("injected")
            return 42

        out, attempts = harness.run_guarded(
            flaky, policy=harness.RetryPolicy(retries=1, backoff_s=0.0),
            label="t",
        )
        assert out == 42 and attempts == 2

    def test_exhausted_retries_raise_config_failed(self):
        from capital_tpu.bench import harness

        def always():
            raise fi.FaultInjected("injected")

        with pytest.raises(harness.ConfigFailed) as ei:
            harness.run_guarded(
                always, policy=harness.RetryPolicy(retries=1, backoff_s=0.0),
                label="cfg7",
            )
        assert ei.value.label == "cfg7" and ei.value.attempts == 2
        assert isinstance(ei.value.cause, jax.errors.JaxRuntimeError)
