"""Online factor maintenance (PR 12): ops/update_small rank-k Cholesky
update/downdate, models/blocktri.extend, the serve FactorCache, and the
factor-residency wire protocol through a real SolveEngine.

The acceptance properties of ISSUE 12 / docs/SERVING.md "Factor residency"
are asserted directly:

* update/downdate match f64 NumPy refactor references across an (n, k)
  ladder on both impls (TestUpdateParity);
* breakdown surfaces as a nonzero info, never a silent wrong answer, and a
  flagged downdate degrades to a fresh refactor from the still-resident
  factor (TestBreakdown, TestDowndateDegrade);
* extending a factored chain equals refactoring the whole chain
  (TestExtendParity);
* the FactorCache enforces its byte budget by LRU eviction with tombstones
  (TestFactorCache);
* factor traffic causes ZERO steady-state executable compiles, and
  ServeConfig.factor_cache_bytes stays out of the executable identity
  (TestServeResidency, TestCfgHashSeparation);
* an injected ingest fault corrupts exactly one request — neighbor tokens'
  resident factors stay bitwise intact (TestFaultContainment).

Everything runs on the conftest CPU rig (x64 on, f32 arrays kept f32
explicitly); engines use tiny bucket ladders so every executable compiles
fast.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from capital_tpu.models import blocktri
from capital_tpu.obs import __main__ as obs_main
from capital_tpu.obs import ledger
from capital_tpu.ops import update_small
from capital_tpu.robust import faultinject
from capital_tpu.serve import ServeConfig, SolveEngine, stats
from capital_tpu.serve.factorcache import FactorCache


def _spd(rng, n, dtype=np.float32):
    M = rng.standard_normal((n, n))
    return (M @ M.T / n + 3.0 * np.eye(n)).astype(dtype)


def _chol_upper(A):
    return np.linalg.cholesky(np.asarray(A, np.float64)).T


def _rel_err(R, A):
    """‖RᵀR − A‖_F / ‖A‖_F in f64 — the bench-update residual gate."""
    R = np.asarray(R, np.float64)
    A = np.asarray(A, np.float64)
    return float(np.linalg.norm(R.T @ R - A) / np.linalg.norm(A))


def _tol(dtype):
    return 5e-5 if np.dtype(dtype) == np.float32 else 1e-12


# ---------------------------------------------------------------------------
# ops/update_small parity ladders
# ---------------------------------------------------------------------------


class TestUpdateParity:
    @pytest.mark.parametrize("n,k", [(8, 1), (16, 4), (48, 8)])
    @pytest.mark.parametrize("impl", ["pallas", "xla"])
    def test_update_downdate_roundtrip_f32(self, n, k, impl):
        rng = np.random.default_rng(n * 31 + k)
        batch = 2
        A = np.stack([_spd(rng, n, np.float64) for _ in range(batch)])
        R = np.stack([_chol_upper(a) for a in A]).astype(np.float32)
        V = ((0.1 / np.sqrt(n))
             * rng.standard_normal((batch, n, k))).astype(np.float32)
        R1, i1 = update_small.chol_update(
            jnp.asarray(R), jnp.asarray(V), impl=impl)
        assert i1.dtype == jnp.int32 and i1.shape == (batch,)
        assert not np.any(np.asarray(i1))
        Ap = A + np.asarray(V, np.float64) @ np.asarray(
            V, np.float64).transpose(0, 2, 1)
        for b in range(batch):
            assert _rel_err(R1[b], Ap[b]) < _tol(np.float32)
            # the factor stays upper triangular
            assert not np.any(np.tril(np.asarray(R1[b]), -1))
        R2, i2 = update_small.chol_downdate(R1, jnp.asarray(V), impl=impl)
        assert not np.any(np.asarray(i2))
        for b in range(batch):
            assert _rel_err(R2[b], A[b]) < _tol(np.float32)

    @pytest.mark.parametrize("n,k", [(16, 2), (64, 8)])
    def test_update_downdate_roundtrip_f64(self, n, k):
        # f64 always routes to the XLA panel scan (_resolve_impl)
        rng = np.random.default_rng(n + k)
        A = _spd(rng, n, np.float64)[None]
        R = _chol_upper(A[0])[None]
        V = ((0.1 / np.sqrt(n)) * rng.standard_normal((1, n, k)))
        R1, i1 = update_small.chol_update(
            jnp.asarray(R), jnp.asarray(V), impl="auto")
        assert not np.any(np.asarray(i1))
        Ap = A + V @ V.transpose(0, 2, 1)
        assert _rel_err(R1[0], Ap[0]) < _tol(np.float64)
        R2, i2 = update_small.chol_downdate(R1, jnp.asarray(V))
        assert not np.any(np.asarray(i2))
        assert _rel_err(R2[0], A[0]) < _tol(np.float64)

    def test_impls_agree(self):
        n, k = 32, 4
        rng = np.random.default_rng(7)
        R = _chol_upper(_spd(rng, n, np.float64))[None].astype(np.float32)
        V = ((0.1 / np.sqrt(n))
             * rng.standard_normal((1, n, k))).astype(np.float32)
        Rp, _ = update_small.chol_update(
            jnp.asarray(R), jnp.asarray(V), impl="pallas")
        Rx, _ = update_small.chol_update(
            jnp.asarray(R), jnp.asarray(V), impl="xla")
        # different rotation orders — agreement to f32 sweep tolerance,
        # checked through the reconstruction both must reproduce
        Ap = (np.asarray(R[0], np.float64).T @ np.asarray(R[0], np.float64)
              + np.asarray(V[0], np.float64) @ np.asarray(V[0], np.float64).T)
        assert _rel_err(Rp[0], Ap) < 5e-5
        assert _rel_err(Rx[0], Ap) < 5e-5

    def test_shape_validation(self):
        R = jnp.eye(8)[None]
        with pytest.raises(ValueError, match="rank-k batch"):
            update_small.chol_update(R, jnp.zeros((1, 4, 2)))
        with pytest.raises(ValueError, match="factor batch"):
            update_small.chol_update(jnp.zeros((8, 8)), jnp.zeros((8, 2)))


class TestBreakdown:
    @pytest.mark.parametrize("impl", ["pallas", "xla"])
    def test_infeasible_downdate_flags(self, impl):
        n, k = 16, 2
        rng = np.random.default_rng(3)
        A = _spd(rng, n, np.float64)
        R = _chol_upper(A)[None].astype(np.float32)
        # removing 100·(first columns of Rᵀ) is far outside A: indefinite
        W = (10.0 * _chol_upper(A).T[:, :k])[None].astype(np.float32)
        _, info = update_small.chol_downdate(
            jnp.asarray(R), jnp.asarray(W), impl=impl)
        assert int(np.asarray(info)[0]) != 0

    @pytest.mark.parametrize("impl", ["pallas", "xla"])
    def test_nonfinite_operand_flags_update(self, impl):
        n, k = 16, 2
        rng = np.random.default_rng(4)
        R = _chol_upper(_spd(rng, n, np.float64))[None].astype(np.float32)
        V = np.zeros((1, n, k), np.float32)
        V[0, 0, 0] = np.nan
        _, info = update_small.chol_update(
            jnp.asarray(R), jnp.asarray(V), impl=impl)
        assert int(np.asarray(info)[0]) != 0

    def test_only_failed_problem_flags(self):
        # batch containment: problem 0 infeasible, problem 1 clean
        n, k = 16, 2
        rng = np.random.default_rng(5)
        A = np.stack([_spd(rng, n, np.float64) for _ in range(2)])
        R = np.stack([_chol_upper(a) for a in A]).astype(np.float32)
        W = np.stack([
            10.0 * _chol_upper(A[0]).T[:, :k],
            (0.1 / np.sqrt(n)) * rng.standard_normal((n, k)),
        ]).astype(np.float32)
        R2, info = update_small.chol_downdate(
            jnp.asarray(R), jnp.asarray(W), impl="xla")
        info = np.asarray(info)
        assert info[0] != 0 and info[1] == 0
        A1m = A[1] - np.asarray(W[1], np.float64) @ np.asarray(
            W[1], np.float64).T
        assert _rel_err(R2[1], A1m) < _tol(np.float32)


# ---------------------------------------------------------------------------
# models/blocktri.extend == full refactor
# ---------------------------------------------------------------------------


class TestExtendParity:
    def _chain(self, rng, nblocks, b, dtype=np.float32):
        D = np.stack([_spd(rng, b, np.float64) for _ in range(nblocks)])
        C = 0.1 * rng.standard_normal((nblocks, b, b))
        C[0] = 0.0
        return D.astype(dtype)[None], C.astype(dtype)[None]

    @pytest.mark.parametrize("impl", ["xla", "pallas"])
    def test_extend_equals_refactor(self, impl):
        rng = np.random.default_rng(11)
        nblocks, b, split = 6, 8, 4
        D, C = self._chain(rng, nblocks, b)
        Lf, Wtf, inf_full = blocktri.factor(
            jnp.asarray(D), jnp.asarray(C), impl=impl)
        assert not np.any(np.asarray(inf_full))
        Lp, Wtp, inf_p = blocktri.factor(
            jnp.asarray(D[:, :split]), jnp.asarray(C[:, :split]), impl=impl)
        Ls, Wts, inf_s = blocktri.extend(
            jnp.asarray(D[:, split:]), jnp.asarray(C[:, split:]),
            Lp[:, -1], impl=impl)
        assert not np.any(np.asarray(inf_p)) and not np.any(np.asarray(inf_s))
        # the recurrence is identical step for step: bitwise equality
        np.testing.assert_array_equal(
            np.concatenate([np.asarray(Lp), np.asarray(Ls)], axis=1),
            np.asarray(Lf))
        np.testing.assert_array_equal(
            np.concatenate([np.asarray(Wtp), np.asarray(Wts)], axis=1),
            np.asarray(Wtf))

    def test_extend_info_offset(self):
        rng = np.random.default_rng(12)
        nblocks, b = 4, 8
        D, C = self._chain(rng, nblocks, b)
        D = np.array(D)
        D[0, 2] = -np.eye(b, dtype=np.float32)  # appended block 2 breaks
        Lp, _, _ = blocktri.factor(
            jnp.asarray(D[:, :1]), jnp.asarray(C[:, :1]))
        _, _, info0 = blocktri.extend(
            jnp.asarray(D[:, 1:]), jnp.asarray(C[:, 1:]), Lp[:, -1])
        _, _, info_off = blocktri.extend(
            jnp.asarray(D[:, 1:]), jnp.asarray(C[:, 1:]), Lp[:, -1],
            offset=1 * b)
        i0, ioff = int(np.asarray(info0)[0]), int(np.asarray(info_off)[0])
        assert i0 != 0
        # offset shifts the SEGMENT-relative pivot by the prefix length
        assert ioff == i0 + 1 * b


# ---------------------------------------------------------------------------
# serve/factorcache.FactorCache
# ---------------------------------------------------------------------------


class TestFactorCache:
    def _R(self, n, fill=1.0):
        return jnp.asarray(np.eye(n, dtype=np.float32) * fill)

    def test_put_lookup_counters(self):
        fc = FactorCache(budget_bytes=1 << 20)
        assert fc.lookup("a") is None
        fc.put("a", "dense", (self._R(8),), {"n": 8})
        ent = fc.lookup("a")
        assert ent is not None and ent.kind == "dense"
        st = fc.stats()
        assert st["hits"] == 1 and st["misses"] == 1
        assert st["installs"] == 1 and st["entries"] == 1
        assert st["bytes"] == 8 * 8 * 4
        assert st["hit_rate"] == pytest.approx(0.5)

    def test_byte_budget_evicts_lru(self):
        one = 8 * 8 * 4
        fc = FactorCache(budget_bytes=2 * one)
        fc.put("a", "dense", (self._R(8),), {})
        fc.put("b", "dense", (self._R(8),), {})
        assert fc.lookup("a") is not None  # refresh a: b is now LRU
        evicted = fc.put("c", "dense", (self._R(8),), {})
        assert evicted == ["b"]
        assert fc.lookup("b") is None and fc.evicted("b")
        assert fc.lookup("a") is not None and fc.lookup("c") is not None
        st = fc.stats()
        assert st["evictions"] == 1 and st["entries"] == 2
        assert st["bytes"] <= st["budget_bytes"]

    def test_oversized_entry_kept_newest(self):
        # one entry over budget: everything older evicts, newest stays
        one = 8 * 8 * 4
        fc = FactorCache(budget_bytes=one)
        fc.put("a", "dense", (self._R(8),), {})
        fc.put("big", "dense", (self._R(16),), {})
        assert fc.lookup("a") is None
        assert fc.lookup("big") is not None

    def test_release_clears_tombstone(self):
        fc = FactorCache(budget_bytes=1 << 20)
        fc.put("a", "dense", (self._R(8),), {})
        assert fc.release("a") is True
        assert fc.release("a") is False
        assert not fc.evicted("a")  # released, not evicted: no tombstone
        assert fc.stats()["released"] == 1
        assert len(fc) == 0

    def test_reseed_discards_tombstone(self):
        one = 8 * 8 * 4
        fc = FactorCache(budget_bytes=one)
        fc.put("a", "dense", (self._R(8),), {})
        fc.put("b", "dense", (self._R(8),), {})  # evicts a -> tombstone
        assert fc.evicted("a")
        fc.put("a", "dense", (self._R(8),), {})  # re-seed discards it
        assert not fc.evicted("a")


# ---------------------------------------------------------------------------
# the serve wire protocol (docs/SERVING.md "Factor residency")
# ---------------------------------------------------------------------------


CFG = ServeConfig(
    buckets=(16, 32),
    rows_buckets=(64,),
    nrhs_buckets=(2, 4),
    nblocks_buckets=(2, 4),
    block_buckets=(8,),
    max_batch=2,
    max_delay_s=0.0,
)


@pytest.fixture(scope="module")
def engine():
    return SolveEngine(cfg=CFG)


@pytest.fixture(scope="module")
def problem():
    rng = np.random.default_rng(0)
    n, k, nrhs = 16, 2, 2
    A = _spd(rng, n)
    B = rng.standard_normal((n, nrhs)).astype(np.float32)
    V = ((0.1 / np.sqrt(n)) * rng.standard_normal((n, k))).astype(np.float32)
    return n, k, A, B, V


class TestServeResidency:
    def test_protocol_end_to_end(self, engine, problem):
        n, k, A, B, V = problem
        eng = engine
        A64 = np.asarray(A, np.float64)

        # miss -> seeds; hit -> potrs-only solve
        r = eng.solve("posv_cached", A, B, factor_token="tokA")
        assert r.ok, r.error
        st = eng.factor_stats()
        assert st["misses"] >= 1 and st["installs"] >= 1
        r2 = eng.solve("posv_cached", A, B, factor_token="tokA")
        assert r2.ok, r2.error
        np.testing.assert_allclose(
            np.asarray(r2.x), np.linalg.solve(A64, B), atol=5e-4)

        # rank-k update of the resident factor: client ships only V
        r3 = eng.solve("chol_update", V, factor_token="tokA")
        assert r3.ok, r3.error
        A2 = A64 + np.asarray(V, np.float64) @ np.asarray(V, np.float64).T
        assert _rel_err(np.asarray(r3.x), A2) < 5e-5

        # solve against the UPDATED resident factor
        r4 = eng.solve("posv_cached", A2.astype(np.float32), B,
                       factor_token="tokA")
        assert r4.ok, r4.error
        np.testing.assert_allclose(
            np.asarray(r4.x), np.linalg.solve(A2, B), atol=5e-4)

        # downdate back to A
        r5 = eng.solve("chol_downdate", V, factor_token="tokA")
        assert r5.ok, r5.error
        assert _rel_err(np.asarray(r5.x), A64) < 5e-5

        # steady state: the whole mix recompiles nothing
        before = eng.cache_stats()["compiles"]
        for _ in range(3):
            assert eng.solve("posv_cached", A, B, factor_token="tokA").ok
            assert eng.solve("chol_update", V, factor_token="tokA").ok
            assert eng.solve("chol_downdate", V, factor_token="tokA").ok
        assert eng.cache_stats()["compiles"] == before

        # the emitted record carries the factor_cache block and validates
        rec = eng.emit_stats()
        fc = rec["request_stats"]["factor_cache"]
        assert fc["installs"] >= 1 and fc["hits"] >= 1
        assert ledger.validate_request_stats(rec["request_stats"]) == []

    def test_never_seeded_token_fails_loudly(self, engine, problem):
        _, _, _, _, V = problem
        r = engine.solve("chol_update", V, factor_token="nope")
        assert not r.ok
        assert "not resident" in r.error and "SERVING.md" in r.error

    def test_factor_token_on_non_factor_op_rejected(self, engine, problem):
        _, _, A, B, _ = problem
        with pytest.raises(ValueError, match="factor_token"):
            engine.solve("posv", A, B, factor_token="tokA")
        with pytest.raises(ValueError, match="factor_token"):
            engine.solve("chol_update", B)

    def test_blocktri_extend_matches_refactor(self, engine):
        rng = np.random.default_rng(21)
        nb, b = 2, 8
        D = np.stack([_spd(rng, b) for _ in range(2 * nb)])
        C = (0.1 * rng.standard_normal((2 * nb, b, b))).astype(np.float32)
        C[0] = 0.0
        r1 = engine.solve(
            "blocktri_extend", np.stack([D[:nb], C[:nb]]),
            factor_token="chain1")
        assert r1.ok, r1.error
        r2 = engine.solve(
            "blocktri_extend", np.stack([D[nb:], C[nb:]]),
            factor_token="chain1")
        assert r2.ok, r2.error
        ent = engine.factors.peek("chain1")
        assert ent is not None and ent.kind == "blocktri"
        Lf, Wtf, info = blocktri.factor(
            jnp.asarray(D, jnp.float32)[None], jnp.asarray(C)[None])
        assert not np.any(np.asarray(info))
        np.testing.assert_array_equal(
            np.asarray(ent.arrays[0]), np.asarray(Lf)[0])
        np.testing.assert_array_equal(
            np.asarray(ent.arrays[1]), np.asarray(Wtf)[0])

    def test_evicted_chain_fails_loudly(self, engine):
        rng = np.random.default_rng(22)
        b = 8
        D = np.stack([_spd(rng, b) for _ in range(2)])
        C = np.zeros((2, b, b), np.float32)
        engine.factors._tombstones.add("chain-gone")
        r = engine.solve("blocktri_extend", np.stack([D, C]),
                         factor_token="chain-gone")
        assert not r.ok and "EVICTED" in r.error


class TestDowndateDegrade:
    def test_infeasible_downdate_fails_loud_factor_intact(
            self, engine, problem):
        n, k, A, B, V = problem
        A64 = np.asarray(A, np.float64)
        assert engine.solve("posv_cached", A, B, factor_token="tokD").ok
        W = (10.0 * _chol_upper(A64).T[:, :k]).astype(np.float32)
        before = engine.factor_stats()["downdate_degrades"]
        r = engine.solve("chol_downdate", W, factor_token="tokD")
        assert not r.ok
        assert "degrade refactor ALSO failed" in r.error
        assert engine.factor_stats()["downdate_degrades"] == before + 1
        # the resident factor survived BOTH failures untouched
        ent = engine.factors.peek("tokD")
        assert _rel_err(np.asarray(ent.arrays[0]), A64) < 5e-5

    def test_degrade_success_installs_refactor(self, engine, problem):
        # drive the landing sink with a simulated sweep flag: the degrade
        # must refactor S = RᵀR − VVᵀ from the RESIDENT factor and install
        # it with RobustInfo(escalated=1) — the recovery half of the
        # docs/ROBUSTNESS.md downdate contract, deterministic here because
        # the sweep itself (correctly) refuses to flag feasible problems.
        n, k, A, B, V = problem
        A64 = np.asarray(A, np.float64)
        assert engine.solve("posv_cached", A, B, factor_token="tokE").ok
        sink = engine._update_sink("chol_downdate", "tokE", n, jnp.asarray(V))
        garbage = jnp.full((n, n), jnp.nan, jnp.float32)
        x, info, err = sink(garbage, (), jnp.int32(3))
        assert err is None
        assert info.info == 0 and info.breakdown == 1 and info.escalated == 1
        Am = A64 - np.asarray(V, np.float64) @ np.asarray(V, np.float64).T
        ent = engine.factors.peek("tokE")
        assert _rel_err(np.asarray(ent.arrays[0]), Am) < 5e-5
        assert _rel_err(np.asarray(x), Am) < 5e-5

    def test_update_flag_refuses_result(self, engine, problem):
        n, k, A, B, V = problem
        assert engine.solve("posv_cached", A, B, factor_token="tokF").ok
        ent0 = engine.factors.peek("tokF")
        R0 = np.asarray(ent0.arrays[0]).copy()
        sink = engine._update_sink("chol_update", "tokF", n, jnp.asarray(V))
        x, info, err = sink(jnp.zeros((n, n), jnp.float32), (), jnp.int32(2))
        assert err is not None and "left unchanged" in err
        np.testing.assert_array_equal(
            np.asarray(engine.factors.peek("tokF").arrays[0]), R0)


class TestCfgHashSeparation:
    def test_factor_cache_bytes_not_in_executable_identity(self):
        a = SolveEngine(cfg=CFG)
        b = SolveEngine(
            cfg=ServeConfig(**{**CFG.__dict__,
                               "factor_cache_bytes": 1 << 30}))
        assert a.cfg.factor_cache_bytes != b.cfg.factor_cache_bytes
        assert a._cfg_hash == b._cfg_hash

    def test_bucket_change_does_alter_identity(self):
        a = SolveEngine(cfg=CFG)
        c = SolveEngine(cfg=ServeConfig(**{**CFG.__dict__,
                                           "buckets": (16, 64)}))
        assert a._cfg_hash != c._cfg_hash


class TestFaultContainment:
    def test_ingest_fault_corrupts_one_request_only(self):
        rng = np.random.default_rng(33)
        n, k = 16, 2
        eng = SolveEngine(cfg=CFG)
        A1, A2 = _spd(rng, n), _spd(rng, n)
        B = rng.standard_normal((n, 2)).astype(np.float32)
        V = ((0.1 / np.sqrt(n))
             * rng.standard_normal((n, k))).astype(np.float32)
        assert eng.solve("posv_cached", A1, B, factor_token="tokX").ok
        assert eng.solve("posv_cached", A2, B, factor_token="tokY").ok
        RX = np.asarray(eng.factors.peek("tokX").arrays[0]).copy()
        RY = np.asarray(eng.factors.peek("tokY").arrays[0]).copy()
        with faultinject.active_plan(
            faultinject.Fault(tag="serve::ingest", kind="nan"),
        ) as plan:
            r = eng.solve("chol_update", V, factor_token="tokX")
        assert plan.fired == [("serve::ingest", 0)]
        # the poisoned sweep flags; landing refuses the corrupt result
        assert not r.ok and "left unchanged" in r.error
        # BOTH resident factors bitwise intact, and the neighbor still
        # serves clean updates afterwards
        np.testing.assert_array_equal(
            np.asarray(eng.factors.peek("tokX").arrays[0]), RX)
        np.testing.assert_array_equal(
            np.asarray(eng.factors.peek("tokY").arrays[0]), RY)
        r2 = eng.solve("chol_update", V, factor_token="tokY")
        assert r2.ok, r2.error


# ---------------------------------------------------------------------------
# stats / obs seams
# ---------------------------------------------------------------------------


def _fc_block(hits=8, misses=2, **over):
    blk = {
        "hits": hits, "misses": misses, "evictions": 1, "installs": 3,
        "released": 0, "downdate_degrades": 0, "entries": 2,
        "bytes": 1024, "budget_bytes": 4096,
        "hit_rate": hits / (hits + misses) if hits + misses else 1.0,
    }
    blk.update(over)
    return blk


class TestStatsFactorBlock:
    def test_block_absent_without_factor_traffic(self):
        snap = stats.Collector().snapshot(
            factor_cache=_fc_block(hits=0, misses=0, installs=0))
        assert "factor_cache" not in snap

    def test_block_attached_and_merged(self):
        c = stats.Collector()
        c.record_request("posv_cached", 0.01, ok=True)
        s1 = c.snapshot(factor_cache=_fc_block(hits=8, misses=2))
        s2 = c.snapshot(factor_cache=_fc_block(hits=2, misses=8))
        merged = stats.merge_snapshots([s1, s2])
        fc = merged["factor_cache"]
        assert fc["hits"] == 10 and fc["misses"] == 10
        assert fc["hit_rate"] == pytest.approx(0.5)
        # mixed fleets: replicas without the block don't lose it
        s3 = c.snapshot()
        assert "factor_cache" in stats.merge_snapshots([s1, s3])
        assert "factor_cache" not in stats.merge_snapshots([s3, s3])

    def test_validate_request_stats_factor_block(self):
        c = stats.Collector()
        c.record_request("chol_update", 0.01, ok=True)
        good = c.snapshot(factor_cache=_fc_block())
        assert ledger.validate_request_stats(good) == []
        bad = dict(good, factor_cache=_fc_block(hits=-1))
        assert any("factor_cache.hits" in p
                   for p in ledger.validate_request_stats(bad))
        bad = dict(good, factor_cache=_fc_block(hit_rate=1.5))
        assert any("hit_rate" in p
                   for p in ledger.validate_request_stats(bad))
        # hit_rate must be consistent with the counters it claims
        bad = dict(good, factor_cache=_fc_block(hits=8, misses=2,
                                                hit_rate=0.3))
        assert any("inconsistent" in p
                   for p in ledger.validate_request_stats(bad))


def _update_measured(**over):
    m = {
        "metric": "update_speedup", "value": 0.006, "unit": "TFLOP/s",
        "n": 1024, "k": 16, "batch": 2, "impl": "auto", "speedup": 6.0,
        "refactor_ms": 36.0, "update_ms": 6.0,
        "wall_ms": {"p50": 12.0, "p95": 13.0, "p99": 13.0},
        "serve_smoke": {"requests": 50, "hit_rate": 0.92, "recompiles": 0},
    }
    m.update(over)
    return m


class TestValidateUpdateMeasured:
    def test_valid(self):
        assert ledger.validate_update_measured(_update_measured()) == []
        no_smoke = _update_measured()
        del no_smoke["serve_smoke"]
        assert ledger.validate_update_measured(no_smoke) == []

    @pytest.mark.parametrize("field,value,frag", [
        ("n", 0, "n must be"),
        ("impl", "cuda", "impl must be"),
        ("speedup", -1.0, "speedup must be"),
        ("wall_ms", {"p50": 1.0}, "wall_ms.p9"),
        ("serve_smoke", {"requests": 50, "hit_rate": 2.0, "recompiles": 0},
         "hit_rate"),
    ])
    def test_invalid(self, field, value, frag):
        m = _update_measured(**{field: value})
        assert any(frag in p for p in ledger.validate_update_measured(m))

    def test_diff_validates_update_records(self, tmp_path):
        rec = {"manifest": {"schema_version": ledger.SCHEMA_VERSION,
                            "device": "cpu"},
               "measured": _update_measured(speedup=-1.0)}
        with pytest.raises(ledger.LedgerIncompatible, match="update"):
            ledger.diff([rec], [rec])


class TestServeReportResidencyGate:
    def _emit(self, path, fc):
        c = stats.Collector()
        c.record_request("posv_cached", 0.01, ok=True)
        rec = c.emit(str(path), factor_cache=fc)
        return rec

    def test_gate_passes_and_prints(self, tmp_path, capsys):
        path = tmp_path / "serve.jsonl"
        self._emit(path, _fc_block(hits=9, misses=1))
        assert obs_main.main(["serve-report", str(path),
                              "--min-residency-hit-rate", "0.9"]) == 0
        assert "factor_cache hits=9" in capsys.readouterr().out

    def test_gate_fails_below_floor(self, tmp_path, capsys):
        path = tmp_path / "serve.jsonl"
        self._emit(path, _fc_block(hits=1, misses=9))
        assert obs_main.main(["serve-report", str(path),
                              "--min-residency-hit-rate", "0.9"]) == 1
        assert "factor-residency hit_rate" in capsys.readouterr().err

    def test_gate_fails_loudly_when_block_missing(self, tmp_path, capsys):
        path = tmp_path / "serve.jsonl"
        c = stats.Collector()
        c.record_request("posv", 0.01, ok=True)
        c.emit(str(path))
        assert obs_main.main(["serve-report", str(path),
                              "--min-residency-hit-rate", "0.5"]) == 1
        assert "no record carries a factor_cache block" in (
            capsys.readouterr().err)
