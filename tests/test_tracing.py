"""Tracing / cost-model subsystem tests.

The reference's profiling layer (critter, SURVEY §5.1) decomposes cost per
algorithm phase; here the equivalent is trace-time cost attribution under
named scopes.  These tests check the attribution wiring, the analytic model's
arithmetic, and the table writers.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from capital_tpu.models import cholesky, qr
from capital_tpu.parallel.topology import Grid
from capital_tpu.utils import tracing


def _spd(n, dtype=jnp.float64, seed=0):
    rng = np.random.default_rng(seed)
    M = rng.standard_normal((n, n))
    return jnp.asarray(M @ M.T + n * np.eye(n), dtype=dtype)


def test_gemm_cost_arithmetic(grid2x2x2):
    M = N = K = 64
    flops, comm, ncoll = tracing.gemm_cost(grid2x2x2, M, N, K, jnp.float32)
    # flops split evenly over 8 devices
    assert flops == pytest.approx(2 * M * N * K / 8)
    # c=2 takes the masked-psum branch: d/c = 1 step, one psum-bcast pair of
    # the (M/2, K/2) and (K/2, N/2) panels (2x ring bytes each), plus the z
    # allreduce of the C block — what _explicit_matmul emits for c>1
    # (TestExplicitEmission::test_psum_bcast_path_matches_model_c2)
    a_pan = (M / 2) * (K / 2) * 4
    b_pan = (K / 2) * (N / 2) * 4
    c_blk = (M / 2) * (N / 2) * 4
    expect = 2 * a_pan * 0.5 + 2 * b_pan * 0.5 + 2 * c_blk * 0.5
    assert comm == pytest.approx(expect)
    assert ncoll == 3
    # and the c=1 branch prices the amortized gathers
    g1 = Grid.square(c=1, devices=jax.devices("cpu")[:4])
    _, comm1, ncoll1 = tracing.gemm_cost(g1, M, N, K, jnp.float32)
    a_row = (M / 2) * K * 4
    b_col = K * (N / 2) * 4
    assert comm1 == pytest.approx(a_row * 0.5 + b_col * 0.5)
    assert ncoll1 == 2


def test_single_device_costs_no_comm():
    g1 = Grid.square(c=1, devices=jax.devices("cpu")[:1])
    flops, comm, ncoll = tracing.gemm_cost(g1, 32, 32, 32, jnp.float32)
    assert comm == 0.0 and ncoll == 0
    assert flops == pytest.approx(2 * 32**3)


def test_recorder_captures_cholinv_phases(grid2x2x1):
    n = 64
    A = _spd(n)
    cfg = cholesky.CholinvConfig(base_case_dim=16)
    with tracing.Recorder() as rec:
        R, Rinv = jax.jit(lambda a: cholesky.factor(grid2x2x1, a, cfg))(A)
    jax.block_until_ready((R, Rinv))
    tags = set(rec.stats)
    assert {"CI::factor_diag", "CI::trsm", "CI::tmu", "CI::inv"} <= tags
    total = rec.total()
    assert total.flops > 0 and total.calls > 0
    # base case: at least one panel factorization worth of flops
    assert rec.stats["CI::factor_diag"].flops >= tracing.potrf_trtri_flops(16)
    # distributed trmm moves bytes on a 2x2 grid
    assert rec.stats["CI::trsm"].comm_bytes > 0


def test_recorder_captures_cacqr_phases(grid_flat8):
    m, n = 256, 16
    rng = np.random.default_rng(1)
    A = jnp.asarray(rng.standard_normal((m, n)))
    with tracing.Recorder() as rec:
        Q, R = jax.jit(
            lambda a: qr.factor(grid_flat8, a, qr.CacqrConfig(num_iter=2, regime="1d"))
        )(A)
    jax.block_until_ready((Q, R))
    assert {"CQR::gram", "CQR::chol", "CQR::formR", "CQR::merge"} <= set(rec.stats)
    # two sweeps -> gram recorded twice
    assert rec.stats["CQR::gram"].calls == 2
    # gram flops: 2mn^2/P per sweep
    assert rec.stats["CQR::gram"].flops == pytest.approx(2 * 2 * m * n * n / 8)
    # the gram allreduce is the only collective of the 1D sweep
    assert rec.stats["CQR::gram"].collectives == 2


def test_recorder_inactive_is_free(grid2x2x1):
    # emit with no active recorder must not raise or leak state
    tracing.emit(flops=1.0)
    with tracing.Recorder() as rec:
        pass
    assert rec.total().flops == 0


def test_estimate_and_tables(tmp_path, grid2x2x1):
    A = _spd(32)
    cfg = cholesky.CholinvConfig(base_case_dim=16)
    with tracing.Recorder() as rec:
        out = jax.jit(lambda a: cholesky.factor(grid2x2x1, a, cfg))(A)
    jax.block_until_ready(out)
    est = rec.estimate_seconds(tracing.device_spec(), jnp.float64)
    assert all(c >= 0 and m >= 0 for c, m in est.values())

    times = tmp_path / "cp_times.txt"
    costs = tmp_path / "cp_costs.txt"
    tracing.write_times_table(str(times), [("cfg0", 0.123, est)])
    tracing.write_costs_table(str(costs), [("cfg0", rec)])
    t_lines = times.read_text().splitlines()
    c_lines = costs.read_text().splitlines()
    assert len(t_lines) == 2 and t_lines[0].startswith("Config")
    assert "Raw" in t_lines[0] and "0.123" in t_lines[1]
    assert len(c_lines) == 2 and "CI::trsm-comp" in c_lines[0]


def test_note_counts_under_own_tag():
    with tracing.Recorder() as rec:
        tracing.note("layout_fallback")
        tracing.note("layout_fallback")
    assert rec.stats["layout_fallback"].calls == 2
    assert rec.stats["layout_fallback"].flops == 0.0
    tracing.note("layout_fallback")  # no active recorder: must be a no-op


def test_tables_with_empty_rows(tmp_path):
    # an all-UNRESOLVED sweep still writes its tables; header-only output,
    # no max() crash on the empty column set
    times = tmp_path / "t.txt"
    costs = tmp_path / "c.txt"
    tracing.write_times_table(str(times), [])
    tracing.write_costs_table(str(costs), [])
    assert times.read_text().splitlines() == ["Config  Raw     "]
    assert costs.read_text().splitlines()[0].startswith("Config")


def test_estimate_seconds_prices_alpha_latency():
    # the comm term is beta (bytes/bandwidth) PLUS alpha per collective;
    # same bytes at a higher synchronization count must cost more
    spec = tracing.DeviceSpec("test", 100.0, 1000.0, 100.0, alpha_s=1e-6)
    few, many = tracing.Recorder(), tracing.Recorder()
    with few:
        with tracing.scope("CI::trsm"):
            tracing.emit(1e9, 1e6, collectives=1)
    with many:
        with tracing.scope("CI::trsm"):
            tracing.emit(1e9, 1e6, collectives=100)
    _, comm_few = few.estimate_seconds(spec, jnp.bfloat16)["CI::trsm"]
    _, comm_many = many.estimate_seconds(spec, jnp.bfloat16)["CI::trsm"]
    assert comm_many == pytest.approx(comm_few + 99 * spec.alpha_s)
    beta = 1e6 / (spec.ici_gbps * 1e9)
    assert comm_few == pytest.approx(beta + spec.alpha_s)


def test_scope_rejects_unregistered_tag():
    with pytest.raises(ValueError, match="unregistered phase tag"):
        with tracing.scope("XX::nope"):
            pass


def test_register_phase_extends_live_registry():
    tag = "XX::test_only"
    assert tag not in tracing.PHASE_REGISTRY
    try:
        tracing.register_phase(tag)
        assert tag in tracing.PHASE_REGISTRY
        with tracing.Recorder() as rec:
            with tracing.scope(tag):
                tracing.emit(flops=1.0)
        assert rec.stats[tag].flops == 1.0
        # the trace tool's dot-form buckets see live registrations
        from capital_tpu.bench import trace as trace_tool

        assert "XX.test_only" in trace_tool._phase_tags()
    finally:
        # registry is module-global: restore to keep other tests order-free
        tracing.PHASE_REGISTRY = tuple(
            t for t in tracing.PHASE_REGISTRY if t != tag
        )
        tracing._PHASE_SET.discard(tag)


def test_trace_tool_tags_derive_from_registry():
    from capital_tpu.bench import trace as trace_tool

    # _phase_tags() is the live derivation (PHASE_TAGS is a snapshot frozen
    # at import, which another test's transient registration may predate)
    assert set(trace_tool._phase_tags()) == {
        t.replace("::", ".") for t in tracing.PHASE_REGISTRY
    }
    # the tag the old hardcoded list silently dropped to 'other'
    assert "RT.batch_write" in trace_tool.PHASE_TAGS


def test_measure_returns_sane_wall():
    f = jax.jit(lambda x: x @ x)
    x = jnp.ones((64, 64))
    t = tracing.measure(f, x, iters=2, repeats=2)
    assert 0 < t < 5.0


def test_device_spec_lookup():
    s = tracing.device_spec(jax.devices("cpu")[0])
    assert s.name == "cpu"
    assert tracing.device_spec().peak_tflops(jnp.float32) > 0


class TestTriFractions:
    """VERDICT r2 #4: the max-per-process vs volumetric executed-flop views
    of the explicit schedule's dead-segment skipping, verified against an
    independent element-level enumeration of triangle/rectangle
    intersections."""

    @staticmethod
    def _brute(M, K, N, d, c, q, a_uplo=None, b_uplo=None, out_uplo=None):
        import numpy as np

        lk, w = K // d, K // d // max(1, q)
        mb, nb = M // d, N // d
        spl = d // c
        fracs = []
        for zi in range(c):
            segs = range(d) if c == 1 else [zi * spl + i for i in range(spl)]
            for xi in range(d):
                for yi in range(d):
                    if out_uplo is not None:
                        rows = np.arange(xi * mb, (xi + 1) * mb)[:, None]
                        cols = np.arange(yi * nb, (yi + 1) * nb)[None, :]
                        live_o = (
                            (rows <= cols) if out_uplo == "U" else (rows >= cols)
                        ).any()
                        if not live_o:
                            fracs.append(0.0)
                            continue
                    live = 0
                    for s in segs:
                        for ch in range(q):
                            klo = s * lk + ch * w
                            ks = np.arange(klo, klo + w)
                            ok = True
                            if a_uplo is not None:
                                rows = np.arange(xi * mb, (xi + 1) * mb)[:, None]
                                tri = (
                                    (rows <= ks[None, :])
                                    if a_uplo == "U"
                                    else (rows >= ks[None, :])
                                )
                                ok = ok and bool(tri.any())
                            if b_uplo is not None:
                                cols = np.arange(yi * nb, (yi + 1) * nb)[None, :]
                                tri = (
                                    (ks[:, None] <= cols)
                                    if b_uplo == "U"
                                    else (ks[:, None] >= cols)
                                )
                                ok = ok and bool(tri.any())
                            live += bool(ok)
                    fracs.append(live / (len(segs) * q))
        return sum(fracs) / len(fracs), max(fracs)

    @pytest.mark.parametrize("d", [2, 4])
    def test_matches_brute_force_and_closed_form(self, d):
        import types

        from capital_tpu.parallel import summa

        # tri_fractions is pure shape arithmetic: a stub grid covers the
        # d=4 face (16 devices) the 8-device rig cannot build
        g = types.SimpleNamespace(dx=d, dy=d, c=1, num_chunks=0, num_devices=d * d)
        n = 64
        mean_f, max_f = summa.tri_fractions(g, n, n, n, a_uplo="U")
        bm, bx = self._brute(n, n, n, d, 1, 1, a_uplo="U")
        assert (mean_f, max_f) == (bm, bx)
        # closed form: device row xi executes (d-xi)/d of the segments
        assert max_f == 1.0
        assert mean_f == pytest.approx((d + 1) / (2 * d))

    def test_c2_and_chunks_match_brute_force(self, grid2x2x2):
        from capital_tpu.parallel import summa

        g = grid2x2x2
        for kw in (dict(a_uplo="L"), dict(b_uplo="U"), dict(out_uplo="U")):
            got = summa.tri_fractions(g, 64, 64, 64, **kw)
            want = self._brute(64, 64, 64, g.dx, g.c, 1, **kw)
            assert got == want, (kw, got, want)

    def test_recorder_carries_three_views(self, grid2x2x1):
        from capital_tpu.parallel import summa

        g = grid2x2x1
        M = jax.device_put(
            jnp.asarray(np.random.default_rng(0).standard_normal((64, 64))),
            g.face_sharding(),
        )
        with tracing.Recorder() as rec:
            jax.jit(
                lambda a: summa.trmm(
                    g, a, a, summa.TrmmArgs(side="L", uplo="U"), mode="explicit"
                )
            ).lower(M)
        st = rec.total()
        # homogeneous model: dense; executed: mean 3/4, critical path full
        assert st.flops_max == pytest.approx(st.flops)
        assert st.flops_vol == pytest.approx(0.75 * st.flops)
