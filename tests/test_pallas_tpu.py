"""Triangular-predicated Pallas kernel tests (interpret mode on the CPU rig).

Checks every structure-flag combination of ops/pallas_tpu.tri_matmul against
dense masked references, odd (non-tile-aligned) shapes, and the summa-layer
pallas mode end to end through cholinv (the consumer whose Schur windows
carry upper-triangle-only data)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from capital_tpu.models import cholesky
from capital_tpu.ops.pallas_tpu import default_blocks, tri_matmul
from capital_tpu.parallel import summa
from capital_tpu.parallel.topology import Grid
from capital_tpu.utils import rand48, residual


@pytest.fixture(scope="module")
def grid1():
    return Grid.square(c=1, devices=jax.devices("cpu")[:1])


@pytest.fixture(scope="module")
def mats():
    rng = np.random.default_rng(0)
    n, m = 300, 200  # deliberately not multiples of 128
    A = jnp.asarray(rng.standard_normal((n, n)))
    B = jnp.asarray(rng.standard_normal((n, m)))
    C = jnp.asarray(rng.standard_normal((m, n)))
    return A, B, C


def _close(got, want, tol=1e-10):
    assert float(jnp.max(jnp.abs(got - want))) < tol


def test_plain_matmul(mats):
    A, B, _ = mats
    _close(tri_matmul(A, B), A @ B)


def test_f32_three_pass_high():
    """precision='high' on f32 operands runs the in-kernel bf16x3
    split-accumulate (VERDICT r3 #3): ~f32-grade accuracy, far better than
    single-pass bf16, no in-kernel error."""
    rng = np.random.default_rng(7)
    n = 256
    A = jnp.asarray(rng.standard_normal((n, n)), jnp.float32)
    B = jnp.asarray(rng.standard_normal((n, n)), jnp.float32)
    want = np.asarray(A, np.float64) @ np.asarray(B, np.float64)
    scale = np.abs(want).max()

    def err(precision):
        got = tri_matmul(A, B, a_uplo="U", precision=precision)
        ref = np.triu(np.asarray(A, np.float64)) @ np.asarray(B, np.float64)
        return float(np.abs(np.asarray(got, np.float64) - ref).max()) / scale

    e_high = err("high")
    e_highest = err("highest")
    e_bf16 = float(
        np.abs(
            np.asarray(
                jnp.matmul(
                    jnp.triu(A).astype(jnp.bfloat16), B.astype(jnp.bfloat16),
                    preferred_element_type=jnp.float32,
                ),
                np.float64,
            )
            - np.triu(np.asarray(A, np.float64)) @ np.asarray(B, np.float64)
        ).max()
    ) / scale
    # 3-pass lands within an order of magnitude of full f32 and far below
    # single-pass bf16 (classic split-accumulate error profile)
    assert e_high < 50 * max(e_highest, 1e-9)
    assert e_high < e_bf16 / 20


@pytest.mark.parametrize("uplo", ["U", "L"])
@pytest.mark.parametrize("trans", [False, True])
def test_a_triangular(mats, uplo, trans):
    A, B, _ = mats
    T = jnp.triu(A) if uplo == "U" else jnp.tril(A)
    Top = T.T if trans else T
    _close(tri_matmul(A, B, a_uplo=uplo, a_trans=trans), Top @ B)


@pytest.mark.parametrize("uplo", ["U", "L"])
@pytest.mark.parametrize("trans", [False, True])
def test_b_triangular(mats, uplo, trans):
    A, _, C = mats
    T = jnp.triu(A) if uplo == "U" else jnp.tril(A)
    Top = T.T if trans else T
    _close(tri_matmul(C, A, b_uplo=uplo, b_trans=trans), C @ Top)


@pytest.mark.parametrize("uplo", ["U", "L"])
def test_syrk_out_triangle(mats, uplo):
    _, B, _ = mats
    full = B.T @ B
    want = jnp.triu(full) if uplo == "U" else jnp.tril(full)
    _close(tri_matmul(B, B, a_trans=True, out_uplo=uplo), want, tol=1e-9)


def test_alpha_and_explicit_blocks(mats):
    A, B, _ = mats
    _close(
        tri_matmul(A, B, a_uplo="U", alpha=-2.0, blocks=(128, 128, 128)),
        -2.0 * jnp.triu(A) @ B,
    )


def test_dead_triangle_ignored(mats):
    """Entries in the dead triangle must be treated as zero regardless of
    buffer contents (BLAS trmm contract)."""
    A, B, _ = mats
    garbage = A + jnp.tril(jnp.full_like(A, 1e6), k=-1)
    _close(tri_matmul(garbage, B, a_uplo="U"), jnp.triu(A) @ B)


def test_flag_validation(mats):
    A, B, _ = mats
    with pytest.raises(ValueError, match="at most one"):
        tri_matmul(A, A, a_uplo="U", b_uplo="L")
    with pytest.raises(ValueError, match="out_uplo"):
        tri_matmul(A, A, a_uplo="U", out_uplo="U")
    with pytest.raises(ValueError, match="mismatch"):
        tri_matmul(A, B.T)


def test_default_blocks_budget():
    from capital_tpu.ops.pallas_tpu import _device_budget

    cap, _ = _device_budget()  # 512 on the CPU rig, 1024 on v5e+
    bm, bn, bk = default_blocks(8192, 8192, 8192, itemsize=2)
    assert (bm, bn) == (cap, cap) and bk >= cap
    # f32 halves the dtype K-budget on every chip class (VMEM headroom)
    assert default_blocks(8192, 8192, 8192, itemsize=4)[2] <= 1024
    # skinny output tiles afford a deep K panel regardless of chip cap
    assert default_blocks(8192, 100000, 256, itemsize=2)[2] == 2048
    # small operands shrink to their padded size
    assert default_blocks(100, 100, 100) == (128, 128, 128)
    assert default_blocks(300, 8192, 8192)[0] == 384


def test_summa_trmm_pallas_mode(grid1, mats):
    A, B, _ = mats
    out = summa.trmm(
        grid1, A, B, summa.TrmmArgs(side="L", uplo="U", trans_a=True),
        mode="pallas",
    )
    _close(out, jnp.triu(A).T @ B)


def test_summa_syrk_pallas_mode_fused_beta(grid1, mats):
    """beta*C accumulates inside the kernel: the live (uplo) triangle carries
    alpha*AᵀA + beta*C; the dead half is UNDEFINED by contract (callers read
    only the live triangle — cholinv's Schur consumer does)."""
    A, B, _ = mats
    C0 = jnp.asarray(np.random.default_rng(1).standard_normal((B.shape[1],) * 2))
    out = summa.syrk(
        grid1, B, C0, summa.SyrkArgs(trans=True, alpha=-1.0, beta=1.0),
        mode="pallas",
    )
    want_upper = jnp.triu(-(B.T @ B) + C0)
    _close(jnp.triu(out), want_upper, tol=1e-9)


def test_tri_matmul_fused_beta_views():
    """Aligned in-kernel beta*C with every operand a window of a larger
    buffer — the exact shape of cholinv's Schur update at 128-multiples."""
    rng = np.random.default_rng(2)
    buf = jnp.asarray(rng.standard_normal((512, 512)))
    Rp = jnp.asarray(rng.standard_normal((512, 512)))
    got = tri_matmul(
        Rp, Rp, a_trans=True, b_trans=False, out_uplo="U", alpha=-1.0,
        a_view=(128, 256, 128, 256), b_view=(128, 256, 128, 256),
        c=buf, c_view=(256, 256, 256, 256), beta=1.0,
        blocks=(128, 128, 128),  # multi-tile: 2x2 output, 3 live tiles
    )
    R12 = Rp[128:256, 256:512]
    want = jnp.triu(-(R12.T @ R12) + buf[256:512, 256:512])
    _close(jnp.triu(got), want)
    # misaligned windows fall back to materializing but keep the same live
    # triangle
    got2 = tri_matmul(
        Rp, Rp, a_trans=True, b_trans=False, out_uplo="U", alpha=-1.0,
        a_view=(100, 200, 100, 200), b_view=(100, 200, 100, 200),
        c=buf, c_view=(200, 200, 200, 200), beta=1.0,
    )
    R12m = Rp[100:200, 200:400]
    wantm = jnp.triu(-(R12m.T @ R12m) + buf[200:400, 200:400])
    _close(jnp.triu(got2), wantm)


def test_tri_matmul_inplace_rmw_syrk():
    """In-place tri-output RMW: out IS the C buffer — live tiles are read,
    updated, and written back at the same offsets; every untouched region of
    the buffer (outside the window, and the window's dead half on the
    aligned path) is preserved.  This is the no-Schur-chain memory mode of
    cholinv (schur_in_place)."""
    rng = np.random.default_rng(7)
    buf = jnp.asarray(rng.standard_normal((512, 512)))
    Rp = jnp.asarray(rng.standard_normal((512, 512)))
    got = tri_matmul(
        Rp, Rp, a_trans=True, b_trans=False, out_uplo="U", alpha=-1.0,
        a_view=(128, 256, 128, 256), b_view=(128, 256, 128, 256),
        c=buf, c_view=(256, 256, 256, 256), beta=1.0,
        out=buf, out_off=(256, 256),
        blocks=(128, 128, 128),  # multi-tile: 2x2 output window, 3 live tiles
    )
    assert got.shape == buf.shape
    R12 = Rp[128:256, 256:512]
    want = jnp.triu(-(R12.T @ R12) + buf[256:512, 256:512])
    _close(jnp.triu(got[256:512, 256:512]), want)
    # untouched regions of the buffer survive the aliased write
    _close(got[:256, :], buf[:256, :])
    _close(got[256:, :256], buf[256:, :256])
    # aligned kernel path: the window's dead (strictly-lower) tiles are
    # never visited, so they keep the ORIGINAL buffer contents — here the
    # (1, 0) tile of the 2x2 window
    _close(got[384:512, 256:384], buf[384:512, 256:384])

    # shifted-window / non-C out combinations are rejected, not mis-written
    with pytest.raises(ValueError, match="out to BE the C operand"):
        tri_matmul(
            Rp, Rp, a_trans=True, out_uplo="U",
            a_view=(128, 256, 128, 256), b_view=(128, 256, 128, 256),
            c=buf, c_view=(256, 256, 256, 256), beta=1.0,
            out=buf, out_off=(0, 0),
        )

    # misaligned windows: the materializing fallback writes the full window
    # (dead half = beta*C, the documented fallback behavior) but preserves
    # everything outside it
    got2 = tri_matmul(
        Rp, Rp, a_trans=True, b_trans=False, out_uplo="U", alpha=-1.0,
        a_view=(100, 200, 100, 200), b_view=(100, 200, 100, 200),
        c=buf, c_view=(200, 200, 200, 200), beta=1.0,
        out=buf, out_off=(200, 200),
    )
    R12m = Rp[100:200, 200:400]
    wantm = jnp.triu(-(R12m.T @ R12m) + buf[200:400, 200:400])
    _close(jnp.triu(got2[200:400, 200:400]), wantm)
    _close(got2[:200, :], buf[:200, :])


def test_summa_syrk_in_place_modes(grid1):
    """summa.syrk(in_place=True) agrees with the out-of-place result across
    pallas and xla modes (window write-back semantics only differ in where
    the result lands)."""
    rng = np.random.default_rng(8)
    buf = jnp.asarray(rng.standard_normal((256, 256)))
    A = jnp.asarray(rng.standard_normal((256, 256)))
    args = summa.SyrkArgs(trans=True, alpha=-1.0, beta=1.0)
    for mode in ("pallas", "xla"):
        got = summa.syrk(
            grid1, A, buf, args, mode=mode,
            a_view=(0, 128, 128, 128), c_view=(128, 128, 128, 128),
            in_place=True,
        )
        R12 = A[0:128, 128:256]
        want = jnp.triu(-(R12.T @ R12) + buf[128:256, 128:256])
        _close(jnp.triu(got[128:, 128:]), want, tol=1e-9)
        _close(got[:128, :], buf[:128, :])


def test_tri_matmul_fused_beta_promotes_c_dtype():
    """Mixed dtypes: a wider C promotes the result exactly like the unfused
    `AB + beta*C` (mode='xla') would — on the aligned kernel path and the
    misaligned fallback alike."""
    rng = np.random.default_rng(3)
    A = jnp.asarray(rng.standard_normal((256, 256)), jnp.bfloat16)
    C = jnp.asarray(rng.standard_normal((256, 256)), jnp.float32)
    # the aligned kernel path adds C onto the f32 accumulator (slightly
    # BETTER than unfused); the misaligned fallback first rounds the product
    # to the operands' bf16 — exactly what unfused mode='xla' produces.
    # Each path gets its own bit-matched reference so tolerances stay tight.
    def product(a):
        return jnp.matmul(a.T, a, preferred_element_type=jnp.float32)

    got = tri_matmul(A, A, a_trans=True, out_uplo="U", c=C, beta=1.0)
    assert got.dtype == jnp.float32
    _close(jnp.triu(got), jnp.triu(product(A) + C), tol=1e-3)
    got2 = tri_matmul(
        A[:200, :200], A[:200, :200], a_trans=True, out_uplo="U",
        c=C[:200, :200], beta=1.0,
    )
    assert got2.dtype == jnp.float32
    want2 = product(A[:200, :200]).astype(jnp.bfloat16).astype(jnp.float32)
    # the kernel's blocked f32 accumulation and jnp.matmul's order can land
    # on opposite sides of a bf16 rounding boundary, so individual entries
    # may differ by one ulp (~1.0 at these ~200 magnitudes); boundary hits
    # are rare, so the MEAN stays tiny unless the beta*C term or a window
    # is actually wrong (dropping C would shift the mean by ~0.8)
    diff = jnp.abs(jnp.triu(got2) - jnp.triu(want2 + C[:200, :200]))
    assert float(jnp.max(diff)) < 1.5
    assert float(jnp.mean(diff)) < 0.01


def test_cholinv_pallas_mode_end_to_end(grid1):
    n = 192
    A = jnp.asarray(rand48.symmetric(n))
    cfg = cholesky.CholinvConfig(base_case_dim=64, mode="pallas")
    R, Rinv = jax.jit(lambda a: cholesky.factor(grid1, a, cfg))(A)
    assert float(residual.cholesky_residual(A, R)) < 1e-13
    assert float(residual.cholesky_inverse_residual(R, Rinv)) < 1e-13


def test_cholinv_schur_in_place_matches_default(grid1):
    """schur_in_place=True (the no-Schur-chain memory mode that fits n=49152
    on one v5e) must produce the same factor/inverse as the default — on the
    aligned pallas path (views + aliased RMW end to end) and on xla mode,
    and on a misaligned size that exercises the fallbacks."""
    for n, bc, mode in ((512, 128, "pallas"), (512, 128, "xla"), (192, 64, "pallas")):
        A = jnp.asarray(rand48.symmetric(n))
        base = cholesky.CholinvConfig(base_case_dim=bc, mode=mode)
        inpl = cholesky.CholinvConfig(
            base_case_dim=bc, mode=mode, schur_in_place=True
        )
        R0, RI0 = jax.jit(lambda a: cholesky.factor(grid1, a, base))(A)
        R1, RI1 = jax.jit(lambda a: cholesky.factor(grid1, a, inpl))(A)
        np.testing.assert_array_equal(np.asarray(R0), np.asarray(R1))
        np.testing.assert_array_equal(np.asarray(RI0), np.asarray(RI1))
        assert float(residual.cholesky_residual(A, R1)) < 1e-13


def test_cholinv_out_buffers_reuse(grid1):
    """factor(out_buffers=...): factoring into a PREVIOUS factor's outputs
    (the benchmark-loop carry that kills the hoisted-zeros copies) must give
    exactly the fresh-buffer result — every upper tile rewritten, dead lower
    zeros preserved."""
    # n = bc·2^k shapes only: with padding (p != n) factor returns CROPPED
    # arrays that cannot serve as the next call's p x p buffers
    for n, bc, mode in ((512, 128, "pallas"), (256, 64, "xla")):
        cfg = cholesky.CholinvConfig(base_case_dim=bc, mode=mode)
        A1 = jnp.asarray(rand48.symmetric(n))
        A2 = jnp.asarray(rand48.symmetric(n)) + 0.5 * jnp.eye(n)

        def chain(a1, a2):
            bufs = cholesky.factor_buffers(grid1, n, a1.dtype, cfg)
            R1, RI1 = cholesky.factor(grid1, a1, cfg, out_buffers=bufs)
            # second factor reuses the first's outputs as its buffers
            return cholesky.factor(grid1, a2, cfg, out_buffers=(R1, RI1))

        R2, RI2 = jax.jit(chain)(A1, A2)
        Rf, RIf = jax.jit(lambda a: cholesky.factor(grid1, a, cfg))(A2)
        np.testing.assert_array_equal(np.asarray(R2), np.asarray(Rf))
        np.testing.assert_array_equal(np.asarray(RI2), np.asarray(RIf))
    # contract violations are rejected
    cfg = cholesky.CholinvConfig(base_case_dim=64, complete_inv=False)
    with pytest.raises(ValueError, match="complete_inv"):
        cholesky.factor(
            grid1, jnp.asarray(rand48.symmetric(128)), cfg,
            out_buffers=(jnp.zeros((128, 128)), jnp.zeros((128, 128))),
        )


def test_cholinv_pallas_mode_aligned_views(grid1):
    """bc=128 at n=512: every window size/offset is a multiple of 128, so
    this drives the ALIGNED in-place path end to end — offset index maps for
    the trmm/syrk operand views and aliased `out`/`out_off` writes for the
    leaf transposes, TRSM, and inverse completion (the n=192/bc=64 test
    above always takes the _fit_block==0 materializing fallback, which
    would mask a regression in the aligned kernels)."""
    n = 512
    A = jnp.asarray(rand48.symmetric(n))
    cfg = cholesky.CholinvConfig(base_case_dim=128, mode="pallas")
    R, Rinv = jax.jit(lambda a: cholesky.factor(grid1, a, cfg))(A)
    assert float(residual.cholesky_residual(A, R)) < 1e-13
    assert float(residual.cholesky_inverse_residual(R, Rinv)) < 1e-13
    # dead halves must be true zeros (mask inside the aliased writes)
    assert float(jnp.abs(jnp.tril(R, -1)).max()) == 0.0
    assert float(jnp.abs(jnp.tril(Rinv, -1)).max()) == 0.0


class TestWriteDiagBlocks:
    """In-place aliased diagonal-block scatter (round 5 — the rectri
    batched-prefix write-back)."""

    def test_aligned_kernel_path(self):
        from capital_tpu.ops import pallas_tpu

        rng = np.random.default_rng(0)
        out = jnp.asarray(rng.standard_normal((512, 512)).astype(np.float32))
        W = jnp.asarray(rng.standard_normal((4, 128, 128)).astype(np.float32))
        # `out` is consumed (aliased donation): snapshot the expectation
        # BEFORE the call
        want = np.asarray(out).copy()
        for i in range(4):
            want[i * 128:(i + 1) * 128, i * 128:(i + 1) * 128] = np.asarray(W[i])
        got = np.asarray(pallas_tpu.write_diag_blocks(out, W))
        np.testing.assert_array_equal(got, want)

    def test_misaligned_falls_back_to_dus(self):
        from capital_tpu.ops import pallas_tpu

        rng = np.random.default_rng(1)
        out = jnp.asarray(rng.standard_normal((192, 192)).astype(np.float32))
        W = jnp.asarray(rng.standard_normal((3, 64, 64)).astype(np.float32))
        want = np.asarray(out).copy()
        for i in range(3):
            want[i * 64:(i + 1) * 64, i * 64:(i + 1) * 64] = np.asarray(W[i])
        got = np.asarray(pallas_tpu.write_diag_blocks(out, W))
        np.testing.assert_array_equal(got, want)

    def test_dtype_cast_on_write(self):
        from capital_tpu.ops import pallas_tpu

        out = jnp.zeros((256, 256), jnp.bfloat16)
        W = jnp.ones((2, 128, 128), jnp.float32) * 1.5
        got = np.asarray(pallas_tpu.write_diag_blocks(out, W), np.float32)
        assert got[0, 0] == 1.5 and got[255, 255] == 1.5 and got[0, 200] == 0.0
