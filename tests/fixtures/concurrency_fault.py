"""DELIBERATELY BROKEN concurrency fixture — the sanitizer's dead-gate.

This file commits the two bug classes the concurrency sanitizer exists
to catch, so the gate can prove it is alive on every run (`lint
concurrency` self-checks against it; `obs serve-report`'s dead-gate
discipline applied to the sanitizer itself):

* ``RacyCounter`` annotates its counters guarded-by ``self._lock`` and
  then increments them WITHOUT taking it — the static pass must emit
  guarded-by errors here, and the interleaving explorer must reproduce
  the lost update with a minimal failing schedule.
* ``LockCycle`` acquires its two locks in both orders — the static pass
  must report exactly one canonical lock-order cycle.

DO NOT FIX THIS FILE.  A sanitizer release that stops flagging it is
broken, not this fixture (tests/test_concurrency.py pins both halves,
and the CLI exits non-zero with a loud ``self-check-dead`` finding).
It lives under tests/fixtures — never imported by production code; the
CLI and tests load it by file path.
"""

import threading


class RacyCounter:
    """Annotated like a disciplined class, implemented like a bug:
    ``increment`` does an unguarded read-modify-write with an injectable
    yield point in the window, so the explorer can interleave a second
    thread between the read and the write and lose an update."""

    def __init__(self, yield_point=None):
        self._lock = threading.Lock()       # guarded-by: <lock>
        self.count = 0                      # guarded-by: self._lock
        self.increments = 0                 # guarded-by: self._lock
        self._yield = yield_point or (lambda reason: None)  # guarded-by: <frozen>

    def increment(self):
        v = self.count                      # BUG: unguarded read
        self._yield("between read and write")
        self.count = v + 1                  # BUG: unguarded write
        self.increments += 1                # BUG: unguarded read+write


class LockCycle:
    """Two locks, both orders: the canonical ABBA deadlock shape the
    lock-acquisition graph must report as a cycle."""

    def __init__(self):
        self._a = threading.Lock()          # guarded-by: <lock>
        self._b = threading.Lock()          # guarded-by: <lock>

    def left(self):
        with self._a:
            with self._b:
                pass

    def right(self):
        with self._b:
            with self._a:
                pass
