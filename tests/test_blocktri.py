"""Block-tridiagonal Cholesky fast-path tests (ISSUE 10 acceptance).

The properties pinned here, mapped to the issue's criteria:

* factor/solve/posv match the dense reference on the assembled matrix —
  and an independent SciPy banded solver — across (nblocks, b) ladders,
  xla f64 and pallas f32/bf16 (TestParity);
* the serve pad is structure-safe: appended identity chain blocks leave
  the real blocks' solution BITWISE unchanged (the chain is sequential,
  trailing blocks never feed back), in-block diag(D, I) embeds stay
  tight, and fill problems solve to exact zeros (TestPadding);
* per-block breakdown infos min-combine to one global LAPACK-convention
  pivot: a negative diagonal pins the EXACT global index, a NaN pins the
  block range while batch neighbors stay healthy, and the n+1 sentinel
  survives the merge (TestInfo, the combine_block_infos regression);
* dispatch plumbing: seg resolution, the f64 forced-pallas fallback
  (PR 6 contract: no silent precision downgrade), dead-C[0] hygiene,
  shape validation (TestDispatch);
* the engine buckets posv_blocktri with the zero-recompile invariant
  (same bucket -> cache hit), counts it in request_stats.ops, and keeps
  blocktri ladders in the config hash (TestServeBlocktri);
* bench:blocktri ledger records validate structurally and a malformed
  one is LedgerIncompatible, not silently compared (TestLedgerSeam).

Everything runs on the conftest CPU rig (x64 on): f64 chains resolve to
the xla scan, tests that want the pallas kernels say float32 explicitly
(interpret=None resolves to interpret mode off-TPU, so tier-1 executes
the actual kernel bodies).  Long chains (nblocks=256) are slow-marked.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from capital_tpu.models import blocktri
from capital_tpu.obs import ledger
from capital_tpu.robust import detect
from capital_tpu.serve import ServeConfig, SolveEngine, batching

# Small ladders so every executable compiles in well under a second (same
# posture as test_serve.CFG); blocktri gets its own two ladders.
BT_CFG = ServeConfig(
    buckets=(8, 16),
    rows_buckets=(32,),
    nrhs_buckets=(1, 4),
    max_batch=2,
    max_delay_s=10.0,
    nblocks_buckets=(2, 4),
    block_buckets=(4, 8),
)


def _chain(rng, batch, nblocks, b, k, dtype=np.float64):
    """A well-conditioned SPD chain + RHS (the sweep/driver operand
    recipe: gram/b + 3I diagonals, 0.3/sqrt(b) couplings, C[:, 0] dead)."""
    G = rng.standard_normal((batch, nblocks, b, b))
    D = G @ G.transpose(0, 1, 3, 2) / b + 3.0 * np.eye(b)
    C = 0.3 / np.sqrt(b) * rng.standard_normal((batch, nblocks, b, b))
    C[:, 0] = 0.0
    B = rng.standard_normal((batch, nblocks, b, k))
    return D.astype(dtype), C.astype(dtype), B.astype(dtype)


def _np_dense(D, C):
    """NumPy-side dense assembly of one problem's chain — independent of
    blocktri.assemble, so the reference never touches the code under
    test (the bench-driver discipline)."""
    nblocks, b = D.shape[0], D.shape[1]
    n = nblocks * b
    A = np.zeros((n, n), dtype=np.float64)
    for i in range(nblocks):
        sl = slice(i * b, (i + 1) * b)
        A[sl, sl] = D[i]
        if i:
            up = slice((i - 1) * b, i * b)
            A[sl, up] = C[i]
            A[up, sl] = C[i].T
    return A


def _dense_solve(D, C, B):
    """f64 dense reference X for a batched chain."""
    out = []
    for j in range(D.shape[0]):
        A = _np_dense(np.float64(D[j]), np.float64(C[j]))
        x = np.linalg.solve(A, np.float64(B[j]).reshape(A.shape[0], -1))
        out.append(x.reshape(B.shape[1:]))
    return np.stack(out)


# ---------------------------------------------------------------------------
# numerical parity: chain vs dense / SciPy
# ---------------------------------------------------------------------------


class TestParity:
    @pytest.mark.parametrize("nblocks,b", [(2, 4), (4, 8), (6, 4)])
    def test_posv_matches_dense_xla_f64(self, nblocks, b):
        rng = np.random.default_rng(20)
        D, C, B = _chain(rng, 2, nblocks, b, 3)
        X, info = blocktri.posv(jnp.asarray(D), jnp.asarray(C),
                                jnp.asarray(B), impl="xla")
        np.testing.assert_array_equal(np.asarray(info), 0)
        np.testing.assert_allclose(np.asarray(X), _dense_solve(D, C, B),
                                   rtol=0, atol=1e-11)

    @pytest.mark.parametrize("dtype,tol", [(jnp.float32, 5e-5),
                                           (jnp.bfloat16, 5e-2)])
    def test_posv_matches_dense_pallas(self, dtype, tol):
        rng = np.random.default_rng(21)
        D, C, B = _chain(rng, 2, 4, 8, 2)
        X, info = blocktri.posv(
            jnp.asarray(D, dtype), jnp.asarray(C, dtype),
            jnp.asarray(B, dtype), impl="pallas")
        ref = _dense_solve(D, C, B)
        np.testing.assert_array_equal(np.asarray(info), 0)
        err = np.abs(np.float64(np.asarray(X)) - ref).max()
        assert err < tol * np.abs(ref).max()

    def test_factor_reconstructs_chain(self):
        # L_i·L_iᵀ (+ W_i·W_iᵀ for i>0) rebuilds D_i, W_i·L_{i−1}ᵀ
        # rebuilds C_i — the residual the bench factor gate computes
        rng = np.random.default_rng(22)
        D, C, B = _chain(rng, 1, 4, 4, 1)
        L, Wt, info = blocktri.factor(jnp.asarray(D), jnp.asarray(C),
                                      impl="xla")
        assert int(info[0]) == 0
        Ln = np.float64(np.asarray(L))[0]
        Wn = np.float64(np.asarray(Wt))[0].transpose(0, 2, 1)  # W_i
        for i in range(4):
            rec = Ln[i] @ Ln[i].T + (Wn[i] @ Wn[i].T if i else 0.0)
            np.testing.assert_allclose(rec, D[0, i], rtol=0, atol=1e-12)
            if i:
                np.testing.assert_allclose(Wn[i] @ Ln[i - 1].T, C[0, i],
                                           rtol=0, atol=1e-12)

    def test_solve_from_factor_matches_posv(self):
        rng = np.random.default_rng(23)
        D, C, B = _chain(rng, 2, 4, 4, 2)
        Dj, Cj, Bj = jnp.asarray(D), jnp.asarray(C), jnp.asarray(B)
        L, Wt, _ = blocktri.factor(Dj, Cj, impl="xla")
        X2 = blocktri.solve(L, Wt, Bj, impl="xla")
        X1, _ = blocktri.posv(Dj, Cj, Bj, impl="xla")
        np.testing.assert_allclose(np.asarray(X2), np.asarray(X1),
                                   rtol=0, atol=1e-13)

    def test_assemble_matches_numpy(self):
        rng = np.random.default_rng(24)
        D, C, _ = _chain(rng, 2, 3, 4, 1)
        A = blocktri.assemble(jnp.asarray(D), jnp.asarray(C))
        ref = np.stack([_np_dense(D[j], C[j]) for j in range(2)])
        np.testing.assert_array_equal(np.asarray(A), ref)

    def test_posv_matches_scipy_banded(self):
        # independent-library reference: SciPy's Hermitian banded solver
        # on the lower band form (bandwidth 2b-1 for block size b)
        sla = pytest.importorskip("scipy.linalg")
        rng = np.random.default_rng(25)
        nblocks, b = 4, 4
        D, C, B = _chain(rng, 1, nblocks, b, 1)
        A = _np_dense(D[0], C[0])
        n, bw = A.shape[0], 2 * b - 1
        ab = np.zeros((bw + 1, n))
        for i in range(bw + 1):
            ab[i, : n - i] = np.diag(A, -i)
        ref = sla.solveh_banded(ab, B[0].reshape(n), lower=True)
        X, _ = blocktri.posv(jnp.asarray(D), jnp.asarray(C),
                             jnp.asarray(B), impl="xla")
        np.testing.assert_allclose(np.asarray(X)[0].reshape(n), ref,
                                   rtol=0, atol=1e-11)

    @pytest.mark.slow
    def test_long_chain_parity(self):
        # nblocks=256 — the scan length regime the flagship bench runs;
        # excluded from tier-1, covered by `make audit` wall-clock gates
        rng = np.random.default_rng(26)
        D, C, B = _chain(rng, 1, 256, 8, 1)
        X, info = blocktri.posv(jnp.asarray(D), jnp.asarray(C),
                                jnp.asarray(B), impl="xla")
        assert int(info[0]) == 0
        np.testing.assert_allclose(np.asarray(X), _dense_solve(D, C, B),
                                   rtol=0, atol=1e-10)


# ---------------------------------------------------------------------------
# dispatch plumbing + validation
# ---------------------------------------------------------------------------


class TestDispatch:
    def test_resolve_seg_divides(self):
        assert blocktri.resolve_seg(16) == 8        # default, divides
        assert blocktri.resolve_seg(12, 8) == 6     # decrement to divisor
        assert blocktri.resolve_seg(4, 8) == 4      # clamp to nblocks
        assert blocktri.resolve_seg(5, 8) == 5      # prime chain: itself
        assert blocktri.resolve_seg(7, 3) == 1      # nothing divides -> 1

    def test_f64_forced_pallas_falls_back_to_xla(self):
        # the PR 6 dispatch-gate contract: the kernels compute f32, so a
        # forced 'pallas' for f64 must not silently downgrade precision
        assert blocktri._resolve_impl(
            "pallas", jnp.dtype(jnp.float64), 8, 2, 4, None) == "xla"

    def test_unknown_impl_rejected(self):
        rng = np.random.default_rng(27)
        D, C, B = _chain(rng, 1, 2, 4, 1)
        with pytest.raises(ValueError, match="impl"):
            blocktri.posv(jnp.asarray(D), jnp.asarray(C), jnp.asarray(B),
                          impl="cuda")

    def test_shape_validation(self):
        D = jnp.zeros((1, 2, 4, 4))
        with pytest.raises(ValueError, match="must match"):
            blocktri.factor(D, jnp.zeros((1, 2, 4, 3)))
        with pytest.raises(ValueError, match="batch, nblocks, b, b"):
            blocktri.factor(jnp.zeros((2, 4, 4)), jnp.zeros((2, 4, 4)))
        with pytest.raises(ValueError, match="riding"):
            blocktri.posv(D, D, jnp.zeros((1, 3, 4, 1)))

    def test_dead_first_coupling_ignored(self):
        # C[:, 0] is dead weight by the chain contract; garbage there
        # must produce the bitwise-identical solution
        rng = np.random.default_rng(28)
        D, C, B = _chain(rng, 1, 3, 4, 1)
        C_bad = C.copy()
        C_bad[:, 0] = 1e6 * rng.standard_normal((4, 4))
        X0, i0 = blocktri.posv(jnp.asarray(D), jnp.asarray(C),
                               jnp.asarray(B), impl="xla")
        X1, i1 = blocktri.posv(jnp.asarray(D), jnp.asarray(C_bad),
                               jnp.asarray(B), impl="xla")
        np.testing.assert_array_equal(np.asarray(X0), np.asarray(X1))
        np.testing.assert_array_equal(np.asarray(i0), np.asarray(i1))


# ---------------------------------------------------------------------------
# breakdown info: global pivot convention + containment
# ---------------------------------------------------------------------------


class TestInfo:
    def test_negative_pivot_exact_global_index(self):
        # block 2 gets a clean-diagonal operand with one negative entry
        # and a zeroed incoming coupling, so its Schur complement IS the
        # block.  The pallas kernels' guarded in-kernel sweep reports the
        # EXACT local pivot (3 -> global 2·b + 3); the xla path's LAPACK
        # cholesky NaN-fills the whole failed block, so its post-hoc scan
        # is block-exact only — both land inside block 2, never block 3
        # (whose NaN-fed info min-combines away)
        rng = np.random.default_rng(30)
        nblocks, b = 4, 4
        D, C, B = _chain(rng, 1, nblocks, b, 1, dtype=np.float32)
        D[0, 2] = np.diag([1.0, 1.0, -5.0, 1.0]).astype(np.float32)
        C[0, 2] = 0.0
        _, _, info = blocktri.factor(jnp.asarray(D), jnp.asarray(C),
                                     impl="pallas")
        assert int(info[0]) == 2 * b + 3
        _, _, info = blocktri.factor(jnp.asarray(np.float64(D)),
                                     jnp.asarray(np.float64(C)),
                                     impl="xla")
        assert 2 * b + 1 <= int(info[0]) <= 3 * b

    @pytest.mark.parametrize("impl,dtype", [("xla", np.float64),
                                            ("pallas", np.float32)])
    def test_nan_contained_to_one_batch_problem(self, impl, dtype):
        # poison problem 1's block-1 diagonal: its info lands at or past
        # that block (the exact pivot differs between impls — 0·NaN
        # propagation through the sweeps — but the RANGE is pinned),
        # while problem 0 stays healthy and correct
        rng = np.random.default_rng(31)
        nblocks, b = 4, 8
        D, C, B = _chain(rng, 2, nblocks, b, 2, dtype=dtype)
        D[1, 1, 0, 0] = np.nan
        X, info = blocktri.posv(jnp.asarray(D), jnp.asarray(C),
                                jnp.asarray(B), impl=impl)
        info = np.asarray(info)
        assert info[0] == 0
        assert b + 1 <= info[1] <= nblocks * b + 1
        X0 = np.float64(np.asarray(X)[0])
        ref = _dense_solve(D[:1], C[:1], B[:1])[0]
        tol = 1e-11 if dtype == np.float64 else 5e-5
        assert np.abs(X0 - ref).max() < tol * np.abs(ref).max()

    def test_combine_block_infos_first_pivot_wins(self):
        start = jnp.zeros((1,), jnp.int32)
        tails = [(0, 4, jnp.array([0])), (4, 4, jnp.array([5])),
                 (8, 4, jnp.array([2]))]
        # block at offset 4 reports the off-diagonal sentinel (w=nw+1 ->
        # global n+1=13); block at offset 8 a true pivot (global 10) —
        # any pivot <= n ranks above the sentinel
        assert int(detect.combine_block_infos(start, tails, 12)[0]) == 10

    def test_combine_block_infos_sentinel_alone(self):
        start = jnp.zeros((1,), jnp.int32)
        tails = [(4, 4, jnp.array([5]))]
        assert int(detect.combine_block_infos(start, tails, 12)[0]) == 13


# ---------------------------------------------------------------------------
# serve padding contract
# ---------------------------------------------------------------------------


class TestPadding:
    def test_appended_chain_blocks_are_bitwise_inert(self):
        # same b, nblocks 3 -> 4: the sequential chain never feeds
        # trailing identity blocks back, so the cropped solution is
        # BITWISE the unpadded one (the _pad_blocktri contract)
        rng = np.random.default_rng(32)
        D, C, B = _chain(rng, 1, 3, 4, 2)
        A = jnp.asarray(np.stack([D[0], C[0]]))
        Bj = jnp.asarray(B[0])
        bucket = batching.Bucket("posv_blocktri", "float64",
                                 (2, 4, 4, 4), (4, 4, 2), 2)
        pa, pb = batching.pad_operands("posv_blocktri", A, Bj, bucket)
        Xp, ip = blocktri.posv(pa[None, 0], pa[None, 1], pb[None],
                               impl="xla")
        X0, i0 = blocktri.posv(A[None, 0], A[None, 1], Bj[None],
                               impl="xla")
        Xc = batching.crop("posv_blocktri", Xp[0], A.shape, Bj.shape)
        np.testing.assert_array_equal(np.asarray(Xc), np.asarray(X0)[0])
        # the identity tail solves to exact zeros, and info stays clean
        np.testing.assert_array_equal(np.asarray(Xp)[0, 3:], 0.0)
        assert int(ip[0]) == int(i0[0]) == 0

    def test_block_pad_embeds_identity_tail(self):
        # b 3 -> 4 AND nblocks 3 -> 4: diag(D_i, I) embed, zero-filled
        # couplings/RHS — tight (not bitwise: the contraction length
        # changes) and the padded operand stays a valid SPD chain
        rng = np.random.default_rng(33)
        D, C, B = _chain(rng, 1, 3, 3, 1)
        A = jnp.asarray(np.stack([D[0], C[0]]))
        Bj = jnp.asarray(B[0])
        bucket = batching.Bucket("posv_blocktri", "float64",
                                 (2, 4, 4, 4), (4, 4, 1), 2)
        pa, pb = batching.pad_operands("posv_blocktri", A, Bj, bucket)
        # real blocks completed to diag(D_i, I), appended block pure I
        np.testing.assert_array_equal(np.asarray(pa)[0, 0, 3, :],
                                      np.eye(4)[3])
        np.testing.assert_array_equal(np.asarray(pa)[0, 3], np.eye(4))
        np.testing.assert_array_equal(np.asarray(pa)[1, 3], 0.0)
        Xp, ip = blocktri.posv(pa[None, 0], pa[None, 1], pb[None],
                               impl="xla")
        assert int(ip[0]) == 0
        Xc = batching.crop("posv_blocktri", Xp[0], A.shape, Bj.shape)
        np.testing.assert_allclose(np.asarray(Xc),
                                   _dense_solve(D, C, B)[0],
                                   rtol=0, atol=1e-12)

    def test_fill_problem_is_identity_chain(self):
        bucket = batching.Bucket("posv_blocktri", "float64",
                                 (2, 4, 4, 4), (4, 4, 2), 2)
        fa, fb = batching.fill_problem(bucket)
        np.testing.assert_array_equal(np.asarray(fa)[0],
                                      np.broadcast_to(np.eye(4), (4, 4, 4)))
        np.testing.assert_array_equal(np.asarray(fa)[1], 0.0)
        X, info = blocktri.posv(fa[None, 0], fa[None, 1], fb[None],
                                impl="xla")
        np.testing.assert_array_equal(np.asarray(X), 0.0)
        assert int(info[0]) == 0


# ---------------------------------------------------------------------------
# serve engine: bucketing, zero-recompile, ops counter, config hash
# ---------------------------------------------------------------------------


class TestServeBlocktri:
    def test_engine_matches_dense(self):
        rng = np.random.default_rng(34)
        D, C, B = _chain(rng, 1, 2, 3, 1)
        eng = SolveEngine(cfg=BT_CFG)
        r = eng.solve("posv_blocktri", np.stack([D[0], C[0]]), B[0])
        assert r.ok and r.batched and r.bucket is not None
        np.testing.assert_allclose(np.asarray(r.x),
                                   _dense_solve(D, C, B)[0],
                                   rtol=0, atol=1e-10)

    def test_same_bucket_zero_recompile(self):
        # (nblocks=2, b=3) and (nblocks=2, b=4) land in the same
        # (2, 4)-bucket: one compile, then steady-state hits
        rng = np.random.default_rng(35)
        eng = SolveEngine(cfg=BT_CFG)
        for b in (3, 4):
            D, C, B = _chain(rng, 1, 2, b, 1)
            r = eng.solve("posv_blocktri", np.stack([D[0], C[0]]), B[0])
            assert r.ok
        c = eng.cache_stats()
        assert (c["hits"], c["misses"]) == (1, 1)
        assert eng.stats.ops["posv_blocktri"] == 2

    def test_submit_validation(self):
        eng = SolveEngine(cfg=BT_CFG)
        with pytest.raises(ValueError, match="diagonal blocks"):
            eng.submit("posv_blocktri", np.zeros((3, 2, 4, 4)),
                       np.zeros((2, 4, 1)))
        with pytest.raises(ValueError, match="riding"):
            eng.submit("posv_blocktri", np.zeros((2, 2, 4, 4)),
                       np.zeros((2, 3, 1)))

    def test_blocktri_ladders_join_config_hash(self):
        e1 = SolveEngine(cfg=BT_CFG)
        e2 = SolveEngine(cfg=ServeConfig(
            buckets=BT_CFG.buckets, rows_buckets=BT_CFG.rows_buckets,
            nrhs_buckets=BT_CFG.nrhs_buckets, max_batch=BT_CFG.max_batch,
            max_delay_s=BT_CFG.max_delay_s,
            nblocks_buckets=BT_CFG.nblocks_buckets,
            block_buckets=(4, 16),
        ))
        assert e1._cfg_hash != e2._cfg_hash

    def test_oversize_chain_routes_single(self):
        # nblocks beyond the ladder: unbatched single-problem route,
        # still correct
        rng = np.random.default_rng(36)
        D, C, B = _chain(rng, 1, 6, 3, 1)
        eng = SolveEngine(cfg=BT_CFG)
        r = eng.solve("posv_blocktri", np.stack([D[0], C[0]]), B[0])
        assert r.ok and not r.batched and r.bucket is None
        np.testing.assert_allclose(np.asarray(r.x),
                                   _dense_solve(D, C, B)[0],
                                   rtol=0, atol=1e-10)


# ---------------------------------------------------------------------------
# ledger seam: exemption-with-validation for bench:blocktri records
# ---------------------------------------------------------------------------


def _bt_measured(**over):
    m = {"metric": "blocktri_tflops", "value": 1.5, "nblocks": 4,
         "block": 8, "n": 32, "batch": 2, "nrhs": 1, "impl": "xla",
         "speedup": 40.0}
    m.update(over)
    return m


class TestLedgerSeam:
    def test_valid_record_passes_diff(self):
        rec = ledger.record("bench:blocktri", ledger.manifest(),
                            measured=_bt_measured())
        assert ledger.diff([rec], [rec]) == []

    def test_validate_flags_geometry_mismatch(self):
        probs = ledger.validate_blocktri_measured(_bt_measured(n=33))
        assert any("nblocks*block" in p for p in probs)

    def test_malformed_record_is_incompatible(self):
        rec = ledger.record("bench:blocktri", ledger.manifest(),
                            measured=_bt_measured(impl="cuda"))
        with pytest.raises(ledger.LedgerIncompatible, match="blocktri"):
            ledger.diff([rec], [rec])

    def test_latency_metric_also_validated(self):
        m = _bt_measured(metric="blocktri_latency", nblocks=0)
        rec = ledger.record("bench:blocktri", ledger.manifest(), measured=m)
        with pytest.raises(ledger.LedgerIncompatible, match="nblocks"):
            ledger.diff([rec], [rec])
