"""Hermeticity of the multi-chip dryrun path (round-1 MULTICHIP gate).

The round-1 gate failed for two distinct reasons (VERDICT r1, weak #1):
  (a) dryrun operands were created with bare ``jnp.asarray``, committing them
      to the *process default* backend (a TPU in the bench environment) even
      though the mesh had fallen back to CPU devices — dying at device_put
      with a libtpu client/terminal mismatch;
  (b) Pallas interpret-mode selection keyed off ``jax.default_backend()``
      instead of the platform of the mesh's devices, so a CPU mesh in a
      TPU-backed process picked the Mosaic lowering and died with "Only
      interpret mode is supported on CPU backend" (the base case reaches
      pallas_tpu.transpose via lapack.potrf_trtri_upper on every grid).

These tests simulate the mixed environment on the CPU-only rig by
monkeypatching ``pallas_tpu._default_backend`` to report 'tpu' while every
mesh is CPU: any kernel-dispatch path not threaded through the Grid's
platform scope then tries the Mosaic path and fails loudly.  The last test
runs the driver's actual ``dryrun_multichip(8)`` end to end.
"""

import importlib.util
import pathlib

import jax
import numpy as np
import pytest

from capital_tpu.models import cholesky, inverse, qr
from capital_tpu.ops import pallas_tpu
from capital_tpu.parallel.topology import Grid
from capital_tpu.utils import residual


@pytest.fixture
def tpu_default_backend(monkeypatch):
    """Pretend the process default backend is a TPU (the bench environment)
    while all devices in play are CPU."""
    monkeypatch.setattr(pallas_tpu, "_default_backend", lambda: "tpu")


def _spd(n: int, dtype=np.float32) -> np.ndarray:
    rng = np.random.default_rng(7)
    M = rng.standard_normal((n, n)).astype(dtype)
    return M @ M.T + n * np.eye(n, dtype=dtype)


def test_interpret_keys_off_mesh_platform(tpu_default_backend):
    # without a scope the (simulated) TPU default backend selects Mosaic...
    assert pallas_tpu._interpret_default() is False
    # ...but inside a CPU grid's scope the interpreter must win
    with pallas_tpu.platform_scope("cpu"):
        assert pallas_tpu._interpret_default() is True
        # and the tile budget must follow the scope too (never touching
        # jax.devices('tpu'), which does not exist on this rig)
        assert pallas_tpu._device_budget() == (512, None)
    assert Grid.square(c=1, devices=jax.devices("cpu")[:1]).platform == "cpu"


def test_single_device_pallas_factor_with_tpu_default(tpu_default_backend):
    # the flagship config family (pallas mode: live-tile kernels, views,
    # aliased in-place writes) on a CPU device while the default backend
    # claims TPU — every pallas call must resolve interpret via the grid
    grid = Grid.square(c=1, devices=jax.devices("cpu")[:1])
    A = jax.device_put(_spd(256), grid.face_sharding())
    cfg = cholesky.CholinvConfig(base_case_dim=128, mode="pallas")
    R, Rinv = jax.jit(lambda a: cholesky.factor(grid, a, cfg))(A)
    jax.block_until_ready((R, Rinv))
    assert float(residual.cholesky_residual(A, R)) < 1e-4
    assert float(residual.cholesky_inverse_residual(R, Rinv)) < 1e-4


def test_multidevice_factor_with_tpu_default(tpu_default_backend):
    # multi-device grids reach pallas_tpu.transpose through the base case's
    # lapack.potrf_trtri_upper — the exact crash site of round-1 bug (b)
    grid = Grid.square(c=1, devices=jax.devices("cpu")[:4])
    A = jax.device_put(_spd(128), grid.face_sharding())
    cfg = cholesky.CholinvConfig(base_case_dim=32, mode="explicit")
    R, Rinv = jax.jit(lambda a: cholesky.factor(grid, a, cfg))(A)
    jax.block_until_ready((R, Rinv))
    assert float(residual.cholesky_residual(A, R)) < 1e-4


def test_qr_and_rectri_scoped_with_tpu_default(tpu_default_backend):
    grid = Grid.flat(jax.devices("cpu"))
    rng = np.random.default_rng(3)
    X = jax.device_put(
        rng.standard_normal((128, 16)).astype(np.float32), grid.rows_sharding()
    )
    Q, R = jax.jit(
        lambda x: qr.factor(grid, x, qr.CacqrConfig(num_iter=2, regime="1d"))
    )(X)
    jax.block_until_ready((Q, R))
    assert float(residual.qr_orthogonality(Q)) < 1e-4

    g1 = Grid.square(c=1, devices=jax.devices("cpu")[:1])
    T = jax.device_put(
        np.tril(rng.standard_normal((64, 64)).astype(np.float32))
        + 64 * np.eye(64, dtype=np.float32),
        g1.face_sharding(),
    )
    Tinv = jax.jit(
        lambda t: inverse.rectri(g1, t, "L", inverse.RectriConfig(base_case_dim=32))
    )(T)
    assert float(residual.inverse_residual(T, Tinv)) < 1e-4


def _load_graft_entry():
    path = pathlib.Path(__file__).resolve().parent.parent / "__graft_entry__.py"
    spec = importlib.util.spec_from_file_location("graft_entry_for_test", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_dryrun_hermetic_no_default_backend_execution(
    tpu_default_backend, monkeypatch
):
    """Round-4 MULTICHIP regression: rc=1 because eager ops in the dryrun
    (the residual-gate block's mask constants) dispatched on the *process
    default* backend, which was a TPU with a libtpu client/terminal version
    skew.  Simulate exactly that: default-backend *resolution* for execution
    raises (as the skewed TPU client did), while explicit-platform lookups
    and the device listing still work (they did in the real environment —
    ``jax.devices()`` returned the TPU fine; only executing on it died).
    The dryrun must survive because ``jax.default_device`` pins every
    uncommitted eager op to the mesh's own devices."""
    import jax._src.xla_bridge as xb

    mod = _load_graft_entry()
    cpu_devices = jax.devices("cpu")
    real_get_backend = xb.get_backend

    def broken_default_backend(platform=None):
        if platform is None:
            raise RuntimeError(
                "SIMULATED FAILED_PRECONDITION: libtpu version mismatch "
                "(process-default backend touched by the dryrun)"
            )
        return real_get_backend(platform)

    # the dryrun's own device listing is allowed (it worked in the real
    # failure env); execution-time default-backend resolution is not
    monkeypatch.setattr(mod.jax, "devices", lambda *a: cpu_devices)
    monkeypatch.setattr(xb, "get_backend", broken_default_backend)
    mod.dryrun_multichip(8)


def test_dryrun_multichip_runs_end_to_end(tpu_default_backend):
    # the driver imports __graft_entry__ and calls dryrun_multichip(N)
    # directly (the __main__ platform guard never runs) — do the same,
    # under the simulated TPU default backend so every kernel-dispatch
    # decision in the dryrun call tree is exercised in the mixed environment
    _load_graft_entry().dryrun_multichip(8)
