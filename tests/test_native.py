"""Native engine tests: C++/NumPy bit-parity and planner lock-step.

The native library must be a drop-in for utils/rand48 + utils/layout —
every function here asserts exact equality against the pure-Python path.
"""

import numpy as np
import pytest

from capital_tpu import native
from capital_tpu.utils import layout, rand48
from capital_tpu.utils.config import BaseCasePolicy


pytestmark = pytest.mark.skipif(
    not native.available(), reason="no C++ toolchain — NumPy fallback in use"
)


def test_available():
    assert native.available()


def test_symmetric_parity():
    for n, dd in [(1, True), (5, True), (17, False), (64, True)]:
        assert np.array_equal(native.symmetric(n, dd), rand48.symmetric(n, dd))
    # ground truth from C srand48/drand48 (verify SKILL.md probe)
    assert native.symmetric(5)[0, 0] == 5.1708280361062897


def test_symmetric_subblock():
    n = 32
    full = native.symmetric(n)
    sub = native.symmetric(n, rows=slice(8, 16), cols=slice(4, 30))
    assert np.array_equal(sub, full[8:16, 4:30])


def test_random_parity():
    assert np.array_equal(native.random(13, 7, key=3), rand48.random(13, 7, key=3))
    sub = native.random(13, 7, key=3, rows=slice(2, 9), cols=slice(1, 6))
    assert np.array_equal(sub, rand48.random(13, 7, key=3)[2:9, 1:6])


def test_repack_parity():
    rng = np.random.default_rng(0)
    G = rng.standard_normal((12, 8))
    for dx, dy in [(1, 1), (2, 2), (3, 4), (4, 2)]:
        assert np.array_equal(
            native.block_to_cyclic(G, dx, dy), layout.block_to_cyclic(G, dx, dy)
        )
        assert np.array_equal(
            native.cyclic_to_block(G, dx, dy), layout.cyclic_to_block(G, dx, dy)
        )


def test_pack_parity():
    rng = np.random.default_rng(1)
    for n in (1, 5, 9):
        U = np.triu(rng.standard_normal((n, n)))
        assert np.array_equal(native.pack_upper(U), layout.pack_upper(U))
        assert np.array_equal(native.unpack_upper(native.pack_upper(U), n), U)
        L = np.tril(rng.standard_normal((n, n)))
        assert np.array_equal(native.pack_lower(L), layout.pack_lower(L))
        assert np.array_equal(native.unpack_lower(native.pack_lower(L), n), L)


def test_predict_matches_fallback():
    """C++ planner and the NumPy reference model must stay in lock-step,
    including the copy-bytes term's balance knob."""
    bcs = [64, 128, 256]
    pols = [BaseCasePolicy.REPLICATE_COMM_COMP, BaseCasePolicy.NO_REPLICATION]
    for grid in [(1, 1, 1), (2, 2, 1), (2, 2, 2)]:
        for bal in ("block", "tile_cyclic_persistent"):
            out, best = native.cholinv_predict(
                2048, grid, bcs, pols, peak_flops=1e14, balance=bal,
            )
            ref = np.array(
                [
                    [
                        native._predict_py(
                            2048, *grid, 1e14, 4.5e10, 1e-6, 2, bc, p.value,
                            1, True, 0, int(bal != "block"),
                        )
                        for bc in bcs
                    ]
                    for p in pols
                ]
            )
            np.testing.assert_allclose(out, ref, rtol=1e-12)
            assert out[best] == out.min()
            assert np.all(out > 0)


def test_predict_copy_term():
    """The copy-bytes term mirrors the runtime's emissions: materializing
    whole-buffer round-trips on a mesh, band-sized residue under the
    persistent layout, nothing at all on one device (the copy-free d==1
    route)."""
    bcs = [128]
    pols = [BaseCasePolicy.REPLICATE_COMM_COMP]
    kw = dict(peak_flops=1e14)
    blk, _ = native.cholinv_predict(8192, (2, 2, 1), bcs, pols, **kw)
    per, _ = native.cholinv_predict(
        8192, (2, 2, 1), bcs, pols, balance="tile_cyclic_persistent", **kw
    )
    # the persistent layout's band-sized residue + 3 lifetime permutes
    # must undercut the materializing schedule's per-phase P^2 round-trips
    assert per[0, 0] < blk[0, 0]
    # d==1: balance changes nothing — there is no copy term to remove
    one_b, _ = native.cholinv_predict(8192, (1, 1, 1), bcs, pols, **kw)
    one_p, _ = native.cholinv_predict(
        8192, (1, 1, 1), bcs, pols, balance="tile_cyclic_persistent", **kw
    )
    np.testing.assert_allclose(one_b, one_p)
    # and the term is real: an infinitely fast HBM recovers the old model
    fast, _ = native.cholinv_predict(
        8192, (2, 2, 1), bcs, pols, hbm_bytes_per_s=1e30, **kw
    )
    assert fast[0, 0] < blk[0, 0]


def test_predict_chunks_axis():
    """num_chunks moves ONLY the alpha (collective-launch) term: monotone in
    q on a mesh, identical bytes (round-4: the planner previously ignored
    chunks, ranking every q identically), no-op on one device."""
    bcs = [128, 256]
    pols = [BaseCasePolicy.REPLICATE_COMM_COMP]
    prev = None
    for q in (0, 2, 4):
        out, _ = native.cholinv_predict(
            2048, (2, 2, 2), bcs, pols, peak_flops=1e14, num_chunks=q,
        )
        ref = np.array(
            [[
                native._predict_py(
                    2048, 2, 2, 2, 1e14, 4.5e10, 1e-6, 2, bc, 0, 1, True, q
                )
                for bc in bcs
            ]]
        )
        np.testing.assert_allclose(out, ref, rtol=1e-12)
        if prev is not None:
            assert np.all(out > prev)
        prev = out
    one, _ = native.cholinv_predict(
        2048, (1, 1, 1), bcs, pols, peak_flops=1e14, num_chunks=4,
    )
    one0, _ = native.cholinv_predict(
        2048, (1, 1, 1), bcs, pols, peak_flops=1e14,
    )
    np.testing.assert_allclose(one, one0)


def test_predict_model_sanity():
    """Replicated base case should beat gather-to-root in predicted collective
    count; distributed grids pay communication a 1x1x1 grid does not."""
    bcs = [128]
    out_multi, _ = native.cholinv_predict(
        4096, (2, 2, 2), bcs,
        [BaseCasePolicy.REPLICATE_COMM_COMP, BaseCasePolicy.NO_REPLICATION],
        peak_flops=1e14,
    )
    assert out_multi[0, 0] < out_multi[1, 0]  # fewer collective rounds
    out_single, _ = native.cholinv_predict(
        4096, (1, 1, 1), bcs, [BaseCasePolicy.REPLICATE_COMM_COMP],
        peak_flops=1e14,
    )
    assert out_single[0, 0] < out_multi[0, 0]  # no comm term
