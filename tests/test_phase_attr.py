"""Phase-level wall-time attribution (bench/trace.phase_attribution).

Three layers, none needing a TPU:

* pure logic — hlo_phase_map parsing, the host-plane bucketing over
  synthesized xplane protos, and the check_bubble_fraction gate math;
* the ledger validation contract (obs/ledger.validate_phase_seconds),
  including backward compatibility with records that predate the block;
* one real end-to-end attribution on the CPU rig: a traced cholinv loop
  must attribute nonzero seconds to registered CI:: phases with
  attributed <= wall (after the documented clamp), and synthetic work
  stamped under one scope must land in that scope's bucket.
"""

import jax
import jax.numpy as jnp
import pytest

pytest.importorskip("tensorflow.tsl.profiler.protobuf.xplane_pb2")
from tensorflow.tsl.profiler.protobuf import xplane_pb2  # noqa: E402

from capital_tpu.bench import trace  # noqa: E402
from capital_tpu.obs import ledger  # noqa: E402
from capital_tpu.utils import tracing  # noqa: E402


class TestHloPhaseMap:
    def test_maps_instruction_to_registered_tag(self):
        text = (
            '%dot.5 = f32[64,64] dot(%a, %b), metadata={'
            'op_name="jit(loop)/jit(main)/CI.tmu/dot_general" '
            'source_file="x.py"}\n'
        )
        assert trace.hlo_phase_map(text) == {"dot.5": "CI::tmu"}

    def test_longest_tag_wins(self):
        # an op_name mentioning a nested scope chain attributes to the
        # innermost (longest) registered tag, same as _bucket
        text = (
            '%f.1 = f32[8] add(%x, %y), metadata={'
            'op_name="jit(f)/CI.inv/CI.factor_diag/add"}\n'
        )
        assert trace.hlo_phase_map(text)["f.1"] == "CI::factor_diag"

    def test_entry_computation_wins_name_collision(self):
        # the entry computation is printed last; its binding must win a
        # name collision with a nested computation (the runtime's thunk
        # events carry ENTRY instruction names)
        text = (
            '%dot.1 = f32[8] dot(%a, %b), metadata={op_name="jit(f)/CI.trsm/dot"}\n'
            'ENTRY %main {\n'
            '%dot.1 = f32[8] dot(%a, %b), metadata={op_name="jit(f)/CI.tmu/dot"}\n'
            '}\n'
        )
        assert trace.hlo_phase_map(text)["dot.1"] == "CI::tmu"

    def test_unregistered_scopes_absent(self):
        text = '%c.1 = f32[8] copy(%x), metadata={op_name="jit(f)/transpose"}\n'
        assert trace.hlo_phase_map(text) == {}


def _host_space(events, stat_mid=7):
    """One host plane whose line carries `events` =
    [(off_ps, dur_ps, mid, name, has_hlo_stat)]."""
    space = xplane_pb2.XSpace()
    plane = space.planes.add(name="/host:CPU (pid 1)")
    plane.stat_metadata[stat_mid].name = "hlo_op"
    line = plane.lines.add(name="tf_XLATfrtCpuClient/1")
    for off, dur, mid, name, has_stat in events:
        ev = line.events.add(offset_ps=off, duration_ps=dur, metadata_id=mid)
        if has_stat:
            ev.stats.add(metadata_id=stat_mid, str_value=name)
        plane.event_metadata[mid].name = name
    return space


class TestHostPlaneBudget:
    def test_buckets_through_phase_map(self):
        ps = 1_000_000  # 1 us -> 1e-3 ms
        space = _host_space([
            (0, 4 * ps, 1, "dot.5", True),
            (4 * ps, 2 * ps, 2, "broadcast_add_fusion", True),
        ])
        pm = {"dot.5": "CI::tmu", "broadcast_add_fusion": "CI::trsm"}
        budget = trace._host_plane_budget([("t", space)], pm)
        assert budget == {
            "CI::tmu": pytest.approx(4e-3),
            "CI::trsm": pytest.approx(2e-3),
        }

    def test_bookkeeping_events_dropped_before_sweep(self):
        # a ThunkExecutor wait-region spanning everything carries no
        # hlo_op stat: it must neither bucket anywhere nor absorb the op
        # events' durations as children
        ps = 1_000_000
        space = _host_space([
            (0, 100 * ps, 9, "ThunkExecutor::Execute (wait)", False),
            (10 * ps, 4 * ps, 1, "dot.5", True),
        ])
        budget = trace._host_plane_budget([("t", space)], {"dot.5": "CI::tmu"})
        assert budget == {"CI::tmu": pytest.approx(4e-3)}

    def test_unmapped_ops_fall_to_kind_buckets(self):
        ps = 1_000_000
        space = _host_space([
            (0, 1 * ps, 1, "copy.3", True),
            (1 * ps, 1 * ps, 2, "loop_fusion.2", True),
            (2 * ps, 1 * ps, 3, "tuple.1", True),
        ])
        budget = trace._host_plane_budget([("t", space)], {})
        assert budget == {
            "copy": pytest.approx(1e-3),
            "fusion": pytest.approx(1e-3),
            "other": pytest.approx(1e-3),
        }

    def test_tpu_planes_ignored(self):
        space = _host_space([(0, 1_000_000, 1, "dot.5", True)])
        space.planes[0].name = "/device:TPU:0 (pid 1)"
        assert trace._host_plane_budget([("t", space)], {"dot.5": "CI::tmu"}) == {}


class TestBubbleGate:
    def test_within_budget_returns_fraction(self):
        frac = trace.check_bubble_fraction({"CI::tmu": 1.0}, 0.2, 0.5)
        assert frac == 0.2

    def test_over_budget_raises(self):
        with pytest.raises(RuntimeError, match="bubble-budget regression"):
            trace.check_bubble_fraction({"CI::tmu": 1.0}, 0.6, 0.5)

    def test_empty_attribution_is_a_dead_gate(self):
        # nothing attributed -> the gate must fail LOUDLY, not pass
        with pytest.raises(RuntimeError, match="dead"):
            trace.check_bubble_fraction({}, 0.0, 0.5)

    def test_clamp_math(self):
        # CPU thunk concurrency can attribute more op-seconds than wall;
        # phase_attribution clamps at 0 rather than reporting a negative
        # bubble.  Reproduce the formula on synthetic budgets.
        wall, attributed = 1.0, 1.3
        bubble = max(0.0, (wall - attributed) / wall)
        assert bubble == 0.0
        assert trace.check_bubble_fraction({"x": attributed}, bubble, 0.5) == 0.0


class TestLedgerValidation:
    def _meas(self, **over):
        meas = {
            "metric": "trace_cholinv_attributed",
            "value": 0.76,
            "unit": "frac",
            "phase_seconds": {"CI::tmu": 0.004, "copy": 0.001},
            "bubble_frac": 0.24,
        }
        meas.update(over)
        return meas

    def test_valid_block(self):
        assert ledger.validate_phase_seconds(self._meas()) == []

    def test_records_without_the_block_stay_valid(self):
        # backward compatibility: a measured block that predates the
        # fields validates clean
        assert ledger.validate_phase_seconds(
            {"metric": "cholinv_tflops", "value": 171.7}
        ) == []

    def test_negative_and_nan_phase_seconds_flagged(self):
        probs = ledger.validate_phase_seconds(
            self._meas(phase_seconds={"CI::tmu": -1.0})
        )
        assert any("non-negative" in p for p in probs)
        probs = ledger.validate_phase_seconds(
            self._meas(phase_seconds={"CI::tmu": float("nan")})
        )
        assert probs

    def test_bubble_frac_range(self):
        assert ledger.validate_phase_seconds(self._meas(bubble_frac=1.5))
        assert ledger.validate_phase_seconds(self._meas(bubble_frac=-0.1))

    def test_bubble_without_phases_flagged(self):
        meas = self._meas()
        del meas["phase_seconds"]
        probs = ledger.validate_phase_seconds(meas)
        assert any("without phase_seconds" in p for p in probs)

    def test_diff_rejects_malformed_attribution_record(self):
        man = ledger.manifest(dtype="float32")
        good = ledger.record("bench:trace:cholinv", dict(man),
                             measured=self._meas())
        bad = ledger.record("bench:trace:cholinv", dict(man),
                            measured=self._meas(bubble_frac=2.0))
        assert ledger.diff([good], [good]) == []
        with pytest.raises(ledger.LedgerIncompatible, match="phase"):
            ledger.diff([good], [bad])

    def test_diff_watches_attributed_fraction_drift(self):
        # the drift watch the ISSUE names: measured.value is the
        # attributed fraction, so a bubble growth reads as a value drop
        man = ledger.manifest(dtype="float32")
        a = ledger.record("bench:trace:cholinv", dict(man),
                          measured=self._meas(value=0.9, bubble_frac=0.1))
        b = ledger.record("bench:trace:cholinv", dict(man),
                          measured=self._meas(value=0.5, bubble_frac=0.5))
        regs = ledger.diff([a], [b], tol_metric=0.10)
        assert len(regs) == 1 and regs[0].field == "measured.value"


class TestEndToEndAttribution:
    def test_cholinv_loop_attributes_to_registered_phases(self):
        run = trace._cholinv_run(
            256, jnp.float32, 128, 1, False, "highest", mode="xla"
        )
        phase_s, bubble, wall = trace.phase_attribution(run, 1)
        assert phase_s, "nothing attributed on the CPU rig"
        assert 0.0 <= bubble <= 1.0
        assert wall > 0.0
        # the attributed seconds respect the wall after the clamp:
        # bubble == max(0, 1 - attributed/wall)
        attributed = sum(phase_s.values())
        assert bubble == pytest.approx(
            max(0.0, (wall - attributed) / wall), abs=1e-12
        )
        # real cholinv phases must appear — attribution through the
        # compiled metadata, not just kind catch-alls
        assert any(k.startswith("CI::") for k in phase_s)

    def test_synthetic_work_lands_in_its_scope(self):
        # a loop whose only heavy op is stamped CI::tmu must put CI::tmu
        # at the top of the attribution
        a = jnp.ones((512, 512), jnp.float32)

        @jax.jit
        def loop(a, k):
            def body(_, c):
                with tracing.scope("CI::tmu"):
                    c = jnp.dot(c, c, precision="highest") / 512.0
                return c

            return jnp.sum(jax.lax.fori_loop(0, k, body, a),
                           dtype=jnp.float32)

        run = trace._aot_run(loop, a, jnp.int32(4))
        run()
        phase_s, bubble, _wall = trace.phase_attribution(run, 4)
        assert phase_s
        assert max(phase_s, key=phase_s.get) == "CI::tmu"
        assert 0.0 <= bubble <= 1.0
