"""PR 16 observability tests: per-request span tracing (obs/spans.py),
rolling-window live telemetry (serve/telemetry.py), their ledger
validators and CLI gates, the reservoir-capped stats populations, and the
nearest-rank percentile edge cases.

The acceptance properties of ISSUE 16 / docs/OBSERVABILITY.md
"Per-request tracing and live windows" are asserted directly:

* **complete chains** — every request a traced engine admits exports a
  span chain that `trace_dict_problems` accepts, for all three kinds
  (batched / oversize-single / failed), and the in-run verdicts equal the
  ledger validator's recount (TestEngineTraceIntegration,
  TestLedgerValidators);
* **deadline attribution** — a violated request reports
  slack_at_dispatch_ms and names the span that ate the budget
  (TestSpanChains, TestEngineTraceIntegration);
* **loud-when-dead gates** — `obs serve-report --min-trace-complete /
  --min-windows` and `obs timeline` fail on ledgers with no trace/window
  records, exit 2 on malformed ones (TestServeReportTraceGates);
* **honest degradation** — a reservoir-capped sample population marks
  its snapshot and merge_snapshots refuses to pool the subsample,
  degrading to the elementwise worst-tail max (TestReservoirAndMerge).

Window tests drive the aggregator with an injected fake clock so window
boundaries are exact, not wall-time races.
"""

import json
import time

import numpy as np
import pytest

from capital_tpu.bench.harness import percentiles
from capital_tpu.obs import __main__ as obs_main
from capital_tpu.obs import ledger, spans
from capital_tpu.serve import ServeConfig, SolveEngine, telemetry
from capital_tpu.serve import stats as serve_stats


# ---------------------------------------------------------------------------
# helpers: synthetic traces with explicit timestamps (no wall clock)
# ---------------------------------------------------------------------------


def _mk_trace(rid=0, op="posv", kind="batched", t0=100.0, dur_s=0.001,
              deadline_ms=None, **tags):
    """A complete chain of `kind` with uniform span durations, stamped at
    explicit monotonic-clock offsets."""
    tr = spans.RequestTrace(rid, op, t0, deadline_ms=deadline_ms, **tags)
    tr.kind = kind
    t = t0
    for name in spans.REQUIRED[kind]:
        t += dur_s
        tr.extend(name, t)
    return tr


def _spd(rng, n, dtype=np.float32):
    M = rng.standard_normal((n, n))
    return (M @ M.T / n + 3.0 * np.eye(n)).astype(dtype)


def _ecfg(**kw):
    kw.setdefault("buckets", (8,))
    kw.setdefault("rows_buckets", (32,))
    kw.setdefault("nrhs_buckets", (1,))
    kw.setdefault("max_batch", 2)
    kw.setdefault("max_delay_s", 10.0)
    kw.setdefault("small_n_impl", "pallas")
    return ServeConfig(**kw)


# ---------------------------------------------------------------------------
# spans: chain validation, derived deadline signals, export round-trip
# ---------------------------------------------------------------------------


class TestSpanChains:
    @pytest.mark.parametrize("kind", ["batched", "single", "failed"])
    def test_required_chain_is_complete(self, kind):
        tr = _mk_trace(kind=kind)
        assert tr.problems() == []
        assert tr.complete()

    def test_refine_is_optional_everywhere(self):
        tr = spans.RequestTrace(1, "posv", 100.0)
        t = 100.0
        for name in ("admit", "enqueue", "cache_lookup", "batch_form",
                     "device", "refine", "respond"):
            t += 0.001
            tr.extend(name, t)
        assert tr.problems() == []

    def test_missing_span_is_incomplete(self):
        tr = spans.RequestTrace(1, "posv", 100.0)
        t = 100.0
        for name in ("admit", "enqueue", "device", "respond"):  # no lookup
            t += 0.001
            tr.extend(name, t)
        probs = tr.problems()
        assert any("incomplete chain" in p for p in probs)

    def test_out_of_order_names_rejected(self):
        tr = spans.RequestTrace(1, "posv", 100.0)
        tr.extend("device", 100.001)
        tr.extend("admit", 100.002)
        assert any("out of chain order" in p for p in tr.problems())

    def test_unknown_span_name_rejected(self):
        tr = spans.RequestTrace(1, "posv", 100.0)
        tr.extend("teleport", 100.001)
        assert any("unknown span name" in p for p in tr.problems())

    def test_empty_chain_rejected(self):
        tr = spans.RequestTrace(1, "posv", 100.0)
        assert any("empty span chain" in p for p in tr.problems())

    def test_bubble_gap_beyond_tolerance(self):
        tr = _mk_trace()
        # re-stamp the device span 100ms after batch_form ended
        names = [sp.name for sp in tr.spans]
        i = names.index("device")
        sp = tr.spans[i]
        tr.spans[i] = spans.Span("device", sp.t_start + 0.1, sp.t_end + 0.2)
        for later in range(i + 1, len(tr.spans)):
            old = tr.spans[later]
            tr.spans[later] = spans.Span(old.name, old.t_start + 0.2,
                                         old.t_end + 0.2)
        assert any("bubble tolerance" in p for p in tr.problems(25.0))
        # a generous tolerance absorbs the same gap
        assert tr.problems(bubble_tol_ms=500.0) == []

    def test_overlapping_spans_rejected(self):
        tr = spans.RequestTrace(1, "posv", 100.0)
        tr.span("admit", 100.0, 100.010)
        tr.span("device", 100.002, 100.020)  # starts inside admit
        tr.span("respond", 100.020, 100.021)
        assert any("non-monotonic" in p for p in tr.problems())

    def test_negative_duration_rejected(self):
        tr = spans.RequestTrace(1, "posv", 100.0)
        tr.span("admit", 100.010, 100.001)
        assert any("ends before it starts" in p for p in tr.problems())

    def test_latency_and_slack(self):
        tr = _mk_trace(kind="batched", dur_s=0.002, deadline_ms=50.0)
        # 6 required spans x 2ms
        assert tr.latency_ms == pytest.approx(12.0, abs=1e-6)
        # device starts after admit/enqueue/cache_lookup/batch_form = 8ms
        assert tr.slack_at_dispatch_ms == pytest.approx(42.0, abs=1e-6)
        assert not tr.violated and tr.attribution is None

    def test_violation_attributes_longest_span(self):
        tr = spans.RequestTrace(7, "posv", 100.0, deadline_ms=5.0)
        t = 100.0
        for name, d in [("admit", 0.001), ("enqueue", 0.001),
                        ("cache_lookup", 0.001), ("batch_form", 0.001),
                        ("device", 0.020), ("respond", 0.001)]:
            t += d
            tr.extend(name, t)
        assert tr.violated
        assert tr.attribution == "device"
        assert tr.slack_at_dispatch_ms == pytest.approx(1.0, abs=1e-6)

    def test_no_deadline_means_no_slack_no_violation(self):
        tr = _mk_trace()
        assert tr.slack_at_dispatch_ms is None
        assert not tr.violated

    def test_asdict_roundtrips_through_dict_validator(self):
        tr = _mk_trace(rid=3, deadline_ms=1000.0, bucket="posv/f32/n8",
                       tier="balanced", replica_id="r0", cfg_hash="abc")
        d = tr.asdict()
        assert spans.trace_dict_problems(d) == []
        assert d["bucket"] == "posv/f32/n8" and d["replica_id"] == "r0"
        assert d["violated"] is False

    def test_dict_validator_catches_corruption(self):
        d = _mk_trace().asdict()
        bad = dict(d, request_id="nope")
        assert any("request_id" in p
                   for p in spans.trace_dict_problems(bad))
        bad = dict(d, spans="nope")
        assert any("not a list" in p
                   for p in spans.trace_dict_problems(bad))
        bad = dict(d, spans=[dict(d["spans"][0], dur_ms=-1.0)]
                   + d["spans"][1:])
        assert any("negative duration" in p
                   for p in spans.trace_dict_problems(bad))
        bad = dict(d, spans=[dict(d["spans"][0], t_start_s="x")]
                   + d["spans"][1:])
        assert any("non-numeric" in p
                   for p in spans.trace_dict_problems(bad))


class TestTraceLog:
    def test_cap_drops_oldest_visibly(self):
        log = spans.TraceLog(cap=3)
        for i in range(5):
            log.start(i, "posv", 100.0 + i)
        assert len(log) == 3 and log.total == 5 and log.dropped == 2
        ids = [t["request_id"] for t in log.trace_dicts()]
        assert ids == [2, 3, 4]  # oldest two gone

    def test_cap_must_be_positive(self):
        with pytest.raises(ValueError):
            spans.TraceLog(cap=0)

    def test_block_counts_complete_and_violations(self):
        log = spans.TraceLog()
        log.add(_mk_trace(rid=0).asdict())  # complete
        log.add(_mk_trace(rid=1, deadline_ms=0.5).asdict())  # violated
        incomplete = spans.RequestTrace(2, "posv", 100.0)
        incomplete.extend("admit", 100.001)
        log.add(incomplete.asdict())  # batched kind missing most spans
        blk = log.block()
        assert blk["requests"] == 3
        assert blk["complete"] == 2
        assert blk["violations"] == 1
        assert blk["dropped"] == 0
        assert ledger.validate_serve_trace(blk) == []

    def test_emit_appends_valid_record(self, tmp_path):
        p = tmp_path / "t.jsonl"
        log = spans.TraceLog()
        log.add(_mk_trace().asdict())
        rec = log.emit(str(p))
        assert rec["kind"] == "serve:trace"
        assert ledger.validate_serve_trace(rec["serve_trace"]) == []
        assert len(ledger.read(str(p))) == 1


class TestChromeExport:
    def test_event_structure(self):
        traces = [
            _mk_trace(rid=0, replica_id="r0").asdict(),
            _mk_trace(rid=1, t0=200.0, replica_id="r1",
                      deadline_ms=0.5).asdict(),
        ]
        doc = spans.to_chrome(traces)
        assert doc["displayTimeUnit"] == "ms"
        ev = doc["traceEvents"]
        meta = [e for e in ev if e["ph"] == "M"]
        xs = [e for e in ev if e["ph"] == "X"]
        assert {m["args"]["name"] for m in meta} == {"serve:r0", "serve:r1"}
        assert len(xs) == sum(len(t["spans"]) for t in traces)
        # timestamps normalize to the earliest span
        assert min(e["ts"] for e in xs) == 0.0
        # request_id rides as the thread id; deadline verdicts ride args
        assert {e["tid"] for e in xs} == {0, 1}
        violated = [e for e in xs if e["args"]["violated"]]
        assert violated and all(e["tid"] == 1 for e in violated)
        json.dumps(doc)  # must be JSON-serializable as-is

    def test_unlabeled_traces_group_under_engine(self):
        doc = spans.to_chrome([_mk_trace().asdict()])
        meta = [e for e in doc["traceEvents"] if e["ph"] == "M"]
        assert meta[0]["args"]["name"] == "serve:engine"


# ---------------------------------------------------------------------------
# telemetry: rolling windows on an injected clock
# ---------------------------------------------------------------------------


class _FakeClock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t


class TestWindowAggregator:
    def test_rejects_bad_params(self):
        with pytest.raises(ValueError):
            telemetry.WindowAggregator(0.0)
        with pytest.raises(ValueError):
            telemetry.WindowAggregator(1.0, sample_cap=0)

    def test_windows_close_on_the_clock(self):
        clk = _FakeClock()
        agg = telemetry.WindowAggregator(1.0, clock=clk)
        for i in range(4):
            agg.note_request("posv", 0.002, bucket="b8")
        clk.t += 1.5  # past the first window's end
        agg.note_request("inv", 0.004)
        assert len(agg.windows()) == 1  # first window closed
        agg.flush()
        ws = agg.windows()
        assert len(ws) == 2
        w0, w1 = ws
        assert w0["requests"] == 4 and w0["ok"] == 4
        assert w0["ops"] == {"posv": 4}
        assert w1["requests"] == 1 and w1["ops"] == {"inv": 1}
        # closed-window end is clamped to the window boundary
        assert w0["t_end_s"] - w0["t_start_s"] == pytest.approx(1.0)

    def test_window_internal_coherence(self):
        clk = _FakeClock()
        agg = telemetry.WindowAggregator(1.0, clock=clk)
        rng = np.random.default_rng(0)
        for lat in rng.uniform(0.001, 0.2, size=40):
            agg.note_request("posv", float(lat), bucket="b8")
        agg.note_request("posv", 0.01, ok=False, failed=True)
        agg.note_request("posv", None, shed=True, bucket="b8")
        agg.note_batch(0.75, bucket="b8")
        agg.note_queue_depth(5)
        agg.flush()
        (w,) = agg.windows()
        assert ledger.validate_serve_window(w) == []
        assert w["requests"] == 42 and w["ok"] == 40
        assert w["failed"] == 1 and w["shed"] == 1
        assert sum(w["hist_ms"]["counts"]) == 41  # shed carries no latency
        lat = w["latency_ms"]
        assert lat["p50"] <= lat["p95"] <= lat["p99"]
        assert w["queue_depth_max"] == 5 and w["batches"] == 1
        assert w["per_bucket"]["b8"]["shed"] == 1
        assert w["per_bucket"]["b8"]["occupancy_mean"] == pytest.approx(0.75)

    def test_empty_windows_are_skipped(self):
        clk = _FakeClock()
        agg = telemetry.WindowAggregator(0.5, clock=clk)
        agg.note_request("posv", 0.001)
        clk.t += 10.0  # nine idle windows elapse
        agg.note_request("posv", 0.001)
        agg.flush()
        assert len(agg.windows()) == 2  # only the two with traffic

    def test_batches_only_window_is_valid(self):
        clk = _FakeClock()
        agg = telemetry.WindowAggregator(1.0, clock=clk)
        agg.note_batch(0.5, bucket="b8")  # dispatch; requests land later
        agg.flush()
        (w,) = agg.windows()
        assert w["requests"] == 0 and w["batches"] == 1
        assert ledger.validate_serve_window(w) == []

    def test_sample_cap_marks_window_honestly(self):
        clk = _FakeClock()
        agg = telemetry.WindowAggregator(1.0, sample_cap=8, clock=clk)
        for i in range(50):
            agg.note_request("posv", 0.001 * (i + 1))
        agg.flush()
        (w,) = agg.windows()
        assert w["samples_capped"] is True and w["sampled"] == 8
        assert sum(w["hist_ms"]["counts"]) == 50  # hist stays exact
        assert ledger.validate_serve_window(w) == []

    def test_emit_is_incremental(self, tmp_path):
        p = tmp_path / "w.jsonl"
        clk = _FakeClock()
        agg = telemetry.WindowAggregator(1.0, clock=clk)
        agg.note_request("posv", 0.001)
        clk.t += 1.5
        agg.note_request("posv", 0.001)
        recs1 = agg.emit(str(p))
        assert len(recs1) == 2
        clk.t += 1.5
        agg.note_request("posv", 0.001)
        recs2 = agg.emit(str(p))
        assert len(recs2) == 1  # only the fresh window
        rows = ledger.read(str(p))
        assert len(rows) == 3
        assert all(r["kind"] == "serve:window" for r in rows)
        assert all(ledger.validate_serve_window(r["serve_window"]) == []
                   for r in rows)


# ---------------------------------------------------------------------------
# ledger: the serve_trace / serve_window validators and diff's posture
# ---------------------------------------------------------------------------


class TestLedgerValidators:
    def _trace_block(self):
        log = spans.TraceLog()
        log.add(_mk_trace(rid=0).asdict())
        log.add(_mk_trace(rid=1, deadline_ms=0.5).asdict())
        return log.block()

    def _window_block(self):
        clk = _FakeClock()
        agg = telemetry.WindowAggregator(1.0, clock=clk)
        agg.note_request("posv", 0.002, bucket="b8")
        agg.note_batch(0.5, bucket="b8")
        agg.flush()
        return agg.windows()[0]

    def test_valid_blocks_pass(self):
        assert ledger.validate_serve_trace(self._trace_block()) == []
        assert ledger.validate_serve_window(self._window_block()) == []

    def test_trace_complete_recount_disagreement(self):
        blk = dict(self._trace_block(), complete=999)
        assert any("disagrees with recount" in p
                   for p in ledger.validate_serve_trace(blk))

    def test_trace_violations_recount_disagreement(self):
        blk = dict(self._trace_block(), violations=0)
        assert any("violations" in p
                   for p in ledger.validate_serve_trace(blk))

    def test_trace_count_and_type_checks(self):
        blk = dict(self._trace_block(), requests=99)
        assert any("requests" in p
                   for p in ledger.validate_serve_trace(blk))
        blk = dict(self._trace_block(), dropped=-1)
        assert ledger.validate_serve_trace(blk)
        blk = dict(self._trace_block(), traces="nope")
        assert ledger.validate_serve_trace(blk)

    def test_incomplete_chain_is_data_not_schema_problem(self):
        # an honest trace block whose chain is incomplete must VALIDATE —
        # completeness is the serve-report gate's job, not diff's
        tr = spans.RequestTrace(0, "posv", 100.0)
        tr.extend("admit", 100.001)
        blk = spans.build_block([tr.asdict()])
        assert blk["complete"] == 0
        assert ledger.validate_serve_trace(blk) == []

    def test_window_percentile_order_enforced(self):
        blk = dict(self._window_block())
        blk["latency_ms"] = {"p50": 10.0, "p95": 5.0, "p99": 20.0}
        assert any("p50" in p or "order" in p
                   for p in ledger.validate_serve_window(blk))

    def test_window_count_identity_enforced(self):
        blk = dict(self._window_block(), shed=7)
        assert any("requests" in p
                   for p in ledger.validate_serve_window(blk))

    def test_window_hist_shape_enforced(self):
        blk = dict(self._window_block())
        h = dict(blk["hist_ms"])
        h["counts"] = h["counts"][:-1]
        blk["hist_ms"] = h
        assert ledger.validate_serve_window(blk)

    def test_window_occupancy_range_enforced(self):
        blk = dict(self._window_block(), occupancy_mean=1.5)
        assert ledger.validate_serve_window(blk)

    def test_diff_exempts_but_validates(self, tmp_path):
        trec = ledger.record("serve:trace", ledger.manifest(),
                             serve_trace=self._trace_block())
        wrec = ledger.record("serve:window", ledger.manifest(),
                             serve_window=self._window_block())
        regs = ledger.diff([trec, wrec], [trec, wrec])
        assert regs == []
        bad = dict(trec, serve_trace=dict(self._trace_block(),
                                          complete=999))
        with pytest.raises(ledger.LedgerIncompatible,
                           match="malformed serve_trace"):
            ledger.diff([bad], [bad])
        badw = dict(wrec, serve_window=dict(self._window_block(), shed=7))
        with pytest.raises(ledger.LedgerIncompatible,
                           match="malformed serve_window"):
            ledger.diff([badw], [badw])


# ---------------------------------------------------------------------------
# engine integration: real traced requests end to end
# ---------------------------------------------------------------------------


class TestEngineTraceIntegration:
    def test_batched_requests_trace_completely(self, tmp_path):
        eng = SolveEngine(cfg=_ecfg())
        rng = np.random.default_rng(0)
        tickets = [eng.submit("posv", _spd(rng, 8),
                              rng.standard_normal((8, 1)).astype(np.float32))
                   for _ in range(4)]
        eng.drain()
        assert all(t.result().ok for t in tickets)
        rec = eng.emit_trace(str(tmp_path / "t.jsonl"))
        st = rec["serve_trace"]
        assert st["requests"] == 4
        assert st["complete"] == 4, [
            p for t in st["traces"]
            for p in spans.trace_dict_problems(t)]
        assert st["violations"] == 0 and st["dropped"] == 0
        for t in st["traces"]:
            assert t["kind"] == "batched"
            assert t["bucket"] and t["cfg_hash"]
            assert t["tier"] == "balanced"
        assert ledger.validate_serve_trace(st) == []

    def test_oversize_single_and_failed_kinds(self):
        # oversize with models fallback -> "single"; with reject -> "failed"
        rng = np.random.default_rng(1)
        A = _spd(rng, 12, np.float64).astype(np.float32)
        B = rng.standard_normal((12, 1)).astype(np.float32)

        eng = SolveEngine(cfg=_ecfg(oversize="models"))
        assert eng.solve("posv", A, B).ok
        (tr,) = eng.emit_trace()["serve_trace"]["traces"]
        assert tr["kind"] == "single"
        assert spans.trace_dict_problems(tr) == []

        eng = SolveEngine(cfg=_ecfg(oversize="reject"))
        assert not eng.solve("posv", A, B).ok
        st = eng.emit_trace()["serve_trace"]
        (tr,) = st["traces"]
        assert tr["kind"] == "failed"
        assert st["complete"] == 1  # failed chains still validate

    def test_deadline_violation_attributed(self, tmp_path):
        eng = SolveEngine(cfg=_ecfg())
        rng = np.random.default_rng(2)
        args = (_spd(rng, 8), rng.standard_normal((8, 1)).astype(np.float32))
        assert eng.solve("posv", *args, deadline_ms=1e-4).ok  # late, landed
        assert eng.solve("posv", *args, deadline_ms=60000.0).ok
        st = eng.emit_trace()["serve_trace"]
        assert st["violations"] == 1
        late, met = st["traces"]
        assert late["violated"] and late["attribution"] in spans.CHAIN
        assert late["slack_at_dispatch_ms"] < 0  # doomed before dispatch
        assert not met["violated"] and met["attribution"] is None
        assert met["slack_at_dispatch_ms"] > 0

    def test_telemetry_windows_from_real_traffic(self, tmp_path):
        p = tmp_path / "w.jsonl"
        eng = SolveEngine(cfg=_ecfg())
        agg = eng.enable_telemetry(window_s=60.0)
        rng = np.random.default_rng(3)
        for _ in range(5):
            assert eng.solve(
                "posv", _spd(rng, 8),
                rng.standard_normal((8, 1)).astype(np.float32)).ok
        recs = agg.emit(str(p))
        assert len(recs) >= 1
        total = sum(r["serve_window"]["requests"] for r in recs)
        assert total == 5
        assert all(ledger.validate_serve_window(r["serve_window"]) == []
                   for r in recs)
        assert sum(r["serve_window"]["batches"] for r in recs) >= 1


class TestRouterTraceRoundtrip:
    def test_replica_traces_ride_back_tagged(self, tmp_path):
        from capital_tpu.serve.replica import ThreadReplica
        from capital_tpu.serve.router import Router, RouterConfig

        p = tmp_path / "r.jsonl"
        r = Router(RouterConfig())
        r.add_replica(ThreadReplica("r0", _ecfg(max_delay_s=0.005)))
        r.start()
        try:
            rng = np.random.default_rng(4)
            A = _spd(rng, 8)
            B = rng.standard_normal((8, 1)).astype(np.float32)
            tks = [r.submit("posv", A, B) for _ in range(3)]
            deadline = time.monotonic() + 60.0
            while not all(t.done for t in tks):
                r.pump()
                if time.monotonic() > deadline:
                    raise TimeoutError("tickets never landed")
                time.sleep(1e-3)
            assert all(t.result().ok for t in tks)
            srecs = r.emit_stats(str(p))
            trec = r.emit_trace(str(p))
        finally:
            r.stop()
        # emit_stats stays pure request_stats (its consumers iterate it)
        assert all(x.get("request_stats") for x in srecs)
        st = trec["serve_trace"]
        assert st["requests"] == 3 and st["complete"] == 3
        assert all(t["replica_id"] == "r0" for t in st["traces"])
        assert ledger.validate_serve_trace(st) == []


# ---------------------------------------------------------------------------
# CLI gates: serve-report trace/window gates and the timeline tool
# ---------------------------------------------------------------------------


class TestServeReportTraceGates:
    def _write(self, path, n_traces=2, n_windows=3, complete=True):
        log = spans.TraceLog()
        for i in range(n_traces):
            if complete:
                log.add(_mk_trace(rid=i).asdict())
            else:
                tr = spans.RequestTrace(i, "posv", 100.0)
                tr.extend("admit", 100.001)
                log.add(tr.asdict())
        if n_traces:
            log.emit(str(path))
        clk = _FakeClock()
        agg = telemetry.WindowAggregator(1.0, clock=clk)
        for _ in range(n_windows):
            agg.note_request("posv", 0.002)
            clk.t += 1.5
        agg.emit(str(path))

    def test_gates_pass_on_healthy_ledger(self, tmp_path, capsys):
        p = tmp_path / "l.jsonl"
        self._write(p)
        rc = obs_main.main(["serve-report", str(p),
                            "--min-trace-complete", "1.0",
                            "--min-windows", "3"])
        out = capsys.readouterr().out
        assert rc == 0
        assert "serve_trace" in out and "serve_window" in out

    def test_trace_gate_fails_loudly_without_records(self, tmp_path):
        p = tmp_path / "l.jsonl"
        self._write(p, n_traces=0, n_windows=1)
        assert obs_main.main(["serve-report", str(p),
                              "--min-trace-complete", "1.0"]) == 1

    def test_trace_gate_fails_on_incomplete_chains(self, tmp_path):
        p = tmp_path / "l.jsonl"
        self._write(p, complete=False)
        assert obs_main.main(["serve-report", str(p),
                              "--min-trace-complete", "1.0"]) == 1

    def test_window_gate_fails_short(self, tmp_path):
        p = tmp_path / "l.jsonl"
        self._write(p, n_windows=2)
        assert obs_main.main(["serve-report", str(p),
                              "--min-windows", "3"]) == 1

    def test_malformed_trace_record_exits_2(self, tmp_path):
        p = tmp_path / "l.jsonl"
        log = spans.TraceLog()
        log.add(_mk_trace().asdict())
        rec = log.emit()
        rec["serve_trace"]["complete"] = 999
        ledger.append(str(p), rec)
        assert obs_main.main(["serve-report", str(p)]) == 2

    def test_timeline_summary_and_chrome_export(self, tmp_path, capsys):
        p = tmp_path / "l.jsonl"
        out_json = tmp_path / "chrome.json"
        self._write(p)
        rc = obs_main.main(["timeline", str(p),
                            "--chrome", str(out_json)])
        out = capsys.readouterr().out
        assert rc == 0
        assert "timeline OK" in out
        doc = json.loads(out_json.read_text())
        assert doc["traceEvents"]

    def test_timeline_fails_loudly_without_traces(self, tmp_path):
        p = tmp_path / "l.jsonl"
        self._write(p, n_traces=0, n_windows=1)
        assert obs_main.main(["timeline", str(p)]) == 1


class TestServeReportAggregateNaming:
    def test_hit_rate_failure_names_the_replica(self, tmp_path, capsys):
        # r0's cache went cold (hit_rate 0.5); the fleet message must say
        # so instead of reporting only the anonymous merged number
        p = tmp_path / "l.jsonl"
        snaps = []
        for rid, (h, m) in [("r0", (1, 1)), ("r1", (4, 0))]:
            c = serve_stats.Collector(replica_id=rid)
            c.record_request("posv", 0.01, ok=True)
            cache = {"hits": h, "misses": m, "warmup_compiles": 0,
                     "hit_rate": h / (h + m)}
            snaps.append(c.snapshot(cache, samples=True))
            clean = {k: v for k, v in snaps[-1].items() if k != "samples"}
            ledger.append(str(p), ledger.record(
                "serve:request_stats", ledger.manifest(),
                request_stats=clean))
        ledger.append(str(p), ledger.record(
            "serve:request_stats", ledger.manifest(),
            request_stats=serve_stats.merge_snapshots(snaps)))
        rc = obs_main.main(["serve-report", str(p), "--aggregate",
                            "--min-hit-rate", "0.9"])
        captured = capsys.readouterr()
        text = captured.out + captured.err
        assert rc == 1
        assert "r0" in text and "offending" in text
        assert "r0=0.500" in text and "r1=1.000" in text


# ---------------------------------------------------------------------------
# stats: reservoir capping and the merge's honest degradation
# ---------------------------------------------------------------------------


class TestReservoirAndMerge:
    def test_under_cap_is_exact(self):
        r = serve_stats.Reservoir(cap=10)
        for v in range(5):
            r.append(float(v))
        assert list(r) == [0.0, 1.0, 2.0, 3.0, 4.0]
        assert not r.capped and r.count == 5

    def test_over_cap_bounds_memory_and_marks(self):
        r = serve_stats.Reservoir(cap=16)
        for v in range(1000):
            r.append(float(v))
        assert len(r) == 16 and r.count == 1000 and r.capped
        assert set(r) <= {float(v) for v in range(1000)}

    def test_deterministic_across_instances(self):
        a, b = serve_stats.Reservoir(cap=8), serve_stats.Reservoir(cap=8)
        for v in range(100):
            a.append(float(v))
            b.append(float(v))
        assert list(a) == list(b)

    def test_bad_cap_rejected(self):
        with pytest.raises(ValueError):
            serve_stats.Reservoir(cap=0)

    def test_collector_snapshot_marks_capped_populations(self):
        c = serve_stats.Collector(sample_cap=4)
        for i in range(10):
            c.record_request("posv", 0.001 * (i + 1), ok=True)
        snap = c.snapshot(samples=True)
        assert snap["samples_capped"] is True
        assert len(snap["samples"]["latency_s"]) == 4
        # an uncapped collector carries no marker at all (schema unchanged)
        c2 = serve_stats.Collector()
        c2.record_request("posv", 0.001, ok=True)
        assert "samples_capped" not in c2.snapshot(samples=True)

    def test_merge_pools_exact_when_uncapped(self):
        snaps = []
        pool = []
        for rid, lats in [("r0", [0.001, 0.002]), ("r1", [0.010, 0.020])]:
            c = serve_stats.Collector(replica_id=rid)
            for v in lats:
                c.record_request("posv", v, ok=True)
                pool.append(v * 1e3)
            snaps.append(c.snapshot(samples=True))
        merged = serve_stats.merge_snapshots(snaps)
        expect = {k: round(v, 4) for k, v in percentiles(pool).items()}
        assert merged["latency_ms"] == expect

    def test_merge_degrades_to_worst_tail_when_capped(self):
        # r0's population outgrew its reservoir: its samples are a uniform
        # subsample, so pooling them would bias the union's tail — the
        # merge must fall back to the elementwise max instead
        c0 = serve_stats.Collector(replica_id="r0", sample_cap=4)
        for i in range(50):
            c0.record_request("posv", 0.001 * (i + 1), ok=True)
        c1 = serve_stats.Collector(replica_id="r1")
        for v in [0.002, 0.004]:
            c1.record_request("posv", v, ok=True)
        s0 = c0.snapshot(samples=True)
        s1 = c1.snapshot(samples=True)
        merged = serve_stats.merge_snapshots([s0, s1])
        for p in ("p50", "p95", "p99"):
            assert merged["latency_ms"][p] == max(
                s0["latency_ms"][p], s1["latency_ms"][p])


# ---------------------------------------------------------------------------
# bench/harness.percentiles: nearest-rank on tiny samples
# ---------------------------------------------------------------------------


class TestPercentilesTinySamples:
    def test_single_sample_is_every_percentile(self):
        assert percentiles([7.0]) == {"p50": 7.0, "p95": 7.0, "p99": 7.0}

    def test_two_samples_nearest_rank(self):
        # rank = ceil(p/100 * 2): p50 -> rank 1 (the min), p95/p99 -> rank 2
        got = percentiles([3.0, 9.0])
        assert got == {"p50": 3.0, "p95": 9.0, "p99": 9.0}
        assert percentiles([9.0, 3.0]) == got  # order-independent

    def test_all_equal_samples(self):
        assert percentiles([5.0] * 17) == {"p50": 5.0, "p95": 5.0,
                                           "p99": 5.0}

    def test_empty_rejected(self):
        with pytest.raises(ValueError):
            percentiles([])

    def test_reported_values_are_actual_samples(self):
        rng = np.random.default_rng(5)
        s = list(rng.uniform(0, 1, size=13))
        got = percentiles(s)
        assert all(v in s for v in got.values())
