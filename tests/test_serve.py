"""Serving layer tests: potrs, identity-tail padding, bucketing, the
SolveEngine's AOT cache + flush policy + fault containment, and the
request_stats ledger/CLI seam.

The acceptance properties of ISSUE 4 / docs/SERVING.md are asserted
directly on the counters here:

* after warmup over >= 3 shape buckets, a 50-request mixed workload shows
  misses == 0 and hit_rate == 1.0 (TestEngineAcceptance);
* batched posv/lstsq match the unbatched models/ paths within dtype
  tolerance (TestEngineAcceptance, TestEngineResults);
* a fault-injected request comes back flagged with a RobustInfo while its
  batch neighbors and every subsequent request succeed (TestEngineFaults).

Everything runs on the conftest CPU rig (x64 on); engines default to a
1-device grid so the batched kernels compile fast, and models-path
comparisons reuse the same grid.
"""

import time

import jax.numpy as jnp
import numpy as np
import pytest

from capital_tpu.bench import harness
from capital_tpu.models import cholesky
from capital_tpu.obs import __main__ as obs_main
from capital_tpu.obs import ledger
from capital_tpu.ops import lapack, masking
from capital_tpu.robust import faultinject
from capital_tpu.robust.config import RobustConfig, RobustInfo
from capital_tpu.serve import ServeConfig, SolveEngine, batching, stats

# Small ladders so every executable compiles in well under a second; the
# huge max_delay_s means the deadline path only fires when a test passes an
# explicit `now` to pump() — flush timing stays deterministic.
CFG = ServeConfig(
    buckets=(8, 16, 32),
    rows_buckets=(32, 64, 128),
    nrhs_buckets=(1, 4),
    max_batch=3,
    max_delay_s=10.0,
)


def _spd(rng, n, dtype=np.float64):
    M = rng.standard_normal((n, n))
    return (M @ M.T / n + 3.0 * np.eye(n)).astype(dtype)


# ---------------------------------------------------------------------------
# ops/lapack.potrs + models/cholesky.solve (satellite a)
# ---------------------------------------------------------------------------


class TestPotrs:
    @pytest.mark.parametrize("uplo", ["U", "L"])
    def test_matches_dense_solve(self, uplo):
        rng = np.random.default_rng(0)
        A = _spd(rng, 24)
        B = rng.standard_normal((24, 3))
        C = np.linalg.cholesky(A)  # lower
        T = jnp.asarray(C if uplo == "L" else C.T)
        X = lapack.potrs(T, jnp.asarray(B), uplo=uplo)
        np.testing.assert_allclose(np.asarray(X), np.linalg.solve(A, B),
                                   rtol=0, atol=1e-11)

    def test_roundtrips_potrf(self):
        rng = np.random.default_rng(1)
        A = _spd(rng, 16)
        B = rng.standard_normal((16, 2))
        R = lapack.potrf(jnp.asarray(A), uplo="U")
        X = lapack.potrs(R, jnp.asarray(B), uplo="U")
        np.testing.assert_allclose(np.asarray(A @ X), B, rtol=0, atol=1e-11)

    def test_bad_uplo_rejected(self):
        with pytest.raises(ValueError, match="uplo"):
            lapack.potrs(jnp.eye(4), jnp.ones((4, 1)), uplo="X")


class TestCholeskySolve:
    def test_matches_numpy(self, grid2x2x1):
        rng = np.random.default_rng(2)
        A = _spd(rng, 32)
        B = rng.standard_normal((32, 4))
        X = cholesky.solve(grid2x2x1, jnp.asarray(A), jnp.asarray(B))
        np.testing.assert_allclose(np.asarray(X), np.linalg.solve(A, B),
                                   rtol=0, atol=1e-10)

    def test_robust_returns_info(self, grid2x2x1):
        rng = np.random.default_rng(3)
        A = _spd(rng, 16)
        B = rng.standard_normal((16, 1))
        cfg = cholesky.CholinvConfig(robust=RobustConfig())
        X, info = cholesky.solve(grid2x2x1, jnp.asarray(A), jnp.asarray(B),
                                 cfg)
        assert int(info) == 0
        np.testing.assert_allclose(np.asarray(X), np.linalg.solve(A, B),
                                   rtol=0, atol=1e-10)

    def test_shape_mismatch_rejected(self, grid2x2x1):
        with pytest.raises(ValueError, match="shape mismatch"):
            cholesky.solve(grid2x2x1, jnp.eye(8), jnp.ones((6, 1)))


# ---------------------------------------------------------------------------
# ops/masking.embed_identity_tail + serve/batching
# ---------------------------------------------------------------------------


class TestEmbedIdentityTail:
    def test_square_is_block_diag(self):
        rng = np.random.default_rng(4)
        A = _spd(rng, 5)
        P = np.asarray(masking.embed_identity_tail(jnp.asarray(A), 8, 8))
        np.testing.assert_array_equal(P[:5, :5], A)
        np.testing.assert_array_equal(P[5:, 5:], np.eye(3))
        np.testing.assert_array_equal(P[:5, 5:], 0)
        # stays SPD: Cholesky of diag(A, I) succeeds with finite entries
        assert np.all(np.isfinite(np.linalg.cholesky(P)))

    def test_tall_keeps_full_rank_gram(self):
        rng = np.random.default_rng(5)
        A = rng.standard_normal((12, 3))
        P = np.asarray(masking.embed_identity_tail(jnp.asarray(A), 16, 6))
        # unit columns live in the appended rows: gram is diag(AᵀA, I)
        G = P.T @ P
        np.testing.assert_allclose(G[:3, :3], A.T @ A, rtol=0, atol=1e-12)
        np.testing.assert_array_equal(G[3:, 3:], np.eye(3))
        np.testing.assert_array_equal(G[:3, 3:], 0)

    def test_noop_when_already_sized(self):
        A = jnp.ones((4, 4))
        assert masking.embed_identity_tail(A, 4, 4) is A

    def test_contract_violations_raise(self):
        A = jnp.ones((4, 2))
        with pytest.raises(ValueError):  # shrink
            masking.embed_identity_tail(A, 3, 2)
        with pytest.raises(ValueError):  # more new cols than new rows
            masking.embed_identity_tail(A, 5, 6)


class TestBucketing:
    def test_ladder_pick(self):
        b = batching.bucket_for("posv", (10, 10), (10, 2), "float64", CFG)
        assert b.a_shape == (16, 16) and b.b_shape == (16, 4)
        assert b.capacity == CFG.max_batch
        b = batching.bucket_for("inv", (8, 8), None, "float64", CFG)
        assert b.a_shape == (8, 8) and b.b_shape is None

    def test_lstsq_rows_include_column_pad(self):
        # m=30, n=10 -> nb=16; rows bucket at 30 + (16 - 10) = 36 -> 64
        b = batching.bucket_for("lstsq", (30, 10), (30, 1), "float64", CFG)
        assert b.a_shape == (64, 16) and b.b_shape == (64, 1)
        # contract holds: rows - m >= cols - n for the embed
        assert b.a_shape[0] - 30 >= b.a_shape[1] - 10

    def test_oversize_is_none(self):
        assert batching.bucket_for("posv", (40, 40), (40, 1), "float64",
                                   CFG) is None
        assert batching.bucket_for("lstsq", (200, 8), (200, 1), "float64",
                                   CFG) is None

    def test_unknown_op_rejected(self):
        with pytest.raises(ValueError, match="unknown serve op"):
            batching.bucket_for("gesv", (8, 8), (8, 1), "float64", CFG)

    def test_pad_assemble_crop_roundtrip(self):
        rng = np.random.default_rng(6)
        A = _spd(rng, 10)
        B = rng.standard_normal((10, 2))
        b = batching.bucket_for("posv", A.shape, B.shape, "float64", CFG)
        pa, pb = batching.pad_operands("posv", jnp.asarray(A),
                                       jnp.asarray(B), b)
        assert pa.shape == b.a_shape and pb.shape == b.b_shape
        Ab, Bb, occ = batching.assemble([pa], [pb], b)
        assert Ab.shape == (b.capacity,) + b.a_shape
        assert occ == pytest.approx(1 / b.capacity)
        # fill problems are benign identities against zero RHS
        np.testing.assert_array_equal(np.asarray(Ab[1]), np.eye(16))
        np.testing.assert_array_equal(np.asarray(Bb[1]), 0)
        # padded problem solves to the original solution + exact-zero tail
        Xp = np.linalg.solve(np.asarray(Ab[0]), np.asarray(Bb[0]))
        np.testing.assert_allclose(Xp[:10, :2], np.linalg.solve(A, B),
                                   rtol=0, atol=1e-12)
        np.testing.assert_array_equal(Xp[10:], 0)
        X = batching.crop("posv", jnp.asarray(Xp), A.shape, B.shape)
        assert X.shape == (10, 2)


# ---------------------------------------------------------------------------
# SolveEngine: results, cache, flush policy, faults
# ---------------------------------------------------------------------------


class TestEngineResults:
    def test_posv_matches_models_path(self, grid2x2x1):
        rng = np.random.default_rng(7)
        eng = SolveEngine(cfg=CFG)
        A = _spd(rng, 12)
        B = rng.standard_normal((12, 2))
        r = eng.solve("posv", A, B)
        assert r.ok and r.batched and r.bucket is not None
        ref = cholesky.solve(grid2x2x1, jnp.asarray(A), jnp.asarray(B))
        np.testing.assert_allclose(np.asarray(r.x), np.asarray(ref),
                                   rtol=0, atol=1e-10)

    def test_lstsq_matches_numpy(self):
        rng = np.random.default_rng(8)
        eng = SolveEngine(cfg=CFG)
        A = rng.standard_normal((40, 10))
        B = rng.standard_normal((40, 2))
        r = eng.solve("lstsq", A, B)
        assert r.ok and r.batched
        ref, *_ = np.linalg.lstsq(A, B, rcond=None)
        np.testing.assert_allclose(np.asarray(r.x), ref, rtol=0, atol=1e-9)

    def test_inv_matches_numpy(self):
        rng = np.random.default_rng(9)
        eng = SolveEngine(cfg=CFG)
        A = _spd(rng, 20)
        r = eng.solve("inv", A)
        assert r.ok and r.batched
        np.testing.assert_allclose(np.asarray(r.x), np.linalg.inv(A),
                                   rtol=0, atol=1e-10)

    def test_mixed_shapes_share_one_batch(self):
        # two different true shapes land in the SAME bucket and flush as one
        # batch, each cropping back to its own solution
        rng = np.random.default_rng(10)
        eng = SolveEngine(cfg=CFG)
        probs = [(_spd(rng, n), rng.standard_normal((n, 1))) for n in (9, 14)]
        tickets = [eng.submit("posv", A, B) for A, B in probs]
        assert eng.drain() == 1
        for (A, B), t in zip(probs, tickets):
            r = t.result()
            assert r.bucket[2] == (16, 16)
            np.testing.assert_allclose(np.asarray(r.x),
                                       np.linalg.solve(A, B),
                                       rtol=0, atol=1e-10)

    def test_submit_validation(self):
        eng = SolveEngine(cfg=CFG)
        with pytest.raises(ValueError, match="unknown serve op"):
            eng.submit("gesv", np.eye(4), np.ones((4, 1)))
        with pytest.raises(ValueError, match="RHS"):
            eng.submit("posv", np.eye(4), np.ones((3, 1)))
        with pytest.raises(ValueError, match="square"):
            eng.submit("inv", np.ones((4, 3)))
        with pytest.raises(ValueError, match="tall"):
            eng.submit("lstsq", np.ones((3, 5)), np.ones((3, 1)))


class TestEngineCache:
    def test_second_request_hits(self):
        rng = np.random.default_rng(11)
        eng = SolveEngine(cfg=CFG)
        A, B = _spd(rng, 8), rng.standard_normal((8, 1))
        eng.solve("posv", A, B)
        c = eng.cache_stats()
        assert (c["hits"], c["misses"], c["entries"]) == (0, 1, 1)
        eng.solve("posv", _spd(rng, 7), rng.standard_normal((7, 1)))
        c = eng.cache_stats()  # different true shape, same bucket -> hit
        assert (c["hits"], c["misses"], c["entries"]) == (1, 1, 1)
        assert c["hit_rate"] == pytest.approx(0.5)

    def test_warmup_compiles_do_not_count_as_misses(self):
        eng = SolveEngine(cfg=CFG)
        n = eng.warmup([("posv", (8, 8), (8, 1), "float64"),
                        ("posv", (6, 6), (6, 1), "float64"),  # same bucket
                        ("inv", (8, 8), None, "float64")])
        assert n == 2  # the duplicate bucket warms once
        c = eng.cache_stats()
        assert c == {"hits": 0, "misses": 0, "warmup_compiles": 2,
                     "compiles": 2, "entries": 2, "hit_rate": 1.0}

    def test_distinct_configs_never_share_entries(self):
        e1 = SolveEngine(cfg=CFG)
        e2 = SolveEngine(
            cfg=ServeConfig(buckets=CFG.buckets,
                            rows_buckets=CFG.rows_buckets,
                            nrhs_buckets=CFG.nrhs_buckets,
                            max_batch=2, max_delay_s=10.0)
        )
        assert e1._cfg_hash != e2._cfg_hash

    def test_oversize_routes_through_models(self):
        rng = np.random.default_rng(12)
        eng = SolveEngine(cfg=CFG)
        A = _spd(rng, 40)  # beyond the 32 ladder
        B = rng.standard_normal((40, 1))
        r = eng.solve("posv", A, B)
        assert r.ok and not r.batched and r.bucket is None
        np.testing.assert_allclose(np.asarray(r.x), np.linalg.solve(A, B),
                                   rtol=0, atol=1e-10)
        c = eng.cache_stats()
        assert (c["hits"], c["misses"]) == (0, 1)
        # identical oversize shape: exact-shape single-route cache hit
        r2 = eng.solve("posv", _spd(rng, 40), rng.standard_normal((40, 1)))
        assert r2.ok and not r2.batched
        c = eng.cache_stats()
        assert (c["hits"], c["misses"]) == (1, 1)

    def test_oversize_reject_policy(self):
        rng = np.random.default_rng(13)
        cfg = ServeConfig(buckets=(8,), rows_buckets=(32,), nrhs_buckets=(1,),
                          max_batch=2, max_delay_s=10.0, oversize="reject")
        eng = SolveEngine(cfg=cfg)
        r = eng.solve("posv", _spd(rng, 16), rng.standard_normal((16, 1)))
        assert not r.ok and r.x is None and "reject" in r.error
        assert eng.stats.failed == 1

    def test_unknown_oversize_policy_rejected(self):
        with pytest.raises(ValueError, match="oversize"):
            SolveEngine(cfg=ServeConfig(oversize="panic"))


class TestEngineFlush:
    def test_capacity_flush_inside_submit(self):
        rng = np.random.default_rng(14)
        eng = SolveEngine(cfg=CFG)
        tickets = [
            eng.submit("posv", _spd(rng, 8), rng.standard_normal((8, 1)))
            for _ in range(CFG.max_batch)
        ]
        # the max_batch-th submit flushed the bucket: no pump/drain needed
        assert all(t.done for t in tickets)
        assert eng.queue_depth() == 0
        assert eng.stats.batches == 1
        assert eng.stats.occupancies == [1.0]

    def test_deadline_flush_via_pump(self):
        rng = np.random.default_rng(15)
        eng = SolveEngine(cfg=CFG)
        t = eng.submit("posv", _spd(rng, 8), rng.standard_normal((8, 1)))
        assert not t.done and eng.queue_depth() == 1
        assert eng.pump() == 0  # younger than max_delay_s: stays queued
        assert not t.done
        # age the queue past the deadline with an explicit clock
        assert eng.pump(now=time.monotonic() + CFG.max_delay_s + 1) == 1
        assert t.done and t.result().ok
        assert eng.stats.occupancies == [pytest.approx(1 / CFG.max_batch)]

    def test_unflushed_ticket_raises(self):
        rng = np.random.default_rng(16)
        eng = SolveEngine(cfg=CFG)
        t = eng.submit("posv", _spd(rng, 8), rng.standard_normal((8, 1)))
        with pytest.raises(RuntimeError, match="not flushed"):
            t.result()
        eng.drain()
        assert t.result().ok


class TestEngineFaults:
    def _robust_cfg(self):
        return ServeConfig(buckets=CFG.buckets, rows_buckets=CFG.rows_buckets,
                           nrhs_buckets=CFG.nrhs_buckets, max_batch=3,
                           max_delay_s=10.0, robust=RobustConfig())

    def test_fault_flags_one_request_only(self):
        rng = np.random.default_rng(17)
        eng = SolveEngine(cfg=self._robust_cfg())
        probs = [(_spd(rng, 8), rng.standard_normal((8, 1)))
                 for _ in range(3)]
        with faultinject.active_plan(
            faultinject.Fault(tag="serve::ingest", kind="rank_deficient",
                              index=1)
        ) as plan:
            tickets = [eng.submit("posv", A, B) for A, B in probs]
            eng.drain()
        assert plan.fired == [("serve::ingest", 1)]
        rs = [t.result() for t in tickets]
        assert [r.ok for r in rs] == [True, False, True]
        # the poisoned neighbor carries a RobustInfo naming the breakdown
        assert isinstance(rs[1].info, RobustInfo)
        assert rs[1].info.breakdown == 1 and rs[1].info.info != 0
        for (A, B), r in ((probs[0], rs[0]), (probs[2], rs[2])):
            assert r.info.breakdown == 0
            np.testing.assert_allclose(np.asarray(r.x),
                                       np.linalg.solve(A, B),
                                       rtol=0, atol=1e-10)
        assert eng.stats.flagged == 1 and eng.stats.ok == 2

    def test_raise_fault_fails_request_engine_survives(self):
        rng = np.random.default_rng(18)
        eng = SolveEngine(cfg=self._robust_cfg())
        A, B = _spd(rng, 8), rng.standard_normal((8, 1))
        with faultinject.active_plan(
            faultinject.Fault(tag="serve::ingest", kind="raise")
        ):
            r = eng.solve("posv", A, B)
        assert not r.ok and r.x is None and "injected fault" in r.error
        assert eng.stats.failed == 1
        # the engine is not wedged: the next request succeeds normally
        r2 = eng.solve("posv", A, B)
        assert r2.ok
        np.testing.assert_allclose(np.asarray(r2.x), np.linalg.solve(A, B),
                                   rtol=0, atol=1e-10)


class TestEngineAcceptance:
    """The ISSUE 4 acceptance workload: warmup over >= 3 shape buckets,
    then a 50-request mixed stream -> zero steady-state recompiles, with
    every batched result checked against an unbatched reference."""

    def test_mixed_50_request_workload_zero_recompiles(self, grid2x2x1):
        rng = np.random.default_rng(19)
        eng = SolveEngine(cfg=CFG)
        ns = (6, 12, 24)  # -> buckets 8 / 16 / 32
        ops = ("posv", "inv", "lstsq", "posv", "lstsq")
        work = []
        for i in range(50):
            op, n, k = ops[i % 5], ns[i % 3], (1, 3)[i % 2]
            if op == "lstsq":
                A = rng.standard_normal((4 * n, n))
                B = rng.standard_normal((4 * n, k))
            else:
                A = _spd(rng, n)
                B = rng.standard_normal((n, k)) if op == "posv" else None
            work.append((op, A, B))
        compiled = eng.warmup(
            (op, A.shape, B.shape if B is not None else None, "float64")
            for op, A, B in work
        )
        assert compiled >= 3
        buckets = {
            batching.bucket_for(op, A.shape,
                                B.shape if B is not None else None,
                                "float64", CFG).a_shape
            for op, A, B in work
        }
        assert len(buckets) >= 3  # the ISSUE's >= 3 shape buckets

        tickets = [eng.submit(op, A, B) for op, A, B in work]
        eng.drain()
        c = eng.cache_stats()
        assert c["misses"] == 0 and c["hits"] > 0
        assert c["hit_rate"] == 1.0
        assert c["warmup_compiles"] == compiled == c["entries"]

        for (op, A, B), t in zip(work, tickets):
            r = t.result()
            assert r.ok and r.batched, (op, r.error)
            if op == "posv":
                ref = cholesky.solve(grid2x2x1, jnp.asarray(A),
                                     jnp.asarray(B))
            elif op == "lstsq":
                ref, *_ = np.linalg.lstsq(A, B, rcond=None)
            else:
                ref = np.linalg.inv(A)
            np.testing.assert_allclose(np.asarray(r.x), np.asarray(ref),
                                       rtol=0, atol=1e-8)

        rs = eng.emit_stats()["request_stats"]
        assert rs["requests"] == 50 and rs["ok"] == 50
        assert rs["cache"]["hit_rate"] == 1.0
        assert 0.0 < rs["batch_occupancy_mean"] <= 1.0


# ---------------------------------------------------------------------------
# stats + ledger + CLI (satellites b, c)
# ---------------------------------------------------------------------------


class TestPercentiles:
    def test_nearest_rank(self):
        out = harness.percentiles(range(1, 101))
        assert out == {"p50": 50, "p95": 95, "p99": 99}
        # every reported value is a sample that actually occurred
        assert harness.percentiles([40.0, 10.0, 30.0, 20.0]) == {
            "p50": 20.0, "p95": 40.0, "p99": 40.0,
        }

    def test_single_sample(self):
        assert harness.percentiles([7.0]) == {"p50": 7.0, "p95": 7.0,
                                              "p99": 7.0}

    def test_custom_points(self):
        out = harness.percentiles(range(1, 11), points=(10.0, 100.0))
        assert out == {"p10": 1, "p100": 10}

    def test_errors(self):
        with pytest.raises(ValueError, match="at least one"):
            harness.percentiles([])
        with pytest.raises(ValueError, match="outside"):
            harness.percentiles([1.0], points=(0.0,))


class TestStatsCollector:
    def test_snapshot_counts(self):
        c = stats.Collector()
        c.record_request("posv", 0.010, ok=True)
        c.record_request("posv", 0.030, ok=False, flagged=True)
        c.record_request("inv", 0.020, ok=False, failed=True)
        c.note_batch(0.5)
        c.note_batch(1.0)
        c.note_queue_depth(4)
        snap = c.snapshot({"hits": 3, "misses": 1, "warmup_compiles": 2,
                           "entries": 3, "hit_rate": 0.75})
        assert snap["requests"] == 3 and snap["ok"] == 1
        assert snap["flagged"] == 1 and snap["failed"] == 1
        assert snap["ops"] == {"posv": 2, "inv": 1}
        assert snap["latency_ms"]["p50"] == pytest.approx(20.0)
        assert snap["batch_occupancy_mean"] == pytest.approx(0.75)
        assert snap["queue_depth_max"] == 4
        assert ledger.validate_request_stats(snap) == []

    def test_empty_snapshot_is_valid(self):
        snap = stats.Collector().snapshot()
        assert snap["latency_ms"] == {"p50": 0.0, "p95": 0.0, "p99": 0.0}
        assert ledger.validate_request_stats(snap) == []


def _mk_bench_record(value=1.0):
    return ledger.record(
        "bench:test", ledger.manifest(dtype=jnp.float32),
        measured={"metric": "test_tflops", "value": value, "unit": "TFLOP/s",
                  "shape": [64, 64]},
    )


class TestRequestStatsLedger:
    def _emit(self, path=None, latency=0.01, hit_rate=1.0):
        c = stats.Collector()
        c.record_request("posv", latency, ok=True)
        return c.emit(str(path) if path else None,
                      cache={"hits": 4, "misses": 0, "warmup_compiles": 2,
                             "entries": 2, "hit_rate": hit_rate})

    def test_emit_roundtrip(self, tmp_path):
        path = tmp_path / "serve.jsonl"
        rec = self._emit(path)
        assert rec["kind"] == "serve:request_stats"
        (read,) = ledger.read(str(path))
        assert read["request_stats"] == rec["request_stats"]
        assert ledger.validate_request_stats(read["request_stats"]) == []

    def test_diff_exempts_request_stats_latency(self):
        # wildly different latency mixes: workload property, not a kernel
        # regression -> diff stays clean
        a, b = self._emit(latency=0.001), self._emit(latency=5.0)
        assert ledger.diff([a], [b]) == []

    def test_diff_still_flags_real_metric_drop(self):
        # exemption must not swallow a genuine bench regression riding in
        # the same ledgers
        a = [self._emit(), _mk_bench_record(value=1.0)]
        b = [self._emit(), _mk_bench_record(value=0.5)]
        regs = ledger.diff(a, b)
        assert [r.field for r in regs] == ["measured.value"]

    def test_diff_refuses_malformed_block(self):
        a, b = self._emit(), self._emit()
        b["request_stats"]["cache"]["hit_rate"] = 1.5
        with pytest.raises(ledger.LedgerIncompatible, match="hit_rate"):
            ledger.diff([a], [b])
        del a["request_stats"]["latency_ms"]
        with pytest.raises(ledger.LedgerIncompatible, match="latency_ms"):
            ledger.diff([a], [self._emit()])

    def test_validate_rejects_non_dict(self):
        assert ledger.validate_request_stats([1, 2]) != []


class TestServeReportCLI:
    def _emit(self, path, hit_rate=1.0, p99=None):
        c = stats.Collector()
        c.record_request("posv", (p99 or 10.0) / 1e3, ok=True)
        c.emit(str(path), cache={"hits": 9, "misses": 0, "warmup_compiles": 3,
                                 "entries": 3, "hit_rate": hit_rate})

    def test_report_ok(self, tmp_path, capsys):
        path = tmp_path / "serve.jsonl"
        self._emit(path)
        assert obs_main.main(["serve-report", str(path)]) == 0
        out = capsys.readouterr().out
        assert "hit_rate=1.000" in out and "serve-report OK" in out

    def test_hit_rate_gate_fails(self, tmp_path, capsys):
        path = tmp_path / "serve.jsonl"
        self._emit(path, hit_rate=0.5)
        assert obs_main.main(["serve-report", str(path),
                              "--min-hit-rate", "1.0"]) == 1
        assert "hit_rate 0.500 < 1.0" in capsys.readouterr().err

    def test_p99_gate_fails(self, tmp_path, capsys):
        path = tmp_path / "serve.jsonl"
        self._emit(path, p99=500.0)
        assert obs_main.main(["serve-report", str(path),
                              "--max-p99-ms", "100"]) == 1
        assert "p99" in capsys.readouterr().err

    def test_malformed_record_exits_2(self, tmp_path, capsys):
        path = tmp_path / "serve.jsonl"
        rec = stats.Collector().emit(None)
        rec["request_stats"]["schema_version"] = 999
        ledger.append(str(path), rec)
        assert obs_main.main(["serve-report", str(path)]) == 2
        assert "malformed" in capsys.readouterr().err

    def test_no_records_with_gates_fails(self, tmp_path, capsys):
        path = tmp_path / "empty.jsonl"
        ledger.append(str(path), _mk_bench_record())
        assert obs_main.main(["serve-report", str(path)]) == 0
        assert obs_main.main(["serve-report", str(path),
                              "--min-hit-rate", "1.0"]) == 1


@pytest.mark.slow
class TestSmokeCLI:
    def test_smoke_end_to_end(self, tmp_path, capsys):
        from capital_tpu.serve import __main__ as serve_main

        path = tmp_path / "smoke.jsonl"
        rc = serve_main.main(["smoke", "--requests", "24",
                              "--ledger", str(path)])
        assert rc == 0
        assert "serve-smoke OK" in capsys.readouterr().out
        assert obs_main.main(["serve-report", str(path),
                              "--min-hit-rate", "1.0"]) == 0
