"""Concurrency-sanitizer tests: the guarded-by/lock-order static pass
(per-rule pass/fail source fixtures), the invariant registry, the
deterministic interleaving explorer (same seed -> same schedule -> same
trace; minimal-trace reproduction of the seeded race), the engine's
mid-flight-eviction loud-failure fix, the CLI exit codes (1 gate-fail /
2 malformed), the dead-gate self-check, and the lint:report ledger
round-trip through ``obs lint-report --require-pass concurrency``
(docs/STATIC_ANALYSIS.md "Concurrency sanitizer")."""

import importlib.util
import json

import numpy as np
import pytest

from capital_tpu.lint import __main__ as lint_main
from capital_tpu.lint import concurrency, invariants, rules, schedule
from capital_tpu.obs import __main__ as obs_main
from capital_tpu.serve import ServeConfig, SolveEngine
from capital_tpu.serve.factorcache import FactorCache

FIXTURE = lint_main._fixture_path()

S_CFG = ServeConfig(
    buckets=(8,),
    rows_buckets=(32,),
    nrhs_buckets=(2,),
    max_batch=2,
    max_delay_s=10.0,
    nblocks_buckets=(2, 4),
    block_buckets=(4,),
)


def _lint(text, path="x/box.py"):
    return concurrency.lint_concurrency_source(path, text=text)


def _by_rule(findings, rule):
    return [f for f in findings if f.rule == rule]


def _load_fixture():
    spec = importlib.util.spec_from_file_location("concurrency_fault",
                                                  FIXTURE)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# ---------------------------------------------------------------------------
# static layer: per-rule pass/fail source fixtures
# ---------------------------------------------------------------------------


GOOD = """
import threading

class Box:
    def __init__(self):
        self._lock = threading.Lock()  # guarded-by: <lock>
        self.items = []                # guarded-by: self._lock
        self.cfg = 1                   # guarded-by: <frozen>
        self.tally = 0                 # guarded-by: <owner-thread>

    def add(self, x):
        with self._lock:
            self.items.append(x)

    def size(self):  # lock-held: self._lock
        return len(self.items)

    def use(self):
        with self._lock:
            return self.size()

    def bump(self):
        self.tally += 1
"""


class TestGuardedBy:
    def test_disciplined_class_is_clean(self):
        assert _lint(GOOD) == []

    def test_unguarded_read_and_write_flagged(self):
        bad = GOOD + """
    def racy(self):
        self.items = []
        return self.items
"""
        fs = _by_rule(_lint(bad), concurrency.GUARDED_BY)
        assert len(fs) == 2
        assert all(f.severity == rules.ERROR for f in fs)
        assert any("write" in f.message for f in fs)
        assert any("read" in f.message for f in fs)

    def test_lock_held_marker_covers_access(self):
        # size() touches items with no lexical with — the marker is the
        # contract, and use() holds the lock at the call site: clean
        assert _lint(GOOD) == []

    def test_lock_held_call_without_lock_flagged(self):
        bad = GOOD + """
    def sloppy(self):
        return self.size()
"""
        fs = _by_rule(_lint(bad), concurrency.LOCK_HELD_CALL)
        assert len(fs) == 1
        assert "size()" in fs[0].message

    def test_missing_annotation_flagged_exhaustively(self):
        bad = GOOD.replace("self.tally = 0                 "
                           "# guarded-by: <owner-thread>",
                           "self.tally = 0")
        fs = _by_rule(_lint(bad), concurrency.GUARDED_BY_MISSING)
        assert len(fs) == 1
        assert "Box.tally" in fs[0].message

    def test_grammar_unknown_guard_and_nonlock_flagged(self):
        bad = GOOD.replace("# guarded-by: <frozen>", "# guarded-by: <bogus>")
        bad = bad.replace("# guarded-by: self._lock",
                          "# guarded-by: self.items")
        fs = _by_rule(_lint(bad), concurrency.GUARDED_BY_GRAMMAR)
        assert len(fs) == 2

    def test_frozen_write_flagged_and_read_free(self):
        bad = GOOD + """
    def refreeze(self):
        self.cfg = 2
        return self.cfg
"""
        fs = _by_rule(_lint(bad), concurrency.GUARDED_BY_FROZEN)
        assert len(fs) == 1
        assert "refreeze" in fs[0].message

    def test_inline_allow_unguarded_suppresses(self):
        bad = GOOD + """
    def racy(self):
        return self.items  # lint: allow-unguarded — snapshot for repr
"""
        assert _lint(bad) == []

    def test_unannotated_lockless_class_is_skipped(self):
        assert _lint("class Plain:\n"
                     "    def __init__(self):\n"
                     "        self.x = 1\n") == []


class TestBlockingAndCycles:
    def test_blocking_under_lock_flagged(self):
        bad = GOOD + """
    def stall(self):
        import time
        with self._lock:
            time.sleep(1.0)
"""
        fs = _by_rule(_lint(bad), concurrency.BLOCKING_UNDER_LOCK)
        assert len(fs) == 1
        assert "time.sleep" in fs[0].message

    def test_blocking_suppression_marker(self):
        ok = GOOD + """
    def stall(self):
        import time
        with self._lock:
            time.sleep(1.0)  # lint: allow-blocking-under-lock — test rig
"""
        assert _lint(ok) == []

    def test_closure_body_not_under_enclosing_lock(self):
        # the router-pump shape: a loop closure DEFINED under the lock
        # but run later on its own thread must not be flagged
        ok = GOOD + """
    def start(self):
        import time
        with self._lock:
            def loop():
                time.sleep(1.0)
            return loop
"""
        assert _lint(ok) == []

    def test_lock_order_cycle_detected_once_canonically(self):
        fs = _by_rule(concurrency.lint_concurrency_source(FIXTURE),
                      concurrency.LOCK_ORDER_CYCLE)
        assert len(fs) == 1
        assert "LockCycle._a -> LockCycle._b -> LockCycle._a" \
            in fs[0].message

    def test_consistent_order_is_acyclic(self):
        ok = """
import threading

class TwoLocks:
    def __init__(self):
        self._a = threading.Lock()  # guarded-by: <lock>
        self._b = threading.Lock()  # guarded-by: <lock>

    def one(self):
        with self._a:
            with self._b:
                pass

    def two(self):
        with self._a:
            with self._b:
                pass
"""
        assert _lint(ok) == []

    def test_cycle_through_call_propagation(self):
        bad = """
import threading

class CallCycle:
    def __init__(self):
        self._a = threading.Lock()  # guarded-by: <lock>
        self._b = threading.Lock()  # guarded-by: <lock>

    def inner_b(self):
        with self._b:
            pass

    def inner_a(self):
        with self._a:
            pass

    def left(self):
        with self._a:
            self.inner_b()

    def right(self):
        with self._b:
            self.inner_a()
"""
        fs = _by_rule(_lint(bad), concurrency.LOCK_ORDER_CYCLE)
        assert len(fs) == 1

    def test_reentrant_same_lock_is_not_a_cycle(self):
        ok = """
import threading

class Reent:
    def __init__(self):
        self._lock = threading.RLock()  # guarded-by: <lock>

    def outer(self):
        with self._lock:
            self.inner()

    def inner(self):
        with self._lock:
            pass
"""
        assert _by_rule(_lint(ok), concurrency.LOCK_ORDER_CYCLE) == []


class TestRepoIsClean:
    def test_serve_plane_has_zero_concurrency_errors(self):
        # the satellite contract: fixes landed, not baseline entries
        fs = [f for f in concurrency.lint_tree()
              if f.severity == rules.ERROR]
        assert fs == [], "\n".join(f.render() for f in fs)

    def test_fixture_is_still_broken(self):
        fs = concurrency.lint_concurrency_source(FIXTURE)
        assert _by_rule(fs, concurrency.GUARDED_BY)
        assert _by_rule(fs, concurrency.LOCK_ORDER_CYCLE)


# ---------------------------------------------------------------------------
# invariant registry
# ---------------------------------------------------------------------------


def _router_block(**over):
    base = {"dispatched": 3, "completed": 2, "parked": 0, "redispatched": 0,
            "duplicates": 0, "failed_replicas": 0,
            "per_replica": {"r0": {"dispatched": 3, "completed": 2,
                                   "outstanding": 1, "draining": False}}}
    base.update(over)
    return base


def _window_block(**over):
    base = {"requests": 4, "ok": 3, "failed": 1, "shed": 0,
            "hist_ms": {"edges": [1.0], "counts": [4, 0]}, "sampled": 4,
            "samples_capped": False}
    base.update(over)
    return base


def _session_block(**over):
    base = {"opens": 2, "reseeds": 1, "appends": 3, "solves": 2,
            "contracts": 1, "closes": 1, "failures": 0,
            "evicted_failures": 1, "hits": 6, "misses": 1,
            "blocks_appended": 8, "blocks_dropped": 2}
    base.update(over)
    return base


class TestInvariantRegistry:
    def test_registry_names_subjects_and_lookup(self):
        names = [inv.name for inv in invariants.REGISTRY]
        assert names == ["router-no-drop", "router-counter-sanity",
                         "cache-byte-ledger", "cache-counter-conservation",
                         "window-coherence", "session-ledger"]
        assert {inv.subject for inv in invariants.REGISTRY} \
            == set(invariants.SUBJECTS)
        assert len(invariants.by_subject(invariants.ROUTER)) == 2
        with pytest.raises(ValueError, match="subject"):
            invariants.Invariant("x", "nope", "d", lambda b: None)

    def test_router_no_drop_pass_and_fail(self):
        assert invariants.check({invariants.ROUTER: _router_block()}) == []
        v = invariants.check(
            {invariants.ROUTER: _router_block(completed=1)})
        assert len(v) == 1 and v[0].startswith("router-no-drop:")

    def test_router_counter_sanity(self):
        v = invariants.check(
            {invariants.ROUTER: _router_block(duplicates=-1)})
        assert any("router-counter-sanity" in m for m in v)

    def test_cache_invariants_on_the_real_cache(self):
        blk = np.zeros((1, 8, 8), dtype=np.float32)
        cache = FactorCache(budget_bytes=3 * blk.nbytes)
        cache.put("a", "dense", (blk,), {})
        cache.put("b", "dense", (blk, blk), {})
        cache.lookup("a")
        cache.put("c", "dense", (blk, blk), {})   # evicts under pressure
        cache.release("b") if "b" in cache else None
        assert invariants.check(
            {invariants.FACTOR_CACHE: cache.stats()}) == []

    def test_cache_invariants_catch_doctored_blocks(self):
        blk = np.zeros((1, 8, 8), dtype=np.float32)
        cache = FactorCache(budget_bytes=4 * blk.nbytes)
        cache.put("a", "dense", (blk,), {})
        s = dict(cache.stats())
        s["bytes"] = s["bytes"] + 1
        v = invariants.check({invariants.FACTOR_CACHE: s})
        assert any("cache-byte-ledger" in m for m in v)
        s = dict(cache.stats())
        s["installs"] = 0
        v = invariants.check({invariants.FACTOR_CACHE: s})
        assert any("cache-counter-conservation" in m for m in v)

    def test_window_coherence_pass_and_fail(self):
        assert invariants.check(
            {invariants.SERVE_WINDOW: _window_block()}) == []
        v = invariants.check(
            {invariants.SERVE_WINDOW: _window_block(shed=1)})
        assert any("window-coherence" in m for m in v)
        v = invariants.check(
            {invariants.SERVE_WINDOW: _window_block(sampled=9)})
        assert any("window-coherence" in m for m in v)

    def test_session_ledger_pass_and_fail(self):
        assert invariants.check(
            {invariants.SESSIONS: _session_block()}) == []
        v = invariants.check(
            {invariants.SESSIONS: _session_block(misses=0)})
        assert any("session-ledger" in m for m in v)
        v = invariants.check(
            {invariants.SESSIONS: _session_block(hits=5)})
        assert any("session-ledger" in m for m in v)

    def test_malformed_block_is_a_violation_not_a_pass(self):
        v = invariants.check({invariants.ROUTER: {"completed": 1}})
        assert v and "malformed" in v[0]

    def test_absent_subject_is_skipped(self):
        assert invariants.check({}) == []


# ---------------------------------------------------------------------------
# the engine fix: mid-flight eviction fails loudly, never truncates
# ---------------------------------------------------------------------------


class TestEvictionLoudFailure:
    def _engine_with_tiny_cache(self):
        eng = SolveEngine(cfg=S_CFG)
        blk = np.zeros((1, 8, 8), dtype=np.float32)
        eng.factors = FactorCache(budget_bytes=3 * blk.nbytes)
        return eng, blk

    def test_session_extend_sink_raises_session_evicted(self):
        eng, blk = self._engine_with_tiny_cache()
        eng.factors.put("tok", "session", (blk, blk), {"dropped": 0})
        big = np.zeros((3, 8, 8), dtype=np.float32)
        eng.factors.put("hog", "dense", (big,), {})   # evicts "tok"
        assert eng.factors.evicted("tok")
        sink = eng._session_extend_sink("session_append", "tok", 8)
        x, info, err = sink((blk, blk), (), 0)
        assert err is not None and err.startswith("SessionEvicted:")
        assert eng.factors.peek("tok") is None        # nothing installed

    def test_session_open_still_installs_fresh(self):
        eng, blk = self._engine_with_tiny_cache()
        sink = eng._session_extend_sink("session_open", "fresh", 8)
        x, info, err = sink((blk, blk), (), 0)
        assert err is None
        assert eng.factors.peek("fresh") is not None

    def test_blocktri_extend_sink_fails_loudly(self):
        eng, blk = self._engine_with_tiny_cache()
        eng.factors.put("chain", "blocktri", (blk, blk), {})
        big = np.zeros((3, 8, 8), dtype=np.float32)
        eng.factors.put("hog", "dense", (big,), {})   # evicts "chain"
        sink = eng._extend_sink("chain", 8, prior=1)
        x, info, err = sink((blk, blk), (), 0)
        assert err is not None and "evicted" in err
        assert eng.factors.peek("chain") is None


# ---------------------------------------------------------------------------
# interleaving explorer
# ---------------------------------------------------------------------------


class TestExplorer:
    def test_same_seed_same_schedule_same_trace(self):
        sc = schedule.SCENARIOS[0]
        a = schedule.run_schedule(sc, seed=7)
        b = schedule.run_schedule(sc, seed=7)
        assert a.choices == b.choices and a.trace == b.trace

    def test_different_seed_different_schedule(self):
        sc = schedule.SCENARIOS[0]
        assert schedule.run_schedule(sc, seed=7).choices \
            != schedule.run_schedule(sc, seed=8).choices

    def test_forced_replay_reproduces_trace(self):
        sc = schedule.SCENARIOS[0]
        a = schedule.run_schedule(sc, seed=7)
        b = schedule.run_schedule(sc, seed=7, forced=a.choices)
        assert b.trace == a.trace

    @pytest.mark.parametrize("sc", schedule.SCENARIOS,
                             ids=[s.name for s in schedule.SCENARIOS])
    def test_scenarios_hold_every_invariant(self, sc):
        failing, runs = schedule.explore(sc, 25, seed=0)
        assert failing is None, (
            f"{failing and failing.violation.messages}\n"
            f"{failing and failing.render_trace()}")
        assert runs == 25

    def test_seeded_race_reproduced_with_minimal_trace(self):
        sc = schedule.fault_scenario(_load_fixture())
        first = next(res for res in
                     (schedule.run_schedule(sc, seed=s) for s in range(50))
                     if res.violation is not None)
        shrunk = schedule.shrink(sc, first)
        assert shrunk.violation is not None
        assert shrunk.violation.kind == first.violation.kind
        assert 0 < len(shrunk.trace) <= len(first.trace)
        # the minimal schedule replays to the same violation
        again = schedule.run_schedule(sc, seed=shrunk.seed,
                                      forced=shrunk.choices)
        assert again.violation is not None
        assert again.violation.kind == shrunk.violation.kind

    def test_deadlock_detected_with_trace(self):
        def build(sched):
            import threading
            a, b = threading.Lock(), threading.Lock()

            def left():
                with a:
                    sched.yield_point("holding a")
                    with b:
                        pass

            def right():
                with b:
                    sched.yield_point("holding b")
                    with a:
                        pass

            return schedule.ScenarioCtx(
                threads=[("left", left), ("right", right)])

        sc = schedule.Scenario("abba", "deadlock shape", build)
        failing, _ = schedule.explore(sc, 30, seed=0)
        assert failing is not None
        assert failing.violation.kind == "deadlock"
        assert failing.trace

    def test_thread_exception_is_a_violation(self):
        def build(sched):
            def boom():
                raise ValueError("scripted failure")

            return schedule.ScenarioCtx(threads=[("boom", boom)])

        res = schedule.run_schedule(
            schedule.Scenario("boom", "raises", build), seed=0)
        assert res.violation is not None
        assert res.violation.kind == "thread-exception"
        assert "scripted failure" in res.violation.messages[0]

    def test_patched_primitives_are_restored(self):
        import threading
        before = (threading.Lock, threading.RLock, threading.Event)
        schedule.run_schedule(schedule.SCENARIOS[2], seed=0)
        assert (threading.Lock, threading.RLock, threading.Event) == before


# ---------------------------------------------------------------------------
# CLI exit codes, self-check dead-gate, ledger round-trip
# ---------------------------------------------------------------------------


class TestCLI:
    def test_clean_repo_exits_zero(self):
        assert lint_main.main(["concurrency", "--static-only",
                               "--no-self-check", "--no-baseline"]) == 0

    def test_gate_failure_exits_one(self, tmp_path):
        assert lint_main.main(["concurrency", FIXTURE, "--static-only",
                               "--no-self-check", "--no-baseline"]) == 1

    def test_malformed_exits_two(self):
        assert lint_main.main(["concurrency", "--schedules", "0",
                               "--no-baseline"]) == 2
        assert lint_main.main(["concurrency", "--dynamic-only",
                               "--scenario", "no-such-scenario",
                               "--no-baseline"]) == 2

    def test_dynamic_scenario_filter_runs(self):
        assert lint_main.main(["concurrency", "--dynamic-only",
                               "--schedules", "5", "--no-self-check",
                               "--scenario", "evict-vs-append",
                               "--no-baseline"]) == 0

    def test_self_check_passes_on_the_real_fixture(self):
        assert lint_main.main(["concurrency", "--static-only",
                               "--schedules", "30", "--no-baseline"]) == 0

    def test_self_check_dead_gate_fires_on_a_fixed_fixture(
            self, tmp_path, monkeypatch, capsys):
        fixed = tmp_path / "fixed_fault.py"
        fixed.write_text(
            "import threading\n\n\n"
            "class RacyCounter:\n"
            "    def __init__(self, yield_point=None):\n"
            "        self._lock = threading.Lock()  # guarded-by: <lock>\n"
            "        self.count = 0  # guarded-by: self._lock\n"
            "        self.increments = 0  # guarded-by: self._lock\n"
            "        self._yield = yield_point or (lambda r: None)"
            "  # guarded-by: <frozen>\n\n"
            "    def increment(self):\n"
            "        with self._lock:\n"
            "            v = self.count\n"
            "            self.count = v + 1\n"
            "            self.increments += 1\n")
        monkeypatch.setattr(lint_main, "_fixture_path",
                            lambda: str(fixed))
        rc = lint_main.main(["concurrency", "--static-only",
                             "--schedules", "30", "--no-baseline"])
        assert rc == 1
        out = capsys.readouterr().out
        assert "self-check-dead" in out

    def test_ledger_round_trip_and_require_pass(self, tmp_path, capsys):
        led = str(tmp_path / "lint_report.jsonl")
        assert lint_main.main(["concurrency", "--dynamic-only",
                               "--schedules", "5",
                               "--scenario", "evict-vs-append",
                               "--no-baseline", "--ledger", led]) == 0
        with open(led) as f:
            recs = [json.loads(line) for line in f]
        assert len(recs) == 1
        block = recs[0]["lint_report"]
        assert block["pass"] == "concurrency" and block["ok"] is True
        assert obs_main.main(["lint-report", led,
                              "--require-pass", "concurrency"]) == 0
        capsys.readouterr()
        assert obs_main.main(["lint-report", led,
                              "--require-pass", "source"]) == 1

    def test_failing_dynamic_report_carries_minimal_trace(self, tmp_path):
        mod = _load_fixture()
        sc = schedule.fault_scenario(mod)
        failing, _ = schedule.explore(sc, 50, seed=0)
        f = schedule.violation_finding(sc, failing)
        assert f.rule == schedule.INTERLEAVING
        assert f.severity == rules.ERROR
        assert "minimal schedule" in f.message and "step" in f.message
