"""PR 7 serving tests: the continuous-batching scheduler, the persistent
AOT executable cache, the queue-wait/device latency split, and the
closed-loop load generator.

The acceptance properties of ISSUE 7 / docs/SERVING.md are asserted
directly:

* **cold-start** — a SECOND SolveEngine pointed at a warm persist_dir
  serves a 50-request mixed smoke with ZERO fresh XLA compiles and
  hit_rate == 1.0 (TestColdStartAcceptance);
* **persistence failure modes** — a corrupt entry, a fingerprint (jaxlib/
  platform) mismatch, and concurrent writers all degrade to
  compile-and-overwrite, never to an exception, and each miss/error is
  visible in cache_stats() (TestPersistentCacheFailureModes);
* **continuous vs sync** — the continuous scheduler dispatches without
  landing (ticket done, response pending) and beats the PR 4 stop-and-go
  baseline on the same fixed-seed closed-loop workload (TestScheduler,
  TestLoadgen — the in-test speedup bound is a lenient sanity floor; the
  measured A/B lives in `make serve-bench`'s ledger records).

Persistence tests force small_n_impl='pallas' with f32: on the CPU rig
only pure-HLO programs persist (the pallas interpret kernels discharge to
plain HLO; LAPACK custom calls serialize as process-local addresses —
serve/cache.persistable_program), so the vmap/f64 routes used elsewhere in
the serve tests would legitimately skip the disk tier.
"""

import os
import pickle
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from capital_tpu.obs import __main__ as obs_main
from capital_tpu.obs import ledger
from capital_tpu.serve import (
    ExecutableCache,
    ServeConfig,
    SolveEngine,
    loadgen,
    stats,
)
from capital_tpu.serve import cache as serve_cache
from capital_tpu.utils import tracing

# pallas-route f32 config: every bucket program is pure HLO -> persistable
# on the CPU rig.  One tiny bucket keeps each test at 1-2 compiles.
def _pcfg(persist_dir=None, **kw):
    return ServeConfig(
        buckets=(8,), rows_buckets=(32,), nrhs_buckets=(1,),
        max_batch=2, max_delay_s=10.0, small_n_impl="pallas",
        persist_dir=str(persist_dir) if persist_dir else None, **kw,
    )


def _spd(rng, n, dtype=np.float32):
    M = rng.standard_normal((n, n))
    return (M @ M.T / n + 3.0 * np.eye(n)).astype(dtype)


def _posv_args(rng, n=8, k=1, dtype=np.float32):
    return _spd(rng, n, dtype), rng.standard_normal((n, k)).astype(dtype)


POSV_SPEC = [("posv", (8, 8), (8, 1), "float32")]


# ---------------------------------------------------------------------------
# ExecutableCache: the two-tier resolution and its counters
# ---------------------------------------------------------------------------


def _toy_build(sds_n=4):
    """A tiny pure-HLO program (persistable on every backend)."""
    sds = jax.ShapeDtypeStruct((sds_n,), jnp.float32)
    return lambda: jax.jit(lambda x: x * 2.0 + 1.0).lower(sds).compile()


class TestExecutableCache:
    def test_memory_tier_counters(self):
        c = ExecutableCache()
        exe = c.get(("k",), _toy_build())
        assert c.get(("k",), _toy_build()) is exe
        s = c.stats()
        assert (s["hits"], s["misses"], s["compiles"]) == (1, 1, 1)
        assert "disk" not in s  # no persist_dir -> no disk block

    def test_warmup_lookup_excluded_from_hit_rate(self):
        c = ExecutableCache()
        c.get(("k",), _toy_build(), warmup=True)
        s = c.stats()
        assert s == {"hits": 0, "misses": 0, "warmup_compiles": 1,
                     "compiles": 1, "entries": 1, "hit_rate": 1.0}

    def test_disk_roundtrip_across_instances(self, tmp_path):
        c1 = ExecutableCache(str(tmp_path))
        c1.get(("k",), _toy_build())
        assert c1.disk_misses == 1  # cold dir
        files = [f for f in os.listdir(tmp_path) if f.endswith(".exe")]
        assert len(files) == 1
        c2 = ExecutableCache(str(tmp_path))
        exe = c2.get(("k",), _toy_build())
        assert (c2.disk_hits, c2.compiles) == (1, 0)
        np.testing.assert_allclose(
            np.asarray(exe(jnp.ones(4, jnp.float32))), 3.0)

    def test_distinct_keys_distinct_files(self, tmp_path):
        c = ExecutableCache(str(tmp_path))
        assert c.entry_path(("a",)) != c.entry_path(("b",))

    def test_no_tmp_files_left_behind(self, tmp_path):
        c = ExecutableCache(str(tmp_path))
        c.get(("k1",), _toy_build())
        c.get(("k2",), _toy_build(3))
        assert not [f for f in os.listdir(tmp_path) if ".tmp." in f]


class TestPersistentCacheFailureModes:
    def test_corrupt_entry_recompiles_and_overwrites(self, tmp_path):
        c1 = ExecutableCache(str(tmp_path))
        c1.get(("k",), _toy_build())
        path = c1.entry_path(("k",))
        with open(path, "wb") as f:
            f.write(b"\x00garbage that is not a pickle")
        c2 = ExecutableCache(str(tmp_path))
        exe = c2.get(("k",), _toy_build())  # must NOT raise
        assert c2.disk_errors == 1 and c2.compiles == 1
        assert c2.stats()["disk"]["errors"] == 1
        np.testing.assert_allclose(
            np.asarray(exe(jnp.zeros(4, jnp.float32))), 1.0)
        # the overwrite healed the entry: a third instance disk-hits
        c3 = ExecutableCache(str(tmp_path))
        c3.get(("k",), _toy_build())
        assert (c3.disk_hits, c3.compiles) == (1, 0)

    def test_truncated_entry_recompiles(self, tmp_path):
        c1 = ExecutableCache(str(tmp_path))
        c1.get(("k",), _toy_build())
        path = c1.entry_path(("k",))
        blob = open(path, "rb").read()
        with open(path, "wb") as f:
            f.write(blob[: len(blob) // 2])  # torn write, as if non-atomic
        c2 = ExecutableCache(str(tmp_path))
        c2.get(("k",), _toy_build())
        assert c2.disk_errors == 1 and c2.compiles == 1

    def test_fingerprint_mismatch_reads_as_stale_not_corrupt(self, tmp_path):
        c1 = ExecutableCache(str(tmp_path))
        c1.get(("k",), _toy_build())
        path = c1.entry_path(("k",))
        entry = pickle.load(open(path, "rb"))
        entry["fingerprint"] = dict(entry["fingerprint"], jaxlib="0.0.0")
        with open(path, "wb") as f:
            pickle.dump(entry, f)
        c2 = ExecutableCache(str(tmp_path))
        c2.get(("k",), _toy_build())  # must NOT raise, must not load
        assert c2.disk_misses == 1 and c2.disk_errors == 0
        assert c2.compiles == 1

    def test_entry_version_is_part_of_fingerprint(self, monkeypatch,
                                                  tmp_path):
        c1 = ExecutableCache(str(tmp_path))
        c1.get(("k",), _toy_build())
        monkeypatch.setattr(serve_cache, "ENTRY_VERSION",
                            serve_cache.ENTRY_VERSION + 1)
        c2 = ExecutableCache(str(tmp_path))
        c2.get(("k",), _toy_build())
        # different entry_version -> different filename hash -> plain miss
        assert c2.disk_misses == 1 and c2.compiles == 1

    def test_concurrent_writers_race_benignly(self, tmp_path):
        # two caches compile the same key independently (the classic race:
        # both missed before either's store landed) and both store.
        # last-writer-wins via the atomic os.replace: the surviving file is
        # valid, and no torn / *.tmp.* remnants linger for a reader to trip
        # on
        c1 = ExecutableCache(str(tmp_path))
        e1 = c1.get(("k",), _toy_build())
        os.remove(c1.entry_path(("k",)))  # c2 misses as if c1 hadn't stored
        c2 = ExecutableCache(str(tmp_path))
        c2.get(("k",), _toy_build())  # compiles + stores
        c1._store(("k",), e1)  # c1's store lands second
        files = os.listdir(tmp_path)
        assert len([f for f in files if f.endswith(".exe")]) == 1
        assert not [f for f in files if ".tmp." in f]
        c3 = ExecutableCache(str(tmp_path))
        c3.get(("k",), _toy_build())
        assert (c3.disk_hits, c3.disk_errors) == (1, 0)

    def test_unwritable_dir_counts_error_not_raise(self, tmp_path):
        # a persist_dir that can never materialize (its parent is a FILE,
        # so makedirs raises even for root) must cost disk_errors, not an
        # exception — the in-memory entry still serves
        blocker = tmp_path / "blocker"
        blocker.write_text("not a directory")
        c = ExecutableCache(str(blocker / "sub"))
        exe = c.get(("k",), _toy_build())  # must NOT raise
        assert c.disk_errors >= 1
        np.testing.assert_allclose(
            np.asarray(exe(jnp.zeros(4, jnp.float32))), 1.0)

    def test_custom_call_programs_stay_off_disk_on_cpu(self, tmp_path):
        # an f64 bucket routes vmap -> LAPACK custom calls; on the CPU rig
        # those serialize as process-local addresses, so the cache must
        # keep them memory-only (disk_skips) rather than persist a file
        # that would segfault the next process
        eng = SolveEngine(cfg=ServeConfig(
            buckets=(8,), rows_buckets=(32,), nrhs_buckets=(1,),
            max_batch=2, persist_dir=str(tmp_path),
        ))
        eng.warmup([("posv", (8, 8), (8, 1), "float64")])
        s = eng.cache_stats()
        assert s["disk"]["skips"] == 1
        assert not [f for f in os.listdir(tmp_path) if f.endswith(".exe")]

    def test_persistable_program_predicate(self):
        pure = _toy_build()()
        assert serve_cache.persistable_program(pure)
        lapacky = jax.jit(jnp.linalg.inv).lower(
            jax.ShapeDtypeStruct((4, 4), jnp.float64)).compile()
        if jax.default_backend() == "cpu":
            assert not serve_cache.persistable_program(lapacky)


# ---------------------------------------------------------------------------
# cold-start acceptance (ISSUE 7): warm dir -> zero fresh compiles
# ---------------------------------------------------------------------------


class TestColdStartAcceptance:
    def _work(self, requests=50):
        """50-request mixed smoke over all three ops and two n-buckets,
        every shape pallas-eligible f32 (persistable on the CPU rig)."""
        rng = np.random.default_rng(7)
        out = []
        for i in range(requests):
            op = ("posv", "inv", "lstsq")[(i // 2) % 3]
            n = (8, 16)[(i // 6) % 2]
            if op == "lstsq":
                A = rng.standard_normal((4 * n, n)).astype(np.float32)
                B = rng.standard_normal((4 * n, 1)).astype(np.float32)
            else:
                A = _spd(rng, n)
                B = (rng.standard_normal((n, 1)).astype(np.float32)
                     if op == "posv" else None)
            out.append((op, A, B))
        return out

    def test_second_engine_serves_with_zero_compiles(self, tmp_path):
        cfg = ServeConfig(
            buckets=(8, 16), rows_buckets=(32, 64), nrhs_buckets=(1,),
            max_batch=4, max_delay_s=10.0, small_n_impl="pallas",
            persist_dir=str(tmp_path),
        )
        work = self._work()
        specs = [(op, A.shape, B.shape if B is not None else None,
                  "float32") for op, A, B in work]

        cold = SolveEngine(cfg=cfg)
        assert cold.warmup(specs) > 0  # cold dir genuinely compiled
        ncold = cold.cache_stats()["compiles"]
        assert ncold == cold.cache_stats()["entries"]

        warm = SolveEngine(cfg=cfg)  # fresh process-equivalent: empty memory
        assert warm.warmup(specs) == 0
        tickets = [warm.submit(op, A, B) for op, A, B in work]
        warm.drain()
        assert all(t.result().ok for t in tickets)
        s = warm.cache_stats()
        assert s["compiles"] == 0  # THE cold-start gate
        assert s["hit_rate"] == 1.0 and s["misses"] == 0
        assert s["disk"]["hits"] == ncold and s["disk"]["errors"] == 0


# ---------------------------------------------------------------------------
# scheduler: continuous vs sync semantics
# ---------------------------------------------------------------------------


class TestScheduler:
    def test_unknown_scheduler_rejected(self):
        with pytest.raises(ValueError, match="scheduler"):
            SolveEngine(cfg=ServeConfig(scheduler="eventual"))

    def test_max_inflight_validated(self):
        with pytest.raises(ValueError, match="max_inflight"):
            SolveEngine(cfg=ServeConfig(max_inflight=0))

    def test_continuous_capacity_flush_dispatches_without_landing(self):
        rng = np.random.default_rng(0)
        eng = SolveEngine(cfg=_pcfg())
        A, B = _posv_args(rng)
        ts = [eng.submit("posv", A, B) for _ in range(2)]  # capacity flush
        assert all(t.done for t in ts)  # dispatched == fate sealed
        assert all(t.response is None for t in ts)  # ...but NOT landed
        assert eng.scheduler.inflight_depth == 1
        r = ts[0].result()  # lands the whole batch on demand
        assert r.ok and ts[1].response is not None
        assert r.queue_wait_s is not None and r.device_s is not None
        assert r.latency_s == pytest.approx(
            r.queue_wait_s + r.device_s, abs=1e-6)

    def test_sync_mode_lands_inside_flush(self):
        rng = np.random.default_rng(1)
        eng = SolveEngine(cfg=_pcfg(scheduler="sync"))
        A, B = _posv_args(rng)
        ts = [eng.submit("posv", A, B) for _ in range(2)]
        assert all(t.response is not None for t in ts)  # PR 4 behavior
        assert eng.scheduler.inflight_depth == 0

    def test_inflight_window_bounded(self):
        rng = np.random.default_rng(2)
        eng = SolveEngine(cfg=_pcfg(max_inflight=1))
        A, B = _posv_args(rng)
        batches = [[eng.submit("posv", A, B) for _ in range(2)]
                   for _ in range(3)]
        # 3 batches dispatched; the window held at most 1 unlanded, so the
        # two oldest were collected along the way
        assert eng.scheduler.inflight_depth <= 1
        assert all(t.response is not None for t in batches[0])
        eng.drain()
        assert all(t.response is not None for b in batches for t in b)

    def test_drain_lands_everything(self):
        rng = np.random.default_rng(3)
        eng = SolveEngine(cfg=_pcfg())
        A, B = _posv_args(rng)
        ts = [eng.submit("posv", A, B) for _ in range(3)]  # 1 flush + 1 queued
        assert eng.queue_depth() == 1
        flushed = eng.drain()
        assert flushed == 1 and eng.queue_depth() == 0
        assert all(t.response is not None for t in ts)
        assert eng.scheduler.inflight_depth == 0

    def test_pump_reaps_ready_batches(self):
        rng = np.random.default_rng(4)
        eng = SolveEngine(cfg=_pcfg())
        A, B = _posv_args(rng)
        ts = [eng.submit("posv", A, B) for _ in range(2)]
        time.sleep(0.01)  # CPU results are ready ~immediately
        eng.pump()  # no deadline flush due, but reap() lands the batch
        assert all(t.response is not None for t in ts)

    def test_sync_and_continuous_share_cache_entries(self, tmp_path):
        # scheduler mode is NOT in the config hash: both modes run
        # byte-identical programs, so a warm dir serves either
        rng = np.random.default_rng(5)
        A, B = _posv_args(rng)
        e1 = SolveEngine(cfg=_pcfg(tmp_path, scheduler="sync"))
        e1.solve("posv", A, B)
        e2 = SolveEngine(cfg=_pcfg(tmp_path, scheduler="continuous"))
        r = e2.solve("posv", A, B)
        assert r.ok and e2.cache_stats()["compiles"] == 0

    def test_continuous_matches_sync_results(self):
        rng = np.random.default_rng(6)
        work = [_posv_args(rng) for _ in range(5)]
        out = {}
        for mode in ("sync", "continuous"):
            eng = SolveEngine(cfg=_pcfg(scheduler=mode))
            ts = [eng.submit("posv", A, B) for A, B in work]
            eng.drain()
            out[mode] = [np.asarray(t.result().x) for t in ts]
        for xs, xc in zip(out["sync"], out["continuous"]):
            np.testing.assert_allclose(xs, xc, rtol=0, atol=0)  # same program

    def test_unflushed_ticket_still_raises(self):
        rng = np.random.default_rng(7)
        eng = SolveEngine(cfg=_pcfg())
        t = eng.submit("posv", *_posv_args(rng))  # capacity 2: still queued
        assert not t.done
        with pytest.raises(RuntimeError, match="not flushed"):
            t.result()


# ---------------------------------------------------------------------------
# the queue-wait / device split (stats + ledger + serve-report gates)
# ---------------------------------------------------------------------------


class TestLatencySplit:
    def test_snapshot_carries_split_when_fed(self):
        c = stats.Collector()
        c.record_request("posv", 0.010, ok=True,
                         queue_wait_s=0.004, device_s=0.006)
        snap = c.snapshot()
        assert snap["queue_wait_ms"]["p50"] == pytest.approx(4.0)
        assert snap["device_ms"]["p50"] == pytest.approx(6.0)
        assert ledger.validate_request_stats(snap) == []

    def test_snapshot_omits_split_when_never_dispatched(self):
        c = stats.Collector()
        c.record_request("posv", 0.010, ok=False, failed=True)  # ingest fault
        snap = c.snapshot()
        assert "queue_wait_ms" not in snap and "device_ms" not in snap
        assert ledger.validate_request_stats(snap) == []  # optional block

    def test_malformed_split_blocks_flag(self):
        c = stats.Collector()
        c.record_request("posv", 0.01, ok=True, queue_wait_s=0.004,
                         device_s=0.006)
        snap = c.snapshot()
        snap["queue_wait_ms"] = {"p50": 1.0}  # missing p95/p99
        probs = ledger.validate_request_stats(snap)
        assert any("queue_wait_ms.p95" in p for p in probs)
        snap["queue_wait_ms"] = "fast"
        assert any("queue_wait_ms must be an object" in p
                   for p in ledger.validate_request_stats(snap))
        snap2 = c.snapshot()
        snap2["device_ms"]["p99"] = None
        assert any("device_ms.p99" in p
                   for p in ledger.validate_request_stats(snap2))

    def test_engine_populates_split(self):
        rng = np.random.default_rng(8)
        eng = SolveEngine(cfg=_pcfg())
        [eng.submit("posv", *_posv_args(rng)) for _ in range(2)]
        eng.drain()
        snap = eng.stats.snapshot()
        assert snap["queue_wait_ms"]["p99"] >= 0.0
        assert snap["device_ms"]["p99"] > 0.0


def _emit_record(path, occupancy=None, split=True):
    c = stats.Collector()
    kw = dict(queue_wait_s=0.005, device_s=0.015) if split else {}
    c.record_request("posv", 0.020, ok=True, **kw)
    if occupancy is not None:
        c.note_batch(occupancy)
    c.emit(str(path), cache={"hits": 4, "misses": 0, "warmup_compiles": 1,
                             "entries": 1, "hit_rate": 1.0})


class TestServeReportGates:
    def test_min_occupancy_passes_and_fails(self, tmp_path, capsys):
        path = tmp_path / "s.jsonl"
        _emit_record(path, occupancy=0.75)
        assert obs_main.main(["serve-report", str(path),
                              "--min-occupancy", "0.5"]) == 0
        assert obs_main.main(["serve-report", str(path),
                              "--min-occupancy", "0.9"]) == 1
        assert "batch occupancy 0.75 < 0.9" in capsys.readouterr().err

    def test_max_queue_wait_passes_and_fails(self, tmp_path, capsys):
        path = tmp_path / "s.jsonl"
        _emit_record(path, occupancy=1.0)
        assert obs_main.main(["serve-report", str(path),
                              "--max-queue-wait-ms", "10"]) == 0
        assert obs_main.main(["serve-report", str(path),
                              "--max-queue-wait-ms", "1"]) == 1
        assert "queue-wait p99 5.0ms > 1.0ms" in capsys.readouterr().err

    def test_queue_wait_gate_fails_loudly_without_split(self, tmp_path,
                                                        capsys):
        path = tmp_path / "s.jsonl"
        _emit_record(path, occupancy=1.0, split=False)  # pre-split record
        assert obs_main.main(["serve-report", str(path),
                              "--max-queue-wait-ms", "1000"]) == 1
        assert "no record carries a queue_wait_ms" in capsys.readouterr().err

    def test_split_shows_in_report_line(self, tmp_path, capsys):
        path = tmp_path / "s.jsonl"
        _emit_record(path, occupancy=1.0)
        assert obs_main.main(["serve-report", str(path)]) == 0
        out = capsys.readouterr().out
        assert "queue_wait p99=5.0" in out and "device p99=15.0" in out

    def test_malformed_split_exits_2(self, tmp_path, capsys):
        path = tmp_path / "s.jsonl"
        _emit_record(path, occupancy=1.0)
        recs = ledger.read(str(path))
        recs[0]["request_stats"]["queue_wait_ms"] = {"p50": 1.0}
        os.remove(path)
        for r in recs:
            ledger.append(str(path), r)
        assert obs_main.main(["serve-report", str(path)]) == 2
        assert "queue_wait_ms.p95" in capsys.readouterr().err


# ---------------------------------------------------------------------------
# loadgen: the closed-loop A/B harness
# ---------------------------------------------------------------------------

LG_CFG = ServeConfig(
    buckets=(8, 16), rows_buckets=(32, 64), nrhs_buckets=(1,),
    max_batch=4, max_delay_s=0.002, small_n_impl="pallas",
)
LG_WL = loadgen.Workload(requests=24, concurrency=6, seed=0,
                         ops=("posv", "lstsq"), ns=(8, 16), nrhs=(1,))


class TestLoadgen:
    def test_workload_is_deterministic(self):
        a = loadgen.build_requests(LG_WL)
        b = loadgen.build_requests(LG_WL)
        assert [op for op, _, _ in a] == [op for op, _, _ in b]
        for (_, A1, _), (_, A2, _) in zip(a, b):
            np.testing.assert_array_equal(A1, A2)

    def test_warmup_specs_cover_the_grid(self):
        specs = loadgen.warmup_specs(LG_WL)
        assert ("posv", (8, 8), (8, 1), "float32") in specs
        assert ("lstsq", (64, 16), (64, 1), "float32") in specs
        assert len(specs) == len(LG_WL.ops) * len(LG_WL.ns) * len(LG_WL.nrhs)

    def test_closed_loop_completes_all_requests(self):
        eng = SolveEngine(cfg=LG_CFG)
        eng.warmup(loadgen.warmup_specs(LG_WL))
        res = loadgen.run_closed_loop(
            eng, loadgen.build_requests(LG_WL), LG_WL.concurrency)
        assert res["requests"] == LG_WL.requests
        assert res["failed"] == 0 and res["qps"] > 0

    def test_compare_emits_gated_records(self, tmp_path):
        path = tmp_path / "lg.jsonl"
        results = loadgen.compare(LG_CFG, LG_WL, ledger_path=str(path))
        for mode in ("sync", "continuous"):
            res = results[mode]
            assert res["requests"] == LG_WL.requests and res["failed"] == 0
            assert res["cache"]["misses"] == 0  # warmup covered the grid
            block = res["record"]["loadgen"]
            assert block["mode"] == mode and block["qps"] == res["qps"]
        assert results["continuous"]["record"]["loadgen"]["baseline_qps"] == \
            results["sync"]["qps"]
        # lenient sanity floor — the real A/B number lives in the ledger
        # records `make serve-bench` gates; CPU CI only pins "not absurdly
        # slower than the stop-and-go baseline"
        assert results["speedup"] is not None and results["speedup"] > 0.3
        # the records pass the serve-report gates serve-bench applies
        assert obs_main.main([
            "serve-report", str(path), "--min-hit-rate", "1.0",
            "--min-occupancy", "0.05", "--max-queue-wait-ms", "600000",
        ]) == 0


# ---------------------------------------------------------------------------
# phase tags + the inv identity-posv route
# ---------------------------------------------------------------------------


class TestPhaseTagsAndInvRoute:
    def test_stage_dispatch_tags_registered(self):
        assert "SV::stage" in tracing.PHASE_REGISTRY
        assert "SV::dispatch" in tracing.PHASE_REGISTRY

    def test_serve_sched_lint_target_builds(self):
        from capital_tpu.lint import targets as lint_targets

        tgts = lint_targets.flagship_targets(["serve_sched"])
        assert len(tgts) == 1 and "serve-sched" in tgts[0].name
        assert tgts[0].flops_audited is False

    def test_small_inv_routes_pallas_and_matches_numpy(self):
        rng = np.random.default_rng(9)
        eng = SolveEngine(cfg=_pcfg())
        bucket = None
        A = _spd(rng, 8)
        ts = [eng.submit("inv", A) for _ in range(2)]
        eng.drain()
        for t in ts:
            r = t.result()
            assert r.ok
            bucket = r.bucket
            np.testing.assert_allclose(
                np.asarray(r.x, dtype=np.float64), np.linalg.inv(A),
                rtol=0, atol=5e-4)
        assert bucket is not None
        # the split says these requests rode the small-N kernels
        assert eng.stats.latencies_small_s

    def test_f64_inv_still_vmap(self):
        cfg = ServeConfig(buckets=(8,), rows_buckets=(32,),
                          nrhs_buckets=(1,), max_batch=2)
        eng = SolveEngine(cfg=cfg)
        from capital_tpu.serve import batching

        b = batching.bucket_for("inv", (8, 8), None, "float64", cfg)
        assert b is not None and not eng._small_route(b)
