"""Observability layer tests: HLO collective scan, drift classifier, ledger.

The scan/classifier tests are pure logic against synthetic HLO text plus
REAL Recorder models (the cost helpers + emit fire without compiling), so
they exercise the drift branches on the 2x2x{1,2} grids even on rigs where
multi-device compilation is unavailable.  The end-to-end tests compile
single-device programs and run the audit CLI in-process.
"""

import json

import jax
import jax.numpy as jnp
import pytest

from capital_tpu.models import cholesky
from capital_tpu.models.cholesky import CholinvConfig
from capital_tpu.obs import __main__ as obs_main
from capital_tpu.obs import ledger, xla_audit
from capital_tpu.parallel.topology import Grid
from capital_tpu.utils import rand48, tracing


def _hlo_line(kind, idx=0, operand="f32[2,8]{1,0} %param", res="f32[8,8]{1,0}",
              phase=None, asyn=False):
    """One synthetic (post-optimization-style) HLO instruction line."""
    op = f"{kind}-start" if asyn else kind
    meta = f', metadata={{op_name="jit(f)/jit(main)/{phase}/mul"}}' if phase else ""
    return (
        f"  %{op}.{idx} = {res} {op}({operand}), channel_id={idx}, "
        f"replica_groups={{{{0,1,2,3}}}}{meta}"
    )


class TestScanCollectives:
    def test_counts_and_bytes(self):
        txt = "\n".join(
            [
                "HloModule jit_f",
                _hlo_line("all-gather", 1, phase="CI.trsm"),
                _hlo_line("all-gather", 2, phase="CI.trsm"),
                _hlo_line("all-reduce", 3, operand="f32[4,4]{1,0} %x"),
                _hlo_line("collective-permute", 4),
                "  %add.5 = f32[8,8]{1,0} add(%a, %b)",
            ]
        )
        ops = xla_audit.scan_collectives(txt)
        assert [o.kind for o in ops] == [
            "all-gather", "all-gather", "all-reduce", "collective-permute",
        ]
        # typed operands price the payload: f32[2,8] = 64 B, f32[4,4] = 64 B
        assert ops[0].operand_bytes == 64.0
        assert ops[2].operand_bytes == 64.0
        # phase from the named-scope chain in op_name metadata
        assert ops[0].phase == "CI::trsm" and ops[3].phase == "other"

    def test_async_start_counted_done_not(self):
        # TPU lowering splits collectives into -start/-done pairs; the
        # inventory must count the pair ONCE (via -start), or async rigs
        # would double every pinned snapshot
        txt = "\n".join(
            [
                _hlo_line("all-gather", 1, asyn=True),
                "  %all-gather-done.2 = f32[8,8]{1,0} all-gather-done(%all-gather-start.1)",
            ]
        )
        aud = xla_audit.audit_text(txt)
        assert aud.collective_counts["all-gather"] == 1

    def test_bare_ref_falls_back_to_result_shape(self):
        txt = _hlo_line("all-reduce", 7, operand="%partial.6")
        (op,) = xla_audit.scan_collectives(txt)
        assert op.operand_bytes == 8 * 8 * 4  # result f32[8,8]

    def test_audit_text_aggregates_by_phase(self):
        txt = "\n".join(
            [
                _hlo_line("all-gather", 1, phase="CI.trsm"),
                _hlo_line("all-reduce", 2, phase="CI.trsm"),
                _hlo_line("collective-permute", 3),
            ]
        )
        aud = xla_audit.audit_text(txt)
        assert aud.phase_collectives == {"CI::trsm": 2, "other": 1}
        assert aud.total_collectives() == 3
        d = aud.asdict()
        assert "ops" not in d and d["collective_counts"]["all-gather"] == 1


class TestDriftClassifier:
    def test_model_undercount_branch_c1(self, grid2x2x1):
        # real model: one distributed gemm booked under CI::trsm on the
        # 2x2x1 face; synthetic program emits past tol_ratio*m + slack
        g = grid2x2x1
        rec = tracing.Recorder()
        with rec:
            with tracing.scope("CI::trsm"):
                f, b, nc = tracing.gemm_cost(g, 64, 64, 64, jnp.float32)
                tracing.emit(f, b, nc)
        m = rec.stats["CI::trsm"].collectives
        assert m == 2  # the c=1 branch: one gather per mesh axis
        over = int(m * 4.0 + 8) + 1
        txt = "\n".join(
            _hlo_line("all-gather", i, phase="CI.trsm") for i in range(over)
        )
        rep = xla_audit.drift(xla_audit.audit_text(txt), rec)
        ph = {p.phase: p for p in rep.phases}
        assert ph["CI::trsm"].classification == xla_audit.UNDERCOUNT
        assert not rep.ok

    def test_compiled_extra_branch_c2(self, grid2x2x2):
        # real model on the 2x2x2 grid: a gram psum under CQR::gram; the
        # compiled text adds GSPMD resharding permutes OUTSIDE every scope
        # -> 'other' is compiled-extra (informational), gram stays within,
        # and the report as a whole is ok
        g = grid2x2x2
        rec = tracing.Recorder()
        with rec:
            with tracing.scope("CQR::gram"):
                cb, nc = tracing.allreduce_cost(g, 16, 16, jnp.float32)
                tracing.emit(2.0 * 256 * 16 * 16 / g.num_devices, cb, nc)
        assert rec.stats["CQR::gram"].collectives == 1
        txt = "\n".join(
            [_hlo_line("all-reduce", 1, phase="CQR.gram")]
            + [_hlo_line("collective-permute", 10 + i) for i in range(5)]
        )
        rep = xla_audit.drift(xla_audit.audit_text(txt), rec)
        ph = {p.phase: p for p in rep.phases}
        assert ph["CQR::gram"].classification == xla_audit.WITHIN
        assert ph["other"].classification == xla_audit.EXTRA
        assert ph["other"].model_collectives == 0
        assert ph["other"].compiled_collectives == 5
        assert rep.ok
        assert any("DRIFT" in l or "WITHIN" in l for l in rep.lines())

    def test_fewer_compiled_than_modeled_is_within(self, grid2x2x1):
        # XLA merging collectives costs nothing: c < m stays within
        rec = tracing.Recorder()
        with rec:
            with tracing.scope("CI::inv"):
                tracing.emit(1e6, 1024.0, collectives=6)
        txt = _hlo_line("all-gather", 1, phase="CI.inv")
        rep = xla_audit.drift(xla_audit.audit_text(txt), rec)
        (ph,) = [p for p in rep.phases if p.phase == "CI::inv"]
        assert ph.classification == xla_audit.WITHIN
        assert rep.ok

    def test_flops_tolerance_gate(self):
        rec = tracing.Recorder()
        with rec:
            with tracing.scope("CI::tmu"):
                tracing.emit(flops=1e9)
        aud = xla_audit.audit_text("")
        aud.flops = 3e9  # past the default 2x ratio
        rep = xla_audit.drift(aud, rec)
        assert not rep.flops_within and not rep.ok
        aud.flops = 1.5e9
        assert xla_audit.drift(aud, rec).ok


class TestEndToEndAudit:
    def test_single_device_cholinv(self):
        # n=128, not 64: below ~bc the base-case dense ops dominate and the
        # compiled/model flop ratio exceeds the 2x gate by construction
        # (docs/OBSERVABILITY.md tolerance policy) — that regime is what
        # --flops-tol exists for, not what this test pins
        g = Grid.square(c=1, devices=jax.devices()[:1])
        A = jnp.asarray(rand48.symmetric(128))
        cfg = CholinvConfig(base_case_dim=32, mode="xla")
        fn = lambda a: cholesky.factor(g, a, cfg)
        aud, rec, rep = xla_audit.audit_and_drift(fn, A)
        assert aud.total_collectives() == 0  # one device: no collectives
        assert aud.flops > 0  # cost_analysis populated
        assert aud.peak_hbm_bytes > 0  # memory_analysis populated
        assert rec.total().flops > 0
        assert rep.ok

    def test_trace_model_immune_to_trace_cache(self):
        # jax caches traces by function identity: without trace_model's
        # fresh-wrapper indirection, a second trace of the SAME function
        # object (prior trace_model, or audit()'s jit/lower) hits the
        # cache, skips the Python bodies, and returns an EMPTY Recorder —
        # the collective-audit tests then compare against model totals of
        # 0.  Pin: repeated captures agree and stay nonzero.
        g = Grid.square(c=1, devices=jax.devices()[:1])
        A = jnp.asarray(rand48.symmetric(128))
        cfg = CholinvConfig(base_case_dim=32, mode="xla")
        fn = lambda a: cholesky.factor(g, a, cfg)
        first = xla_audit.trace_model(fn, A).total()
        assert first.flops > 0
        xla_audit.audit(fn, A)  # compiles the same fn object
        again = xla_audit.trace_model(fn, A).total()
        assert again.flops == first.flops
        assert again.calls == first.calls

    def test_cli_audit_emits_ledger_record(self, tmp_path, capsys):
        led = tmp_path / "runs.jsonl"
        rc = obs_main.main(
            ["audit", "cholinv", "--n", "128", "--bc", "32", "--dtype",
             "float32", "--devices", "1", "--ledger", str(led), "--no-strict"]
        )
        assert rc == 0
        out = capsys.readouterr().out
        row = json.loads([l for l in out.splitlines() if l.startswith("{")][-1])
        assert row["record"] == "capital_tpu.ledger"
        assert row["kind"] == "audit:cholinv"
        assert row["manifest"]["schema_version"] == ledger.SCHEMA_VERSION
        assert row["model"]["totals"]["flops"] > 0
        assert row["audit"]["collective_counts"]["all-to-all"] == 0
        assert row["drift"]["ok"] is True
        (on_disk,) = ledger.read(str(led))
        assert on_disk["kind"] == "audit:cholinv"


# --------------------------------------------------------------------------
# ledger
# --------------------------------------------------------------------------


def _mk_record(value=1.0, ag=4, peak=1000.0, schema=None, device=None):
    man = ledger.manifest(dtype=jnp.float32)
    if schema is not None:
        man["schema_version"] = schema
    if device is not None:
        man["device"] = device
    return ledger.record(
        "bench:test",
        man,
        audit={
            "collective_counts": {"all-gather": ag, "all-reduce": 0},
            "peak_hbm_bytes": peak,
        },
        measured={"metric": "test_tflops", "value": value, "unit": "TFLOP/s",
                  "n": 64},
    )


class TestLedger:
    def test_append_read_round_trip(self, tmp_path):
        path = tmp_path / "sub" / "runs.jsonl"  # parent dir auto-created
        ledger.append(str(path), _mk_record(value=1.0))
        ledger.append(str(path), _mk_record(value=2.0))
        recs = ledger.read(str(path))
        assert [r["measured"]["value"] for r in recs] == [1.0, 2.0]
        assert recs[0]["manifest"]["jax_version"] == jax.__version__

    def test_manifest_jsonable_config(self):
        man = ledger.manifest(
            dtype=jnp.bfloat16, config=CholinvConfig(base_case_dim=128)
        )
        assert man["config"]["__class__"] == "CholinvConfig"
        assert man["config"]["base_case_dim"] == 128
        json.dumps(man)  # whole manifest must serialize

    def test_diff_clean(self):
        assert ledger.diff([_mk_record()], [_mk_record()]) == []

    def test_diff_flags_metric_drop(self):
        regs = ledger.diff([_mk_record(value=1.0)], [_mk_record(value=0.8)])
        assert [r.field for r in regs] == ["measured.value"]
        assert "REGRESSION" in regs[0].line()

    def test_diff_flags_collective_regression(self):
        regs = ledger.diff([_mk_record(ag=4)], [_mk_record(ag=6)])
        assert [r.field for r in regs] == ["collectives.all-gather"]

    def test_diff_flags_peak_hbm_regression(self):
        regs = ledger.diff([_mk_record(peak=1000.0)], [_mk_record(peak=1200.0)])
        assert [r.field for r in regs] == ["peak_hbm_bytes"]

    def test_diff_within_tolerance_passes(self):
        assert ledger.diff([_mk_record(value=1.0, peak=1000.0)],
                           [_mk_record(value=0.95, peak=1030.0)]) == []

    def test_schema_mismatch_refused(self):
        with pytest.raises(ledger.LedgerIncompatible):
            ledger.diff([_mk_record()], [_mk_record(schema=999)])

    def test_device_mismatch_refused(self):
        with pytest.raises(ledger.LedgerIncompatible):
            ledger.diff([_mk_record()], [_mk_record(device="mars-tpu")])

    def test_cli_diff_exit_codes(self, tmp_path, capsys):
        a, b, c, d = (tmp_path / n for n in ("a.jsonl", "b.jsonl", "c.jsonl",
                                             "d.jsonl"))
        ledger.append(str(a), _mk_record(value=1.0, ag=4))
        ledger.append(str(b), _mk_record(value=1.0, ag=4))
        assert obs_main.main(["diff", str(a), str(b)]) == 0
        # injected collective-count regression -> exit 1
        ledger.append(str(c), _mk_record(value=1.0, ag=7))
        assert obs_main.main(["diff", str(a), str(c)]) == 1
        assert "collectives.all-gather" in capsys.readouterr().out
        # schema mismatch -> exit 2
        ledger.append(str(d), _mk_record(schema=999))
        assert obs_main.main(["diff", str(a), str(d)]) == 2
