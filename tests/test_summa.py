"""M1 tests: SUMMA gemm/trmm/syrk vs numpy, both execution modes, all grids."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from capital_tpu.parallel import summa
from capital_tpu.parallel.summa import GemmArgs, SyrkArgs, TrmmArgs
from capital_tpu.parallel import topology as summa_topology
from capital_tpu.utils import rand48

MODES = ["xla", "explicit"]


def _put(grid, x):
    return jax.device_put(jnp.asarray(x), grid.face_sharding())


@pytest.fixture(params=["grid2x2x1", "grid2x2x2"])
def grid(request):
    return request.getfixturevalue(request.param)


class TestGemm:
    @pytest.mark.parametrize("mode", MODES)
    def test_plain(self, grid, mode):
        A = rand48.random(32, 48, key=1)
        B = rand48.random(48, 24, key=2)
        C = summa.gemm(grid, _put(grid, A), _put(grid, B), mode=mode)
        np.testing.assert_allclose(np.asarray(C), A @ B, rtol=1e-12)

    @pytest.mark.parametrize("mode", MODES)
    def test_alpha_beta_transposes(self, grid, mode):
        A = rand48.random(40, 32, key=3)
        B = rand48.random(24, 40, key=4)
        C0 = rand48.random(32, 24, key=5)
        args = GemmArgs(alpha=2.5, beta=-0.5, trans_a=True, trans_b=True)
        C = summa.gemm(grid, _put(grid, A), _put(grid, B), _put(grid, C0), args, mode=mode)
        np.testing.assert_allclose(
            np.asarray(C), 2.5 * (A.T @ B.T) - 0.5 * C0, rtol=1e-12
        )

    def test_jit_and_sharded_output(self, grid2x2x2):
        g = grid2x2x2
        A = _put(g, rand48.random(64, 64, key=1))
        B = _put(g, rand48.random(64, 64, key=2))
        f = jax.jit(lambda a, b: summa.gemm(g, a, b, mode="explicit"))
        C = f(A, B)
        assert C.sharding == g.face_sharding()
        np.testing.assert_allclose(
            np.asarray(C), np.asarray(A) @ np.asarray(B), rtol=1e-12
        )

    def test_explicit_requires_divisibility(self, grid2x2x2):
        A = jnp.asarray(rand48.random(7, 7, key=1))
        with pytest.raises(ValueError):
            summa.gemm(grid2x2x2, A, A, mode="explicit")


class TestTrmm:
    @pytest.mark.parametrize("mode", MODES)
    @pytest.mark.parametrize("side,uplo,trans_a,diag", [
        ("L", "U", False, "N"),
        ("L", "L", True, "N"),
        ("R", "U", True, "U"),
        ("R", "L", False, "U"),
    ])
    def test_variants(self, grid, mode, side, uplo, trans_a, diag):
        n, m = 32, 32
        A = rand48.random(n, n, key=6)
        B = rand48.random(n, m, key=7)
        T = np.triu(A) if uplo == "U" else np.tril(A)
        if diag == "U":
            np.fill_diagonal(T, 1.0)
        Top = T.T if trans_a else T
        want = 1.5 * (Top @ B if side == "L" else B @ Top)
        args = TrmmArgs(side=side, uplo=uplo, trans_a=trans_a, diag=diag, alpha=1.5)
        got = summa.trmm(grid, _put(grid, A), _put(grid, B), args, mode=mode)
        np.testing.assert_allclose(np.asarray(got), want, rtol=1e-12)


class TestSyrk:
    @pytest.mark.parametrize("mode", MODES)
    @pytest.mark.parametrize("trans", [False, True])
    def test_variants(self, grid, mode, trans):
        A = rand48.random(32, 32, key=8)
        C0 = rand48.symmetric(32)
        want = 2.0 * (A.T @ A if trans else A @ A.T) + 0.5 * C0
        args = SyrkArgs(trans=trans, alpha=2.0, beta=0.5)
        got = summa.syrk(grid, _put(grid, A), _put(grid, C0), args, mode=mode)
        np.testing.assert_allclose(np.asarray(got), want, rtol=1e-12)


class TestTranspose:
    def test_transpose(self, grid2x2x2):
        g = grid2x2x2
        A = rand48.random(32, 16, key=9)
        At = summa.transpose(g, _put(g, A))
        assert At.sharding == g.face_sharding()
        np.testing.assert_array_equal(np.asarray(At), A.T)


class TestViews:
    """summa.trmm/syrk buffer-view + in-place-out API: on multi-device /
    non-pallas paths these materialize windows and scatter the result
    (parallel/summa.py), so the semantics must match hand-done slicing
    regardless of path taken."""

    @pytest.mark.parametrize("mode", MODES)
    def test_trmm_views_and_out(self, grid, mode):
        buf = rand48.random(64, 64, key=11)
        out0 = rand48.random(64, 64, key=12)
        # A operand = upper-tri window at (0,0,32,32); B = window (0,32,32,32)
        want_blk = np.triu(buf[:32, :32]).T @ buf[:32, 32:]
        args = TrmmArgs(side="L", uplo="U", trans_a=True)
        got = summa.trmm(
            grid, _put(grid, buf), _put(grid, buf), args, mode=mode,
            a_view=(0, 0, 32, 32), b_view=(0, 32, 32, 32),
            out=_put(grid, out0), out_off=(32, 0),
        )
        want = out0.copy()
        want[32:, 0:32] = want_blk
        np.testing.assert_allclose(np.asarray(got), want, rtol=1e-12)

    @pytest.mark.parametrize("mode", MODES)
    def test_syrk_views(self, grid, mode):
        buf = rand48.random(64, 64, key=13)
        C = rand48.random(64, 64, key=14)
        W = buf[:32, 32:]
        want = -(W.T @ W) + 1.0 * C[32:, 32:]
        args = SyrkArgs(trans=True, alpha=-1.0, beta=1.0)
        got = summa.syrk(
            grid, _put(grid, buf), _put(grid, C), args, mode=mode,
            a_view=(0, 32, 32, 32), c_view=(32, 32, 32, 32),
        )
        np.testing.assert_allclose(np.asarray(got), want, rtol=1e-12)


class TestTopologyKnobs:
    """Reference topology ctor knobs: layout (rank->coordinate variants,
    topology.h:77-123) and num_chunks (chunked bcast pipeline,
    summa.hpp:196-215) — both must leave results bit-identical."""

    @pytest.mark.parametrize("layout", [0, 1, 2])
    def test_layouts_correct_and_cover_devices(self, layout):
        from capital_tpu.parallel.topology import Grid

        devs = jax.devices("cpu")[:8]
        g = Grid.square(c=2, devices=devs, layout=layout)
        placed = sorted(d.id for d in g.mesh.devices.ravel())
        assert placed == sorted(d.id for d in devs)
        A = rand48.random(32, 48, key=1)
        B = rand48.random(48, 24, key=2)
        C = summa.gemm(g, _put(g, A), _put(g, B), mode="explicit")
        np.testing.assert_allclose(np.asarray(C), A @ B, rtol=1e-12)

    def test_layouts_permute_device_order(self):
        from capital_tpu.parallel.topology import Grid

        devs = jax.devices("cpu")[:8]
        orders = {
            layout: tuple(
                d.id for d in Grid.square(c=2, devices=devs, layout=layout)
                .mesh.devices.ravel()
            )
            for layout in (0, 1, 2)
        }
        # layout 1 must differ from the natural order on a 2x2x2 grid;
        # layout 2's subcube equals the whole grid here, so it may coincide
        assert orders[1] != orders[0]

    @pytest.mark.parametrize("chunks", [2, 4])
    def test_chunked_explicit_pipeline(self, chunks):
        from capital_tpu.parallel.topology import Grid

        g = Grid.square(c=2, devices=jax.devices("cpu")[:8], num_chunks=chunks)
        A = rand48.random(32, 16 * chunks, key=6)
        B = rand48.random(16 * chunks, 24, key=7)
        C = summa.gemm(g, _put(g, A), _put(g, B), mode="explicit")
        np.testing.assert_allclose(np.asarray(C), A @ B, rtol=1e-12)

    def test_chunks_must_divide_panel(self):
        from capital_tpu.parallel.topology import Grid

        g = Grid.square(c=2, devices=jax.devices("cpu")[:8], num_chunks=3)
        A = _put(g, rand48.random(32, 32, key=8))
        with pytest.raises(ValueError, match="num_chunks"):
            summa.gemm(g, A, A, mode="explicit")


class TestExplicitEmission:
    """VERDICT r1 #5: the cost model must price what the explicit schedule
    actually emits.  Lower the jitted kernel and compare the collectives in
    the compiled HLO against tracing.gemm_cost."""

    def test_allgather_shapes_and_bytes_match_model_c1(self):
        import re

        from capital_tpu.parallel.topology import Grid
        from capital_tpu.utils import tracing

        q = 2
        g = Grid.rect(2, 2, 1, devices=jax.devices("cpu")[:4], num_chunks=q)
        M, K, N = 32, 64, 16
        A = _put(g, rand48.random(M, K, key=1))
        B = _put(g, rand48.random(K, N, key=2))
        txt = (
            jax.jit(lambda a, b: summa.gemm(g, a, b, mode="explicit"))
            .lower(A, B)
            .compile()
            .as_text()
        )
        ag_shapes = re.findall(r"= (\S+?)\{[^}]*\} all-gather", txt)
        # c=1: one amortized gather per operand per chunk; no psum bcasts,
        # no depth collect
        assert len(ag_shapes) == 2 * q, ag_shapes
        mb, nb, w = M // g.dx, N // g.dy, K // g.dy // q
        expect_a = f"f64[{mb},{g.dy * w}]"
        expect_b = f"f64[{g.dx * w},{nb}]"
        assert sorted(ag_shapes) == sorted([expect_a] * q + [expect_b] * q)
        assert len(re.findall(r"all-reduce\(", txt)) == 0

        # gathered bytes == the model's ring terms exactly
        item = 8
        gathered = q * (mb * g.dy * w + g.dx * w * nb) * item
        ring = (
            tracing._ring_bytes((M / g.dx) * K * item, g.dy)
            + tracing._ring_bytes(K * (N / g.dy) * item, g.dx)
        )
        assert ring == pytest.approx(gathered * (g.dy - 1) / g.dy)
        _, comm, ncoll = tracing.gemm_cost(g, M, N, K, jnp.float64)
        assert comm == pytest.approx(ring)
        assert ncoll == 2 * q

    def test_psum_bcast_path_matches_model_c2(self):
        # c>1 keeps the per-step masked-psum broadcasts so each depth layer
        # moves only its 1/c of the panels (the 2.5D comm saving) — the
        # schedule must emit NO all-gathers, and the model prices psum pairs
        # per step plus the chunked depth collect
        import re

        from capital_tpu.parallel.topology import Grid
        from capital_tpu.utils import tracing

        q = 2
        g = Grid.square(c=2, num_chunks=q)
        M, K, N = 32, 64, 16
        A = _put(g, rand48.random(M, K, key=1))
        B = _put(g, rand48.random(K, N, key=2))
        txt = (
            jax.jit(lambda a, b: summa.gemm(g, a, b, mode="explicit"))
            .lower(A, B)
            .compile()
            .as_text()
        )
        assert len(re.findall(r"all-gather", txt)) == 0
        assert len(re.findall(r"all-reduce\(", txt)) >= 1  # XLA may merge

        item = 8
        d, steps = g.dx, g.dx // g.c
        a_pan = (M / d) * (K / d) * item
        b_pan = (K / d) * (N / d) * item
        c_blk = (M / d) * (N / d) * item
        _, comm, ncoll = tracing.gemm_cost(g, M, N, K, jnp.float64)
        assert comm == pytest.approx(
            steps
            * (
                tracing._allreduce_bytes(a_pan, d)
                + tracing._allreduce_bytes(b_pan, d)
            )
            + tracing._allreduce_bytes(c_blk, g.c)
        )
        assert ncoll == steps * 2 * q + q

    def test_trmm_dead_segments_guarded(self, grid2x2x1):
        # triangular-operand explicit schedule must emit per-segment
        # conditionals (the dead-block skipping), and stay correct — value
        # checks live in TestTrmm::test_variants
        import re

        g = grid2x2x1
        A = _put(g, np.triu(rand48.random(32, 32, key=1)))
        B = _put(g, rand48.random(32, 32, key=2))
        txt = (
            jax.jit(
                lambda a, b: summa.trmm(
                    g, a, b, TrmmArgs(side="L", uplo="U"), mode="explicit"
                )
            )
            .lower(A, B)
            .compile()
            .as_text()
        )
        assert "conditional" in txt


def test_chunked_bf16_accumulates_f32():
    # ADVICE r1: chunking must not multiply low-precision partial-sum
    # roundoff — local accumulation is f32 for sub-f32 inputs, so a heavily
    # chunked schedule matches the unchunked one to bf16 resolution
    from capital_tpu.parallel.topology import Grid

    devs = jax.devices("cpu")[:4]
    K = 256
    A64 = np.asarray(rand48.random(32, K, key=31))
    B64 = np.asarray(rand48.random(K, 32, key=32))
    ref = A64 @ B64

    def err(chunks):
        g = Grid.rect(2, 2, 1, devices=devs, num_chunks=chunks)
        C = summa.gemm(
            g,
            _put(g, jnp.asarray(A64, jnp.bfloat16)),
            _put(g, jnp.asarray(B64, jnp.bfloat16)),
            mode="explicit",
        )
        return float(np.abs(np.asarray(C, np.float64) - ref).max())

    e1, e8 = err(1), err(8)
    # identical f32 accumulators, one output rounding each: the chunked
    # error may differ only by reassociation of the f32 partials
    assert e8 <= e1 * 1.05 + 1e-6, (e1, e8)


@pytest.mark.parametrize("chunks", [2, 4])
def test_chunked_explicit_triangular(chunks):
    # the per-(segment, chunk) liveness math under a chunked schedule, for
    # both a triangular operand (trmm) and a triangular output (syrk), on
    # the full 3D grid — the interplay the plain chunked-gemm test misses
    from capital_tpu.parallel.topology import Grid

    g = Grid.square(c=2, devices=jax.devices("cpu")[:8], num_chunks=chunks)
    n = 16 * chunks
    A = rand48.random(n, n, key=41)
    B = rand48.random(n, 24, key=42)
    got = summa.trmm(
        g, _put(g, A), _put(g, B), TrmmArgs(side="L", uplo="U", trans_a=True),
        mode="explicit",
    )
    np.testing.assert_allclose(np.asarray(got), np.triu(A).T @ B, rtol=1e-12)

    C0 = rand48.symmetric(n)
    got2 = summa.syrk(
        g, _put(g, A), _put(g, C0), SyrkArgs(trans=True, alpha=-1.0, beta=1.0),
        mode="explicit",
    )
    np.testing.assert_allclose(np.asarray(got2), -(A.T @ A) + C0, rtol=1e-12)


class TestCollectiveConcurrency:
    """Grid(collective_concurrency='solo'): the runtime re-expression of the
    reference's COLLECTIVE_CONCURRENCY_SOLO compile flag (summa.hpp:179-192,
    230-235) — every explicit-SUMMA collective chained behind the previous
    one.  Identical results; the serialization barrier must be in the HLO."""

    def _grids(self, base):
        devs = list(base.mesh.devices.ravel())
        free = summa_topology.Grid.rect(2, 2, 2, devices=devs)
        solo = summa_topology.Grid.rect(
            2, 2, 2, devices=devs, collective_concurrency="solo"
        )
        return free, solo

    def test_solo_matches_free(self, grid2x2x2):
        free, solo = self._grids(grid2x2x2)
        A = jax.device_put(jnp.asarray(rand48.random(64, 64, key=51)),
                           free.face_sharding())
        B = jax.device_put(jnp.asarray(rand48.random(64, 64, key=52)),
                           free.face_sharding())
        want = jax.jit(lambda a, b: summa.gemm(free, a, b, mode="explicit"))(A, B)
        got = jax.jit(lambda a, b: summa.gemm(solo, a, b, mode="explicit"))(A, B)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-12)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(A) @ np.asarray(B), rtol=1e-11
        )

    def test_solo_cholinv_end_to_end(self, grid2x2x2):
        # the knob must survive the full recursive algorithm (many SUMMA
        # invocations, each chaining its own collectives)
        from capital_tpu.models import cholesky
        from capital_tpu.utils import rand48 as r48, residual

        _, solo = self._grids(grid2x2x2)
        A = jax.device_put(jnp.asarray(r48.symmetric(128)), solo.face_sharding())
        R, Rinv = jax.jit(
            lambda a: cholesky.factor(
                solo, a, cholesky.CholinvConfig(base_case_dim=32, mode="explicit")
            )
        )(A)
        assert float(residual.cholesky_residual(A, R)) < 1e-13
        assert float(residual.cholesky_inverse_residual(R, Rinv)) < 1e-12

    def test_solo_emits_barriers(self, grid2x2x2):
        free, solo = self._grids(grid2x2x2)
        A = jax.device_put(jnp.asarray(rand48.random(64, 64, key=53)),
                           free.face_sharding())
        txt_solo = jax.jit(
            lambda a: summa.gemm(solo, a, a, mode="explicit")
        ).lower(A).as_text()
        txt_free = jax.jit(
            lambda a: summa.gemm(free, a, a, mode="explicit")
        ).lower(A).as_text()
        assert "opt-barrier" in txt_solo or "optimization_barrier" in txt_solo
        assert "opt-barrier" not in txt_free and (
            "optimization_barrier" not in txt_free
        )


class TestTileCyclicBalance:
    """balance='tile_cyclic' trmm (VERDICT r2 missing #1 — the reference's
    element-cyclic load balancer, rebuilt at MXU-tile granularity): equal
    per-device executed work, identical results."""

    def test_matches_block_and_xla(self, grid2x2x1):
        g = grid2x2x1
        n, m = 64, 32
        A = jax.device_put(jnp.asarray(rand48.random(n, n, key=31)), g.face_sharding())
        B = jax.device_put(jnp.asarray(rand48.random(n, m, key=32)), g.face_sharding())
        want = np.triu(np.asarray(A)) @ np.asarray(B)
        for uplo, ref in (("U", want), ("L", np.tril(np.asarray(A)) @ np.asarray(B))):
            args = TrmmArgs(side="L", uplo=uplo)
            blocked = jax.jit(
                lambda a, b, ar=args: summa.trmm(g, a, b, ar, mode="explicit")
            )(A, B)
            cyc = jax.jit(
                lambda a, b, ar=args: summa.trmm(
                    g, a, b, ar, mode="explicit", balance="tile_cyclic"
                )
            )(A, B)
            np.testing.assert_allclose(np.asarray(cyc), ref, atol=1e-12)
            np.testing.assert_allclose(
                np.asarray(cyc), np.asarray(blocked), atol=1e-12
            )

    def test_alpha_and_out(self, grid2x2x1):
        g = grid2x2x1
        A = jax.device_put(jnp.asarray(rand48.random(64, 64, key=33)), g.face_sharding())
        B = jax.device_put(jnp.asarray(rand48.random(64, 8, key=34)), g.face_sharding())
        out = jnp.zeros((128, 16))
        res = summa.trmm(
            g, A, B, TrmmArgs(side="L", uplo="U", alpha=-2.0),
            mode="explicit", balance="tile_cyclic",
            out=out, out_off=(64, 8),
        )
        want = -2.0 * np.triu(np.asarray(A)) @ np.asarray(B)
        np.testing.assert_allclose(np.asarray(res)[64:, 8:], want, atol=1e-12)
        np.testing.assert_array_equal(np.asarray(res)[:64, :], 0.0)

    def test_balance_in_cost_model(self):
        """The whole point: max-per-process == volumetric under the cyclic
        schedule, vs max == 1.0 (full dense on the critical device) under
        blocks; work is conserved."""
        import types

        for d in (2, 4):
            g = types.SimpleNamespace(
                dx=d, dy=d, c=1, num_chunks=0, num_devices=d * d
            )
            n = 64
            T = n // d // 4
            bm, bx = summa.tri_fractions(g, n, n, n, a_uplo="U")
            cm, cx = summa.tri_fractions(g, n, n, n, a_uplo="U", cyclic_rows=T)
            assert bx == 1.0
            assert cx < bx  # the critical path actually drops
            assert cx - cm <= 1.0 / (4 * d)  # max ≈ mean at tile granularity
            # volumetric work is conserved up to tile-boundary rounding
            assert cm == pytest.approx(bm, abs=1.0 / (2 * d))

    def test_auto_tile_is_mxu_aligned(self):
        """Auto-picked tiles must be 128 multiples once the local dim can
        carry them (round-3 advisor: local rows 384 -> tile 96 produced
        ragged sub-MXU row slices); sub-128 locals keep the small-shape
        heuristic (alignment moot), and explicit overrides are honored."""
        import types

        g = types.SimpleNamespace(dx=2, dy=2, c=1, num_chunks=0, num_devices=4)
        # dim 49152 / d 2: base 6144 is already a 128 multiple
        assert summa._pick_cyclic_tile(g, 49152, 0) == 6144
        # dim 768 / d 2: base 96 -> NOT eligible raw; falls to single-128
        assert summa._pick_cyclic_tile(g, 768, 0) == 128
        # dim 2560 / d 2: base 320 -> rounds down to 256
        assert summa._pick_cyclic_tile(g, 2560, 0) == 256
        # dim 4608 / d 2: 512 fails divisibility -> next 128-multiple 384
        assert summa._pick_cyclic_tile(g, 4608, 0) == 384
        # dim 2304 / d 2: 256 fails divisibility -> 128
        assert summa._pick_cyclic_tile(g, 2304, 0) == 128
        # dim 256 / d 2: tile 128 would mean nt == d (identity perm,
        # phantom shuffle cost) -> ineligible
        assert summa._pick_cyclic_tile(g, 256, 0) == 0
        # sub-MXU local dim (tests): 64/2 = 32 -> heuristic tile 8
        assert summa._pick_cyclic_tile(g, 64, 0) == 8
        # explicit override passes through eligibility unchanged
        assert summa._pick_cyclic_tile(g, 64, 16) == 16

    def test_syrk_tile_cyclic_matches_block(self, grid2x2x1):
        g = grid2x2x1
        A = jax.device_put(
            jnp.asarray(rand48.random(64, 64, key=41)), g.face_sharding()
        )
        for trans in (True, False):
            for uplo in ("U", "L"):
                args = SyrkArgs(trans=trans, uplo=uplo, alpha=0.5)
                blocked = jax.jit(
                    lambda a, ar=args: summa.syrk(g, a, args=ar, mode="explicit")
                )(A)
                cyc = jax.jit(
                    lambda a, ar=args: summa.syrk(
                        g, a, args=ar, mode="explicit", balance="tile_cyclic"
                    )
                )(A)
                An = np.asarray(A)
                ref = 0.5 * (An.T @ An if trans else An @ An.T)
                np.testing.assert_allclose(np.asarray(cyc), ref, atol=1e-12)
                np.testing.assert_allclose(
                    np.asarray(cyc), np.asarray(blocked), atol=1e-12
                )

    def test_syrk_balance_in_cost_model(self):
        import types

        for d in (2, 4):
            g = types.SimpleNamespace(
                dx=d, dy=d, c=1, num_chunks=0, num_devices=d * d
            )
            n = 64
            T = n // d // 4
            bm, bx = summa.tri_fractions(g, n, n, n, out_uplo="U")
            cm, cx = summa.tri_fractions(g, n, n, n, out_uplo="U", cyclic_out=T)
            # block layout: some device's C block is fully live (max=1.0),
            # another's fully dead; cyclic: every device ~half the pairs
            assert bx == 1.0
            assert cx < 0.7
            assert cx - cm <= 2.0 / (4 * d)
            assert cm == pytest.approx(bm, abs=1.0 / (2 * d))

    def test_unsupported_combinations_fall_back(self, grid2x2x2):
        # c=2 grid: tile_cyclic is c==1-only — must still produce correct
        # results through the block fallback (with a tracing note)
        from capital_tpu.utils import tracing

        g = grid2x2x2
        A = jax.device_put(jnp.asarray(rand48.random(64, 64, key=35)), g.face_sharding())
        B = jax.device_put(jnp.asarray(rand48.random(64, 16, key=36)), g.face_sharding())
        with tracing.Recorder() as rec:
            res = jax.jit(
                lambda a, b: summa.trmm(
                    g, a, b, TrmmArgs(side="L", uplo="U"),
                    mode="explicit", balance="tile_cyclic",
                )
            )(A, B)
        np.testing.assert_allclose(
            np.asarray(res), np.triu(np.asarray(A)) @ np.asarray(B), atol=1e-12
        )
        assert rec.stats["trmm::tile_cyclic_fallback"].calls >= 1


class TestShardKernelsD1:
    """d=1 grids: the explicit schedule rides the copy-free aliasing
    kernels directly (trmm/syrk's single-device route — no take_triangle
    copy, no window materialization, no dus round-trip; interpret kernels
    on this CPU rig).  Must agree with the xla spelling."""

    @pytest.fixture
    def grid1(self):
        from capital_tpu.parallel.topology import Grid

        return Grid.square(c=1, devices=jax.devices("cpu")[:1])

    def test_trmm_sides_match_xla(self, grid1):
        n = 256  # 128-aligned: the per-shard kernel route engages
        T = np.tril(rand48.random(n, n, key=11)) + 4 * np.eye(n)
        B = rand48.random(n, n, key=12)
        for side in ("L", "R"):
            want = np.asarray(
                summa.trmm(
                    grid1, _put(grid1, T), _put(grid1, B),
                    TrmmArgs(side=side, uplo="L"), mode="xla",
                )
            )
            got = np.asarray(
                summa.trmm(
                    grid1, _put(grid1, T), _put(grid1, B),
                    TrmmArgs(side=side, uplo="L"), mode="explicit",
                )
            )
            np.testing.assert_allclose(got, want, rtol=1e-10, atol=1e-10)

    def test_syrk_matches_xla(self, grid1):
        n = 256
        A = rand48.random(n, n, key=13)
        want = np.asarray(
            summa.syrk(grid1, _put(grid1, A), args=SyrkArgs(trans=True), mode="xla")
        )
        got = np.asarray(
            summa.syrk(
                grid1, _put(grid1, A), args=SyrkArgs(trans=True), mode="explicit"
            )
        )
        # d==1 explicit adopts the pallas triangle-only contract: the
        # args.uplo ('U') triangle is valid and the dead half zeroed
        np.testing.assert_allclose(got, np.triu(want), rtol=1e-10, atol=1e-10)

    def test_route_taken_and_misaligned_still_copy_free(self, grid1):
        # the route is asserted via the tracing note, not just numerics
        # (a broken gate with tri_matmul's padding would still produce
        # correct values)
        from capital_tpu.utils import tracing

        T = np.tril(rand48.random(256, 256, key=14)) + 4 * np.eye(256)
        B = rand48.random(256, 256, key=15)
        with tracing.Recorder() as rec:
            summa.trmm(
                grid1, _put(grid1, T), _put(grid1, B),
                TrmmArgs(side="L", uplo="L"), mode="explicit",
            )
        assert "explicit::copy_free" in rec.stats
        # the copy-free route materializes nothing the model would price
        assert rec.total().copy_bytes == 0.0

        # 192 is not a 128 multiple: the aliasing kernel falls back to
        # materializing windows INTERNALLY, but the route (and its
        # avoidance of take_triangle + dus round-trips) still engages
        n = 192
        T = np.tril(rand48.random(n, n, key=16)) + 4 * np.eye(n)
        B = rand48.random(n, n, key=17)
        with tracing.Recorder() as rec:
            got = np.asarray(
                summa.trmm(
                    grid1, _put(grid1, T), _put(grid1, B),
                    TrmmArgs(side="L", uplo="L"), mode="explicit",
                )
            )
        assert "explicit::copy_free" in rec.stats
        np.testing.assert_allclose(got, np.asarray(T @ B), rtol=1e-10, atol=1e-10)


class TestShardSchedD2:
    """Round 5: d > 1 grids route explicit trmm through the RUNTIME-
    scheduled per-shard kernels — each device selects its own live-tile
    schedule (stacked scalar-prefetch arrays indexed by axis_index) and
    runs pallas_tpu.sched_matmul on the gathered slabs.  c == 1,
    unchunked, 128-tileable shapes only."""

    @pytest.fixture
    def grid4(self):
        from capital_tpu.parallel.topology import Grid

        return Grid.square(c=1, devices=jax.devices("cpu")[:4])

    @pytest.mark.parametrize("side,uplo", [
        ("L", "L"), ("L", "U"), ("R", "L"), ("R", "U"),
    ])
    def test_all_combos_match_dense(self, grid4, side, uplo):
        from capital_tpu.utils import tracing

        n = 512
        T0 = np.tril(rand48.random(n, n, key=21)) + 4 * np.eye(n)
        T = T0 if uplo == "L" else T0.T
        B = rand48.random(n, n, key=22)
        with tracing.Recorder() as rec:
            got = np.asarray(
                summa.trmm(
                    grid4, _put(grid4, T), _put(grid4, B),
                    TrmmArgs(side=side, uplo=uplo), mode="explicit",
                )
            )
        assert "explicit::shard_sched" in rec.stats
        Topm = np.tril(T) if uplo == "L" else np.triu(T)
        want = Topm @ B if side == "L" else B @ Topm
        np.testing.assert_allclose(got, want, rtol=1e-10, atol=1e-10)

    def test_sched_fraction_prices_the_skipping(self, grid4):
        # the emitted executed view is the PADDED schedule's fraction — the
        # fullest slab's live share.  At n=512, d=2, 128-tiles: the bottom
        # slab runs 7 of its 8 (tile, k) pairs -> 0.875, strictly below
        # the K-segment spelling's critical path (1.0: the fullest block
        # row executes every segment) and above the volumetric 0.5
        from capital_tpu.parallel.summa import _shard_sched_gate

        sched = _shard_sched_gate(grid4, 512, 512, 512, "L", None, None)
        assert sched is not None
        frac = sched[1]
        assert abs(frac - 0.875) < 1e-9
        assert 0.5 <= frac < 1.0

    def test_untileable_falls_back(self, grid4):
        from capital_tpu.utils import tracing

        n = 192  # 96-per-shard: not 128-tileable
        T = np.tril(rand48.random(n, n, key=23)) + 4 * np.eye(n)
        B = rand48.random(n, n, key=24)
        with tracing.Recorder() as rec:
            got = np.asarray(
                summa.trmm(
                    grid4, _put(grid4, T), _put(grid4, B),
                    TrmmArgs(side="L", uplo="L"), mode="explicit",
                )
            )
        assert "explicit::shard_sched" not in rec.stats
        np.testing.assert_allclose(got, np.asarray(np.tril(T) @ B), rtol=1e-10, atol=1e-10)

    # ------------------------------------------------------------------
    # d=4 EXECUTED tile-cyclic schedules.  The d=4 max-per-process drop
    # (block 1.00 -> cyclic ~0.63) was previously asserted only through
    # the tri_fractions closed form; these run the real 4x4 schedule.
    # The parent process is pinned to 8 virtual devices (conftest), so a
    # 4x4 c=1 face needs a subprocess with its own XLA_FLAGS.
    # ------------------------------------------------------------------

    _D4_SCRIPT = r"""
import os, sys
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=16"
os.environ["JAX_PLATFORMS"] = "cpu"
import jax
jax.config.update("jax_enable_x64", True)
import jax.numpy as jnp
import numpy as np
from capital_tpu.parallel import summa
from capital_tpu.parallel.summa import SyrkArgs, TrmmArgs
from capital_tpu.parallel.topology import Grid
from capital_tpu.utils import rand48, tracing

op = sys.argv[1]
g = Grid.square(c=1, devices=jax.devices("cpu")[:16])
assert g.dx == 4 and g.dy == 4 and g.num_devices == 16
n, t = 128, 8
A = jax.device_put(jnp.asarray(rand48.random(n, n, key=61)), g.face_sharding())
with tracing.Recorder() as rec:
    if op == "trmm":
        B = jax.device_put(
            jnp.asarray(rand48.random(n, n, key=62)), g.face_sharding()
        )
        got = np.asarray(summa.trmm(
            g, A, B, TrmmArgs(side="L", uplo="L"),
            mode="explicit", balance="tile_cyclic", cyclic_tile=t,
        ))
        want = np.tril(np.asarray(A)) @ np.asarray(B)
        fallback = "trmm::tile_cyclic_fallback"
    else:
        got = np.asarray(summa.syrk(
            g, A, args=SyrkArgs(trans=True, uplo="U"),
            mode="explicit", balance="tile_cyclic", cyclic_tile=t,
        ))
        An = np.asarray(A)
        want = An.T @ An
        fallback = "syrk::tile_cyclic_fallback"
np.testing.assert_allclose(got, want, rtol=1e-10, atol=1e-10)
assert fallback not in rec.stats, sorted(rec.stats)
tot = rec.total()
assert tot.flops > 0
ratio = tot.flops_max / tot.flops
# the executed critical path must actually drop toward the volumetric
# mean (block layout pins this at 1.0 for d=4)
assert ratio < 0.75, ratio
print("D4_OK", ratio)
"""

    @pytest.mark.parametrize("op", ["trmm", "syrk"])
    def test_d4_tile_cyclic_executed(self, op, tmp_path):
        import os
        import subprocess
        import sys

        env = dict(os.environ)
        env.pop("XLA_FLAGS", None)  # the script pins its own 16 devices
        proc = subprocess.run(
            [sys.executable, "-c", self._D4_SCRIPT, op],
            capture_output=True, text=True, timeout=600, env=env,
        )
        assert proc.returncode == 0, proc.stdout + "\n" + proc.stderr
        assert "D4_OK" in proc.stdout, proc.stdout


class TestPersistentLayout:
    """balance='tile_cyclic_persistent': buffers live in the symmetric
    tile-cyclic layout V = X[perm][:, perm] for the whole matrix lifetime;
    trmm/syrk read aligned windows with chunk-local reshapes
    (cyclic_window), schedule liveness at original tile indices, and write
    band-sized updates back (cyclic_window_update) — zero per-call row
    shuffles and no whole-buffer dus round-trips."""

    @staticmethod
    def _layout(X, d, t):
        perm, inv = summa.tile_cyclic_perm(X.shape[0], d, t)
        return X[perm][:, perm], perm, inv

    def test_cyclic_window_roundtrip_and_locality(self):
        # windows of a persistent buffer come out in WINDOW-LOCAL cyclic
        # layout whose perm depends only on (extent, d, tile) — never the
        # offset — so aligned same-size windows interoperate
        d, t, n = 2, 8, 96
        X = rand48.random(n, n, key=71)
        V, perm, inv = self._layout(X, d, t)
        V = jnp.asarray(V)
        for view in [(0, 0, 32, 32), (32, 16, 64, 48), (64, 0, 32, 96)]:
            r0, c0, rows, cols = view
            W = np.asarray(summa.cyclic_window(V, view, d, t))
            rp, _ = summa.tile_cyclic_perm(rows, d, t)
            cp, _ = summa.tile_cyclic_perm(cols, d, t)
            want = X[r0:r0 + rows, c0:c0 + cols][rp][:, cp]
            np.testing.assert_array_equal(W, want)
            # write-back is the exact inverse
            back = summa.cyclic_window_update(V, jnp.asarray(W), view, d, t)
            np.testing.assert_array_equal(np.asarray(back), np.asarray(V))
        # misaligned views violate the storage contract -> raise
        with pytest.raises(ValueError):
            summa.cyclic_window(V, (8, 0, 32, 32), d, t)

    def test_take_triangle_cyclic_masks_original_indices(self):
        from capital_tpu.ops import masking

        d, t, n = 2, 8, 64
        X = rand48.random(n, n, key=72)
        V, perm, inv = self._layout(X, d, t)
        for uplo, ref in (("U", np.triu), ("L", np.tril)):
            got = np.asarray(
                masking.take_triangle_cyclic(jnp.asarray(V), uplo, d, t)
            )
            np.testing.assert_array_equal(got, ref(X)[perm][:, perm])
        strict = np.asarray(
            masking.take_triangle_cyclic(jnp.asarray(V), "U", d, t, strict=True)
        )
        np.testing.assert_array_equal(strict, np.triu(X, 1)[perm][:, perm])

    def test_trmm_persistent_matches_dense(self, grid2x2x1):
        from capital_tpu.utils import tracing

        g, d, t, n = grid2x2x1, 2, 8, 64
        T0 = np.tril(rand48.random(n, n, key=73)) + 4 * np.eye(n)
        B0 = rand48.random(n, n, key=74)
        Tp, perm, inv = self._layout(T0, d, t)
        Bp = B0[perm][:, perm]
        for side, uplo, ref in (
            ("L", "L", np.tril(T0) @ B0),
            ("R", "U", B0 @ np.triu(T0.T)),
        ):
            Tin = Tp if uplo == "L" else Tp.T
            with tracing.Recorder() as rec:
                res = summa.trmm(
                    g, _put(g, Tin), _put(g, Bp),
                    TrmmArgs(side=side, uplo=uplo),
                    mode="explicit", balance="tile_cyclic_persistent",
                    cyclic_tile=t,
                )
            assert "trmm::persistent_cyclic" in rec.stats, sorted(rec.stats)
            got = np.asarray(res)[inv][:, inv]
            np.testing.assert_allclose(got, ref, atol=1e-12)

    def test_trmm_persistent_windowed_out(self, grid2x2x1):
        # window reads + band-sized write-back into a larger persistent
        # buffer: the round-trip the layout exists to avoid
        g, d, t = grid2x2x1, 2, 8
        p, n = 128, 64
        X = rand48.random(p, p, key=75)
        O0 = rand48.random(p, p, key=76)
        Xp, perm, inv = self._layout(X, d, t)
        Op = O0[perm][:, perm]
        res = summa.trmm(
            g, _put(g, Xp), _put(g, Xp),
            TrmmArgs(side="L", uplo="L", alpha=-1.0),
            mode="explicit", balance="tile_cyclic_persistent", cyclic_tile=t,
            a_view=(0, 0, n, n), b_view=(0, 64, n, n),
            out=_put(g, Op), out_off=(64, 0),
        )
        got = np.asarray(res)[inv][:, inv]
        want = O0.copy()
        want[64:, :64] = -np.tril(X[:n, :n]) @ X[:n, 64:]
        np.testing.assert_allclose(got, want, atol=1e-12)

    def test_syrk_persistent_in_place(self, grid2x2x1):
        from capital_tpu.utils import tracing

        g, d, t = grid2x2x1, 2, 8
        p, n = 128, 64
        X = rand48.random(p, p, key=77)
        C0 = rand48.random(p, p, key=78)
        C0 = C0 + C0.T
        Xp, perm, inv = self._layout(X, d, t)
        Cp = C0[perm][:, perm]
        with tracing.Recorder() as rec:
            res = summa.syrk(
                g, _put(g, Xp), _put(g, Cp),
                SyrkArgs(trans=True, uplo="U", alpha=-1.0, beta=1.0),
                mode="explicit", balance="tile_cyclic_persistent",
                cyclic_tile=t,
                a_view=(0, 0, n, n), c_view=(64, 64, n, n), in_place=True,
            )
        assert "syrk::persistent_cyclic" in rec.stats, sorted(rec.stats)
        assert rec.total().copy_bytes > 0  # residual motion stays priced
        got = np.asarray(res)[inv][:, inv]
        A = X[:n, :n]
        S = C0[64:, 64:] - A.T @ A
        want = C0.copy()
        want[64:, 64:] = S  # symmetrized full update, window-local
        np.testing.assert_allclose(got, want, atol=1e-12)

    def test_persistent_contract_raises(self, grid2x2x1, grid2x2x2):
        # persistent is a STORAGE contract, not a schedule preference — a
        # silent fallback would read block-ordered data as cyclic, so
        # ineligible topologies/args raise instead of noting-and-falling-back
        A = _put(grid2x2x1, rand48.random(64, 64, key=79))
        with pytest.raises(ValueError):
            summa.trmm(
                grid2x2x1, A, A, TrmmArgs(side="L", uplo="L"),
                mode="explicit", balance="tile_cyclic_persistent",
            )  # no cyclic_tile
        with pytest.raises(ValueError):
            summa.trmm(
                grid2x2x1, A, A, TrmmArgs(side="L", uplo="L", diag="U"),
                mode="explicit", balance="tile_cyclic_persistent",
                cyclic_tile=8,
            )  # unit diagonal unsupported
        B = _put(grid2x2x2, rand48.random(64, 64, key=80))
        with pytest.raises(ValueError):
            summa.syrk(
                grid2x2x2, B, args=SyrkArgs(trans=True),
                mode="explicit", balance="tile_cyclic_persistent",
                cyclic_tile=8,
            )  # c=2 face
