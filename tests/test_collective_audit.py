"""End-to-end collective audit of the explicit schedule (VERDICT r2 #5).

TestExplicitEmission (test_summa.py) pins single gemms; these tests pin the
collective inventory of WHOLE programs — a full cholinv factor and a
dist-regime CQR2 — compiled for the 2x2x{1,2} grids, against (a) structural
invariants of the schedule and (b) exact emitted-count snapshots.

Since the obs layer landed, the inventory is taken through
capital_tpu.obs.xla_audit (the library the ledger and the audit CLI use)
rather than a private regex here — so these pins also exercise the
production scan path.

Why snapshots and not model equality: the Recorder prices the *schedule's*
collectives (panel gathers / masked-psum broadcasts / depth collects /
base-case replications — e.g. 43 for the c=2 factor below), while the
compiled HLO additionally carries GSPMD data-motion the model deliberately
does not book as collectives (collective-permutes from sharding
constraints, window slices and dynamic-update-slices of face-sharded
buffers, base-case panel replication gathers).  Those extras are a
*property of the schedule too*: a change that silently adds collectives —
the failure this test exists to catch — moves these counts.  When a
deliberate schedule change trips this test, re-run the audit probe
(docstring of each test prints the procedure) and re-pin with the new
derivation.

Invariants (version-robust):
  * no all-to-all anywhere (the schedule never uses one);
  * c=1 explicit cholinv emits ZERO all-reduce — the contraction path is
    pure ring gathers and the default base-case policy factors redundantly
    (any all-reduce appearing means a psum snuck into the c=1 path);
  * c=2 emits both gathers (window/replication motion) and all-reduces
    (masked-psum panel broadcasts + depth collects + base-case bcasts).
"""

import jax
import jax.numpy as jnp
import pytest

from capital_tpu.models import cholesky, qr
from capital_tpu.models.cholesky import CholinvConfig
from capital_tpu.models.qr import CacqrConfig
from capital_tpu.obs import xla_audit
from capital_tpu.parallel.topology import Grid
from capital_tpu.utils import jax_compat, rand48


def _emitted(fn, arg) -> dict[str, int]:
    return xla_audit.audit(fn, arg).collective_counts


def _model_collectives(fn, arg) -> int:
    rec = xla_audit.trace_model(fn, arg)
    return sum(s.collectives for s in rec.stats.values())


def _counts(ag=0, ar=0, rs=0, cp=0, aa=0) -> dict[str, int]:
    return {
        "all-gather": ag, "all-reduce": ar, "reduce-scatter": rs,
        "collective-permute": cp, "all-to-all": aa,
    }


class TestCholinvAudit:
    def test_c1_factor_inventory(self, grid2x2x1):
        g = grid2x2x1
        A = jax.device_put(jnp.asarray(rand48.symmetric(64)), g.face_sharding())
        cfg = CholinvConfig(base_case_dim=16, mode="explicit")
        fn = lambda a: cholesky.factor(g, a, cfg)
        got = _emitted(fn, A)
        # schedule invariants
        assert got["all-to-all"] == 0
        assert got["all-reduce"] == 0, (
            "the c=1 explicit factor has no psum in its schedule (ring "
            "gathers + redundant base cases); an all-reduce appeared: "
            f"{got}"
        )
        # snapshot (jax 0.4.37, 8-dev CPU mesh): 44 gathers = the model's
        # 31 schedule collectives (6 trsm + 9 tmu + 12 inv ring gathers +
        # 4 base-case replications) plus GSPMD window materializations; 51
        # permutes are sharding-constraint/window motion — down from the
        # pre-copy-free schedule's 55 (the whole-buffer
        # dynamic_update_slice write-backs the copy-free windows removed;
        # docs/DISTRIBUTED.md "Round 6").  Re-pin only after re-deriving
        # (see module docstring).
        assert _model_collectives(fn, A) == 31
        assert got == _counts(ag=44, cp=51), got

    @pytest.mark.skipif(
        not jax_compat.has_shard_map(),
        reason="multi-device explicit-mode compile needs a shard_map",
    )
    def test_c1_drift_totals(self, grid2x2x1):
        # the drift report must carry the SAME totals the snapshots pin —
        # model 31 vs compiled 95 (44 gathers + 51 permutes) — and every
        # phase lands in one of the three classifications (drift() is the
        # gate `make audit` runs).  audit() runs FIRST here on the same fn
        # object: trace_model defeating jax's fn-identity trace cache is
        # part of what this pins (an empty model Recorder after a compile
        # of the same function was a real bug).
        g = grid2x2x1
        A = jax.device_put(jnp.asarray(rand48.symmetric(64)), g.face_sharding())
        cfg = CholinvConfig(base_case_dim=16, mode="explicit")
        fn = lambda a: cholesky.factor(g, a, cfg)
        rep = xla_audit.drift(xla_audit.audit(fn, A), xla_audit.trace_model(fn, A))
        assert rep.model_collectives_total == 31
        assert rep.compiled_collectives_total == 95
        kinds = {p.classification for p in rep.phases}
        assert kinds <= {xla_audit.WITHIN, xla_audit.UNDERCOUNT, xla_audit.EXTRA}

    def test_c2_factor_inventory(self, grid2x2x2):
        g = grid2x2x2
        A = jax.device_put(jnp.asarray(rand48.symmetric(64)), g.face_sharding())
        cfg = CholinvConfig(base_case_dim=16, mode="explicit")
        fn = lambda a: cholesky.factor(g, a, cfg)
        got = _emitted(fn, A)
        assert got["all-to-all"] == 0
        assert got["all-reduce"] > 0  # masked-psum bcasts + depth collects
        # model: 43 = 4 factor_diag + 9 trsm + 12 tmu + 18 inv
        assert _model_collectives(fn, A) == 43
        # snapshot (jax 0.4.37, 8-dev CPU mesh; re-derived with the
        # copy-free windows — permutes down 55 → 51 like the c=1 row,
        # all-reduce 32 → 36 is this jax line's GSPMD lowering of the
        # depth motion, not a schedule change: the model total above is
        # version-independent and unchanged)
        assert got == _counts(ag=20, ar=36, cp=51), got

    def test_c2_skipping_does_not_change_collectives(self, grid2x2x2):
        # dead-segment skipping guards ONLY local matmuls; disabling the
        # triangular flags (dense gemm of the same shapes) must not change
        # the collective inventory of a single explicit product — a cond
        # around a collective would desynchronize the mesh and typically
        # shows up here as a different gather/psum count
        from capital_tpu.parallel import summa

        g = grid2x2x2
        M = jax.device_put(jnp.asarray(rand48.random(64, 64, key=3)), g.face_sharding())
        tri = _emitted(
            lambda a: summa.trmm(
                g, a, a, summa.TrmmArgs(side="L", uplo="U"), mode="explicit"
            ),
            M,
        )
        dense = _emitted(
            lambda a: summa.gemm(g, a, a, mode="explicit"), M
        )
        assert tri["all-reduce"] == dense["all-reduce"]
        assert tri["all-gather"] == dense["all-gather"]


class TestCacqrAudit:
    def test_dist_cqr2_inventory(self, grid2x2x2):
        g = grid2x2x2
        cfg = CacqrConfig(
            num_iter=2, regime="dist", mode="explicit",
            cholinv=CholinvConfig(base_case_dim=16, mode="explicit"),
        )
        A = jax.device_put(
            jnp.asarray(rand48.random(256, 64, key=9)), g.face_sharding()
        )
        fn = lambda a: qr.factor(g, a, cfg)
        got = _emitted(fn, A)
        assert got["all-to-all"] == 0
        # model: 103 = 8 gram + (43 + 43 both sweeps' cholinv) + 6 formR +
        # 3 merge — the two full cholinv factors dominate, as upstream
        # (cacqr.hpp:103)
        assert _model_collectives(fn, A) == 103
        # snapshot (jax 0.4.37, 8-dev CPU mesh; re-derived with the
        # copy-free windows — permutes 114 → 106, all-reduce 74 → 87 is
        # the same GSPMD lowering drift as the c=2 factor row; the model
        # total above is version-independent and unchanged)
        assert got == _counts(ag=40, ar=87, cp=106), got
