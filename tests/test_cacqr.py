"""M4 tests: CholeskyQR / CholeskyQR2 across regimes, solve, apply_Q/QT."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from capital_tpu.models import qr
from capital_tpu.parallel.topology import Grid
from capital_tpu.models.cholesky import CholinvConfig
from capital_tpu.models.qr import CacqrConfig
from capital_tpu.utils import rand48, residual


def _tall(m, n, key=11):
    return jnp.asarray(rand48.random(m, n, key=key))


class TestCQR2_1D:
    def test_orthogonality_and_residual(self, grid_flat8):
        g = grid_flat8
        A = jax.device_put(_tall(1024, 64), g.rows_sharding())
        Q, R = jax.jit(lambda a: qr.factor(g, a, CacqrConfig(regime="1d")))(A)
        assert residual.qr_orthogonality(Q) < 1e-14
        assert residual.qr_residual(A, Q, R) < 1e-13
        assert np.allclose(np.asarray(R), np.triu(np.asarray(R)))

    def test_cqr1_vs_cqr2_orthogonality(self, grid_flat8):
        # CQR2's second sweep must tighten orthogonality vs plain CQR
        g = grid_flat8
        # genuinely ill-conditioned (cond=1e6, singular directions not axis-
        # aligned, so R cannot absorb the scaling): A = Q0 diag(s) Vᵀ
        Q0, _ = np.linalg.qr(np.asarray(_tall(2048, 32)))
        V, _ = np.linalg.qr(np.asarray(rand48.random(32, 32, key=12)))
        A = jnp.asarray(Q0 * np.logspace(0, 6, 32)[None, :] @ V.T)
        q1, _ = qr.factor(g, A, CacqrConfig(num_iter=1, regime="1d"))
        q2, _ = qr.factor(g, A, CacqrConfig(num_iter=2, regime="1d"))
        o1 = float(residual.qr_orthogonality(q1))
        o2 = float(residual.qr_orthogonality(q2))
        assert o2 < o1 * 1e-2
        assert o2 < 1e-13


class TestCQR2_Dist:
    def test_dist_regime(self, grid2x2x2):
        g = grid2x2x2
        A = jax.device_put(_tall(512, 64), g.face_sharding())
        cfg = CacqrConfig(
            regime="dist", cholinv=CholinvConfig(base_case_dim=16, complete_inv=True)
        )
        Q, R = jax.jit(lambda a: qr.factor(g, a, cfg))(A)
        assert residual.qr_orthogonality(Q) < 1e-14
        assert residual.qr_residual(A, Q, R) < 1e-13

    def test_dist_blocked_solve_path(self, grid2x2x1):
        # complete_inv=False exercises the 2x2 blocked TRSM (cacqr.hpp:46-73)
        g = grid2x2x1
        A = _tall(256, 64)
        cfg = CacqrConfig(
            regime="dist", cholinv=CholinvConfig(base_case_dim=16, complete_inv=False)
        )
        Q, R = qr.factor(g, A, cfg)
        assert residual.qr_orthogonality(Q) < 1e-14
        assert residual.qr_residual(A, Q, R) < 1e-13

    def test_solve_single_base_case_window(self, grid2x2x1):
        g = grid2x2x1
        A = _tall(128, 16)
        cfg = CacqrConfig(
            regime="dist", cholinv=CholinvConfig(base_case_dim=32, complete_inv=False)
        )
        Q, R = qr.factor(g, A, cfg)
        assert residual.qr_orthogonality(Q) < 1e-14

    def test_auto_regime_picks_1d_for_small_n(self, grid2x2x2):
        cfg = CacqrConfig(regime="auto")
        assert qr._pick_regime(grid2x2x2, 64, cfg) == "1d"
        assert qr._pick_regime(grid2x2x2, 8192, cfg) == "dist"
        cfg2 = CacqrConfig(regime="dist")
        assert qr._pick_regime(grid2x2x2, 64, cfg2) == "dist"


class TestApply:
    def test_apply_q_and_qt(self, grid_flat8):
        g = grid_flat8
        A = _tall(512, 32)
        Q, R = qr.factor(g, A, CacqrConfig(regime="1d"))
        X = jnp.asarray(rand48.random(32, 16, key=13))
        np.testing.assert_allclose(
            np.asarray(qr.apply_Q(g, Q, X)), np.asarray(Q) @ np.asarray(X), rtol=1e-12, atol=1e-14
        )
        # apply_QT: reference never implemented it (cacqr.hpp:284); we do.
        Y = jnp.asarray(rand48.random(512, 8, key=14))
        np.testing.assert_allclose(
            np.asarray(qr.apply_QT(g, Q, Y)),
            np.asarray(Q).T @ np.asarray(Y),
            rtol=1e-12,
            atol=1e-14,
        )

    def test_bad_inputs(self, grid_flat8):
        A = _tall(16, 64)  # wide, not tall
        with pytest.raises(ValueError):
            qr.factor(grid_flat8, A)
        with pytest.raises(ValueError):
            qr.factor(grid_flat8, _tall(64, 16), CacqrConfig(num_iter=3))


class TestSweep1DBlocked:
    """VERDICT r1 #3: the 1d sweep's triangular flop savings — XLA-level
    column blocking for the gram (upper block-rows only) in every mode,
    plus the live-tile trmm scaling kernel when mode='pallas' on one device
    (the bench driver's auto-resolution on a TPU; see _sweep_1d docstring
    for the measured design space).  All paths must agree numerically."""

    def test_blocked_matches_unblocked(self, monkeypatch):
        # n=512 engages g=2 column blocking; forcing g=1 must give the
        # same factorization to fp roundoff (same per-element K order)
        g1 = Grid.square(c=1, devices=jax.devices("cpu")[:1])
        A = _tall(2048, 512).astype(jnp.float64)
        assert qr._col_blocks(512) == 2
        Qb, Rb = qr.factor(g1, A, CacqrConfig(num_iter=2, regime="1d"))
        # the pallas tri-kernel scaling path (mode='pallas') must agree too
        Qp, Rp = qr.factor(
            g1, A, CacqrConfig(num_iter=2, regime="1d", mode="pallas")
        )
        np.testing.assert_allclose(np.asarray(Qp), np.asarray(Qb), atol=1e-12)
        monkeypatch.setattr(qr, "_col_blocks", lambda n: 1)
        Qu, Ru = qr.factor(g1, A, CacqrConfig(num_iter=2, regime="1d"))
        np.testing.assert_allclose(np.asarray(Qb), np.asarray(Qu), atol=1e-12)
        np.testing.assert_allclose(
            np.triu(np.asarray(Rb)), np.triu(np.asarray(Ru)), atol=1e-10
        )
        assert float(residual.qr_orthogonality(Qb)) < 1e-14
        assert float(residual.qr_residual(A, Qb, Rb)) < 1e-13

    def test_blocked_distributed(self, grid_flat8):
        g = grid_flat8
        A = jax.device_put(_tall(1024, 512), g.rows_sharding())
        Q, R = jax.jit(
            lambda a: qr.factor(g, a, CacqrConfig(num_iter=2, regime="1d"))
        )(A)
        assert float(residual.qr_orthogonality(Q)) < 1e-14
        assert float(residual.qr_residual(A, Q, R)) < 1e-13

    def test_pallas_matches_xla_1d(self):
        g1 = Grid.square(c=1, devices=jax.devices("cpu")[:1])
        A = _tall(256, 64).astype(jnp.float32)
        Qx, Rx = jax.jit(
            lambda a: qr.factor(g1, a, CacqrConfig(num_iter=2, regime="1d", mode="xla"))
        )(A)
        Qp, Rp = jax.jit(
            lambda a: qr.factor(g1, a, CacqrConfig(num_iter=2, regime="1d", mode="pallas"))
        )(A)
        assert float(residual.qr_orthogonality(Qp)) < 1e-5
        assert float(residual.qr_residual(A, Qp, Rp)) < 1e-5
        np.testing.assert_allclose(np.asarray(Qp), np.asarray(Qx), atol=1e-5)
        np.testing.assert_allclose(
            np.triu(np.asarray(Rp)), np.triu(np.asarray(Rx)), atol=1e-5
        )

    def test_gram_emission_reduces_before_assembly(self, grid_flat8):
        """ADVICE r2: the blocked-gram comm model prices g collectives of
        live_frac·n² bytes total, which requires each block-row partial to
        be reduced BEFORE the transpose/concat assembly.  Pin the emitted
        HLO: per-block all-reduce result shapes appear (merged tuples
        allowed) and the dense n x n never rides a single collective."""
        import re

        g = grid_flat8
        n, nb = 512, 256  # g=2 blocking
        A = jax.device_put(_tall(1024, n), g.rows_sharding())
        txt = (
            jax.jit(lambda a: qr._sweep_1d(g, a, CacqrConfig(regime="1d")))
            .lower(A)
            .compile()
            .as_text()
        )
        ar_lines = [l for l in txt.splitlines() if re.search(r"= .*all-reduce\(", l)]
        shapes = []
        for l in ar_lines:
            shapes += re.findall(r"f64\[(\d+),(\d+)\]", l.split(" = ")[1].split("all-reduce")[0])
        shapes = [tuple(map(int, s)) for s in shapes]
        # the two block-row partials: (256, 512) and (256, 256)
        assert (nb, n) in shapes, (shapes, ar_lines)
        assert (nb, nb) in shapes, (shapes, ar_lines)
        # no collective carries the assembled dense gram
        assert (n, n) not in shapes, (shapes, ar_lines)

    def test_pallas_mode_multidevice_falls_back(self, grid_flat8):
        # mode='pallas' on a mesh must silently use the distributed path
        g = grid_flat8
        A = jax.device_put(_tall(512, 32), g.rows_sharding())
        Q, R = jax.jit(
            lambda a: qr.factor(g, a, CacqrConfig(num_iter=2, regime="1d", mode="pallas"))
        )(A)
        assert float(residual.qr_orthogonality(Q)) < 1e-13


def test_qr_residual_blocked_matches_dense():
    """The row-blocked residual (memory-lean validation for the 2M x 1024
    shape) must agree with the dense form."""
    from capital_tpu.utils import residual

    g1 = Grid.square(c=1, devices=jax.devices("cpu")[:1])
    A = _tall(2048, 512).astype(jnp.float64)
    Q, R = qr.factor(g1, A, CacqrConfig(num_iter=2, regime="1d"))
    dense = float(residual.qr_residual(A, Q, R))
    blocked = float(residual.qr_residual_blocked(A, Q, R, block_rows=256))
    assert blocked == pytest.approx(dense, rel=1e-6)
    # non-dividing block falls back to the dense form
    fb = float(residual.qr_residual_blocked(A, Q, R, block_rows=1000))
    assert fb == pytest.approx(dense, rel=1e-12)
