"""Static-analysis tests: the lint rules engine, the program sanitizer (one
failing + one passing program per rule), the source AST lint, the baseline
round-trip, the lint:report ledger/CLI seam, and the SolveEngine
validate=True donation assert (docs/STATIC_ANALYSIS.md).

Program-rule tests build tiny synthetic jit programs on the conftest CPU rig
(8 virtual devices, x64 on) — each compiles in well under a second.  HLO
donation parsing is additionally covered on handwritten module text, so the
rule's text contract survives a jax upgrade changing what CPU compiles.
"""

import json
import warnings

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from capital_tpu.lint import __main__ as lint_main
from capital_tpu.lint import baseline, program, rules, source
from capital_tpu.obs import __main__ as obs_main
from capital_tpu.obs import ledger, xla_audit
from capital_tpu.serve import ServeConfig, SolveEngine
from capital_tpu.serve import api as serve_api
from capital_tpu.utils import tracing


def _target(fn, *args, **kw):
    return program.ProgramTarget(name="t", fn=fn, args=args, **kw)


def _by_rule(findings, rule):
    return [f for f in findings if f.rule == rule]


def _trace_rules(fn, *args, **kw):
    """Trace-side findings only (no compile): the per-rule tests."""
    return program.sanitize(_target(fn, *args, **kw), compile_program=False)


# ---------------------------------------------------------------------------
# rules engine
# ---------------------------------------------------------------------------


class TestRulesEngine:
    def test_unknown_severity_rejected(self):
        with pytest.raises(ValueError, match="severity"):
            rules.make("r", "fatal", "t", "m")

    def test_fingerprint_ignores_line_number(self):
        a = rules.make("r", rules.ERROR, "f.py", "msg", line=10)
        b = rules.make("r", rules.ERROR, "f.py", "msg", line=99)
        c = rules.make("r", rules.ERROR, "f.py", "other", line=10)
        assert a.fingerprint == b.fingerprint
        assert a.fingerprint != c.fingerprint

    def test_gate_severity_ladder(self):
        err = [rules.make("r", rules.ERROR, "t", "m")]
        wrn = [rules.make("r", rules.WARN, "t", "m")]
        inf = [rules.make("r", rules.INFO, "t", "m")]
        assert not rules.gate(err, "error")
        assert rules.gate(wrn, "error")
        assert not rules.gate(wrn, "warn")
        assert rules.gate(inf, "warn")
        with pytest.raises(ValueError, match="fail-on"):
            rules.gate([], "info")

    def test_sort_errors_first(self):
        w = rules.make("r", rules.WARN, "a.py", "m", line=1)
        e = rules.make("r", rules.ERROR, "z.py", "m", line=9)
        assert rules.sort_findings([w, e]) == [e, w]


# ---------------------------------------------------------------------------
# program sanitizer, one failing + one passing program per rule
# ---------------------------------------------------------------------------


class TestPhaseCoverage:
    def test_untagged_matmul_fails(self):
        x = jnp.ones((8, 8), jnp.float64)
        found = _by_rule(_trace_rules(lambda a: a @ a, x),
                         program.PHASE_COVERAGE)
        assert len(found) == 1
        assert "dot_general" in found[0].message
        assert found[0].severity == rules.ERROR

    def test_scoped_matmul_passes(self):
        x = jnp.ones((8, 8), jnp.float64)

        def fn(a):
            with tracing.scope("CI::tmu"):
                return a @ a

        assert _by_rule(_trace_rules(fn, x), program.PHASE_COVERAGE) == []

    def test_scan_body_inherits_enclosing_phase(self):
        # scan bodies trace with a fresh name stack; the walk must carry
        # the scan equation's own scope into the body's matmul.
        x = jnp.ones((8, 8), jnp.float64)

        def fn(a):
            with tracing.scope("CI::tmu"):
                out, _ = jax.lax.scan(
                    lambda c, _: (c @ a, None), a, None, length=3)
            return out

        assert _by_rule(_trace_rules(fn, x), program.PHASE_COVERAGE) == []


class TestNoHostSync:
    @staticmethod
    def _callback_fn(a):
        with tracing.scope("CI::tmu"):
            b = jax.pure_callback(
                lambda v: np.asarray(v), jax.ShapeDtypeStruct(a.shape, a.dtype), a
            )
            return b @ b

    def test_callback_in_hot_path_fails(self):
        x = jnp.ones((4, 4), jnp.float64)
        found = _by_rule(_trace_rules(self._callback_fn, x),
                         program.NO_HOST_SYNC)
        assert len(found) == 1
        assert "pure_callback" in found[0].message

    def test_cold_path_exempt(self):
        x = jnp.ones((4, 4), jnp.float64)
        found = _by_rule(_trace_rules(self._callback_fn, x, hot_path=False),
                         program.NO_HOST_SYNC)
        assert found == []


class TestCacheKeyHygiene:
    def test_baked_operand_sized_constant_fails(self):
        big = jnp.asarray(np.ones((64, 64)))  # 32 KiB closure capture

        def fn(a):
            with tracing.scope("CI::tmu"):
                return a @ big

        found = _by_rule(_trace_rules(fn, jnp.ones((64, 64))),
                         program.CACHE_KEY_HYGIENE)
        assert len(found) == 1
        assert "baked-in constant" in found[0].message
        assert found[0].severity == rules.ERROR

    def test_small_inline_constant_passes(self):
        def fn(a):
            with tracing.scope("CI::tmu"):
                return a @ a + jnp.eye(8, dtype=a.dtype)[:4, :4].sum()

        found = _by_rule(_trace_rules(fn, jnp.ones((4, 4))),
                         program.CACHE_KEY_HYGIENE)
        assert found == []

    def test_weak_typed_input_warns(self):
        def fn(a, s):
            with tracing.scope("CI::tmu"):
                return a @ a * s

        # a bare Python scalar traces to a weak-typed aval — the
        # double-compile hazard for an AOT cache keyed on avals
        found = _by_rule(_trace_rules(fn, jnp.ones((4, 4)), 2.0),
                         program.CACHE_KEY_HYGIENE)
        assert [f.severity for f in found] == [rules.WARN]
        assert "weak" in found[0].message

    def test_non_cacheable_target_exempt(self):
        big = jnp.asarray(np.ones((64, 64)))
        found = _by_rule(
            _trace_rules(lambda a: a @ big, jnp.ones((64, 64)),
                         cacheable=False),
            program.CACHE_KEY_HYGIENE,
        )
        assert found == []


class TestDtypeDrift:
    def test_f64_leak_from_f32_program_fails(self):
        def fn(a):
            with tracing.scope("CI::tmu"):
                w = a.astype(jnp.float64)
                return w @ w

        found = _by_rule(_trace_rules(fn, jnp.ones((4, 4), jnp.float32)),
                         program.DTYPE_DRIFT)
        assert found and all(f.severity == rules.ERROR for f in found)

    def test_pure_f32_program_passes(self):
        def fn(a):
            with tracing.scope("CI::tmu"):
                return a @ a * jnp.float32(2.0)

        assert _by_rule(_trace_rules(fn, jnp.ones((4, 4), jnp.float32)),
                        program.DTYPE_DRIFT) == []

    def test_genuinely_f64_program_allowed(self):
        def fn(a):
            with tracing.scope("CI::tmu"):
                return a @ a

        assert _by_rule(_trace_rules(fn, jnp.ones((4, 4), jnp.float64)),
                        program.DTYPE_DRIFT) == []


class TestDonationHonored:
    HONORED = """HloModule m, input_output_alias={ {}: (0, {}, may-alias) }
ENTRY e { ROOT p = f32[4]{0} parameter(0) }
"""
    NESTED = """HloModule m, input_output_alias={ {0}: (0, {}, may-alias), {1}: (2, {}, may-alias) }, entry_computation_layout={(f32[4]{0})->f32[4]{0}}
ENTRY e { ROOT p = f32[4]{0} parameter(0) }
"""
    DROPPED = """HloModule m, entry_computation_layout={(f32[4]{0})->f32[]}
ENTRY e { ROOT p = f32[4]{0} parameter(0) }
"""

    def test_aliased_params_parses_nested_braces(self):
        assert program.aliased_params(self.HONORED) == {0}
        assert program.aliased_params(self.NESTED) == {0, 2}
        assert program.aliased_params(self.DROPPED) == set()

    def test_text_check_flags_only_dropped_args(self):
        found = program.check_donation_text(self.NESTED, (0, 1, 2), "program:t")
        assert [f.rule for f in found] == [program.DONATION_HONORED]
        assert "#1" in found[0].message

    def test_compiled_honored_donation_passes(self):
        exe = jax.jit(lambda x: x + 1.0, donate_argnums=(0,)) \
            .lower(jax.ShapeDtypeStruct((32,), jnp.float64)).compile()
        assert program.check_donation(exe, (0,), "program:t") == []

    def test_compiled_dropped_donation_fails(self):
        # a (32,) input can never alias the scalar output; XLA drops the
        # donation with only a UserWarning — the rule turns it into an error
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            exe = jax.jit(lambda x: jnp.sum(x), donate_argnums=(0,)) \
                .lower(jax.ShapeDtypeStruct((32,), jnp.float64)).compile()
        found = program.check_donation(exe, (0,), "program:t")
        assert [f.rule for f in found] == [program.DONATION_HONORED]


class TestCollectiveBudget:
    @staticmethod
    def _audit(phase_collectives, flops=0.0):
        counts = {"all-reduce": sum(phase_collectives.values())}
        return xla_audit.ProgramAudit(
            collective_counts=counts, collective_bytes={},
            phase_collectives=dict(phase_collectives), phase_comm_bytes={},
            flops=flops, bytes_accessed=0.0, peak_hbm_bytes=0.0,
            argument_bytes=0.0, output_bytes=0.0, temp_bytes=0.0,
        )

    @staticmethod
    def _recorder(collectives=1, flops=0.0):
        with tracing.Recorder() as rec:
            with tracing.scope("CI::tmu"):
                tracing.emit(flops=flops, collectives=collectives,
                             comm_bytes=64.0)
        return rec

    def test_model_undercount_fails(self):
        tgt = _target(lambda: None)
        found = program.rule_collective_budget(
            tgt, self._audit({"CI::tmu": 100}), self._recorder(1),
            tol_ratio=4.0, slack=8,
        )
        assert [f.severity for f in found] == [rules.ERROR]
        assert "CI::tmu" in found[0].message

    def test_within_envelope_and_gspmd_extra_pass(self):
        tgt = _target(lambda: None)
        # 3 <= 1*4+8 within; a phase the model never books is EXTRA (GSPMD
        # motion), tolerated by the same policy make audit applies
        found = program.rule_collective_budget(
            tgt, self._audit({"CI::tmu": 3, "CQR::gram": 5}),
            self._recorder(1),
        )
        assert found == []

    def test_whole_program_flops_drift_warns(self):
        tgt = _target(lambda: None)
        found = program.rule_collective_budget(
            tgt, self._audit({"CI::tmu": 1}, flops=1e12),
            self._recorder(1, flops=1e9), flops_tol_ratio=2.0,
        )
        assert [f.severity for f in found] == [rules.WARN]
        assert "flops drift" in found[0].message


class TestSanitizeEndToEnd:
    def test_clean_program_is_clean(self):
        def fn(a, b):
            with tracing.scope("CI::tmu"):
                return a @ b

        tgt = _target(fn, jnp.ones((16, 16), jnp.float64),
                      jnp.ones((16, 16), jnp.float64))
        assert program.sanitize(tgt) == []

    def test_flagship_serve_targets_are_clean(self):
        from capital_tpu.lint import targets

        # f64 pins these buckets to the vmap-over-LAPACK path this test has
        # always covered — f32 at n=16 now auto-routes the batched-grid
        # pallas kernels, whose interpret-mode bodies are invisible to the
        # flops envelope (their own targets opt out via flops_audited;
        # tests/test_batched_small.py::TestLintTargets covers them)
        for tgt in targets.serve_bucket_targets(n=16, rows=64, nrhs=2,
                                                capacity=2,
                                                dtype=jnp.float64):
            assert program.sanitize(tgt) == [], tgt.name


# ---------------------------------------------------------------------------
# source lint
# ---------------------------------------------------------------------------


def _src(text, path="capital_tpu/models/fake.py"):
    return source.lint_source(path, text=text)


class TestSourceExcepts:
    def test_bare_except_fails(self):
        found = _by_rule(_src("try:\n    f()\nexcept:\n    pass\n"),
                        source.BARE_EXCEPT)
        assert [f.line for f in found] == [3]

    def test_broad_except_without_exit_fails(self):
        found = _by_rule(
            _src("try:\n    f()\nexcept Exception:\n    pass\n"),
            source.BROAD_EXCEPT)
        assert len(found) == 1

    @pytest.mark.parametrize("handler", [
        "except ValueError:\n    pass\n",
        "except Exception:\n    raise\n",
        "except Exception as e:\n    log.warning('gone: %s', e)\n",
        "except Exception:  # lint: allow-broad-except — shutdown path\n"
        "    pass\n",
    ])
    def test_accepted_spellings_pass(self, handler):
        found = _src("try:\n    f()\n" + handler)
        assert _by_rule(found, source.BROAD_EXCEPT) == []
        assert _by_rule(found, source.BARE_EXCEPT) == []


class TestSourceComputeScope:
    def test_unscoped_matmul_in_models_warns(self):
        found = _by_rule(_src("import jax.numpy as jnp\n"
                              "def f(a):\n    return jnp.matmul(a, a)\n"),
                         source.COMPUTE_OUTSIDE_SCOPE)
        assert [f.severity for f in found] == [rules.WARN]

    def test_matmult_operator_detected(self):
        found = _by_rule(_src("def f(a):\n    return a @ a\n"),
                         source.COMPUTE_OUTSIDE_SCOPE)
        assert len(found) == 1

    def test_scoped_matmul_passes(self):
        text = ("from capital_tpu.utils import tracing\n"
                "def f(a):\n"
                "    with tracing.scope('CI::tmu'):\n"
                "        return a @ a\n")
        assert _by_rule(_src(text), source.COMPUTE_OUTSIDE_SCOPE) == []

    def test_rule_limited_to_scoped_dirs(self):
        text = "def f(a):\n    return a @ a\n"
        found = _src(text, path="capital_tpu/bench/fake.py")
        assert _by_rule(found, source.COMPUTE_OUTSIDE_SCOPE) == []


class TestSourcePhaseTags:
    def test_unregistered_scope_tag_fails(self):
        found = _by_rule(_src("with tracing.scope('CI::nope'):\n    pass\n"),
                         source.UNREGISTERED_PHASE_TAG)
        assert len(found) == 1 and "CI::nope" in found[0].message

    def test_registered_scope_and_tap_pass(self):
        text = ("with tracing.scope('CI::tmu'):\n"
                "    x = faultinject.tap(x, point='serve::ingest')\n")
        assert _by_rule(_src(text), source.UNREGISTERED_PHASE_TAG) == []

    def test_unregistered_tap_point_fails(self):
        found = _by_rule(_src("x = faultinject.tap(x, point='bad::tag')\n"),
                         source.UNREGISTERED_PHASE_TAG)
        assert len(found) == 1

    def test_syntax_error_is_a_finding_not_a_crash(self):
        found = _src("def f(:\n")
        assert [f.rule for f in found] == ["syntax"]

    def test_seed_tree_has_no_source_errors(self):
        # the satellite contract: every error-severity violation the lint
        # found at seed was FIXED, not baselined (warns are the baseline)
        errors = [f for f in source.lint_tree("capital_tpu")
                  if f.severity == rules.ERROR]
        assert errors == [], [f.render() for f in errors]


# ---------------------------------------------------------------------------
# baseline round-trip + CLI gate
# ---------------------------------------------------------------------------

BAD_SOURCE = "try:\n    f()\nexcept:\n    pass\n"


class TestBaselineRoundTrip:
    def test_finding_to_baseline_to_suppressed_to_refail(self, tmp_path):
        bad = tmp_path / "bad.py"
        bad.write_text(BAD_SOURCE)
        bl = str(tmp_path / "bl.jsonl")
        args = ["source", str(bad), "--baseline", bl]

        # 1. fresh violation fails the gate
        assert lint_main.main(args) == 1
        # 2. baseline it
        assert lint_main.main(args + ["--update-baseline"]) == 0
        recs = [json.loads(ln) for ln in
                open(bl).read().splitlines()]
        assert [r["rule"] for r in recs] == [source.BARE_EXCEPT]
        # 3. suppressed now
        assert lint_main.main(args) == 0
        # 4. --no-baseline surfaces the full debt again
        assert lint_main.main(args + ["--no-baseline"]) == 1

    def test_baseline_survives_line_churn(self, tmp_path):
        bad = tmp_path / "bad.py"
        bad.write_text(BAD_SOURCE)
        fps = {f.fingerprint for f in source.lint_source(str(bad))}
        bad.write_text("# comment\n# comment\n" + BAD_SOURCE)
        moved = source.lint_source(str(bad))
        fresh, suppressed = baseline.apply(moved, fps)
        assert fresh == [] and len(suppressed) == 1

    def test_malformed_baseline_rejected(self, tmp_path):
        bl = tmp_path / "bl.jsonl"
        bl.write_text('{"rule": "x"}\n')  # no fingerprint
        with pytest.raises(ValueError, match="fingerprint"):
            baseline.load(str(bl))

    def test_missing_baseline_is_empty(self, tmp_path):
        assert baseline.load(str(tmp_path / "nope.jsonl")) == set()


class TestLintReportSeam:
    def test_record_validates_and_gates_ok(self, tmp_path):
        bad = tmp_path / "ok.py"
        bad.write_text("x = 1\n")
        led = str(tmp_path / "led.jsonl")
        assert lint_main.main(["source", str(bad), "--no-baseline",
                               "--ledger", led]) == 0
        recs = ledger.read(led)
        assert len(recs) == 1
        block = recs[0]["lint_report"]
        assert ledger.validate_lint_report(block) == []
        assert block["ok"] and block["pass"] == "source"
        assert obs_main.main(["lint-report", led,
                              "--require-pass", "source"]) == 0

    def test_failing_report_fails_the_obs_gate(self, tmp_path):
        bad = tmp_path / "bad.py"
        bad.write_text(BAD_SOURCE)
        led = str(tmp_path / "led.jsonl")
        assert lint_main.main(["source", str(bad), "--no-baseline",
                               "--ledger", led]) == 1
        assert obs_main.main(["lint-report", led]) == 1

    def test_malformed_record_exits_2(self, tmp_path):
        led = str(tmp_path / "led.jsonl")
        ledger.append(led, ledger.record(
            "lint:report", ledger.manifest(),
            lint_report={"schema_version": ledger.SCHEMA_VERSION},
        ))
        assert obs_main.main(["lint-report", led]) == 2

    def test_required_pass_missing_exits_1(self, tmp_path):
        led = tmp_path / "led.jsonl"
        led.write_text("")  # a ledger with no lint_report records
        assert obs_main.main(["lint-report", str(led),
                              "--require-pass", "program"]) == 1
        assert obs_main.main(["lint-report", str(led)]) == 0

    def test_diff_rejects_malformed_lint_record(self):
        rec = ledger.record("lint:report", ledger.manifest(),
                            lint_report={"pass": "nope"})
        with pytest.raises(ledger.LedgerIncompatible, match="lint_report"):
            ledger.diff([rec], [rec])


# ---------------------------------------------------------------------------
# SolveEngine(validate=True): the donation assert at cache-insert
# ---------------------------------------------------------------------------

ENGINE_CFG = ServeConfig(
    buckets=(8, 16),
    rows_buckets=(32, 64),
    nrhs_buckets=(1, 4),
    max_batch=2,
    max_delay_s=10.0,
    donate=True,  # CPU honors donation in this jax; exercise the assert
)


class TestEngineValidate:
    def test_honored_donations_insert_cleanly(self):
        eng = SolveEngine(cfg=ENGINE_CFG, validate=True)
        rng = np.random.default_rng(0)
        M = rng.standard_normal((8, 8))
        A = M @ M.T + 8 * np.eye(8)
        B = rng.standard_normal((8, 3))
        r = eng.solve("posv", A, B)
        assert r.ok
        np.testing.assert_allclose(np.asarray(A @ r.x), B, atol=1e-8)
        r = eng.solve("inv", A)
        assert r.ok

    def test_lstsq_declares_no_droppable_donation(self):
        # the (m, nrhs) RHS can never alias the (n, nrhs) solution; the
        # engine must not declare it, so compiling raises no drop warning
        eng = SolveEngine(cfg=ENGINE_CFG, validate=True)
        with warnings.catch_warnings():
            warnings.simplefilter("error", UserWarning)
            assert eng.warmup([("lstsq", (24, 8), (24, 1), "float64")]) == 1

    def test_dropped_donation_raises_at_insert(self, monkeypatch):
        # force the hazard: a posv whose "solution" cannot alias the donated
        # RHS batch — validate must refuse the cache insert
        def bad_batched(op, precision, impl="auto", **kw):
            def fn(Ab, Bb):
                return jnp.sum(Bb, axis=2), jnp.zeros(
                    Ab.shape[0], jnp.int32)
            return fn

        monkeypatch.setattr(serve_api, "batched", bad_batched)
        eng = SolveEngine(cfg=ENGINE_CFG, validate=True)
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            with pytest.raises(AssertionError, match="donation"):
                eng.warmup([("posv", (8, 8), (8, 1), "float64")])

    def test_validate_off_keeps_seed_behavior(self, monkeypatch):
        def bad_batched(op, precision, impl="auto", **kw):
            def fn(Ab, Bb):
                return jnp.sum(Bb, axis=2), jnp.zeros(
                    Ab.shape[0], jnp.int32)
            return fn

        monkeypatch.setattr(serve_api, "batched", bad_batched)
        eng = SolveEngine(cfg=ENGINE_CFG)
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            assert eng.warmup([("posv", (8, 8), (8, 1), "float64")]) == 1
