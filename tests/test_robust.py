"""Robustness tests: breakdown detection, shifted-CholeskyQR recovery, and
the honest-failure contract (docs/ROBUSTNESS.md).

Calibrated on the CPU/x64 rig at m=384, n=48, seed 0: f64 recovers fully at
cond=1e12 (one shifted sweep contracts cond by ~7e-6, then sCQR3 polishes);
f32 recovers at cond=1e4 but is FUNDAMENTALLY beyond the shift envelope at
cond>=1e6 (contraction/sweep is only ~0.165 and repeated shifts stall), so
those cases must come back finite with the `info = n + 2` sentinel.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from capital_tpu.models import cholesky, qr
from capital_tpu.models.cholesky import CholinvConfig
from capital_tpu.models.qr import CacqrConfig
from capital_tpu.parallel.topology import Grid
from capital_tpu.robust import RobustConfig, detect, recovery

M, N = 384, 48


def _illcond(m, n, cond, dtype, seed=0):
    """Tall matrix with a log-spaced spectrum spanning exactly `cond`."""
    rng = np.random.default_rng(seed)
    Q0, _ = np.linalg.qr(rng.standard_normal((m, n)))
    V, _ = np.linalg.qr(rng.standard_normal((n, n)))
    s = np.logspace(0, -np.log10(cond), n)
    return jnp.asarray(Q0 @ np.diag(s) @ V.T, dtype=dtype)


def _grid1():
    return Grid.square(c=1, devices=[jax.devices()[0]])


def _cfg(regime, robust=True):
    return CacqrConfig(
        regime=regime, robust=RobustConfig() if robust else None
    )


def _tol(dtype):
    return 100.0 * N * recovery.unit_roundoff(jnp.dtype(dtype))


# --------------------------------------------------------------------------
# detection
# --------------------------------------------------------------------------


class TestDetect:
    def test_healthy(self):
        R = jnp.triu(jnp.eye(4) + 0.1)
        assert int(detect.factor_info(R)) == 0

    def test_first_bad_diagonal(self):
        R = jnp.diag(jnp.array([1.0, 2.0, jnp.nan, -1.0]))
        assert int(detect.factor_info(R)) == 3  # 1-based, FIRST bad entry

    def test_nonpositive_diagonal(self):
        R = jnp.diag(jnp.array([1.0, 0.0, 2.0]))
        assert int(detect.factor_info(R)) == 2

    def test_offdiag_nonfinite(self):
        R = jnp.eye(4).at[0, 3].set(jnp.inf)
        assert int(detect.factor_info(R)) == 5  # n + 1

    def test_nan_filled_cholesky_is_flagged(self):
        # the real failure shape: lax.linalg.cholesky NaN-fills silently
        G = jnp.eye(4).at[0, 0].set(-1.0)
        R = jnp.linalg.cholesky(G).T
        assert int(detect.factor_info(R)) != 0

    def test_jit_and_ops_with_info(self):
        from capital_tpu.ops import lapack

        G = jnp.asarray(np.diag([4.0, 1.0, -9.0]), dtype=jnp.float64)
        T, info = jax.jit(lambda a: lapack.potrf(a, with_info=True))(G)
        assert int(info) != 0
        G2 = jnp.eye(3, dtype=jnp.float64) * 4.0
        _, _, info2 = lapack.potrf_trtri(G2, with_info=True)
        assert int(info2) == 0


class TestGuardedChol:
    def test_healthy_no_shift(self):
        from capital_tpu.ops import lapack

        A = _illcond(64, 8, 10.0, jnp.float64)
        G = A.T @ A
        R, Rinv, ev = recovery.guarded_chol(
            G, 64, RobustConfig(), lapack.potrf_trtri
        )
        assert int(ev.info) == 0 and float(ev.sigma) == 0.0
        np.testing.assert_allclose(
            np.asarray(R.T @ R), np.asarray(G), atol=1e-12
        )

    def test_breakdown_shifts_and_repairs(self):
        from capital_tpu.ops import lapack

        A = _illcond(64, 8, 1e12, jnp.float64)
        G = (A.T @ A).astype(jnp.float64)
        R, Rinv, ev = recovery.guarded_chol(
            G, 64, RobustConfig(), lapack.potrf_trtri
        )
        assert int(ev.info) != 0          # raw factorization broke
        assert float(ev.sigma) > 0.0      # a shift was applied
        assert int(ev.info_after) == 0    # shifted factorization is clean
        assert bool(jnp.all(jnp.isfinite(R)))

    def test_indefinite_stays_flagged(self):
        # the shift repairs roundoff-induced breakdown only; a genuinely
        # indefinite matrix must keep a nonzero residual info
        from capital_tpu.ops import lapack

        G = jnp.asarray(np.diag([1.0, -5.0, 2.0]), dtype=jnp.float64)
        _, _, ev = recovery.guarded_chol(G, 3, RobustConfig(), lapack.potrf_trtri)
        assert int(ev.info) != 0 and int(ev.info_after) != 0


# --------------------------------------------------------------------------
# qr.factor under RobustConfig — the acceptance matrix
# --------------------------------------------------------------------------


HEALTHY = [
    (1e3, jnp.float32),
    (1e3, jnp.float64),
    (1e6, jnp.float64),
]
RECOVERS = [
    (1e4, jnp.float32),
    (1e12, jnp.float64),
]
BEYOND_ENVELOPE = [  # f32 shift stall: finite + sentinel, never NaN
    (1e6, jnp.float32),
    (1e12, jnp.float32),
]


class TestRobustQR:
    @pytest.mark.parametrize("cond,dtype", HEALTHY)
    @pytest.mark.parametrize("regime", ["1d", "dist"])
    def test_healthy_matches_unguarded(self, cond, dtype, regime):
        g = _grid1()
        A = _illcond(M, N, cond, dtype)
        Q, R, ri = qr.factor(g, A, _cfg(regime))
        assert int(ri.breakdown) == 0
        assert int(ri.info) == 0
        assert float(ri.sigma) == 0.0
        Q0, R0 = qr.factor(g, A, _cfg(regime, robust=False))
        np.testing.assert_allclose(np.asarray(Q), np.asarray(Q0))
        np.testing.assert_allclose(np.asarray(R), np.asarray(R0))

    @pytest.mark.parametrize("cond,dtype", RECOVERS)
    @pytest.mark.parametrize("regime", ["1d", "dist"])
    def test_breakdown_recovers_to_tolerance(self, cond, dtype, regime):
        g = _grid1()
        A = _illcond(M, N, cond, dtype)
        Q, R, ri = qr.factor(g, A, _cfg(regime))
        assert int(ri.breakdown) > 0
        assert int(ri.shifted) > 0
        assert float(ri.sigma) > 0.0
        assert int(ri.escalated) == 1
        assert int(ri.info) == 0
        assert bool(jnp.all(jnp.isfinite(Q)))
        # the gate RobustInfo reports is the post-escalation measurement
        assert 0.0 <= float(ri.ortho) <= _tol(dtype)
        # and it agrees with a from-scratch measurement of the returned Q
        I = np.eye(N)
        gate = np.linalg.norm(I - np.asarray(Q, np.float64).T @ np.asarray(Q, np.float64)) / np.sqrt(N)
        assert gate <= _tol(dtype)
        # R still reproduces A
        resid = np.linalg.norm(np.asarray(A, np.float64) - np.asarray(Q, np.float64) @ np.asarray(R, np.float64))
        rtol = 1e-4 if dtype == jnp.float32 else 1e-10
        assert resid / np.linalg.norm(np.asarray(A, np.float64)) < rtol

    @pytest.mark.parametrize("cond,dtype", BEYOND_ENVELOPE)
    def test_beyond_envelope_finite_with_sentinel(self, cond, dtype):
        g = _grid1()
        A = _illcond(M, N, cond, dtype)
        Q, R, ri = qr.factor(g, A, _cfg("1d"))
        assert bool(jnp.all(jnp.isfinite(Q)))     # no NaN propagation, ever
        assert int(ri.breakdown) > 0
        assert int(ri.info) == N + 2              # honest-failure sentinel
        assert float(ri.ortho) > _tol(dtype)      # the gate says why

    def test_f64_cond1e12_nans_without_robust(self):
        # the baseline behavior the tentpole exists to fix
        g = _grid1()
        A = _illcond(M, N, 1e12, jnp.float64)
        Q, R = qr.factor(g, A, _cfg("1d", robust=False))
        assert not bool(jnp.all(jnp.isfinite(Q)))

    def test_jit_roundtrip(self):
        g = _grid1()
        A = _illcond(M, N, 1e12, jnp.float64)
        cfg = _cfg("1d")
        Q, R, ri = jax.jit(lambda a: qr.factor(g, a, cfg))(A)
        assert int(ri.breakdown) > 0 and int(ri.info) == 0
        assert float(ri.ortho) <= _tol(jnp.float64)

    def test_multidevice_1d_routes_unfused(self, grid_flat8):
        g = grid_flat8
        A = jax.device_put(
            _illcond(1024, 64, 1e12, jnp.float64), g.rows_sharding()
        )
        Q, R, ri = qr.factor(g, A, _cfg("1d"))
        assert int(ri.breakdown) > 0 and int(ri.info) == 0
        assert float(ri.ortho) <= 100.0 * 64 * recovery.unit_roundoff(
            jnp.dtype(jnp.float64)
        )

    @pytest.mark.skipif(
        not hasattr(jax, "typeof"),
        reason="fused qr tier needs a newer jax (jax.typeof)",
    )
    def test_fused_regime_robust(self):
        g = _grid1()
        A = _illcond(M, N, 1e12, jnp.float64)
        cfg = CacqrConfig(regime="1d", mode="pallas", robust=RobustConfig())
        Q, R, ri = qr.factor(g, A, cfg)
        assert int(ri.info) == 0 and int(ri.breakdown) > 0


class TestRobustCholesky:
    def test_non_spd_flags_instead_of_nan(self, grid2x2x1):
        n = 64
        rng = np.random.default_rng(3)
        Mx = rng.standard_normal((n, n))
        A = jnp.asarray(Mx + Mx.T, dtype=jnp.float64)  # symmetric, indefinite
        cfg = CholinvConfig(robust=RobustConfig())
        R, Rinv, info = cholesky.factor(grid2x2x1, A, cfg)
        assert int(info) != 0

    def test_spd_info_zero_and_values_unchanged(self, grid2x2x1):
        from capital_tpu.bench.drivers import _spd

        A = _spd(64, jnp.float64)
        cfg = CholinvConfig(robust=RobustConfig())
        R, Rinv, info = cholesky.factor(grid2x2x1, A, cfg)
        assert int(info) == 0
        R0, Rinv0 = cholesky.factor(grid2x2x1, A, CholinvConfig())
        np.testing.assert_allclose(np.asarray(R), np.asarray(R0))


class TestRegimeValidation:
    def test_unknown_regime_raises(self):
        g = _grid1()
        A = _illcond(128, 16, 10.0, jnp.float64)
        with pytest.raises(ValueError, match="unknown regime"):
            qr.factor(g, A, CacqrConfig(regime="2d"))

    def test_pick_regime_rejects_directly(self):
        with pytest.raises(ValueError, match="unknown regime"):
            qr._pick_regime(_grid1(), 64, CacqrConfig(regime="bogus"))


class TestLedgerExemption:
    def test_recovery_record_roundtrips_diff(self):
        # satellite 6: a breakdown-recovery record must not read as a
        # metric regression, while the same drop without the status must
        from capital_tpu.obs import ledger

        man = ledger.manifest(dtype="float64", config_id="robust_rt")
        base = ledger.record(
            "bench:cacqr", dict(man),
            measured={"metric": "cacqr", "value": 10.0, "unit": "TFLOP/s"},
        )
        recov = ledger.record(
            "bench:cacqr", dict(man),
            measured={"metric": "cacqr", "value": 4.0, "unit": "TFLOP/s"},
            robust={"breakdown": 1, "shifted": 1, "escalated": 1, "info": 0},
            event={"status": "recovered"},
        )
        assert ledger.diff([base], [recov]) == []
        plain = dict(recov)
        plain.pop("robust")
        plain.pop("event")
        assert ledger.diff([base], [plain])  # the check is alive

    def test_robust_gate_cli(self):
        from capital_tpu.obs.__main__ import main

        assert main(["robust-gate"]) == 0
