"""M3 tests: recursive Cholesky + inverse (cholinv) on CPU meshes.

Gates mirror the reference's validation workflow (test/cholesky/validate.hpp
+ bench/cholesky/cholinv.cpp:61-66): relative residuals ~1e-14 at f64.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from capital_tpu.models import cholesky
from capital_tpu.models.cholesky import CholinvConfig, padded_dim, plan
from capital_tpu.utils import rand48, residual
from capital_tpu.utils.config import BaseCasePolicy


def _spd(n):
    return jnp.asarray(rand48.symmetric(n))


def _put(grid, x):
    return jax.device_put(x, grid.face_sharding())


class TestPlan:
    def test_padded_dim(self):
        assert padded_dim(100, 32) == 128
        assert padded_dim(128, 32) == 128
        assert padded_dim(8, 32) == 8
        assert padded_dim(33, 32) == 64

    def test_plan_halving(self):
        cfg = CholinvConfig(base_case_dim=32, split=1)
        root = plan(128, cfg)
        assert not root.is_base
        assert root.top[0].n == 64 and root.top[1].n == 64
        leaves = []

        def walk(nd):
            if nd.is_base:
                leaves.append(nd)
            else:
                walk(nd.top[0]), walk(nd.top[1])

        walk(root)
        assert all(l.n == 32 for l in leaves) and len(leaves) == 4
        assert [l.off for l in leaves] == [0, 32, 64, 96]

    def test_plan_aggressive_split(self):
        cfg = CholinvConfig(base_case_dim=16, split=3)
        root = plan(128, cfg)
        assert root.top[0].n == 16 and root.top[1].n == 112


class TestFactor:
    @pytest.mark.parametrize("gridname", ["grid2x2x1", "grid2x2x2"])
    @pytest.mark.parametrize("n,bc", [(64, 16), (128, 32)])
    def test_residual_and_inverse(self, request, gridname, n, bc):
        grid = request.getfixturevalue(gridname)
        A = _spd(n)
        cfg = CholinvConfig(base_case_dim=bc, complete_inv=True)
        R, Rinv = jax.jit(lambda a: cholesky.factor(grid, a, cfg))(_put(grid, A))
        assert residual.cholesky_residual(A, R) < 1e-14
        assert residual.cholesky_inverse_residual(R, Rinv) < 1e-13
        # R matches the textbook factor
        np.testing.assert_allclose(
            np.asarray(R), np.linalg.cholesky(np.asarray(A)).T, rtol=1e-10, atol=1e-12
        )

    def test_non_power_of_two_padding(self, grid2x2x1):
        A = _spd(100)
        cfg = CholinvConfig(base_case_dim=32)
        R, Rinv = cholesky.factor(grid2x2x1, A, cfg)
        assert R.shape == (100, 100)
        assert residual.cholesky_residual(A, R) < 1e-14
        assert residual.cholesky_inverse_residual(R, Rinv) < 1e-13

    def test_single_window_base_case(self, grid2x2x1):
        A = _spd(24)
        cfg = CholinvConfig(base_case_dim=64)
        R, _ = cholesky.factor(grid2x2x1, A, cfg)
        assert residual.cholesky_residual(A, R) < 1e-14

    def test_incomplete_inv_leaves_offdiag_zero(self, grid2x2x1):
        A = _spd(64)
        cfg = CholinvConfig(base_case_dim=16, complete_inv=False)
        R, Rinv = cholesky.factor(grid2x2x1, A, cfg)
        assert residual.cholesky_residual(A, R) < 1e-14
        Ri = np.asarray(Rinv)
        np.testing.assert_array_equal(Ri[:32, 32:], 0.0)
        # diagonal blocks are exact inverses of the diagonal blocks of R
        for sl in (slice(0, 32), slice(32, 64)):
            blk = np.asarray(R)[sl, sl]
            np.testing.assert_allclose(blk @ Ri[sl, sl], np.eye(32), atol=1e-12)

    def test_balanced_schedule_matches_block(self, grid2x2x1):
        # balance='tile_cyclic' with a tiny threshold forces the balanced
        # trmm/syrk schedules at every window — results must agree with the
        # block schedule to reduction-order roundoff, and the recorder's
        # max-per-process column must actually DROP
        from capital_tpu.utils import tracing

        g = grid2x2x1
        A = jax.device_put(_spd(128), g.face_sharding())
        block = CholinvConfig(base_case_dim=32, mode="explicit")
        bal = CholinvConfig(
            base_case_dim=32, mode="explicit",
            balance="tile_cyclic", balance_min_window=32,
        )
        with tracing.Recorder() as rb:
            Rb, RIb = jax.jit(lambda a: cholesky.factor(g, a, block))(A)
        with tracing.Recorder() as rc:
            Rc, RIc = jax.jit(lambda a: cholesky.factor(g, a, bal))(A)
        np.testing.assert_allclose(np.asarray(Rc), np.asarray(Rb), atol=1e-12)
        np.testing.assert_allclose(np.asarray(RIc), np.asarray(RIb), atol=1e-11)
        assert residual.cholesky_residual(A, Rc) < 1e-14
        # the balanced schedule's critical path is strictly below block's
        # on the trsm phase (max-per-process view)
        assert (
            rc.stats["CI::trsm"].flops_max < rb.stats["CI::trsm"].flops_max
        )
        assert rc.stats["CI::tmu"].flops_max < rb.stats["CI::tmu"].flops_max
        # combined with the in-place Schur memory mode (the flagship's
        # pairing at scale): same results again
        both = CholinvConfig(
            base_case_dim=32, mode="explicit",
            balance="tile_cyclic", balance_min_window=32,
            schur_in_place=True,
        )
        R2, RI2 = jax.jit(lambda a: cholesky.factor(g, a, both))(A)
        np.testing.assert_allclose(np.asarray(R2), np.asarray(Rb), atol=1e-12)
        np.testing.assert_allclose(np.asarray(RI2), np.asarray(RIb), atol=1e-11)
        # invalid knob values raise instead of silently running block
        with pytest.raises(ValueError, match="balance"):
            cholesky.factor(g, A, CholinvConfig(balance="cyclic"))
        with pytest.raises(ValueError, match="explicit"):
            cholesky.factor(
                g, A, CholinvConfig(balance="tile_cyclic", mode="xla")
            )

    @pytest.mark.slow  # heaviest tier-1 test (~34s on the 1-core rig);
    # the persistent layout keeps cheap coverage via test_summa's
    # persistent in-place schedules and the multichip dryrun face
    def test_persistent_layout_matches_block(self, grid2x2x1):
        # balance='tile_cyclic_persistent': ONE symmetric tile-cyclic
        # permute at entry, every recursion window read/written through
        # chunk-local reshapes, un-permute at exit — vs 'tile_cyclic'
        # paying 2-3 row shuffles inside every trmm/syrk call.  Results
        # must match the block schedule to reduction-order roundoff on
        # both the aligned and the padded/cropped (n=100) paths.
        from capital_tpu.utils import tracing

        g = grid2x2x1
        for n, sip in ((128, False), (128, True), (100, False)):
            A = jax.device_put(_spd(n), g.face_sharding())
            block = CholinvConfig(
                base_case_dim=16, mode="explicit", complete_inv=True,
                schur_in_place=sip,
            )
            pers = CholinvConfig(
                base_case_dim=16, mode="explicit", complete_inv=True,
                schur_in_place=sip, balance="tile_cyclic_persistent",
            )
            Rb, RIb = jax.jit(lambda a: cholesky.factor(g, a, block))(A)
            with tracing.Recorder() as rec:
                Rp, RIp = jax.jit(lambda a: cholesky.factor(g, a, pers))(A)
            assert "cholinv::persistent_fallback" not in rec.stats, (n, sip)
            assert "syrk::persistent_cyclic" in rec.stats, sorted(rec.stats)
            np.testing.assert_allclose(np.asarray(Rp), np.asarray(Rb), atol=1e-11)
            np.testing.assert_allclose(np.asarray(RIp), np.asarray(RIb), atol=1e-10)
            assert residual.cholesky_residual(A, Rp) < 1e-14
            assert residual.cholesky_inverse_residual(Rp, RIp) < 1e-13

    def test_persistent_ineligible_falls_back_with_note(self, grid2x2x2):
        # the cholinv ENTRY is where persistent eligibility is decided
        # (before any buffer is permuted), so unlike summa's raising
        # storage contract a c=2 / misaligned topology falls back to the
        # block schedule — with a note, never silently
        from capital_tpu.utils import tracing

        g = grid2x2x2
        A = jax.device_put(_spd(64), g.face_sharding())
        cfg = CholinvConfig(
            base_case_dim=16, mode="explicit",
            balance="tile_cyclic_persistent",
        )
        with tracing.Recorder() as rec:
            R, _ = jax.jit(lambda a: cholesky.factor(g, a, cfg))(A)
        assert rec.stats["cholinv::persistent_fallback"].calls >= 1
        assert "syrk::persistent_cyclic" not in rec.stats
        assert residual.cholesky_residual(A, R) < 1e-14

    @pytest.mark.parametrize("split", [1, 2])
    @pytest.mark.parametrize("mode", ["xla", "explicit"])
    def test_split_and_mode_knobs(self, grid2x2x2, split, mode):
        A = _spd(64)
        cfg = CholinvConfig(base_case_dim=16, split=split, mode=mode)
        R, Rinv = cholesky.factor(grid2x2x2, _put(grid2x2x2, A), cfg)
        assert residual.cholesky_residual(A, R) < 1e-14
        assert residual.cholesky_inverse_residual(R, Rinv) < 1e-13

    @pytest.mark.parametrize("policy", list(BaseCasePolicy))
    def test_policies(self, grid2x2x1, policy):
        A = _spd(64)
        cfg = CholinvConfig(base_case_dim=32, policy=policy)
        R, _ = cholesky.factor(grid2x2x1, A, cfg)
        assert residual.cholesky_residual(A, R) < 1e-14

    def test_policy_schedules_differ(self, grid2x2x2):
        # VERDICT r1 weak #6: the four policies must be distinct schedules,
        # not aliases.  Root/layer compute emits a guarded factorization
        # (conditional in HLO) + result-broadcast psums; the all-compute
        # default does neither.  Results must agree exactly (psum of one
        # masked value is exact).
        g = grid2x2x2
        A = jax.device_put(_spd(64), g.face_sharding())

        def lowered(policy):
            cfg = CholinvConfig(base_case_dim=32, policy=policy, mode="xla")
            return (
                jax.jit(lambda a: cholesky.factor(g, a, cfg))
                .lower(A)
                .compile()
                .as_text()
            )

        assert "conditional" not in lowered(BaseCasePolicy.REPLICATE_COMM_COMP)
        assert "conditional" in lowered(BaseCasePolicy.NO_REPLICATION)
        assert "conditional" in lowered(BaseCasePolicy.REPLICATE_COMP)

        outs = {}
        for pol in BaseCasePolicy:
            cfg = CholinvConfig(base_case_dim=32, policy=pol, mode="xla")
            R, Rinv = jax.jit(lambda a, cfg=cfg: cholesky.factor(g, a, cfg))(A)
            outs[pol] = (np.asarray(R), np.asarray(Rinv))
            assert residual.cholesky_residual(A, R) < 1e-14
        ref = outs[BaseCasePolicy.REPLICATE_COMM_COMP]
        for pol, (R, Rinv) in outs.items():
            np.testing.assert_allclose(R, ref[0], atol=1e-13)
            np.testing.assert_allclose(Rinv, ref[1], atol=1e-13)

    def test_spd_inverse(self, grid2x2x1):
        A = _spd(64)
        Ainv = cholesky.spd_inverse(grid2x2x1, A, CholinvConfig(base_case_dim=16))
        assert residual.inverse_residual(A, Ainv) < 1e-12

    def test_bf16_input_uses_f32_base_case(self, grid2x2x1):
        A = _spd(64).astype(jnp.bfloat16)
        cfg = CholinvConfig(base_case_dim=16)
        R, _ = cholesky.factor(grid2x2x1, A, cfg)
        assert R.dtype == jnp.bfloat16
        # loose gate: bf16 storage, f32 base case keeps things sane
        res = residual.cholesky_residual(A.astype(jnp.float64), R.astype(jnp.float64))
        assert res < 0.05


class TestReviewRegressions:
    def test_split_zero_raises(self, grid2x2x1):
        from capital_tpu.models.cholesky import top_split

        with pytest.raises(ValueError):
            plan(128, CholinvConfig(base_case_dim=32, split=0))
        # top_split agrees with the plan used by factor
        cfg = CholinvConfig(base_case_dim=32, split=1)
        assert top_split(128, cfg) == 64
        assert top_split(100, cfg) == 64  # padded to 128, split at 64
        assert top_split(24, cfg) == 24  # single base-case window


@pytest.mark.slow  # ~27s of plan compiles on the 1-core rig; the
# structural gate itself is trace-time, so the full (unmarked) suite
# still trips it
def test_zeros_fast_path_gated_on_leaf_alignment(monkeypatch):
    """split>=2 plans produce leaves smaller than the zero-fill tile; the
    dead-lower fast path must fall back to full jnp.zeros there or real
    hardware gets garbage below the diagonal (invisible on CPU interpret,
    which zero-fills unvisited blocks — hence this structural assertion)."""
    from capital_tpu.models import cholesky as chol
    from capital_tpu.ops import pallas_tpu

    calls = []
    real = pallas_tpu.zeros_dead_lower

    def spy(p, dtype, tile, extra=(), interpret=None):
        calls.append(tile)
        return real(p, dtype, tile, extra=extra, interpret=interpret)

    monkeypatch.setattr(pallas_tpu, "zeros_dead_lower", spy)
    import jax
    from capital_tpu.parallel.topology import Grid

    grid1 = Grid.square(c=1, devices=jax.devices()[:1])
    A = jnp.asarray(rand48.symmetric(512, dtype=jnp.float64))

    # aligned plan (split=1, bc=128): fast path taken
    cfg = chol.CholinvConfig(base_case_dim=128, split=1, mode="pallas")
    chol.factor(grid1, A, cfg)
    assert calls, "aligned plan should use the dead-lower fast path"

    # misaligned plan (split=2 -> 128-wide leaves at non-tile offsets for
    # bc=256): must NOT use the fast path
    calls.clear()
    cfg = chol.CholinvConfig(base_case_dim=256, split=2, mode="pallas")
    node = chol.plan(chol.padded_dim(2048, 256), cfg)

    def leaves(nd):
        return [nd] if nd.is_base else leaves(nd.top[0]) + leaves(nd.top[1])

    if any(lf.n % 256 or lf.off % 256 for lf in leaves(node)):
        A2 = jnp.asarray(rand48.symmetric(2048, dtype=jnp.float64))
        chol.factor(grid1, A2, cfg)
        assert not calls, "misaligned leaves must fall back to jnp.zeros"
