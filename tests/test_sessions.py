"""Streaming state-space session tests (PR 19 acceptance).

The properties pinned here, mapped to the issue's criteria:

* ``models/blocktri.contract`` is a PURE SLICE of the factor, bitwise
  equal to refactoring the truncated chain (extend-replay from the
  retained carry) across (nblocks, b) ladders and both impls, and the
  contracted factor answers for the MARGINALIZED window matrix — head
  diagonal L_k·L_kᵀ, head coupling zero (TestContract);
* extend-after-contract roundtrips: sliding the window never perturbs
  the surviving factor blocks, so append-then-contract and
  contract-then-append land bitwise-identical state (TestContract);
* the serve protocol end to end: open / append / solve (all three
  accuracy tiers, residuals against the window mirror's dense assembly)
  / contract / close through a real SolveEngine, steady-state cycles at
  zero recompiles, and whole-chain pivot bookkeeping under breakdown —
  a flagged append leaves the resident chain untouched and
  ``absolute_pivot`` maps the segment-relative info to whole-stream
  coordinates, contracted blocks included (TestSessionProtocol);
* eviction is tombstone-LOUD: cache pressure converts the next session
  request into the typed SessionEvicted raise, drops the local mirror,
  and re-open is the one sanctioned reseed path (TestEviction);
* FactorCache stats carry the per-entry byte ledger and the
  power-of-two eviction-age histogram on the deterministic op clock,
  with `born` preserved across overwrites (TestFactorCacheStats);
* serve:session_stats records validate (accept + reject seams) and
  `obs serve-report --min-session-hit-rate / --max-reseeds` gate them,
  failing LOUDLY when no record carries the block (TestSessionLedger,
  TestServeReportGates);
* session-sticky routing: the affinity token dominates the bucket
  signature, and rendezvous hashing remaps ONLY the dead replica's
  sessions on membership change (TestAffinityRouting).

Runs on the conftest CPU rig (x64 on).  Engine tests keep blocks tiny
(b=4) so every executable compiles in well under a second; the long
contract ladder is slow-marked.
"""

import re

import jax.numpy as jnp
import numpy as np
import pytest

from capital_tpu.models import blocktri
from capital_tpu.obs import __main__ as obs_main
from capital_tpu.obs import ledger
from capital_tpu.serve import ServeConfig, SolveEngine
from capital_tpu.serve import router as router_mod
from capital_tpu.serve.factorcache import FactorCache
from capital_tpu.serve.sessions import SessionEvicted, SessionManager

S_CFG = ServeConfig(
    buckets=(8,),
    rows_buckets=(32,),
    nrhs_buckets=(2,),
    max_batch=2,
    max_delay_s=10.0,
    nblocks_buckets=(2, 4),
    block_buckets=(4,),
)


def _chain(rng, nblocks, b, dtype=np.float64, live_head=False):
    """One unbatched SPD window (the session wire shape): gram/b + 3I
    diagonals, 0.3/sqrt(b) couplings.  `live_head` keeps C[0] — the
    append segment contract (it couples into the previous window)."""
    G = rng.standard_normal((nblocks, b, b))
    D = G @ G.transpose(0, 2, 1) / b + 3.0 * np.eye(b)
    C = 0.3 / np.sqrt(b) * rng.standard_normal((nblocks, b, b))
    if not live_head:
        C[0] = 0.0
    return D.astype(dtype), C.astype(dtype)


def _np_dense(D, C):
    """NumPy-side dense assembly of one window — independent of the code
    under test (the test_blocktri discipline)."""
    nblocks, b = D.shape[0], D.shape[1]
    n = nblocks * b
    A = np.zeros((n, n), dtype=np.float64)
    for i in range(nblocks):
        sl = slice(i * b, (i + 1) * b)
        A[sl, sl] = D[i]
        if i:
            up = slice((i - 1) * b, i * b)
            A[sl, up] = C[i]
            A[up, sl] = C[i].T
    return A


def _mgr(cfg=S_CFG):
    eng = SolveEngine(cfg=cfg)
    return eng, SessionManager(eng)


# ---------------------------------------------------------------------------
# models/blocktri.contract: pure slice, bitwise replay, marginal window
# ---------------------------------------------------------------------------


class TestContract:
    @pytest.mark.parametrize("nblocks,b,k", [(4, 4, 1), (6, 4, 2),
                                             (4, 8, 3)])
    @pytest.mark.parametrize("impl,dtype", [("xla", np.float64),
                                            ("pallas", np.float32)])
    def test_bitwise_vs_truncated_refactor(self, nblocks, b, k, impl,
                                           dtype):
        # the contract docstring's claim, both impls: re-extending the
        # truncated chain — head coupling LIVE, carried from the retained
        # L_{k-1} — reproduces every block the contract kept, bit for bit
        rng = np.random.default_rng(40)
        D, C = _chain(rng, nblocks, b, dtype=dtype)
        Dj, Cj = jnp.asarray(D)[None], jnp.asarray(C)[None]
        L, Wt, info = blocktri.factor(Dj, Cj, impl=impl)
        assert int(info[0]) == 0
        Lc, Wtc = blocktri.contract(L, Wt, k)
        Lr, Wtr, infor = blocktri.extend(Dj[:, k:], Cj[:, k:],
                                         L[:, k - 1], impl=impl)
        assert int(infor[0]) == 0
        np.testing.assert_array_equal(np.asarray(Lr), np.asarray(Lc))
        np.testing.assert_array_equal(np.asarray(Wtr), np.asarray(Wtc))

    def test_contract_is_pure_slice(self):
        rng = np.random.default_rng(41)
        D, C = _chain(rng, 5, 4)
        L, Wt, _ = blocktri.factor(jnp.asarray(D)[None],
                                   jnp.asarray(C)[None], impl="xla")
        Lc, Wtc = blocktri.contract(L, Wt, 2)
        np.testing.assert_array_equal(np.asarray(Lc), np.asarray(L)[:, 2:])
        np.testing.assert_array_equal(np.asarray(Wtc),
                                      np.asarray(Wt)[:, 2:])

    def test_contract_k_validation(self):
        L = jnp.zeros((1, 4, 3, 3))
        for k in (4, 5, -1):
            with pytest.raises(ValueError, match="contract"):
                blocktri.contract(L, L, k)
        # k=0 is the identity slide — allowed, returns the factor as-is
        Lc, Wtc = blocktri.contract(L, L, 0)
        assert Lc.shape == L.shape and Wtc.shape == L.shape

    def test_contracted_factor_solves_marginal_window(self):
        # the bookkeeping every session client must do at slide time:
        # the contracted factor answers for the MARGINALIZED window —
        # head diagonal L_k·L_kᵀ, head coupling zero — NOT the original
        # trailing window (which still couples into dropped blocks)
        rng = np.random.default_rng(42)
        nblocks, b, k, nrhs = 5, 4, 2, 3
        D, C = _chain(rng, nblocks, b)
        L, Wt, _ = blocktri.factor(jnp.asarray(D)[None],
                                   jnp.asarray(C)[None], impl="xla")
        Lc, Wtc = blocktri.contract(L, Wt, k)
        Dw, Cw = D[k:].copy(), C[k:].copy()
        Lk = np.asarray(L)[0, k]
        Dw[0] = Lk @ Lk.T
        Cw[0] = 0.0
        B = rng.standard_normal((nblocks - k, b, nrhs))
        X = blocktri.solve(Lc, Wtc, jnp.asarray(B)[None], impl="xla")
        n = (nblocks - k) * b
        ref = np.linalg.solve(_np_dense(Dw, Cw), B.reshape(n, nrhs))
        np.testing.assert_allclose(np.asarray(X)[0].reshape(n, nrhs),
                                   ref, rtol=0, atol=1e-11)

    def test_extend_after_contract_roundtrip(self):
        # sliding never perturbs survivors: extending the CONTRACTED
        # factor and contracting the EXTENDED factor land bitwise on the
        # same state (both orders append from the identical carry)
        rng = np.random.default_rng(43)
        nblocks, b, k, m = 4, 4, 2, 2
        D, C = _chain(rng, nblocks, b)
        Dm, Cm = _chain(rng, m, b, live_head=True)
        Dj, Cj = jnp.asarray(D)[None], jnp.asarray(C)[None]
        Dmj, Cmj = jnp.asarray(Dm)[None], jnp.asarray(Cm)[None]
        L, Wt, _ = blocktri.factor(Dj, Cj, impl="xla")
        Lx, Wtx, info = blocktri.extend(Dmj, Cmj, L[:, -1], impl="xla")
        assert int(info[0]) == 0
        # contract-then-extend
        Lc, Wtc = blocktri.contract(L, Wt, k)
        a_L = np.concatenate([np.asarray(Lc), np.asarray(Lx)], axis=1)
        a_Wt = np.concatenate([np.asarray(Wtc), np.asarray(Wtx)], axis=1)
        # extend-then-contract
        Lf = jnp.concatenate([L, Lx], axis=1)
        Wtf = jnp.concatenate([Wt, Wtx], axis=1)
        b_L, b_Wt = blocktri.contract(Lf, Wtf, k)
        np.testing.assert_array_equal(a_L, np.asarray(b_L))
        np.testing.assert_array_equal(a_Wt, np.asarray(b_Wt))

    @pytest.mark.slow
    def test_contract_ladder_long_chain(self):
        # nblocks=64 with repeated slides — the flagship bench geometry
        # shape family (excluded from tier-1; `make bench-session` gates
        # the wall-clock half)
        rng = np.random.default_rng(44)
        D, C = _chain(rng, 64, 8)
        Dj, Cj = jnp.asarray(D)[None], jnp.asarray(C)[None]
        L, Wt, _ = blocktri.factor(Dj, Cj, impl="xla")
        for k in (1, 8, 16):
            Lc, Wtc = blocktri.contract(L, Wt, k)
            Lr, Wtr, info = blocktri.extend(Dj[:, k:], Cj[:, k:],
                                            L[:, k - 1], impl="xla")
            assert int(info[0]) == 0
            np.testing.assert_array_equal(np.asarray(Lr), np.asarray(Lc))
            np.testing.assert_array_equal(np.asarray(Wtr),
                                          np.asarray(Wtc))


# ---------------------------------------------------------------------------
# serve protocol end to end
# ---------------------------------------------------------------------------


class TestSessionProtocol:
    def test_lifecycle_residuals_all_tiers(self):
        rng = np.random.default_rng(50)
        eng, mgr = _mgr()
        nblocks, b, nrhs = 4, 4, 2
        D, C = _chain(rng, nblocks, b)
        assert mgr.open("s", D, C).ok

        def check(tier, tol):
            Dw, Cw = mgr.window("s")
            B = rng.standard_normal((Dw.shape[0], b, nrhs))
            r = mgr.solve("s", B, accuracy_tier=tier)
            assert r.ok, r.error
            n = Dw.shape[0] * b
            ref = np.linalg.solve(_np_dense(Dw, Cw), B.reshape(n, nrhs))
            err = np.abs(np.float64(np.asarray(r.x)).reshape(n, nrhs)
                         - ref).max()
            assert err < tol * np.abs(ref).max(), (tier, err)

        for tier, tol in (("balanced", 1e-9), ("guaranteed", 1e-9),
                          ("fast", 5e-4)):
            check(tier, tol)
        # slide: contract 2, solve the marginalized 2-window, append 2
        # back to a 4-window, solve again — the full steady-state cycle
        assert mgr.contract("s", 2).ok
        check("balanced", 1e-9)
        Da, Ca = _chain(rng, 2, b, live_head=True)
        assert mgr.append("s", Da, Ca).ok
        check("balanced", 1e-9)
        check("guaranteed", 1e-9)
        r = mgr.close("s")
        assert r.ok and int(np.asarray(r.x)) == 1
        assert not mgr.is_open("s")
        st = mgr.stats()
        assert st["misses"] == 0 and st["hit_rate"] == 1.0
        assert st["blocks_appended"] == 6 and st["blocks_dropped"] == 2

    def test_steady_state_cycles_zero_recompile(self):
        # session residency is host-side state keyed by session id:
        # after the first full cycle compiles its two programs, further
        # cycles — and brand-new sessions — must never compile again
        rng = np.random.default_rng(51)
        eng, mgr = _mgr()
        nblocks, b, nrhs = 4, 4, 2

        def cycle(sid):
            Da, Ca = _chain(rng, 2, b, live_head=True)
            assert mgr.append(sid, Da, Ca).ok
            assert mgr.contract(sid, 2).ok
            B = rng.standard_normal((nblocks, b, nrhs))
            assert mgr.solve(sid, B).ok

        D, C = _chain(rng, nblocks, b)
        assert mgr.open("s1", D, C).ok
        cycle("s1")
        c0 = eng.cache_stats()["compiles"]
        for _ in range(3):
            cycle("s1")
        D, C = _chain(rng, nblocks, b)
        assert mgr.open("s2", D, C).ok
        cycle("s2")
        assert eng.cache_stats()["compiles"] == c0

    def test_pivot_offset_bookkeeping_under_breakdown(self):
        # a flagged append fails LOUDLY, leaves the resident chain AND
        # the window mirror untouched, and reports a segment-relative
        # pivot the manager maps to whole-chain coordinates — contracted
        # blocks included
        rng = np.random.default_rng(52)
        eng, mgr = _mgr()
        b = 4
        D, C = _chain(rng, 2, b)
        assert mgr.open("s", D, C).ok
        assert mgr.segment_offset("s") == 2 * b
        # poison the SECOND appended block: clean negative diagonal,
        # zeroed incoming coupling, so its Schur complement is the block
        Da, Ca = _chain(rng, 2, b, live_head=True)
        Da[1] = np.diag([1.0, 1.0, -5.0, 1.0])
        Ca[1] = 0.0
        r = mgr.append("s", Da, Ca)
        assert not r.ok
        assert "flagged breakdown" in r.error
        assert "left unchanged" in r.error
        local = int(re.search(r"info=(\d+)", r.error).group(1))
        # the xla scan is block-exact: the pivot lands inside appended
        # block 1 (1-based local rows b+1 .. 2b)
        assert b + 1 <= local <= 2 * b
        assert 3 * b + 1 <= mgr.absolute_pivot("s", local) <= 4 * b
        # resident chain unchanged: the window did not grow, solves work
        Dw, _ = mgr.window("s")
        assert Dw.shape[0] == 2
        assert mgr.segment_offset("s") == 2 * b
        assert mgr.solve("s", rng.standard_normal((2, b, 2))).ok
        # contract slides the window but NOT the stream position of the
        # tail: segment_offset counts every block ever streamed
        assert mgr.contract("s", 1).ok
        assert mgr.pivot_offset("s") == b
        assert mgr.segment_offset("s") == 2 * b
        st = mgr.stats()
        assert st["failures"] == 1 and st["evicted_failures"] == 0

    def test_append_before_open_fails(self):
        rng = np.random.default_rng(53)
        eng, mgr = _mgr()
        with pytest.raises(KeyError, match="not open"):
            mgr.append("ghost", *_chain(rng, 2, 4))
        with pytest.raises(KeyError, match="not open"):
            mgr.solve("ghost", np.zeros((2, 4, 2)))
        # engine-level: a never-opened token is 'not open', NOT a silent
        # fresh-start (and points at the protocol docs)
        D, C = _chain(rng, 2, 4)
        r = eng.solve("session_append", np.stack([D, C]),
                      factor_token="ghost")
        assert not r.ok and "not open" in r.error
        assert "SERVING.md" in r.error

    def test_window_shape_validation(self):
        rng = np.random.default_rng(54)
        eng, mgr = _mgr()
        D, C = _chain(rng, 2, 4)
        with pytest.raises(ValueError, match="ride"):
            mgr.open("s", D, C[:1])
        assert mgr.open("s", D, C).ok
        with pytest.raises(ValueError, match="block size"):
            mgr.append("s", *_chain(rng, 2, 8))
        with pytest.raises(ValueError, match="nblocks"):
            mgr.solve("s", np.zeros((3, 4, 2)))
        with pytest.raises(ValueError, match="contract"):
            mgr.contract("s", 2)


# ---------------------------------------------------------------------------
# eviction: tombstone-loud, typed raise, reseed path
# ---------------------------------------------------------------------------


class TestEviction:
    def test_evicted_session_raises_and_reseeds(self):
        rng = np.random.default_rng(60)
        # budget fits ONE 4-block session entry (L + Wt + carry =
        # (2·4·16 + 16) f64 elements = 1152 bytes) but not two
        cfg = ServeConfig(
            buckets=S_CFG.buckets, rows_buckets=S_CFG.rows_buckets,
            nrhs_buckets=S_CFG.nrhs_buckets, max_batch=S_CFG.max_batch,
            max_delay_s=S_CFG.max_delay_s,
            nblocks_buckets=S_CFG.nblocks_buckets,
            block_buckets=S_CFG.block_buckets,
            factor_cache_bytes=2000,
        )
        eng, mgr = _mgr(cfg)
        b = 4
        D1, C1 = _chain(rng, 4, b)
        D2, C2 = _chain(rng, 4, b)
        assert mgr.open("s1", D1, C1).ok
        assert mgr.open("s2", D2, C2).ok     # evicts s1 under the budget
        B = rng.standard_normal((4, b, 2))
        with pytest.raises(SessionEvicted, match="re-seed") as ei:
            mgr.solve("s1", B)
        assert ei.value.sid == "s1"
        # the mirror is gone with the resident state
        assert not mgr.is_open("s1")
        with pytest.raises(KeyError):
            mgr.solve("s1", B)
        st = mgr.stats()
        assert st["evicted_failures"] == 1 and st["misses"] == 1
        assert st["hit_rate"] < 1.0
        # re-open is the sanctioned reseed: clears the tombstone, counts
        # as a reseed, and the session serves again
        assert mgr.open("s1", D1, C1).ok
        assert mgr.stats()["reseeds"] == 1
        r = mgr.solve("s1", B)
        assert r.ok
        n = 4 * b
        ref = np.linalg.solve(_np_dense(D1, np.where(
            np.arange(4)[:, None, None] == 0, 0.0, C1)),
            B.reshape(n, 2))
        np.testing.assert_allclose(
            np.float64(np.asarray(r.x)).reshape(n, 2), ref,
            rtol=0, atol=1e-9)
        assert ledger.validate_session_stats(mgr.stats()) == []


# ---------------------------------------------------------------------------
# FactorCache stats: per-entry bytes + eviction-age histogram
# ---------------------------------------------------------------------------


class TestFactorCacheStats:
    def _arrays(self, n=4):
        return (jnp.zeros((n, n), jnp.float64),)

    def test_entry_bytes_ledger(self):
        fc = FactorCache(budget_bytes=1 << 20)
        fc.put("a", "chol", self._arrays(4), {})
        fc.put("b", "chol", self._arrays(8), {})
        s = fc.stats()
        assert s["entry_bytes"] == {"a": 4 * 4 * 8, "b": 8 * 8 * 8}
        assert s["bytes"] == sum(s["entry_bytes"].values())
        assert s["entries"] == 2

    def test_eviction_age_histogram_on_op_clock(self):
        # ages are cache OPERATIONS, not wall time: deterministic under
        # replay.  Entry 'a' survives 4 lookups + 1 put before eviction
        # (age 6 -> power-of-two bucket '8'); validator cross-checks the
        # histogram sum against the eviction counter
        fc = FactorCache(budget_bytes=200)
        fc.put("a", "chol", self._arrays(4), {})       # 128 bytes, clock 1
        for _ in range(4):
            assert fc.lookup("a") is not None          # clock 2..5
        evicted = fc.put("b", "chol", self._arrays(4), {})  # clock 6
        assert evicted == ["a"]
        s = fc.stats()
        assert s["eviction_age_hist"] == {"8": 1}
        assert sum(s["eviction_age_hist"].values()) == s["evictions"]
        assert fc.evicted("a")

    def test_born_preserved_across_overwrite(self):
        # overwriting a resident token refreshes arrays, NOT age: the
        # entry's eviction age keeps counting from first install (an
        # overwrite-heavy session would otherwise always look young)
        fc = FactorCache(budget_bytes=1 << 20)
        fc.put("a", "chol", self._arrays(4), {})
        born0 = fc.peek("a").born
        fc.lookup("a")
        fc.put("a", "chol", self._arrays(4), {})
        assert fc.peek("a").born == born0

    @staticmethod
    def _fc_probs(eng, fc_stats):
        # the factor_cache block validates inside its request_stats
        # carrier (ledger.validate_request_stats) — swap the block into
        # a real engine snapshot and filter its problems
        snap = eng.emit_stats()["request_stats"]
        snap["factor_cache"] = fc_stats
        return [p for p in ledger.validate_request_stats(snap)
                if "factor_cache" in p]

    def test_stats_block_validates_in_request_stats(self):
        eng = SolveEngine(cfg=S_CFG)
        fc = FactorCache(budget_bytes=200)
        fc.put("a", "session", self._arrays(4), {})
        fc.lookup("a")
        fc.put("b", "session", self._arrays(4), {})
        assert self._fc_probs(eng, fc.stats()) == []
        # reject seams: byte ledger out of sync with the pool total,
        # histogram out of sync with the eviction counter
        s = fc.stats()
        s["entry_bytes"]["b"] += 8
        assert any("entry_bytes" in p for p in self._fc_probs(eng, s))
        s = fc.stats()
        s["eviction_age_hist"]["8"] = (
            s["eviction_age_hist"].get("8", 0) + 1)
        assert any("eviction_age_hist" in p
                   for p in self._fc_probs(eng, s))


# ---------------------------------------------------------------------------
# ledger seam: serve:session_stats accept/reject + serve-report gates
# ---------------------------------------------------------------------------


def _session_stats(**over):
    s = {"schema_version": 1, "opens": 2, "reseeds": 0, "appends": 3,
         "solves": 4, "contracts": 2, "closes": 1, "failures": 0,
         "evicted_failures": 0, "hits": 9, "misses": 0, "hit_rate": 1.0,
         "sessions_open": 1, "sessions_known": 2, "blocks_appended": 10,
         "blocks_dropped": 4}
    s.update(over)
    return s


class TestSessionLedger:
    def test_valid_block_accepts_and_diffs(self):
        assert ledger.validate_session_stats(_session_stats()) == []
        rec = ledger.record("serve:session_stats", ledger.manifest(),
                            session_stats=_session_stats())
        assert ledger.diff([rec], [rec]) == []

    @pytest.mark.parametrize("over,needle", [
        ({"hit_rate": 1.5}, "hit_rate"),
        ({"hits": -1}, "hits"),
        ({"misses": 2}, "misses"),                 # != evicted_failures
        ({"reseeds": 3}, "reseeds"),               # > opens
        ({"sessions_open": 5}, "sessions_open"),   # > sessions_known
        ({"blocks_dropped": 99}, "blocks_dropped"),
        ({"schema_version": 0}, "schema"),
        ({"opens": "two"}, "opens"),
    ])
    def test_reject_seams(self, over, needle):
        probs = ledger.validate_session_stats(_session_stats(**over))
        assert any(needle in p for p in probs), probs

    def test_malformed_record_is_incompatible(self):
        rec = ledger.record("serve:session_stats", ledger.manifest(),
                            session_stats=_session_stats(hit_rate=2.0))
        with pytest.raises(ledger.LedgerIncompatible,
                           match="session_stats"):
            ledger.diff([rec], [rec])


class TestServeReportGates:
    def _write(self, path, stats):
        ledger.append(str(path), ledger.record(
            "serve:session_stats", ledger.manifest(),
            session_stats=stats))

    def test_gates_pass_and_fail(self, tmp_path, capsys):
        good = tmp_path / "good.jsonl"
        self._write(good, _session_stats())
        assert obs_main.main([
            "serve-report", str(good),
            "--min-session-hit-rate", "0.85", "--max-reseeds", "0"]) == 0
        assert "session[0]" in capsys.readouterr().out
        # a cold ledger: 2 of 6 resident requests lost their factor
        bad = tmp_path / "bad.jsonl"
        self._write(bad, _session_stats(
            reseeds=2, hits=4, misses=2, evicted_failures=2,
            hit_rate=4 / 6))
        assert obs_main.main([
            "serve-report", str(bad),
            "--min-session-hit-rate", "0.85"]) == 1
        assert "session hit_rate" in capsys.readouterr().err
        assert obs_main.main([
            "serve-report", str(bad), "--max-reseeds", "1"]) == 1
        assert "reseed" in capsys.readouterr().err
        assert obs_main.main([
            "serve-report", str(bad), "--max-reseeds", "2"]) == 0

    def test_malformed_record_exits_2(self, tmp_path):
        path = tmp_path / "mal.jsonl"
        self._write(path, _session_stats(hit_rate=2.0))
        assert obs_main.main(["serve-report", str(path)]) == 2

    def test_dead_gate_fails_loudly(self, tmp_path, capsys):
        # gates requested against a ledger with serve records but NO
        # session_stats block: a gate nothing exercised must fail
        eng = SolveEngine(cfg=S_CFG)
        path = tmp_path / "nosession.jsonl"
        eng.emit_stats(str(path))
        assert obs_main.main([
            "serve-report", str(path),
            "--min-session-hit-rate", "0.85"]) == 1
        assert "no record carries a session_stats block" in (
            capsys.readouterr().err)
        # and the all-gates-no-records posture still holds
        empty = tmp_path / "empty.jsonl"
        empty.write_text("")
        assert obs_main.main([
            "serve-report", str(empty), "--max-reseeds", "0"]) == 1


# ---------------------------------------------------------------------------
# session-sticky routing: affinity signature + rendezvous remap locality
# ---------------------------------------------------------------------------


class TestAffinityRouting:
    LADDERS = {"buckets": (8,), "rows_buckets": (32,),
               "nrhs_buckets": (2,), "nblocks_buckets": (2, 4),
               "block_buckets": (4,)}

    def test_affinity_token_dominates_signature(self):
        # every request of one session lands on one replica regardless
        # of op or shape — the resident factor lives on exactly one
        # engine, so shape-class affinity would scatter the session
        s1 = router_mod.bucket_signature(
            "session_solve", (2, 4, 4, 4), (4, 4, 2), "float64",
            self.LADDERS, affinity="sess-1")
        s2 = router_mod.bucket_signature(
            "session_append", (2, 2, 4, 4), None, "float64",
            self.LADDERS, tier="guaranteed", affinity="sess-1")
        assert s1 == s2 == ("affinity", "sess-1")
        s3 = router_mod.bucket_signature(
            "session_solve", (2, 4, 4, 4), (4, 4, 2), "float64",
            self.LADDERS, affinity="sess-2")
        assert s3 != s1
        # without affinity the signature is the shape class, as before
        s4 = router_mod.bucket_signature(
            "session_solve", (2, 4, 4, 4), (4, 4, 2), "float64",
            self.LADDERS)
        assert s4[0] != "affinity"

    def test_dead_replica_remaps_only_its_own_sessions(self):
        # the rendezvous (HRW) property the session protocol leans on:
        # killing one replica moves ONLY the sessions it owned — every
        # other session keeps its replica, so its resident factor (and
        # zero-recompile steady state) survives fleet membership churn
        replicas = ["r0", "r1", "r2"]
        sigs = {
            sid: router_mod.bucket_signature(
                "session_solve", (2, 4, 4, 4), (4, 4, 2), "float64",
                self.LADDERS, affinity=sid)
            for sid in (f"sess-{i}" for i in range(64))
        }
        before = {sid: router_mod._rendezvous(sig, replicas)
                  for sid, sig in sigs.items()}
        # sha1 spreads 64 sessions across all three replicas
        assert set(before.values()) == set(replicas)
        dead = "r1"
        alive = [r for r in replicas if r != dead]
        after = {sid: router_mod._rendezvous(sig, alive)
                 for sid, sig in sigs.items()}
        for sid in sigs:
            if before[sid] == dead:
                assert after[sid] in alive
            else:
                assert after[sid] == before[sid], sid
