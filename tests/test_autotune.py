"""Autotune sweep tests (reference autotune/ layer, SURVEY §5.1)."""

import json
import os

import jax.numpy as jnp

from capital_tpu.autotune import sweep
from capital_tpu.parallel.topology import Grid
import jax


def test_cholinv_sweep(tmp_path):
    grid = Grid.square(c=1, devices=jax.devices("cpu")[:1])
    res = sweep.tune_cholinv(
        grid, 128, jnp.float32, str(tmp_path),
        bc_dims=(32, 64), splits=(1,),
    )
    assert len(res) == 4  # 2 policies x 2 bc
    assert res[0].seconds <= res[-1].seconds  # sorted best-first
    # tables + best-config json written
    for f in ("cholinv_cp_times.txt", "cholinv_cp_costs.txt", "cholinv_best.json"):
        assert os.path.exists(tmp_path / f)
    best = json.loads((tmp_path / "cholinv_best.json").read_text())
    assert best["config"]["base_case_dim"] in (32, 64)
    # the model decomposition captured the algorithm phases
    tags = set(res[0].recorder.stats)
    assert {"CI::factor_diag", "CI::trsm", "CI::tmu"} <= tags
    # times table has a Raw column and one row per config
    lines = (tmp_path / "cholinv_cp_times.txt").read_text().splitlines()
    assert "Raw" in lines[0] and len(lines) == 5


def test_cholinv_sweep_prefiltered(tmp_path):
    """Native planner prunes the measured space to top-k model candidates."""
    grid = Grid.square(c=1, devices=jax.devices("cpu")[:1])
    res = sweep.tune_cholinv(
        grid, 128, jnp.float32, str(tmp_path),
        prefilter_top_k=2, bc_dims=(16, 32, 64),
    )
    assert len(res) == 2  # pruned from 2 policies x 3 bc = 6


def test_cholinv_prefilter_with_grid_axis(tmp_path):
    """round 4: the prefilter models each config with ITS topology (round 3
    disabled --top-k under a grid axis); chunked rows rank with q-fold
    collective launches in the alpha term."""
    devs = jax.devices("cpu")[:8]
    grids = [
        Grid.rect(2, 2, 2, devices=devs),
        Grid.rect(2, 2, 2, devices=devs, num_chunks=2),
    ]
    res = sweep.tune_cholinv(
        Grid.square(c=1, devices=devs[:1]), 128, jnp.float32, str(tmp_path),
        prefilter_top_k=1, bc_dims=(32,), policies=(
            sweep.BaseCasePolicy.REPLICATE_COMM_COMP,
        ),
        grids=grids,
    )
    assert len(res) == 1  # pruned from 2 grid rows, not disabled
    # the ONLY axis is chunking: the model must prefer q=0 (fewer
    # collective launches at identical bytes)
    assert "q2" not in res[0].config_id


def test_cacqr_sweep(tmp_path):
    grid = Grid.flat(devices=jax.devices("cpu")[:4])
    res = sweep.tune_cacqr(
        grid, 512, 32, jnp.float32, str(tmp_path),
        bc_dims=(32,), variants=(1, 2),
    )
    assert len(res) == 2
    assert {"CQR::gram", "CQR::chol", "CQR::formR"} <= set(res[0].recorder.stats)
    assert os.path.exists(tmp_path / "cacqr_best.json")


def test_trsm_sweep(tmp_path):
    """bc x leaf x mode over the finished TRSM (the sweep the reference's
    stubbed diaginvert never got)."""
    grid = Grid.square(c=1, devices=jax.devices("cpu")[:1])
    res = sweep.tune_trsm(
        grid, 128, 64, jnp.float32, str(tmp_path),
        bc_dims=(32, 64), leaves=("invert", "solve"),
    )
    assert len(res) == 4
    ids = {r.config_id for r in res}
    assert ids == {
        "bc32_invert_xla", "bc32_solve_xla", "bc64_invert_xla", "bc64_solve_xla"
    }
    assert any("TS::update" in k for k in res[0].recorder.stats)
    assert os.path.exists(tmp_path / "trsm_best.json")


def test_sweep_resume_skips_measured_configs(tmp_path, monkeypatch):
    """A preempted sweep re-run with checkpoint=True resumes: configs in the
    per-config checkpoint are not re-measured, results/tables are identical,
    and a different problem key ignores the stale checkpoint."""
    from capital_tpu.bench import harness

    grid = Grid.square(c=1, devices=jax.devices("cpu")[:1])
    res1 = sweep.tune_cholinv(
        grid, 128, jnp.float32, str(tmp_path),
        bc_dims=(32, 64), splits=(1,), checkpoint=True,
    )
    import glob as _glob

    ckpts = _glob.glob(str(tmp_path / "cholinv_sweep_*.json"))
    assert len(ckpts) == 1

    calls = []
    real = harness.timed_loop

    def counting(*a, **k):
        calls.append(1)
        return real(*a, **k)

    monkeypatch.setattr(harness, "timed_loop", counting)
    res2 = sweep.tune_cholinv(
        grid, 128, jnp.float32, str(tmp_path),
        bc_dims=(32, 64), splits=(1,), checkpoint=True,
    )
    assert not calls  # everything resumed, nothing re-measured
    assert [r.config_id for r in res2] == [r.config_id for r in res1]
    assert [r.seconds for r in res2] == [r.seconds for r in res1]
    # recorder stats survive the JSON round trip
    assert res2[0].recorder.total().flops == res1[0].recorder.total().flops

    # a different problem size must NOT resume from this checkpoint
    res3 = sweep.tune_cholinv(
        grid, 192, jnp.float32, str(tmp_path),
        bc_dims=(32,), splits=(1,), checkpoint=True,
    )
    assert calls  # measured fresh
    assert len(res3) == 2
    # the two problems keep separate checkpoint files (no clobbering): the
    # original can still resume after the second sweep ran in the same dir
    assert len(_glob.glob(str(tmp_path / "cholinv_sweep_*.json"))) == 2
    calls.clear()
    sweep.tune_cholinv(
        grid, 128, jnp.float32, str(tmp_path),
        bc_dims=(32, 64), splits=(1,), checkpoint=True,
    )
    assert not calls  # n=128 sweep still fully resumable


def test_grid_space_enumeration():
    """The rep-factor/grid-shape axis (VERDICT r2 #6): feasible shapes over
    the device set, degenerating gracefully on one device."""
    devs = jax.devices("cpu")
    grids = sweep.grid_space(devs, c_values=(1, 2))
    shapes = {(g.dx, g.dy, g.c) for g in grids}
    assert (2, 2, 1) in shapes and (2, 2, 2) in shapes
    one = sweep.grid_space(devs[:1])
    assert [(g.dx, g.dy, g.c) for g in one] == [(1, 1, 1)]
    flat = sweep.grid_space(devs, c_values=(1,), include_flat=True)
    assert any(g.dx == len(devs) and g.dy == 1 for g in flat)


def test_cholinv_sweep_grid_axis(tmp_path):
    """Grid shape as a swept column: rows for each topology, grid recorded
    in the config dicts and best.json."""
    devs = jax.devices("cpu")
    grids = [
        Grid.square(c=1, devices=devs[:4]),
        Grid.square(c=2, devices=devs[:8]),
    ]
    base = grids[0]
    res = sweep.tune_cholinv(
        base, 64, jnp.float64, str(tmp_path),
        bc_dims=(32,), splits=(1,),
        policies=(sweep.BaseCasePolicy.REPLICATE_COMM_COMP,),
        grids=grids,
    )
    assert len(res) == 2
    assert {r.config["grid"] for r in res} == {repr(g) for g in grids}
    assert all(r.config_id.startswith("g2x2x") for r in res)
    best = json.loads((tmp_path / "cholinv_best.json").read_text())
    assert "grid" in best["config"]
    # the cost tables carry the three compute views per phase
    head = (tmp_path / "cholinv_cp_costs.txt").read_text().splitlines()[0]
    assert "comp-vol" in head and "comp-max" in head


# --------------------------------------------------------------------------
# failure containment (docs/ROBUSTNESS.md)
# --------------------------------------------------------------------------


def _four_configs():
    return [
        (f"c{i}", {"i": i}, (lambda a, _i=i: a * (1.0 + _i)))
        for i in range(4)
    ]


def test_sweep_contains_runtime_failure(tmp_path, monkeypatch):
    """An XlaRuntimeError in ONE config must not abort run_sweep: the rest
    sweep, the failure lands in the checkpoint and the ledger, and a resume
    skips the known-bad config instead of re-crashing into it."""
    from capital_tpu.bench import harness

    operand = jnp.ones((8, 8), jnp.float32)
    real = harness.timed_loop
    measured = []

    def flaky(step, op, iters=2, **k):
        out = float(step(op)[0, 0])
        measured.append(out)
        if out == 3.0:  # config c2 — every attempt fails
            raise jax.errors.JaxRuntimeError("injected OOM")
        return 1e-3 * out

    monkeypatch.setattr(harness, "timed_loop", flaky)
    led = tmp_path / "sweep_led.jsonl"
    res = sweep.run_sweep(
        "faulty", _four_configs(), operand, str(tmp_path),
        checkpoint=True, ledger=str(led),
        retry=harness.RetryPolicy(retries=1, backoff_s=0.0),
    )
    assert [r.config_id for r in res] == ["c0", "c1", "c3"]
    # the failed config is persisted with its error + attempt count
    import glob

    ckpt = json.loads(open(glob.glob(str(tmp_path / "faulty_sweep_*.json"))[0]).read())
    assert ckpt["done"]["c2"]["failed"] is True
    assert ckpt["done"]["c2"]["attempts"] == 2
    # type name via jax's alias: JaxRuntimeError IS XlaRuntimeError
    assert "RuntimeError" in ckpt["done"]["c2"]["error"]
    assert "injected OOM" in ckpt["done"]["c2"]["error"]
    # ledger: one failed event + three measured records
    recs = [json.loads(l) for l in open(led)]
    failed = [r for r in recs if (r.get("event") or {}).get("status") == "failed"]
    assert len(failed) == 1
    assert failed[0]["manifest"]["config_id"] == "c2"
    assert failed[0]["event"]["attempts"] == 2
    # resume: nothing re-measured, c2 not re-crashed into
    measured.clear()
    res2 = sweep.run_sweep(
        "faulty", _four_configs(), operand, str(tmp_path),
        checkpoint=True, retry=harness.RetryPolicy(retries=0),
    )
    assert not measured
    assert [r.config_id for r in res2] == ["c0", "c1", "c3"]
    monkeypatch.setattr(harness, "timed_loop", real)


def test_sweep_recovered_event(tmp_path, monkeypatch):
    """A config that succeeds only after a retry lands in the ledger with a
    status='recovered' event (exempt from obs diff's metric check)."""
    from capital_tpu.bench import harness

    operand = jnp.ones((4, 4), jnp.float32)
    state = {"tries": 0}

    def once_flaky(step, op, iters=2, **k):
        out = float(step(op)[0, 0])
        if out == 2.0:  # config c1 fails exactly once
            state["tries"] += 1
            if state["tries"] == 1:
                raise jax.errors.JaxRuntimeError("transient")
        return 1e-3 * out

    monkeypatch.setattr(harness, "timed_loop", once_flaky)
    led = tmp_path / "rec_led.jsonl"
    res = sweep.run_sweep(
        "flaky1", _four_configs()[:2], operand, str(tmp_path),
        ledger=str(led),
        retry=harness.RetryPolicy(retries=1, backoff_s=0.0),
    )
    assert len(res) == 2
    recs = [json.loads(l) for l in open(led)]
    by_cid = {r["manifest"]["config_id"]: r for r in recs}
    assert (by_cid["c1"].get("event") or {}).get("status") == "recovered"
    assert by_cid["c1"]["event"]["attempts"] == 2
    assert by_cid["c0"].get("event") is None


def test_ckpt_load_tolerates_old_schema(tmp_path):
    """Satellite: a checkpoint written by an older schema (entries missing
    'seconds'/'config'/'stats', malformed rows) must resume without
    KeyError — unusable entries re-measure, usable ones survive."""
    operand = jnp.ones((8, 8), jnp.float32)
    key = sweep._ckpt_key("old", operand, None)
    path = sweep._ckpt_path(str(tmp_path), "old", key)
    json.dump(
        {
            "key": key,
            "done": {
                "good": {"config": {"bc": 32}, "seconds": 0.5, "stats": {}},
                "bare_seconds": {"seconds": 1.5},  # no config/stats
                "older": {"config": {"bc": 64}},  # no seconds at all
                "junk": "not-a-dict",
                "oom": {"failed": True, "error": "XlaRuntimeError: OOM"},
            },
        },
        open(path, "w"),
    )
    done = sweep._ckpt_load(path, key)
    assert set(done) == {"good", "bare_seconds", "oom"}
    assert done["good"]["seconds"] == 0.5
    assert done["bare_seconds"]["config"] == {}  # degraded, not KeyError'd
    assert done["bare_seconds"]["stats"] == {}
    assert done["oom"]["failed"] is True
    # mismatched key (different problem) ignores the checkpoint wholesale
    other = dict(key, shape=[16, 16])
    assert sweep._ckpt_load(path, other) == {}
