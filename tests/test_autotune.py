"""Autotune sweep tests (reference autotune/ layer, SURVEY §5.1)."""

import json
import os

import jax.numpy as jnp

from capital_tpu.autotune import sweep
from capital_tpu.parallel.topology import Grid
import jax


def test_cholinv_sweep(tmp_path):
    grid = Grid.square(c=1, devices=jax.devices("cpu")[:1])
    res = sweep.tune_cholinv(
        grid, 128, jnp.float32, str(tmp_path),
        bc_dims=(32, 64), splits=(1,),
    )
    assert len(res) == 4  # 2 policies x 2 bc
    assert res[0].seconds <= res[-1].seconds  # sorted best-first
    # tables + best-config json written
    for f in ("cholinv_cp_times.txt", "cholinv_cp_costs.txt", "cholinv_best.json"):
        assert os.path.exists(tmp_path / f)
    best = json.loads((tmp_path / "cholinv_best.json").read_text())
    assert best["config"]["base_case_dim"] in (32, 64)
    # the model decomposition captured the algorithm phases
    tags = set(res[0].recorder.stats)
    assert {"CI::factor_diag", "CI::trsm", "CI::tmu"} <= tags
    # times table has a Raw column and one row per config
    lines = (tmp_path / "cholinv_cp_times.txt").read_text().splitlines()
    assert "Raw" in lines[0] and len(lines) == 5


def test_cholinv_sweep_prefiltered(tmp_path):
    """Native planner prunes the measured space to top-k model candidates."""
    grid = Grid.square(c=1, devices=jax.devices("cpu")[:1])
    res = sweep.tune_cholinv(
        grid, 128, jnp.float32, str(tmp_path),
        prefilter_top_k=2, bc_dims=(16, 32, 64),
    )
    assert len(res) == 2  # pruned from 2 policies x 3 bc = 6


def test_cholinv_prefilter_with_grid_axis(tmp_path):
    """round 4: the prefilter models each config with ITS topology (round 3
    disabled --top-k under a grid axis); chunked rows rank with q-fold
    collective launches in the alpha term."""
    devs = jax.devices("cpu")[:8]
    grids = [
        Grid.rect(2, 2, 2, devices=devs),
        Grid.rect(2, 2, 2, devices=devs, num_chunks=2),
    ]
    res = sweep.tune_cholinv(
        Grid.square(c=1, devices=devs[:1]), 128, jnp.float32, str(tmp_path),
        prefilter_top_k=1, bc_dims=(32,), policies=(
            sweep.BaseCasePolicy.REPLICATE_COMM_COMP,
        ),
        grids=grids,
    )
    assert len(res) == 1  # pruned from 2 grid rows, not disabled
    # the ONLY axis is chunking: the model must prefer q=0 (fewer
    # collective launches at identical bytes)
    assert "q2" not in res[0].config_id


def test_cacqr_sweep(tmp_path):
    grid = Grid.flat(devices=jax.devices("cpu")[:4])
    res = sweep.tune_cacqr(
        grid, 512, 32, jnp.float32, str(tmp_path),
        bc_dims=(32,), variants=(1, 2),
    )
    assert len(res) == 2
    assert {"CQR::gram", "CQR::chol", "CQR::formR"} <= set(res[0].recorder.stats)
    assert os.path.exists(tmp_path / "cacqr_best.json")


def test_trsm_sweep(tmp_path):
    """bc x leaf x mode over the finished TRSM (the sweep the reference's
    stubbed diaginvert never got)."""
    grid = Grid.square(c=1, devices=jax.devices("cpu")[:1])
    res = sweep.tune_trsm(
        grid, 128, 64, jnp.float32, str(tmp_path),
        bc_dims=(32, 64), leaves=("invert", "solve"),
    )
    assert len(res) == 4
    ids = {r.config_id for r in res}
    assert ids == {
        "bc32_invert_xla", "bc32_solve_xla", "bc64_invert_xla", "bc64_solve_xla"
    }
    assert any("TS::update" in k for k in res[0].recorder.stats)
    assert os.path.exists(tmp_path / "trsm_best.json")


def test_sweep_resume_skips_measured_configs(tmp_path, monkeypatch):
    """A preempted sweep re-run with checkpoint=True resumes: configs in the
    per-config checkpoint are not re-measured, results/tables are identical,
    and a different problem key ignores the stale checkpoint."""
    from capital_tpu.bench import harness

    grid = Grid.square(c=1, devices=jax.devices("cpu")[:1])
    res1 = sweep.tune_cholinv(
        grid, 128, jnp.float32, str(tmp_path),
        bc_dims=(32, 64), splits=(1,), checkpoint=True,
    )
    import glob as _glob

    ckpts = _glob.glob(str(tmp_path / "cholinv_sweep_*.json"))
    assert len(ckpts) == 1

    calls = []
    real = harness.timed_loop

    def counting(*a, **k):
        calls.append(1)
        return real(*a, **k)

    monkeypatch.setattr(harness, "timed_loop", counting)
    res2 = sweep.tune_cholinv(
        grid, 128, jnp.float32, str(tmp_path),
        bc_dims=(32, 64), splits=(1,), checkpoint=True,
    )
    assert not calls  # everything resumed, nothing re-measured
    assert [r.config_id for r in res2] == [r.config_id for r in res1]
    assert [r.seconds for r in res2] == [r.seconds for r in res1]
    # recorder stats survive the JSON round trip
    assert res2[0].recorder.total().flops == res1[0].recorder.total().flops

    # a different problem size must NOT resume from this checkpoint
    res3 = sweep.tune_cholinv(
        grid, 192, jnp.float32, str(tmp_path),
        bc_dims=(32,), splits=(1,), checkpoint=True,
    )
    assert calls  # measured fresh
    assert len(res3) == 2
    # the two problems keep separate checkpoint files (no clobbering): the
    # original can still resume after the second sweep ran in the same dir
    assert len(_glob.glob(str(tmp_path / "cholinv_sweep_*.json"))) == 2
    calls.clear()
    sweep.tune_cholinv(
        grid, 128, jnp.float32, str(tmp_path),
        bc_dims=(32, 64), splits=(1,), checkpoint=True,
    )
    assert not calls  # n=128 sweep still fully resumable


def test_grid_space_enumeration():
    """The rep-factor/grid-shape axis (VERDICT r2 #6): feasible shapes over
    the device set, degenerating gracefully on one device."""
    devs = jax.devices("cpu")
    grids = sweep.grid_space(devs, c_values=(1, 2))
    shapes = {(g.dx, g.dy, g.c) for g in grids}
    assert (2, 2, 1) in shapes and (2, 2, 2) in shapes
    one = sweep.grid_space(devs[:1])
    assert [(g.dx, g.dy, g.c) for g in one] == [(1, 1, 1)]
    flat = sweep.grid_space(devs, c_values=(1,), include_flat=True)
    assert any(g.dx == len(devs) and g.dy == 1 for g in flat)


def test_cholinv_sweep_grid_axis(tmp_path):
    """Grid shape as a swept column: rows for each topology, grid recorded
    in the config dicts and best.json."""
    devs = jax.devices("cpu")
    grids = [
        Grid.square(c=1, devices=devs[:4]),
        Grid.square(c=2, devices=devs[:8]),
    ]
    base = grids[0]
    res = sweep.tune_cholinv(
        base, 64, jnp.float64, str(tmp_path),
        bc_dims=(32,), splits=(1,),
        policies=(sweep.BaseCasePolicy.REPLICATE_COMM_COMP,),
        grids=grids,
    )
    assert len(res) == 2
    assert {r.config["grid"] for r in res} == {repr(g) for g in grids}
    assert all(r.config_id.startswith("g2x2x") for r in res)
    best = json.loads((tmp_path / "cholinv_best.json").read_text())
    assert "grid" in best["config"]
    # the cost tables carry the three compute views per phase
    head = (tmp_path / "cholinv_cp_costs.txt").read_text().splitlines()[0]
    assert "comp-vol" in head and "comp-max" in head
