"""Autotune sweep tests (reference autotune/ layer, SURVEY §5.1)."""

import json
import os

import jax.numpy as jnp

from capital_tpu.autotune import sweep
from capital_tpu.parallel.topology import Grid
import jax


def test_cholinv_sweep(tmp_path):
    grid = Grid.square(c=1, devices=jax.devices("cpu")[:1])
    res = sweep.tune_cholinv(
        grid, 128, jnp.float32, str(tmp_path),
        bc_dims=(32, 64), splits=(1,),
    )
    assert len(res) == 4  # 2 policies x 2 bc
    assert res[0].seconds <= res[-1].seconds  # sorted best-first
    # tables + best-config json written
    for f in ("cholinv_cp_times.txt", "cholinv_cp_costs.txt", "cholinv_best.json"):
        assert os.path.exists(tmp_path / f)
    best = json.loads((tmp_path / "cholinv_best.json").read_text())
    assert best["config"]["base_case_dim"] in (32, 64)
    # the model decomposition captured the algorithm phases
    tags = set(res[0].recorder.stats)
    assert {"CI::factor_diag", "CI::trsm", "CI::tmu"} <= tags
    # times table has a Raw column and one row per config
    lines = (tmp_path / "cholinv_cp_times.txt").read_text().splitlines()
    assert "Raw" in lines[0] and len(lines) == 5


def test_cholinv_sweep_prefiltered(tmp_path):
    """Native planner prunes the measured space to top-k model candidates."""
    grid = Grid.square(c=1, devices=jax.devices("cpu")[:1])
    res = sweep.tune_cholinv(
        grid, 128, jnp.float32, str(tmp_path),
        prefilter_top_k=2, bc_dims=(16, 32, 64),
    )
    assert len(res) == 2  # pruned from 2 policies x 3 bc = 6


def test_cacqr_sweep(tmp_path):
    grid = Grid.flat(devices=jax.devices("cpu")[:4])
    res = sweep.tune_cacqr(
        grid, 512, 32, jnp.float32, str(tmp_path),
        bc_dims=(32,), variants=(1, 2),
    )
    assert len(res) == 2
    assert {"CQR::gram", "CQR::chol", "CQR::formR"} <= set(res[0].recorder.stats)
    assert os.path.exists(tmp_path / "cacqr_best.json")
