"""Fused CQR2 tall-pass kernels (ops/qr_fused.py) — interpret mode on CPU.

The fused pipeline must agree with the unfused blocked pipeline (same
grams-from-rounded-Q math, different reduction association) and pass the
reference residual gates."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from capital_tpu.models import qr
from capital_tpu.models.qr import CacqrConfig
from capital_tpu.ops import qr_fused
from capital_tpu.parallel.topology import Grid
from capital_tpu.utils import rand48, residual


@pytest.fixture(scope="module")
def grid1():
    return Grid.square(c=1, devices=jax.devices("cpu")[:1])


def _tall(m, n, key=11):
    return jnp.asarray(rand48.random(m, n, key=key))


class TestKernels:
    def test_gram_blocked_matches_dense(self):
        A = _tall(2048, 512).astype(jnp.float32)
        Gu = qr_fused.gram_blocked(A, bm=512)
        G = qr_fused.assemble_sym(Gu, 256)
        want = np.asarray(A, np.float64).T @ np.asarray(A, np.float64)
        np.testing.assert_allclose(np.asarray(G), want, rtol=1e-5, atol=1e-4)
        # lower-left of the raw form is zero (never computed)
        np.testing.assert_array_equal(np.asarray(Gu)[256:, :256], 0.0)

    def test_scale_gram_matches_separate(self):
        rng = np.random.default_rng(5)
        A = _tall(1024, 512, key=7).astype(jnp.float32)
        Rinv = jnp.asarray(
            np.triu(rng.standard_normal((512, 512)) * 0.1 + np.eye(512))
        ).astype(jnp.float32)
        Q, Gu = qr_fused.scale_gram(A, Rinv, bm=512)
        wantQ = np.asarray(A, np.float64) @ np.asarray(Rinv, np.float64)
        np.testing.assert_allclose(np.asarray(Q), wantQ, rtol=1e-4, atol=1e-4)
        # the gram is of the ROUNDED Q (the contract: sweep 2 sees what it
        # would have re-read)
        Qr = np.asarray(Q, np.float64)
        G = qr_fused.assemble_sym(Gu, 256)
        np.testing.assert_allclose(
            np.asarray(G), Qr.T @ Qr, rtol=1e-5, atol=1e-4
        )

    @pytest.mark.parametrize("g", [4, 8])
    def test_gram_blocked_finer_splits(self, g):
        # in-kernel g=4/8 column blocking (VERDICT r3 #1): same gram,
        # fewer executed flops, block-triangular valid region
        A = _tall(2048, 1024).astype(jnp.float32)
        c = 1024 // g
        Gu = qr_fused.gram_blocked(A, bm=512, g=g)
        G = qr_fused.assemble_sym(Gu, c)
        want = np.asarray(A, np.float64).T @ np.asarray(A, np.float64)
        np.testing.assert_allclose(np.asarray(G), want, rtol=1e-5, atol=1e-4)
        Gu_np = np.asarray(Gu)
        for i in range(1, g):
            np.testing.assert_array_equal(Gu_np[i * c:(i + 1) * c, : i * c], 0.0)

    @pytest.mark.parametrize("g", [4, 8])
    def test_scale_gram_finer_splits(self, g):
        rng = np.random.default_rng(9)
        A = _tall(1024, 1024, key=8).astype(jnp.float32)
        n = 1024
        c = n // g
        Rinv = jnp.asarray(
            np.triu(rng.standard_normal((n, n)) * 0.1 + np.eye(n))
        ).astype(jnp.float32)
        Q, Gu = qr_fused.scale_gram(A, Rinv, bm=512, g=g)
        wantQ = np.asarray(A, np.float64) @ np.asarray(Rinv, np.float64)
        np.testing.assert_allclose(np.asarray(Q), wantQ, rtol=1e-4, atol=1e-3)
        Qr = np.asarray(Q, np.float64)
        G = qr_fused.assemble_sym(Gu, c)
        np.testing.assert_allclose(np.asarray(G), Qr.T @ Qr, rtol=1e-5, atol=1e-3)
        Qs = qr_fused.scale_blocked(A, Rinv, bm=512, g=g)
        np.testing.assert_allclose(np.asarray(Qs), wantQ, rtol=1e-4, atol=1e-3)

    def test_f32_precision_high_three_pass(self):
        # precision='high' on f32 operands must take the in-kernel bf16x3
        # split (Mosaic has no HIGH lowering — passing it through crashed
        # with NotImplementedError on hardware) and land f32-grade results
        A = _tall(1024, 512, key=13).astype(jnp.float32)
        Gu = qr_fused.gram_blocked(A, bm=512, precision="high")
        G = qr_fused.assemble_sym(Gu, 256)
        want = np.asarray(A, np.float64).T @ np.asarray(A, np.float64)
        np.testing.assert_allclose(
            np.asarray(G), want, rtol=2e-4, atol=2e-3
        )
        # 3-pass must beat a 1-pass bf16 product by orders of magnitude
        Gd = qr_fused.assemble_sym(qr_fused.gram_blocked(
            A.astype(jnp.bfloat16).astype(jnp.float32), bm=512
        ), 256)
        err3 = np.max(np.abs(np.asarray(G) - want))
        err1 = np.max(np.abs(np.asarray(Gd) - want))
        assert err3 < err1 / 50, (err3, err1)

    def test_pick_g(self):
        assert qr_fused.pick_g(1024) == 8
        assert qr_fused.pick_g(2048) == 16  # 128-wide blocks still eligible
        assert qr_fused.pick_g(4096) == 32
        assert qr_fused.pick_g(512) == 4
        assert qr_fused.pick_g(768) == 2  # 768 % 512 != 0, g=2 slabs OK
        assert qr_fused.pick_g(256) == 0  # g=2 demands n/2 >= 256
        assert qr_fused.pick_g(192) == 0  # no 128-aligned split
        assert qr_fused.pick_g(1024, override=4) == 4
        assert qr_fused.pick_g(384, override=8) == 0  # override ineligible

    def test_shape_gates(self):
        A = _tall(1000, 512).astype(jnp.float32)  # 1000 not tileable
        with pytest.raises(ValueError):
            qr_fused.gram_blocked(A, bm=512)
        g1 = Grid.square(c=1, devices=jax.devices("cpu")[:1])
        assert not qr_fused.fused_ok(g1, 1000, 512, "pallas", dtype=jnp.float32)
        assert not qr_fused.fused_ok(g1, 1024, 192, "pallas", dtype=jnp.float32)  # no g=2 split
        assert not qr_fused.fused_ok(g1, 1024, 512, "xla", dtype=jnp.float32)
        assert qr_fused.fused_ok(g1, 1024, 512, "pallas", dtype=jnp.float32)


class TestFusedPipeline:
    def test_fused_cqr2_matches_unfused(self, grid1):
        A = _tall(2048, 512).astype(jnp.float64)
        fused_cfg = CacqrConfig(num_iter=2, regime="1d", mode="pallas")
        assert qr_fused.fused_ok(grid1, *A.shape, "pallas", dtype=A.dtype)
        Qf, Rf = jax.jit(lambda a: qr.factor(grid1, a, fused_cfg))(A)
        # unfused reference: xla mode takes the separate-pass pipeline
        Qu, Ru = jax.jit(
            lambda a: qr.factor(grid1, a, CacqrConfig(num_iter=2, regime="1d"))
        )(A)
        np.testing.assert_allclose(np.asarray(Qf), np.asarray(Qu), atol=1e-10)
        np.testing.assert_allclose(
            np.triu(np.asarray(Rf)), np.triu(np.asarray(Ru)), atol=1e-8
        )
        assert float(residual.qr_orthogonality(Qf)) < 1e-14
        assert float(residual.qr_residual(A, Qf, Rf)) < 1e-13

    def test_split_plan_matches_full(self, grid1):
        # the wide-n streaming tier ('split': scale and sweep-2 gram as two
        # kernels) must agree with the 'full' scale_gram tier exactly — the
        # gram is taken from the SAME rounded Q1 either way
        from capital_tpu.models.qr import _cqr2_fused

        A = _tall(2048, 512).astype(jnp.float64)
        cfg = CacqrConfig(num_iter=2, regime="1d", mode="pallas")
        g = qr_fused.pick_g(512)
        Qf, Rf = jax.jit(lambda a: _cqr2_fused(grid1, a, cfg, g, "full"))(A)
        Qs, Rs = jax.jit(lambda a: _cqr2_fused(grid1, a, cfg, g, "split"))(A)
        np.testing.assert_allclose(np.asarray(Qs), np.asarray(Qf), atol=1e-12)
        np.testing.assert_allclose(np.asarray(Rs), np.asarray(Rf), atol=1e-10)
        assert float(residual.qr_orthogonality(Qs)) < 1e-14

    def test_fused_plan_tiers(self, grid1, monkeypatch):
        # envelope arithmetic on a simulated v5e budget: narrow n -> 'full';
        # n=4096 exceeds scale_gram's envelope but not the per-kernel ones
        # -> 'split'; n=8192's gram alone exceeds VMEM -> None
        from capital_tpu.ops import pallas_tpu

        monkeypatch.setattr(pallas_tpu, "_default_backend", lambda: "tpu")
        monkeypatch.setattr(
            qr_fused, "_interpret_default", lambda: False
        )
        monkeypatch.setattr(
            qr_fused, "_device_budget", lambda: (512, 128 << 20)
        )
        bf = jnp.bfloat16
        assert qr_fused.fused_plan(
            grid1, 1 << 21, 1024, "pallas", g=8, dtype=bf
        ) == "full"
        assert qr_fused.fused_plan(
            grid1, 262144, 4096, "pallas", g=32, dtype=bf
        ) == "split"
        assert qr_fused.fused_plan(
            grid1, 65536, 8192, "pallas", g=64, dtype=bf
        ) == "panels"

    @pytest.mark.slow  # ~24s (n=2048 f64 on the 1-core rig); the
    # wide-n route's cheaper dispatch pins stay in tier-1
    def test_wide_n_cholinv_route_matches_unfused(self, grid1):
        # n >= 2048 routes the gram factor through the recursive cholinv
        # on the UNASSEMBLED gram (zeros below the valid upper triangle) —
        # the branch's correctness rests on cholinv never reading the
        # lower half; this is the CI tripwire for that contract
        m, n = 2304, 2048
        A = _tall(m, n).astype(jnp.float64)
        cfg = CacqrConfig(num_iter=2, regime="1d", mode="pallas")
        g = qr_fused.pick_g(n)
        assert qr_fused.fused_ok(grid1, m, n, "pallas", g=g, dtype=A.dtype)
        Qf, Rf = jax.jit(lambda a: qr.factor(grid1, a, cfg))(A)
        Qu, Ru = jax.jit(
            lambda a: qr.factor(grid1, a, CacqrConfig(num_iter=2, regime="1d"))
        )(A)
        assert float(residual.qr_orthogonality(Qf)) < 1e-14
        assert float(residual.qr_residual(A, Qf, Rf)) < 1e-13
        np.testing.assert_allclose(np.asarray(Qf), np.asarray(Qu), atol=1e-9)
        np.testing.assert_allclose(
            np.triu(np.asarray(Rf)), np.triu(np.asarray(Ru)), atol=1e-7
        )

    def test_panels_tier_matches_unfused(self, grid1):
        # the very-wide-n XLA panel pipeline (fused_plan 'panels'): same
        # grams-from-rounded-Q math as the sweeps, checked at a small
        # shape by calling the tier directly
        from capital_tpu.models.qr import _cqr2_panels

        m, n = 2048, 1024
        A = _tall(m, n).astype(jnp.float64)
        cfg = CacqrConfig(num_iter=2, regime="1d", mode="pallas")
        Qp, Rp = jax.jit(lambda a: _cqr2_panels(grid1, a, cfg, 256))(A)
        assert float(residual.qr_orthogonality(Qp)) < 1e-14
        assert float(residual.qr_residual(A, Qp, Rp)) < 1e-13
        Qu, Ru = jax.jit(
            lambda a: qr.factor(grid1, a, CacqrConfig(num_iter=2, regime="1d"))
        )(A)
        np.testing.assert_allclose(np.asarray(Qp), np.asarray(Qu), atol=1e-9)
        np.testing.assert_allclose(
            np.triu(np.asarray(Rp)), np.triu(np.asarray(Ru)), atol=1e-7
        )

    def test_fused_bf16_gates(self, grid1):
        A = _tall(1024, 512).astype(jnp.bfloat16)
        cfg = CacqrConfig(num_iter=2, regime="1d", mode="pallas")
        Q, R = jax.jit(lambda a: qr.factor(grid1, a, cfg))(A)
        assert float(residual.qr_orthogonality(Q)) < 5e-2
        assert float(residual.qr_residual(A, Q, R)) < 5e-2

    def test_cqr1_stays_unfused_and_mesh_gates_hold(self, grid_flat8, grid1):
        # num_iter=1 keeps the sweep pipeline; on the mesh the per-shard
        # kernels engage (128-row shards pick bm=128) and must still gate
        A = _tall(1024, 512).astype(jnp.float64)
        cfg1 = CacqrConfig(num_iter=1, regime="1d", mode="pallas")
        Q, R = qr.factor(grid1, A, cfg1)
        assert float(residual.qr_residual(A, Q, R)) < 1e-13
        Ad = jax.device_put(A, grid_flat8.rows_sharding())
        cfgm = CacqrConfig(num_iter=2, regime="1d", mode="pallas")
        Qm, Rm = jax.jit(lambda a: qr.factor(grid_flat8, a, cfgm))(Ad)
        assert float(residual.qr_orthogonality(Qm)) < 1e-13


class TestFusedSharded:
    """The per-shard fused pipeline on a mesh (qr._cqr2_fused_sharded):
    same kernels, run inside shard_map with the grams psum-merged
    (VERDICT r4 #2 — the reference's per-rank local-BLAS saving,
    blas/interface.hpp:74-97)."""

    def test_sharded_matches_single_device(self, grid_flat8, grid1):
        m, n = 4096, 512  # 512 rows per shard: per-shard eligible
        A = _tall(m, n).astype(jnp.float64)
        cfg = CacqrConfig(num_iter=2, regime="1d", mode="pallas")
        g = qr_fused.pick_g(n)
        assert qr_fused.fused_ok(grid_flat8, m, n, "pallas", g=g, dtype=A.dtype)
        Ad = jax.device_put(A, grid_flat8.rows_sharding())
        Qm, Rm = jax.jit(lambda a: qr.factor(grid_flat8, a, cfg))(Ad)
        Q1, R1 = jax.jit(lambda a: qr.factor(grid1, a, cfg))(A)
        assert float(residual.qr_orthogonality(Qm)) < 1e-14
        assert float(residual.qr_residual(Ad, Qm, Rm)) < 1e-13
        # identical math up to the psum's reduction association order
        np.testing.assert_allclose(np.asarray(Qm), np.asarray(Q1), atol=1e-10)
        np.testing.assert_allclose(
            np.triu(np.asarray(Rm)), np.triu(np.asarray(R1)), atol=1e-8
        )

    def test_sharded_bf16_gates(self, grid_flat8):
        m, n = 4096, 512
        A = _tall(m, n, key=3).astype(jnp.bfloat16)
        Ad = jax.device_put(A, grid_flat8.rows_sharding())
        cfg = CacqrConfig(num_iter=2, regime="1d", mode="pallas")
        Q, R = jax.jit(lambda a: qr.factor(grid_flat8, a, cfg))(Ad)
        assert float(residual.qr_orthogonality(Q)) < 5e-2
        assert float(residual.qr_residual(Ad, Q, R)) < 5e-2

    def test_uneven_rows_fall_back_to_sweeps(self, grid_flat8):
        # m not divisible by the device count: the m % p guard must refuse
        # (4100 % 8 = 4 — hits the guard itself, not the bm-tiling rule)
        # and the factor must still produce a correct result via the sweeps
        m, n = 4100, 512
        assert not qr_fused.fused_ok(
            grid_flat8, m, n, "pallas", dtype=jnp.float64
        )
        # uneven rows cannot even be device_put row-sharded (NamedSharding
        # demands divisibility); the factor's in-jit constraint handles the
        # placement, exactly how an uneven caller would reach it
        A = _tall(m, n).astype(jnp.float64)
        cfg = CacqrConfig(num_iter=2, regime="1d", mode="pallas")
        Q, R = jax.jit(lambda a: qr.factor(grid_flat8, a, cfg))(A)
        assert float(residual.qr_orthogonality(Q)) < 1e-13
        assert float(residual.qr_residual(A, Q, R)) < 1e-13
