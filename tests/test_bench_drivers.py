"""Bench-driver smoke tests: every driver runs + validates at tiny sizes.

The reference's drivers ARE its integration tests (validation blocks in
bench/*/*.cpp, SURVEY §4); here they run under pytest on the virtual CPU
mesh so the whole driver surface stays green.
"""

import pytest

from capital_tpu.bench import drivers


def _run(argv):
    drivers.main(argv)


@pytest.mark.parametrize(
    "argv",
    [
        ["cholinv", "--n", "192", "--bc", "64", "--devices", "1"],
        ["cholinv", "--n", "128", "--bc", "32", "--c", "2", "--no-complete-inv"],
        ["cacqr", "--m", "1024", "--n", "32", "--variant", "2"],
        ["cacqr", "--m", "512", "--n", "16", "--variant", "1", "--devices", "1"],
        ["summa_gemm", "--m", "128", "--n", "128", "--k", "128", "--c", "2"],
        ["rectri", "--n", "128", "--bc", "32", "--devices", "1"],
        ["newton", "--n", "96", "--newton-iters", "25", "--devices", "1"],
        ["spd_inverse", "--n", "128", "--bc", "32", "--devices", "4"],
    ],
    ids=lambda a: "-".join(a[:1] + [x for x in a[1:] if not x.startswith("-")]),
)
def test_driver(argv):
    _run(argv + ["--dtype", "float32", "--iters", "1", "--validate"])


def test_suite_scaled():
    _run(["suite", "--dtype", "float32", "--iters", "1", "--scale", "64", "--validate"])
