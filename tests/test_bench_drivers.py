"""Bench-driver smoke tests: every driver runs + validates at tiny sizes.

The reference's drivers ARE its integration tests (validation blocks in
bench/*/*.cpp, SURVEY §4); here they run under pytest on the virtual CPU
mesh so the whole driver surface stays green.
"""

import pytest

from capital_tpu.bench import drivers


def _run(argv):
    drivers.main(argv)


@pytest.mark.parametrize(
    "argv",
    [
        ["cholinv", "--n", "192", "--bc", "64", "--devices", "1"],
        ["cholinv", "--n", "128", "--bc", "32", "--c", "2", "--no-complete-inv"],
        ["cacqr", "--m", "1024", "--n", "32", "--variant", "2"],
        ["cacqr", "--m", "512", "--n", "16", "--variant", "1", "--devices", "1"],
        ["summa_gemm", "--m", "128", "--n", "128", "--k", "128", "--c", "2"],
        ["rectri", "--n", "128", "--bc", "32", "--devices", "1"],
        ["newton", "--n", "96", "--newton-iters", "25", "--devices", "1"],
        ["spd_inverse", "--n", "128", "--bc", "32", "--devices", "4"],
    ],
    ids=lambda a: "-".join(a[:1] + [x for x in a[1:] if not x.startswith("-")]),
)
def test_driver(argv):
    _run(argv + ["--dtype", "float32", "--iters", "1", "--validate"])


def test_suite_scaled():
    _run(["suite", "--dtype", "float32", "--iters", "1", "--scale", "64", "--validate"])


def test_flagship_auto_base_case(capsys):
    # bench.py's base-case pick must keep the flagship n tiled exactly —
    # a wrong pick silently pads (up to 2.4x flops) or misaligns every
    # pallas view window
    import importlib.util
    import pathlib

    path = pathlib.Path(__file__).resolve().parent.parent / "bench.py"
    spec = importlib.util.spec_from_file_location("flagship_bench", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    from capital_tpu.models.cholesky import padded_dim

    assert mod.auto_base_case(32768) == 512
    assert mod.auto_base_case(49152) == 384
    assert mod.auto_base_case(16384) == 512
    assert mod.auto_base_case(24576) == 384
    for n in (32768, 49152, 24576):
        bc = mod.auto_base_case(n)
        assert padded_dim(n, bc) == n and bc % 128 == 0
    # untileable n: falls back to 512 and says so
    assert mod.auto_base_case(40000) == 512
    assert "padding to" in capsys.readouterr().err
