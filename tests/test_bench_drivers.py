"""Bench-driver smoke tests: every driver runs + validates at tiny sizes.

The reference's drivers ARE its integration tests (validation blocks in
bench/*/*.cpp, SURVEY §4); here they run under pytest on the virtual CPU
mesh so the whole driver surface stays green.
"""

import pytest

from capital_tpu.bench import drivers


def _run(argv):
    drivers.main(argv)


@pytest.mark.parametrize(
    "argv",
    [
        ["cholinv", "--n", "192", "--bc", "64", "--devices", "1"],
        ["cholinv", "--n", "128", "--bc", "32", "--c", "2", "--no-complete-inv"],
        ["cacqr", "--m", "1024", "--n", "32", "--variant", "2"],
        ["cacqr", "--m", "512", "--n", "16", "--variant", "1", "--devices", "1"],
        ["summa_gemm", "--m", "128", "--n", "128", "--k", "128", "--c", "2"],
        ["rectri", "--n", "128", "--bc", "32", "--devices", "1"],
        ["newton", "--n", "96", "--newton-iters", "25", "--devices", "1"],
        ["spd_inverse", "--n", "128", "--bc", "32", "--devices", "4"],
    ],
    ids=lambda a: "-".join(a[:1] + [x for x in a[1:] if not x.startswith("-")]),
)
def test_driver(argv):
    _run(argv + ["--dtype", "float32", "--iters", "1", "--validate"])


def test_suite_scaled():
    _run(["suite", "--dtype", "float32", "--iters", "1", "--scale", "64", "--validate"])


def test_flagship_auto_base_case(capsys):
    # bench.py's base-case pick must keep the flagship n tiled exactly —
    # a wrong pick silently pads (up to 2.4x flops) or misaligns every
    # pallas view window
    import importlib.util
    import pathlib

    path = pathlib.Path(__file__).resolve().parent.parent / "bench.py"
    spec = importlib.util.spec_from_file_location("flagship_bench", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    from capital_tpu.models.cholesky import padded_dim

    assert mod.auto_base_case(32768) == 512
    assert mod.auto_base_case(49152) == 384
    assert mod.auto_base_case(16384) == 512
    assert mod.auto_base_case(24576) == 384
    for n in (32768, 49152, 24576):
        bc = mod.auto_base_case(n)
        assert padded_dim(n, bc) == n and bc % 128 == 0
    # untileable n: falls back to the least-padding candidate and says so
    # (40000 pads to 49152 under bc=384 vs 65536 under 512/256)
    assert mod.auto_base_case(40000) == 384
    assert "padding to" in capsys.readouterr().err


def test_flagship_spd_hash_contract():
    """The one-shot loop's fused operand generator: exactly symmetric (hash
    of (min, max) index pair), well-SPD (3I shift vs ~1.16 spectral norm of
    the random part), and salt-dependent (so XLA cannot hoist it out of the
    timed loop)."""
    import importlib.util
    import pathlib

    import jax.numpy as jnp
    import numpy as np

    path = pathlib.Path(__file__).resolve().parent.parent / "bench.py"
    spec = importlib.util.spec_from_file_location("flagship_bench2", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)

    A = np.asarray(mod.spd_hash(256, jnp.float32, 3))
    np.testing.assert_array_equal(A, A.T)
    w = np.linalg.eigvalsh(A.astype(np.float64))
    assert w.min() > 1.0 and w.max() < 5.0  # 3 ± ~1.16 spectral band
    B = np.asarray(mod.spd_hash(256, jnp.float32, 4))
    assert np.abs(A - B).max() > 0.01  # salt actually changes the operand
    # deterministic: same salt, same matrix
    np.testing.assert_array_equal(
        A, np.asarray(mod.spd_hash(256, jnp.float32, 3))
    )


def test_newton_reports_executed_iters():
    """VERDICT r2 weak #3: the newton driver must report flops for the
    iterations actually executed (early exit), not the max_iter budget —
    a run converging in 12 of 30 budgeted steps would otherwise print ~2.5x
    the true throughput."""
    args = drivers.build_parser().parse_args(
        ["newton", "--n", "96", "--newton-iters", "40", "--dtype", "float32",
         "--iters", "1", "--devices", "1"]
    )
    rec = drivers.newton(args)
    it = rec["iters_executed"]
    # a well-conditioned 96x96 f32 operand converges far inside 40 steps
    assert 0 < it < 40
    # reported TF/s must be derived from executed work: 2n³(2·it + 1).
    # rec["seconds"] is rounded to 5 decimals while rec["value"] came from
    # the unrounded time — widen the tolerance by the worst-case rounding
    # error so a fast backend cannot flake the comparison.
    want_flops = 2.0 * 96**3 * (2 * it + 1)
    got_flops = rec["value"] * 1e12 * rec["seconds"]
    tol = 0.05 + 0.5e-5 / rec["seconds"]
    assert abs(got_flops - want_flops) / want_flops < tol


def test_timed_oneshot_refuses_noise_floor():
    """The one-shot protocol must REFUSE (MeasurementUnresolved) rather than
    print a noise artifact when the step never clears the dispatch band —
    the same no-fake-numbers contract as timed_loop."""
    import jax.numpy as jnp
    import pytest as _pytest

    from capital_tpu.bench import harness

    def gen(i):
        return jnp.full((8, 8), 1.0, jnp.float32) * (1.0 + 0.0 * i)

    def step(a):
        return a[0, 0] * 2.0  # trivially below any noise band

    with _pytest.raises(harness.MeasurementUnresolved):
        harness.timed_oneshot(gen, step, iters=2, repeats=2)


def test_hbm_bytes_sane():
    """_hbm_bytes returns the runtime figure when available, else the
    conservative fallback — either way a plausible per-chip capacity."""
    v = drivers._hbm_bytes()
    assert 4e9 <= v <= 1e12
