"""Partitioned (Spike) blocktri driver tests (ISSUE 13 acceptance).

The properties pinned here, mapped to the issue's criteria:

* partitioned posv matches the sequential scan AND the dense reference
  across (nblocks, b, P) ladders, xla-inner f64 and pallas-inner f32 —
  including the m − 1 = 1 edge where both spike columns land in the same
  interior block (TestParity);
* partition-relative breakdown pivots map to EXACT whole-chain indices:
  a negative diagonal in an interior block and in a separator both
  reproduce the sequential impl's global info bit-for-bit, and NaN
  pollution stays contained to its batch element (TestInfoMapping — the
  `_combine_partitioned` regression);
* dispatch policy: resolve_partitions divisor snapping / √nblocks
  default / degenerate fallbacks, auto flips to partitioned only past
  PARTITION_MIN_NBLOCKS (f64 auto keeps the scan; forcing is legal —
  exact-dtype inner), factor/solve/extend reject the posv-only impl
  (TestDispatch);
* the jaxpr sequential-depth counter prices the win the bench gates:
  3·nblocks trips sequential vs 3·(m−1) + 3·P partitioned
  (TestDepthCounter, the obs/xla_audit seam);
* serve: blocktri_impl/blocktri_partitions join the cfg-hash (engines
  differing there never share AOT entries), a partitioned engine solves
  to parity with zero steady-state recompiles, the impl split lands in
  request_stats / merge_snapshots / serve-report and validates under
  obs.ledger (TestServePartitioned);
* bench ledger: partitions/depth/depth_seq/depth_reduction fields
  validate, malformed ones are LedgerIncompatible (TestLedgerFields);
* the autotune partitions × block-unroll axis measures deduped snapped
  configs and checkpoint-resumes without re-measuring (TestAutotune).

Same rig posture as test_blocktri: conftest CPU, x64 on, f64 resolves
to the xla scans, pallas-inner runs the interpreted kernels.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from capital_tpu.models import blocktri
from capital_tpu.obs import ledger, xla_audit
from capital_tpu.obs import __main__ as obs_main
from capital_tpu.serve import ServeConfig, SolveEngine, stats

from tests.test_blocktri import _chain, _dense_solve


def _posv_pair(D, C, B, *, partitions=0, inner="auto", dtype=None,
               **kw):
    """(partitioned, sequential) solves of the same chain — the A/B the
    parity ladder compares.  The sequential side forces the exact-dtype
    scan so f64 references stay f64."""
    dt = dtype or jnp.float64
    Dj, Cj, Bj = (jnp.asarray(x, dt) for x in (D, C, B))
    Xp, ip = blocktri.posv(Dj, Cj, Bj, impl="partitioned",
                           partitions=partitions, partition_inner=inner,
                           **kw)
    Xs, is_ = blocktri.posv(Dj, Cj, Bj, impl="xla", **kw)
    return (np.asarray(Xp), np.asarray(ip)), (np.asarray(Xs),
                                              np.asarray(is_))


class TestParity:
    @pytest.mark.parametrize("nblocks,b,P", [
        (8, 4, 2),   # m - 1 = 3 interior blocks
        (8, 4, 4),   # m - 1 = 1: both spikes in ONE interior block
        pytest.param(16, 4, 4, marks=pytest.mark.slow),
        pytest.param(12, 8, 3, marks=pytest.mark.slow),
        (16, 4, 0),  # default resolve: P = 4
    ])
    def test_partitioned_matches_scan_and_dense_f64(self, nblocks, b, P):
        rng = np.random.default_rng(130)
        D, C, B = _chain(rng, 2, nblocks, b, 3)
        (Xp, ip), (Xs, is_) = _posv_pair(D, C, B, partitions=P)
        np.testing.assert_array_equal(ip, 0)
        np.testing.assert_array_equal(is_, 0)
        np.testing.assert_allclose(Xp, Xs, rtol=0, atol=1e-11)
        np.testing.assert_allclose(Xp, _dense_solve(D, C, B),
                                   rtol=0, atol=1e-11)

    def test_pallas_inner_matches_dense_f32(self):
        rng = np.random.default_rng(131)
        D, C, B = _chain(rng, 2, 16, 8, 2)
        X, info = blocktri.posv(
            jnp.asarray(D, jnp.float32), jnp.asarray(C, jnp.float32),
            jnp.asarray(B, jnp.float32), impl="partitioned",
            partitions=4, partition_inner="pallas")
        ref = _dense_solve(D, C, B)
        np.testing.assert_array_equal(np.asarray(info), 0)
        err = np.abs(np.float64(np.asarray(X)) - ref).max()
        assert err < 5e-5 * np.abs(ref).max()

    def test_auto_dispatch_is_partitioned_above_threshold(self):
        # auto picks the partitioned driver at the flagship length and the
        # result still matches dense — the PR 6 "auto picks the winner"
        # contract on the new algorithm
        assert blocktri.posv_algorithm(64, jnp.float32) == "partitioned"
        rng = np.random.default_rng(132)
        D, C, B = _chain(rng, 1, 16, 4, 1)
        X, info = blocktri.posv(
            jnp.asarray(D, jnp.float32), jnp.asarray(C, jnp.float32),
            jnp.asarray(B, jnp.float32))
        np.testing.assert_array_equal(np.asarray(info), 0)
        ref = _dense_solve(D, C, B)
        err = np.abs(np.float64(np.asarray(X)) - ref).max()
        assert err < 5e-5 * np.abs(ref).max()


class TestInfoMapping:
    def _spiked_identity(self, nblocks, b, batch=2):
        """Identity chain (zero couplings) — breakdown location is then
        exactly where the poison sits, for both algorithms."""
        D = np.broadcast_to(np.eye(b), (batch, nblocks, b, b)).copy()
        C = np.zeros((batch, nblocks, b, b))
        B = np.ones((batch, nblocks, b, 1))
        return D, C, B

    @pytest.mark.parametrize("g,r", [
        pytest.param(2, 1, marks=pytest.mark.slow), (3, 0), (5, 2)])
    def test_interior_pivot_maps_to_global_index(self, g, r):
        # P=2, m=4: separators at blocks 3 and 7; g ∈ {2, 5} interior,
        # g = 3 separator — all must report a pivot INSIDE the poisoned
        # block at the whole-chain offset (the CPU LAPACK path NaN-fills
        # the whole failed block, so the local row is backend-defined;
        # the partition-relative → global mapping is what we pin) and be
        # bit-identical to the sequential impl's answer
        nblocks, b = 8, 4
        D, C, B = self._spiked_identity(nblocks, b)
        D[0, g, r, r] = -1.0
        (Xp, ip), (Xs, is_) = _posv_pair(D, C, B, partitions=2)
        assert ip[0] == is_[0]
        assert g * b < ip[0] <= (g + 1) * b
        assert ip[1] == is_[1] == 0

    def test_nan_contained_to_batch_element(self):
        nblocks, b, g = 8, 4, 4
        D, C, B = self._spiked_identity(nblocks, b)
        D[1, g, 0, 0] = np.nan
        (Xp, ip), (Xs, is_) = _posv_pair(D, C, B, partitions=2)
        assert ip[0] == 0 and is_[0] == 0
        assert ip[1] == is_[1] != 0
        # pollution flows forward: the reported first-broken index can
        # never precede the poisoned block
        assert ip[1] >= g * b + 1
        # the healthy element's solution is untouched by its neighbor
        np.testing.assert_allclose(Xp[0], Xs[0], rtol=0, atol=1e-12)


class TestDispatch:
    def test_resolve_partitions_policy(self):
        assert blocktri.resolve_partitions(64) == 8      # √64
        assert blocktri.resolve_partitions(16) == 4
        assert blocktri.resolve_partitions(64, 16) == 16
        assert blocktri.resolve_partitions(64, 5) == 4   # snap down
        assert blocktri.resolve_partitions(64, 63) == 32  # cap nblocks/2
        assert blocktri.resolve_partitions(7) == 1       # prime
        assert blocktri.resolve_partitions(2) == 1       # m >= 2 floor
        assert blocktri.resolve_partitions(4, 2) == 2

    def test_auto_policy(self):
        f32, f64 = jnp.float32, jnp.float64
        assert blocktri.posv_algorithm(64, f32) == "partitioned"
        assert blocktri.posv_algorithm(8, f32) == "scan"
        # explicit partitions opt in below the threshold
        assert blocktri.posv_algorithm(8, f32, partitions=2) == "partitioned"
        # f64 auto keeps the sequential scan; forcing is the explicit
        # opt-in (exact-dtype inner, no downgrade)
        assert blocktri.posv_algorithm(64, f64) == "scan"
        assert blocktri.posv_algorithm(
            64, f64, impl="partitioned") == "partitioned"
        # unsplittable chains resolve to scan even when forced
        assert blocktri.posv_algorithm(7, f32, impl="partitioned") == "scan"

    def test_forced_partitioned_f64_is_exact(self):
        rng = np.random.default_rng(133)
        D, C, B = _chain(rng, 1, 8, 4, 1)
        X, info = blocktri.posv(
            jnp.asarray(D), jnp.asarray(C), jnp.asarray(B),
            impl="partitioned", partitions=2)
        assert X.dtype == jnp.float64
        np.testing.assert_array_equal(np.asarray(info), 0)
        np.testing.assert_allclose(np.asarray(X), _dense_solve(D, C, B),
                                   rtol=0, atol=1e-11)

    def test_unsplittable_falls_back_to_scan(self):
        rng = np.random.default_rng(134)
        D, C, B = _chain(rng, 1, 3, 4, 1)  # prime: no valid split
        X, info = blocktri.posv(
            jnp.asarray(D), jnp.asarray(C), jnp.asarray(B),
            impl="partitioned")
        np.testing.assert_array_equal(np.asarray(info), 0)
        np.testing.assert_allclose(np.asarray(X), _dense_solve(D, C, B),
                                   rtol=0, atol=1e-11)

    def test_factor_solve_extend_reject_partitioned(self):
        rng = np.random.default_rng(135)
        D, C, B = _chain(rng, 1, 4, 4, 1)
        Dj, Cj, Bj = (jnp.asarray(x) for x in (D, C, B))
        with pytest.raises(ValueError, match="posv-only"):
            blocktri.factor(Dj, Cj, impl="partitioned")
        L, Wt, _ = blocktri.factor(Dj, Cj)
        with pytest.raises(ValueError, match="posv-only"):
            blocktri.solve(L, Wt, Bj, impl="partitioned")
        with pytest.raises(ValueError, match="posv-only"):
            blocktri.extend(Dj, Cj, L[:, -1], impl="partitioned")

    def test_bad_partition_inner_rejected(self):
        rng = np.random.default_rng(136)
        D, C, B = _chain(rng, 1, 8, 4, 1)
        with pytest.raises(ValueError, match="partition_inner"):
            blocktri.posv(jnp.asarray(D), jnp.asarray(C), jnp.asarray(B),
                          impl="partitioned", partition_inner="cuda")


class TestDepthCounter:
    def test_scan_depth_counts_trip_lengths(self):
        def body(c, x):
            return c + x, c

        def fn(xs):
            return jax.lax.scan(body, jnp.zeros(()), xs)

        assert xla_audit.sequential_depth(fn, jnp.ones(5)) == 5

    def test_posv_depth_sequential_vs_partitioned(self):
        rng = np.random.default_rng(137)
        nblocks, b = 16, 4
        D, C, B = _chain(rng, 1, nblocks, b, 1)
        Dj, Cj, Bj = (jnp.asarray(x) for x in (D, C, B))
        d_seq = xla_audit.sequential_depth(
            lambda d, c, r: blocktri.posv(d, c, r, impl="xla"),
            Dj, Cj, Bj)
        d_par = xla_audit.sequential_depth(
            lambda d, c, r: blocktri.posv(
                d, c, r, impl="partitioned", partitions=4,
                partition_inner="xla"),
            Dj, Cj, Bj)
        # 3 scans × nblocks trips vs 3 × (m − 1) interior + 3 × P reduced
        assert d_seq == 3 * nblocks
        assert d_par == 3 * (nblocks // 4 - 1) + 3 * 4
        assert d_seq / d_par > 2


BT_PAR_CFG = ServeConfig(
    buckets=(8,),
    rows_buckets=(32,),
    nrhs_buckets=(1,),
    max_batch=2,
    max_delay_s=10.0,
    nblocks_buckets=(4,),
    block_buckets=(4,),
    blocktri_impl="partitioned",
    blocktri_partitions=2,
)


class TestServePartitioned:
    def test_cfg_validation(self):
        # the engine is the validation seam (ServeConfig is a frozen
        # plain dataclass, like the bucket fields)
        with pytest.raises(ValueError, match="blocktri_impl"):
            SolveEngine(cfg=ServeConfig(blocktri_impl="spike"))
        with pytest.raises(ValueError, match="blocktri_partitions"):
            SolveEngine(cfg=ServeConfig(blocktri_partitions=-1))

    def test_blocktri_knobs_join_config_hash(self):
        base = dict(
            buckets=BT_PAR_CFG.buckets,
            rows_buckets=BT_PAR_CFG.rows_buckets,
            nrhs_buckets=BT_PAR_CFG.nrhs_buckets,
            max_batch=BT_PAR_CFG.max_batch,
            max_delay_s=BT_PAR_CFG.max_delay_s,
            nblocks_buckets=BT_PAR_CFG.nblocks_buckets,
            block_buckets=BT_PAR_CFG.block_buckets,
        )
        hashes = {
            SolveEngine(cfg=ServeConfig(**base, **kw))._cfg_hash
            for kw in (
                {},
                {"blocktri_impl": "partitioned"},
                {"blocktri_impl": "partitioned", "blocktri_partitions": 2},
                {"blocktri_impl": "scan"},
            )
        }
        assert len(hashes) == 4  # no pair may ever share an AOT entry

    def test_partitioned_engine_parity_and_stats(self):
        rng = np.random.default_rng(138)
        eng = SolveEngine(cfg=BT_PAR_CFG)
        for seed in range(2):
            D, C, B = _chain(rng, 1, 4, 4, 1)
            r = eng.solve("posv_blocktri", np.stack([D[0], C[0]]), B[0])
            assert r.ok and r.batched
            np.testing.assert_allclose(
                np.asarray(r.x, np.float64), _dense_solve(D, C, B)[0],
                rtol=0, atol=1e-4)
        c = eng.cache_stats()
        assert (c["hits"], c["misses"]) == (1, 1)  # zero steady-state
        assert eng.stats.blocktri_impls == {"partitioned": 2}
        snap = eng.stats.snapshot(cache=c)
        assert snap["blocktri_impls"] == {"partitioned": 2}
        assert ledger.validate_request_stats(snap) == []

    def test_scan_engine_notes_scan(self):
        rng = np.random.default_rng(139)
        cfg_scan = ServeConfig(
            buckets=(8,), rows_buckets=(32,), nrhs_buckets=(1,),
            max_batch=2, max_delay_s=10.0, nblocks_buckets=(4,),
            block_buckets=(4,), blocktri_impl="scan")
        eng = SolveEngine(cfg=cfg_scan)
        D, C, B = _chain(rng, 1, 4, 4, 1)
        assert eng.solve("posv_blocktri", np.stack([D[0], C[0]]),
                         B[0]).ok
        assert eng.stats.blocktri_impls == {"scan": 1}

    def test_merge_snapshots_pools_the_split(self):
        def snap(n_scan, n_par, replica):
            c = stats.Collector(replica_id=replica)
            for _ in range(n_scan):
                c.note_blocktri_impl("scan")
            for _ in range(n_par):
                c.note_blocktri_impl("partitioned")
            c.record_request("posv_blocktri", 0.01, ok=True)
            return c.snapshot()

        merged = stats.merge_snapshots([snap(2, 1, "r0"), snap(0, 3, "r1")])
        assert merged["blocktri_impls"] == {"scan": 2, "partitioned": 4}
        assert ledger.validate_request_stats(merged) == []

    def test_malformed_split_is_flagged(self):
        c = stats.Collector()
        c.note_blocktri_impl("partitioned")
        c.record_request("posv_blocktri", 0.01, ok=True)
        snap = c.snapshot()
        snap["blocktri_impls"] = {"cuda": 1}
        assert any("blocktri_impls" in p
                   for p in ledger.validate_request_stats(snap))
        snap["blocktri_impls"] = {"scan": -1}
        assert any("blocktri_impls" in p
                   for p in ledger.validate_request_stats(snap))

    def test_serve_report_prints_impl_split(self, tmp_path, capsys):
        c = stats.Collector()
        c.record_request("posv_blocktri", 0.01, ok=True)
        c.note_blocktri_impl("partitioned")
        c.note_blocktri_impl("scan")
        path = tmp_path / "serve.jsonl"
        c.emit(str(path), cache={"hits": 1, "misses": 1,
                                 "warmup_compiles": 1, "entries": 1,
                                 "hit_rate": 0.5})
        assert obs_main.main(["serve-report", str(path)]) == 0
        out = capsys.readouterr().out
        assert "blocktri partitioned=1 scan=1" in out


def _par_measured(**over):
    m = {"metric": "blocktri_tflops", "value": 1.5, "nblocks": 64,
         "block": 8, "n": 512, "batch": 2, "nrhs": 2,
         "impl": "partitioned", "speedup": 6.0, "partitions": 8,
         "depth": 45, "depth_seq": 192, "depth_reduction": 4.267}
    m.update(over)
    return m


class TestLedgerFields:
    def test_partitioned_record_passes_diff(self):
        rec = ledger.record("bench:blocktri", ledger.manifest(),
                            measured=_par_measured())
        assert ledger.diff([rec], [rec]) == []

    @pytest.mark.parametrize("field,bad", [
        ("partitions", 0), ("partitions", "8"), ("depth", -1),
        ("depth_seq", 1.5), ("depth_reduction", 0),
    ])
    def test_malformed_fields_flagged(self, field, bad):
        probs = ledger.validate_blocktri_measured(_par_measured(**{field: bad}))
        assert any(field in p for p in probs)

    def test_malformed_record_is_incompatible(self):
        rec = ledger.record("bench:blocktri", ledger.manifest(),
                            measured=_par_measured(depth=0))
        with pytest.raises(ledger.LedgerIncompatible, match="depth"):
            ledger.diff([rec], [rec])


class TestAutotune:
    def test_partitions_axis_dedupes_and_resumes(self, tmp_path,
                                                 monkeypatch, capsys):
        from capital_tpu.autotune import sweep
        from capital_tpu.bench import harness
        from capital_tpu.parallel.topology import Grid

        grid = Grid.square(c=1, devices=jax.devices("cpu")[:1])
        kw = dict(batch=2, nrhs=1, dtype=jnp.float32,
                  out_dir=str(tmp_path), calls=2, warmup=1,
                  checkpoint=True, impls=("partitioned",),
                  partitions=(0, 2, 4), blocks=(0,))
        res1 = sweep.tune_blocktri(grid, 8, 4, **kw)
        # resolve_partitions(8, 0) == 2: the 0 and 2 requests collapse to
        # ONE measured config; 4 stays distinct
        ids = sorted(r.config_id for r in res1)
        assert ids == ["part_p2_b4", "part_p4_b4"]

        calls = []
        real = harness.latency_samples

        def counting(*a, **k):
            calls.append(1)
            return real(*a, **k)

        monkeypatch.setattr(harness, "latency_samples", counting)
        res2 = sweep.tune_blocktri(grid, 8, 4, **kw)
        assert not calls  # everything resumed, nothing re-measured
        assert [r.config_id for r in res2] == [r.config_id for r in res1]
        assert [r.seconds for r in res2] == [r.seconds for r in res1]
