"""PR 9 multi-replica serving tests: the Router over N EngineReplicas,
the failure paths (crash re-dispatch, drain under load, affinity
rebalance as a disk hit), cross-replica stats merging, the benign
disk-write race counter, and the serve-report aggregation gates.

The acceptance properties of ISSUE 9 / docs/SERVING.md "Multi-replica
serving" are asserted directly:

* **crash re-dispatch** — a killed replica's in-flight requests land on
  the survivors, every submitted ticket completes exactly once (first
  result wins; late crash-race results count as `duplicates`, never as
  a second client-visible landing);
* **drain under load** — draining one replica lands its whole window
  while the rest keep admitting; nothing is dropped, and the drained
  replica admits again after resume;
* **rebalance = disk hit** — with bucket_affinity and a shared
  persist_dir, the replacement for a killed replica warms its remapped
  buckets from disk (zero fresh compiles), the cache-locality half of
  the rendezvous-hash story;
* **aggregation** — merge_snapshots sums counts, pools percentiles from
  raw samples (exact) or takes the worst tail, never a mean of
  percentiles; `obs serve-report --aggregate --min-replicas N` gates
  the same merge from ledger records alone.

Thread replicas throughout (full router semantics, no process-spawn
flakiness); one slow-marked ProcessReplica roundtrip pins the pipe
transport + env-before-jax spawn contract.
"""

import os
import time

import numpy as np
import pytest

from capital_tpu.obs import __main__ as obs_main
from capital_tpu.obs import ledger
from capital_tpu.serve import stats as serve_stats
from capital_tpu.serve.replica import ProcessReplica, ThreadReplica
from capital_tpu.serve.router import (
    Router,
    RouterConfig,
    _rendezvous,
    _rung,
    bucket_signature,
)

# one tiny pallas-route f32 bucket: pure-HLO executables (persistable on
# the CPU rig), 1-2 compiles per replica.  Tight max_delay_s keeps the
# replica worker's deadline flushes fast (no client-side pump forcing —
# the worker loop owns the engine).
def _cfg(persist_dir=None, **kw):
    from capital_tpu.serve.engine import ServeConfig

    return ServeConfig(
        buckets=(8,), rows_buckets=(32,), nrhs_buckets=(1,),
        max_batch=2, max_delay_s=0.005, small_n_impl="pallas",
        persist_dir=str(persist_dir) if persist_dir else None, **kw,
    )


_SPECS = [("posv", (8, 8), (8, 1), "float32")]


def _posv(rng):
    G = rng.standard_normal((8, 8)).astype(np.float32)
    A = (G @ G.T + 8 * np.eye(8, dtype=np.float32)).astype(np.float32)
    B = rng.standard_normal((8, 1)).astype(np.float32)
    return A, B


def _router(n, persist_dir=None, policy="least_loaded", prefix="r"):
    r = Router(RouterConfig(policy=policy))
    for i in range(n):
        r.add_replica(ThreadReplica(f"{prefix}{i}", _cfg(persist_dir)))
    return r


def _pump_until_done(router, tickets, timeout=120.0):
    deadline = time.monotonic() + timeout
    while not all(t.done for t in tickets):
        router.pump()
        if time.monotonic() > deadline:
            raise TimeoutError(
                f"{sum(not t.done for t in tickets)} tickets never landed"
            )
        time.sleep(1e-3)


class TestBucketSignature:
    def test_rung_smallest_fit(self):
        assert _rung((8, 16, 32), 9) == 16
        assert _rung((32, 8, 16), 8) == 8  # order-independent
        assert _rung((8, 16), 17) is None

    def test_posv_and_lstsq_signatures(self):
        lad = {"buckets": (8, 16), "rows_buckets": (32,),
               "nrhs_buckets": (1, 4)}
        assert bucket_signature("posv", (8, 8), (8, 1), "float32", lad) \
            == ("posv", "float32", 8, 1, 0, "balanced")
        assert bucket_signature("lstsq", (30, 7), (30, 3), "float32", lad) \
            == ("lstsq", "float32", 8, 4, 32, "balanced")
        assert bucket_signature("inv", (5, 5), None, "float32", lad) \
            == ("inv", "float32", 8, None, 0, "balanced")
        # the accuracy tier joins the key: a guaranteed request must not
        # share affinity with the same-shape balanced bucket
        assert bucket_signature("posv", (8, 8), (8, 1), "float32", lad,
                                tier="guaranteed") \
            == ("posv", "float32", 8, 1, 0, "guaranteed")

    def test_oversize_keys_on_exact_shape(self):
        lad = {"buckets": (8,), "rows_buckets": (32,), "nrhs_buckets": (1,)}
        sig = bucket_signature("posv", (64, 64), (64, 1), "float32", lad)
        assert sig[0] == "oversize" and sig[3] == (64, 64)

    def test_rendezvous_removal_remaps_only_owner(self):
        ids = ["a", "b", "c"]
        sigs = [("posv", "float32", 8, 1, 0), ("inv", "float32", 8, None, 0),
                ("lstsq", "float32", 8, 4, 32)]
        for sig in sigs:
            owner = _rendezvous(sig, ids)
            survivor_sets = [[i for i in ids if i != gone]
                             for gone in ids if gone != owner]
            for rest in survivor_sets:
                # removing a NON-owner never moves the signature
                assert _rendezvous(sig, rest) == owner


class TestRouterBasics:
    def test_unknown_policy_rejected(self):
        with pytest.raises(ValueError, match="dispatch policy"):
            Router(RouterConfig(policy="round_robin"))

    def test_no_healthy_replica_refuses_admission(self):
        r = Router()
        with pytest.raises(RuntimeError, match="no healthy replica"):
            r.submit("posv", np.eye(8, dtype=np.float32),
                     np.ones((8, 1), np.float32))

    def test_submit_result_roundtrip_and_invariant(self):
        rng = np.random.default_rng(0)
        r = _router(2)
        try:
            fresh = r.warmup(_SPECS)
            assert set(fresh) == {"r0", "r1"}
            work = [_posv(rng) for _ in range(6)]
            tickets = [r.submit("posv", A, B) for A, B in work]
            _pump_until_done(r, tickets)
            for (A, B), t in zip(work, tickets):
                res = t.result(timeout=1.0)
                assert res.ok and res.replica_id in ("r0", "r1")
                x = np.asarray(res.x, dtype=np.float64)
                resid = np.linalg.norm(A.astype(np.float64) @ x - B) \
                    / np.linalg.norm(B)
                assert resid < 1e-4
            c = r.counters()
            assert c["completed"] == 6 and c["parked"] == 0
            assert c["duplicates"] == 0 and c["redispatched"] == 0
            # no-drop invariant: everything dispatched is accounted for
            out = sum(v["outstanding"] for v in c["per_replica"].values())
            assert c["completed"] + c["parked"] + out == c["dispatched"]
        finally:
            r.stop()

    def test_least_loaded_spreads(self):
        rng = np.random.default_rng(1)
        r = _router(2)
        try:
            r.warmup(_SPECS)
            tickets = [r.submit("posv", *_posv(rng)) for _ in range(8)]
            per = r.counters()["per_replica"]
            # fewest-outstanding wins: both replicas carry load (exact split
            # depends on how fast results land between submits)
            assert per["r0"]["dispatched"] + per["r1"]["dispatched"] == 8
            assert per["r0"]["dispatched"] >= 1 and per["r1"]["dispatched"] >= 1
            _pump_until_done(r, tickets)
        finally:
            r.stop()

    def test_ladder_disagreement_rejected(self):
        r = _router(1)
        try:
            from capital_tpu.serve.engine import ServeConfig

            other = ServeConfig(buckets=(16,), rows_buckets=(32,),
                                nrhs_buckets=(1,), small_n_impl="pallas")
            with pytest.raises(ValueError, match="ladders"):
                r.add_replica(ThreadReplica("rX", other))
        finally:
            r.stop()


class TestFailurePaths:
    def test_crash_redispatch_loses_nothing(self):
        rng = np.random.default_rng(2)
        r = _router(2)
        try:
            r.warmup(_SPECS)
            work = [_posv(rng) for _ in range(10)]
            tickets = [r.submit("posv", A, B) for A, B in work]
            # abrupt death with a half-full window on r0; the next pump
            # observes it and re-dispatches everything unanswered
            r.kill_replica("r0")
            _pump_until_done(r, tickets)
            c = r.counters()
            assert c["failed_replicas"] == 1
            assert c["completed"] == 10 and c["parked"] == 0
            # exactly one client-visible result per ticket, all from the
            # survivor or swept from the victim's outbox pre-kill
            for (A, B), t in zip(work, tickets):
                assert t.response is not None and t.response.ok
            # first-wins: duplicates (crash-raced second results) never
            # inflate completed
            assert c["completed"] + c["duplicates"] >= c["redispatched"]
            assert "r0" not in c["per_replica"]
        finally:
            r.stop()

    def test_kill_all_parks_then_new_replica_flushes(self):
        rng = np.random.default_rng(3)
        r = _router(1)
        try:
            r.warmup(_SPECS)
            tickets = [r.submit("posv", *_posv(rng)) for _ in range(3)]
            r.kill_replica("r0")
            r.pump()
            c = r.counters()
            # admitted work parks (never drops); NEW admission refuses
            assert c["parked"] + c["completed"] == 3
            if c["parked"]:
                with pytest.raises(RuntimeError, match="no healthy"):
                    r.submit("posv", *_posv(rng))
            r.add_replica(ThreadReplica("r1", _cfg()))
            r.warmup(_SPECS)
            _pump_until_done(r, tickets)
            assert r.counters()["parked"] == 0
            assert all(t.response.ok for t in tickets)
        finally:
            r.stop()

    def test_drain_under_load_lands_everything(self):
        rng = np.random.default_rng(4)
        r = _router(2)
        try:
            r.warmup(_SPECS)
            first = [r.submit("posv", *_posv(rng)) for _ in range(6)]
            assert r.drain_replica("r0", timeout=60.0)
            per = r.counters()["per_replica"]["r0"]
            assert per["draining"] and per["outstanding"] == 0
            # admission continues on the survivor while r0 is draining
            second = [r.submit("posv", *_posv(rng)) for _ in range(4)]
            assert all(t.replica_id == "r1" for t in second)
            _pump_until_done(r, first + second)
            assert all(t.response.ok for t in first + second)
            r.resume_replica("r0")
            t = r.submit("posv", *_posv(rng))
            # least_loaded sends the next request to the idle, resumed r0
            assert t.replica_id == "r0"
            _pump_until_done(r, [t])
        finally:
            r.stop()

    def test_drain_all_refuses_admission(self):
        r = _router(1)
        try:
            r.warmup(_SPECS)
            r.drain_replica("r0")
            with pytest.raises(RuntimeError, match="no healthy"):
                r.submit("posv", np.eye(8, dtype=np.float32),
                         np.ones((8, 1), np.float32))
            r.resume_replica("r0")
        finally:
            r.stop()

    def test_first_wins_counts_duplicate(self):
        r = _router(1)
        try:
            r.warmup(_SPECS)
            rng = np.random.default_rng(5)
            t = r.submit("posv", *_posv(rng))
            _pump_until_done(r, [t])
            st = r._states["r0"]
            payload = {
                "request_id": t.request_id, "op": "posv", "ok": True,
                "x": np.asarray(t.response.x), "info": None, "error": None,
                "bucket": None, "batched": True, "latency_s": 0.0,
                "queue_wait_s": None, "device_s": None,
            }
            # a crash-raced second landing for the same ticket: dropped,
            # counted, and completed does not double
            assert r._land(st, t.request_id, payload) == 0
            assert r.duplicates == 1 and r.completed == 1
        finally:
            r.stop()


class TestAffinityRebalance:
    def test_rebalance_is_disk_hit_not_compile(self, tmp_path):
        rng = np.random.default_rng(6)
        r = _router(2, persist_dir=tmp_path, policy="bucket_affinity")
        try:
            fresh = r.warmup(_SPECS)
            # shared dir: exactly one replica compiled, the other disk-hit
            vals = sorted(fresh.values())
            assert vals[0] == 0 and vals[-1] > 0
            work = [_posv(rng) for _ in range(4)]
            tickets = [r.submit("posv", A, B) for A, B in work]
            # affinity: one signature in this workload -> ONE owner
            owners = {t.replica_id for t in tickets}
            assert len(owners) == 1
            _pump_until_done(r, tickets)
            before = {rid: s["cache"]["compiles"]
                      for rid, s in r.replica_stats().items()}

            r.kill_replica(owners.pop())
            r.pump()
            rep = ThreadReplica("r2", _cfg(tmp_path))
            r.add_replica(rep)
            rep_fresh = r.warmup(_SPECS)
            # the replacement (and the remapped bucket's new owner) warm
            # from the SHARED disk tier: zero fresh XLA compiles anywhere
            assert all(v == 0 for v in rep_fresh.values() if v is not None)
            more = [r.submit("posv", *_posv(rng)) for _ in range(4)]
            _pump_until_done(r, more)
            assert all(t.response.ok for t in more)
            snaps = r.replica_stats()
            for rid, snap in snaps.items():
                # rebalance cost ZERO new XLA compiles: the survivor keeps
                # whatever cold-warmup count it had, the replacement has none
                assert snap["cache"]["compiles"] == before.get(rid, 0), rid
                assert snap["cache"]["misses"] == 0, rid
        finally:
            r.stop()


class TestMergeSnapshots:
    def _snap(self, replica_id, lat_s, batches=2, occ=0.5, samples=True):
        c = serve_stats.Collector(replica_id=replica_id)
        for v in lat_s:
            c.record_request("posv", v, ok=True, queue_wait_s=v / 2,
                             device_s=v / 2)
        for _ in range(batches):
            c.note_batch(occ)
        cache = {"hits": 3, "misses": 1, "warmup_compiles": 2,
                 "compiles": 2, "entries": 2, "hit_rate": 0.75,
                 "disk": {"hits": 1, "misses": 1, "errors": 0, "skips": 0,
                          "races": 1}}
        return c.snapshot(cache, samples=samples)

    def test_empty_raises(self):
        with pytest.raises(ValueError):
            serve_stats.merge_snapshots([])

    def test_pooled_percentiles_exact(self):
        a = self._snap("r0", [0.001, 0.002, 0.003])
        b = self._snap("r1", [0.100, 0.200, 0.300])
        m = serve_stats.merge_snapshots([a, b])
        assert m["requests"] == 6 and m["replicas"] == 2
        assert m["replica_ids"] == ["r0", "r1"]
        # exact pooled p50 of the union, NOT a mean of the two p50s
        from capital_tpu.bench.harness import percentiles

        pool = [0.001, 0.002, 0.003, 0.1, 0.2, 0.3]
        want = round(percentiles(pool)["p50"] * 1e3, 4)
        assert m["latency_ms"]["p50"] == want
        assert "samples" not in m and "replica_id" not in m

    def test_max_of_tails_without_samples(self):
        a = self._snap("r0", [0.001, 0.002], samples=False)
        b = self._snap("r1", [0.100, 0.200], samples=True)
        m = serve_stats.merge_snapshots([a, b])
        # one contributor lacks populations -> worst-tail bound (max),
        # elementwise, never a mean
        assert m["latency_ms"]["p99"] == max(
            a["latency_ms"]["p99"], b["latency_ms"]["p99"])

    def test_cache_and_occupancy_merge(self):
        a = self._snap("r0", [0.001], batches=1, occ=1.0)
        b = self._snap("r1", [0.002], batches=3, occ=0.5)
        m = serve_stats.merge_snapshots([a, b])
        assert m["cache"]["hits"] == 6 and m["cache"]["misses"] == 2
        assert m["cache"]["hit_rate"] == 0.75
        assert m["cache"]["disk"]["races"] == 2
        # batch-weighted, not a plain mean: (1*1.0 + 3*0.5) / 4
        assert m["batch_occupancy_mean"] == 0.625
        assert not ledger.validate_request_stats(m)

    def test_merged_block_valid_under_ledger(self):
        snaps = [self._snap(f"r{i}", [0.001 * (i + 1)]) for i in range(3)]
        m = serve_stats.merge_snapshots(snaps)
        assert ledger.validate_request_stats(m) == []


class TestLedgerValidation:
    def _base(self):
        return serve_stats.Collector(replica_id="r0").snapshot()

    def test_replica_tags_validate(self):
        snap = self._base()
        assert ledger.validate_request_stats(snap) == []
        bad = dict(snap, replica_id=7)
        assert any("replica_id" in p
                   for p in ledger.validate_request_stats(bad))
        bad = dict(snap, replicas=0)
        assert any("replicas" in p
                   for p in ledger.validate_request_stats(bad))
        bad = dict(snap, replica_ids="r0")
        assert any("replica_ids" in p
                   for p in ledger.validate_request_stats(bad))

    def test_samples_block_flagged_in_records(self):
        snap = serve_stats.Collector(replica_id="r0").snapshot(samples=True)
        assert any("samples" in p
                   for p in ledger.validate_request_stats(snap))


class TestDiskRaces:
    def _exe(self):
        import jax
        import jax.numpy as jnp

        return jax.jit(lambda x: x + 1).lower(
            jnp.ones((4,), np.float32)).compile()

    def test_lost_race_counts_race_not_error(self, tmp_path):
        from capital_tpu.serve.cache import ExecutableCache

        exe = self._exe()
        key = ("k", 1)
        c1 = ExecutableCache(persist_dir=str(tmp_path))
        c2 = ExecutableCache(persist_dir=str(tmp_path))
        c1._store(key, exe)
        assert c1.disk_races == 0 and os.path.exists(c1.entry_path(key))
        # the multi-replica warmup pattern: a second engine compiled the
        # same program and finds a valid entry already on disk
        c2._store(key, exe)
        assert c2.disk_races == 1 and c2.disk_errors == 0
        assert c2.stats()["disk"]["races"] == 1

    def test_store_failure_with_valid_entry_is_race(self, tmp_path,
                                                    monkeypatch):
        from jax.experimental import serialize_executable

        from capital_tpu.serve.cache import ExecutableCache

        exe = self._exe()
        key = ("k", 2)
        c1 = ExecutableCache(persist_dir=str(tmp_path))
        c1._store(key, exe)
        c2 = ExecutableCache(persist_dir=str(tmp_path))
        # make c2 lose the race mid-write: the pre-store peek misses (first
        # call forced False), its serialize explodes, and the post-failure
        # peek finds c1's valid entry -> benign race, NOT a disk error
        monkeypatch.setattr(
            serialize_executable, "serialize",
            lambda _exe: (_ for _ in ()).throw(RuntimeError("boom")))
        real_peek = ExecutableCache._peek_valid

        calls = {"n": 0}

        def peek(self, k):
            calls["n"] += 1
            if calls["n"] == 1:
                return False  # lose the pre-store check, enter the write
            return real_peek(self, k)

        monkeypatch.setattr(ExecutableCache, "_peek_valid", peek)
        c2._store(key, exe)
        assert c2.disk_races == 1 and c2.disk_errors == 0

    def test_store_failure_without_entry_is_error(self, tmp_path,
                                                  monkeypatch):
        from jax.experimental import serialize_executable

        from capital_tpu.serve.cache import ExecutableCache

        c = ExecutableCache(persist_dir=str(tmp_path))
        monkeypatch.setattr(
            serialize_executable, "serialize",
            lambda _exe: (_ for _ in ()).throw(RuntimeError("boom")))
        c._store(("k", 3), self._exe())
        assert c.disk_errors == 1 and c.disk_races == 0


class TestServeReportAggregate:
    def _write_ledger(self, path, replica_ids, router_block=None):
        recs = []
        snaps = []
        for rid in replica_ids:
            c = serve_stats.Collector(replica_id=rid)
            c.record_request("posv", 0.01, ok=True)
            c.note_batch(0.5)
            snaps.append(c.snapshot(samples=True))
            clean = {k: v for k, v in snaps[-1].items() if k != "samples"}
            recs.append(ledger.record("serve:request_stats",
                                      ledger.manifest(),
                                      request_stats=clean))
        if snaps:
            agg = serve_stats.merge_snapshots(snaps)
            extra = {"router": router_block} if router_block else {}
            recs.append(ledger.record("serve:request_stats",
                                      ledger.manifest(),
                                      request_stats=agg, **extra))
        for r in recs:
            ledger.append(str(path), r)

    def test_aggregate_gate_passes(self, tmp_path, capsys):
        p = tmp_path / "l.jsonl"
        self._write_ledger(p, ["r0", "r1"], router_block={"qps": 12.5})
        rc = obs_main.main(["serve-report", str(p), "--aggregate",
                            "--min-replicas", "2", "--min-hit-rate", "1.0"])
        out = capsys.readouterr().out
        assert rc == 0
        assert "aggregate[" in out and "qps_sum=12.5" in out

    def test_min_replicas_fails_short(self, tmp_path):
        p = tmp_path / "l.jsonl"
        self._write_ledger(p, ["r0", "r1"])
        assert obs_main.main(["serve-report", str(p),
                              "--min-replicas", "3"]) == 1

    def test_aggregate_fails_loudly_without_tags(self, tmp_path):
        p = tmp_path / "l.jsonl"
        c = serve_stats.Collector()  # untagged single-engine record
        c.record_request("posv", 0.01, ok=True)
        ledger.append(str(p), ledger.record(
            "serve:request_stats", ledger.manifest(),
            request_stats=c.snapshot()))
        assert obs_main.main(["serve-report", str(p), "--aggregate"]) == 1
        assert obs_main.main(["serve-report", str(p),
                              "--min-replicas", "1"]) == 1

    def test_gates_with_empty_ledger_fail(self, tmp_path):
        p = tmp_path / "empty.jsonl"
        p.write_text("")
        assert obs_main.main(["serve-report", str(p), "--aggregate"]) == 1


class TestHostOnlyLint:
    def test_module_level_jax_import_flagged(self):
        from capital_tpu.lint import source

        bad = ("import jax\n"
               "def f():\n"
               "    import jax.numpy as jnp\n"
               "    return jnp\n")
        fs = source.lint_source("pkg/serve/router.py", text=bad)
        assert [(f.rule, f.line) for f in fs] == [("host-only-dispatch", 1)]
        fs = source.lint_source("pkg/serve/replica.py",
                                text="from jax import numpy\n")
        assert fs and fs[0].rule == "host-only-dispatch"
        # only the dispatch plane is constrained
        assert not source.lint_source("pkg/serve/engine.py",
                                      text="import jax\n")

    def test_real_dispatch_plane_is_clean(self):
        from capital_tpu.lint import source

        root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        for name in ("router.py", "replica.py"):
            path = os.path.join(root, "capital_tpu", "serve", name)
            hits = [f for f in source.lint_source(path)
                    if f.rule == "host-only-dispatch"]
            assert hits == []


class TestScalingAB:
    def test_compare_replicas_records_efficiency(self, tmp_path):
        from capital_tpu.serve import loadgen

        cfg = _cfg(tmp_path / "cache")
        wl = loadgen.Workload(requests=6, concurrency=2, ops=("posv",),
                              ns=(8,), nrhs=(1,))
        res = loadgen.compare_replicas(
            cfg, wl, replica_counts=(1, 2),
            ledger_path=str(tmp_path / "ab.jsonl"))
        for n in (1, 2):
            assert res[n]["failed"] == 0
            assert res[n]["requests"] == 6 * n
        blk = res[2]["router_block"]
        assert blk["baseline_qps"] == res[1]["qps"]
        assert blk["scaling_efficiency"] == pytest.approx(
            (res[2]["qps"] / 2) / res[1]["qps"], rel=1e-3)
        recs = ledger.read(str(tmp_path / "ab.jsonl"))
        for r in recs:
            assert ledger.validate_request_stats(r["request_stats"]) == []
        aggs = [r for r in recs if r.get("router")]
        assert len(aggs) == 2
        assert "scaling_efficiency" in aggs[-1]["router"]


@pytest.mark.slow
class TestProcessReplica:
    def test_pipe_roundtrip(self, tmp_path):
        rep = ProcessReplica("p0", _cfg(tmp_path),
                             env={"JAX_PLATFORMS": "cpu"})
        rep.start()
        try:
            info = rep.warmup(_SPECS, timeout=600.0)
            assert info is not None and info["fresh"] >= 1
            rng = np.random.default_rng(7)
            A, B = _posv(rng)
            rep.submit(0, "posv", A, B)
            deadline = time.monotonic() + 120.0
            result = None
            while result is None and time.monotonic() < deadline:
                for msg in rep.poll():
                    if msg[0] == "result":
                        result = msg[2]
                time.sleep(0.01)
            assert result is not None and result["ok"]
            x = np.asarray(result["x"], dtype=np.float64)
            assert np.linalg.norm(A.astype(np.float64) @ x - B) \
                / np.linalg.norm(B) < 1e-4
            assert rep.ping() is not None
        finally:
            rep.stop()
            assert not rep.alive()
