"""The fused recursion-tail megakernel (pallas_tpu.fused_tail) and its
trace-time gates, plus the double-buffered base-case write-back.

The claims under test, each a contract the 3%-gap work leans on:

* fusing a plan() subtree into ONE pallas_call changes the launch
  structure, NOT the numbers — fused and unfused factors agree at the
  compute dtype's tolerance across depths, dtypes and window positions;
* the kernel symmetrizes from the UPPER half, so Schur windows carrying
  garbage below the diagonal factor identically to fully-symmetric input
  (the "both uplos" contract of the in-kernel sweep);
* f64 falls back to the unfused recursion AT TRACE TIME (the PR 6
  dispatch-gate lesson) — bitwise-equal to tail_fuse_depth=0;
* a fully-fused factor really is exactly one pallas_call in the jaxpr
  (with out_buffers threading, which removes the dead-lower zero inits);
* breakdown info survives fusion: the in-kernel 0/k/n+1 status combines
  with the post-hoc scan so a fault inside a fused window reports the
  TRUE pivot, not the NaN backward-pollution position, and the dead
  lower triangle stays exactly zero even under a fault;
* the VMEM eligibility envelope has the boundary the config comments
  promise (n=512 f32 in, n=768 out, interpret bypasses);
* transpose_pair (base_prefetch=2) is bitwise-equal to the sequential
  two-kernel spelling.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from capital_tpu.models import cholesky
from capital_tpu.models.cholesky import CholinvConfig
from capital_tpu.ops import batched_small, pallas_tpu
from capital_tpu.parallel.topology import Grid
from capital_tpu.robust import RobustConfig
from capital_tpu.utils import rand48


@pytest.fixture(scope="module")
def grid1():
    return Grid.square(c=1, devices=jax.devices("cpu")[:1])


def _spd(n, dtype=jnp.float32):
    return jnp.asarray(rand48.symmetric(n)).astype(dtype)


def _count_pallas_calls(jaxpr) -> int:
    total = 0
    for eqn in jaxpr.eqns:
        if eqn.primitive.name == "pallas_call":
            total += 1
        for v in eqn.params.values():
            if hasattr(v, "jaxpr"):
                total += _count_pallas_calls(v.jaxpr)
    return total


class TestFusedUnfusedParity:
    @pytest.mark.parametrize("depth", [1, 2])
    @pytest.mark.parametrize("mode", ["pallas", "xla"])
    def test_f32(self, grid1, depth, mode):
        A = _spd(512)
        base = CholinvConfig(base_case_dim=128, mode=mode)
        fused = CholinvConfig(base_case_dim=128, mode=mode,
                              tail_fuse_depth=depth)
        R0, RI0 = jax.jit(lambda a: cholesky.factor(grid1, a, base))(A)
        R1, RI1 = jax.jit(lambda a: cholesky.factor(grid1, a, fused))(A)
        np.testing.assert_allclose(np.asarray(R1), np.asarray(R0),
                                   rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(np.asarray(RI1), np.asarray(RI0),
                                   rtol=1e-4, atol=1e-5)

    def test_bf16(self, grid1):
        A = _spd(256, jnp.bfloat16)
        base = CholinvConfig(base_case_dim=128)
        fused = CholinvConfig(base_case_dim=128, tail_fuse_depth=1)
        R0, _ = cholesky.factor(grid1, A, base)
        R1, _ = cholesky.factor(grid1, A, fused)
        # both paths compute in f32 and cast once at the write-back; the
        # bf16 rounding of two algebraically-equal sweeps stays within a
        # couple of ulps
        np.testing.assert_allclose(
            np.asarray(R1, np.float32), np.asarray(R0, np.float32),
            rtol=3e-2, atol=3e-2,
        )

    def test_partial_depth_fuses_subtrees_only(self, grid1):
        # depth=1 at n=512/bc=128 fuses the 256-windows, leaving the
        # top-level trsm/syrk/completion unfused — the mixed schedule
        # must still agree with both pure spellings
        A = _spd(512)
        cfg = CholinvConfig(base_case_dim=128, tail_fuse_depth=1)
        node = cholesky.plan(512, cfg)
        assert not cholesky._tail_fusible(
            grid1, A, 0, node, cfg, True, jnp.zeros((512, 512)), 0
        )
        assert cholesky._tail_fusible(
            grid1, A, 0, node.top[0], cfg, False, jnp.zeros((512, 512)), 0
        )


class TestSymmetrization:
    def test_garbage_lower_half_ignored(self):
        # Schur windows carry only a valid upper triangle; the kernel
        # must symmetrize from it, so poisoning the strict lower half
        # (even with NaN) cannot change the result
        A = _spd(128)
        r, c = np.tril_indices(128, -1)
        bad = np.asarray(A).copy()
        bad[r, c] = np.nan
        Rp = jnp.zeros((128, 128), jnp.float32)
        RIp = jnp.zeros((128, 128), jnp.float32)
        outs = []
        for w in (A, jnp.asarray(bad)):
            R, RI, info = pallas_tpu.fused_tail(
                w, Rp, RIp, off=0, n=128, dest=0, precision="highest"
            )
            assert int(info) == 0
            outs.append((np.asarray(R), np.asarray(RI)))
        np.testing.assert_array_equal(outs[0][0], outs[1][0])
        np.testing.assert_array_equal(outs[0][1], outs[1][1])

    def test_chol_uplo_agreement(self):
        # the sweep the kernel reuses: U and L factors of the same S are
        # transposes of each other
        S = jnp.asarray(rand48.symmetric(64)).astype(jnp.float32)
        R, iu = batched_small._chol(S, uplo="U", block=8,
                                    precision="highest")
        L, il = batched_small._chol(S, uplo="L", block=8,
                                    precision="highest")
        assert int(iu) == 0 and int(il) == 0
        np.testing.assert_allclose(np.asarray(R), np.asarray(L).T,
                                   rtol=1e-5, atol=1e-6)


class TestF64Fallback:
    def test_gate_rejects_f64_at_trace_time(self, grid1):
        A = _spd(256, jnp.float64)
        cfg = CholinvConfig(base_case_dim=128, tail_fuse_depth=2)
        node = cholesky.plan(256, cfg)
        assert not cholesky._tail_fusible(
            grid1, A, 0, node, cfg, True, jnp.zeros((256, 256), A.dtype), 0
        )

    def test_f64_bitwise_equals_unfused(self, grid1):
        A = _spd(256, jnp.float64)
        R0, RI0 = cholesky.factor(
            grid1, A, CholinvConfig(base_case_dim=128)
        )
        R1, RI1 = cholesky.factor(
            grid1, A, CholinvConfig(base_case_dim=128, tail_fuse_depth=2)
        )
        np.testing.assert_array_equal(np.asarray(R1), np.asarray(R0))
        np.testing.assert_array_equal(np.asarray(RI1), np.asarray(RI0))


class TestOnePallasCall:
    def test_fully_fused_factor_is_one_kernel(self, grid1):
        # depth=1 at n=bc<<1 fuses the whole tree from the root; with
        # out_buffers threading (no dead-lower zero-init kernels) the
        # factor lowers to EXACTLY one pallas_call
        n = 256
        cfg = CholinvConfig(base_case_dim=128, tail_fuse_depth=1)
        A = _spd(n)
        bufs = cholesky.factor_buffers(grid1, n, jnp.float32, cfg)
        jx = jax.make_jaxpr(
            lambda a, bs: cholesky.factor(grid1, a, cfg, out_buffers=bs)
        )(A, bufs)
        assert _count_pallas_calls(jx.jaxpr) == 1
        # and the unfused spelling of the same problem is strictly wider
        cfg0 = CholinvConfig(base_case_dim=128)
        jx0 = jax.make_jaxpr(
            lambda a, bs: cholesky.factor(grid1, a, cfg0, out_buffers=bs)
        )(A, bufs)
        assert _count_pallas_calls(jx0.jaxpr) > 1


class TestRobustInfo:
    def _factor_info(self, grid, A, depth):
        cfg = CholinvConfig(base_case_dim=128, tail_fuse_depth=depth,
                            robust=RobustConfig())
        _, _, info = cholesky.factor(grid, A, cfg)
        return int(info)

    def test_healthy_reports_zero(self, grid1):
        A = _spd(256)
        assert self._factor_info(grid1, A, 1) == 0

    def test_fault_in_left_fused_window(self, grid1):
        # breaking pivot 41 (0-based 40) inside the first fused window:
        # the in-kernel info must report 41, not the backward-pollution
        # position the post-hoc NaN scan would see
        A = np.asarray(_spd(256)).copy()
        A[40, 40] = -1.0
        assert self._factor_info(grid1, jnp.asarray(A), 1) == 41

    def test_fault_in_right_subtree(self, grid1):
        A = np.asarray(_spd(256)).copy()
        A[200, 200] = -1.0
        assert self._factor_info(grid1, jnp.asarray(A), 1) == 201

    def test_fused_info_beats_the_polluted_scan(self, grid1):
        # the unfused path only has the post-hoc diagonal scan, and the
        # sweep's backward NaN pollution drags its verdict to an earlier
        # position; the fused path's in-kernel info recovers the TRUE
        # pivot.  Both must flag SOME fault — detection never regresses.
        A = np.asarray(_spd(256)).copy()
        A[40, 40] = -1.0
        fused = self._factor_info(grid1, jnp.asarray(A), 1)
        unfused = self._factor_info(grid1, jnp.asarray(A), 0)
        assert fused == 41
        assert 0 < unfused <= 41

    def test_lower_triangle_stays_zero_under_fault(self, grid1):
        # the kernel's write-back mask contains the contamination: even
        # with garbage filling the fused window's sweep, nothing below
        # the diagonal escapes
        A = np.asarray(_spd(256)).copy()
        A[40, 40] = -1.0
        cfg = CholinvConfig(base_case_dim=128, tail_fuse_depth=1,
                            robust=RobustConfig())
        R, Rinv, _ = cholesky.factor(grid1, jnp.asarray(A), cfg)
        for X in (np.asarray(R), np.asarray(Rinv)):
            low = X[np.tril_indices(256, -1)]
            np.testing.assert_array_equal(low, np.zeros_like(low))


class TestEligibility:
    def test_vmem_boundary(self):
        # need = 3n² x 4B + 4 x 5n² = 32n² against 0.85 x 16MiB: n=512
        # fits (8.4M), n=768 does not (18.9M)
        assert batched_small.tail_eligible(512, jnp.float32,
                                           interpret=False)
        assert not batched_small.tail_eligible(768, jnp.float32,
                                               interpret=False)

    def test_interpret_bypasses(self):
        assert batched_small.tail_eligible(768, jnp.float32,
                                           interpret=True)

    def test_fusible_tracks_the_boundary(self, grid1):
        # the factor-level gate inherits the envelope: the same subtree
        # flips unfusible when the window outgrows VMEM
        cfg = CholinvConfig(base_case_dim=128, tail_fuse_depth=3)
        for n, want in ((512, True), (1024, False)):
            node = cholesky.plan(n, cfg)
            buf = jnp.zeros((n, n), jnp.float32)
            got = (
                cholesky._tail_fusible(grid1, buf, 0, node, cfg, True,
                                       buf, 0)
                and batched_small.tail_eligible(n, jnp.float32,
                                                interpret=False)
            )
            assert got == want


class TestServeCacheKey:
    def test_tail_fuse_depth_is_part_of_cache_identity(self):
        # a fused and an unfused oversize program are different
        # executables; reusing one for the other across the persistent
        # cache would silently serve the wrong launch structure
        from capital_tpu.serve.engine import ServeConfig, SolveEngine

        e1 = SolveEngine(cfg=ServeConfig())
        e2 = SolveEngine(cfg=ServeConfig(tail_fuse_depth=2))
        assert e1._cfg_hash != e2._cfg_hash


class TestTransposePair:
    def test_bitwise_equal_to_sequential(self):
        rng = np.random.default_rng(7)
        n, p, dest = 256, 512, 256
        L = jnp.asarray(rng.standard_normal((n, n)), dtype=jnp.float32)
        Li = jnp.asarray(rng.standard_normal((n, n)), dtype=jnp.float32)
        Rp0 = jnp.zeros((p, p), jnp.float32)
        RIp0 = jnp.zeros((p, p), jnp.float32)
        R_seq = pallas_tpu.transpose(L, out_uplo="U", out=Rp0,
                                     out_off=(dest, dest))
        RI_seq = pallas_tpu.transpose(Li, out_uplo="U", out=RIp0,
                                      out_off=(dest, dest))
        R_pair, RI_pair = pallas_tpu.transpose_pair(
            L, Li, jnp.zeros((p, p), jnp.float32),
            jnp.zeros((p, p), jnp.float32), dest=dest,
        )
        np.testing.assert_array_equal(np.asarray(R_pair), np.asarray(R_seq))
        np.testing.assert_array_equal(np.asarray(RI_pair),
                                      np.asarray(RI_seq))

    def test_base_prefetch_knob_is_bitwise_neutral(self, grid1):
        A = _spd(256)
        outs = []
        for pf in (1, 2):
            cfg = CholinvConfig(base_case_dim=128, base_prefetch=pf)
            R, RI = cholesky.factor(grid1, A, cfg)
            outs.append((np.asarray(R), np.asarray(RI)))
        np.testing.assert_array_equal(outs[0][0], outs[1][0])
        np.testing.assert_array_equal(outs[0][1], outs[1][1])
