"""Banded→blocktri adapter tests (ISSUE 13 satellite): parity against
``scipy.linalg.solveh_banded`` in BOTH storage forms, re-blocking
geometry (padding, block validation), breakdown mapping, and the
partitioned-driver ride-along the adapter exists for."""

import jax.numpy as jnp
import numpy as np
import pytest

scipy_linalg = pytest.importorskip("scipy.linalg")

from capital_tpu.models import banded


def _spd_band(rng, n, u):
    """Lower-form band storage of a well-conditioned SPD banded matrix
    (gram of a banded factor, diagonally dominated)."""
    A = np.zeros((n, n))
    for d in range(u + 1):
        v = rng.standard_normal(n - d) * (0.4 ** d)
        A += np.diag(v, -d)
    A = A @ A.T + (u + 1) * np.eye(n)
    ab = np.zeros((u + 1, n))
    for d in range(u + 1):
        ab[d, : n - d] = np.diag(A, -d)
    return ab, A


def _upper_form(ab):
    u, n = ab.shape[0] - 1, ab.shape[1]
    up = np.zeros_like(ab)
    for d in range(u + 1):
        up[u - d, d:] = ab[d, : n - d]
    return up


class TestReblocking:
    @pytest.mark.parametrize("n,u,block", [(32, 3, 0), (30, 5, 8),
                                           (17, 2, 4), (8, 1, 0)])
    def test_chain_reassembles_the_band(self, n, u, block):
        rng = np.random.default_rng(140)
        ab, A = _spd_band(rng, n, u)
        D, C, n_out = banded.to_blocktri(jnp.asarray(ab), lower=True,
                                         block=block)
        assert n_out == n
        nblocks, b = D.shape[0], D.shape[1]
        assert nblocks * b >= n and b >= u
        dense = np.zeros((nblocks * b, nblocks * b))
        for i in range(nblocks):
            s = i * b
            dense[s:s + b, s:s + b] = np.asarray(D[i])
            if i:
                dense[s:s + b, s - b:s] = np.asarray(C[i])
                dense[s - b:s, s:s + b] = np.asarray(C[i]).T
        np.testing.assert_allclose(dense[:n, :n], A, rtol=0, atol=1e-12)
        # identity padding beyond n, nothing else
        np.testing.assert_allclose(dense[n:, n:],
                                   np.eye(nblocks * b - n), atol=0)
        assert np.all(dense[n:, :n] == 0)

    def test_block_below_bandwidth_rejected(self):
        rng = np.random.default_rng(141)
        ab, _ = _spd_band(rng, 16, 5)
        with pytest.raises(ValueError, match="below the bandwidth"):
            banded.to_blocktri(jnp.asarray(ab), lower=True, block=4)

    def test_resolve_block_policy(self):
        assert banded.resolve_block(3, 64) == 8    # floor wins
        assert banded.resolve_block(12, 64) == 12  # bandwidth wins
        assert banded.resolve_block(3, 64, 16) == 16
        assert banded.resolve_block(1, 4) == 4     # capped by n


class TestSolveParity:
    @pytest.mark.parametrize("n,u", [(32, 3), (30, 5), (17, 2)])
    @pytest.mark.parametrize("lower", [True, False])
    def test_matches_scipy(self, n, u, lower):
        rng = np.random.default_rng(142)
        ab, _ = _spd_band(rng, n, u)
        rhs = rng.standard_normal((n, 2))
        store = ab if lower else _upper_form(ab)
        ref = scipy_linalg.solveh_banded(store, rhs, lower=lower)
        x = banded.solveh_banded(jnp.asarray(store), jnp.asarray(rhs),
                                 lower=lower)
        np.testing.assert_allclose(np.asarray(x), ref, rtol=0, atol=1e-10)

    def test_1d_rhs_round_trips_shape(self):
        rng = np.random.default_rng(143)
        ab, _ = _spd_band(rng, 20, 2)
        rhs = rng.standard_normal(20)
        x = banded.solveh_banded(jnp.asarray(ab), jnp.asarray(rhs),
                                 lower=True)
        assert x.shape == (20,)
        np.testing.assert_allclose(
            np.asarray(x), scipy_linalg.solveh_banded(ab, rhs, lower=True),
            rtol=0, atol=1e-10)

    def test_rhs_row_mismatch_rejected(self):
        rng = np.random.default_rng(144)
        ab, _ = _spd_band(rng, 16, 2)
        with pytest.raises(ValueError, match="rows"):
            banded.solveh_banded(jnp.asarray(ab), jnp.zeros((8, 1)),
                                 lower=True)

    def test_breakdown_raises_like_scipy(self):
        rng = np.random.default_rng(145)
        ab, _ = _spd_band(rng, 16, 2)
        ab[0, 5] = -100.0  # indefinite diagonal entry
        with pytest.raises(ValueError, match="positive definite"):
            banded.solveh_banded(jnp.asarray(ab), jnp.ones(16), lower=True)

    def test_rides_the_partitioned_driver(self):
        # the point of the adapter: a banded solve dispatching to the
        # Spike path, bitwise-compared against scipy
        rng = np.random.default_rng(146)
        n, u = 64, 3
        ab, _ = _spd_band(rng, n, u)
        rhs = rng.standard_normal((n, 2))
        x = banded.solveh_banded(
            jnp.asarray(ab), jnp.asarray(rhs), lower=True,
            impl="partitioned", partitions=2, partition_inner="xla")
        ref = scipy_linalg.solveh_banded(ab, rhs, lower=True)
        np.testing.assert_allclose(np.asarray(x), ref, rtol=0, atol=1e-10)
