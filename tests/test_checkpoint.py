"""Checkpoint/resume (utils/checkpoint.py) — a capability the reference
lacks entirely (SURVEY §5.4): atomic array persistence, fingerprint-gated
restore, and chunked-resumable Newton-Schulz."""

import numpy as np
import jax.numpy as jnp
import pytest

from capital_tpu.models import inverse
from capital_tpu.parallel.topology import Grid
from capital_tpu.utils import checkpoint, rand48


@pytest.fixture
def grid1():
    import jax

    return Grid.square(c=1, devices=jax.devices()[:1])


def test_save_load_roundtrip(tmp_path):
    p = str(tmp_path / "ckpt")
    arrays = {"R": np.arange(12.0).reshape(3, 4), "it": np.asarray(7)}
    checkpoint.save(p, arrays, {"alg": "t", "n": 3})
    got = checkpoint.load(p)
    assert got is not None
    restored, meta = got
    np.testing.assert_array_equal(restored["R"], arrays["R"])
    assert meta["alg"] == "t" and meta["n"] == 3


def test_load_rejects_mismatched_fingerprint(tmp_path):
    p = str(tmp_path / "ckpt")
    checkpoint.save(p, {"X": np.zeros(2)}, {"n": 3, "alg": "newton"})
    assert checkpoint.load(p, expect_meta={"n": 4}) is None
    assert checkpoint.load(p, expect_meta={"n": 3}) is not None
    assert checkpoint.load(str(tmp_path / "missing")) is None


def test_fingerprint_distinguishes_content():
    A = jnp.asarray(rand48.symmetric(16))
    B = A + 1.0
    assert checkpoint.fingerprint(A) != checkpoint.fingerprint(B)
    assert checkpoint.fingerprint(A) == checkpoint.fingerprint(A)


def test_newton_resumable_matches_direct_and_resumes(tmp_path, grid1):
    n = 32
    A = jnp.asarray(rand48.symmetric(n, dtype=jnp.float64))
    cfg = inverse.NewtonConfig()
    p = str(tmp_path / "newton")

    Xr, iters = checkpoint.newton_resumable(grid1, A, cfg, checkpoint_dir=p, chunk=4)
    err = float(jnp.linalg.norm(jnp.eye(n) - A @ Xr)) / np.sqrt(n)
    assert err < 1e-12
    assert iters >= 4

    # checkpoint exists and a re-invocation resumes (no extra chunks needed:
    # the stored state is already converged, so it returns after one chunk)
    st = checkpoint.load(p)
    assert st is not None and st[1]["iters"] == iters
    Xr2, iters2 = checkpoint.newton_resumable(grid1, A, cfg, checkpoint_dir=p, chunk=4)
    np.testing.assert_allclose(np.asarray(Xr2), np.asarray(Xr), rtol=1e-8)

    # a different matrix must NOT resume from this checkpoint
    B = jnp.asarray(rand48.symmetric(n, dtype=jnp.float64)) + jnp.eye(n)
    Xb, _ = checkpoint.newton_resumable(grid1, B, cfg, checkpoint_dir=p, chunk=4)
    errb = float(jnp.linalg.norm(jnp.eye(n) - B @ Xb)) / np.sqrt(n)
    assert errb < 1e-12


def test_newton_resumable_midrun_resume(tmp_path, grid1):
    """A run capped before convergence leaves a checkpoint; a re-invocation
    with a higher cap continues from it (and a third call on the converged
    state is a no-op short-circuit)."""
    import dataclasses

    n = 32
    A = jnp.asarray(rand48.symmetric(n, dtype=jnp.float64))
    p = str(tmp_path / "newton-mid")

    capped = dataclasses.replace(inverse.NewtonConfig(), max_iter=4)
    X1, it1 = checkpoint.newton_resumable(grid1, A, capped, checkpoint_dir=p, chunk=4)
    assert it1 == 4
    st = checkpoint.load(p)
    assert st is not None and st[1]["iters"] == 4
    err1 = float(jnp.linalg.norm(jnp.eye(n) - A @ X1)) / np.sqrt(n)
    assert err1 > 1e-12  # genuinely unconverged at the cap

    full = inverse.NewtonConfig()
    X2, it2 = checkpoint.newton_resumable(grid1, A, full, checkpoint_dir=p, chunk=4)
    assert it2 > 4  # continued beyond the stored state, not restarted at 0
    err2 = float(jnp.linalg.norm(jnp.eye(n) - A @ X2)) / np.sqrt(n)
    assert err2 < 1e-12

    # converged state: resume is a no-op returning the stored iterate
    X3, it3 = checkpoint.newton_resumable(grid1, A, full, checkpoint_dir=p, chunk=4)
    assert it3 == it2
    np.testing.assert_array_equal(np.asarray(X3), np.asarray(X2))
