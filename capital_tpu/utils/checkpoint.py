"""Checkpoint / resume for long-running factorizations and sweeps.

The reference has **no** checkpoint capability (SURVEY §5.4: factor outputs
live only in the in-memory `info` pack, cholinv.h:32-33, and a preempted run
restarts from nothing).  This module goes beyond parity: it persists named
arrays + JSON metadata atomically, and wraps the framework's iterative
algorithms so a preempted run resumes from the last saved state.

Design choices:

* Plain ``.npz`` + ``meta.json`` (no orbax dependency surface): factors are
  dense 2D arrays, synchronous writes are fine at these sizes, and the files
  are inspectable with nothing but numpy.  Sharded `jax.Array`s are gathered
  to host before writing (checkpointing is a host-side concern; the restore
  re-pins to whatever grid the caller provides).
* Atomic: metadata travels INSIDE the single .npz (as a JSON string entry)
  and the file is renamed into place, so a preemption mid-write can never
  leave arrays paired with stale metadata — there is exactly one file to
  tear, and rename is atomic.  A meta.json is also written afterwards as a
  human-readable convenience view; it is never read back.
* Content-addressed resume key: callers pass the config/input fingerprint;
  ``load`` returns None on any mismatch so a stale checkpoint can never be
  resumed into a different problem.
"""

from __future__ import annotations

import json
import os
import tempfile
from typing import Any, Mapping

import jax.numpy as jnp
import numpy as np


def save(path: str, arrays: Mapping[str, Any], meta: dict | None = None) -> None:
    """Atomically persist `arrays` (+ JSON-serializable `meta`) at `path`
    (a directory).  Arrays and metadata land in ONE file via one atomic
    rename; no interleaving of writes can produce arrays with stale meta."""
    if "__meta__" in arrays:
        raise ValueError("'__meta__' is reserved for the embedded metadata")
    os.makedirs(path, exist_ok=True)
    host = {k: np.asarray(v) for k, v in arrays.items()}
    host["__meta__"] = np.frombuffer(
        json.dumps(meta or {}).encode(), dtype=np.uint8
    )
    fd, tmp = tempfile.mkstemp(dir=path, suffix=".npz.tmp")
    os.close(fd)
    try:
        with open(tmp, "wb") as f:
            np.savez(f, **host)
        os.replace(tmp, os.path.join(path, "arrays.npz"))
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)
    # convenience view only — load() never reads it
    with open(os.path.join(path, "meta.json"), "w") as f:
        json.dump(meta or {}, f, indent=1)


def load(path: str, expect_meta: dict | None = None):
    """Restore (arrays, meta) from `path`, or None when absent/mismatched.

    `expect_meta`: every (key, value) must match the stored meta — pass the
    problem fingerprint (shape, dtype, config) so a checkpoint from a
    different run is rejected rather than resumed."""
    npz = os.path.join(path, "arrays.npz")
    if not os.path.exists(npz):
        return None
    with np.load(npz) as z:
        arrays = {k: z[k] for k in z.files}
    meta = json.loads(arrays.pop("__meta__").tobytes().decode())
    for k, v in (expect_meta or {}).items():
        if meta.get(k) != v:
            return None
    return arrays, meta


def fingerprint(A, **config) -> dict:
    """A cheap problem identity: shape/dtype plus a content probe (corner
    checksums, not a full hash — checkpoints are advisory, the gate only
    needs to reject obviously-different inputs)."""
    Ah = np.asarray(A[: min(64, A.shape[0]), : min(64, A.shape[1])], np.float64)
    return dict(
        shape=list(A.shape),
        dtype=str(jnp.dtype(A.dtype)),
        probe=float(np.sum(Ah)),
        **config,
    )


def newton_resumable(
    grid,
    A,
    cfg=None,
    *,
    checkpoint_dir: str,
    chunk: int = 8,
):
    """Newton-Schulz inverse with host-level checkpointing every `chunk`
    iterations.  A preempted run re-invoked with the same arguments resumes
    from the last completed chunk instead of iterating from X0.

    The in-jit variant (models/inverse.newton) runs the whole while_loop on
    device; mid-jit state cannot be checkpointed, so this wrapper re-expresses
    the loop as host-stepped chunks of `chunk` iterations — the standard
    trade for resumability in iterative solvers.  Returns (Ainv, iters).
    """
    import jax

    from capital_tpu.models import inverse as inv_mod

    cfg = cfg or inv_mod.NewtonConfig()
    tol = cfg.tol
    if tol is None:
        tol = 50.0 * float(jnp.finfo(A.dtype).eps)
    fp = fingerprint(A, alg="newton", chunk=chunk, tol=tol, mode=cfg.mode)

    n = A.shape[0]
    eye = jnp.eye(n, dtype=A.dtype)
    state = load(checkpoint_dir, expect_meta=fp)
    if state is not None:
        arrays, meta = state
        X = jnp.asarray(arrays["X"])
        done = int(meta["iters"])
        if meta.get("resid", float("inf")) < tol:
            return X, done  # already converged: resume is a no-op
    else:
        norm1 = jnp.max(jnp.sum(jnp.abs(A), axis=0))
        norminf = jnp.max(jnp.sum(jnp.abs(A), axis=1))
        X = A.T / (norm1 * norminf)
        done = 0

    @jax.jit
    def step(X, A):
        # one chunk of Newton iterations starting from X (not X0): reuse the
        # in-jit loop body by treating X as the running iterate
        from capital_tpu.parallel import summa
        from capital_tpu.parallel.summa import GemmArgs

        gargs = GemmArgs(precision=cfg.precision)

        def body(_, X):
            AX = summa.gemm(grid, A, X, args=gargs, mode=cfg.mode)
            return summa.gemm(grid, X, 2.0 * eye - AX, args=gargs, mode=cfg.mode)

        X = jax.lax.fori_loop(0, chunk, body, X)
        AX = summa.gemm(grid, A, X, args=gargs, mode=cfg.mode)
        r = jnp.linalg.norm(eye - AX) / jnp.sqrt(jnp.asarray(n, A.dtype))
        return X, r

    r = None
    while done < cfg.max_iter:
        X, r = step(X, A)
        done += chunk
        save(checkpoint_dir, {"X": X}, {**fp, "iters": done, "resid": float(r)})
        if float(r) < tol:
            break
    return X, done
