"""Residual / validation norms — the framework's correctness gates.

TPU-native equivalent of the reference's validation layer
(test/cholesky/validate.hpp, test/qr/validate.hpp, src/util/util.hpp:3-53):
relative Frobenius residuals computed *in the distributed layout*.  The
reference accumulates local squared errors and combines them with
``MPI_Allreduce`` over the slice communicator (util.hpp:25-53); here the same
computation is a global jnp reduction — XLA inserts the cross-device psum
automatically from the operands' shardings, so one implementation serves both
the single-chip and the multi-chip mesh cases.

All functions accept (possibly sharded) jax Arrays and return scalars.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

# Validation matmuls run at precision='highest' unconditionally: on TPU the
# default f32 matmul uses bf16-grade MXU passes, which floors the measurable
# residual near 1e-4 and would mask a genuinely bad factor (observed: a
# correct n=1024 f32 factor 'failing' at 4.6e-4 purely from the gate's own
# product).  Gates are not on the timed path; full precision is free here.
_PREC = "highest"


def rel_fro(err: jnp.ndarray, ref: jnp.ndarray) -> jnp.ndarray:
    """sqrt(sum(err^2)) / sqrt(sum(ref^2)) — reference util::residual_local
    (util.hpp:25-53) without the lambda indirection."""
    num = jnp.sqrt(jnp.sum(jnp.square(err)))
    den = jnp.sqrt(jnp.sum(jnp.square(ref)))
    return num / den


def cholesky_residual(A: jnp.ndarray, R: jnp.ndarray) -> jnp.ndarray:
    """‖A − RᵀR‖_F / ‖A‖_F for upper-triangular R.

    Reference: cholesky::validate::residual (test/cholesky/validate.hpp:7-49),
    which forms RᵀR−A via a SUMMA gemm with beta=−1.  Here the matmul is a
    plain jnp.dot: under jit with sharded operands XLA plans the same
    distributed contraction.
    """
    return rel_fro(A - jnp.matmul(R.T, R, precision=_PREC), A)


def cholesky_inverse_residual(R: jnp.ndarray, Rinv: jnp.ndarray) -> jnp.ndarray:
    """‖I − R·R⁻¹‖_F / ‖I‖_F — reference util::get_identity_residual
    (util.hpp:3-23)."""
    n = R.shape[0]
    eye = jnp.eye(n, dtype=R.dtype)
    return rel_fro(eye - jnp.matmul(R, Rinv, precision=_PREC), eye)


def qr_orthogonality(Q: jnp.ndarray) -> jnp.ndarray:
    """‖I − QᵀQ‖_F / ‖I‖_F — reference qr::validate::orthogonality
    (test/qr/validate.hpp:7-32)."""
    n = Q.shape[1]
    eye = jnp.eye(n, dtype=Q.dtype)
    return rel_fro(eye - jnp.matmul(Q.T, Q, precision=_PREC), eye)


def qr_residual(A: jnp.ndarray, Q: jnp.ndarray, R: jnp.ndarray) -> jnp.ndarray:
    """‖A − QR‖_F / ‖A‖_F — reference qr::validate::residual
    (test/qr/validate.hpp:37-52).  Computed at the f32-floor dtype so the
    gate's own accumulation noise (a bf16 sum over m·n squares) cannot
    mask or manufacture a failure — same arithmetic as the blocked form,
    so the two gates agree for any m."""
    ct = jnp.promote_types(A.dtype, jnp.float32)
    err = A.astype(ct) - jnp.matmul(
        Q.astype(ct), R.astype(ct), precision=_PREC
    )
    return rel_fro(err, A.astype(ct))


def qr_residual_blocked(
    A: jnp.ndarray, Q: jnp.ndarray, R: jnp.ndarray, block_rows: int = 65536
) -> jnp.ndarray:
    """qr_residual accumulated over row blocks with a lax.scan: O(block·n)
    extra memory instead of several m x n f32 temporaries — the dense form
    OOMs validating the 2M x 1024 BASELINE shape on one v5e (the
    FACTORIZATION fits; the residual's f32 err/QR buffers did not).
    Falls back to the dense form when block_rows does not tile m."""
    m, n = A.shape
    if m % block_rows or m == block_rows:
        return qr_residual(A, Q, R)
    ct = jnp.promote_types(A.dtype, jnp.float32)  # f32 floor, f64 kept
    Rt = R.astype(ct)  # R as given, like the dense form (no silent triu)
    Ab = A.reshape(m // block_rows, block_rows, n)
    Qb = Q.reshape(m // block_rows, block_rows, n)

    def step(carry, ab_qb):
        ab, qb = ab_qb
        ab = ab.astype(ct)
        err = ab - jnp.matmul(qb.astype(ct), Rt, precision=_PREC)
        return (
            (carry[0] + jnp.sum(jnp.square(err)),
             carry[1] + jnp.sum(jnp.square(ab))),
            None,
        )

    zero = jnp.zeros((), ct)
    (num, den), _ = jax.lax.scan(step, (zero, zero), (Ab, Qb))
    return jnp.sqrt(num) / jnp.sqrt(den)


def inverse_residual(A: jnp.ndarray, Ainv: jnp.ndarray) -> jnp.ndarray:
    """‖I − A·A⁻¹‖_F / ‖I‖_F — reference test/inverse/validate.hpp:12-24
    (that file is bit-rotted upstream; this is the working equivalent).
    Error and norm accumulate at the f32 floor (same arithmetic as the
    blocked form below, so the two gates agree for any n — the qr pair's
    alignment rule)."""
    n = A.shape[0]
    ct = jnp.promote_types(A.dtype, jnp.float32)
    eye = jnp.eye(n, dtype=ct)
    prod = jnp.matmul(A, Ainv, precision=_PREC, preferred_element_type=ct)
    return rel_fro(eye - prod, eye)


def inverse_residual_blocked(
    A: jnp.ndarray, Ainv: jnp.ndarray, block_rows: int = 4096
) -> jnp.ndarray:
    """inverse_residual accumulated over row blocks with a lax.scan:
    O(block·n) extra memory instead of the n x n f32 product — the dense
    form OOMs validating the n=49152 rectri row on one v5e (two 4.8 GB
    bf16 operands fit; the 9.7 GB f32 I−A·A⁻¹ did not).  Same qr_residual
    pattern (qr_residual_blocked above).  Operands enter the contraction
    at their own dtype with an f32-floor accumulator (no upcast copy of
    Ainv — bf16 inputs are exact into f32, so values match the dense
    form).  When block_rows does not tile n, the largest divisor of n
    <= block_rows is used instead (no silent dense cliff at large
    unaligned n); only n <= block_rows takes the dense form."""
    n = A.shape[0]
    if n <= block_rows:
        return inverse_residual(A, Ainv)
    br = next(b for b in range(min(block_rows, n), 0, -1) if n % b == 0)
    ct = jnp.promote_types(A.dtype, jnp.float32)
    Ab = A.reshape(n // br, br, n)

    def step(carry, ab_i):
        ab, i = ab_i
        prod = jnp.matmul(ab, Ainv, precision=_PREC, preferred_element_type=ct)
        # subtract this block's slice of the identity: rows
        # [i*br, (i+1)*br) have their ones at the same global columns
        r = jax.lax.broadcasted_iota(jnp.int32, (br, n), 0)
        c = jax.lax.broadcasted_iota(jnp.int32, (br, n), 1)
        err = jnp.where(c == r + i * br, prod - 1.0, prod)
        return carry + jnp.sum(jnp.square(err)), None

    num, _ = jax.lax.scan(
        step, jnp.zeros((), ct), (Ab, jnp.arange(n // br))
    )
    return jnp.sqrt(num) / jnp.sqrt(jnp.asarray(n, ct))
