"""Layout engine: block/cyclic repack + packed-triangular storage.

TPU-native re-design of two reference components:

* the ``serialize<S1,S2>`` structure-to-structure copy engine
  (src/matrix/serialize.h:16-70) — here packed-triangular <-> dense
  conversions on whole arrays (the reference's 7 pairwise specializations
  collapse to pack/unpack through the dense form, since dense tiles are the
  native TPU representation and packed storage only appears at the
  host/serialization boundary);
* the ``util::block_to_cyclic_* / cyclic_to_block_*`` repack kernels
  (src/util/util.hpp:56-230) that sit between the block distribution and the
  element-cyclic layout the reference's base-case LAPACK calls expect.

The reference implements these as scalar index loops (the "hot repack loop"
on its profile, SURVEY §3.1); here they are O(1) reshape/transpose
compositions that XLA lowers to a single copy — and, because the TPU
framework keeps matrices **block**-distributed everywhere (topology.py
docstring), they are needed only for parity testing against reference
layouts and for import/export of reference-ordered data, never on the
compute path.

Array-API note: functions accept numpy or jax arrays and return the same
family (repacks are pure reshapes; `xp` is chosen from the input).
"""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp


def _xp(A):
    return np if isinstance(A, np.ndarray) else jnp


def get_next_power2(n: int) -> int:
    """Smallest power of two >= n (reference util.hpp:249-264, bit-twiddle)."""
    if n <= 1:
        return 1
    return 1 << (int(n - 1).bit_length())


# --------------------------------------------------------------------------
# block <-> cyclic global reorderings
# --------------------------------------------------------------------------
#
# Conventions, matching the reference (structure.hpp / matrix.hpp:6-18):
#   * "block" layout: the global (M, N) matrix is a (dx, dy) grid of
#     contiguous (M/dx, N/dy) local tiles; rank (x, y) owns tile (x, y).
#   * "cyclic" layout: rank (x, y) owns global elements (i, j) with
#     i % dx == x, j % dy == y — i.e. local element (k, l) is global
#     (k*dx + x, l*dy + y).
# The repack maps a matrix whose *storage order* is one layout's
# gather-concatenation into the other's.  Gathering block-distributed tiles
# over the slice produces storage [x][y][k][l] (tile-major); the cyclic view
# of the same global matrix reads element (i, j) = (k*dx+x, l*dy+y).


def block_to_cyclic(G: "np.ndarray", dx: int, dy: int):
    """Reorder a block-gathered matrix into the true global (cyclic-read)
    element order.

    `G` is (dx*m, dy*n), laid out as dx x dy contiguous tiles where tile
    (x, y) holds the elements rank (x, y) owns under the CYCLIC distribution.
    Returns the (dx*m, dy*n) matrix in natural global order — the repack the
    reference's base case performs before calling LAPACK
    (util.hpp:99-133, block_to_cyclic_rect).
    """
    M, N = G.shape
    m, n = M // dx, N // dy
    T = G.reshape(dx, m, dy, n)  # [x][k][y][l]
    # global (i, j) = (k*dx + x, l*dy + y)  ->  order axes as [k][x][l][y]
    return T.transpose(1, 0, 3, 2).reshape(M, N)


def cyclic_to_block(G: "np.ndarray", dx: int, dy: int):
    """Inverse of :func:`block_to_cyclic` (reference cyclic_to_block_rect /
    cyclic_to_local, util.hpp:135-230): slice a natural-order global matrix
    into each rank's cyclic locals, concatenated tile-major."""
    M, N = G.shape
    m, n = M // dx, N // dy
    T = G.reshape(m, dx, n, dy)  # [k][x][l][y]
    return T.transpose(1, 0, 3, 2).reshape(M, N)


def local_cyclic_tile(G: "np.ndarray", dx: int, dy: int, x: int, y: int):
    """Rank (x, y)'s local shard under the cyclic distribution — global
    elements (k*dx + x, l*dy + y) (reference structure.hpp distribution
    arithmetic)."""
    return G[x::dx, y::dy]


def local_block_tile(G: "np.ndarray", dx: int, dy: int, x: int, y: int):
    """Rank (x, y)'s local shard under the block distribution (this
    framework's native layout, topology.py face_sharding)."""
    M, N = G.shape
    m, n = M // dx, N // dy
    return G[x * m : (x + 1) * m, y * n : (y + 1) * n]


# --------------------------------------------------------------------------
# packed triangular storage (reference structure policies, structure.h:37-72)
# --------------------------------------------------------------------------


def pack_upper(A):
    """Dense (n, n) -> column-packed upper triangle, length n(n+1)/2.

    Matches the reference's `uppertri` storage: column j contributes its
    j+1 leading entries, columns concatenated (structure.h:37-39: offset of
    column x is x(x+1)/2)."""
    xp = _xp(A)
    n = A.shape[0]
    # A.T[tril] walks (col, row<=col) pairs in column-major packed order
    return A.T[xp.tril_indices(n)]


def unpack_upper(packed, n: int):
    """Column-packed upper triangle -> dense (n, n) with zero lower half."""
    xp = _xp(packed)
    out_t = xp.zeros((n, n), dtype=packed.dtype)
    idx = xp.tril_indices(n)
    if isinstance(packed, np.ndarray):
        out_t[idx] = packed
        return out_t.T
    return out_t.at[idx].set(packed).T


def pack_lower(A):
    """Dense (n, n) -> column-packed lower triangle (reference `lowertri`,
    structure.h:57-59: column x holds its n-x trailing entries)."""
    xp = _xp(A)
    n = A.shape[0]
    return A.T[xp.triu_indices(n)]


def unpack_lower(packed, n: int):
    xp = _xp(packed)
    out_t = xp.zeros((n, n), dtype=packed.dtype)
    idx = xp.triu_indices(n)
    if isinstance(packed, np.ndarray):
        out_t[idx] = packed
        return out_t.T
    return out_t.at[idx].set(packed).T


def num_packed_elems(n: int) -> int:
    """n(n+1)/2 (reference structure.h:38, _num_elems)."""
    return n * (n + 1) // 2


def remove_triangle(A, uplo: str):
    """Zero the *dead* half of a triangular matrix, keeping `uplo`
    (reference util::remove_triangle[_local], util.hpp:266-318 — used before
    validation gemms so stale scratch in the dead half cannot pollute
    residuals)."""
    xp = _xp(A)
    n = A.shape[0]
    i = xp.arange(A.shape[0])[:, None]
    j = xp.arange(A.shape[1])[None, :]
    keep = (i <= j) if uplo == "U" else (i >= j)
    return xp.where(keep, A, xp.zeros((), dtype=A.dtype))
