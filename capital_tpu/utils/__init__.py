from capital_tpu.utils import rand48, residual  # noqa: F401
