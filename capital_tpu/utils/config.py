"""Runtime configuration enums and dataclasses.

The reference configures algorithms through four mechanisms (SURVEY §5.6):
positional argv, env vars, compile-time -D flags, and — the real one —
template policy selection (e.g. cholinv<Serialize,SaveIntermediates,
NoReplication>, bench/cholesky/cholinv.cpp:31-33).  JAX retracing replaces
template instantiation, so every policy becomes a runtime enum here; configs
hash into jit static args, giving one compiled executable per configuration,
exactly like one template instantiation per policy combination.
"""

from __future__ import annotations

import enum


class BaseCasePolicy(enum.Enum):
    """Base-case execution strategies (reference cholinv/policy.h:160-514).

    The reference trades replicated computation against gather/scatter
    communication on CPU clusters.  On a TPU mesh, replicating a small
    panel (one all_gather over ICI) and computing it redundantly on every
    chip is usually cheapest (redundant small-matrix compute is free
    relative to extra collectives — SURVEY §7.1), but all four strategies
    are genuinely implemented so the trade is measurable, not asserted
    (models/cholesky.py:_base_case_into / _scoped_base_factor):

      REPLICATE_COMM_COMP   gather to every device, every device factors the
                            panel (TPU default; reference policy.h:160-224
                            'ReplicateCommComp')
      REPLICATE_COMP        only the z=0 depth layer factors; the result is
                            broadcast down 'z' as a psum of the layer-masked
                            value (reference policy.h:226-305)
      NO_REPLICATION        only the root device (0,0,0) factors; the result
                            is broadcast over the whole mesh (reference
                            gather-to-root + scatter, policy.h:307-414)
      NO_REPLICATION_OVERLAP same schedule as NO_REPLICATION; the reference
                            overlaps the scatter with trtri by hand
                            (policy.h:416-514) — on TPU, XLA's
                            latency-hiding scheduler owns that overlap
    """

    REPLICATE_COMM_COMP = 0
    REPLICATE_COMP = 1
    NO_REPLICATION = 2
    NO_REPLICATION_OVERLAP = 3

    @property
    def compute_scope(self) -> str:
        """Which devices run the panel factorization: 'all' | 'layer' |
        'root' (see class docstring)."""
        if self is BaseCasePolicy.REPLICATE_COMM_COMP:
            return "all"
        if self is BaseCasePolicy.REPLICATE_COMP:
            return "layer"
        return "root"
