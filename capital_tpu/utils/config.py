"""Runtime configuration enums and dataclasses.

The reference configures algorithms through four mechanisms (SURVEY §5.6):
positional argv, env vars, compile-time -D flags, and — the real one —
template policy selection (e.g. cholinv<Serialize,SaveIntermediates,
NoReplication>, bench/cholesky/cholinv.cpp:31-33).  JAX retracing replaces
template instantiation, so every policy becomes a runtime enum here; configs
hash into jit static args, giving one compiled executable per configuration,
exactly like one template instantiation per policy combination.
"""

from __future__ import annotations

import enum


class BaseCasePolicy(enum.Enum):
    """Base-case execution strategies (reference cholinv/policy.h:160-514).

    The reference trades replicated computation against gather/scatter
    communication on CPU clusters.  On a TPU mesh the trade collapses:
    replicating a small panel (one all_gather over ICI) and computing it
    redundantly on every chip is strictly cheaper than gathering to a root
    chip and scattering back, because redundant small-matrix compute is free
    relative to the extra collectives and the idle mesh (SURVEY §7.1).  All
    four policies are accepted for config/sweep parity; they select the
    gather scope used before the local potrf+trtri:

      REPLICATE_COMM_COMP   gather to every device, all compute (TPU default;
                            reference policy.h:160-224 'ReplicateCommComp')
      REPLICATE_COMP        reference computes on layer z=0 then bcasts
                            (policy.h:226-305); on TPU identical collective
                            traffic to the above with strictly less useful
                            work per chip — implemented as the same schedule
      NO_REPLICATION        reference gathers to the single root rank
                            (policy.h:307-414); the TPU mapping places no
                            explicit constraint on the panel and lets the
                            SPMD partitioner choose placement (which may
                            gather to fewer devices) — see
                            models/cholesky.py:_base_case_into
      NO_REPLICATION_OVERLAP reference overlaps the scatter with trtri
                            (policy.h:416-514); XLA's latency-hiding
                            scheduler owns overlap on TPU — same mapping as
                            NO_REPLICATION
    """

    REPLICATE_COMM_COMP = 0
    REPLICATE_COMP = 1
    NO_REPLICATION = 2
    NO_REPLICATION_OVERLAP = 3

    @property
    def single_device_compute(self) -> bool:
        return self in (
            BaseCasePolicy.NO_REPLICATION,
            BaseCasePolicy.NO_REPLICATION_OVERLAP,
        )
