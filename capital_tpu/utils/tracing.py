"""Tracing, named scopes, and the communication/computation cost model.

TPU-native re-imagining of the reference's critter profiling integration
(SURVEY §5.1).  The reference compile-gates symbol macros around functions and
algorithm phases (``CRITTER_START(CI::trsm)`` etc., cholinv.hpp:94-136,
cacqr.hpp:82-116) and the external critter library measures per-symbol
computation/communication costs along the critical path, per process, and
volumetrically (autotune/cholesky/cholinv/tune.cpp:28-88).

On TPU the execution model is different: everything inside ``jit`` is compiled
into one XLA program, so per-phase *measurement* from Python is impossible —
the phases fuse.  The equivalent design here has three parts:

1. **Named scopes** (`scope`): phase tags (the same names the reference uses —
   ``CI::trsm``, ``CI::tmu``, ``CQR::gram``...) entered as `jax.named_scope`,
   so every HLO op carries its phase in metadata and `jax.profiler` traces
   (`trace`) decompose by phase in Perfetto/TensorBoard exactly like critter's
   symbol decomposition.

2. **An analytic cost model** (`Recorder` + ``*_cost``): at trace time, the
   SUMMA layer and the algorithm base cases emit per-phase flop counts,
   collective byte counts, and collective (synchronization) counts computed
   from shapes and the grid — the alpha-beta model critter fits empirically
   (cp-comp / cp-comm / cp-synch columns), derived analytically instead.
   Tracing happens once per jit cache entry, so a Recorder activated around
   the *first* call of a jitted function captures exactly one execution's
   worth of costs.

3. **Cost tables** (`write_times_table` / `write_costs_table`): fixed-width
   text tables in the shape of the reference's autotune output
   (autotune/util.h:4-127), consumed by capital_tpu/autotune.

Device constants (`DeviceSpec`) are public-spec estimates used to convert the
model's flops/bytes into seconds for the table's time columns; measured wall
time always comes from `measure`.
"""

from __future__ import annotations

import contextlib
import dataclasses
import statistics
import time
from collections import defaultdict
from typing import Callable, Optional

import jax
import jax.numpy as jnp

# --------------------------------------------------------------------------
# device specs (public numbers; estimates for the model's time conversion)
# --------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class DeviceSpec:
    """Per-chip hardware model: peak matmul throughput + interconnect/memory
    bandwidth.  The analog of the alpha-beta machine parameters critter fits.

    ``alpha_s`` is the per-collective launch/synchronization latency — the
    alpha of the alpha-beta model (CA-CQR2's S term, arXiv:1710.08471 §2).
    Public ICI latencies sit around a microsecond; the CPU rig's in-process
    ring is priced the same order (it only matters for relative ranking
    there)."""

    name: str
    peak_bf16_tflops: float
    hbm_gbps: float
    ici_gbps: float  # per-direction aggregate ICI bandwidth per chip
    alpha_s: float = 1e-6  # per-collective latency (seconds)

    def peak_tflops(self, dtype) -> float:
        if jnp.dtype(dtype).itemsize >= 4:
            return self.peak_bf16_tflops / 2.0
        return self.peak_bf16_tflops


_SPECS = (
    DeviceSpec("v6e", 918.0, 1640.0, 448.0),
    DeviceSpec("v6", 918.0, 1640.0, 448.0),
    DeviceSpec("v5p", 459.0, 2765.0, 600.0),
    DeviceSpec("v5", 197.0, 819.0, 400.0),
    DeviceSpec("lite", 197.0, 819.0, 400.0),
    DeviceSpec("v4", 275.0, 1228.0, 300.0),
    DeviceSpec("v3", 123.0, 900.0, 200.0),
    DeviceSpec("cpu", 0.2, 50.0, 10.0),  # virtual-device test rig
)
_DEFAULT = DeviceSpec("unknown", 197.0, 819.0, 400.0)


def device_spec(device: Optional[jax.Device] = None) -> DeviceSpec:
    device = device or jax.devices()[0]
    kind = getattr(device, "device_kind", device.platform).lower()
    for s in _SPECS:
        if s.name in kind:
            return s
    return _DEFAULT


# --------------------------------------------------------------------------
# phase scopes + recorder
# --------------------------------------------------------------------------

#: The single source of truth for phase tags (critter symbol names).  Every
#: `scope()` tag must be registered here: the trace tool's device-time
#: buckets (bench/trace.py PHASE_TAGS) and the obs drift classifier both
#: derive from this tuple, so an unregistered tag would silently land in
#: 'other' in every downstream view — scope() refuses it instead.
#: Innermost-first ordering is not required (matching is longest-tag-first
#: downstream); grouping by algorithm keeps the registry reviewable.
PHASE_REGISTRY: tuple[str, ...] = (
    # cholinv (cholesky.py, reference cholinv.hpp:94-136).  CI::buffers is
    # the output-buffer zero-init (pallas zeros_dead_lower) at factor
    # entry — schedule-inserted data movement, tagged so the lint
    # phase-coverage rule and the trace tool attribute it instead of
    # bucketing kernel writes under 'other'.
    "CI::factor_diag", "CI::trsm", "CI::tmu", "CI::inv", "CI::buffers",
    # CI::tail_fused is the fused recursion-tail megakernel
    # (ops/pallas_tpu.fused_tail): an entire plan() subtree — potrf panel,
    # trsm, syrk trailing update, inverse-assembly trmm — lowered as ONE
    # pallas_call with the panel VMEM-resident across phases.  One phase,
    # one price, same rationale as SV::fused_posv.
    "CI::tail_fused",
    # cacqr (qr.py, reference cacqr.hpp:82-116; CQR::scale is historical —
    # kept so old traces/ledgers still bucket).  CQR::recover is the
    # shifted-CholeskyQR escalation path (robust/recovery.py) — present in
    # the program only under a RobustConfig, executed only on breakdown.
    "CQR::gram", "CQR::chol", "CQR::scale", "CQR::merge", "CQR::fused",
    "CQR::formR", "CQR::recover",
    # rectri (inverse.py).  RT::buffers: see CI::buffers.
    "RT::base", "RT::merge", "RT::batch_base", "RT::batch_merge",
    "RT::batch_write", "RT::buffers",
    # trsm (trsm.py)
    "TS::dinv", "TS::leaf", "TS::update",
    # serve (serve/, docs/SERVING.md).  serve::ingest is HOST-side — the
    # per-request fault-injection tap fires on the concrete operand at
    # submit(), never inside a traced program, so a planted fault corrupts
    # exactly one request instead of baking into the AOT executable cache.
    # serve::pad wraps bucket padding; serve::solve wraps the per-problem
    # solve kernels inside the batched executables.
    "serve::ingest", "serve::pad", "serve::solve",
    # batched small-N kernels (ops/batched_small.py).  OP::batched_small
    # wraps the standalone batched-grid potrf/trsm/potrs kernels;
    # SV::fused_posv / SV::fused_lstsq wrap the fused factor+solve paths
    # (factor VMEM-resident between phases — priced as ONE phase because
    # no inter-phase HBM boundary exists to attribute across).
    "OP::batched_small", "SV::fused_posv", "SV::fused_lstsq",
    # continuous-batching scheduler (serve/scheduler.py, docs/SERVING.md).
    # SV::stage wraps host->device staging of padded operands ahead of
    # dispatch (jax.device_put at submit time, plus the in-program operand
    # normalization of the staged-dispatch lint target); SV::dispatch wraps
    # the batched bucket dispatch itself — the boundary the queue_wait /
    # device latency split in serve/stats.py measures across.
    "SV::stage", "SV::dispatch",
    # block-tridiagonal chain (models/blocktri.py, docs/SERVING.md).  The
    # scopes wrap the lax.scan CALLS at the models layer, not the scan
    # bodies: an emit inside a scan body would fire once at trace time
    # while the kernel executes nsteps times, so the whole chain is priced
    # outside the scan and the lint phase-inheritance rule extends the
    # scope over the scanned kernels.  BT::factor covers the Schur-
    # complement factor chain (fused with the forward sweep in
    # posv_blocktri — one phase, one price, the SV::fused_posv rationale);
    # BT::solve covers the block-bidiagonal substitution sweeps.
    "BT::factor", "BT::solve",
    # online factor maintenance (ops/update_small.py, models/blocktri.py
    # extend, docs/SERVING.md "Factor residency").  UP::update /
    # UP::downdate wrap the rank-k hyperbolic-rotation Cholesky
    # update/downdate kernels (one scope per public call, priced whole —
    # chol_update_flops); UP::extend wraps the blocktri chain-extension
    # scan at the models layer (same outside-the-scan emit rationale as
    # BT::factor: the scan body executes nsteps times, the price fires
    # once).
    "UP::update", "UP::downdate", "UP::extend",
    # partitioned (Spike / one-level cyclic-reduction) chain solve
    # (models/blocktri.py impl='partitioned', docs/PERF.md round 13).
    # BT::partition wraps the embarrassingly-parallel per-partition work —
    # the interior factor + widened [B | F | G] spike solves with the
    # partition axis folded into the batched grid, and the final
    # back-substitution — priced whole via blocktri_partition_flops.
    # BT::reduce wraps the P-block interface system: the Schur assembly
    # gemms plus the sequential reduced-chain posv
    # (blocktri_reduce_flops).  Same outside-the-scan emit rationale as
    # BT::factor.
    "BT::partition", "BT::reduce",
    # mixed-precision iterative refinement (robust/refine.py,
    # docs/ROBUSTNESS.md "escalation ladder").  IR::residual wraps the
    # high-precision residual r = B − A·X (and the Aᵀr semi-normal
    # product on the lstsq path); IR::correct wraps the correction solve
    # against the low-precision resident factor plus the X += d update.
    # Both scopes fire once per refine() call even though the
    # lax.while_loop body executes a data-dependent number of times: the
    # model prices ONE sweep and the MEASURED iteration counts land in
    # serve request stats (stats.Collector `refine` block) — the
    # outside-the-scan emit rationale of BT::factor.  QR::tsqr wraps the
    # blocked Householder TSQR tree (ops/tsqr.py): leaf panel QRs, the
    # pairwise R-stack reduction levels, and the top-down Q assembly
    # gemms, priced whole via tsqr_flops.
    "IR::residual", "IR::correct", "QR::tsqr",
    # block-arrowhead completion (models/arrowhead.py, docs/SERVING.md
    # "posv_arrowhead").  The chain half of the factorization rides
    # models/blocktri UNCHANGED and keeps emitting its own BT::* phases
    # (the widened [RHS | Bᵀ] forward/backward sweeps are priced there at
    # k + s columns); the AH::* tags price only the completion work the
    # arrowhead adds on top.  AH::schur wraps the Schur-complement
    # assembly S̃ = S − B·T⁻¹·Bᵀ (one batched border gemm) plus the dense
    # corner Cholesky; AH::border wraps the corner RHS correction, the
    # (s, s) triangular corner solves, and the chain back-substitution
    # x_T = Z − Z_B·x_S.  Emits fire outside every scan (the chain scans
    # live inside blocktri) — the BT::factor rationale.
    "AH::schur", "AH::border",
    # streaming state-space sessions (serve/sessions.py, docs/SERVING.md
    # "Streaming sessions").  SS::extend wraps the session open/append
    # chain-extension program, SS::solve the resident-factor sweep
    # program; both price the whole chain OUTSIDE the interior
    # blocktri scans (the BT::factor rationale), and the interior
    # blocktri calls trace muted() so the work is priced exactly once —
    # under the SS::* tag the session stats attribute by.  The
    # session_contract/close ops are host-side (a pure factor slice plus
    # residency bookkeeping) and execute zero device flops: no phase.
    "SS::extend", "SS::solve",
)
_PHASE_SET: set[str] = set(PHASE_REGISTRY)


def register_phase(tag: str) -> str:
    """Register an out-of-tree phase tag so `scope()` accepts it.  Returns
    the tag for inline use.  Downstream tooling picks it up through
    `PHASE_REGISTRY` on next import — in-process registrations extend the
    live set immediately."""
    global PHASE_REGISTRY
    if tag not in _PHASE_SET:
        PHASE_REGISTRY = PHASE_REGISTRY + (tag,)
        _PHASE_SET.add(tag)
    return tag


_SCOPE_STACK: list[str] = []
_ACTIVE: list["Recorder"] = []
_MUTED: list[bool] = []


def current_scope() -> str | None:
    """Innermost active phase tag, or None outside every scope().  This is
    the key the fault-injection taps (robust/faultinject.py) resolve their
    site against — exposed as a function so callers never reach into the
    stack directly."""
    return _SCOPE_STACK[-1] if _SCOPE_STACK else None


@contextlib.contextmanager
def muted():
    """Suppress emit()/note() attribution for the enclosed trace region.

    The robust recovery branches (robust/recovery.guarded_chol, the sCQR3
    escalation in models/qr.py) re-trace the same phase ops inside a
    lax.cond — at runtime only the taken branch executes, but trace-time
    emits would fire for BOTH, double-counting the cost model and poisoning
    the model-vs-compiled drift gate for the healthy path the model is
    meant to price.  Recovery work is therefore traced muted: the model
    describes the healthy path, the audit sees the full program."""
    _MUTED.append(True)
    try:
        yield
    finally:
        _MUTED.pop()


@dataclasses.dataclass
class PhaseStats:
    """Accumulated model costs for one phase tag (one critter symbol).

    Three compute views, mirroring critter's decomposition
    (reference autotune/util.h:63-127, tune.cpp:79-82):

    * ``flops`` — the homogeneous model count (dense work / devices); what
      the round-1/2 tables reported and what the time estimator uses.
    * ``flops_vol`` — volumetric EXECUTED flops per device (mean over the
      mesh): dead-block skipping counts here.
    * ``flops_max`` — max-per-process executed flops: what the
      critical-path device runs.  With block-distributed triangular
      operands this exceeds flops_vol by up to ~2x (the imbalance the
      reference's element-cyclic layout avoids, structure.hpp:80-85) —
      the column that makes that cost visible (VERDICT r2 #4).
    Emitters that don't distinguish (dense ops, single device) leave both
    equal to ``flops``.

    ``copy_bytes`` is the HBM traffic of pure data-movement the schedule
    inserts around the matmuls — masked triangle materializations
    (masking.take_triangle), window slices, transpose materializations, and
    dynamic_update_slice write-backs (each priced as read + write of the
    moved array).  The pallas view/alias kernels and the in-place explicit
    route drive this term to ~0 (ISSUE 3); the materializing paths emit it
    so autotune ranks the copy-free spelling and the trace tool's `copy`
    bucket has a model-side counterpart.
    """

    calls: int = 0
    flops: float = 0.0  # homogeneous model flops, per device
    comm_bytes: float = 0.0  # collective bytes moved, per device
    collectives: int = 0  # collective count (synchronization/latency terms)
    flops_vol: float = 0.0  # executed, volumetric mean per device
    flops_max: float = 0.0  # executed, max over devices (critical path)
    copy_bytes: float = 0.0  # HBM bytes of schedule-inserted copies, per device

    def merge(self, other: "PhaseStats") -> None:
        self.calls += other.calls
        self.flops += other.flops
        self.comm_bytes += other.comm_bytes
        self.collectives += other.collectives
        self.flops_vol += other.flops_vol
        self.flops_max += other.flops_max
        self.copy_bytes += other.copy_bytes


@contextlib.contextmanager
def scope(tag: str):
    """Enter an algorithm phase: named XLA scope + cost-model attribution.

    Tags follow the reference's symbol names (``CI::trsm``, ``CQR::gram``,
    cholinv.hpp:94-136, cacqr.hpp:82-116) and must be registered in
    `PHASE_REGISTRY` (or via `register_phase`): the device-trace tool and
    the drift classifier bucket by the registry, so an unknown tag would
    silently report under 'other' — refused here at trace time instead.
    """
    if tag not in _PHASE_SET:
        raise ValueError(
            f"unregistered phase tag {tag!r}: add it to "
            "tracing.PHASE_REGISTRY (or register_phase) so the trace tool "
            "and drift classifier can bucket it"
        )
    _SCOPE_STACK.append(tag)
    try:
        with jax.named_scope(tag.replace("::", ".")):
            yield
    finally:
        _SCOPE_STACK.pop()


def emit(
    flops: float = 0.0,
    comm_bytes: float = 0.0,
    collectives: int = 0,
    flops_vol: float | None = None,
    flops_max: float | None = None,
    copy_bytes: float = 0.0,
) -> None:
    """Attribute model costs to the innermost active phase.

    Called by the SUMMA layer and algorithm base cases at trace time; no-op
    unless a Recorder is active (zero overhead in production paths).
    flops_vol/flops_max (executed volumetric / max-per-process views)
    default to `flops` — the homogeneous assumption.  copy_bytes prices
    schedule-inserted data movement (see PhaseStats)."""
    if not _ACTIVE or _MUTED:
        return
    tag = _SCOPE_STACK[-1] if _SCOPE_STACK else "<top>"
    for rec in _ACTIVE:
        st = rec.stats[tag]
        st.calls += 1
        st.flops += flops
        st.comm_bytes += comm_bytes
        st.collectives += collectives
        st.flops_vol += flops if flops_vol is None else flops_vol
        st.flops_max += flops if flops_max is None else flops_max
        st.copy_bytes += copy_bytes


def note(tag: str) -> None:
    """Count-only event under its own tag (not the scope stack) — used for
    trace-time telemetry like layout-fallback occurrences.  No-op without an
    active Recorder."""
    if _MUTED:
        return
    for rec in _ACTIVE:
        rec.stats[tag].calls += 1


class Recorder:
    """Collects per-phase model costs during one tracing pass.

    Usage::

        with tracing.Recorder() as rec:
            jitted(args)          # first call: traces, recorder captures
        rec.total().flops, rec.stats['CI::trsm'].comm_bytes, ...

    The reference's equivalent is critter's start/stop + get_*_costs
    (tune.cpp:61-82)."""

    def __init__(self) -> None:
        self.stats: dict[str, PhaseStats] = defaultdict(PhaseStats)

    def __enter__(self) -> "Recorder":
        _ACTIVE.append(self)
        return self

    def __exit__(self, *exc) -> None:
        _ACTIVE.remove(self)

    def total(self) -> PhaseStats:
        t = PhaseStats()
        for s in self.stats.values():
            t.merge(s)
        return t

    def estimate_seconds(
        self, spec: Optional[DeviceSpec] = None, dtype=jnp.float32,
        efficiency: float = 0.6, refine_sweeps: float = 1.0,
    ) -> dict[str, tuple[float, float]]:
        """Per-phase (comp_s, comm_s) estimates from the device model.

        efficiency derates peak matmul throughput (achievable fraction).
        The comm term is the full alpha-beta price: bytes/bandwidth (beta)
        plus collectives x alpha — the synchronization count the model
        already tracks; pricing bytes only under-ranked latency-bound
        small-N / high-q configs (each num_chunks slice adds an alpha,
        not bytes).  Schedule-inserted copies (copy_bytes) are local HBM
        traffic, priced at hbm_gbps into the comp term — they spend device
        time, not interconnect time, which is exactly why the copy-free
        explicit route ranks above the materializing one at equal flops.

        refine_sweeps scales the IR::* phases' flops: the model emits ONE
        refinement sweep per refine() call (the while_loop trip count is
        data-dependent — see the IR::* registry note) while the traffic
        actually executes a measured number of them.  Callers price real
        guaranteed-tier traffic by feeding the measured mean from the
        serve stats `refine` block (`refine_sweeps_from_stats`); the
        default 1.0 keeps the historical one-sweep estimate."""
        spec = spec or device_spec()
        peak = spec.peak_tflops(dtype) * 1e12 * efficiency
        out = {}
        for tag, s in self.stats.items():
            comm = s.comm_bytes / (spec.ici_gbps * 1e9) + s.collectives * spec.alpha_s
            flops = s.flops
            if tag.startswith("IR::"):
                flops *= refine_sweeps
            comp = flops / peak + s.copy_bytes / (spec.hbm_gbps * 1e9)
            out[tag] = (comp, comm)
        return out


# --------------------------------------------------------------------------
# analytic collective/compute cost helpers (the alpha-beta model)
# --------------------------------------------------------------------------


def _ring_bytes(block_bytes: float, p: int) -> float:
    """Bytes per device for a ring broadcast/allgather of `block_bytes` over
    an axis of p devices: (p-1)/p * total."""
    return block_bytes * (p - 1) / p if p > 1 else 0.0


def _allreduce_bytes(block_bytes: float, p: int) -> float:
    """Ring allreduce: 2(p-1)/p * bytes (reduce-scatter + allgather)."""
    return 2.0 * block_bytes * (p - 1) / p if p > 1 else 0.0


def gemm_cost(grid, M: int, N: int, K: int, dtype) -> tuple[float, float, int]:
    """(flops, comm_bytes, collectives) per device for a distributed matmul
    C[M,N] = A[M,K] @ B[K,N] under the SUMMA schedule on a dx x dy x c grid.

    Models exactly what the explicit schedule emits
    (parallel/summa.py:_explicit_matmul).  c == 1: a ring all_gather of the
    A block row over axis 'y' and of the B block column over axis 'x' —
    byte-equal to the reference's d per-step ring Bcasts
    (summa.hpp:185-193).  c > 1: per-step masked-psum broadcasts of only
    this layer's d/c panels (2x ring-bcast bytes per panel, c-fold fewer
    panels — the 2.5D comm saving), plus a ring allreduce of the C block
    over depth (summa.hpp:236).  num_chunks splits each of these into that
    many slice collectives (same bytes, more synchronization points — the
    Ibcast/Iallreduce pipeline).  The 'xla' mode compiles to schedules of
    the same family, so the model serves both.
    """
    dx, dy, c = grid.dx, grid.dy, grid.c
    item = jnp.dtype(dtype).itemsize
    p = dx * dy * c
    flops = 2.0 * M * N * K / p
    q = max(1, getattr(grid, "num_chunks", 0))
    d = max(dx, dy)
    c_blk = (M / dx) * (N / dy) * item
    if c == 1:
        a_row = (M / dx) * K * item  # gathered block row per device
        b_col = K * (N / dy) * item  # gathered block column per device
        comm = _ring_bytes(a_row, dy) + _ring_bytes(b_col, dx)
        ncoll = (q if dy > 1 else 0) + (q if dx > 1 else 0)
    else:
        steps = max(1, d // c)  # this layer's K-steps
        a_pan = (M / dx) * (K / d) * item
        b_pan = (K / d) * (N / dy) * item
        comm = steps * (
            _allreduce_bytes(a_pan, dy) + _allreduce_bytes(b_pan, dx)
        )
        ncoll = steps * ((q if dy > 1 else 0) + (q if dx > 1 else 0))
    comm += _allreduce_bytes(c_blk, c)
    # the collect splits into q column slices, but never more than the
    # block has columns (zero-width tails are skipped by the schedule)
    ncoll += min(q, max(1, int(N // max(1, dy)))) if c > 1 else 0
    return flops, comm, ncoll


def transpose_cost(grid, m: int, n: int, dtype) -> tuple[float, int]:
    """(comm_bytes, collectives) per device for a grid transpose: each
    device exchanges its (m/dx, n/dy) block with the mirrored coordinate —
    the reference's pairwise MPI_Sendrecv_replace (util.hpp:232-247), on
    TPU a collective-permute emitted from the layout constraint."""
    dx, dy = grid.dx, grid.dy
    if dx == 1 and dy == 1:
        return 0.0, 0
    item = jnp.dtype(dtype).itemsize
    return (m / dx) * (n / dy) * item, 1


def replicate_cost(grid, m: int, n: int, dtype) -> tuple[float, int]:
    """(comm_bytes, collectives) to replicate an m x n panel to every device
    (all_gather over the whole mesh) — the base-case gather, the analog of
    MPI_Allgather over the slice communicator (cholinv policy.h:176)."""
    p = grid.num_devices
    bytes_total = m * n * jnp.dtype(dtype).itemsize
    return (_ring_bytes(bytes_total, p), 1 if p > 1 else 0)


def allreduce_cost(grid, m: int, n: int, dtype, axes: str = "all") -> tuple[float, int]:
    """(comm_bytes, collectives) for psum of an m x n value.

    axes='all' reduces over the whole mesh (the 1D gram allreduce,
    cacqr.hpp:22); axes='z' over depth only (SUMMA collect, summa.hpp:236)."""
    p = grid.num_devices if axes == "all" else grid.c
    return (_allreduce_bytes(m * n * jnp.dtype(dtype).itemsize, p), 1 if p > 1 else 0)


def potrf_trtri_flops(n: int) -> float:
    """Local panel factor + triangular inverse: n³/3 + n³/3."""
    return 2.0 * n**3 / 3.0


# -- batched small-N kernel pricing (ops/batched_small.py) -----------------
# These count EXECUTED flops, not textbook useful flops: the batched-grid
# kernels run full-matrix masked sweeps (rank-1 outer-product Cholesky,
# one-hot-extraction triangular substitution) because at small n the
# latency is launch/HBM-bound and dense full-width ops are what Mosaic
# lowers well.  The cost model must price what the program does, or the
# obs drift classifier would flag every fused bucket as compiled-extra.


def batched_chol_flops(n: int) -> float:
    """Full-matrix rank-1 sweep Cholesky, per problem: n columns x
    (extract + scale + rank-1 update + accumulate) ≈ 3 dense (n,n)
    products of width one plus the n-wide extraction ≈ 6n³."""
    return 6.0 * n**3


def batched_trsm_flops(n: int, k: int) -> float:
    """One masked substitution sweep, per problem: n columns x (one-hot
    column extract 2n² + row pick/update 4nk) = 2n³ + 4n²k."""
    return 2.0 * n**3 + 4.0 * n**2 * k


def fused_posv_flops(n: int, k: int) -> float:
    """Fused factor + two substitution sweeps, per problem (SV::fused_posv):
    the factor never leaves VMEM, so this is one phase, one price."""
    return batched_chol_flops(n) + 2.0 * batched_trsm_flops(n, k)


def fused_tail_flops(n: int) -> float:
    """Fused recursion-tail megakernel, whole subtree (CI::tail_fused):
    an (n,n) window factored by the masked column-sweep (guarded-rsqrt
    rank-1 updates, ~6n³ executed like batched_chol_flops) plus the
    back-substitution inverse of the n-wide identity (one masked trsm
    sweep at k=n).  Counts EXECUTED kernel flops — the sweep subsumes the
    subtree's potrf/trsm/syrk/trmm phases, so this single price replaces
    every per-phase emit the unfused recursion would have issued."""
    return batched_chol_flops(n) + batched_trsm_flops(n, n)


def blocktri_chol_flops(nblocks: int, b: int) -> float:
    """Block-tridiagonal factor chain, per problem (BT::factor): each of
    `nblocks` chain blocks runs one masked column-sweep Cholesky of the
    (b, b) Schur complement, one forward substitution sweep for
    Wt = L⁻¹·Cᵀ at k=b, the identity-contraction transpose of C (2b³),
    and the Wtᵀ·Wt Schur update (2b³).  Executed flops, like every
    batched-small price — the textbook useful count is nblocks·(b³/3+3b³)
    (the bench driver's numerator)."""
    return nblocks * (batched_chol_flops(b) + batched_trsm_flops(b, b)
                      + 4.0 * b**3)


def blocktri_solve_flops(nblocks: int, b: int, k: int) -> float:
    """ONE block-bidiagonal substitution sweep (forward or backward), per
    problem (BT::solve): per chain block, one (b, b) triangular sweep at
    width k plus the 2b²k off-diagonal coupling product.  A full potrs
    analog is two of these."""
    return nblocks * (batched_trsm_flops(b, k) + 2.0 * b**2 * k)


def blocktri_partition_flops(nblocks: int, b: int, k: int,
                             partitions: int) -> float:
    """Per-partition side of the partitioned (Spike) chain solve, per
    problem (BT::partition): the `nblocks − P` interior blocks factor
    once, run BOTH substitution sweeps at the widened RHS [B | Φ-cols |
    Ψ-cols] of k + 2b columns (the spike solves ride the same sweep as
    the local solutions), and the back-substitution applies the two
    (b, b) spike blocks to each interior solution (4b²k per block).
    Sequential-depth is O(nblocks/P); the WORK stays O(nblocks·b³) plus
    the spike widening — this price is what the bench driver's A/B row
    shows the depth win costs in executed flops."""
    interior = nblocks - partitions
    return (blocktri_chol_flops(interior, b)
            + 2.0 * blocktri_solve_flops(interior, b, k + 2 * b)
            + 4.0 * interior * b**2 * k)


def blocktri_reduce_flops(partitions: int, b: int, k: int) -> float:
    """Reduced interface system of the partitioned chain solve, per
    problem (BT::reduce): per separator, the Schur assembly gemms (three
    (b, b)·(b, b) products into the reduced diagonal/coupling, 6b³, plus
    two (b, b)·(b, k) RHS corrections, 4b²k), then the P-block reduced
    chain runs the ordinary sequential factor + both sweeps."""
    asm = partitions * (6.0 * b**3 + 4.0 * b**2 * k)
    return (asm + blocktri_chol_flops(partitions, b)
            + 2.0 * blocktri_solve_flops(partitions, b, k))


def chol_update_flops(n: int, k: int) -> float:
    """Rank-k Cholesky update/downdate sweep, per problem (UP::update /
    UP::downdate): k rank passes x n hyperbolic rotations, each a one-hot
    row extract (2n²) plus the full-width row write-back outer product
    (2n²) plus the two width-n axpys — ≈ 4kn³ EXECUTED on the masked
    pallas sweep, same executed-flop convention as batched_chol_flops.
    The textbook useful count is ~2kn² (what the bench driver's speedup
    numerator uses); the blocked J-orthogonal XLA path executes
    ~(4p + 4k + 2k²/p)·n² at panel width p."""
    return 4.0 * k * n**3


def fused_lstsq_flops(m: int, n: int, k: int) -> float:
    """Fused batched CholeskyQR2 lstsq, per problem (SV::fused_lstsq):
    gram 2mn² + AᵀB 2mnk, two sweep factors, the R1⁻ᵀ·G·R1⁻¹ correction
    (n-wide fwd sweep + right-solve ≈ 2 trsm sweeps at k=n), three RHS
    sweeps and one back-substitution, plus the triangular R2·R1 product."""
    return (
        2.0 * m * n * (n + k)
        + 2.0 * batched_chol_flops(n)
        + 2.0 * batched_trsm_flops(n, n)
        + 4.0 * batched_trsm_flops(n, k)
        + 2.0 * n**3
    )


def refine_sweep_flops(n: int, k: int) -> float:
    """ONE iterative-refinement sweep over a dense SPD solve, per problem
    (IR::residual + IR::correct): the high-precision residual gemm
    r = B − A·X (2n²k), the two triangular correction sweeps against the
    resident low-precision factor, and the X += d axpy.  The while_loop
    executes this a data-dependent number of times; the model prices one
    sweep (see the IR::* registry note) and the measured counts live in
    serve stats."""
    return 2.0 * n * n * k + 2.0 * batched_trsm_flops(n, k) + 2.0 * n * k


def arrowhead_schur_flops(nblocks: int, b: int, s: int) -> float:
    """Schur-complement completion of the arrowhead corner, per problem
    (AH::schur): the border reduction gemm B·Z_B over the chain
    (2·nblocks·b·s²) plus the dense corner Cholesky of S̃.  The corner
    rides `lax.linalg.cholesky` — a real dense potrf, not a masked sweep —
    so the textbook s³/3 IS the executed count there; the widened chain
    sweeps that produced Z_B are priced inside blocktri under BT::*."""
    return 2.0 * nblocks * b * s * s + s**3 / 3.0


def arrowhead_border_flops(nblocks: int, b: int, s: int, k: int) -> float:
    """Corner solve + chain back-substitution of the arrowhead completion,
    per problem (AH::border): the corner RHS correction y = b_S − B·Z_rhs
    (2·n·s·k over the chain), the two dense (s, s) triangular corner
    solves at width k (2s²k, XLA triangular_solve), and the chain
    back-substitution x_T = Z_rhs − Z_B·x_S (another 2·n·s·k)."""
    n = nblocks * b
    return 4.0 * n * s * k + 2.0 * s * s * k


def refine_sweeps_from_stats(refine_block: Optional[dict]) -> float:
    """Mean executed refinement sweeps per request, read from a serve
    stats `refine` snapshot block (stats.Collector) — the feed for
    `Recorder.estimate_seconds(refine_sweeps=...)`.  Uses the iters p50
    (the typical request's sweep count); absent or malformed blocks fall
    back to the model's one-sweep default, floored at 1.0 because every
    refined request runs at least the residual check sweep."""
    if not refine_block:
        return 1.0
    iters = refine_block.get("iters") or {}
    try:
        return max(float(iters.get("p50", 1.0)), 1.0)
    except (TypeError, ValueError):
        return 1.0


def refine_lstsq_sweep_flops(m: int, n: int, k: int) -> float:
    """ONE semi-normal-equation refinement sweep over lstsq, per problem:
    residual r = B − A·X (2mnk), gram product g = Aᵀr (2mnk), the two
    triangular sweeps of d = R⁻¹R⁻ᵀg, and the update axpy."""
    return 4.0 * m * n * k + 2.0 * batched_trsm_flops(n, k) + 2.0 * n * k


def tsqr_flops(m: int, n: int, leaves: int) -> float:
    """Blocked Householder TSQR, per problem (QR::tsqr): leaf panel QRs
    (Householder sweep + thin-Q assembly ≈ 4·panel·n² each over `leaves`
    panels of m/leaves rows), the pairwise (2n, n) reduction QRs
    (leaves − 1 of them at ≈ 8n³), and the top-down per-level Q-assembly
    gemms (2·panel·n² per leaf per level)."""
    leaves = max(int(leaves), 1)
    levels = max(leaves.bit_length() - 1, 0)
    return (4.0 * m * n**2 + 8.0 * (leaves - 1) * n**3
            + 2.0 * levels * m * n**2)


# --------------------------------------------------------------------------
# measurement (wall clock) + profiler integration
# --------------------------------------------------------------------------


def measure(
    fn: Callable,
    *args,
    iters: int = 3,
    repeats: int = 3,
    warmup: bool = True,
) -> float:
    """Median wall seconds per call of `fn(*args)`, properly synced.

    The reference's timing discipline is barrier + MPI_Wtime around the
    factor call with a warmup iteration (bench/cholesky/cholinv.cpp:44-59);
    the TPU equivalent must defeat async dispatch: block_until_ready on the
    result is the sync point.
    """
    if warmup:
        jax.block_until_ready(fn(*args))
    walls = []
    for _ in range(repeats):
        t0 = time.perf_counter()
        for _ in range(iters):
            out = fn(*args)
        jax.block_until_ready(out)
        walls.append((time.perf_counter() - t0) / iters)
    return statistics.median(walls)


@contextlib.contextmanager
def trace(logdir: str):
    """jax.profiler trace around a region — the deep-inspection path
    (critter's set_mechanism analog; view in TensorBoard/Perfetto, phases
    appear under the named scopes)."""
    with jax.profiler.trace(logdir):
        yield


# --------------------------------------------------------------------------
# cost tables (reference autotune/util.h format family)
# --------------------------------------------------------------------------

def _rows_to_text(rows: list[list]) -> str:
    """Fixed-width table: column width = longest cell + 2 (the reference
    hardcodes setw(15), which its short numeric configs fit; phase-tag
    columns here are longer, so size to content to keep columns aligned)."""
    cells = [[str(c) for c in r] for r in rows]
    width = max((len(c) for r in cells for c in r), default=0) + 2
    return "".join("".join(f"{c:<{width}}" for c in r) + "\n" for r in cells)


def write_times_table(
    path: str,
    rows: list[tuple[str, float, dict[str, tuple[float, float]]]],
) -> None:
    """Measured + estimated per-phase times, one row per config.

    rows: (config_id, measured_wall_s, {tag: (est_comp_s, est_comm_s)}).
    Mirrors the *_cp_times tables (autotune/util.h:4-20): Raw = measured
    wall; per-tag comp/comm estimate columns.
    """
    tags = sorted({t for _, _, est in rows for t in est})
    table = [["Config", "Raw"] + [f"{t}-comp" for t in tags] + [f"{t}-comm" for t in tags]]
    for cid, wall, est in rows:
        table.append(
            [cid, f"{wall:.6f}"]
            + [f"{est.get(t, (0, 0))[0]:.6f}" for t in tags]
            + [f"{est.get(t, (0, 0))[1]:.6f}" for t in tags]
        )
    with open(path, "w") as f:
        f.write(_rows_to_text(table))


def write_costs_table(path: str, rows: list[tuple[str, Recorder]]) -> None:
    """Model cost decomposition per config: flops / comm bytes / collective
    count per phase — the *_cp_costs analog (autotune/util.h:21-29):
    comp ↔ Decomp-comp, comm bytes ↔ Decomp-BSPcomm, collectives ↔ synch —
    plus critter's other two compute views (util.h:63-127, tune.cpp:79-82):
    comp-vol (volumetric executed, mean per device) and comp-max
    (max-per-process, the critical-path device; with block-distributed
    triangular operands up to ~2x comp-vol — see summa.tri_fractions) —
    plus the copy column (copy_bytes: schedule-inserted HBM data movement;
    ~0 on the view/alias routes, docs/OBSERVABILITY.md)."""
    tags = sorted({t for _, rec in rows for t in rec.stats})
    table = [
        ["Config"]
        + [f"{t}-comp" for t in tags]
        + [f"{t}-comp-vol" for t in tags]
        + [f"{t}-comp-max" for t in tags]
        + [f"{t}-comm" for t in tags]
        + [f"{t}-synch" for t in tags]
        + [f"{t}-copy" for t in tags]
    ]
    for cid, rec in rows:
        table.append(
            [cid]
            + [f"{rec.stats[t].flops:.3e}" if t in rec.stats else "0" for t in tags]
            + [f"{rec.stats[t].flops_vol:.3e}" if t in rec.stats else "0" for t in tags]
            + [f"{rec.stats[t].flops_max:.3e}" if t in rec.stats else "0" for t in tags]
            + [f"{rec.stats[t].comm_bytes:.3e}" if t in rec.stats else "0" for t in tags]
            + [str(rec.stats[t].collectives) if t in rec.stats else "0" for t in tags]
            + [f"{rec.stats[t].copy_bytes:.3e}" if t in rec.stats else "0" for t in tags]
        )
    with open(path, "w") as f:
        f.write(_rows_to_text(table))
