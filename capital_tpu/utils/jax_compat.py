"""Version compatibility for the small set of new-jax APIs the framework
uses (jax >= 0.5 spellings), so the same source runs on the 0.4.x line.

Scope is deliberately tiny — exactly the four surfaces the explicit
shard_map schedule and the Mosaic kernels touch:

* ``shard_map``          — ``jax.shard_map`` (new) vs
                           ``jax.experimental.shard_map.shard_map`` (old).
                           The old entry point has no vma type system; its
                           ``check_rep`` analysis predates the schedules
                           here, so the fallback always disables it — the
                           out_specs still declare the contract.
* ``pcast``              — ``lax.pcast`` casts replicated values to the
                           varying type collectives expect under
                           check_vma.  Without the vma system the cast is
                           meaningless: identity.
* ``vma_of``             — ``jax.typeof(x).vma`` where it exists, else an
                           empty frozenset (nothing is vma-typed on old
                           jax).
* ``pallas_compiler_params`` — ``pltpu.CompilerParams`` was named
                           ``TPUCompilerParams`` on the 0.4.x line.

Everything degrades to the semantics the old APIs actually had; no
behavior changes on new jax (the first branch is always the new API).
"""

from __future__ import annotations

import jax
from jax import lax

_HAS_NEW_SHARD_MAP = hasattr(jax, "shard_map")

if _HAS_NEW_SHARD_MAP:
    def shard_map(f, *, mesh, in_specs, out_specs, check_vma=True):
        return jax.shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=check_vma,
        )
else:
    from jax.experimental.shard_map import shard_map as _shard_map_old

    def shard_map(f, *, mesh, in_specs, out_specs, check_vma=True):
        # old jax: no vma types; check_rep's replication analysis rejects
        # valid schedules the vma system accepts, so it stays off
        return _shard_map_old(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_rep=False,
        )


def has_shard_map() -> bool:
    """True when SOME shard_map entry point exists (new or experimental) —
    the gate multi-device explicit-mode tests should probe instead of
    ``hasattr(jax, "shard_map")``."""
    return True  # import of this module already proved one exists


if hasattr(lax, "pcast"):
    pcast = lax.pcast
else:
    def pcast(x, axes, *, to="varying"):
        return x


if hasattr(jax, "typeof"):
    def vma_of(x) -> frozenset:
        return frozenset(getattr(jax.typeof(x), "vma", ()) or ())
else:
    def vma_of(x) -> frozenset:
        return frozenset()


def pallas_compiler_params(pltpu_module, **kwargs):
    """Build pltpu.CompilerParams / TPUCompilerParams across the rename."""
    cls = getattr(pltpu_module, "CompilerParams", None)
    if cls is None:
        cls = pltpu_module.TPUCompilerParams
    return cls(**kwargs)
