"""Deterministic, grid-independent matrix fillers (drand48-compatible).

The reference fills distributed matrices with per-element values derived from
the POSIX rand48 generator so that the *global* matrix content is independent
of the process-grid shape: the symmetric filler re-seeds ``srand48`` from the
global element coordinates for every element and takes one ``drand48`` draw
(reference src/matrix/structure.hpp:68-105).  That coordinate-seeded scheme is
what makes cross-grid and cross-implementation validation possible, so this
module reproduces it bit-for-bit — vectorized over the whole array instead of
an element loop.

rand48 recurrence (POSIX): X_{n+1} = (a * X_n + c) mod 2^48 with
a = 0x5DEECE66D, c = 0xB; ``srand48(s)`` sets X = (s << 16) | 0x330E;
``drand48()`` advances once and returns X / 2^48.
"""

from __future__ import annotations

import numpy as np

_A = np.uint64(0x5DEECE66D)
_C = np.uint64(0xB)
_MASK48 = np.uint64((1 << 48) - 1)
_SRAND_LOW = np.uint64(0x330E)
_TWO48 = float(1 << 48)


def drand48_from_seed(seeds: np.ndarray) -> np.ndarray:
    """First drand48() draw after srand48(seed), elementwise over `seeds`.

    Equivalent to the reference's per-element ``srand48(seed); drand48()``
    (structure.hpp:80-85) but vectorized.
    """
    seeds = np.asarray(seeds)
    x = ((seeds.astype(np.uint64) << np.uint64(16)) | _SRAND_LOW) & _MASK48
    x = (_A * x + _C) & _MASK48
    return x.astype(np.float64) / _TWO48


def symmetric(
    n: int,
    diagonally_dominant: bool = True,
    dtype=np.float64,
    rows: slice | None = None,
    cols: slice | None = None,
) -> np.ndarray:
    """Dense SPD-ready symmetric matrix, identical to the reference's
    ``distribute_symmetric`` global content (structure.hpp:68-105).

    Element (r, c) is seeded with ``max(r,c) + n*min(r,c)`` (symmetric in
    r,c — the reference computes ``gx>gy ? gx + N*gy : gy + N*gx`` with
    gx=column, gy=row); the diagonal gains +n when `diagonally_dominant`,
    making the matrix SPD.

    `rows`/`cols` optionally restrict generation to a sub-block (so each
    device can generate only its shard).
    """
    r = np.arange(n, dtype=np.uint64)[rows if rows is not None else slice(None)]
    c = np.arange(n, dtype=np.uint64)[cols if cols is not None else slice(None)]
    R = r[:, None]
    C = c[None, :]
    lo = np.minimum(R, C)
    hi = np.maximum(R, C)
    seeds = hi + np.uint64(n) * lo
    out = drand48_from_seed(seeds)
    if diagonally_dominant:
        out = out + np.where(R == C, float(n), 0.0)
    return out.astype(dtype)


def _splitmix64(x: np.ndarray) -> np.ndarray:
    """SplitMix64 finalizer — a nonlinear hash over uint64, vectorized.
    uint64 wrap-around mod 2^64 is the intended semantics; errstate silences
    numpy's scalar/0-d overflow warnings."""
    x = np.asarray(x, dtype=np.uint64)
    with np.errstate(over="ignore"):
        x = x + np.uint64(0x9E3779B97F4A7C15)
        x = (x ^ (x >> np.uint64(30))) * np.uint64(0xBF58476D1CE4E5B9)
        x = (x ^ (x >> np.uint64(27))) * np.uint64(0x94D049BB133111EB)
        return x ^ (x >> np.uint64(31))


def random(
    m: int,
    n: int,
    key: int = 0,
    dtype=np.float64,
    rows: slice | None = None,
    cols: slice | None = None,
) -> np.ndarray:
    """Dense random matrix in [0,1), grid-independent.

    The reference's ``distribute_random`` (structure.hpp:106-130) seeds once
    and draws in local element order, which makes the global content depend on
    the grid shape — a latent bug for cross-grid validation.  Coordinate
    seeding fixes that, but the rand48 *first draw* is affine in the seed, so
    sequentially-seeded elements would be linearly correlated (catastrophic
    conditioning for QR test matrices).  Hence: coordinate seed -> splitmix64
    hash -> [0,1).  Grid-independent and full-rank-quality.
    """
    r = np.arange(m, dtype=np.uint64)[rows if rows is not None else slice(None)]
    c = np.arange(n, dtype=np.uint64)[cols if cols is not None else slice(None)]
    # hash key and shape together so distinct (key, shape) streams occupy
    # unrelated regions of seed space instead of overlapping arithmetically
    base = _splitmix64(
        _splitmix64(np.uint64(key)) ^ ((np.uint64(m) << np.uint64(32)) | np.uint64(n))
    )
    seeds = base + r[:, None] * np.uint64(n) + c[None, :]
    vals = (_splitmix64(seeds) >> np.uint64(11)).astype(np.float64) / float(1 << 53)
    return vals.astype(dtype)


def identity(m: int, n: int, dtype=np.float64) -> np.ndarray:
    """Reference ``distribute_identity`` equivalent (matrix.h:67)."""
    return np.eye(m, n, dtype=dtype)


def debug(m: int, n: int, dtype=np.float64) -> np.ndarray:
    """Reference ``distribute_debug`` equivalent: element = global flat index,
    useful for asserting layouts (matrix.h:68)."""
    return (np.arange(m * n, dtype=np.float64).reshape(m, n)).astype(dtype)
