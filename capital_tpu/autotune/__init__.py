"""Autotune subsystem (reference autotune/) — see sweep.py."""

from capital_tpu.autotune.sweep import (  # noqa: F401
    cacqr_space,
    cholinv_space,
    run_sweep,
    tune_cacqr,
    tune_cholinv,
)
