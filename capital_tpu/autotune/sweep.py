"""Autotune: config sweeps over algorithm knobs, with cost tables.

The reference's autotune layer (autotune/{cholesky,qr}/*/tune.cpp +
autotune/util.h) sweeps base-case policy x bcMultiplier (x grid shape for
QR) under the critter measurement tool and writes critical-path cost tables
(tune.cpp:175-253, autotune/util.h:4-127).  The TPU equivalent here:

* the **measured** axis is wall time per factor call, taken with the in-jit
  loop + delta discipline (bench/harness.py) — the reference's
  barrier+MPI_Wtime with critter's timers;
* the **modeled** axis is the alpha-beta cost decomposition captured by
  tracing.Recorder at trace time (per-phase flops / comm bytes /
  collective counts — critter's comp/comm/synch columns);
* outputs: `<alg>_cp_times.txt` (measured + per-phase estimates) and
  `<alg>_cp_costs.txt` (model decomposition), the *_cp_times/*_cp_costs
  table family of autotune/util.h, plus `<alg>_best.json` with the winning
  config — the piece the reference leaves to the user's eyeballs.

Config spaces mirror tune.cpp: cholinv sweeps policy x base_case_dim
(x split); cacqr sweeps variant x base_case_dim x regime.  Grid-shape
sweeping (the reference's rep-factor loop, qr tune.cpp) plugs in via the
`grids` argument.
"""

from __future__ import annotations

import dataclasses
import itertools
import json
import os
from typing import Callable, Iterable

import jax
import jax.numpy as jnp
import numpy as np

from capital_tpu.bench import harness
from capital_tpu.models import cholesky, qr
from capital_tpu.parallel.topology import Grid
from capital_tpu.utils import tracing
from capital_tpu.utils.config import BaseCasePolicy


@dataclasses.dataclass
class SweepResult:
    config_id: str
    config: dict
    seconds: float
    recorder: tracing.Recorder
    #: measurement-protocol sidecar (e.g. latency_measure's wall_ms
    #: percentile block); merged into the ledger's measured dict.  None
    #: under the default amortized protocol.
    extra: dict | None = None


# --------------------------------------------------------------------------
# sweep checkpointing: hardware sweeps take tens of minutes per pass and the
# environment can preempt them; a resumed sweep (CLI --resume) skips configs
# already measured for the same (shape, dtype, device) problem.  The
# reference has no such capability (its tune.cpp restarts from scratch).
# --------------------------------------------------------------------------


def _grid_key(grid: Grid) -> dict:
    """Topology identity for the resume key: shape AND the concrete device
    ordering (device ids in mesh order), which captures the layout knob —
    two grids differing only in layout place devices differently, time
    collectives differently, and must not share resumed timings."""
    return {
        "grid": repr(grid),
        "devices": [int(d.id) for d in grid.mesh.devices.ravel()],
    }


# Bump on any kernel or measurement-protocol change that invalidates stored
# timings (e.g. the paired-median drift protocol, tri-operand bk halving):
# resumed sweeps must not mix pre-change checkpointed numbers with fresh ones
# and crown a stale config.
MEASUREMENT_PROTOCOL_VERSION = 2


def _ckpt_key(name: str, operand, extra: dict | None = None) -> dict:
    """Problem identity for resume: name, operand, device kind, protocol
    version, and whatever the caller adds (the grid topology — a 2x2x1
    sweep's timings must never be resumed into a 1-device sweep of the same
    matrix)."""
    return {
        "name": name,
        "shape": list(operand.shape),
        "dtype": str(operand.dtype),
        "device": jax.devices()[0].device_kind,
        "protocol": MEASUREMENT_PROTOCOL_VERSION,
        **(extra or {}),
    }


def _ckpt_path(out_dir: str, name: str, key: dict) -> str:
    """Checkpoint file keyed by the problem hash, so sweeps of different
    problems sharing an out_dir cannot clobber each other's partial state."""
    import hashlib

    h = hashlib.sha256(json.dumps(key, sort_keys=True).encode()).hexdigest()[:10]
    return os.path.join(out_dir, f"{name}_sweep_{h}.json")


def _ckpt_load(path: str, key: dict) -> dict:
    """Load the resume state, tolerating entries written by older schemas.

    A checkpoint is a cache, not a contract: an entry missing fields this
    version reads (older writer, hand-edited file) is DROPPED with a note —
    the config simply re-measures — instead of KeyError-aborting the whole
    resume.  Kept entries:
      * failure records ({"failed": true, ...}) — persisted so a config
        that OOMed is not retried forever across resumes;
      * measurement records with a numeric "seconds"; missing "config" /
        "stats" default to {} (the table row degrades, the timing
        survives)."""
    try:
        with open(path) as f:
            data = json.load(f)
    except (OSError, json.JSONDecodeError):
        return {}
    if data.get("key") != key:
        return {}
    done = data.get("done", {})
    if not isinstance(done, dict):
        return {}
    out: dict = {}
    for cid, entry in done.items():
        if not isinstance(entry, dict):
            print(f"# autotune resume: dropping malformed entry {cid!r}")
            continue
        if entry.get("failed"):
            out[cid] = entry
            continue
        if isinstance(entry.get("seconds"), (int, float)):
            out[cid] = {
                "config": entry.get("config", {}),
                "seconds": float(entry["seconds"]),
                "stats": entry.get("stats", {}),
                # protocol sidecar (latency percentiles): optional, rides
                # the resume so a resumed latency sweep keeps its wall_ms
                "extra": entry.get("extra"),
            }
        else:
            print(
                f"# autotune resume: dropping {cid!r} (no usable 'seconds' "
                "— older schema?); it will be re-measured"
            )
    return out


def _ckpt_save(path: str, key: dict, done: dict) -> None:
    # same atomic-rename discipline as utils/checkpoint.save; kept separate
    # because sweep state is pure JSON (no arrays — npz would bury the
    # human-inspectable per-config record the sweep wants to expose)
    tmp = f"{path}.tmp{os.getpid()}"
    try:
        with open(tmp, "w") as f:
            json.dump({"key": key, "done": done}, f)
        os.replace(tmp, path)  # atomic: a preemption mid-write tears nothing
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)


def _recorder_from(stats: dict) -> tracing.Recorder:
    rec = tracing.Recorder()
    for tag, s in stats.items():
        # dataclass round trip: a future PhaseStats field restores too
        rec.stats[tag].merge(tracing.PhaseStats(**s))
    return rec


def _recorder_dump(rec: tracing.Recorder) -> dict:
    return {tag: dataclasses.asdict(s) for tag, s in rec.stats.items()}


def _model_costs(step: Callable, operand) -> tracing.Recorder:
    """Capture the alpha-beta model decomposition for one config by tracing
    (no execution): phase emits fire at trace time."""
    rec = tracing.Recorder()
    with rec:
        jax.eval_shape(step, operand)
    return rec


def run_sweep(
    name: str,
    configs: Iterable[tuple[str, dict, Callable]],
    operand,
    out_dir: str = ".",
    iters: int = 2,
    dtype=None,
    checkpoint: bool = False,
    key_extra: dict | None = None,
    ledger: str | None = None,
    retry: harness.RetryPolicy = harness.RetryPolicy(),
    measure: Callable | None = None,
) -> list[SweepResult]:
    """Measure + model every (config_id, config_dict, step_fn) and write the
    cost tables.  Returns results sorted best-first by measured time.

    `measure` swaps the measurement protocol: a callable
    ``measure(step, operand) -> (seconds, extra_dict | None)`` replacing
    the default amortized ``harness.timed_loop`` (which `iters` feeds).
    The returned seconds is what the sweep SORTS on — a latency protocol
    returning p99 wall seconds (latency_measure) makes the sweep optimize
    p99, not mean throughput — and extra_dict rides the SweepResult, the
    checkpoint, and the ledger's measured block (e.g. the full wall_ms
    percentile split).  Containment is identical either way: the call runs
    under run_guarded with the same retry policy.

    checkpoint=True persists per-config results to a problem-keyed
    ``<out_dir>/<name>_sweep_<hash>.json`` after each measurement; a re-run
    of the same problem (shape/dtype/device/topology) resumes, skipping
    measured configs.  Unresolved (noise-floor) configs are NOT persisted —
    the condition can be a transient drift window, so every resume retries
    them.

    Runtime failures (OOM / compile abort — XlaRuntimeError) of one config
    are CONTAINED: retried per `retry` (harness.run_guarded), then recorded
    as a failure — in the checkpoint (so resumes don't retry a known-bad
    config forever) and as a status='failed' event in the ledger — while
    the remaining configs keep sweeping.

    ledger=PATH additionally appends one obs ledger record per swept config
    (manifest keyed by config_id, the Recorder model decomposition, and the
    measured seconds) so sweeps land in the same queryable JSONL stream as
    bench runs and audits.  Configs that needed retries land with a
    status='recovered' event."""
    dtype = dtype or operand.dtype
    configs = list(configs)
    if not configs:
        raise ValueError(f"autotune sweep {name!r}: no configs to sweep")
    key = _ckpt_key(name, operand, key_extra)
    ckpt_path = _ckpt_path(out_dir, name, key)
    done: dict = {}
    if checkpoint:
        os.makedirs(out_dir, exist_ok=True)
        done = _ckpt_load(ckpt_path, key)
    results: list[SweepResult] = []
    attempts_by: dict[str, int] = {}
    failures: list[tuple[str, dict, dict]] = []  # (cid, cdict, failure entry)
    for cid, cdict, step in configs:
        if cid in done:
            entry = done[cid]
            if entry.get("failed"):
                print(
                    f"# autotune {name}: {cid}  FAILED previously "
                    f"({entry.get('error', '?')}) — skipped on resume"
                )
                continue
            results.append(
                SweepResult(
                    cid, entry["config"], entry["seconds"],
                    _recorder_from(entry["stats"]), entry.get("extra"),
                )
            )
            print(f"# autotune {name}: {cid}  {entry['seconds'] * 1e3:.3f} ms (resumed)")
            continue
        rec = _model_costs(step, operand)
        extra_m: dict | None = None
        try:
            if measure is None:
                secs, attempts = harness.run_guarded(
                    lambda: harness.timed_loop(step, operand, iters=iters),
                    policy=retry,
                    label=f"{name}:{cid}",
                )
            else:
                out, attempts = harness.run_guarded(
                    lambda: measure(step, operand),
                    policy=retry,
                    label=f"{name}:{cid}",
                )
                secs, extra_m = out
        except harness.MeasurementUnresolved as e:
            # below the measurement noise floor: record nothing for this
            # config rather than aborting the sweep and losing the rest
            print(f"# autotune {name}: {cid}  UNRESOLVED ({e})")
            continue  # deliberately not checkpointed: retried on resume
        except harness.ConfigFailed as e:
            # runtime failure contained to this config: the sweep goes on
            print(f"# autotune {name}: {cid}  FAILED ({e})")
            entry = {
                "failed": True,
                "error": f"{type(e.cause).__name__}: {e.cause}",
                "attempts": e.attempts,
                "config": cdict,
            }
            failures.append((cid, cdict, entry))
            if checkpoint:
                done[cid] = entry
                _ckpt_save(ckpt_path, key, done)
            continue
        if attempts > 1:
            attempts_by[cid] = attempts
        results.append(SweepResult(cid, cdict, secs, rec, extra_m))
        print(f"# autotune {name}: {cid}  {secs * 1e3:.3f} ms")
        if checkpoint:
            done[cid] = {
                "config": cdict, "seconds": secs, "stats": _recorder_dump(rec),
            }
            if extra_m is not None:
                done[cid]["extra"] = extra_m
            _ckpt_save(ckpt_path, key, done)

    os.makedirs(out_dir, exist_ok=True)
    spec = tracing.device_spec()
    tracing.write_times_table(
        os.path.join(out_dir, f"{name}_cp_times.txt"),
        [
            (r.config_id, r.seconds, r.recorder.estimate_seconds(spec, dtype))
            for r in results
        ],
    )
    tracing.write_costs_table(
        os.path.join(out_dir, f"{name}_cp_costs.txt"),
        [(r.config_id, r.recorder) for r in results],
    )
    if ledger:
        from capital_tpu.obs import ledger as obs_ledger

        extra = dict(key_extra or {})
        # key_extra's "grid" is already a repr string — it must not bind
        # manifest()'s grid parameter (which expects a Grid object)
        grid_repr = extra.pop("grid", None)

        def _man(cdict, cid):
            man = obs_ledger.manifest(
                dtype=dtype, config=cdict, config_id=cid,
                shape=list(operand.shape), **extra,
            )
            if grid_repr is not None:
                man["grid"] = grid_repr
            return man

        # failure events FIRST: even a sweep where nothing resolved leaves
        # its failures queryable (the raise below fires after this block)
        for cid, cdict, entry in failures:
            obs_ledger.append(
                ledger,
                obs_ledger.record(
                    f"autotune:{name}",
                    _man(cdict, cid),
                    event={
                        "status": "failed",
                        "error": entry["error"],
                        "attempts": entry["attempts"],
                    },
                ),
            )
        for r in results:
            ev = (
                {"status": "recovered", "attempts": attempts_by[r.config_id]}
                if r.config_id in attempts_by
                else None
            )
            obs_ledger.append(
                ledger,
                obs_ledger.record(
                    f"autotune:{name}",
                    _man(r.config, r.config_id),
                    model=obs_ledger.model_costs(r.recorder, dtype=dtype),
                    # value is rate (1/s), not seconds: diff() flags VALUE
                    # drops, and "slower" must read as a drop
                    measured={
                        "metric": f"{name}_sweep",
                        "value": round(1.0 / r.seconds, 4),
                        "unit": "iter/s",
                        "seconds": r.seconds,
                        # protocol sidecar: a latency sweep lands its
                        # wall_ms percentile block here, so per-bucket
                        # p99 is queryable straight off the ledger
                        **(r.extra or {}),
                    },
                    **({"event": ev} if ev else {}),
                ),
            )
    if not results:
        raise RuntimeError(
            f"autotune sweep {name!r}: no config produced a resolvable time"
        )
    results.sort(key=lambda r: r.seconds)
    best = results[0]
    with open(os.path.join(out_dir, f"{name}_best.json"), "w") as f:
        json.dump(
            {
                "config": best.config,
                "seconds": best.seconds,
                "configs_swept": len(results),
                "device": jax.devices()[0].device_kind,
            },
            f,
            indent=1,
        )
    return results


# --------------------------------------------------------------------------
# per-algorithm config spaces (reference tune.cpp sweeps)
# --------------------------------------------------------------------------


def _spd(n: int, dtype) -> jnp.ndarray:
    # one SPD builder for every harness consumer (3I shift + on-device
    # generation — see drivers._spd for the numerical rationale)
    from capital_tpu.bench.drivers import _spd as _drivers_spd

    return _drivers_spd(n, dtype)


def grid_space(
    devices=None,
    c_values: Iterable[int] = (1, 2, 4),
    include_flat: bool = False,
) -> list[Grid]:
    """Feasible grid shapes over the available devices — the reference's
    rep-factor loop (bench/qr/cacqr.cpp:8-25, qr tune.cpp sweeps grid shape
    alongside bc).  For each replication depth c, the largest d x d x c
    square grid the device count supports; plus the flat 1D topology when
    requested (the tall-skinny regime).  Degenerates to [1x1x1] on one
    device."""
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    grids: list[Grid] = []
    seen: set[tuple[int, int, int]] = set()
    for c in c_values:
        d = 1
        while (d + 1) * (d + 1) * c <= n:
            d += 1
        # feasibility: the explicit schedule needs c | d (summa.py K-segment
        # split), so a 2x2x4 "fits 16 devices" shape would abort a sweep
        # mid-run — step DOWN to the largest multiple of c that fits rather
        # than dropping the whole c-axis (128 devices, c=4: d=5 fits but
        # 4x4x4 is the feasible shape); and 1x1xC is pure redundancy
        d -= d % c
        if (
            d >= 1
            and d * d * c <= n
            and (d, d, c) not in seen
            and (d > 1 or c == 1)
        ):
            seen.add((d, d, c))
            grids.append(Grid.square(c=c, devices=devices[: d * d * c]))
    if include_flat and n > 1:
        grids.append(Grid.flat(devices=devices))
    return grids


def _with_grids(grids, base_grid):
    """The grid axis of a config space: explicit list, or just the fixed
    sweep grid."""
    return list(grids) if grids else [base_grid]


def _gid(grid: Grid) -> str:
    tag = f"g{grid.dx}x{grid.dy}x{grid.c}"
    if getattr(grid, "layout", 0):
        tag += f"l{grid.layout}"
    if getattr(grid, "num_chunks", 0):
        tag += f"q{grid.num_chunks}"
    return tag


def cholinv_space(
    grid: Grid,
    dtype,
    bc_dims: Iterable[int] = (128, 256, 512, 1024),
    policies: Iterable[BaseCasePolicy] = (
        BaseCasePolicy.REPLICATE_COMM_COMP,
        BaseCasePolicy.NO_REPLICATION,
    ),
    splits: Iterable[int] = (1,),
    modes: Iterable[str] = ("xla",),
    grids: Iterable[Grid] | None = None,
    balances: Iterable[str] = ("block",),
    tail_depths: Iterable[int] = (0,),
):
    """policy x bc x split x mode (x grid shape) (x balance)
    (x tail_fuse_depth) — the reference's decomposition sweep (cholesky
    tune.cpp:175-253: 3 policies x bcMultiplier range) plus the
    rep-factor/grid-shape axis (`grids`, e.g. from grid_space()).  The
    operand reshards to each grid's face on the first in-loop iteration;
    subsequent iterations carry the face layout, so the measured
    steady-state time is that grid's.  `balances` adds the schedule axis
    ('block' / 'tile_cyclic' / 'tile_cyclic_persistent', explicit mode
    only) — the planner prices the copy-bytes difference, so the
    persistent spelling ranks on the model, not only in the measured
    sweep.  `tail_depths` adds the fused-recursion-tail axis
    (CholinvConfig.tail_fuse_depth; depth 0 = unfused, the default, so
    existing config ids stay stable)."""
    prec = None if jnp.dtype(dtype).itemsize < 4 else "highest"
    glist = _with_grids(grids, grid)
    for g, pol, bc, split, mode, bal, td in itertools.product(
        glist, policies, bc_dims, splits, modes, balances, tail_depths
    ):
        if bal != "block" and mode != "explicit":
            continue  # balanced schedules are explicit-only (cholesky.factor raises)
        cfg = cholesky.CholinvConfig(
            base_case_dim=bc, split=split, policy=pol, mode=mode,
            precision=prec, balance=bal, tail_fuse_depth=td,
        )

        def step(a, cfg=cfg, g=g):
            R, Rinv = cholesky.factor(g, a, cfg)
            return R + Rinv

        cid = f"pol{pol.value}_bc{bc}_s{split}_{mode}"
        if bal != "block":
            cid += f"_{bal}"
        if td:
            cid += f"_tf{td}"
        cdict = {
            "policy": pol.name, "base_case_dim": bc, "split": split, "mode": mode,
        }
        if bal != "block":
            cdict["balance"] = bal
        if td:
            cdict["tail_fuse_depth"] = td
        if grids is not None:
            # topology parameters ride the config dict whenever a grids
            # axis was passed — even a single-element axis may differ from
            # the base grid, and the prefilter must model the topology the
            # step actually measures on
            cdict["grid"] = repr(g)
            cdict["grid_shape"] = [g.dx, g.dy, g.c]
            cdict["num_chunks"] = g.num_chunks
            cdict["layout"] = getattr(g, "layout", 0)
        if len(glist) > 1:
            cid = f"{_gid(g)}_{cid}"
        yield cid, cdict, step


def cacqr_space(
    grid: Grid,
    dtype,
    bc_dims: Iterable[int] = (128, 256, 512),
    variants: Iterable[int] = (1, 2),
    regimes: Iterable[str] = ("auto",),
    grids: Iterable[Grid] | None = None,
):
    """variant x bc x regime (x grid shape) — qr tune.cpp sweeps
    bcMultiplier x grid shape; pass grids=grid_space(include_flat=True) to
    sweep the topology axis on real hardware."""
    prec = None if jnp.dtype(dtype).itemsize < 4 else "highest"
    glist = _with_grids(grids, grid)
    for g, variant, bc, regime in itertools.product(
        glist, variants, bc_dims, regimes
    ):
        cfg = qr.CacqrConfig(
            num_iter=variant,
            regime=regime,
            cholinv=cholesky.CholinvConfig(base_case_dim=bc, precision=prec),
            precision=prec,
        )

        def step(a, cfg=cfg, g=g):
            Q, R = qr.factor(g, a, cfg)
            return Q.at[: R.shape[0], : R.shape[1]].add(R.astype(Q.dtype))

        cid = f"v{variant}_bc{bc}_{regime}"
        cdict = {"variant": variant, "base_case_dim": bc, "regime": regime}
        if len(glist) > 1:
            cid = f"{_gid(g)}_{cid}"
            cdict["grid"] = repr(g)
        yield cid, cdict, step


def trsm_space(
    grid: Grid,
    dtype,
    L,
    bc_dims: Iterable[int] = (256, 512, 1024),
    leaves: Iterable[str] = ("invert", "solve"),
    modes: Iterable[str] = ("xla",),
):
    """bc x leaf x mode for the finished TRSM (the reference's diaginvert
    policies were forward-declared only, trsm/diaginvert/policy.h:8-9 —
    this is the sweep its tune.cpp never got).  The triangular operand L
    rides as a closure constant, so sweeps are bounded to moderate n
    (<= ~8192): at n >= 16384 a closed-over n x n array serializes into
    the program past the compile server's request limit (HTTP 413 — the
    trsm driver's jit-argument loop is the large-n path)."""
    from capital_tpu.models import trsm as trsm_mod

    prec = None if jnp.dtype(dtype).itemsize < 4 else "highest"
    for bc, leaf, mode in itertools.product(bc_dims, leaves, modes):
        cfg = trsm_mod.TrsmConfig(
            base_case_dim=bc, mode=mode, precision=prec, leaf=leaf
        )

        def step(b, cfg=cfg):
            return trsm_mod.solve(grid, L, b, "L", "L", cfg=cfg)

        yield (
            f"bc{bc}_{leaf}_{mode}",
            {"base_case_dim": bc, "leaf": leaf, "mode": mode},
            step,
        )


def latency_measure(calls: int = 32, warmup: int = 3) -> Callable:
    """Measurement protocol for `run_sweep(measure=...)`: per-call wall
    time (harness.latency_samples — one dispatch + one device round-trip
    per sample, the cost a served request actually pays, NOT timed_loop's
    in-jit amortized body), sorted on **p99**.  Returns
    ``(p99_seconds, {"wall_ms": {"p50": .., "p95": .., "p99": ..}})`` so
    the sweep crowns the config with the best tail latency and the full
    percentile split rides the checkpoint and the ledger."""

    def measure(step, operand):
        fn = jax.jit(step)
        samples = harness.latency_samples(
            lambda: fn(operand), calls=calls, warmup=warmup
        )
        pcts = harness.percentiles(samples)
        return pcts["p99"], {
            "wall_ms": {k: round(v * 1e3, 4) for k, v in pcts.items()}
        }

    return measure


def batched_small_space(
    op: str,
    n: int,
    B_rhs,
    dtype,
    impls: Iterable[str] = ("vmap", "pallas", "pallas_split"),
    blocks: Iterable[int] = (0,),
):
    """impl x block for the batched small-N kernel layer (ops/
    batched_small): the serve dispatch alternatives measured against each
    other — vmap-over-LAPACK (the pure-XLA fallback, no block axis),
    the fused batched-grid kernel, and the unfused two-launch split
    (posv only; the A/B that isolates the fusion win from the
    batched-grid win).  `B_rhs` is the bucket's RHS batch, closed over so
    the swept operand stays the single A array run_sweep's manifest and
    checkpoint key expect."""
    from capital_tpu.ops import batched_small
    from capital_tpu.serve import api

    prec = None if jnp.dtype(dtype).itemsize < 4 else "highest"
    for impl in impls:
        if impl == "vmap":
            fn = api.batched(op, prec, "vmap")

            def step(a, fn=fn):
                return fn(a, B_rhs)

            yield "vmap", {"impl": "vmap"}, step
            continue
        if impl == "pallas_split" and op == "lstsq":
            continue  # lstsq has no split form (api.batched routes it fused)
        for blk in blocks:
            blk_eff = blk or batched_small.pick_block(n)
            if impl == "pallas":
                if op == "posv":
                    def step(a, blk=blk):
                        return batched_small.posv(
                            a, B_rhs, block=blk, precision=prec
                        )
                else:
                    def step(a, blk=blk):
                        return batched_small.lstsq(
                            a, B_rhs, block=blk, precision=prec
                        )
            else:
                def step(a, blk=blk):
                    R, info = batched_small.potrf(
                        a, uplo="U", block=blk, precision=prec
                    )
                    X = batched_small.potrs(
                        R, B_rhs, uplo="U", block=blk, precision=prec
                    )
                    return X, info

            yield (
                f"{impl}_b{blk_eff}",
                {"impl": impl, "block": blk_eff},
                step,
            )


def tune_small(
    grid: Grid,
    op: str,
    n: int,
    batch: int = 8,
    nrhs: int = 1,
    dtype=jnp.float32,
    out_dir: str = "autotune_out",
    occupancy: float = 1.0,
    rows: int | None = None,
    calls: int = 32,
    warmup: int = 3,
    checkpoint: bool = False,
    ledger: str | None = None,
    **space,
) -> list[SweepResult]:
    """Latency-mode sweep for ONE serve bucket: impl x block measured by
    per-call p99 wall time (latency_measure) at a FIXED batch occupancy —
    the serving objective, not peak TFLOP/s.  The operand batch carries
    ``round(occupancy * batch)`` real problems and identity fill for the
    tail, exactly the mixture a `serve` bucket flushes at that occupancy
    (batching.assemble's fill problems), so the crowned config is tuned
    for the batches production actually runs.  Results/checkpoints/ledger
    all ride run_sweep: resumable per-config, per-bucket p99 wall_ms in
    every autotune:small_<op> measured block."""
    import numpy as np

    if op not in ("posv", "lstsq"):
        raise ValueError(
            f"tune_small: op must be 'posv' or 'lstsq', got {op!r}"
        )
    if not 0.0 < occupancy <= 1.0:
        raise ValueError(f"tune_small: occupancy {occupancy} outside (0, 1]")
    real = max(1, round(occupancy * batch))
    rng = np.random.default_rng(2)
    if op == "posv":
        m_rows = n
        X = rng.standard_normal((batch, n, n))
        A = X @ X.transpose(0, 2, 1) / n + 3.0 * np.eye(n)
        A[real:] = np.eye(n)
    else:
        m_rows = rows if rows is not None else 4 * n
        A = rng.standard_normal((batch, m_rows, n))
        A[real:] = np.eye(m_rows, n)
    B = rng.standard_normal((batch, m_rows, nrhs))
    B[real:] = 0.0  # fill problems: zero RHS -> exact-zero solutions
    A = jax.block_until_ready(jnp.asarray(A, dtype))
    B = jax.block_until_ready(jnp.asarray(B, dtype))
    return run_sweep(
        f"small_{op}",
        batched_small_space(op, n, B, dtype, **space),
        A,
        out_dir,
        dtype=dtype,
        checkpoint=checkpoint,
        key_extra={
            **_grid_key(grid), "op": op, "n": n, "batch": batch,
            "nrhs": nrhs, "occupancy": occupancy, "calls": calls,
        },
        ledger=ledger,
        measure=latency_measure(calls=calls, warmup=warmup),
    )


def blocktri_space(
    nblocks: int,
    b: int,
    B_rhs,
    dtype,
    impls: Iterable[str] = ("xla", "pallas"),
    blocks: Iterable[int] = (0,),
    segs: Iterable[int] = (1, 4, 8),
    partitions: Iterable[int] = (0,),
):
    """impl x block-unroll x scan-segment-length for the block-tridiagonal
    chain (models/blocktri): the knobs that shape the scan-of-Pallas-blocks
    executable — in-kernel column unroll (`block`, the batched_small knob)
    and chain blocks per pallas_call (`seg`, launch amortization vs the
    VMEM step envelope).  The xla scan ignores both knobs (it scans one
    block per step through lax.linalg), so that impl contributes ONE
    baseline config rather than a degenerate axis product.  The
    'partitioned' impl (the round-13 Spike driver) sweeps the partitions
    x block-unroll plane instead: `partitions` snaps through
    resolve_partitions (so 0 is the √nblocks default and infeasible
    requests collapse — duplicates are deduped rather than re-measured),
    and `seg` is NOT an axis there (the interior fold already amortizes
    launches across batch·P problems; its inner scans keep the resolved
    default).  `B_rhs` rides as a closure so the swept operand stays the
    single packed A array (batch, 2, nblocks, b, b) — A[:, 0] the
    diagonal blocks, A[:, 1] the couplings, the serve bucket packing."""
    from capital_tpu.models import blocktri
    from capital_tpu.ops import batched_small

    prec = None if jnp.dtype(dtype).itemsize < 4 else "highest"
    for impl in impls:
        if impl not in ("xla", "pallas", "partitioned"):
            raise ValueError(
                "blocktri_space: impl must be 'xla', 'pallas' or "
                f"'partitioned', got {impl!r}"
            )
        if impl == "xla":
            def step(a):
                return blocktri.posv(a[:, 0], a[:, 1], B_rhs,
                                     precision=prec, impl="xla")

            yield "xla", {"impl": "xla"}, step
            continue
        if impl == "partitioned":
            seen_p = set()
            for part in partitions:
                p_eff = blocktri.resolve_partitions(nblocks, part)
                for blk in blocks:
                    blk_eff = blk or batched_small.pick_block(b)
                    if (p_eff, blk_eff) in seen_p:
                        continue
                    seen_p.add((p_eff, blk_eff))

                    def step(a, blk=blk, part=p_eff):
                        return blocktri.posv(
                            a[:, 0], a[:, 1], B_rhs, block=blk,
                            precision=prec, impl="partitioned",
                            partitions=part)

                    yield (
                        f"part_p{p_eff}_b{blk_eff}",
                        {"impl": "partitioned", "partitions": p_eff,
                         "block": blk_eff},
                        step,
                    )
            continue
        for blk in blocks:
            blk_eff = blk or batched_small.pick_block(b)
            for seg in segs:
                seg_eff = blocktri.resolve_seg(nblocks, seg)

                def step(a, blk=blk, seg=seg_eff):
                    return blocktri.posv(
                        a[:, 0], a[:, 1], B_rhs, block=blk, seg=seg,
                        precision=prec, impl="pallas")

                yield (
                    f"pallas_b{blk_eff}_s{seg_eff}",
                    {"impl": "pallas", "block": blk_eff, "seg": seg_eff},
                    step,
                )


def tune_blocktri(
    grid: Grid,
    nblocks: int,
    b: int,
    batch: int = 8,
    nrhs: int = 1,
    dtype=jnp.float32,
    out_dir: str = "autotune_out",
    occupancy: float = 1.0,
    calls: int = 32,
    warmup: int = 3,
    checkpoint: bool = False,
    ledger: str | None = None,
    **space,
) -> list[SweepResult]:
    """Latency-mode sweep for ONE posv_blocktri serve bucket: impl x
    block-unroll x scan-segment-length measured by per-call p99 wall time
    (latency_measure) at fixed batch occupancy — the same serving
    objective as tune_small, on the chain op.  The operand batch carries
    ``round(occupancy * batch)`` real SPD chains and identity-chain fill
    (identity diagonal blocks, zero couplings, zero RHS — exactly
    batching.fill_problem) for the tail."""
    import numpy as np

    if not 0.0 < occupancy <= 1.0:
        raise ValueError(f"tune_blocktri: occupancy {occupancy} outside (0, 1]")
    real = max(1, round(occupancy * batch))
    rng = np.random.default_rng(4)
    G = rng.standard_normal((batch, nblocks, b, b))
    D = G @ G.transpose(0, 1, 3, 2) / b + 3.0 * np.eye(b)
    C = 0.3 / np.sqrt(b) * rng.standard_normal((batch, nblocks, b, b))
    C[:, 0] = 0.0
    D[real:] = np.eye(b)
    C[real:] = 0.0
    B = rng.standard_normal((batch, nblocks, b, nrhs))
    B[real:] = 0.0  # fill chains: zero RHS -> exact-zero solutions
    A = jax.block_until_ready(jnp.asarray(np.stack([D, C], axis=1), dtype))
    B = jax.block_until_ready(jnp.asarray(B, dtype))
    return run_sweep(
        "blocktri",
        blocktri_space(nblocks, b, B, dtype, **space),
        A,
        out_dir,
        dtype=dtype,
        checkpoint=checkpoint,
        key_extra={
            **_grid_key(grid), "op": "posv_blocktri", "nblocks": nblocks,
            "b": b, "batch": batch, "nrhs": nrhs, "occupancy": occupancy,
            "calls": calls,
        },
        ledger=ledger,
        measure=latency_measure(calls=calls, warmup=warmup),
    )


def arrowhead_space(
    nblocks: int,
    b: int,
    tail,
    dtype,
    impls: Iterable[str] = ("xla", "pallas"),
    blocks: Iterable[int] = (0,),
    segs: Iterable[int] = (1, 4, 8),
    partitions: Iterable[int] = (0,),
):
    """impl x border-column blocking x scan-segment-length for the
    block-arrowhead solve (models/arrowhead): the chain knobs of
    blocktri_space applied to the WIDENED chain solve that carries the
    border columns alongside the RHS.  `block` is the border-blocking
    knob — the batched_small in-kernel column unroll tiles the s + nrhs
    solve columns, so it decides how the border block-row is chunked
    through the chain sweep; `seg` amortizes pallas_call launches exactly
    as in blocktri_space; the xla impl contributes one baseline config
    and 'partitioned' sweeps the partitions x block plane (seg is not an
    axis there).  `tail` = (F, S, B_rhs, Bs) rides as a closure so the
    swept operand stays the single packed chain array
    (batch, 2, nblocks, b, b) — the serve bucket packing of the chain
    half, like blocktri_space."""
    from capital_tpu.models import arrowhead, blocktri
    from capital_tpu.ops import batched_small

    F, S, B_rhs, Bs = tail
    prec = None if jnp.dtype(dtype).itemsize < 4 else "highest"
    for impl in impls:
        if impl not in ("xla", "pallas", "partitioned"):
            raise ValueError(
                "arrowhead_space: impl must be 'xla', 'pallas' or "
                f"'partitioned', got {impl!r}"
            )
        if impl == "xla":
            def step(a):
                return arrowhead.posv(a[:, 0], a[:, 1], F, S, B_rhs, Bs,
                                      precision=prec, impl="xla")

            yield "xla", {"impl": "xla"}, step
            continue
        if impl == "partitioned":
            seen_p = set()
            for part in partitions:
                p_eff = blocktri.resolve_partitions(nblocks, part)
                for blk in blocks:
                    blk_eff = blk or batched_small.pick_block(b)
                    if (p_eff, blk_eff) in seen_p:
                        continue
                    seen_p.add((p_eff, blk_eff))

                    def step(a, blk=blk, part=p_eff):
                        return arrowhead.posv(
                            a[:, 0], a[:, 1], F, S, B_rhs, Bs, block=blk,
                            precision=prec, impl="partitioned",
                            partitions=part)

                    yield (
                        f"part_p{p_eff}_b{blk_eff}",
                        {"impl": "partitioned", "partitions": p_eff,
                         "block": blk_eff},
                        step,
                    )
            continue
        for blk in blocks:
            blk_eff = blk or batched_small.pick_block(b)
            for seg in segs:
                seg_eff = blocktri.resolve_seg(nblocks, seg)

                def step(a, blk=blk, seg=seg_eff):
                    return arrowhead.posv(
                        a[:, 0], a[:, 1], F, S, B_rhs, Bs, block=blk,
                        seg=seg, precision=prec, impl="pallas")

                yield (
                    f"pallas_b{blk_eff}_s{seg_eff}",
                    {"impl": "pallas", "block": blk_eff, "seg": seg_eff},
                    step,
                )


def tune_arrowhead(
    grid: Grid,
    nblocks: int,
    b: int,
    border: int = 8,
    batch: int = 8,
    nrhs: int = 1,
    dtype=jnp.float32,
    out_dir: str = "autotune_out",
    occupancy: float = 1.0,
    calls: int = 32,
    warmup: int = 3,
    checkpoint: bool = False,
    ledger: str | None = None,
    **space,
) -> list[SweepResult]:
    """Latency-mode sweep for ONE posv_arrowhead serve bucket: impl x
    border blocking x scan-segment-length measured by per-call p99 wall
    time at fixed batch occupancy — tune_blocktri's objective, on the
    bordered op.  The operand batch carries ``round(occupancy * batch)``
    real arrowheads and identity fill for the tail (identity chain +
    identity corner + zero border/RHS — exactly batching.fill_problem)."""
    import numpy as np

    if not 0.0 < occupancy <= 1.0:
        raise ValueError(
            f"tune_arrowhead: occupancy {occupancy} outside (0, 1]")
    real = max(1, round(occupancy * batch))
    rng = np.random.default_rng(4)
    G = rng.standard_normal((batch, nblocks, b, b))
    D = G @ G.transpose(0, 1, 3, 2) / b + 3.0 * np.eye(b)
    C = 0.3 / np.sqrt(b) * rng.standard_normal((batch, nblocks, b, b))
    C[:, 0] = 0.0
    # border coupling shrinks with chain length: it touches every chain
    # block, so its Schur correction grows with nblocks·b and a fixed
    # scale would push the corner indefinite at long chains
    F = 0.3 / np.sqrt(nblocks * b) * rng.standard_normal(
        (batch, nblocks, border, b))
    S0 = rng.standard_normal((batch, border, border))
    S = S0 @ S0.transpose(0, 2, 1) / border + 5.0 * np.eye(border)
    B = rng.standard_normal((batch, nblocks, b, nrhs))
    Bs = rng.standard_normal((batch, border, nrhs))
    D[real:] = np.eye(b)
    C[real:] = 0.0
    F[real:] = 0.0
    S[real:] = np.eye(border)
    B[real:] = 0.0  # fill problems: zero RHS -> exact-zero solutions
    Bs[real:] = 0.0
    A = jax.block_until_ready(jnp.asarray(np.stack([D, C], axis=1), dtype))
    tail = tuple(
        jax.block_until_ready(jnp.asarray(t, dtype)) for t in (F, S, B, Bs)
    )
    return run_sweep(
        "arrowhead",
        arrowhead_space(nblocks, b, tail, dtype, **space),
        A,
        out_dir,
        dtype=dtype,
        checkpoint=checkpoint,
        key_extra={
            **_grid_key(grid), "op": "posv_arrowhead", "nblocks": nblocks,
            "b": b, "border": border, "batch": batch, "nrhs": nrhs,
            "occupancy": occupancy, "calls": calls,
        },
        ledger=ledger,
        measure=latency_measure(calls=calls, warmup=warmup),
    )


def update_small_space(
    n: int,
    k: int,
    V,
    dtype,
    op: str = "chol_update",
    impls: Iterable[str] = ("xla", "pallas"),
    blocks: Iterable[int] = (0,),
    panels: Iterable[int] = (0,),
):
    """impl x block-unroll (pallas) / panel-width (xla) for the rank-k
    factor-maintenance kernels (ops/update_small): the serve dispatch
    alternatives for the chol_update / chol_downdate buckets — the masked
    hyperbolic-rotation pallas sweep (knob: in-kernel column unroll
    `block`, the batched_small convention) against the blocked
    J-orthogonal XLA panel scan (knob: `panel`, rows factored per
    J-Cholesky step).  Each impl sweeps ITS OWN knob so the product stays
    non-degenerate (the other impl ignores it).  `V` rides as a closure
    so the swept operand stays the single resident-factor batch R the
    run_sweep manifest and checkpoint key expect."""
    from capital_tpu.ops import batched_small, update_small

    if op not in ("chol_update", "chol_downdate"):
        raise ValueError(
            f"update_small_space: op must be 'chol_update' or "
            f"'chol_downdate', got {op!r}"
        )
    fn = getattr(update_small, op)
    prec = None if jnp.dtype(dtype).itemsize < 4 else "highest"
    for impl in impls:
        if impl not in ("xla", "pallas"):
            raise ValueError(
                f"update_small_space: impl must be 'xla' or 'pallas', "
                f"got {impl!r}"
            )
        if impl == "xla":
            for pan in panels:
                pan_eff = update_small.resolve_panel(n, k, pan)

                def step(r, pan=pan):
                    return fn(r, V, panel=pan, precision=prec, impl="xla")

                yield (
                    f"xla_p{pan_eff}",
                    {"impl": "xla", "panel": pan_eff},
                    step,
                )
            continue
        for blk in blocks:
            blk_eff = blk or batched_small.pick_block(n)

            def step(r, blk=blk):
                return fn(r, V, block=blk, precision=prec, impl="pallas")

            yield (
                f"pallas_b{blk_eff}",
                {"impl": "pallas", "block": blk_eff},
                step,
            )


def tune_update(
    grid: Grid,
    n: int,
    k: int,
    batch: int = 8,
    op: str = "chol_update",
    dtype=jnp.float32,
    out_dir: str = "autotune_out",
    occupancy: float = 1.0,
    calls: int = 32,
    warmup: int = 3,
    checkpoint: bool = False,
    ledger: str | None = None,
    **space,
) -> list[SweepResult]:
    """Latency-mode sweep for ONE chol_update / chol_downdate serve
    bucket: impl x block-unroll/panel measured by per-call p99 wall time
    (latency_measure) at fixed batch occupancy — the serving objective
    (a residency update sits on a request's critical path), not peak
    TFLOP/s.  The operand batch carries ``round(occupancy * batch)``
    real resident factors and identity fill for the tail (identity R
    with a zero V panel — exactly batching.pad_operands' fixed-point pad,
    so fill rotations are t = 0 no-ops); a downdate sweep downdates a
    panel the real factors provably contain (V scaled well inside the
    smallest eigenvalue), so no swept config ever measures the breakdown
    path."""
    import numpy as np

    if not 0.0 < occupancy <= 1.0:
        raise ValueError(f"tune_update: occupancy {occupancy} outside (0, 1]")
    real = max(1, round(occupancy * batch))
    rng = np.random.default_rng(7)
    X = rng.standard_normal((batch, n, n))
    A = X @ X.transpose(0, 2, 1) / n + 3.0 * np.eye(n)
    R = np.linalg.cholesky(A).transpose(0, 2, 1)
    R[real:] = np.eye(n)
    # 0.1/sqrt(n) scaling keeps ||VVᵀ|| well under the 3I shift: the
    # downdate stays deep inside SPD territory for every real problem
    V = 0.1 / np.sqrt(n) * rng.standard_normal((batch, n, k))
    V[real:] = 0.0  # fill factors: zero panel -> t = 0 no-op rotations
    R = jax.block_until_ready(jnp.asarray(R, dtype))
    V = jax.block_until_ready(jnp.asarray(V, dtype))
    return run_sweep(
        "update",
        update_small_space(n, k, V, dtype, op=op, **space),
        R,
        out_dir,
        dtype=dtype,
        checkpoint=checkpoint,
        key_extra={
            **_grid_key(grid), "op": op, "n": n, "k": k, "batch": batch,
            "occupancy": occupancy, "calls": calls,
        },
        ledger=ledger,
        measure=latency_measure(calls=calls, warmup=warmup),
    )


def tune_trsm(
    grid: Grid,
    n: int,
    nrhs: int,
    dtype=jnp.bfloat16,
    out_dir: str = "autotune_out",
    checkpoint: bool = False,
    ledger: str | None = None,
    **space,
) -> list[SweepResult]:
    from capital_tpu.bench.drivers import _tri_operand

    if n > 8192:
        raise ValueError(
            f"tune_trsm: n={n} exceeds the sweep bound (8192): the closed-"
            "over n x n operand serializes into every config's program and "
            "breaks the compile server at n >= 16384 (HTTP 413) — use the "
            "trsm bench driver's jit-argument loop for large-n measurement"
        )
    L = _tri_operand(n, dtype)
    B = jax.block_until_ready(
        jax.random.normal(jax.random.key(1), (n, nrhs), dtype=dtype)
    )
    return run_sweep(
        "trsm", trsm_space(grid, dtype, L, **space), B, out_dir, dtype=dtype,
        checkpoint=checkpoint, key_extra={**_grid_key(grid), "n": n},
        ledger=ledger,
    )


def tune_cholinv(
    grid: Grid,
    n: int,
    dtype=jnp.bfloat16,
    out_dir: str = "autotune_out",
    prefilter_top_k: int = 0,
    checkpoint: bool = False,
    ledger: str | None = None,
    **space,
) -> list[SweepResult]:
    """Sweep cholinv configs.  With prefilter_top_k > 0, the native
    alpha-beta planner (native.cholinv_predict) ranks the (policy, bc) space
    first and only the top-k model candidates are measured — the predictive
    upgrade over the reference's measure-everything sweep (tune.cpp:239-253)."""
    A = _spd(n, dtype)
    configs = list(cholinv_space(grid, dtype, **space))
    if prefilter_top_k and prefilter_top_k < len(configs):
        from capital_tpu import native

        if len({c[1].get("layout", 0) for c in configs}) > 1:
            # the alpha-beta model is layout-insensitive (device ordering
            # is a locality knob): layout variants TIE in the ranking and
            # a top-k cut keeps whichever was generated first — the
            # dropped layouts go unmeasured
            print(
                "# autotune cholinv: --top-k with a layout axis prunes on "
                "modeled cost only (layouts tie in the model)"
            )
        spec = tracing.device_spec()
        peak = spec.peak_tflops(dtype) * 1e12 * 0.6
        preds = []
        for cid, cdict, step in configs:
            # each config is modeled with ITS OWN topology (grid axis rows
            # carry grid_shape/num_chunks in the config dict) — round 3
            # disabled the prefilter under a grid axis; with chunks in the
            # alpha term the model now ranks those rows too.  Layout
            # variants tie (the model is layout-insensitive), so a top-k
            # cut across a layout axis prunes on modeled cost only.
            shape = tuple(cdict.get("grid_shape", (grid.dx, grid.dy, grid.c)))
            q = cdict.get("num_chunks", grid.num_chunks)
            out, _ = native.cholinv_predict(
                n, shape,
                [cdict["base_case_dim"]],
                [BaseCasePolicy[cdict["policy"]]],
                peak_flops=peak,
                itemsize=jnp.dtype(dtype).itemsize,
                split=cdict["split"],
                num_chunks=q,
                balance=cdict.get("balance", "block"),
            )
            preds.append(float(out[0, 0]))
        order = sorted(range(len(configs)), key=preds.__getitem__)
        kept = [configs[i] for i in order[:prefilter_top_k]]
        print(
            f"# autotune cholinv: planner kept {len(kept)}/{len(configs)} configs"
        )
        configs = kept
    return run_sweep(
        "cholinv", configs, A, out_dir, dtype=dtype, checkpoint=checkpoint,
        key_extra=_grid_key(grid), ledger=ledger,
    )


def tune_cacqr(
    grid: Grid,
    m: int,
    n: int,
    dtype=jnp.bfloat16,
    out_dir: str = "autotune_out",
    checkpoint: bool = False,
    ledger: str | None = None,
    **space,
) -> list[SweepResult]:
    A = jax.block_until_ready(
        jax.random.normal(jax.random.key(0), (m, n), dtype=dtype)
    )
    return run_sweep(
        "cacqr", cacqr_space(grid, dtype, **space), A, out_dir, dtype=dtype,
        checkpoint=checkpoint, key_extra=_grid_key(grid), ledger=ledger,
    )
