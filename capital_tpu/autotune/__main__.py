"""CLI: python -m capital_tpu.autotune
{cholinv,cacqr,trsm,small,blocktri,arrowhead,update} [flags]."""

from __future__ import annotations

import argparse

import jax


def main(argv=None) -> None:
    p = argparse.ArgumentParser(prog="capital_tpu.autotune")
    p.add_argument("alg", choices=["cholinv", "cacqr", "trsm", "small",
                                   "blocktri", "arrowhead", "update"])
    p.add_argument("--n", type=int, default=4096)
    p.add_argument("--m", type=int, default=65536)
    p.add_argument("--dtype", default="bfloat16")
    p.add_argument("--out", default="autotune_out")
    p.add_argument("--bc", type=int, nargs="+", default=None)
    p.add_argument(
        "--modes", nargs="+", default=None,
        choices=["xla", "explicit", "pallas"],
        help="cholinv/trsm: SUMMA modes to sweep (the winning flagship "
        "config is pallas on one TPU for cholinv, xla for trsm — a sweep "
        "that cannot reach it is useless)",
    )
    p.add_argument("--splits", type=int, nargs="+", default=None)
    p.add_argument(
        "--policies", nargs="+", default=None,
        help="cholinv: BaseCasePolicy names (e.g. REPLICATE_COMM_COMP)",
    )
    p.add_argument(
        "--tail-depths", type=int, nargs="+", default=None,
        help="cholinv: tail_fuse_depth values to sweep (fused recursion "
        "tail, CholinvConfig.tail_fuse_depth; 0 = unfused)",
    )
    p.add_argument(
        "--top-k", type=int, default=0,
        help="cholinv: measure only the native planner's top-k model candidates",
    )
    p.add_argument(
        "--resume", action="store_true",
        help="checkpoint per-config results under --out and skip configs a "
        "previous (preempted) sweep of the same problem already measured",
    )
    p.add_argument(
        "--grids", nargs="+", default=None,
        help="grid-shape axis (the reference rep-factor loop, "
        "bench/qr/cacqr.cpp:8-25): 'auto' enumerates feasible d x d x c "
        "shapes over the devices (+ flat for cacqr), or explicit "
        "DXxDYxC tokens like 2x2x1 2x2x2 flat",
    )
    p.add_argument(
        "--layouts", type=int, nargs="+", default=None,
        help="device-ordering layouts crossed with each --grids token "
        "(reference topology.h:77-123)",
    )
    p.add_argument(
        "--chunks", type=int, nargs="+", default=None,
        help="num_chunks values crossed with each --grids token (the "
        "reference Ibcast/Iallreduce pipeline; the planner prices q since "
        "round 4)",
    )
    p.add_argument(
        "--op", default="posv", choices=["posv", "lstsq"],
        help="small: which serve op's bucket executables to sweep",
    )
    p.add_argument(
        "--batch", type=int, default=8,
        help="small: bucket batch capacity (ServeConfig.max_batch)",
    )
    p.add_argument(
        "--nrhs", type=int, default=1,
        help="small: RHS columns per problem",
    )
    p.add_argument(
        "--buckets", type=int, nargs="+", default=None,
        help="small: bucket n ladder to sweep, one latency sweep per "
        "bucket (default: 16 32 64 128)",
    )
    p.add_argument(
        "--occupancy", type=float, default=1.0,
        help="small: fixed batch occupancy the latency is measured at "
        "(real problems / capacity; the tail is identity fill, exactly a "
        "serve flush at that occupancy)",
    )
    p.add_argument(
        "--impls", nargs="+", default=None,
        choices=["vmap", "pallas", "pallas_split", "xla", "partitioned"],
        help="small: implementation axis (default all three; 'xla' is the "
        "blocktri baseline impl and 'partitioned' the blocktri Spike "
        "driver, both invalid for small)",
    )
    p.add_argument(
        "--blocks", type=int, nargs="+", default=None,
        help="small/blocktri: column-block unroll axis for the pallas "
        "impls (0 = pick_block default)",
    )
    p.add_argument(
        "--rank", type=int, default=16,
        help="update: rank k of the swept chol_update/chol_downdate panel",
    )
    p.add_argument(
        "--update-op", default="chol_update",
        choices=["chol_update", "chol_downdate"],
        help="update: which maintenance op's bucket executables to sweep",
    )
    p.add_argument(
        "--panels", type=int, nargs="+", default=None,
        help="update: panel-width axis for the xla J-orthogonal impl "
        "(resolve_panel snaps each to a divisor of --n; 0 = auto)",
    )
    p.add_argument(
        "--nblocks", type=int, default=8,
        help="blocktri: chain length (diagonal blocks per problem)",
    )
    p.add_argument(
        "--block", type=int, default=32,
        help="blocktri: block size b (each diagonal block is b x b)",
    )
    p.add_argument(
        "--segs", type=int, nargs="+", default=None,
        help="blocktri: scan-segment-length axis — chain blocks per "
        "pallas_call (resolve_seg snaps each to a divisor of --nblocks; "
        "default 1 4 8)",
    )
    p.add_argument(
        "--partitions", type=int, nargs="+", default=None,
        help="blocktri: partition-count axis for --impls partitioned "
        "(resolve_partitions snaps each to a feasible divisor of "
        "--nblocks; 0 = the √nblocks default; duplicates after snapping "
        "are deduped)",
    )
    p.add_argument(
        "--border", type=int, default=8,
        help="arrowhead: border rank s (rows of the coupling block-row)",
    )
    p.add_argument(
        "--calls", type=int, default=32,
        help="small: per-config latency samples (harness.latency_samples)",
    )
    p.add_argument("--devices", type=int, default=0)
    p.add_argument("--platform", default=None)
    p.add_argument("--host-devices", type=int, default=0)
    p.add_argument(
        "--ledger", default=None,
        help="append one obs ledger record per swept config to this JSONL "
        "file (query with python -m capital_tpu.obs diff)",
    )
    args = p.parse_args(argv)

    if args.host_devices:
        import os

        flags = [
            f
            for f in os.environ.get("XLA_FLAGS", "").split()
            if "xla_force_host_platform_device_count" not in f
        ]
        flags.append(f"--xla_force_host_platform_device_count={args.host_devices}")
        os.environ["XLA_FLAGS"] = " ".join(flags)
    if args.platform:
        jax.config.update("jax_platforms", args.platform)

    import jax.numpy as jnp

    from capital_tpu.autotune import sweep
    from capital_tpu.parallel.topology import Grid

    dev = jax.devices()
    if args.devices:
        dev = dev[: args.devices]
    dtype = jnp.dtype(args.dtype)
    space = {"bc_dims": tuple(args.bc)} if args.bc else {}
    if args.grids:
        layouts = args.layouts or [0]
        chunks = args.chunks or [0]
        if args.grids == ["auto"]:
            base = sweep.grid_space(dev, include_flat=(args.alg == "cacqr"))
            shapes = [
                None if g.dy == 1 and g.c == 1 and g.dx == len(dev)
                else (g.dx, g.dy, g.c)
                for g in base
            ]
        else:
            shapes = []
            for tok in args.grids:
                if tok == "flat":
                    shapes.append(None)
                    continue
                shapes.append(tuple(int(x) for x in tok.split("x")))
        gs = []
        for shp in shapes:
            if shp is None:
                gs.append(Grid.flat(devices=dev))
                continue
            dx, dy, c = shp
            for lo in layouts:
                for q in chunks:
                    gs.append(
                        Grid.rect(
                            dx, dy, c, devices=dev[: dx * dy * c],
                            layout=lo, num_chunks=q,
                        )
                    )
        space["grids"] = gs
    if args.alg == "cholinv":
        # these knobs exist only in the cholinv space (cacqr sweeps
        # variant x bc x regime)
        if args.modes:
            space["modes"] = tuple(args.modes)
        if args.splits:
            space["splits"] = tuple(args.splits)
        if args.policies:
            from capital_tpu.utils.config import BaseCasePolicy

            space["policies"] = tuple(BaseCasePolicy[p] for p in args.policies)
        if args.tail_depths:
            space["tail_depths"] = tuple(args.tail_depths)
        # with a grid axis the base grid is just a placeholder (every config
        # carries its own); devices counts like 8 have no square c=1 face
        grid = (
            space["grids"][0]
            if "grids" in space
            else Grid.square(c=1, devices=dev)
        )
        res = sweep.tune_cholinv(
            grid, args.n, dtype, args.out, prefilter_top_k=args.top_k,
            checkpoint=args.resume, ledger=args.ledger, **space,
        )
    elif args.alg == "trsm":
        # reject every non-axis rather than silently ignoring it (ADVICE r4:
        # a sweep with --splits would report results that don't reflect it)
        for flag, given in (
            ("--grids", "grids" in space),
            ("--splits", bool(args.splits)),
            ("--policies", bool(args.policies)),
            ("--tail-depths", bool(args.tail_depths)),
            ("--top-k", args.top_k != 0),
            ("--layouts", bool(args.layouts)),
            ("--chunks", bool(args.chunks)),
        ):
            if given:
                p.error(f"{flag} is not a trsm sweep axis (bc x leaf x mode only)")
        if args.modes:
            space["modes"] = tuple(args.modes)
        grid = Grid.square(c=1, devices=dev)
        # the driver's nrhs convention (drivers.py trsm): --m is honored
        # whenever it is not the untouched 65536 default, else nrhs = n
        nrhs = args.m if args.m != 65536 else args.n
        res = sweep.tune_trsm(
            grid, args.n, nrhs, dtype, args.out,
            checkpoint=args.resume, ledger=args.ledger, **space,
        )
    elif args.alg == "small":
        # latency-mode sweep, one per bucket: the objective is per-bucket
        # p99 wall_ms at fixed occupancy, so each bucket n gets its own
        # run_sweep (own checkpoint, own best.json overwritten per bucket
        # is avoided by nesting out dirs per bucket)
        for flag, given in (
            ("--grids", "grids" in space),
            ("--splits", bool(args.splits)),
            ("--policies", bool(args.policies)),
            ("--tail-depths", bool(args.tail_depths)),
            ("--top-k", args.top_k != 0),
            ("--modes", bool(args.modes)),
            ("--bc", bool(args.bc)),
        ):
            if given:
                p.error(
                    f"{flag} is not a small sweep axis (impl x block per "
                    "bucket only)"
                )
        space = {}
        if args.impls:
            if any(i in ("xla", "partitioned") for i in args.impls):
                p.error("--impls xla/partitioned are blocktri impls, not "
                        "small axes (vmap/pallas/pallas_split)")
            space["impls"] = tuple(args.impls)
        if args.blocks:
            space["blocks"] = tuple(args.blocks)
        grid = Grid.square(c=1, devices=dev[:1])
        buckets = args.buckets or [16, 32, 64, 128]
        import os

        res = []
        for n in buckets:
            out_n = os.path.join(args.out, f"n{n}")
            rs = sweep.tune_small(
                grid, args.op, n, batch=args.batch, nrhs=args.nrhs,
                dtype=dtype, out_dir=out_n, occupancy=args.occupancy,
                calls=args.calls, checkpoint=args.resume,
                ledger=args.ledger, **space,
            )
            if not rs:
                # every config's measurement fell below the noise floor
                # (MeasurementUnresolved): skip the bucket, keep sweeping
                print(f"bucket n={n}: no resolved measurements")
                continue
            b = rs[0]
            p99 = (b.extra or {}).get("wall_ms", {}).get("p99")
            print(
                f"bucket n={n}: best {b.config_id}  p99 {p99} ms  "
                f"-> {out_n}/"
            )
            res.extend(rs)
        res.sort(key=lambda r: r.seconds)
    elif args.alg == "blocktri":
        # latency-mode sweep for ONE posv_blocktri bucket: impl x
        # block-unroll x scan-segment-length at fixed occupancy
        for flag, given in (
            ("--grids", "grids" in space),
            ("--splits", bool(args.splits)),
            ("--policies", bool(args.policies)),
            ("--tail-depths", bool(args.tail_depths)),
            ("--top-k", args.top_k != 0),
            ("--modes", bool(args.modes)),
            ("--bc", bool(args.bc)),
            ("--buckets", bool(args.buckets)),
        ):
            if given:
                p.error(
                    f"{flag} is not a blocktri sweep axis (impl x block x "
                    "seg only)"
                )
        space = {}
        if args.impls:
            if any(i in ("vmap", "pallas_split") for i in args.impls):
                p.error("blocktri impls are 'xla', 'pallas' and "
                        "'partitioned' only")
            space["impls"] = tuple(args.impls)
        if args.blocks:
            space["blocks"] = tuple(args.blocks)
        if args.segs:
            space["segs"] = tuple(args.segs)
        if args.partitions:
            space["partitions"] = tuple(args.partitions)
        grid = Grid.square(c=1, devices=dev[:1])
        res = sweep.tune_blocktri(
            grid, args.nblocks, args.block, batch=args.batch,
            nrhs=args.nrhs, dtype=dtype, out_dir=args.out,
            occupancy=args.occupancy, calls=args.calls,
            checkpoint=args.resume, ledger=args.ledger, **space,
        )
    elif args.alg == "arrowhead":
        # latency-mode sweep for ONE posv_arrowhead bucket: impl x
        # border blocking x scan-segment-length at fixed occupancy
        for flag, given in (
            ("--grids", "grids" in space),
            ("--splits", bool(args.splits)),
            ("--policies", bool(args.policies)),
            ("--tail-depths", bool(args.tail_depths)),
            ("--top-k", args.top_k != 0),
            ("--modes", bool(args.modes)),
            ("--bc", bool(args.bc)),
            ("--buckets", bool(args.buckets)),
        ):
            if given:
                p.error(
                    f"{flag} is not an arrowhead sweep axis (impl x block "
                    "x seg only)"
                )
        space = {}
        if args.impls:
            if any(i in ("vmap", "pallas_split") for i in args.impls):
                p.error("arrowhead impls are 'xla', 'pallas' and "
                        "'partitioned' only")
            space["impls"] = tuple(args.impls)
        if args.blocks:
            space["blocks"] = tuple(args.blocks)
        if args.segs:
            space["segs"] = tuple(args.segs)
        if args.partitions:
            space["partitions"] = tuple(args.partitions)
        grid = Grid.square(c=1, devices=dev[:1])
        res = sweep.tune_arrowhead(
            grid, args.nblocks, args.block, border=args.border,
            batch=args.batch, nrhs=args.nrhs, dtype=dtype, out_dir=args.out,
            occupancy=args.occupancy, calls=args.calls,
            checkpoint=args.resume, ledger=args.ledger, **space,
        )
    elif args.alg == "update":
        # latency-mode sweep for ONE chol_update/chol_downdate bucket:
        # impl x block-unroll (pallas) / panel (xla) at fixed occupancy
        for flag, given in (
            ("--grids", "grids" in space),
            ("--splits", bool(args.splits)),
            ("--policies", bool(args.policies)),
            ("--tail-depths", bool(args.tail_depths)),
            ("--top-k", args.top_k != 0),
            ("--modes", bool(args.modes)),
            ("--bc", bool(args.bc)),
            ("--buckets", bool(args.buckets)),
            ("--segs", bool(args.segs)),
            ("--partitions", bool(args.partitions)),
        ):
            if given:
                p.error(
                    f"{flag} is not an update sweep axis (impl x "
                    "block/panel only)"
                )
        space = {}
        if args.impls:
            if any(i in ("vmap", "pallas_split") for i in args.impls):
                p.error("update impls are 'xla' and 'pallas' only")
            space["impls"] = tuple(args.impls)
        if args.blocks:
            space["blocks"] = tuple(args.blocks)
        if args.panels:
            space["panels"] = tuple(args.panels)
        grid = Grid.square(c=1, devices=dev[:1])
        res = sweep.tune_update(
            grid, args.n, args.rank, batch=args.batch, op=args.update_op,
            dtype=dtype, out_dir=args.out, occupancy=args.occupancy,
            calls=args.calls, checkpoint=args.resume, ledger=args.ledger,
            **space,
        )
    else:
        grid = Grid.flat(devices=dev)
        res = sweep.tune_cacqr(grid, args.m, args.n if args.n < args.m else 512,
                               dtype, args.out, checkpoint=args.resume,
                               ledger=args.ledger, **space)
    if not res:
        print(f"no resolved measurements -> {args.out}/")
        return
    best = res[0]
    print(f"best: {best.config_id}  {best.seconds * 1e3:.3f} ms  -> {args.out}/")


if __name__ == "__main__":
    main()
