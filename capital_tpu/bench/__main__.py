from capital_tpu.bench.drivers import main

main()
