"""Per-algorithm benchmark drivers — the reference's bench/ executables.

One driver per reference binary, same knob surface expressed as named flags
instead of positional argv (SURVEY §5.6: the reference's argv + template
policies collapse to runtime config here):

  cholinv     <- bench/cholesky/cholinv.cpp  (num_rows, rep_div, complete_inv,
                 split, bcMultiplier, layout, num_chunks, num_iter)
  cacqr       <- bench/qr/cacqr.cpp          (variant, m, n, rep factors, ...)
  summa_gemm  <- bench/matmult/summa_gemm.cpp (M, N, K, c, ...)
  rectri      <- bench/inverse/rectri.cpp
  newton      <- bench/inverse/newton.cpp    (bit-rotted upstream; functional here)
  spd_inverse <- the BASELINE.md "SPD inverse via Cholesky" config

Each run prints one JSON line (harness.report) and, with --validate, appends
the residual gates the reference keeps commented out in its drivers
(bench/cholesky/cholinv.cpp:61-66, bench/qr/cacqr.cpp:64-71) — enabled ones
fail the process on a blown tolerance, making every bench double as an
integration test.

Usage: python -m capital_tpu.bench <driver> [--n 4096 ...]
"""

from __future__ import annotations

import argparse
import logging
import sys

import jax
import jax.numpy as jnp

from capital_tpu.bench import harness
from capital_tpu.models import cholesky, inverse, qr
from capital_tpu.parallel import summa
from capital_tpu.parallel.topology import Grid
from capital_tpu.robust.config import RobustConfig
from capital_tpu.utils import residual

_log = logging.getLogger(__name__)


def _tolerance(dtype) -> float:
    """Residual gate by dtype: the reference's f64/MPI runs sit at ~1e-14
    (SURVEY §4); scaled to the working precision here."""
    return {2: 5e-2, 4: 5e-5, 8: 1e-13}[jnp.dtype(dtype).itemsize]


#: Residual gate values of the current driver invocation, keyed by gate
#: name — snapshotted and cleared by _ledger_append at the end of each
#: driver, so --validate runs carry their numerics in the same ledger
#: record as the timing and the audit (suite runs several drivers in one
#: process; values must not bleed across rows).
_RESIDUALS: dict[str, float] = {}


def _gate(name: str, value: float, tol: float) -> None:
    _RESIDUALS[name] = value
    ok = value < tol
    print(f"# validate {name} = {value:.3e} (tol {tol:.0e}) {'OK' if ok else 'FAIL'}")
    if not ok:
        sys.exit(f"validation failed: {name} = {value:.3e} >= {tol:.0e}")


def _ledger_append(
    args, rec: dict, *, name: str, grid: Grid, cfg=None, step=None,
    operand=None, dtype=None, extra_record: dict | None = None,
) -> None:
    """Append one unified ledger record for a finished driver run (opt-in
    via --ledger PATH; no-op otherwise).  `name` is the driver's own name —
    args.driver says "suite" for suite rows.

    The record carries the measured JSON line plus, when the driver can
    hand over its (step, operand), the Recorder model decomposition and the
    compiled-program audit + drift report — the same facts
    ``python -m capital_tpu.obs audit`` derives, attached to a real
    measurement.  Model/audit capture is best-effort: a config whose
    re-lowering fails (e.g. a mode unsupported on this backend) still gets
    its manifest + measurement + residuals recorded, with the error noted,
    rather than losing the run."""
    residuals = dict(_RESIDUALS)
    _RESIDUALS.clear()
    path = getattr(args, "ledger", None)
    if not path:
        return
    from capital_tpu.obs import ledger, xla_audit

    model = audit_d = drift_d = None
    err = None
    if step is not None and operand is not None:
        op_args = operand if isinstance(operand, tuple) else (operand,)
        try:
            recd = xla_audit.trace_model(step, *op_args)
            audit = xla_audit.audit(step, *op_args)
            rep = xla_audit.drift(audit, recd)
            model = ledger.model_costs(recd, dtype=dtype)
            audit_d = audit.asdict()
            drift_d = rep.asdict()
        except Exception as e:  # broad on purpose: ledger must not fail the run
            err = f"{type(e).__name__}: {e}"
            _log.warning("ledger audit capture failed: %s", err)
    row = ledger.record(
        f"bench:{name}",
        ledger.manifest(grid=grid, dtype=dtype, config=cfg),
        model=model,
        audit=audit_d,
        drift=drift_d,
        measured=rec,
        residuals=residuals or None,
        **({"audit_error": err} if err else {}),
        **(extra_record or {}),
    )
    ledger.append(path, row)


def _spd(n: int, dtype, seed: int = 0) -> jnp.ndarray:
    """Well-conditioned SPD test matrix, built on device (Wigner + dominant
    diagonal — same spectrum family as the reference's distribute_symmetric
    diagonal dominance, structure.hpp:87-89)."""
    @jax.jit
    def make(key):
        M = jax.random.normal(key, (n, n), dtype=jnp.float32)
        A = (M + M.T) / jnp.sqrt(2.0 * n)
        # 3I, not 2I: the Wigner semicircle edge sits at exactly 2, so a
        # 2I shift leaves lambda_min grazing zero and f32 cholesky can NaN
        # depending on the RNG stream
        return (A + 3.0 * jnp.eye(n, dtype=M.dtype)).astype(dtype)

    return jax.block_until_ready(make(jax.random.key(seed)))


def _hbm_bytes() -> float:
    """Per-chip HBM capacity: the runtime's own figure when it exposes one
    (memory_stats()['bytes_limit']), else a conservative small default —
    assuming big wrongly reproduces known OOMs, assuming small only
    switches measurement protocols."""
    try:
        stats = jax.devices()[0].memory_stats() or {}
        limit = float(stats.get("bytes_limit", 0))
        if limit > 1e9:
            return limit
    except Exception as e:
        # runtimes without memory_stats fall through to the conservative
        # default; keep the swallow visible for anything less expected
        _log.debug("memory_stats unavailable: %s: %s", type(e).__name__, e)
    return 15.5e9


def _tall_hash(m: int, n: int, dtype, salt) -> jnp.ndarray:
    """Deterministic full-rank tall operand as ONE fused elementwise
    program (the cacqr analog of bench.py's spd_hash): splitmix32 of
    (i, j, salt) mapped to U[-1, 1].  A tall matrix of i.i.d.-ish uniform
    entries has gram ≈ (m/3)(I + O(sqrt(n/m))) — comfortably full-rank for
    CholeskyQR2 at the bench's m >> n shapes."""
    from jax import lax

    r = lax.broadcasted_iota(jnp.uint32, (m, n), 0)
    c = lax.broadcasted_iota(jnp.uint32, (m, n), 1)
    h = r * jnp.uint32(0x9E3779B1) ^ c * jnp.uint32(0x85EBCA77)
    h = h + jnp.asarray(salt).astype(jnp.uint32) * jnp.uint32(0xC2B2AE3D)
    h = (h ^ (h >> 16)) * jnp.uint32(0x7FEB352D)
    h = (h ^ (h >> 15)) * jnp.uint32(0x846CA68B)
    h = h ^ (h >> 16)
    u = h.astype(jnp.float32) * jnp.float32(2.0**-32)
    return (2.0 * u - 1.0).astype(dtype)


def _knobs(args) -> dict:
    """Topology knobs echoed into every JSON record so sweep rows over
    --layout/--chunks stay attributable to the config that produced them."""
    return dict(layout=getattr(args, "layout", 0), chunks=getattr(args, "chunks", 0))


def _timed(args, step, operand, coupling: str = "full", loop=None) -> tuple[float, dict]:
    """timed_loop plus the suite's drift guard (VERDICT r2 weak #4): with
    args.device_check, the device-counter op total of the same in-jit loop
    is measured (drift-immune), a wall that lands BELOW it is re-measured
    (favorable-drift artifact — seen: a 19.0 ms suite row against a 24.7 ms
    device total), and if it still undercuts after retries the row reports
    the device floor as its time with the raw wall kept alongside.  The
    returned extras (device_ms, ...) ride the JSON record."""
    # ONE jitted loop shared by the wall measurement, the device floor, and
    # any retries — each _make_loop product is a fresh jit cache entry, and
    # these fori_loop programs take seconds-to-minutes to trace+compile.
    # Callers with operands _make_loop cannot carry (the trsm driver's
    # (L, B) tuple) pass their own loop of the same shape.
    loop = loop or harness._make_loop(step, coupling)
    samples: list[float] = []
    t = harness.timed_loop(
        step, operand, iters=args.iters, coupling=coupling, loop=loop,
        samples_out=samples,
    )
    extra: dict = {}
    if len(samples) >= 2:
        # per-iteration wall spread (paired-delta samples at the resolved
        # trip count) through the shared quantile helper — the same
        # p50/p95/p99 shape serve/stats.py reports, so bench rows and
        # request_stats records read on one scale
        extra["wall_ms"] = {
            k: round(v * 1e3, 3)
            for k, v in harness.percentiles(samples).items()
        }
    if getattr(args, "device_check", False):
        dms = harness.device_ms_per_iter(
            step, operand, iters=max(3, args.iters), coupling=coupling, loop=loop
        )
        if dms > 0.0:
            extra["device_ms"] = round(dms, 3)
            tries = 0
            while t * 1e3 < dms and tries < 2:
                t = harness.timed_loop(
                    step, operand, iters=args.iters, coupling=coupling, loop=loop
                )
                tries += 1
            if t * 1e3 < dms:
                extra["wall_ms_below_floor"] = round(t * 1e3, 3)
                t = dms / 1e3
    return t, extra


def _precision(args, dtype) -> str | None:
    """The per-driver precision default ('highest' keeps f32 factors at
    f32-grade accuracy; bf16 runs the MXU native path) with the --precision
    override — added to bound the f32 'high' (3-pass) XLA-path gap the
    default 6-pass 'highest' leaves unmeasured (VERDICT r2 weak #7)."""
    if getattr(args, "precision", None):
        return None if args.precision == "default" else args.precision
    return None if jnp.dtype(dtype).itemsize < 4 else "highest"


def _resolve_mode(mode: str, grid: Grid) -> str:
    """'auto' picks the best SUMMA mode for the topology: the
    dead-block-skipping pallas kernels on a single TPU (the flagship
    bench.py path — mode='xla' leaves ~40% of cholinv throughput on the
    table there), GSPMD planning on a mesh (pallas is single-device-only
    and would silently fall back anyway).  Off-TPU, pallas means the
    interpreter — orders of magnitude slower than xla — so the CPU smoke
    rig stays on xla."""
    if mode != "auto":
        return mode
    one_tpu = grid.num_devices == 1 and grid.platform == "tpu"
    return "pallas" if one_tpu else "xla"


def _grid(args) -> Grid:
    """Largest d x d x c grid the device set supports, preferring the
    requested replication depth c (reference rep_div knob,
    bench/cholesky/cholinv.cpp:16)."""
    dev = jax.devices()
    if args.devices:
        dev = dev[: args.devices]
    layout = getattr(args, "layout", 0)
    chunks = getattr(args, "chunks", 0)
    n = len(dev)
    if n == 1:
        return Grid.square(c=1, devices=dev, num_chunks=chunks)
    best = (1, 1)  # (d, c)
    for c in (args.c, 1, 2, 4, 8):
        d = 1
        while (d + 1) * (d + 1) * c <= n:
            d += 1
        if d * d * c <= n and d * d * c > best[0] ** 2 * best[1]:
            best = (d, c)
    d, c = best
    return Grid.square(
        c=c, devices=dev[: d * d * c], layout=layout, num_chunks=chunks
    )


# --------------------------------------------------------------------------


def pick_bc(n: int, override: int = 0, cholinv_family: bool = True) -> int:
    """Padding-aware base-case auto-pick (--bc 0), shared with bench.py's
    auto_base_case.  The cholinv family's leaf potrf chain is the latency
    floor at small n, so finer leaves win below the measured crossovers
    (docs/PERF.md "Small-N — round 5": at n=4096, 128/256/512 measure
    25.3/24.7/23.5 TF/s; at n=8192, 57.5/60.3/59.1; 512 holds from 16384
    up within drift).  Candidates that tile n exactly are preferred; when
    none does, the same preference order breaks ties among the least-
    padding candidates.  Non-cholinv drivers keep the committed 512."""
    if override:
        return override
    from capital_tpu.models import cholesky as _ch

    if not cholinv_family:
        return 512
    if n <= 4096:
        order = (128, 256, 512, 384)
    elif n <= 8192:
        order = (256, 512, 384, 128)
    else:
        order = (512, 384, 256)
    for cand in order:
        if _ch.padded_dim(n, cand) == n:
            return cand
    return min(order, key=lambda c: (_ch.padded_dim(n, c), order.index(c)))


def cholinv(args) -> dict:
    grid = _grid(args)
    mode = _resolve_mode(args.mode, grid)
    dtype = jnp.dtype(args.dtype)
    bc = pick_bc(args.n, args.bc)
    cfg = cholesky.CholinvConfig(
        complete_inv=not args.no_complete_inv,
        split=args.split,
        base_case_dim=bc,
        mode=mode,
        balance=getattr(args, "balance", "block"),
        precision=_precision(args, dtype),
    )
    A = _spd(args.n, dtype)

    def step(a):
        R, Rinv = cholesky.factor(grid, a, cfg)
        return R + Rinv

    t, extra = _timed(args, step, A)
    if getattr(args, "phase_attr", False):
        # opt-in wall attribution (bench.trace.phase_attribution): the
        # bubble_frac rides the report line next to the TFLOP/s number and
        # the phase split rides the ledger record for obs trace-report
        from capital_tpu.bench import trace as trace_mod

        arun = trace_mod._cholinv_run(
            args.n, dtype, bc, args.iters, False, cfg.precision, mode=mode
        )
        ps, bubble, _wall = trace_mod.phase_attribution(arun, args.iters)
        extra = {
            **extra,
            "bubble_frac": round(bubble, 4),
            "phase_seconds": {k: round(v, 9) for k, v in ps.items()},
        }
    flops = 2.0 * args.n**3 / 3.0  # factor n³/3 + triangular inverse n³/3
    rec = harness.report(
        "cholinv_tflops", t, flops, dtype, n=args.n, grid=repr(grid), bc=bc,
        mode=mode, balance=cfg.balance, **_knobs(args), **extra,
    )
    if args.validate:
        R, Rinv = jax.jit(lambda a: cholesky.factor(grid, a, cfg))(A)
        tol = _tolerance(dtype)
        _gate("cholesky_residual", float(residual.cholesky_residual(A, R)), tol)
        if cfg.complete_inv:
            _gate(
                "inverse_residual",
                float(residual.cholesky_inverse_residual(R, Rinv)),
                tol,
            )
    _ledger_append(
        args, rec, name="cholinv", grid=grid, cfg=cfg, step=step, operand=A,
        dtype=dtype,
    )
    return rec


def cacqr(args) -> dict:
    # the nested config factors the n x n GRAM — a cholinv-family workload,
    # so the auto-pick follows the cholinv crossovers at the gram size
    bc = pick_bc(args.n, args.bc)
    # tall-skinny topology: the reference uses a tunable rect grid
    # (topology.h:16-65); the 1d/auto regimes want the whole mesh on the
    # long axis (Grid.flat), 'dist' wants a square face
    dev = jax.devices()
    if args.devices:
        dev = dev[: args.devices]
    if args.regime == "dist" or len(dev) == 1:
        grid = _grid(args)
        applied_knobs = _knobs(args)
    else:
        grid = Grid.flat(devices=dev)  # natural order, unchunked
        applied_knobs = dict(layout=0, chunks=0)
    dtype = jnp.dtype(args.dtype)
    mode = _resolve_mode(args.mode, grid)
    precision = _precision(args, dtype)
    robust = getattr(args, "robust", False)
    cfg = qr.CacqrConfig(
        num_iter=args.variant,
        regime=args.regime,
        mode=mode,
        cholinv=cholesky.CholinvConfig(
            base_case_dim=bc, mode=mode, precision=precision
        ),
        precision=precision,
        fused_g=getattr(args, "fused_g", 0),
        robust=RobustConfig() if robust else None,
    )
    # One-shot regen protocol when the A-carry would not fit: the standard
    # loop keeps FOUR Q-sized buffers at peak (A carry, Q1, Q, and the
    # carry's while-loop double buffer — measured "Used 16.01G of 15.75G"
    # at the true 2M x 1024 BASELINE shape); regenerating A per iteration
    # from a fused hash (scalar loop carry) drops the peak to ~2 Q-sized
    # buffers, putting the 8-rank BASELINE shape on ONE chip.  Requires
    # the element-coupling eligibility (qr.pallas_coupled) — the one-shot
    # consume is a one-element read.
    elem_ok = qr.pallas_coupled(grid, args.n, mode, m=args.m, dtype=dtype)
    # --robust measures the guarded path (status scalars in the carry), which
    # the scalar one-shot consume would dead-code-eliminate
    oneshot = (
        elem_ok
        and not robust
        and grid.num_devices == 1
        and 4.1 * args.m * args.n * dtype.itemsize > _hbm_bytes()
    )
    if oneshot:
        def gen(i):
            return _tall_hash(args.m, args.n, dtype, i)

        def scalar_step(a):
            Q, R = qr.factor(grid, a, cfg)
            return (Q[0, 0] + R[0, 0]).astype(jnp.float32)

        t, t_regen, extra = harness.timed_oneshot(
            gen, scalar_step, iters=args.iters,
            device_check=getattr(args, "device_check", False),
        )
        extra = {"oneshot": True, "regen_seconds": round(t_regen, 5), **extra}
        A = None
        # the ledger audit lowers against an abstract operand — a concrete
        # A is exactly what the one-shot protocol exists to avoid holding
        step = scalar_step
        audit_operand = jax.ShapeDtypeStruct((args.m, args.n), dtype)
    else:
        # generate on device directly at the target dtype (an f32 staging
        # buffer alone is 8GB at the 2M x 1024 BASELINE shape)
        A = jax.block_until_ready(
            jax.random.normal(jax.random.key(0), (args.m, args.n), dtype=dtype)
        )

        def step(a):
            res = qr.factor(grid, a, cfg)
            Q, R = res[0], res[1]
            # fold R into the tall carry via a slice-add so the carry keeps
            # A's shape while both outputs stay live (the carry is
            # Q-shaped, so the loop factors its own running output — same
            # discipline as bench.py's cholinv loop)
            out = Q.at[: R.shape[0], : R.shape[1]].add(R.astype(Q.dtype))
            if cfg.robust is not None:
                # keep the guard live in the measured program: the shift is
                # data-dependent and exactly 0 on a healthy factorization
                ri = res[2]
                out = out.at[0, 0].add(
                    (ri.sigma * ri.breakdown.astype(ri.sigma.dtype)).astype(
                        out.dtype
                    )
                )
            return out

        # element carry only when the factor's outputs ride un-narrowable
        # ops (saves a Q-sized full-add, ~5 ms/iter at 1M x 1024); the
        # predicate lives in qr next to the kernel gating it must track
        coupling = "elem" if elem_ok else "full"
        t, extra = _timed(args, step, A, coupling=coupling)
        audit_operand = A
    # useful flops per sweep: gram mn² + Q·R⁻¹ mn²; CQR2 doubles the sweeps
    flops = 2.0 * args.m * args.n**2 * cfg.num_iter
    robust_d = None
    if cfg.robust is not None:
        # one extra factorization of the bench operand to read the status
        # scalars out (the timed loop only keeps them live, not inspectable)
        ri = jax.jit(lambda a: qr.factor(grid, a, cfg)[2])(A)
        robust_d = {
            "info": int(ri.info),
            "breakdown": int(ri.breakdown),
            "shifted": int(ri.shifted),
            "sigma": float(ri.sigma),
            "escalated": int(ri.escalated),
            "ortho": float(ri.ortho),
        }
    rec = harness.report(
        "cacqr_tflops", t, flops, dtype, m=args.m, n=args.n,
        variant=args.variant, grid=repr(grid), mode=mode, **applied_knobs,
        **extra, **({"robust": robust_d} if robust_d else {}),
    )
    if args.validate:
        if A is None:  # one-shot runs: validate one regenerated instance
            A = jax.block_until_ready(
                jax.jit(lambda: _tall_hash(args.m, args.n, dtype, 0))()
            )
        res = jax.jit(lambda a: qr.factor(grid, a, cfg))(A)
        Q, R = res[0], res[1]
        tol = _tolerance(dtype)
        _gate("qr_orthogonality", float(residual.qr_orthogonality(Q)), tol)
        # row-blocked accumulation: the dense residual's m x n f32
        # temporaries OOM the 2M x 1024 shape whose factorization fits
        _gate(
            "qr_residual",
            float(jax.jit(residual.qr_residual_blocked)(A, Q, R)),
            tol,
        )
    extra_record = None
    if robust_d is not None:
        extra_record = {"robust": robust_d}
        if robust_d["breakdown"]:
            status = "recovered" if robust_d["info"] == 0 else "breakdown"
            extra_record["event"] = {"status": status}
    _ledger_append(
        args, rec, name="cacqr", grid=grid, cfg=cfg, step=step,
        operand=audit_operand, dtype=dtype, extra_record=extra_record,
    )
    return rec


def summa_gemm(args) -> dict:
    grid = _grid(args)
    mode = _resolve_mode(args.mode, grid)
    dtype = jnp.dtype(args.dtype)
    A = jax.random.normal(jax.random.key(0), (args.m, args.k), dtype)
    B = jax.random.normal(jax.random.key(1), (args.k, args.n), dtype)
    gargs = summa.GemmArgs(precision=_precision(args, dtype))

    def step(a):
        return summa.gemm(grid, a, B, args=gargs, mode=mode)

    # carry must match operand shape: square M=N=K benches only need A
    if not (args.m == args.n == args.k):
        raise SystemExit("summa_gemm bench uses square M=N=K")
    t, extra = _timed(args, step, A)
    rec = harness.report(
        "summa_gemm_tflops", t, 2.0 * args.m * args.n * args.k, dtype,
        m=args.m, n=args.n, k=args.k, grid=repr(grid), mode=mode,
        **_knobs(args), **extra,
    )
    if args.validate:
        C = jax.jit(lambda a: summa.gemm(grid, a, B, args=gargs, mode=mode))(A)
        ref = jnp.matmul(A.astype(jnp.float32), B.astype(jnp.float32))
        err = float(residual.rel_fro(C.astype(jnp.float32) - ref, ref))
        _gate("gemm_residual", err, _tolerance(dtype))
    _ledger_append(
        args, rec, name="summa_gemm", grid=grid, cfg=gargs, step=step,
        operand=A, dtype=dtype,
    )
    return rec


def _tri_operand(n: int, dtype, seed: int = 0) -> jnp.ndarray:
    """Well-conditioned lower-triangular bench operand, built DIRECTLY at
    dtype (no chol-of-SPD setup — its two extra f32 n² staging buffers
    OOM'd the n=32768 row on one v5e).  Off-diagonal scale 1/sqrt(n):
    kappa ~ 2 at every n (measured 1.9-2.0 at 512-8192 in f64) while the
    off-diagonal part carries ~23% of the matrix norm, so the --validate
    residual gate still SEES off-diagonal bugs — a 1/n scale would shrink
    them ~sqrt(n)x below the bf16 tolerance.  Shared by the rectri/trsm
    drivers and bench.trace so the traced operand IS the benched one."""

    @jax.jit
    def _make(key):
        G = jax.random.normal(key, (n, n), dtype=jnp.float32)
        L = jnp.tril(G, -1) / jnp.sqrt(
            jnp.asarray(n, jnp.float32)
        ) + 3.0 * jnp.eye(n, dtype=jnp.float32)
        return L.astype(dtype)

    return jax.block_until_ready(_make(jax.random.key(seed)))


def rectri(args) -> dict:
    bc = pick_bc(args.n, args.bc, cholinv_family=False)
    grid = _grid(args)
    mode = _resolve_mode(args.mode, grid)
    dtype = jnp.dtype(args.dtype)
    L = _tri_operand(args.n, dtype)
    extra_cfg = {} if args.batch_below < 0 else {"batch_below": args.batch_below}
    cfg = inverse.RectriConfig(
        base_case_dim=bc, mode=mode,
        precision=_precision(args, dtype), **extra_cfg,
    )

    def step(a):
        return inverse.rectri(grid, a, "L", cfg)

    t, extra = _timed(args, step, L)
    rec = harness.report(
        "rectri_tflops", t, args.n**3 / 3.0, dtype, n=args.n, grid=repr(grid),
        mode=mode, **_knobs(args), **extra,
    )
    if args.validate:
        Linv = jax.jit(lambda a: inverse.rectri(grid, a, "L", cfg))(L)
        # row-blocked gate: the dense I − L·L⁻¹ is an n² f32 buffer that
        # OOMs one v5e at n=49152 (falls back to dense for small n)
        _gate(
            "trtri_residual",
            float(jax.jit(residual.inverse_residual_blocked)(L, Linv)),
            _tolerance(dtype),
        )
    _ledger_append(
        args, rec, name="rectri", grid=grid, cfg=cfg, step=step, operand=L,
        dtype=dtype,
    )
    return rec


def newton(args) -> dict:
    grid = _grid(args)
    # xla mode regardless of 'auto': Newton is two dense gemms per step,
    # where the pallas path adds nothing (gemm falls through to xla anyway)
    mode = args.mode if args.mode != "auto" else "xla"
    dtype = jnp.dtype(args.dtype)
    A = _spd(args.n, dtype)
    cfg = inverse.NewtonConfig(
        max_iter=args.newton_iters, mode=mode,
        precision=_precision(args, dtype),
    )

    def step(a):
        X, _ = inverse.newton(grid, a, cfg)
        return X

    t, extra = _timed(args, step, A)
    # Executed flops, not the budget: the while_loop exits early on
    # convergence (often ~12 of 30 budgeted steps), so scaling by max_iter
    # would inflate TF/s ~2.5x.  Count the actual data-dependent iteration
    # count — one init gemm (A@X0) plus 2 gemms per executed step, 2n³ each.
    # One extra inversion serves both the count and the --validate gate.
    Ainv, it = jax.jit(lambda a: inverse.newton(grid, a, cfg))(A)
    newton_iters = int(it)
    flops = 2.0 * args.n**3 * (2.0 * newton_iters + 1.0)
    rec = harness.report(
        "newton_tflops", t, flops, dtype, n=args.n, grid=repr(grid),
        iters_executed=newton_iters, max_iters=args.newton_iters, mode=mode,
        **_knobs(args), **extra,
    )
    if args.validate:
        _gate(
            "newton_residual",
            float(residual.inverse_residual(A, Ainv)),
            10 * _tolerance(dtype),
        )
    _ledger_append(
        args, rec, name="newton", grid=grid, cfg=cfg, step=step, operand=A,
        dtype=dtype,
    )
    return rec


def spd_inverse(args) -> dict:
    bc = pick_bc(args.n, args.bc)
    grid = _grid(args)
    mode = _resolve_mode(args.mode, grid)
    dtype = jnp.dtype(args.dtype)
    cfg = cholesky.CholinvConfig(
        base_case_dim=bc, mode=mode,
        precision=_precision(args, dtype),
    )
    A = _spd(args.n, dtype)

    def step(a):
        return cholesky.spd_inverse(grid, a, cfg)

    t, extra = _timed(args, step, A)
    flops = 2.0 * args.n**3 / 3.0 + args.n**3 / 3.0
    rec = harness.report(
        "spd_inverse_tflops", t, flops, dtype, n=args.n, grid=repr(grid),
        mode=mode, **_knobs(args), **extra,
    )
    if args.validate:
        Ainv = jax.jit(lambda a: cholesky.spd_inverse(grid, a, cfg))(A)
        _gate(
            "spd_inverse_residual",
            float(residual.inverse_residual(A, Ainv)),
            10 * _tolerance(dtype),
        )
    _ledger_append(
        args, rec, name="spd_inverse", grid=grid, cfg=cfg, step=step,
        operand=A, dtype=dtype,
    )
    return rec


def trsm(args) -> dict:
    """Bench the finished distributed TRSM (models/trsm.py — the capability
    the reference stubs at diaginvert.hpp:9).  Times side='L', uplo='L'
    (the back-substitution shape cholinv/cacqr lean on); --validate smoke-
    tests all four side/uplo combos plus the unit_diag (Diag::AblasUnit)
    surface at the bench size."""
    from capital_tpu.models import trsm as trsm_mod

    bc = pick_bc(args.n, args.bc, cholinv_family=False)
    grid = _grid(args)
    # 'auto' resolves to xla for the invert leaf, not the usual single-TPU
    # pallas pick: with diaginvert leaves every TRSM gemm is DENSE
    # (off-diagonal updates + leaf multiplies), so the live-tile kernels'
    # triangular bookkeeping is pure overhead (measured 163.9 vs 165.2
    # TF/s at n=32768).  The solve leaf keeps the standard resolution.
    if args.mode == "auto":
        mode = "xla" if args.leaf == "invert" else _resolve_mode(args.mode, grid)
    else:
        mode = args.mode
    dtype = jnp.dtype(args.dtype)
    L = _tri_operand(args.n, dtype)
    nrhs = args.m if args.m != 65536 or args.n >= 65536 else args.n
    B = jax.block_until_ready(
        jax.random.normal(jax.random.key(1), (args.n, nrhs), dtype=dtype)
    )
    cfg = trsm_mod.TrsmConfig(
        base_case_dim=bc, mode=mode, precision=_precision(args, dtype),
        leaf=args.leaf,
    )

    # L must be a REAL jit argument, not a step() closure: a closed-over
    # n x n array becomes an HLO constant, and at n >= 16384 the serialized
    # program blows past the tunnel compile server's request limit
    # (HTTP 413; n=32768 killed it outright with a broken pipe).  A custom
    # loop with a (L, B) tuple operand mirrors _make_loop's 'full'
    # coupling body and shares wall + device floor like every driver.
    @jax.jit
    def loop(op, eps, k):
        Lo, B0 = op

        def body(_, carry):
            X = trsm_mod.solve(grid, Lo, carry, side="L", uplo="L", cfg=cfg)
            return carry + eps.astype(carry.dtype) * X

        return jnp.sum(jax.lax.fori_loop(0, k, body, B0), dtype=jnp.float32)

    t, extra = _timed(args, None, (L, B), loop=loop)
    # standard TRSM flop count: n² flops per right-hand side
    flops = 1.0 * args.n**2 * nrhs
    rec = harness.report(
        "trsm_tflops", t, flops, dtype, n=args.n, nrhs=nrhs, grid=repr(grid),
        bc=bc, mode=mode, **_knobs(args), **extra,
    )
    if args.validate:
        # each combo solves + checks inside ONE jit over (L, B) arguments
        # (an f32 copy of the n x n operand is 4.3 GB at n=32768 — holding
        # several eagerly OOM'd the chip), against a reduced RHS
        tol = _tolerance(dtype)
        Bv = B[:, : min(nrhs, 4096)]

        def combo_err(t, b, side, uplo, unit):
            tf = t.astype(jnp.float32)
            if unit:
                # solve against the RAW operand (stored diagonal 3.0) with
                # unit_diag: the reference product uses diag == 1, so the
                # gate only passes if the solver truly ignores the stored
                # diagonal (Diag::AblasUnit semantics)
                Tf = jnp.tril(tf, -1) + jnp.eye(t.shape[0], dtype=jnp.float32)
                solve_op = t
            else:
                Tf = jnp.tril(tf) if uplo == "L" else jnp.triu(tf.T)
                solve_op = Tf.astype(dtype)
            X = trsm_mod.solve(
                grid, solve_op, b, side=side, uplo=uplo, cfg=cfg,
                unit_diag=unit,
            )
            # gate matmul at 'highest' like every residual.* helper
            # (residual.py _PREC note): the default f32 product floors the
            # measurable residual near 1e-3 and fails a CORRECT f32 solve
            got = (
                jnp.matmul(Tf, X.astype(jnp.float32), precision="highest")
                if side == "L"
                else jnp.matmul(X.astype(jnp.float32), Tf, precision="highest")
            )
            return residual.rel_fro(got - b.astype(jnp.float32), b)

        for side in ("L", "R"):
            for uplo in ("L", "U"):
                Bs = Bv if side == "L" else Bv.T
                err = float(
                    jax.jit(
                        lambda t, b, s=side, u=uplo: combo_err(t, b, s, u, False)
                    )(L, Bs)
                )
                _gate(f"trsm_residual_{side}{uplo}", err, tol)
        # Diag::AblasUnit parity: the solve must ignore the stored diagonal
        err = float(
            jax.jit(lambda t, b: combo_err(t, b, "L", "L", True))(L, Bv)
        )
        _gate("trsm_residual_unit_diag", err, tol)

    # audit step takes (L, B) as REAL arguments (same HLO-constant rule as
    # the timing loop above); skipped past n=8192 where re-lowering the
    # whole solve just for the inventory costs more than the bench itself
    def audit_step(lo, b):
        return trsm_mod.solve(grid, lo, b, side="L", uplo="L", cfg=cfg)

    _ledger_append(
        args, rec, name="trsm", grid=grid, cfg=cfg,
        step=audit_step if args.n <= 8192 else None, operand=(L, B),
        dtype=dtype,
    )
    return rec


def _small_batch(op: str, n: int, batch: int, nrhs: int, dtype,
                 seed: int = 3):
    """One bucket-shaped problem batch for the small-N drivers: SPD
    problems for posv, tall (4n, n) problems for lstsq — the serve
    bucket geometry, full occupancy."""
    import numpy as np

    rng = np.random.default_rng(seed)
    if op == "posv":
        m = n
        X = rng.standard_normal((batch, n, n))
        A = X @ X.transpose(0, 2, 1) / n + 3.0 * np.eye(n)
    else:
        m = 4 * n
        A = rng.standard_normal((batch, m, n))
    B = rng.standard_normal((batch, m, nrhs))
    return (
        jax.block_until_ready(jnp.asarray(A, dtype)),
        jax.block_until_ready(jnp.asarray(B, dtype)),
    )


def _small_residual(op: str, A, B, X) -> float:
    """Worst per-problem f64 residual of a batch solve (numpy reference)."""
    import numpy as np

    A = np.asarray(A, np.float64)
    B = np.asarray(B, np.float64)
    X = np.asarray(X, np.float64)
    worst = 0.0
    for i in range(A.shape[0]):
        if op == "posv":
            r = np.linalg.norm(A[i] @ X[i] - B[i]) / np.linalg.norm(B[i])
        else:
            num = np.linalg.norm(A[i].T @ (A[i] @ X[i] - B[i]))
            r = num / np.linalg.norm(A[i].T @ B[i])
        worst = max(worst, r)
    return worst


def _small_solve(args, op: str):
    """Shared body of the posv/lstsq small-N drivers: one bucket batch
    through api.batched under --small-impl, measured either amortized
    (TFLOP/s row, the default) or per-call (--latency: p50/p95/p99
    wall_ms via harness.latency_samples + percentiles, sorted facts for
    the latency regime ROADMAP item 5 names — each sample pays the
    dispatch a served request pays)."""
    from capital_tpu.serve import api

    dtype = jnp.dtype(args.dtype)
    n, batch, nrhs = args.n, args.batch, args.nrhs
    grid = Grid.square(c=1, devices=jax.devices()[:1])
    prec = _precision(args, dtype)
    A, B = _small_batch(op, n, batch, nrhs, dtype)
    fn = jax.jit(api.batched(op, prec, args.small_impl))

    if args.validate:
        X, info = jax.block_until_ready(fn(A, B))
        bad = int(jnp.sum(info != 0))
        if bad:
            sys.exit(f"validation failed: {bad} problem(s) report info != 0")
        tol = _tolerance(dtype)
        gate = 10 * tol if op == "lstsq" else tol
        _gate(f"{op}_batch_residual", _small_residual(op, A, B, X), gate)

    # useful flops (not the kernels' executed sweep counts): the
    # cross-impl comparable figure
    m = A.shape[1]
    if op == "posv":
        flops = batch * (n**3 / 3.0 + 2.0 * n * n * nrhs)
    else:
        flops = batch * (2.0 * m * n * n + 2.0 * m * n * nrhs)

    if args.latency:
        samples = harness.latency_samples(
            lambda: fn(A, B), calls=args.calls, warmup=3
        )
        pcts = harness.percentiles(samples)
        wall_ms = {k: round(v * 1e3, 4) for k, v in pcts.items()}
        from capital_tpu.obs.ledger import SCHEMA_VERSION

        rec = {
            "metric": f"small_{op}_latency",
            "schema_version": SCHEMA_VERSION,
            # value is rate so an obs diff value-drop reads as "slower p99"
            "value": round(1.0 / pcts["p99"], 3),
            "unit": "batch/s",
            "seconds": pcts["p99"],
            "wall_ms": wall_ms,
            "dtype": str(dtype),
            "device": jax.devices()[0].device_kind,
            "platform": jax.default_backend(),
            "n": n, "batch": batch, "nrhs": nrhs,
            "impl": args.small_impl, "calls": args.calls,
        }
        import json as _json

        print(_json.dumps(rec))
        _ledger_append(args, rec, name="latency", grid=grid, dtype=dtype,
                       cfg={"op": op, "impl": args.small_impl})
        return rec

    samples = harness.latency_samples(
        lambda: fn(A, B), calls=max(args.iters, 3), warmup=3
    )
    t = sum(samples) / len(samples)
    rec = harness.report(
        f"small_{op}_tflops", t, flops, dtype, n=n, batch=batch, nrhs=nrhs,
        impl=args.small_impl, grid=repr(grid),
        wall_ms={k: round(v * 1e3, 4)
                 for k, v in harness.percentiles(samples).items()},
    )
    _ledger_append(args, rec, name=op, grid=grid, dtype=dtype,
                   cfg={"op": op, "impl": args.small_impl})
    return rec


def _blocktri_batch(nblocks: int, b: int, batch: int, nrhs: int, dtype,
                    seed: int = 5):
    """One batch of SPD block-tridiagonal chains (the serve posv_blocktri
    geometry): D_i = G·Gᵀ/b + 3I per block (the _spd spectrum family),
    couplings at 0.3/√b — strong enough that a sweep bug blows the
    residual gate, weak enough that the chain stays well-conditioned
    (block diagonal dominance).  Returns device arrays plus the f64 numpy
    masters for --validate."""
    import numpy as np

    rng = np.random.default_rng(seed)
    G = rng.standard_normal((batch, nblocks, b, b))
    D = G @ G.transpose(0, 1, 3, 2) / b + 3.0 * np.eye(b)
    C = 0.3 / np.sqrt(b) * rng.standard_normal((batch, nblocks, b, b))
    C[:, 0] = 0.0
    B = rng.standard_normal((batch, nblocks, b, nrhs))
    dev = tuple(
        jax.block_until_ready(jnp.asarray(x, dtype)) for x in (D, C, B)
    )
    return dev, (D, C, B)


def _blocktri_dense(D, C) -> "jnp.ndarray":
    """Assemble the f64 numpy chain masters to dense (batch, n, n) —
    NumPy-side so the reference the residual gates compare against never
    touches the code under test (models/blocktri.assemble is itself new
    in this round)."""
    import numpy as np

    batch, nblocks, b, _ = D.shape
    n = nblocks * b
    A = np.zeros((batch, n, n))
    for i in range(nblocks):
        sl = slice(i * b, (i + 1) * b)
        A[:, sl, sl] = D[:, i]
        if i:
            up = slice((i - 1) * b, i * b)
            A[:, sl, up] = C[:, i]
            A[:, up, sl] = C[:, i].transpose(0, 2, 1)
    return A


def blocktri(args) -> dict:
    """Bench the block-tridiagonal fast path (models/blocktri.posv) and
    measure its wall-clock speedup against the equal-n dense batched posv
    on the SAME problems assembled dense — the structural O(n·b³) vs
    O(n³) win the round-11 flagship gate pins (docs/PERF.md).  Reports
    useful-flop TF/s (the chain's O(n·b³) count, not dense n³ — so the
    TF/s figure is comparable across impls, and the speedup column
    carries the structural win)."""
    from capital_tpu.models import blocktri as bt_mod
    from capital_tpu.serve import api

    dtype = jnp.dtype(args.dtype)
    grid = Grid.square(c=1, devices=jax.devices()[:1])
    prec = _precision(args, dtype)
    nblocks, b, batch, nrhs = args.nblocks, args.block, args.batch, args.nrhs
    n = nblocks * b
    impl = args.impl
    if impl == "auto" and jax.default_backend() != "tpu":
        # off-TPU 'auto' pins the xla scan — pallas means the interpreter
        # there (the _resolve_mode rationale), and a bench must measure an
        # honest wall time.  serve keeps interpret-pallas off-TPU for its
        # own reason (pure-HLO executables persist in the AOT disk cache);
        # the bench and serve resolve 'auto' differently ON PURPOSE.
        impl = "xla"
    (Dj, Cj, Bj), (Dn, Cn, Bn) = _blocktri_batch(nblocks, b, batch, nrhs,
                                                 dtype)
    partitions = 0
    seq_impl = impl
    if impl == "partitioned":
        # the A/B satellite: bench the partitioned driver against the
        # sequential scan on the SAME problems, and measure the thing the
        # algorithm actually buys — jaxpr sequential scan depth (the
        # critical path a 1-core rig can still count honestly even when
        # wall time can't show the parallel win).  Inner scans obey the
        # same off-TPU honest-wall pin as 'auto' above.
        partitions = bt_mod.resolve_partitions(nblocks, args.partitions)
        inner = "xla" if jax.default_backend() != "tpu" else "auto"
        seq_impl = "xla" if jax.default_backend() != "tpu" else "pallas"
        fn = jax.jit(
            lambda d, c, rhs: bt_mod.posv(
                d, c, rhs, precision=prec, impl="partitioned",
                partitions=partitions, partition_inner=inner,
            )
        )
    else:
        fn = jax.jit(
            lambda d, c, rhs: bt_mod.posv(d, c, rhs, precision=prec,
                                          impl=impl)
        )
    seq_fn = fn if impl != "partitioned" else jax.jit(
        lambda d, c, rhs: bt_mod.posv(d, c, rhs, precision=prec,
                                      impl=seq_impl)
    )

    if args.validate:
        X, info = jax.block_until_ready(fn(Dj, Cj, Bj))
        bad = int(jnp.sum(info != 0))
        if bad:
            sys.exit(f"validation failed: {bad} problem(s) report info != 0")
        import numpy as np

        Ad = _blocktri_dense(Dn, Cn)
        Xn = np.asarray(X, np.float64).reshape(batch, n, nrhs)
        Bd = Bn.reshape(batch, n, nrhs)
        tol = _tolerance(dtype)
        worst = max(
            float(np.linalg.norm(Ad[i] @ Xn[i] - Bd[i])
                  / np.linalg.norm(Bd[i]))
            for i in range(batch)
        )
        _gate("blocktri_solve_residual", worst, tol)
        # factor residual: reconstruct A from (L, Wt) blockwise in f64 —
        # ‖A − L̃·L̃ᵀ‖_F/‖A‖_F over the whole batch.  factor() is the
        # sequential representation (it rejects 'partitioned'), so the
        # reconstruction rides seq_impl; the partitioned X was already
        # residual-gated above, which is the contract that matters.
        L, Wt, _ = jax.jit(
            lambda d, c: bt_mod.factor(d, c, precision=prec, impl=seq_impl)
        )(Dj, Cj)
        Ln = np.asarray(L, np.float64)
        Wn = np.asarray(Wt, np.float64).transpose(0, 1, 3, 2)  # W_i
        R = np.zeros_like(Ad)
        for i in range(nblocks):
            sl = slice(i * b, (i + 1) * b)
            # A_ii = L_i·L_iᵀ + W_i·W_iᵀ  (W_1 = 0); A_{i,i−1} = W_i·L_{i−1}ᵀ
            R[:, sl, sl] = Ln[:, i] @ Ln[:, i].transpose(0, 2, 1)
            if i:
                up = slice((i - 1) * b, i * b)
                R[:, sl, sl] += Wn[:, i] @ Wn[:, i].transpose(0, 2, 1)
                blk = Wn[:, i] @ Ln[:, i - 1].transpose(0, 2, 1)
                R[:, sl, up] = blk
                R[:, up, sl] = blk.transpose(0, 2, 1)
        _gate(
            "blocktri_factor_residual",
            float(np.linalg.norm(R - Ad) / np.linalg.norm(Ad)),
            tol,
        )

    # useful flops per chain: factor nblocks·(b³/3 chol + b³ trsm + 2b³
    # Schur) + solve nblocks·2 sweeps·(b²k trsm + 2b²k coupling gemm)
    flops = batch * nblocks * (b**3 / 3.0 + 3.0 * b**3
                               + 6.0 * b * b * nrhs)

    if args.latency:
        samples = harness.latency_samples(
            lambda: fn(Dj, Cj, Bj), calls=args.calls, warmup=3
        )
        pcts = harness.percentiles(samples)
        from capital_tpu.obs.ledger import SCHEMA_VERSION

        rec = {
            "metric": "blocktri_latency",
            "schema_version": SCHEMA_VERSION,
            "value": round(1.0 / pcts["p99"], 3),
            "unit": "batch/s",
            "seconds": pcts["p99"],
            "wall_ms": {k: round(v * 1e3, 4) for k, v in pcts.items()},
            "dtype": str(dtype),
            "device": jax.devices()[0].device_kind,
            "platform": jax.default_backend(),
            "nblocks": nblocks, "block": b, "n": n, "batch": batch,
            "nrhs": nrhs, "impl": impl, "calls": args.calls,
        }
        if impl == "partitioned":
            from capital_tpu.obs import xla_audit

            rec["partitions"] = partitions
            rec["depth"] = xla_audit.sequential_depth(fn, Dj, Cj, Bj)
        import json as _json

        print(_json.dumps(rec))
        _ledger_append(args, rec, name="blocktri_latency", grid=grid,
                       dtype=dtype,
                       cfg={"op": "posv_blocktri", "impl": impl})
        return rec

    samples = harness.latency_samples(
        lambda: fn(Dj, Cj, Bj), calls=max(args.iters, 3), warmup=3
    )
    t = sum(samples) / len(samples)

    par_extra: dict = {}
    if impl == "partitioned":
        # A/B rows vs the sequential scan: latency AND jaxpr sequential
        # scan depth — the depth column is the honest metric on a 1-core
        # rig (wall time can't show the parallel win when the P interior
        # factorizations time-slice one core; the shortened critical path
        # is a property of the program, not the host).
        from capital_tpu.obs import xla_audit

        depth = xla_audit.sequential_depth(fn, Dj, Cj, Bj)
        depth_seq = xla_audit.sequential_depth(seq_fn, Dj, Cj, Bj)
        depth_reduction = depth_seq / depth if depth else 0.0
        Xp, _ = jax.block_until_ready(fn(Dj, Cj, Bj))
        Xs, _ = jax.block_until_ready(seq_fn(Dj, Cj, Bj))
        scale = max(float(jnp.max(jnp.abs(Xs))), 1e-30)
        parity = float(jnp.max(jnp.abs(Xp - Xs))) / scale
        sseq = harness.latency_samples(
            lambda: seq_fn(Dj, Cj, Bj), calls=max(args.iters, 3), warmup=3
        )
        t_seq = sum(sseq) / len(sseq)
        print(f"# impl={seq_impl:<12s} {t_seq / batch * 1e3:9.3f} "
              f"ms/problem  depth={depth_seq}")
        print(f"# impl=partitioned  {t / batch * 1e3:9.3f} ms/problem  "
              f"depth={depth}  (P={partitions}, "
              f"{depth_reduction:.2f}x shallower, parity {parity:.2e})")
        par_extra = {
            "partitions": partitions, "depth": depth,
            "depth_seq": depth_seq,
            "depth_reduction": round(depth_reduction, 3),
            "parity": parity,
            "seq_ms": round(t_seq / batch * 1e3, 4),
        }

    # dense comparison on the same problems, per-problem amortized both
    # sides; the dense batch shrinks when batch·n² won't reasonably fit
    # (the structural point survives — per-problem time is the comparand)
    dense_batch = batch
    dense_bytes = batch * n * n * dtype.itemsize
    if dense_bytes > 2e9:
        dense_batch = max(1, int(2e9 // (n * n * dtype.itemsize)))
    Adj = jax.block_until_ready(
        jnp.asarray(_blocktri_dense(Dn[:dense_batch], Cn[:dense_batch]),
                    dtype))
    Bdj = Bj[:dense_batch].reshape(dense_batch, n, nrhs)
    dense_fn = jax.jit(api.batched("posv", prec, args.small_impl))
    dsamples = harness.latency_samples(
        lambda: dense_fn(Adj, Bdj), calls=max(args.iters, 3), warmup=1
    )
    t_dense = sum(dsamples) / len(dsamples)
    speedup = (t_dense / dense_batch) / (t / batch)
    print(f"# speedup {speedup:.1f}x vs dense posv n={n} "
          f"(dense {t_dense / dense_batch * 1e3:.1f} ms/problem, "
          f"blocktri {t / batch * 1e3:.3f} ms/problem)")

    rec = harness.report(
        "blocktri_tflops", t, flops, dtype, nblocks=nblocks, block=b, n=n,
        batch=batch, nrhs=nrhs, impl=impl, grid=repr(grid),
        speedup=round(speedup, 2),
        dense_ms=round(t_dense / dense_batch * 1e3, 3),
        wall_ms={k: round(v * 1e3, 4)
                 for k, v in harness.percentiles(samples).items()},
        **par_extra,
    )
    if args.min_depth_reduction:
        if impl != "partitioned":
            sys.exit("--min-depth-reduction requires --impl partitioned")
        ptol = _tolerance(dtype)
        if parity > ptol or depth_reduction < args.min_depth_reduction:
            _ledger_append(args, rec, name="blocktri", grid=grid,
                           dtype=dtype,
                           cfg={"op": "posv_blocktri", "impl": impl,
                                "nblocks": nblocks, "block": b})
            if parity > ptol:
                sys.exit(
                    f"partitioned parity gate failed: max|X_par - X_seq| "
                    f"= {parity:.2e} > {ptol:g} vs impl={seq_impl}"
                )
            sys.exit(
                f"depth gate failed: {depth_reduction:.2f}x < "
                f"{args.min_depth_reduction}x "
                f"(seq {depth_seq} trips -> partitioned {depth})"
            )
    if args.min_speedup and speedup < args.min_speedup:
        _ledger_append(args, rec, name="blocktri", grid=grid, dtype=dtype,
                       cfg={"op": "posv_blocktri", "impl": impl,
                            "nblocks": nblocks, "block": b})
        sys.exit(
            f"speedup gate failed: {speedup:.1f}x < {args.min_speedup}x "
            f"vs dense posv at n={n}"
        )
    _ledger_append(args, rec, name="blocktri", grid=grid, dtype=dtype,
                   cfg={"op": "posv_blocktri", "impl": impl,
                        "nblocks": nblocks, "block": b})
    return rec


def _arrowhead_batch(nblocks: int, b: int, s: int, batch: int, nrhs: int,
                     dtype, seed: int = 5):
    """One batch of SPD block-arrowhead systems (the serve posv_arrowhead
    geometry): the _blocktri_batch chain family plus a thin border at
    0.3/√(nblocks·b)·randn — the border couples EVERY chain block, so the
    Schur correction F·T⁻¹·Fᵀ grows with chain length and the coupling
    must shrink with it or the corner S = S₀·S₀ᵀ/s + 5I goes indefinite
    at flagship n (the whole matrix stops being SPD, not a solver bug).
    Returns device arrays plus the f64 numpy masters."""
    import numpy as np

    rng = np.random.default_rng(seed)
    G = rng.standard_normal((batch, nblocks, b, b))
    D = G @ G.transpose(0, 1, 3, 2) / b + 3.0 * np.eye(b)
    C = 0.3 / np.sqrt(b) * rng.standard_normal((batch, nblocks, b, b))
    C[:, 0] = 0.0
    F = 0.3 / np.sqrt(nblocks * b) * rng.standard_normal(
        (batch, nblocks, s, b))
    S0 = rng.standard_normal((batch, s, s))
    S = S0 @ S0.transpose(0, 2, 1) / s + 5.0 * np.eye(s)
    B = rng.standard_normal((batch, nblocks, b, nrhs))
    Bs = rng.standard_normal((batch, s, nrhs))
    dev = tuple(
        jax.block_until_ready(jnp.asarray(x, dtype))
        for x in (D, C, F, S, B, Bs)
    )
    return dev, (D, C, F, S, B, Bs)


def _arrowhead_chain_solve_np(D, C, R):
    """f64 NumPy block-Cholesky chain solve (batch, nblocks, b, r) — an
    independent reference implementation (LAPACK via numpy/scipy, never
    models/blocktri) so the residual gates compare the code under test
    against something it cannot share a bug with."""
    import numpy as np
    from scipy.linalg import solve_triangular

    batch, nblocks, b, _ = D.shape
    L = np.zeros_like(D)
    W = np.zeros_like(C)
    Y = np.zeros_like(R)
    for z in range(batch):
        for i in range(nblocks):
            Di = D[z, i].copy()
            if i:
                # W_i = C_i · L_{i−1}⁻ᵀ  (solve L·Xᵀ = C_iᵀ, transpose)
                W[z, i] = solve_triangular(
                    L[z, i - 1], C[z, i].T, lower=True).T
                Di -= W[z, i] @ W[z, i].T
            L[z, i] = np.linalg.cholesky(Di)
            rhs = R[z, i] - (W[z, i] @ Y[z, i - 1] if i else 0.0)
            Y[z, i] = solve_triangular(L[z, i], rhs, lower=True)
        for i in range(nblocks - 1, -1, -1):
            rhs = Y[z, i].copy()
            if i + 1 < nblocks:
                rhs -= W[z, i + 1].T @ Y[z, i + 1]
            Y[z, i] = solve_triangular(L[z, i], rhs, lower=True, trans="T")
    return Y


def _arrowhead_dense(D, C, F, S):
    """Assemble the f64 numpy arrowhead masters to dense (batch, n, n) —
    NumPy-side for the same reason as _blocktri_dense (the reference must
    never touch models/arrowhead.assemble, itself new this round)."""
    import numpy as np

    A = _blocktri_dense(D, C)
    batch, nblocks, s, b = F.shape
    n_t = nblocks * b
    Bd = F.transpose(0, 2, 1, 3).reshape(batch, s, n_t)
    top = np.concatenate([A, Bd.transpose(0, 2, 1)], axis=2)
    bottom = np.concatenate([Bd, S], axis=2)
    return np.concatenate([top, bottom], axis=1)


def arrowhead(args) -> dict:
    """Bench the block-arrowhead fast path (models/arrowhead.posv) and
    measure its wall-clock speedup against the equal-n dense batched posv
    on the SAME problems assembled dense — the structural
    O(nblocks·b³ + nblocks·b²·s + s³) vs O((nblocks·b + s)³) win the
    round-15 flagship gate pins (docs/PERF.md).  Unlike the blocktri
    driver this one ALWAYS runs its f64 residual gates — both halves of
    the factorization are new (the widened chain solve and the Schur
    completion), so a speedup row must prove its answers every run:
    the solve gate is the whole-matrix backward error computed blockwise
    in f64 (no densification needed), the factor gate reconstructs
    L_S·L_Sᵀ against a Schur complement built from an independent NumPy
    block-Cholesky chain solve."""
    from capital_tpu.models import arrowhead as ah_mod
    from capital_tpu.models import blocktri as bt_mod
    from capital_tpu.serve import api

    import numpy as np

    dtype = jnp.dtype(args.dtype)
    grid = Grid.square(c=1, devices=jax.devices()[:1])
    prec = _precision(args, dtype)
    nblocks, b, s = args.nblocks, args.block, args.border
    batch, nrhs = args.batch, args.nrhs
    n_t = nblocks * b
    n = n_t + s
    impl = args.impl
    if impl == "auto" and jax.default_backend() != "tpu":
        # the blocktri driver's off-TPU honest-wall pin, same rationale
        impl = "xla"
    (Dj, Cj, Fj, Sj, Bj, Bsj), (Dn, Cn, Fn, Sn, Bn, Bsn) = _arrowhead_batch(
        nblocks, b, s, batch, nrhs, dtype)
    partitions = 0
    if impl == "partitioned":
        partitions = bt_mod.resolve_partitions(nblocks, args.partitions)
        inner = "xla" if jax.default_backend() != "tpu" else "auto"
        fn = jax.jit(
            lambda d, c, f, sc, rhs, bs: ah_mod.posv(
                d, c, f, sc, rhs, bs, precision=prec, impl="partitioned",
                partitions=partitions, partition_inner=inner,
            )
        )
    else:
        fn = jax.jit(
            lambda d, c, f, sc, rhs, bs: ah_mod.posv(
                d, c, f, sc, rhs, bs, precision=prec, impl=impl)
        )

    # --- residual gates (always on; see the docstring) ---
    X, Xs, info = jax.block_until_ready(fn(Dj, Cj, Fj, Sj, Bj, Bsj))
    bad = int(jnp.sum(info != 0))
    if bad:
        sys.exit(f"validation failed: {bad} problem(s) report info != 0")
    tol = _tolerance(dtype)
    Xn = np.asarray(X, np.float64)
    Xsn = np.asarray(Xs, np.float64)
    # blockwise residual: chain rows D_i·x_i + C_i·x_{i−1} + C_{i+1}ᵀ·x_{i+1}
    # + F_iᵀ·x_s − b_i, corner rows Σ F_i·x_i + S·x_s − b_s
    Rc = np.einsum("znab,znbk->znak", Dn, Xn) - Bn
    Rc[:, 1:] += np.einsum("znab,znbk->znak", Cn[:, 1:], Xn[:, :-1])
    Rc[:, :-1] += np.einsum("znba,znbk->znak", Cn[:, 1:], Xn[:, 1:])
    Rc += np.einsum("znsb,zsk->znbk", Fn, Xsn)
    Rs = np.einsum("znsb,znbk->zsk", Fn, Xn) + Sn @ Xsn - Bsn
    rhs_n = np.concatenate([Bn.reshape(batch, n_t, nrhs), Bsn], axis=1)
    res = np.concatenate([Rc.reshape(batch, n_t, nrhs), Rs], axis=1)
    solve_resid = max(
        float(np.linalg.norm(res[i]) / np.linalg.norm(rhs_n[i]))
        for i in range(batch)
    )
    _gate("arrowhead_solve_residual", solve_resid, tol)
    # factor gate: L_S·L_Sᵀ vs the f64 reference Schur complement
    # S̃ = S − F·(T⁻¹·Fᵀ) built from the independent NumPy chain solve
    Zb_ref = _arrowhead_chain_solve_np(Dn, Cn, Fn.transpose(0, 1, 3, 2))
    St_ref = Sn - np.einsum("znsb,znbt->zst", Fn, Zb_ref)
    _, _, Ls, _ = jax.block_until_ready(
        jax.jit(lambda d, c, f, sc: ah_mod.schur(
            d, c, f, sc, precision=prec,
            impl="xla" if impl == "partitioned" else impl,
        ))(Dj, Cj, Fj, Sj)
    )
    Lsn = np.asarray(Ls, np.float64)
    factor_resid = max(
        float(np.linalg.norm(Lsn[i] @ Lsn[i].T - St_ref[i])
              / np.linalg.norm(St_ref[i]))
        for i in range(batch)
    )
    _gate("arrowhead_factor_residual", factor_resid, tol)

    # useful flops per system: the widened chain solve (s + nrhs columns
    # through the blocktri count) + the AH::schur / AH::border phases
    flops = batch * (
        nblocks * (b**3 / 3.0 + 3.0 * b**3 + 6.0 * b * b * (s + nrhs))
        + 2.0 * n_t * s * s + s**3 / 3.0
        + 4.0 * n_t * s * nrhs + 2.0 * s * s * nrhs
    )

    if args.latency:
        samples = harness.latency_samples(
            lambda: fn(Dj, Cj, Fj, Sj, Bj, Bsj), calls=args.calls, warmup=3
        )
        pcts = harness.percentiles(samples)
        from capital_tpu.obs.ledger import SCHEMA_VERSION

        rec = {
            "metric": "arrowhead_latency",
            "schema_version": SCHEMA_VERSION,
            "value": round(1.0 / pcts["p99"], 3),
            "unit": "batch/s",
            "seconds": pcts["p99"],
            "wall_ms": {k: round(v * 1e3, 4) for k, v in pcts.items()},
            "dtype": str(dtype),
            "device": jax.devices()[0].device_kind,
            "platform": jax.default_backend(),
            "nblocks": nblocks, "block": b, "border": s, "n": n,
            "batch": batch, "nrhs": nrhs, "impl": impl, "calls": args.calls,
        }
        import json as _json

        print(_json.dumps(rec))
        _ledger_append(args, rec, name="arrowhead_latency", grid=grid,
                       dtype=dtype,
                       cfg={"op": "posv_arrowhead", "impl": impl})
        return rec

    samples = harness.latency_samples(
        lambda: fn(Dj, Cj, Fj, Sj, Bj, Bsj), calls=max(args.iters, 3),
        warmup=3
    )
    t = sum(samples) / len(samples)

    # dense comparison on the same problems, per-problem amortized both
    # sides, batch shrunk when batch·n² won't fit (the blocktri policy)
    dense_batch = batch
    dense_bytes = batch * n * n * dtype.itemsize
    if dense_bytes > 2e9:
        dense_batch = max(1, int(2e9 // (n * n * dtype.itemsize)))
    Adj = jax.block_until_ready(jnp.asarray(
        _arrowhead_dense(Dn[:dense_batch], Cn[:dense_batch],
                         Fn[:dense_batch], Sn[:dense_batch]), dtype))
    Bdj = jax.block_until_ready(
        jnp.asarray(rhs_n[:dense_batch], dtype))
    dense_fn = jax.jit(api.batched("posv", prec, args.small_impl))
    dsamples = harness.latency_samples(
        lambda: dense_fn(Adj, Bdj), calls=max(args.iters, 3), warmup=1
    )
    t_dense = sum(dsamples) / len(dsamples)
    speedup = (t_dense / dense_batch) / (t / batch)
    print(f"# speedup {speedup:.1f}x vs dense posv n={n} "
          f"(dense {t_dense / dense_batch * 1e3:.1f} ms/problem, "
          f"arrowhead {t / batch * 1e3:.3f} ms/problem)")

    rec = harness.report(
        "arrowhead_tflops", t, flops, dtype, nblocks=nblocks, block=b,
        border=s, n=n, batch=batch, nrhs=nrhs, impl=impl, grid=repr(grid),
        speedup=round(speedup, 2),
        arrow_ms=round(t / batch * 1e3, 4),
        dense_ms=round(t_dense / dense_batch * 1e3, 3),
        factor_resid=factor_resid, solve_resid=solve_resid,
        wall_ms={k: round(v * 1e3, 4)
                 for k, v in harness.percentiles(samples).items()},
        **({"partitions": partitions} if impl == "partitioned" else {}),
    )
    if args.min_speedup and speedup < args.min_speedup:
        _ledger_append(args, rec, name="arrowhead", grid=grid, dtype=dtype,
                       cfg={"op": "posv_arrowhead", "impl": impl,
                            "nblocks": nblocks, "block": b, "border": s})
        sys.exit(
            f"speedup gate failed: {speedup:.1f}x < {args.min_speedup}x "
            f"vs dense posv at n={n}"
        )
    _ledger_append(args, rec, name="arrowhead", grid=grid, dtype=dtype,
                   cfg={"op": "posv_arrowhead", "impl": impl,
                        "nblocks": nblocks, "block": b, "border": s})
    return rec


def update(args) -> dict:
    """Bench online factor maintenance (ops/update_small): measured rank-k
    chol_update against the REFACTOR-FROM-RESIDENT-STATE baseline — the
    cache-less server's only alternative on the factor-residency wire
    protocol (docs/SERVING.md: clients ship the rank-k panel V, never A,
    so serving the same request without a resident factor means
    reassembling S = RᵀR + VVᵀ and running a fresh potrf).  That framing
    is load-bearing for the speedup gate and stated with the number
    everywhere it lands (docs/PERF.md round 12): against a
    client-shipped-A refactor (one potrf, no reassembly) the rank-k
    update's algorithmic edge is k/n-bounded and this 1-core CPU rig
    measures ~3x at n=1024, k=16 — the protocol baseline is the honest
    serving comparison, not the flattering one.

    --validate adds f64-NumPy-side residual gates (the bench-blocktri
    discipline): ‖R₊ᵀR₊ − (A + VVᵀ)‖_F/‖·‖_F for the update, the same
    for a downdate back to A, and zero info flags on both sweeps.

    --min-hit-rate additionally runs the 50-request serve smoke: mixed
    chol_update / posv_cached traffic over a handful of tokens through a
    real SolveEngine, gating residency hit-rate >= the floor AND zero
    steady-state executable recompiles (residency is host-side state, so
    factor traffic must never recompile)."""
    from capital_tpu.ops import update_small

    dtype = jnp.dtype(args.dtype)
    grid = Grid.square(c=1, devices=jax.devices()[:1])
    prec = _precision(args, dtype)
    n, k, batch = args.n, args.k, args.batch
    if k > n:
        sys.exit(f"update: rank --k {k} exceeds --n {n}")
    impl = args.impl  # auto/pallas/xla, the blocktri flag; update_small
    # resolves 'auto' per shape (pallas only inside the small-N envelope)

    import numpy as np

    rng = np.random.default_rng(11)
    X = rng.standard_normal((batch, n, n))
    A = (X @ X.transpose(0, 2, 1) / n + 3.0 * np.eye(n)).astype(np.float64)
    R0 = np.linalg.cholesky(A).transpose(0, 2, 1)
    V0 = (0.1 / np.sqrt(n)) * rng.standard_normal((batch, n, k))
    Rj = jax.block_until_ready(jnp.asarray(R0, dtype))
    Vj = jax.block_until_ready(jnp.asarray(V0, dtype))

    fn = jax.jit(lambda r, v: update_small.chol_update(
        r, v, precision=prec, impl=impl))
    dn = jax.jit(lambda r, v: update_small.chol_downdate(
        r, v, precision=prec, impl=impl))

    if args.validate:
        R1, info1 = jax.block_until_ready(fn(Rj, Vj))
        bad = int(jnp.sum(info1 != 0))
        if bad:
            sys.exit(f"validation failed: {bad} update(s) report info != 0")
        R1n = np.asarray(R1, np.float64)
        Ap = A + V0 @ V0.transpose(0, 2, 1)
        tol = _tolerance(dtype)
        worst = max(
            float(np.linalg.norm(R1n[i].T @ R1n[i] - Ap[i])
                  / np.linalg.norm(Ap[i]))
            for i in range(batch)
        )
        _gate("update_residual", worst, tol)
        R2, info2 = jax.block_until_ready(dn(R1, Vj))
        if int(jnp.sum(info2 != 0)):
            sys.exit("validation failed: downdate of a just-updated factor "
                     "reports info != 0")
        R2n = np.asarray(R2, np.float64)
        worst = max(
            float(np.linalg.norm(R2n[i].T @ R2n[i] - A[i])
                  / np.linalg.norm(A[i]))
            for i in range(batch)
        )
        _gate("downdate_residual", worst, tol)

    # the baseline the wire protocol forces on a cache-less server:
    # reassemble S = RᵀR + VVᵀ (the operand only the factor encodes) and
    # refactor from scratch — measured with the same per-call protocol
    from capital_tpu.ops import lapack as lapack_mod

    def refactor(r, v):
        s = (jnp.einsum("bji,bjk->bik", r, r, precision=prec)
             + jnp.einsum("bik,bjk->bij", v, v, precision=prec))
        return jax.vmap(
            lambda m: lapack_mod.potrf(m, uplo="U", with_info=True))(s)

    base_fn = jax.jit(refactor)
    calls = max(args.iters, 3)
    samples = harness.latency_samples(
        lambda: fn(Rj, Vj), calls=calls, warmup=3)
    bsamples = harness.latency_samples(
        lambda: base_fn(Rj, Vj), calls=calls, warmup=1)
    # min-of-samples on BOTH sides: the speedup gate compares algorithms,
    # not scheduler noise, and best-observed latency is the stable
    # estimator of that on a shared CPU rig (mean would let one preempted
    # baseline call flip the gate either way).
    t = min(samples)
    t_base = min(bsamples)
    speedup = t_base / t
    print(f"# speedup {speedup:.1f}x vs refactor-from-resident-state at "
          f"n={n} k={k} (refactor {t_base / batch * 1e3:.2f} ms/problem, "
          f"update {t / batch * 1e3:.3f} ms/problem)")

    smoke = None
    if args.min_hit_rate:
        smoke = _update_serve_smoke(min(n, 256), min(k, 16), dtype,
                                    ledger=args.ledger)
        print(f"# serve smoke: {smoke['requests']} requests, residency "
              f"hit_rate {smoke['hit_rate']:.3f}, "
              f"{smoke['recompiles']} steady-state recompiles")

    # useful flops (textbook ~2kn² per problem), not the masked-sweep
    # executed count — comparable against the baseline's ~2n³ reassembly
    flops = batch * 2.0 * k * n * n
    rec = harness.report(
        "update_speedup", t, flops, dtype, n=n, k=k, batch=batch,
        impl=impl, grid=repr(grid), speedup=round(speedup, 2),
        refactor_ms=round(t_base / batch * 1e3, 3),
        update_ms=round(t / batch * 1e3, 4),
        wall_ms={kk: round(v * 1e3, 4)
                 for kk, v in harness.percentiles(samples).items()},
        **({"serve_smoke": smoke} if smoke else {}),
    )
    cfg = {"op": "chol_update", "impl": impl, "n": n, "k": k}
    gates = []
    if args.min_speedup and speedup < args.min_speedup:
        gates.append(
            f"speedup gate failed: {speedup:.1f}x < {args.min_speedup}x vs "
            f"refactor-from-resident-state at n={n} k={k}"
        )
    if smoke and smoke["hit_rate"] < args.min_hit_rate:
        gates.append(
            f"residency gate failed: hit_rate {smoke['hit_rate']:.3f} < "
            f"{args.min_hit_rate}"
        )
    if smoke and smoke["recompiles"]:
        gates.append(
            f"zero-recompile gate failed: {smoke['recompiles']} executable "
            "compiles during steady-state factor traffic"
        )
    _ledger_append(args, rec, name="update", grid=grid, dtype=dtype, cfg=cfg)
    if gates:
        sys.exit("; ".join(gates))
    return rec


def _update_serve_smoke(n: int, k: int, dtype, ledger=None) -> dict:
    """The 50-request mixed-traffic residency smoke (bench-update gate):
    seed a few tokens through posv_cached misses, then drive
    chol_update / posv_cached hits against them through a real
    SolveEngine.  Returns the delta counters the caller gates on —
    hit_rate over THIS traffic (not engine lifetime) and executable
    compiles after the one-time per-bucket warmup.  When `ledger` is
    given, also appends the engine's serve:request_stats record (carrying
    the LIFETIME factor_cache counter block, warmup lookups included) so
    ``obs serve-report --min-residency-hit-rate`` has a record to gate."""
    import numpy as np

    from capital_tpu.serve.engine import ServeConfig, SolveEngine

    rng = np.random.default_rng(13)
    cfg = ServeConfig(buckets=(n,), rows_buckets=(4 * n,),
                      nrhs_buckets=(min(4, k), k), max_batch=2,
                      max_delay_s=0.0, oversize="reject")
    eng = SolveEngine(cfg=cfg)
    X = rng.standard_normal((n, n))
    A = (X @ X.T / n + 3.0 * np.eye(n)).astype(dtype)
    B = rng.standard_normal((n, min(4, k))).astype(dtype)
    V = ((0.05 / np.sqrt(n))
         * rng.standard_normal((n, k))).astype(dtype)
    # warm every program the mix touches (the per-bucket one-time cost);
    # everything after this line must hit the executable cache
    for i in range(2):
        assert eng.solve("posv_cached", A, B,
                         factor_token=f"warm{i}").ok
    assert eng.solve("chol_update", V, factor_token="warm0").ok
    assert eng.solve("posv_cached", A, B, factor_token="warm1").ok
    c0 = eng.cache_stats()["compiles"]
    f0 = eng.factor_stats()
    tokens = [f"tok{i}" for i in range(4)]
    requests = 0
    for tok in tokens:  # 4 seeding misses
        assert eng.solve("posv_cached", A, B, factor_token=tok).ok
        requests += 1
    while requests < 50:  # 46 resident hits, mixed ops
        tok = tokens[requests % len(tokens)]
        if requests % 3 == 0:
            r = eng.solve("chol_update", V, factor_token=tok)
        else:
            r = eng.solve("posv_cached", A, B, factor_token=tok)
        assert r.ok, r.error
        requests += 1
    f1 = eng.factor_stats()
    hits = f1["hits"] - f0["hits"]
    lookups = hits + f1["misses"] - f0["misses"]
    if ledger:
        eng.emit_stats(ledger)
    return {
        "requests": requests,
        "hit_rate": round(hits / lookups, 4),
        "recompiles": eng.cache_stats()["compiles"] - c0,
    }


def session(args) -> dict:
    """Bench streaming state-space sessions (serve/sessions.py +
    models/blocktri.extend/contract): the steady-state sliding-window
    cycle — append --slide new blocks onto the resident chain factor,
    contract the --slide oldest away — measured incrementally against
    REFACTOR-FROM-SCRATCH of the slid window, the only alternative a
    cache-less server has (docs/SERVING.md 'Streaming sessions': the
    wire carries only the new blocks, so serving without a resident
    factor means re-factoring all nblocks).  contract() is a pure slice
    (zero flops), so the incremental cycle costs one extend(slide) and
    the structural win is ~nblocks/slide — the round-19 flagship gate:
    >= 5x at nblocks=64, block=128, slide=8.

    The f64-NumPy residual gates are always-on (the bench-arrowhead
    discipline): the slid-window factor — resident chain extended then
    contracted, exactly the serve composition — must solve the
    MARGINALIZED window matrix (head D ← L_k·L_kᵀ, head coupling zero;
    models/blocktri.contract docstring) to working-precision tolerance,
    both solve and factor-reconstruction residuals.  The replay pin
    holds the docstring's bitwise claim: re-extending the truncated
    chain from the retained carry reproduces the contracted factor's
    trailing blocks exactly (max |Δ| == 0).

    --min-hit-rate additionally runs the 50-request mixed session serve
    workload (bursty arrivals, long-tail lifetimes, sliding append/
    contract/solve cycles over all three accuracy tiers) through a real
    SolveEngine + SessionManager, gating post-warmup session hit-rate
    >= the floor AND zero steady-state executable recompiles (session
    residency is host-side state keyed by session id — session churn
    must never trigger a compile), and emitting the serve:session_stats
    ledger record ``obs serve-report --min-session-hit-rate /
    --max-reseeds`` re-gates."""
    from capital_tpu.models import blocktri as bt_mod

    dtype = jnp.dtype(args.dtype)
    grid = Grid.square(c=1, devices=jax.devices()[:1])
    prec = _precision(args, dtype)
    nblocks, b, batch, nrhs = args.nblocks, args.block, args.batch, args.nrhs
    slide = args.slide
    if not 0 < slide < nblocks:
        sys.exit(f"session: --slide {slide} must be in (0, --nblocks "
                 f"{nblocks})")
    impl = args.impl
    if impl == "auto" and jax.default_backend() != "tpu":
        # the bench-blocktri honest-wall pin: off-TPU 'auto' is the xla
        # scan, never the pallas interpreter
        impl = "xla"

    import numpy as np

    # nblocks + slide chain blocks: the first nblocks seed the resident
    # window, the last slide are the streamed-in extension (its leading
    # coupling C[:, nblocks] is LIVE — it ties the new blocks to the old
    # window tail, the session_append contract)
    (Dj, Cj, _), (Dn, Cn, _) = _blocktri_batch(nblocks + slide, b, batch,
                                               nrhs, dtype, seed=7)
    ext_fn = jax.jit(lambda d, c, carry: bt_mod.extend(
        d, c, carry, precision=prec, impl=impl))
    fac_fn = jax.jit(lambda d, c: bt_mod.factor(
        d, c, precision=prec, impl=impl))

    L0, Wt0, info0 = jax.block_until_ready(
        fac_fn(Dj[:, :nblocks], Cj[:, :nblocks]))
    if int(jnp.sum(info0 != 0)):
        sys.exit("session: seed window factorization reports info != 0")
    carry = L0[:, -1]
    Dext = jax.block_until_ready(Dj[:, nblocks:])
    Cext = jax.block_until_ready(Cj[:, nblocks:])

    calls = max(args.iters, 3)
    # incremental side: ONE extend(slide) per cycle — contract is a pure
    # slice with no device work, so it contributes nothing to time
    samples = harness.latency_samples(
        lambda: ext_fn(Dext, Cext, carry), calls=calls, warmup=3)
    # baseline: refactor the slid nblocks-window from scratch (factor()
    # zeroes the head coupling itself, so the operand slice is exact)
    bsamples = harness.latency_samples(
        lambda: fac_fn(Dj[:, slide:], Cj[:, slide:]), calls=calls,
        warmup=1)
    # min-of-samples both sides: algorithms, not scheduler noise
    # (the bench-update estimator rationale)
    t = min(samples)
    t_base = min(bsamples)
    speedup = t_base / t
    print(f"# speedup {speedup:.1f}x vs refactor-from-scratch at "
          f"nblocks={nblocks} b={b} slide={slide} "
          f"(refactor {t_base / batch * 1e3:.2f} ms/problem, "
          f"append {t / batch * 1e3:.3f} ms/problem)")

    # ---- always-on correctness gates (f64 NumPy side) ----------------------
    Lx, Wtx, infox = jax.block_until_ready(ext_fn(Dext, Cext, carry))
    if int(jnp.sum(infox != 0)):
        sys.exit("session: extend of the streamed blocks reports info != 0")
    Lfull = jnp.concatenate([L0, Lx], axis=1)
    Wtfull = jnp.concatenate([Wt0, Wtx], axis=1)
    Lc, Wtc = bt_mod.contract(Lfull, Wtfull, slide)
    # replay pin (the contract docstring's bitwise claim): re-extending
    # the truncated chain — head coupling LIVE, carried from the retained
    # L_{slide-1} — reproduces every factor block the contract kept, bit
    # for bit
    Lr, Wtr, infor = jax.block_until_ready(
        ext_fn(Dj[:, slide:], Cj[:, slide:], Lfull[:, slide - 1]))
    if int(jnp.sum(infor != 0)):
        sys.exit("session: replay refactor reports info != 0")
    replay_delta = max(
        float(jnp.max(jnp.abs(Lr - Lc))),
        float(jnp.max(jnp.abs(Wtr - Wtc))),
    )
    print(f"# contract replay pin: max |Δ| = {replay_delta:g} "
          f"(extend-replay of the truncated chain vs contracted factor)")
    if replay_delta != 0.0:
        sys.exit(
            f"contract replay pin failed: trailing factor blocks differ "
            f"from the truncated-chain refactor by {replay_delta:g} "
            "(contract must be a pure slice)"
        )
    # the MARGINALIZED window matrix the contracted factor answers for
    # (f64 masters; head diagonal from the f64 cast of the factor block)
    Lcn = np.asarray(Lc, np.float64)
    Wcn = np.asarray(Wtc, np.float64).transpose(0, 1, 3, 2)  # W_i
    Dw = Dn[:, slide:].copy()
    Dw[:, 0] = Lcn[:, 0] @ Lcn[:, 0].transpose(0, 2, 1)
    Cw = Cn[:, slide:].copy()
    Cw[:, 0] = 0.0
    Ad = _blocktri_dense(Dw, Cw)
    rng = np.random.default_rng(19)
    Bn = rng.standard_normal((batch, nblocks, b, nrhs))
    Bj = jax.block_until_ready(jnp.asarray(Bn, dtype))
    X = jax.block_until_ready(jax.jit(
        lambda l, w, rhs: bt_mod.solve(l, w, rhs, precision=prec,
                                       impl=impl))(Lc, Wtc, Bj))
    n = nblocks * b
    Xn = np.asarray(X, np.float64).reshape(batch, n, nrhs)
    Bd = Bn.reshape(batch, n, nrhs)
    tol = _tolerance(dtype)
    worst = max(
        float(np.linalg.norm(Ad[i] @ Xn[i] - Bd[i])
              / np.linalg.norm(Bd[i]))
        for i in range(batch)
    )
    _gate("session_solve_residual", worst, tol)
    # factor reconstruction residual of the contracted chain vs the
    # marginalized window (blockwise, the bench-blocktri reconstruction)
    R = np.zeros_like(Ad)
    for i in range(nblocks):
        sl = slice(i * b, (i + 1) * b)
        R[:, sl, sl] = Lcn[:, i] @ Lcn[:, i].transpose(0, 2, 1)
        if i:
            up = slice((i - 1) * b, i * b)
            R[:, sl, sl] += Wcn[:, i] @ Wcn[:, i].transpose(0, 2, 1)
            blk = Wcn[:, i] @ Lcn[:, i - 1].transpose(0, 2, 1)
            R[:, sl, up] = blk
            R[:, up, sl] = blk.transpose(0, 2, 1)
    _gate(
        "session_factor_residual",
        float(np.linalg.norm(R - Ad) / np.linalg.norm(Ad)),
        tol,
    )

    smoke = None
    if args.min_hit_rate:
        smoke = _session_serve_workload(min(b, 16), dtype,
                                        ledger=args.ledger)
        print(f"# serve workload: {smoke['requests']} requests over "
              f"{smoke['sessions']} sessions, session hit_rate "
              f"{smoke['hit_rate']:.3f}, {smoke['reseeds']} reseeds, "
              f"{smoke['recompiles']} steady-state recompiles")

    # useful flops of the incremental side: extend(slide) chain work
    flops = batch * slide * (b**3 / 3.0 + 3.0 * b**3)
    rec = harness.report(
        "session_speedup", t, flops, dtype, nblocks=nblocks, block=b,
        slide=slide, batch=batch, nrhs=nrhs, impl=impl, grid=repr(grid),
        speedup=round(speedup, 2),
        refactor_ms=round(t_base / batch * 1e3, 3),
        append_ms=round(t / batch * 1e3, 4),
        wall_ms={k: round(v * 1e3, 4)
                 for k, v in harness.percentiles(samples).items()},
        **({"serve_workload": smoke} if smoke else {}),
    )
    cfg = {"op": "session_append", "impl": impl, "nblocks": nblocks,
           "block": b, "slide": slide}
    gates = []
    if args.min_speedup and speedup < args.min_speedup:
        gates.append(
            f"speedup gate failed: {speedup:.1f}x < {args.min_speedup}x "
            f"vs refactor-from-scratch at nblocks={nblocks} b={b} "
            f"slide={slide}"
        )
    if smoke and smoke["hit_rate"] < args.min_hit_rate:
        gates.append(
            f"session residency gate failed: hit_rate "
            f"{smoke['hit_rate']:.3f} < {args.min_hit_rate}"
        )
    if smoke and smoke["recompiles"]:
        gates.append(
            f"zero-recompile gate failed: {smoke['recompiles']} executable "
            "compiles during steady-state session traffic"
        )
    _ledger_append(args, rec, name="session", grid=grid, dtype=dtype,
                   cfg=cfg)
    if gates:
        sys.exit("; ".join(gates))
    return rec


def _session_serve_workload(b: int, dtype, ledger=None) -> dict:
    """The 50-request mixed session workload (bench-session gate): bursty
    session arrivals (seeded RNG, 1-3 sessions per burst), long-tail
    lifetimes (geometric cycle counts — most sessions die young, a few
    live many sliding-window cycles), each cycle one append(slide) +
    contract(slide) + solve at a mixed accuracy tier.  Returns the delta
    counters the caller gates on — session hit-rate over THIS traffic and
    executable compiles after the one-time per-bucket warmup — and, when
    `ledger` is given, appends the manager's serve:session_stats record
    plus the engine's serve:request_stats record so ``obs serve-report
    --min-session-hit-rate / --max-reseeds`` has records to gate."""
    import numpy as np

    from capital_tpu.serve import sessions as sessions_mod
    from capital_tpu.serve.engine import ServeConfig, SolveEngine

    rng = np.random.default_rng(17)
    nb_w, nb_s, nrhs = 8, 4, 2  # window blocks, slide blocks, RHS cols
    cfg = ServeConfig(nblocks_buckets=(nb_s, nb_w), block_buckets=(b,),
                      nrhs_buckets=(nrhs,), max_batch=2, max_delay_s=0.0,
                      oversize="reject")
    eng = SolveEngine(cfg=cfg)
    mgr = sessions_mod.SessionManager(eng)

    def chain(k):
        G = rng.standard_normal((k, b, b))
        D = (G @ G.transpose(0, 2, 1) / b + 3.0 * np.eye(b)).astype(dtype)
        C = (0.3 / np.sqrt(b)
             * rng.standard_normal((k, b, b))).astype(dtype)
        return D, C

    def rhs():
        return rng.standard_normal((nb_w, b, nrhs)).astype(dtype)

    # warm every program the mix touches (open@nb_w, append@nb_s, solve
    # at all three tiers); everything after this must hit the executable
    # cache — session residency is host-side state, so session churn must
    # never compile
    D, C = chain(nb_w)
    assert mgr.open("warm", D, C).ok
    Da, Ca = chain(nb_s)
    assert mgr.append("warm", Da, Ca).ok
    assert mgr.contract("warm", nb_s).ok
    for tier in ("balanced", "fast", "guaranteed"):
        r = mgr.solve("warm", rhs(), accuracy_tier=tier)
        assert r.ok, r.error
    assert mgr.close("warm").ok
    c0 = eng.cache_stats()["compiles"]
    h0, m0 = mgr.hits, mgr.misses

    tiers = ("balanced", "balanced", "balanced", "fast", "guaranteed")
    active: list[list] = []
    sid_n = 0
    requests = 0
    while requests < 50:
        if not active or (len(active) < 6 and rng.random() < 0.3):
            # burst arrival: 1-3 sessions open back to back
            for _ in range(int(rng.integers(1, 4))):
                sid = f"s{sid_n}"
                sid_n += 1
                D, C = chain(nb_w)
                assert mgr.open(sid, D, C).ok
                requests += 1
                # long-tail lifetime in sliding-window cycles
                active.append([sid, 1 + int(rng.geometric(0.35))])
        i = int(rng.integers(len(active)))
        sid = active[i][0]
        Da, Ca = chain(nb_s)
        assert mgr.append(sid, Da, Ca).ok
        assert mgr.contract(sid, nb_s).ok
        r = mgr.solve(sid, rhs(),
                      accuracy_tier=tiers[int(rng.integers(len(tiers)))])
        assert r.ok, r.error
        requests += 3
        active[i][1] -= 1
        if active[i][1] <= 0:
            assert mgr.close(active.pop(i)[0]).ok
            requests += 1
    for sid, _ in active:
        assert mgr.close(sid).ok
    recompiles = eng.cache_stats()["compiles"] - c0
    if ledger:
        mgr.emit_session_stats(ledger)
        eng.emit_stats(ledger)
    hits = mgr.hits - h0
    lookups = hits + mgr.misses - m0
    st = mgr.stats()
    return {
        "requests": requests,
        "sessions": sid_n,
        "hit_rate": round(hits / lookups, 4) if lookups else 1.0,
        "reseeds": st["reseeds"],
        "recompiles": recompiles,
    }


def refine(args) -> dict:
    """Bench mixed-precision iterative refinement (robust/refine + the
    serve accuracy tiers): the guaranteed-tier posv program — factor one
    precision down, Wilkinson residual/correction sweeps at the request
    precision — against the straight request-dtype factor, at matched
    residual on cond ~1e5 masters.

    The --min-speedup gate is on the FACTOR PHASE (potrf at the tier's
    factor dtype vs potrf at the request dtype): that ratio is where the
    mixed-precision advantage lives and what scales with the rig's
    narrow:wide throughput gap — this 1-core CPU's f32:f64 LAPACK gap
    measures ~1.9x at n=1024, a TPU MXU's bf16:f32 gap is ~4-8x and its
    f32-vs-emulated-f64 gap far larger.  End-to-end guaranteed-vs-
    balanced latency is measured and REPORTED UNGATED in the same record
    (`end_to_end_speedup`): on this rig it lands below 1.0 — the fused
    LAPACK f64 posv baseline sits within that same ~1.9x of the f32
    factor, while every sweep pays a skinny-RHS triangular solve that
    XLA's CPU backend runs at ~2.4 GFLOP/s — and a bench that hid that
    behind the phase number would be lying about the serving economics.
    The accuracy half is gated both ways: --max-resid-ratio bounds the
    refined normwise backward error as a multiple of the straight wide
    factor's (round-14 gate: 10; measured ~0.9-1.8x, i.e. genuinely
    f64-grade answers), and --validate adds the absolute residual gate
    plus all-converged / zero-info checks.

    Also rides: the TSQR escalation probe — a cond 1e12 tall-skinny
    factor through recovery.tsqr_escalate, --validate gating ortho
    <= 1e-13, the regime where the gram-forming CQR family cannot
    recover (docs/ROBUSTNESS.md escalation ladder) — and the three-tier
    serve smoke: mixed balanced/fast/guaranteed traffic through a real
    SolveEngine with any steady-state recompile failing the run
    (precision is a bucket dimension, never a recompile), emitting the
    serve:request_stats record whose refine block
    ``obs serve-report --max-refine-iters/--min-converged-frac``
    re-gates."""
    from capital_tpu.ops import lapack as lapack_mod
    from capital_tpu.robust import recovery
    from capital_tpu.robust import refine as refine_mod
    from capital_tpu.serve import api

    # the guaranteed tier's correction dtype and the TSQR escalation
    # dtype are both f64 for the flagship request dtypes; without x64 the
    # whole bench would silently measure f32-vs-f32
    jax.config.update("jax_enable_x64", True)
    dtype = jnp.dtype(args.dtype)
    grid = Grid.square(c=1, devices=jax.devices()[:1])
    n, nrhs, batch = args.n, args.nrhs, args.batch
    tp = refine_mod.plan("guaranteed", dtype)
    fd, cd = jnp.dtype(tp.factor_dtype), jnp.dtype(tp.correction_dtype)

    import numpy as np

    # cond ~1e5 SPD masters (f64 NumPy side): enough to make the narrow
    # factor's raw answer visibly wrong (f32 backward error ~cond·u32)
    # so convergence is a measured property, not a well-conditioned gift
    rng = np.random.default_rng(17)
    eigs = np.logspace(0.0, -5.0, n)
    A = np.empty((batch, n, n))
    for i in range(batch):
        Q, _ = np.linalg.qr(rng.standard_normal((n, n)))
        A[i] = (Q * eigs) @ Q.T
    A = 0.5 * (A + A.transpose(0, 2, 1))
    Bm = rng.standard_normal((batch, n, nrhs))
    Aj = jax.block_until_ready(jnp.asarray(A, dtype))
    Bj = jax.block_until_ready(jnp.asarray(Bm, dtype))

    prec = _precision(args, dtype)
    base_fn = jax.jit(api.batched("posv", precision=prec, impl="vmap"))
    ref_fn = jax.jit(api.batched("posv", precision=prec, impl="vmap",
                                 tier="guaranteed"))
    calls = max(args.iters, 3)

    # --- factor phase: the gated number -------------------------------
    potrf_fn = jax.jit(jax.vmap(
        lambda m: lapack_mod.potrf(m, uplo="U", with_info=True)))
    An = jax.block_until_ready(Aj.astype(fd))
    ws = harness.latency_samples(lambda: potrf_fn(Aj), calls=calls, warmup=1)
    ns = harness.latency_samples(lambda: potrf_fn(An), calls=calls, warmup=1)
    t_wide, t_narrow = min(ws), min(ns)
    factor_speedup = t_wide / t_narrow
    print(f"# factor-phase speedup {factor_speedup:.2f}x "
          f"({fd} potrf {t_narrow * 1e3:.1f} ms vs {dtype} potrf "
          f"{t_wide * 1e3:.1f} ms, n={n} batch={batch})")

    # --- end to end: measured, reported, ungated ----------------------
    bs = harness.latency_samples(lambda: base_fn(Aj, Bj),
                                 calls=calls, warmup=2)
    rs = harness.latency_samples(lambda: ref_fn(Aj, Bj),
                                 calls=calls, warmup=2)
    t_base, t_ref = min(bs), min(rs)
    end_to_end = t_base / t_ref
    print(f"# end-to-end guaranteed {t_ref * 1e3:.1f} ms vs balanced "
          f"{t_base * 1e3:.1f} ms ({end_to_end:.2f}x, ungated — the "
          f"sweeps price in at this backend's potrs throughput)")

    # --- matched residual (f64 NumPy side, the bench-blocktri posture) -
    Xb, info_b = jax.block_until_ready(base_fn(Aj, Bj))
    Xr, it_r, conv_r, _resid, info_r = jax.block_until_ready(
        ref_fn(Aj, Bj))
    iters = int(jnp.max(it_r))

    def _bwerr(Xn):
        worst = 0.0
        for i in range(batch):
            r = A[i] @ Xn[i] - Bm[i]
            denom = (np.linalg.norm(A[i]) * np.linalg.norm(Xn[i])
                     + np.linalg.norm(Bm[i]) + np.finfo(np.float64).tiny)
            worst = max(worst, float(np.linalg.norm(r) / denom))
        return worst

    err_base = _bwerr(np.asarray(Xb, np.float64))
    err_ref = _bwerr(np.asarray(Xr, np.float64))
    resid_ratio = err_ref / max(err_base, np.finfo(np.float64).tiny)
    print(f"# matched residual: refined {err_ref:.3e} vs wide-factor "
          f"{err_base:.3e} (ratio {resid_ratio:.2f}) after {iters} "
          f"sweep(s)")

    # --- TSQR escalation probe: cond 1e12, past the CQR-family envelope
    mt, kt = 2048, 64
    Ut, _ = np.linalg.qr(rng.standard_normal((mt, kt)))
    Vt, _ = np.linalg.qr(rng.standard_normal((kt, kt)))
    At = (Ut * np.logspace(0.0, -12.0, kt)) @ Vt.T
    _Qt, _Rt, ortho = recovery.tsqr_escalate(
        jnp.asarray(At, jnp.float32), precision=prec)
    tsqr_ortho = float(ortho)
    print(f"# tsqr escalation: ortho {tsqr_ortho:.3e} at cond 1e12 "
          f"(m={mt} k={kt}, escalation dtype "
          f"{recovery.escalation_dtype(jnp.float32)})")

    if args.validate:
        # conv_r ships as the executor's stacked extras (integer 0/1)
        nonconv = int(conv_r.size) - int(jnp.count_nonzero(conv_r))
        if nonconv:
            sys.exit(f"validation failed: {nonconv} guaranteed-tier "
                     "problem(s) did not converge")
        if int(jnp.sum(info_b != 0)) or int(jnp.sum(info_r != 0)):
            sys.exit("validation failed: nonzero factorization info flag")
        _gate("refine_residual", err_ref, _tolerance(dtype))
        _gate("tsqr_ortho", tsqr_ortho, 1e-13)

    smoke = _refine_serve_smoke(min(n, 256), min(nrhs, 4), dtype,
                                ledger=args.ledger)
    print(f"# serve smoke: {smoke['requests']} mixed-tier requests, "
          f"{smoke['recompiles']} steady-state recompiles")

    # useful flops of one guaranteed batch: the narrow factor plus
    # (X0 + iters) solve/residual passes — comparable to the baseline's
    # straight n³/3 factor
    flops = batch * (n ** 3 / 3.0 + (iters + 1) * 4.0 * n * n * nrhs)
    rec = harness.report(
        "refine_speedup", t_ref, flops, dtype, n=n, nrhs=nrhs,
        batch=batch, grid=repr(grid),
        factor_dtype=str(fd), correction_dtype=str(cd),
        speedup=round(factor_speedup, 2),
        factor_wide_ms=round(t_wide * 1e3, 2),
        factor_narrow_ms=round(t_narrow * 1e3, 2),
        end_to_end_speedup=round(end_to_end, 3),
        baseline_ms=round(t_base * 1e3, 2),
        refined_ms=round(t_ref * 1e3, 2),
        resid_ratio=round(resid_ratio, 3),
        iters=iters,
        tsqr_ortho=tsqr_ortho,
        wall_ms={kk: round(v * 1e3, 3)
                 for kk, v in harness.percentiles(rs).items()},
        serve_smoke=smoke,
    )
    cfg = {"op": "posv", "tier": "guaranteed", "n": n, "nrhs": nrhs,
           "factor_dtype": str(fd), "correction_dtype": str(cd)}
    gates = []
    if args.min_speedup and factor_speedup < args.min_speedup:
        gates.append(
            f"factor-phase speedup gate failed: {factor_speedup:.2f}x < "
            f"{args.min_speedup}x ({fd} vs {dtype} potrf at n={n})"
        )
    if args.max_resid_ratio and resid_ratio > args.max_resid_ratio:
        gates.append(
            f"matched-residual gate failed: refined backward error is "
            f"{resid_ratio:.2f}x the wide factor's > "
            f"{args.max_resid_ratio}x"
        )
    if smoke["recompiles"]:
        gates.append(
            f"zero-recompile gate failed: {smoke['recompiles']} "
            "executable compiles during steady-state mixed-tier traffic"
        )
    _ledger_append(args, rec, name="refine", grid=grid, dtype=dtype,
                   cfg=cfg)
    if gates:
        sys.exit("; ".join(gates))
    return rec


def _refine_serve_smoke(n: int, nrhs: int, dtype, ledger=None) -> dict:
    """The mixed-tier serve smoke (bench-refine gate): warm one posv
    bucket per accuracy tier through a real SolveEngine, then drive 24
    requests cycling balanced/fast/guaranteed and count executable
    compiles after warmup — the zero-recompile invariant with precision
    as a bucket dimension.  When `ledger` is given, also appends the
    engine's serve:request_stats record (carrying the refine block the
    guaranteed requests populate) so ``obs serve-report
    --max-refine-iters/--min-converged-frac`` has a record to gate."""
    import numpy as np

    from capital_tpu.serve.engine import ServeConfig, SolveEngine

    rng = np.random.default_rng(23)
    cfg = ServeConfig(buckets=(n,), nrhs_buckets=(nrhs,), max_batch=2,
                      max_delay_s=0.0, oversize="reject")
    eng = SolveEngine(cfg=cfg)
    X = rng.standard_normal((n, n))
    A = np.asarray((X @ X.T / n + 3.0 * np.eye(n)), dtype)
    B = np.asarray(rng.standard_normal((n, nrhs)), dtype)
    tiers = ("balanced", "fast", "guaranteed")
    for t in tiers:  # the one-time per-(bucket, tier) warmup compiles
        assert eng.solve("posv", A, B, accuracy_tier=t).ok
    c0 = eng.cache_stats()["compiles"]
    requests = 0
    while requests < 24:
        r = eng.solve("posv", A, B,
                      accuracy_tier=tiers[requests % len(tiers)])
        assert r.ok, r.error
        requests += 1
    if ledger:
        eng.emit_stats(ledger)
    return {
        "requests": requests,
        "recompiles": eng.cache_stats()["compiles"] - c0,
    }


def posv(args):
    return _small_solve(args, "posv")


def lstsq(args):
    return _small_solve(args, "lstsq")


DRIVERS = {
    "cholinv": cholinv,
    "cacqr": cacqr,
    "summa_gemm": summa_gemm,
    "rectri": rectri,
    "newton": newton,
    "spd_inverse": spd_inverse,
    "trsm": trsm,
    "posv": posv,
    "lstsq": lstsq,
    "blocktri": blocktri,
    "arrowhead": arrowhead,
    "update": update,
    "refine": refine,
    "session": session,
}


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="capital_tpu.bench")
    p.add_argument("driver", choices=[*DRIVERS, "suite"])
    p.add_argument("--n", type=int, default=4096)
    p.add_argument("--m", type=int, default=65536)
    p.add_argument("--k", type=int, default=4096)
    p.add_argument("--dtype", default="bfloat16")
    p.add_argument("--iters", type=int, default=3)
    p.add_argument(
        "--bc", type=int, default=0,
        help="base-case dim (0 = auto: cholinv/spd pick 256 below the "
        "n<=8192 crossover, 512 above; every other driver keeps 512)",
    )
    p.add_argument("--split", type=int, default=1)
    p.add_argument(
        "--mode", default="auto", choices=["auto", "xla", "explicit", "pallas"],
        help="SUMMA mode; auto = pallas on one device, xla on a mesh",
    )
    p.add_argument(
        "--balance", default="block",
        choices=["block", "tile_cyclic", "tile_cyclic_persistent"],
        help="cholinv: explicit-mode triangular work balance; "
        "tile_cyclic_persistent permutes once per factor lifetime instead "
        "of per trmm/syrk call (docs/DISTRIBUTED.md)",
    )
    p.add_argument("--variant", type=int, default=2, help="1=CQR, 2=CQR2")
    p.add_argument("--regime", default="auto", choices=["auto", "1d", "dist"])
    p.add_argument("--c", type=int, default=1, help="replication depth")
    p.add_argument(
        "--layout", type=int, default=0, choices=[0, 1, 2],
        help="device->grid-coordinate layout (reference topology.h:77-123)",
    )
    p.add_argument(
        "--chunks", type=int, default=0,
        help="explicit-SUMMA bcast pipelining chunks (reference num_chunks)",
    )
    p.add_argument("--devices", type=int, default=0, help="limit device count")
    p.add_argument(
        "--precision", default=None, choices=["default", "high", "highest"],
        help="matmul precision override for f32 operands: 'high' (3-pass "
        "bf16) exists only on the XLA paths — Mosaic kernels round it up "
        "to 'highest' (6-pass); default: 'highest' for f32, None for bf16",
    )
    p.add_argument(
        "--device-check", action="store_true",
        help="measure the device-counter op total of the timed loop and "
        "re-measure (then floor) walls that land below it — the suite's "
        "drift guard; on by default under the suite driver on TPU",
    )
    p.add_argument("--newton-iters", type=int, default=30)
    p.add_argument(
        "--fused-g", type=int, default=0,
        help="cacqr: in-kernel column split of the fused tall-pass kernels "
        "(0 = auto, qr_fused.pick_g)",
    )
    p.add_argument(
        "--leaf", default="invert", choices=["invert", "solve"],
        help="trsm leaf policy (TrsmConfig.leaf)",
    )
    p.add_argument(
        "--batch-below", type=int, default=-1,
        help="rectri batched-level-sweep threshold (-1 = config default)",
    )
    p.add_argument("--no-complete-inv", action="store_true")
    p.add_argument(
        "--robust", action="store_true",
        help="cacqr: factor under RobustConfig (breakdown detection + "
        "shifted-CholeskyQR recovery, docs/ROBUSTNESS.md); the status "
        "scalars ride the report and the ledger record",
    )
    p.add_argument("--validate", action="store_true")
    p.add_argument(
        "--batch", type=int, default=8,
        help="posv/lstsq: problems per bucket batch (serve max_batch)",
    )
    p.add_argument(
        "--nrhs", type=int, default=1,
        help="posv/lstsq: RHS columns per problem",
    )
    p.add_argument(
        "--latency", action="store_true",
        help="posv/lstsq: per-call latency mode — p50/p95/p99 wall_ms via "
        "harness.latency_samples/percentiles (one dispatch per sample, the "
        "serving protocol) and a bench:latency ledger record, instead of "
        "the amortized TFLOP/s row",
    )
    p.add_argument(
        "--calls", type=int, default=32,
        help="posv/lstsq --latency: number of per-call samples",
    )
    p.add_argument(
        "--small-impl", default="auto",
        choices=["auto", "vmap", "pallas", "pallas_split"],
        help="posv/lstsq: batched implementation (api.batched impl switch; "
        "auto resolves from the bucket shape like serve does)",
    )
    p.add_argument(
        "--nblocks", type=int, default=8,
        help="blocktri: chain length (diagonal blocks per problem)",
    )
    p.add_argument(
        "--block", type=int, default=32,
        help="blocktri: block size b (each diagonal block is b x b; "
        "n = nblocks * block)",
    )
    p.add_argument(
        "--border", type=int, default=32,
        help="arrowhead: border rank s (rows of the coupling block-row "
        "and the dense corner; n = nblocks * block + border)",
    )
    p.add_argument(
        "--slide", type=int, default=8,
        help="session: sliding-window stride in blocks — each steady-state "
        "cycle appends this many new blocks and contracts this many old "
        "ones away (must be in (0, --nblocks))",
    )
    p.add_argument(
        "--impl", default="auto",
        choices=["auto", "pallas", "xla", "partitioned"],
        help="blocktri: chain implementation; auto = pallas scan on TPU, "
        "xla scan elsewhere (off-TPU pallas is the interpreter — serve "
        "keeps it there for AOT-cache persistability, a bench must not); "
        "partitioned = the Spike chain driver, benched A/B against the "
        "sequential scan with latency + jaxpr-depth columns",
    )
    p.add_argument(
        "--partitions", type=int, default=0,
        help="blocktri --impl partitioned: requested partition count "
        "(0 = resolve_partitions default, the largest divisor of nblocks "
        "<= sqrt(nblocks); requests decrement to a valid divisor)",
    )
    p.add_argument(
        "--min-depth-reduction", type=float, default=0.0,
        help="blocktri --impl partitioned: fail the run when the measured "
        "jaxpr sequential scan-depth reduction vs the sequential impl "
        "lands below this factor (the round-13 gate: 4 at nblocks=64) or "
        "when partitioned results drift past the pinned parity tolerance",
    )
    p.add_argument(
        "--min-speedup", type=float, default=0.0,
        help="blocktri/arrowhead: fail the run when the measured "
        "per-problem speedup vs equal-n dense posv lands below this "
        "factor (the round-11 flagship gate: 25 at nblocks=64, block=128, "
        "f32; the round-15 arrowhead gate: 10 at nblocks=64, block=128, "
        "border=32, f32); "
        "refine: the same flag gates the FACTOR-PHASE narrow-vs-wide "
        "potrf speedup (the round-14 gate: 1.5 at n=1024 f64 on the CPU "
        "rig — end-to-end latency is reported ungated, see the driver "
        "docstring); "
        "session: gates the incremental append(slide) vs "
        "refactor-from-scratch speedup (the round-19 gate: 5 at "
        "nblocks=64, block=128, slide=8)",
    )
    p.add_argument(
        "--max-resid-ratio", type=float, default=0.0,
        help="refine: fail when the guaranteed-tier normwise backward "
        "error exceeds this multiple of the straight request-dtype "
        "factor's (the matched-residual half of the round-14 gate: 10; "
        "0 = report only)",
    )
    p.add_argument(
        "--min-hit-rate", type=float, default=0.0,
        help="update: run the 50-request mixed chol_update/posv_cached "
        "serve smoke and fail below this residency hit-rate (the round-12 "
        "gate: 0.9) or on any steady-state executable recompile; "
        "session: the same flag gates the 50-request mixed session "
        "workload (the round-19 gate: 0.85, zero recompiles)",
    )
    p.add_argument(
        "--phase-attr", action="store_true",
        help="cholinv: decompose the measured wall into per-phase seconds "
        "(bench.trace.phase_attribution) — bubble_frac joins the report "
        "line and the phase_seconds split rides the ledger record, "
        "re-readable via obs trace-report",
    )
    p.add_argument(
        "--ledger", default=None,
        help="append one unified obs ledger record per run (manifest + "
        "model costs + compiled-program audit + measured + residuals) to "
        "this JSONL file; query with python -m capital_tpu.obs diff",
    )
    p.add_argument("--scale", type=int, default=1, help="suite: divide problem sizes")
    p.add_argument(
        "--platform", default=None,
        help="jax platform override (e.g. 'cpu'); uses the config API because "
        "the session's site hook clears JAX_PLATFORMS env selections",
    )
    p.add_argument(
        "--host-devices", type=int, default=0,
        help="virtual CPU device count (--xla_force_host_platform_device_count)",
    )
    return p


def main(argv=None) -> None:
    args = build_parser().parse_args(argv)
    if args.host_devices:
        import os

        flags = [
            f
            for f in os.environ.get("XLA_FLAGS", "").split()
            if "xla_force_host_platform_device_count" not in f
        ]
        flags.append(f"--xla_force_host_platform_device_count={args.host_devices}")
        os.environ["XLA_FLAGS"] = " ".join(flags)
    if args.platform:
        jax.config.update("jax_platforms", args.platform)
    if args.driver == "suite":
        from capital_tpu.bench import suite

        suite.run(args)
    else:
        DRIVERS[args.driver](args)


if __name__ == "__main__":
    main()
