"""Benchmark drivers (reference bench/) — see drivers.py and suite.py."""
