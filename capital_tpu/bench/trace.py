"""Per-phase device-time budgets from real hardware traces.

The reference ships critter's symbol decomposition (autotune/util.h:63-127:
per-phase cp-comp/cp-comm columns); the runtime counterpart here is a
`jax.profiler` device trace of the actual benchmark loop, bucketed by the
``CI::*`` / ``CQR::*`` phase scopes that `tracing.scope` stamps into every
HLO op's metadata.  Wall clocks through the axon tunnel drift 2-3x on a
minutes timescale — per-kernel device *own time* from the trace is the one
measurement immune to that (docs/PERF.md "Measurement discipline"), so this
is the tool that settles where a flagship millisecond actually goes.

CLI::

    python -m capital_tpu.bench.trace cholinv --n 16384 [--bc 512] [--iters 3]
    python -m capital_tpu.bench.trace cacqr --m 1048576 --n 1024

prints one line per phase bucket (device ms per iteration, % of total) plus
a JSON record, from a trace of `iters` in-jit iterations of the same loop
the flagship bench runs.

Parsing: the xplane protobuf's "XLA Ops" line carries one event per HLO op
execution with its self (own) duration; each op's metadata carries the
named_scope chain (``CI.trsm`` etc.), searched longest-scope-first so
nested scopes attribute to the innermost phase, matching critter's
innermost-symbol attribution.
"""

from __future__ import annotations

import argparse
import collections
import glob
import json
import os
import re
import tempfile
import time

import jax
import jax.numpy as jnp

from capital_tpu.utils import tracing


def _phase_tags() -> tuple[str, ...]:
    """Named-scope (dot) forms of the registered phase tags.  Derived from
    tracing.PHASE_REGISTRY — the single source of truth — so a phase added
    to scope() can never be silently bucketed into 'other' here (the fate
    of RT::batch_write under the old hardcoded copy of this tuple).
    Re-evaluated lazily so in-process register_phase() calls are seen."""
    return tuple(t.replace("::", ".") for t in tracing.PHASE_REGISTRY)


#: flagship phase buckets (dot form).  An op whose metadata mentions none
#: of these lands in 'copy' / 'fusion' / 'other' by HLO kind — the
#: catch-alls that caught the round-2 relayout-copy regressions.
PHASE_TAGS = _phase_tags()


def _own_times(line):
    """(metadata_id, own_duration_ps) per event: the 'XLA Ops' line is
    hierarchical (a `while` event spans its whole body), so an op's own time
    is its duration minus the durations of the events it directly contains —
    a stack sweep over (offset, duration)-sorted events.  Accepts either an
    XLine or a pre-filtered event list (the host-plane fallback filters
    thread-pool bookkeeping events out BEFORE the sweep, so a listener
    region can't absorb a real op's duration as its child)."""
    evs = sorted(
        getattr(line, "events", line),
        key=lambda e: (e.offset_ps, -e.duration_ps),
    )
    out = []
    stack = []  # [end_ps, metadata_id, duration_ps, child_sum]
    for e in evs:
        start, dur = e.offset_ps, e.duration_ps
        while stack and stack[-1][0] <= start:
            fin = stack.pop()
            own = fin[2] - fin[3]
            if stack:
                stack[-1][3] += fin[2]
            out.append((fin[1], own))
        while stack and start + dur > stack[-1][0]:
            # overlapping, not nested (async tails) — close EVERY stacked
            # ancestor the new event outlasts, not just the top, so a tail
            # spanning several ancestors doesn't leave the deeper ones open
            # to absorb the overlap into the wrong phase bucket
            fin = stack.pop()
            own = fin[2] - fin[3]
            if stack:
                stack[-1][3] += fin[2]
            out.append((fin[1], own))
        stack.append([start + dur, e.metadata_id, dur, 0])
    while stack:
        fin = stack.pop()
        own = fin[2] - fin[3]
        if stack:
            stack[-1][3] += fin[2]
        out.append((fin[1], own))
    return out


def _iter_xla_op_events(space):
    """Yield (plane_name, metadata, own_duration_ps, stat_metadata, is_async)
    for every device XLA-op event.  The 'Async XLA Ops' line reports
    in-flight occupancy of DMAs that overlap compute — kept separate
    (occupancy is not additive with op own time)."""
    for plane in space.planes:
        if "TPU" not in plane.name:
            continue
        for line in plane.lines:
            if line.name == "XLA Ops":
                for mid, own_ps in _own_times(line):
                    yield (plane.name, plane.event_metadata.get(mid), own_ps,
                           plane.stat_metadata, False)
            elif line.name == "Async XLA Ops":
                for ev in line.events:
                    md = plane.event_metadata.get(ev.metadata_id)
                    yield (plane.name, md, ev.duration_ps,
                           plane.stat_metadata, True)


def _bucket(md, stat_metadata) -> str:
    """Phase bucket for one op.  The HLO op NAME (XLA names each op after
    the named_scope that produced it: %CI.tmu.90) is authoritative; the
    metadata stats (tf_op paths etc.) often mention *several* scopes for
    fused/derived ops and are only consulted when the name says nothing —
    matching against them first mis-filed tmu kernels under trsm."""

    def match(hay: str) -> str | None:
        best = None
        for tag in _phase_tags():
            if tag in hay and (best is None or len(tag) > len(best)):
                best = tag
        return best

    name = md.name or md.display_name
    best = match(name.split(" = ")[0])  # the op's own %name only
    if best is None:
        hay = name + " " + md.display_name
        for s in md.stats:
            sm = stat_metadata.get(s.metadata_id)
            if sm is not None and sm.name in ("tf_op", "hlo_op", "name_scope"):
                hay += " " + s.str_value
        best = match(hay)
    if best is not None:
        return best.replace(".", "::")
    if "copy" in name:
        return "copy"
    if "fusion" in name:
        return "fusion"
    if "custom-call" in name or "cholesky" in name or "triangular" in name:
        return "custom-call"
    return "other"


def device_budget(run, trace_dir: str | None = None) -> dict[str, float]:
    """Trace `run()` (which must block on completion) and return
    {bucket: device milliseconds} of XLA-op own time for the
    **critical-path device plane** (the plane with the largest total own
    time), plus an 'async (overlapped)' entry for that plane's DMA
    in-flight occupancy (informational — overlaps compute, not additive).

    Per-plane selection matters: on an n-device run every device's own time
    ~equals the wall, so summing planes would report ~n x the true
    per-iteration floor and poison harness.device_ms_per_iter's
    below-floor check (round-3 advisor finding).  Taking the max plane is
    the device-side critical path — the same max-over-ranks convention the
    reference's bench timing uses (bench/cholesky/cholinv.cpp:51-59)."""
    return _critical_plane_budget(_trace_spaces(run, trace_dir))


def _trace_spaces(run, trace_dir: str | None = None):
    """Trace `run()` once and return the parsed [(path, XSpace)] protos —
    the raw material shared by device_budget and phase_attribution so a
    gated CLI invocation profiles exactly once."""
    from tensorflow.tsl.profiler.protobuf import xplane_pb2

    with tempfile.TemporaryDirectory() as tmp:
        d = trace_dir or tmp
        with jax.profiler.trace(d):
            run()
        paths = glob.glob(os.path.join(d, "**", "*.xplane.pb"), recursive=True)
        if not paths:
            raise RuntimeError(f"no xplane.pb under {d}")
        spaces = []
        for p in paths:
            space = xplane_pb2.XSpace()
            with open(p, "rb") as f:
                space.ParseFromString(f.read())
            spaces.append((p, space))
    return spaces


def _critical_plane_budget(spaces) -> dict[str, float]:
    """{bucket: ms} of the max-total device plane over [(tag, XSpace)]."""
    per_plane: dict[str, dict[str, float]] = collections.defaultdict(
        lambda: collections.defaultdict(float)
    )
    for tag, space in spaces:
        for plane, md, dur_ps, stat_md, is_async in _iter_xla_op_events(space):
            if md is None:
                continue
            key = "async (overlapped)" if is_async else _bucket(md, stat_md)
            # key planes by (source, plane-name): one xplane.pb per host,
            # one plane per local device
            per_plane[f"{tag}::{plane}"][key] += dur_ps * 1e-9  # ps -> ms
    if not per_plane:
        return {}

    def compute_total(buckets):
        return sum(v for k, v in buckets.items() if k != "async (overlapped)")

    crit = max(per_plane.values(), key=compute_total)
    return dict(crit)


def check_copy_fraction(
    budget: dict[str, float], max_frac: float, label: str = ""
) -> float:
    """Gate the 'copy' bucket — schedule-inserted relayout/materialization
    copies, the device-time cost the copy-free explicit routes and the
    persistent tile-cyclic layout exist to remove — at <= ``max_frac`` of
    the plane's compute own-time.  Returns the measured fraction; raises
    RuntimeError on violation so copy regressions fail as loudly as
    collective-inventory regressions (tests/test_collective_audit.py)
    already do.  The async-occupancy row is excluded from both sides
    (it overlaps compute; it is not additive own-time).  The cost-model
    counterpart is the copy_bytes column of tracing.Recorder
    (docs/OBSERVABILITY.md)."""
    compute = {
        k: v for k, v in budget.items() if k != "async (overlapped)"
    }
    total = sum(compute.values())
    frac = (compute.get("copy", 0.0) / total) if total > 0 else 0.0
    if frac > max_frac:
        raise RuntimeError(
            f"copy-budget regression{f' ({label})' if label else ''}: "
            f"copy bucket is {100 * frac:.1f}% of device own-time, "
            f"budget {100 * max_frac:.1f}% — a schedule copy "
            "(take_triangle materialization / whole-buffer "
            "dynamic_update_slice) crept back in"
        )
    return frac


# --------------------------------------------------------------------------
# phase-level wall-time attribution
# --------------------------------------------------------------------------

#: one optimized-HLO instruction definition with its op_name metadata —
#: the scope chain tracing.scope stamped through jax.named_scope
#: survives XLA optimization in exactly this field (fusions inherit a
#: constituent op's chain).
_HLO_OP_RE = re.compile(
    r"%?([A-Za-z0-9_.\-]+)\s*=\s*[^\n]*metadata=\{[^}\n]*op_name=\"([^\"]*)\""
)


def hlo_phase_map(compiled_text: str) -> dict[str, str]:
    """{instruction name: phase tag ('CI::tmu' form)} from an optimized-HLO
    dump (``compiled.as_text()``).  Longest registered tag mentioned in the
    op_name wins (innermost scope, same convention as _bucket); instructions
    whose op_name names no registered phase are simply absent.  Nested
    computations are parsed too — dict insertion order means the ENTRY
    computation (printed last) wins a name collision, which is the
    computation whose instruction names the runtime's thunk events carry."""
    out: dict[str, str] = {}
    tags = sorted(_phase_tags(), key=len)  # ascending: longest match wins
    for m in _HLO_OP_RE.finditer(compiled_text):
        name, op_name = m.groups()
        best = None
        for tag in tags:
            if tag in op_name:
                best = tag
        if best is not None:
            out[name] = best.replace(".", "::")
    return out


def _host_plane_budget(spaces, phase_map: dict[str, str]) -> dict[str, float]:
    """{bucket: ms} fallback for rigs with no device plane (the CPU CI rig):
    the host trace's XLA-client lines carry one event per executed thunk,
    named after the entry-computation HLO instruction and stamped with an
    ``hlo_op`` stat.  Those events are bucketed through `phase_map` (from
    hlo_phase_map of the SAME compiled program that ran).  Events without
    the hlo_op stat (ThreadpoolListener / ThunkExecutor bookkeeping) are
    dropped BEFORE the own-time sweep so they can't swallow op durations.
    Busiest host plane wins, mirroring _critical_plane_budget."""
    per_plane: dict[str, dict[str, float]] = collections.defaultdict(
        lambda: collections.defaultdict(float)
    )
    for tag, space in spaces:
        for plane in space.planes:
            if "TPU" in plane.name:
                continue
            stat_names = {
                sid: sm.name for sid, sm in plane.stat_metadata.items()
            }
            for line in plane.lines:
                evs = [
                    e for e in line.events
                    if any(
                        stat_names.get(s.metadata_id) == "hlo_op"
                        for s in e.stats
                    )
                ]
                if not evs:
                    continue
                buckets = per_plane[f"{tag}::{plane.name}"]
                for mid, own_ps in _own_times(evs):
                    md = plane.event_metadata.get(mid)
                    if md is None:
                        continue
                    name = (md.name or md.display_name).lstrip("%")
                    key = phase_map.get(name)
                    if key is None:
                        if "copy" in name:
                            key = "copy"
                        elif "fusion" in name:
                            key = "fusion"
                        else:
                            key = "other"
                    buckets[key] += own_ps * 1e-9  # ps -> ms
    if not per_plane:
        return {}
    return dict(max(per_plane.values(), key=lambda b: sum(b.values())))


def wall_seconds(run, repeats: int = 3) -> float:
    """min-of-repeats wall clock of one (compiled, warm) run() — the min is
    the drift-resistant estimator docs/PERF.md's measurement discipline
    prescribes for walls that only err upward."""
    best = float("inf")
    for _ in range(max(1, repeats)):
        t0 = time.perf_counter()
        run()
        best = min(best, time.perf_counter() - t0)
    return best


def phase_attribution(run, iters: int, spaces=None, trace_dir=None):
    """Decompose measured wall-clock into per-phase seconds.

    Returns ``(phase_seconds, bubble_frac, wall_s_per_iter)`` where
    phase_seconds maps each PHASE_REGISTRY tag (plus the copy/fusion/other
    catch-alls) to seconds per iteration and
    ``bubble_frac = max(0, (wall − Σ attributed) / wall)`` — the fraction
    of the wall no op execution accounts for (launch gaps, host stalls,
    inter-phase bubbles).  Clamped at 0 because concurrent thunk execution
    on the CPU rig can legitimately attribute MORE op-seconds than wall.

    Device planes ('XLA Ops' own time) are authoritative when present; on
    rigs without one, host-side thunk events are bucketed through the
    compiled module's op_name metadata (``run.compiled`` — the AOT
    executable every _*_run builder attaches).  Pass `spaces` to reuse an
    existing _trace_spaces parse; the wall always comes from separate
    UNtraced runs (profiling overhead must not count as bubble)."""
    wall = wall_seconds(run) / iters
    if spaces is None:
        spaces = _trace_spaces(run, trace_dir)
    budget = _critical_plane_budget(spaces)
    budget.pop("async (overlapped)", None)
    if not budget:
        compiled = getattr(run, "compiled", None)
        if compiled is not None:
            budget = _host_plane_budget(
                spaces, hlo_phase_map(compiled.as_text())
            )
    phase_seconds = {
        k: v * 1e-3 / iters for k, v in budget.items() if v > 0.0
    }
    attributed = sum(phase_seconds.values())
    bubble = max(0.0, (wall - attributed) / wall) if wall > 0 else 0.0
    return phase_seconds, bubble, wall


def check_bubble_fraction(
    phase_seconds: dict[str, float],
    bubble_frac: float,
    max_frac: float,
    label: str = "",
) -> float:
    """Gate the un-attributed fraction of the wall — the --max-bubble-frac
    CI mirror of check_copy_fraction.  An EMPTY attribution fails too: a
    gate that passes because nothing was attributed is a dead gate, and
    dead gates are how the round-2 copy regressions shipped."""
    tag = f" ({label})" if label else ""
    if not phase_seconds:
        raise RuntimeError(
            f"bubble gate is dead{tag}: no phase seconds were attributed — "
            "no device plane in the trace and no compiled module to map "
            "host events through; fix the attribution before trusting the "
            "gate"
        )
    if bubble_frac > max_frac:
        raise RuntimeError(
            f"bubble-budget regression{tag}: {100 * bubble_frac:.1f}% of "
            f"wall is unattributed (budget {100 * max_frac:.1f}%) — "
            "inter-phase bubbles / launch gaps grew; see the phase "
            "breakdown above"
        )
    return bubble_frac


def print_budget(budget: dict[str, float], iters: int, label: str) -> dict:
    budget = dict(budget)
    async_ms = budget.pop("async (overlapped)", 0.0)
    total = sum(budget.values())
    rows = sorted(budget.items(), key=lambda kv: -kv[1])
    print(f"# device-op budget: {label} ({iters} traced iterations)")
    for k, ms in rows:
        print(f"#   {k:16s} {ms / iters:9.3f} ms/iter  {100 * ms / total:5.1f}%")
    print(f"#   {'TOTAL':16s} {total / iters:9.3f} ms/iter")
    if async_ms:
        print(
            f"#   {'async-overlap':16s} {async_ms / iters:9.3f} ms/iter  "
            "(DMA occupancy, overlaps the rows above)"
        )
        rows = rows + [("async (overlapped)", async_ms)]
    rec = {
        "metric": "device_budget",
        "label": label,
        "iters": iters,
        "total_ms_per_iter": round(total / iters, 3),
        "phases_ms_per_iter": {k: round(v / iters, 3) for k, v in rows},
    }
    print(json.dumps(rec))
    return rec


def _aot_run(jitted, *args):
    """AOT-compile ``jitted(*args)`` and return a zero-arg runner that
    blocks on the scalar result.  The runner carries the executable as
    ``run.compiled`` so phase_attribution can read the optimized HLO of
    EXACTLY the program the trace ran (hlo_phase_map) — a re-jit could
    legally schedule differently."""
    compiled = jitted.lower(*args).compile()

    def run():
        float(compiled(*args))

    run.compiled = compiled
    return run


def _cholinv_run(n: int, dtype, bc: int, iters: int, oneshot: bool, prec=None,
                 mode: str = "pallas"):
    """The flagship loop (bench.py's shape: fori_loop + element coupling),
    compiled once and traced for `iters` iterations."""
    from capital_tpu.models import cholesky
    from capital_tpu.parallel.topology import Grid

    grid = Grid.square(c=1, devices=[jax.devices()[0]])
    cfg = cholesky.CholinvConfig(
        base_case_dim=bc, mode=mode,
        precision=prec,
        schur_in_place=oneshot,
    )
    eps = jnp.asarray(0.0, jnp.float32)

    if oneshot:
        import importlib.util
        import pathlib

        bench_path = pathlib.Path(__file__).resolve().parents[2] / "bench.py"
        spec = importlib.util.spec_from_file_location("flagship_bench", bench_path)
        bench = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(bench)
        if cholesky.padded_dim(n, bc) != n:
            # same guard as bench.py: cropped outputs cannot serve as the
            # next iteration's p x p carries
            raise SystemExit(
                f"--oneshot needs n = bc * 2^k (n={n}, bc={bc} pads to "
                f"{cholesky.padded_dim(n, bc)})"
            )

        @jax.jit
        def loop(eps, k):
            def body(i, carry):
                acc, Rp, RIp = carry
                a = jax.lax.optimization_barrier(bench.spd_hash(n, dtype, i))
                R, Rinv = cholesky.factor(grid, a, cfg, out_buffers=(Rp, RIp))
                return (
                    acc + eps * (R[0, 0] + Rinv[0, 0]).astype(jnp.float32),
                    R, Rinv,
                )

            Rp0, RIp0 = cholesky.factor_buffers(grid, n, dtype, cfg)
            out, _, _ = jax.lax.fori_loop(
                0, k, body, (jnp.float32(0.0), Rp0, RIp0)
            )
            return out

        run = _aot_run(loop, eps, jnp.int32(iters))
    else:
        from capital_tpu.bench.drivers import _spd

        A = _spd(n, dtype)

        @jax.jit
        def loop(a, eps, k):
            def body(_, carry):
                R, Rinv = cholesky.factor(grid, carry, cfg)
                d = R[0, 0] + Rinv[0, 0]
                return carry.at[0, 0].add(eps.astype(carry.dtype) * d)

            return jnp.sum(jax.lax.fori_loop(0, k, body, a), dtype=jnp.float32)

        run = _aot_run(loop, A, eps, jnp.int32(iters))

    run()  # warm (already AOT-compiled)
    return run


def _rectri_run(n: int, dtype, bc: int, iters: int, prec=None):
    from capital_tpu.bench.drivers import _tri_operand
    from capital_tpu.models import inverse
    from capital_tpu.parallel.topology import Grid

    grid = Grid.square(c=1, devices=[jax.devices()[0]])
    cfg = inverse.RectriConfig(
        base_case_dim=bc, mode="pallas",
        precision=prec,
    )
    T = _tri_operand(n, dtype)
    eps = jnp.asarray(0.0, jnp.float32)

    @jax.jit
    def loop(a, eps, k):
        def body(_, carry):
            inv = inverse.rectri(grid, carry, "L", cfg)
            return carry.at[0, 0].add(eps.astype(carry.dtype) * inv[0, 0])

        return jnp.sum(jax.lax.fori_loop(0, k, body, a), dtype=jnp.float32)

    run = _aot_run(loop, T, eps, jnp.int32(iters))
    run()
    return run


def _cacqr_run(m: int, n: int, dtype, bc: int, iters: int, prec=None):
    from capital_tpu.models import cholesky, qr
    from capital_tpu.parallel.topology import Grid

    grid = Grid.square(c=1, devices=[jax.devices()[0]])
    precision = prec
    cfg = qr.CacqrConfig(
        num_iter=2, mode="pallas",
        cholinv=cholesky.CholinvConfig(
            base_case_dim=bc, mode="pallas", precision=precision
        ),
        precision=precision,
    )
    A = jax.block_until_ready(
        jax.random.normal(jax.random.key(0), (m, n), dtype=dtype)
    )
    eps = jnp.asarray(0.0, jnp.float32)

    @jax.jit
    def loop(a, eps, k):
        def body(_, carry):
            Q, R = qr.factor(grid, carry, cfg)
            return Q.at[: R.shape[0], : R.shape[1]].add(R.astype(Q.dtype))

        return jnp.sum(jax.lax.fori_loop(0, k, body, a), dtype=jnp.float32)

    run = _aot_run(loop, A, eps, jnp.int32(iters))
    run()
    return run


def _trsm_run(n: int, nrhs: int, dtype, bc: int, iters: int, prec=None):
    from capital_tpu.bench.drivers import _tri_operand
    from capital_tpu.models import trsm as trsm_mod
    from capital_tpu.parallel.topology import Grid

    grid = Grid.square(c=1, devices=[jax.devices()[0]])
    cfg = trsm_mod.TrsmConfig(
        base_case_dim=bc, mode="xla",
        precision=prec,
    )
    L = _tri_operand(n, dtype)
    B = jax.block_until_ready(
        jax.random.normal(jax.random.key(1), (n, nrhs), dtype=dtype)
    )
    eps = jnp.asarray(0.0, jnp.float32)

    @jax.jit
    def loop(op, eps, k):
        Lo, B0 = op

        def body(_, carry):
            X = trsm_mod.solve(grid, Lo, carry, side="L", uplo="L", cfg=cfg)
            return carry + eps.astype(carry.dtype) * X

        return jnp.sum(jax.lax.fori_loop(0, k, body, B0), dtype=jnp.float32)

    run = _aot_run(loop, (L, B), eps, jnp.int32(iters))
    run()
    return run


def main(argv=None) -> None:
    p = argparse.ArgumentParser(prog="capital_tpu.bench.trace")
    p.add_argument("algo", choices=["cholinv", "cacqr", "rectri", "trsm"])
    p.add_argument("--n", type=int, default=16384)
    p.add_argument("--m", type=int, default=1 << 20)
    p.add_argument("--bc", type=int, default=512)
    p.add_argument("--dtype", default="bfloat16")
    p.add_argument("--iters", type=int, default=3)
    p.add_argument("--oneshot", action="store_true",
                   help="cholinv: trace the one-shot regen loop (the large-n "
                        "flagship protocol) instead of the carry loop")
    p.add_argument("--trace-dir", default=None,
                   help="keep the raw trace here instead of a temp dir")
    p.add_argument("--max-copy-frac", type=float, default=None,
                   help="fail (non-zero exit) if the 'copy' bucket exceeds "
                        "this fraction of device own-time — the CI gate for "
                        "schedule-copy regressions (see "
                        "trace.check_copy_fraction)")
    p.add_argument("--max-bubble-frac", type=float, default=None,
                   help="fail (non-zero exit) if more than this fraction of "
                        "measured wall-clock is attributed to NO phase "
                        "(launch gaps / host stalls / inter-phase bubbles); "
                        "also fails when nothing could be attributed at all "
                        "— no silently-dead gates (trace."
                        "check_bubble_fraction)")
    p.add_argument("--ledger", default=None,
                   help="append one bench:trace:<algo> ledger record "
                        "carrying the phase_seconds / bubble_frac block "
                        "(obs diff watches measured.value = attributed "
                        "fraction for drift)")
    p.add_argument("--precision", default=None,
                   choices=["default", "high", "highest"],
                   help="override the matmul precision ('high' traces the "
                        "f32 3-pass family, 'default' the TPU-default "
                        "1-pass) — same semantics as the drivers CLI")
    p.add_argument("--platform", default=None,
                   help="jax platform override (e.g. 'cpu') — config API, "
                        "same reason as the drivers CLI")
    args = p.parse_args(argv)
    if args.platform:
        jax.config.update("jax_platforms", args.platform)
    dtype = jnp.dtype(args.dtype)
    # ONE precision rule shared with the drivers CLI (drivers._precision):
    # 'default' -> None (TPU default), unset -> the dtype rule
    from capital_tpu.bench.drivers import _precision

    prec = _precision(args, dtype)
    ptag = f" prec={args.precision}" if args.precision else ""

    if args.algo == "cholinv":
        run = _cholinv_run(args.n, dtype, args.bc, args.iters, args.oneshot, prec)
        label = f"cholinv n={args.n} bc={args.bc} {dtype}" + (
            " oneshot" if args.oneshot else ""
        ) + ptag
    elif args.algo == "rectri":
        run = _rectri_run(args.n, dtype, args.bc, args.iters, prec)
        label = f"rectri n={args.n} bc={args.bc} {dtype}" + ptag
    elif args.algo == "trsm":
        nrhs = min(args.m, args.n)
        run = _trsm_run(args.n, nrhs, dtype, args.bc, args.iters, prec)
        label = f"trsm n={args.n} nrhs={nrhs} bc={args.bc} {dtype}" + ptag
    else:
        run = _cacqr_run(args.m, args.n, dtype, args.bc, args.iters, prec)
        label = f"cacqr {args.m}x{args.n} {dtype}" + ptag

    spaces = _trace_spaces(run, args.trace_dir)
    budget = _critical_plane_budget(spaces)
    print_budget(budget, args.iters, label)
    if args.max_copy_frac is not None:
        frac = check_copy_fraction(budget, args.max_copy_frac, label)
        print(
            f"# copy budget OK: {100 * frac:.1f}% <= "
            f"{100 * args.max_copy_frac:.1f}%"
        )
    if args.max_bubble_frac is not None or args.ledger is not None:
        phase_s, bubble, wall = phase_attribution(
            run, args.iters, spaces=spaces
        )
        attributed = sum(phase_s.values())
        print(
            f"# phase attribution: wall {wall * 1e3:.3f} ms/iter, "
            f"attributed {attributed * 1e3:.3f} ms/iter, "
            f"bubble_frac {bubble:.4f}"
        )
        for k, v in sorted(phase_s.items(), key=lambda kv: -kv[1]):
            print(f"#   {k:16s} {v * 1e3:9.3f} ms/iter")
        if args.ledger:
            from capital_tpu.obs import ledger

            meas = {
                "metric": f"trace_{args.algo}_attributed",
                # value is the attributed fraction so an obs diff
                # value-drop reads as "bubbles grew"
                "value": round(1.0 - bubble, 4),
                "unit": "frac",
                "seconds": wall,
                "n": args.n,
                "bc": args.bc,
                "phase_seconds": {k: round(v, 9) for k, v in phase_s.items()},
                "bubble_frac": round(bubble, 4),
            }
            row = ledger.record(
                f"bench:trace:{args.algo}",
                ledger.manifest(
                    dtype=dtype,
                    config={
                        "algo": args.algo, "n": args.n, "bc": args.bc,
                        "iters": args.iters, "oneshot": bool(args.oneshot),
                    },
                ),
                measured=meas,
            )
            ledger.append(args.ledger, row)
            print(f"# ledger: bench:trace:{args.algo} -> {args.ledger}")
        if args.max_bubble_frac is not None:
            check_bubble_fraction(phase_s, bubble, args.max_bubble_frac, label)
            print(
                f"# bubble budget OK: {100 * bubble:.1f}% <= "
                f"{100 * args.max_bubble_frac:.1f}%"
            )


if __name__ == "__main__":
    main()
