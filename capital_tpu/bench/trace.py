"""Per-phase device-time budgets from real hardware traces.

The reference ships critter's symbol decomposition (autotune/util.h:63-127:
per-phase cp-comp/cp-comm columns); the runtime counterpart here is a
`jax.profiler` device trace of the actual benchmark loop, bucketed by the
``CI::*`` / ``CQR::*`` phase scopes that `tracing.scope` stamps into every
HLO op's metadata.  Wall clocks through the axon tunnel drift 2-3x on a
minutes timescale — per-kernel device *own time* from the trace is the one
measurement immune to that (docs/PERF.md "Measurement discipline"), so this
is the tool that settles where a flagship millisecond actually goes.

CLI::

    python -m capital_tpu.bench.trace cholinv --n 16384 [--bc 512] [--iters 3]
    python -m capital_tpu.bench.trace cacqr --m 1048576 --n 1024

prints one line per phase bucket (device ms per iteration, % of total) plus
a JSON record, from a trace of `iters` in-jit iterations of the same loop
the flagship bench runs.

Parsing: the xplane protobuf's "XLA Ops" line carries one event per HLO op
execution with its self (own) duration; each op's metadata carries the
named_scope chain (``CI.trsm`` etc.), searched longest-scope-first so
nested scopes attribute to the innermost phase, matching critter's
innermost-symbol attribution.
"""

from __future__ import annotations

import argparse
import collections
import glob
import json
import os
import tempfile

import jax
import jax.numpy as jnp

from capital_tpu.utils import tracing


def _phase_tags() -> tuple[str, ...]:
    """Named-scope (dot) forms of the registered phase tags.  Derived from
    tracing.PHASE_REGISTRY — the single source of truth — so a phase added
    to scope() can never be silently bucketed into 'other' here (the fate
    of RT::batch_write under the old hardcoded copy of this tuple).
    Re-evaluated lazily so in-process register_phase() calls are seen."""
    return tuple(t.replace("::", ".") for t in tracing.PHASE_REGISTRY)


#: flagship phase buckets (dot form).  An op whose metadata mentions none
#: of these lands in 'copy' / 'fusion' / 'other' by HLO kind — the
#: catch-alls that caught the round-2 relayout-copy regressions.
PHASE_TAGS = _phase_tags()


def _own_times(line):
    """(metadata_id, own_duration_ps) per event: the 'XLA Ops' line is
    hierarchical (a `while` event spans its whole body), so an op's own time
    is its duration minus the durations of the events it directly contains —
    a stack sweep over (offset, duration)-sorted events."""
    evs = sorted(line.events, key=lambda e: (e.offset_ps, -e.duration_ps))
    out = []
    stack = []  # [end_ps, metadata_id, duration_ps, child_sum]
    for e in evs:
        start, dur = e.offset_ps, e.duration_ps
        while stack and stack[-1][0] <= start:
            fin = stack.pop()
            own = fin[2] - fin[3]
            if stack:
                stack[-1][3] += fin[2]
            out.append((fin[1], own))
        while stack and start + dur > stack[-1][0]:
            # overlapping, not nested (async tails) — close EVERY stacked
            # ancestor the new event outlasts, not just the top, so a tail
            # spanning several ancestors doesn't leave the deeper ones open
            # to absorb the overlap into the wrong phase bucket
            fin = stack.pop()
            own = fin[2] - fin[3]
            if stack:
                stack[-1][3] += fin[2]
            out.append((fin[1], own))
        stack.append([start + dur, e.metadata_id, dur, 0])
    while stack:
        fin = stack.pop()
        own = fin[2] - fin[3]
        if stack:
            stack[-1][3] += fin[2]
        out.append((fin[1], own))
    return out


def _iter_xla_op_events(space):
    """Yield (plane_name, metadata, own_duration_ps, stat_metadata, is_async)
    for every device XLA-op event.  The 'Async XLA Ops' line reports
    in-flight occupancy of DMAs that overlap compute — kept separate
    (occupancy is not additive with op own time)."""
    for plane in space.planes:
        if "TPU" not in plane.name:
            continue
        for line in plane.lines:
            if line.name == "XLA Ops":
                for mid, own_ps in _own_times(line):
                    yield (plane.name, plane.event_metadata.get(mid), own_ps,
                           plane.stat_metadata, False)
            elif line.name == "Async XLA Ops":
                for ev in line.events:
                    md = plane.event_metadata.get(ev.metadata_id)
                    yield (plane.name, md, ev.duration_ps,
                           plane.stat_metadata, True)


def _bucket(md, stat_metadata) -> str:
    """Phase bucket for one op.  The HLO op NAME (XLA names each op after
    the named_scope that produced it: %CI.tmu.90) is authoritative; the
    metadata stats (tf_op paths etc.) often mention *several* scopes for
    fused/derived ops and are only consulted when the name says nothing —
    matching against them first mis-filed tmu kernels under trsm."""

    def match(hay: str) -> str | None:
        best = None
        for tag in _phase_tags():
            if tag in hay and (best is None or len(tag) > len(best)):
                best = tag
        return best

    name = md.name or md.display_name
    best = match(name.split(" = ")[0])  # the op's own %name only
    if best is None:
        hay = name + " " + md.display_name
        for s in md.stats:
            sm = stat_metadata.get(s.metadata_id)
            if sm is not None and sm.name in ("tf_op", "hlo_op", "name_scope"):
                hay += " " + s.str_value
        best = match(hay)
    if best is not None:
        return best.replace(".", "::")
    if "copy" in name:
        return "copy"
    if "fusion" in name:
        return "fusion"
    if "custom-call" in name or "cholesky" in name or "triangular" in name:
        return "custom-call"
    return "other"


def device_budget(run, trace_dir: str | None = None) -> dict[str, float]:
    """Trace `run()` (which must block on completion) and return
    {bucket: device milliseconds} of XLA-op own time for the
    **critical-path device plane** (the plane with the largest total own
    time), plus an 'async (overlapped)' entry for that plane's DMA
    in-flight occupancy (informational — overlaps compute, not additive).

    Per-plane selection matters: on an n-device run every device's own time
    ~equals the wall, so summing planes would report ~n x the true
    per-iteration floor and poison harness.device_ms_per_iter's
    below-floor check (round-3 advisor finding).  Taking the max plane is
    the device-side critical path — the same max-over-ranks convention the
    reference's bench timing uses (bench/cholesky/cholinv.cpp:51-59)."""
    from tensorflow.tsl.profiler.protobuf import xplane_pb2

    with tempfile.TemporaryDirectory() as tmp:
        d = trace_dir or tmp
        with jax.profiler.trace(d):
            run()
        paths = glob.glob(os.path.join(d, "**", "*.xplane.pb"), recursive=True)
        if not paths:
            raise RuntimeError(f"no xplane.pb under {d}")
        spaces = []
        for p in paths:
            space = xplane_pb2.XSpace()
            with open(p, "rb") as f:
                space.ParseFromString(f.read())
            spaces.append((p, space))
    return _critical_plane_budget(spaces)


def _critical_plane_budget(spaces) -> dict[str, float]:
    """{bucket: ms} of the max-total device plane over [(tag, XSpace)]."""
    per_plane: dict[str, dict[str, float]] = collections.defaultdict(
        lambda: collections.defaultdict(float)
    )
    for tag, space in spaces:
        for plane, md, dur_ps, stat_md, is_async in _iter_xla_op_events(space):
            if md is None:
                continue
            key = "async (overlapped)" if is_async else _bucket(md, stat_md)
            # key planes by (source, plane-name): one xplane.pb per host,
            # one plane per local device
            per_plane[f"{tag}::{plane}"][key] += dur_ps * 1e-9  # ps -> ms
    if not per_plane:
        return {}

    def compute_total(buckets):
        return sum(v for k, v in buckets.items() if k != "async (overlapped)")

    crit = max(per_plane.values(), key=compute_total)
    return dict(crit)


def check_copy_fraction(
    budget: dict[str, float], max_frac: float, label: str = ""
) -> float:
    """Gate the 'copy' bucket — schedule-inserted relayout/materialization
    copies, the device-time cost the copy-free explicit routes and the
    persistent tile-cyclic layout exist to remove — at <= ``max_frac`` of
    the plane's compute own-time.  Returns the measured fraction; raises
    RuntimeError on violation so copy regressions fail as loudly as
    collective-inventory regressions (tests/test_collective_audit.py)
    already do.  The async-occupancy row is excluded from both sides
    (it overlaps compute; it is not additive own-time).  The cost-model
    counterpart is the copy_bytes column of tracing.Recorder
    (docs/OBSERVABILITY.md)."""
    compute = {
        k: v for k, v in budget.items() if k != "async (overlapped)"
    }
    total = sum(compute.values())
    frac = (compute.get("copy", 0.0) / total) if total > 0 else 0.0
    if frac > max_frac:
        raise RuntimeError(
            f"copy-budget regression{f' ({label})' if label else ''}: "
            f"copy bucket is {100 * frac:.1f}% of device own-time, "
            f"budget {100 * max_frac:.1f}% — a schedule copy "
            "(take_triangle materialization / whole-buffer "
            "dynamic_update_slice) crept back in"
        )
    return frac


def print_budget(budget: dict[str, float], iters: int, label: str) -> dict:
    budget = dict(budget)
    async_ms = budget.pop("async (overlapped)", 0.0)
    total = sum(budget.values())
    rows = sorted(budget.items(), key=lambda kv: -kv[1])
    print(f"# device-op budget: {label} ({iters} traced iterations)")
    for k, ms in rows:
        print(f"#   {k:16s} {ms / iters:9.3f} ms/iter  {100 * ms / total:5.1f}%")
    print(f"#   {'TOTAL':16s} {total / iters:9.3f} ms/iter")
    if async_ms:
        print(
            f"#   {'async-overlap':16s} {async_ms / iters:9.3f} ms/iter  "
            "(DMA occupancy, overlaps the rows above)"
        )
        rows = rows + [("async (overlapped)", async_ms)]
    rec = {
        "metric": "device_budget",
        "label": label,
        "iters": iters,
        "total_ms_per_iter": round(total / iters, 3),
        "phases_ms_per_iter": {k: round(v / iters, 3) for k, v in rows},
    }
    print(json.dumps(rec))
    return rec


def _cholinv_run(n: int, dtype, bc: int, iters: int, oneshot: bool, prec=None):
    """The flagship loop (bench.py's shape: fori_loop + element coupling),
    compiled once and traced for `iters` iterations."""
    from capital_tpu.models import cholesky
    from capital_tpu.parallel.topology import Grid

    grid = Grid.square(c=1, devices=[jax.devices()[0]])
    cfg = cholesky.CholinvConfig(
        base_case_dim=bc, mode="pallas",
        precision=prec,
        schur_in_place=oneshot,
    )
    eps = jnp.asarray(0.0, jnp.float32)

    if oneshot:
        import importlib.util
        import pathlib

        bench_path = pathlib.Path(__file__).resolve().parents[2] / "bench.py"
        spec = importlib.util.spec_from_file_location("flagship_bench", bench_path)
        bench = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(bench)
        if cholesky.padded_dim(n, bc) != n:
            # same guard as bench.py: cropped outputs cannot serve as the
            # next iteration's p x p carries
            raise SystemExit(
                f"--oneshot needs n = bc * 2^k (n={n}, bc={bc} pads to "
                f"{cholesky.padded_dim(n, bc)})"
            )

        @jax.jit
        def loop(eps, k):
            def body(i, carry):
                acc, Rp, RIp = carry
                a = jax.lax.optimization_barrier(bench.spd_hash(n, dtype, i))
                R, Rinv = cholesky.factor(grid, a, cfg, out_buffers=(Rp, RIp))
                return (
                    acc + eps * (R[0, 0] + Rinv[0, 0]).astype(jnp.float32),
                    R, Rinv,
                )

            Rp0, RIp0 = cholesky.factor_buffers(grid, n, dtype, cfg)
            out, _, _ = jax.lax.fori_loop(
                0, k, body, (jnp.float32(0.0), Rp0, RIp0)
            )
            return out

        def run():
            float(loop(eps, iters))
    else:
        from capital_tpu.bench.drivers import _spd

        A = _spd(n, dtype)

        @jax.jit
        def loop(a, eps, k):
            def body(_, carry):
                R, Rinv = cholesky.factor(grid, carry, cfg)
                d = R[0, 0] + Rinv[0, 0]
                return carry.at[0, 0].add(eps.astype(carry.dtype) * d)

            return jnp.sum(jax.lax.fori_loop(0, k, body, a), dtype=jnp.float32)

        def run():
            float(loop(A, eps, iters))

    run()  # compile + warm
    return run


def _rectri_run(n: int, dtype, bc: int, iters: int, prec=None):
    from capital_tpu.bench.drivers import _tri_operand
    from capital_tpu.models import inverse
    from capital_tpu.parallel.topology import Grid

    grid = Grid.square(c=1, devices=[jax.devices()[0]])
    cfg = inverse.RectriConfig(
        base_case_dim=bc, mode="pallas",
        precision=prec,
    )
    T = _tri_operand(n, dtype)
    eps = jnp.asarray(0.0, jnp.float32)

    @jax.jit
    def loop(a, eps, k):
        def body(_, carry):
            inv = inverse.rectri(grid, carry, "L", cfg)
            return carry.at[0, 0].add(eps.astype(carry.dtype) * inv[0, 0])

        return jnp.sum(jax.lax.fori_loop(0, k, body, a), dtype=jnp.float32)

    def run():
        float(loop(T, eps, iters))

    run()
    return run


def _cacqr_run(m: int, n: int, dtype, bc: int, iters: int, prec=None):
    from capital_tpu.models import cholesky, qr
    from capital_tpu.parallel.topology import Grid

    grid = Grid.square(c=1, devices=[jax.devices()[0]])
    precision = prec
    cfg = qr.CacqrConfig(
        num_iter=2, mode="pallas",
        cholinv=cholesky.CholinvConfig(
            base_case_dim=bc, mode="pallas", precision=precision
        ),
        precision=precision,
    )
    A = jax.block_until_ready(
        jax.random.normal(jax.random.key(0), (m, n), dtype=dtype)
    )
    eps = jnp.asarray(0.0, jnp.float32)

    @jax.jit
    def loop(a, eps, k):
        def body(_, carry):
            Q, R = qr.factor(grid, carry, cfg)
            return Q.at[: R.shape[0], : R.shape[1]].add(R.astype(Q.dtype))

        return jnp.sum(jax.lax.fori_loop(0, k, body, a), dtype=jnp.float32)

    def run():
        float(loop(A, eps, iters))

    run()
    return run


def _trsm_run(n: int, nrhs: int, dtype, bc: int, iters: int, prec=None):
    from capital_tpu.bench.drivers import _tri_operand
    from capital_tpu.models import trsm as trsm_mod
    from capital_tpu.parallel.topology import Grid

    grid = Grid.square(c=1, devices=[jax.devices()[0]])
    cfg = trsm_mod.TrsmConfig(
        base_case_dim=bc, mode="xla",
        precision=prec,
    )
    L = _tri_operand(n, dtype)
    B = jax.block_until_ready(
        jax.random.normal(jax.random.key(1), (n, nrhs), dtype=dtype)
    )
    eps = jnp.asarray(0.0, jnp.float32)

    @jax.jit
    def loop(op, eps, k):
        Lo, B0 = op

        def body(_, carry):
            X = trsm_mod.solve(grid, Lo, carry, side="L", uplo="L", cfg=cfg)
            return carry + eps.astype(carry.dtype) * X

        return jnp.sum(jax.lax.fori_loop(0, k, body, B0), dtype=jnp.float32)

    def run():
        float(loop((L, B), eps, iters))

    run()
    return run


def main(argv=None) -> None:
    p = argparse.ArgumentParser(prog="capital_tpu.bench.trace")
    p.add_argument("algo", choices=["cholinv", "cacqr", "rectri", "trsm"])
    p.add_argument("--n", type=int, default=16384)
    p.add_argument("--m", type=int, default=1 << 20)
    p.add_argument("--bc", type=int, default=512)
    p.add_argument("--dtype", default="bfloat16")
    p.add_argument("--iters", type=int, default=3)
    p.add_argument("--oneshot", action="store_true",
                   help="cholinv: trace the one-shot regen loop (the large-n "
                        "flagship protocol) instead of the carry loop")
    p.add_argument("--trace-dir", default=None,
                   help="keep the raw trace here instead of a temp dir")
    p.add_argument("--max-copy-frac", type=float, default=None,
                   help="fail (non-zero exit) if the 'copy' bucket exceeds "
                        "this fraction of device own-time — the CI gate for "
                        "schedule-copy regressions (see "
                        "trace.check_copy_fraction)")
    p.add_argument("--precision", default=None,
                   choices=["default", "high", "highest"],
                   help="override the matmul precision ('high' traces the "
                        "f32 3-pass family, 'default' the TPU-default "
                        "1-pass) — same semantics as the drivers CLI")
    args = p.parse_args(argv)
    dtype = jnp.dtype(args.dtype)
    # ONE precision rule shared with the drivers CLI (drivers._precision):
    # 'default' -> None (TPU default), unset -> the dtype rule
    from capital_tpu.bench.drivers import _precision

    prec = _precision(args, dtype)
    ptag = f" prec={args.precision}" if args.precision else ""

    if args.algo == "cholinv":
        run = _cholinv_run(args.n, dtype, args.bc, args.iters, args.oneshot, prec)
        label = f"cholinv n={args.n} bc={args.bc} {dtype}" + (
            " oneshot" if args.oneshot else ""
        ) + ptag
    elif args.algo == "rectri":
        run = _rectri_run(args.n, dtype, args.bc, args.iters, prec)
        label = f"rectri n={args.n} bc={args.bc} {dtype}" + ptag
    elif args.algo == "trsm":
        nrhs = min(args.m, args.n)
        run = _trsm_run(args.n, nrhs, dtype, args.bc, args.iters, prec)
        label = f"trsm n={args.n} nrhs={nrhs} bc={args.bc} {dtype}" + ptag
    else:
        run = _cacqr_run(args.m, args.n, dtype, args.bc, args.iters, prec)
        label = f"cacqr {args.m}x{args.n} {dtype}" + ptag

    budget = device_budget(run, args.trace_dir)
    print_budget(budget, args.iters, label)
    if args.max_copy_frac is not None:
        frac = check_copy_fraction(budget, args.max_copy_frac, label)
        print(
            f"# copy budget OK: {100 * frac:.1f}% <= "
            f"{100 * args.max_copy_frac:.1f}%"
        )


if __name__ == "__main__":
    main()
