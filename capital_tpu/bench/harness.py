"""Shared benchmark harness: timing discipline + result reporting.

The reference's drivers (bench/*/*.cpp) share a fixed shape: parse argv,
build a topology, distribute a matrix, warm up, run timed iterations under
`MPI_Barrier; MPI_Wtime`, and print the max-over-ranks wall time
(bench/cholesky/cholinv.cpp:44-59).  On TPU the same discipline needs two
changes:

* async dispatch means host-side walls lie — so the iteration loop runs
  INSIDE one jit (`lax.fori_loop` with a data-dependent carry that consumes
  every algorithm output, preventing dead-code elimination of the work), and
  the per-iteration time is the delta between an (iters+1)-iteration run and
  a 1-iteration run, which also cancels the fixed dispatch/tunnel overhead;
* "max over ranks" is automatic — one XLA program spans the mesh, so the
  wall covers the slowest chip.

Each driver prints ONE JSON line: {"metric", "value", "unit",
"vs_baseline", ...context}.  `vs_baseline` is achieved/target where the
target is 90% of the chip's peak dense-matmul throughput at the bench dtype
(BASELINE.md: the reference publishes no absolute numbers, so the
peak-relative north star *is* the baseline).
"""

from __future__ import annotations

import dataclasses
import json
import sys
import time
from typing import Callable

import jax
import jax.numpy as jnp

from capital_tpu.utils import tracing

# The device-trace floor machinery (bench/trace.device_budget) can fail for
# exactly these reasons: the xplane protobuf import is unavailable
# (ImportError), the profiler emitted no xplane.pb / an unreadable one
# (RuntimeError / OSError), or a malformed plane parses to nonsense
# (ValueError).  Anything else — XlaRuntimeError from the measured program,
# KeyboardInterrupt, bugs — must PROPAGATE: the old bare `except Exception`
# here swallowed real failures into a silent "no floor".
TRACE_FLOOR_ERRORS = (ImportError, OSError, RuntimeError, ValueError)


def _warn(msg: str) -> None:
    print(f"# harness: {msg}", file=sys.stderr)


def peak_tflops(device=None, dtype=jnp.bfloat16) -> float:
    """Peak dense-matmul TFLOP/s for one chip at `dtype` (public specs)."""
    return tracing.device_spec(device).peak_tflops(dtype)


class MeasurementUnresolved(RuntimeError):
    """timed_loop could not resolve a positive per-iteration time — the step
    is below the host-wall noise floor even at the escalated trip cap.
    Distinct from generic RuntimeError so sweep drivers can skip noise-floor
    configs without also swallowing real failures (XlaRuntimeError — OOM,
    compile errors — subclasses RuntimeError)."""


# The runtime failure class the containment layer bounds: OOMs, compile
# errors, device aborts.  jax.errors.JaxRuntimeError IS the XlaRuntimeError
# alias in current jax; the tuple exists so a jaxlib rename stays a one-line
# fix here instead of a hunt through every sweep driver.
RUNTIME_FAILURES = (jax.errors.JaxRuntimeError,)


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """Bounded retry/backoff for per-config runtime failures in a sweep.

    retries: attempts AFTER the first (0 = fail immediately).  Default 1:
        transient device OOMs (fragmentation after a big predecessor
        config) often clear on a retry; deterministic failures shouldn't
        burn more than one.
    backoff_s / multiplier: sleep before attempt k is
        backoff_s * multiplier**(k-1) — gives the runtime a beat to release
        buffers before the retry."""

    retries: int = 1
    backoff_s: float = 0.25
    multiplier: float = 2.0


class ConfigFailed(RuntimeError):
    """One sweep config exhausted its RetryPolicy on runtime failures.
    Carries the attempt count and the final cause so the sweep can persist
    a useful failure record instead of a bare traceback."""

    def __init__(self, label: str, attempts: int, cause: BaseException):
        super().__init__(
            f"{label} failed after {attempts} attempt(s): "
            f"{type(cause).__name__}: {cause}"
        )
        self.label = label
        self.attempts = attempts
        self.cause = cause


def run_guarded(
    fn: Callable[[], object],
    policy: RetryPolicy = RetryPolicy(),
    label: str = "config",
) -> tuple[object, int]:
    """Run fn() with the bounded retry/backoff of `policy`; returns
    (result, attempts).  Catches ONLY RUNTIME_FAILURES — an OOM/compile
    abort of this config must not kill the whole sweep — and re-raises as
    ConfigFailed once the policy is exhausted.  MeasurementUnresolved and
    every other exception propagate untouched (they already have their own
    handling story in the callers)."""
    delay = policy.backoff_s
    attempt = 0
    while True:
        attempt += 1
        try:
            return fn(), attempt
        except RUNTIME_FAILURES as e:
            if attempt > policy.retries:
                raise ConfigFailed(label, attempt, e) from e
            _warn(
                f"{label} attempt {attempt} failed "
                f"({type(e).__name__}); retrying in {delay:.2f}s"
            )
            time.sleep(delay)
            delay *= policy.multiplier


def noise_band_seconds() -> float:
    """The dispatch-noise band a measured delta must clear to be trusted:
    ~50ms on the TPU tunnel (~70ms fixed dispatch + multi-ms jitter)."""
    import jax as _jax

    return 0.05 if _jax.default_backend() == "tpu" else 0.002


def percentiles(
    samples, points: tuple[float, ...] = (50.0, 95.0, 99.0)
) -> dict[str, float]:
    """Nearest-rank percentiles of raw samples: {'p50': ..., 'p95': ...,
    'p99': ...}.  The ONE quantile implementation shared by the bench
    report lines (drivers._timed's wall_ms block) and the serving layer's
    latency stats (serve/stats.py) — duplicated quantile code is how two
    dashboards end up disagreeing about the same run.

    Nearest-rank (ceil) deliberately: every reported value is a sample that
    actually occurred, so a p99 can be shown next to the raw max without
    interpolation artifacts.  Dependency-free (no numpy) so stats paths add
    zero imports."""
    s = sorted(samples)
    if not s:
        raise ValueError("percentiles() needs at least one sample")
    import math

    out = {}
    for p in points:
        if not 0.0 < p <= 100.0:
            raise ValueError(f"percentile point {p} outside (0, 100]")
        rank = max(1, math.ceil(p / 100.0 * len(s)))
        label = f"p{int(p)}" if float(p).is_integer() else f"p{p}"
        out[label] = s[rank - 1]
    return out


def latency_samples(fn, calls: int = 32, warmup: int = 3) -> list[float]:
    """Per-call wall seconds of `fn()` — the SERVING-latency protocol, the
    deliberate opposite of timed_loop's in-jit amortized one: each sample
    is one dispatch + one device round-trip (block_until_ready), because a
    served request pays exactly that, and a p99 over amortized loop bodies
    would hide the dispatch tail a latency SLO exists to catch.  Compile
    time stays out via the warmup calls.  Feed the result to
    `percentiles()` — the shared quantile rule keeps a bench latency row
    and a serve request_stats record on one scale."""
    import time

    if calls < 1:
        raise ValueError(f"latency_samples needs calls >= 1, got {calls}")
    for _ in range(max(warmup, 1)):
        jax.block_until_ready(fn())
    samples = []
    for _ in range(calls):
        t0 = time.perf_counter()
        jax.block_until_ready(fn())
        samples.append(time.perf_counter() - t0)
    return samples


def _resolve_delta(
    run, k: int, cap: int, repeats: int, noise: float, samples_out=None
) -> tuple[float, float, int]:
    """The one escalate-until-the-delta-clears-the-noise-band loop shared by
    every protocol (timed_loop, timed_oneshot x2): returns (per-iter
    seconds, raw delta, final trip count).  Callers decide what an
    unresolved result means."""
    t, delta = paired_median_delta(run, k, repeats, samples_out)
    while k < cap and delta < noise:
        k = min(cap, max(k * 2, int(3.0 * noise / max(t, 1e-9))))
        if samples_out is not None:
            samples_out.clear()  # samples from a rejected trip count
        t, delta = paired_median_delta(run, k, repeats, samples_out)
    return t, delta, k


def paired_median_delta(
    run, k: int, nrep: int, samples_out=None
) -> tuple[float, float]:
    """(per-iteration seconds, raw delta): median over INTERLEAVED
    (base, full) wall pairs of `run(1)` vs `run(k+1)`.

    The one measurement protocol shared by the flagship bench.py and
    timed_loop.  Adjacent pairs share a drift window, so the delta isolates
    the in-jit iterations; sampling all bases then all fulls lets monotone
    drift between the blocks bias the result (observed: 16.8 ms/iter
    reported for a step whose device-counter op time is 26.6 ms and whose
    200-iteration sustained marginal is 24.9 ms).  The median rejects
    jitter outliers — a single paired delta can even go negative for sub-ms
    steps, which once let an autotune sweep crown a config with a negative
    "time".

    `samples_out` (a list) collects the raw per-iteration seconds of each
    pair (delta / k) for percentile reporting (percentiles()); individual
    samples keep the jitter the median rejects — including possible
    negatives — which is exactly what a spread statistic should see."""
    import statistics

    deltas = []
    for _ in range(nrep):
        b = run(1)
        f = run(k + 1)
        deltas.append(f - b)
    if samples_out is not None:
        samples_out.extend(d / k for d in deltas)
    d = statistics.median(deltas)
    return d / k, d


def _make_loop(step: Callable, coupling: str):
    """The one in-jit measurement loop shared by timed_loop and
    device_ms_per_iter — both must run the SAME program or the device
    floor would not be the wall's floor."""

    @jax.jit
    def loop(a, eps, k):
        def body(_, carry):
            out = step(carry)
            e = eps.astype(carry.dtype)
            if coupling == "elem":
                return carry.at[0, 0].add(e * out[0, 0])
            return carry + e * out

        out = jax.lax.fori_loop(0, k, body, a)
        return jnp.sum(out, dtype=jnp.float32)

    return loop


def device_ms_per_iter(
    step: Callable[[jnp.ndarray], jnp.ndarray],
    operand: jnp.ndarray,
    iters: int = 3,
    coupling: str = "full",
    loop=None,
) -> float:
    """Device-op own-time per iteration of the SAME in-jit loop timed_loop
    measures, from jax.profiler traces — the drift-immune floor a wall
    reading must not undercut (a wall below it is a favorable-drift
    artifact, docs/PERF.md "Measurement discipline").  Measured as a
    PAIRED DELTA, device_total(iters+1) - device_total(1), exactly like
    the wall protocol: a single run's total would include the per-call
    epilogue (the full-operand DCE sum — ~2.6 ms at the 1M x 1024 proxy)
    that the wall's delta cancels, and the floor would sit above honest
    walls.  Returns 0.0 when no device plane exists (CPU rigs) — callers
    skip the guard then.  Pass `loop` (a _make_loop product) to share the
    compiled program with timed_loop."""
    from capital_tpu.bench import trace as trace_mod

    loop = loop or _make_loop(step, coupling)
    eps = jnp.asarray(0.0, jnp.float32)
    float(loop(operand, eps, 1))  # compile + warm outside the trace

    def total(k: int) -> float:
        budget = trace_mod.device_budget(lambda: float(loop(operand, eps, k)))
        budget.pop("async (overlapped)", None)
        return sum(budget.values())

    try:
        return max(0.0, (total(iters + 1) - total(1)) / iters)
    except TRACE_FLOOR_ERRORS as e:
        if isinstance(e, jax.errors.JaxRuntimeError):
            raise  # a device-side failure of the measured program itself
        _warn(
            f"device trace unavailable ({type(e).__name__}: {e}); "
            "no device floor, wall stands"
        )
        return 0.0


def timed_loop(
    step: Callable[[jnp.ndarray], jnp.ndarray],
    operand: jnp.ndarray,
    iters: int = 3,
    repeats: int = 3,
    coupling: str = "full",
    loop=None,
    samples_out=None,
) -> float:
    """Per-iteration seconds of `step`, run `iters` times inside jit —
    the median over interleaved (1-trip, iters+1-trip) wall pairs
    (paired_median_delta); escalates the trip count when the delta is below
    the tunnel noise band.  Raises if it never resolves.

    `step(operand) -> array of operand's shape/dtype` must consume all the
    outputs it wants timed (see module docstring on DCE).  The perturbation
    scalar `eps` is 0.0 at call time but runtime-valued, so XLA cannot fold
    the iteration chain away.

    The default carry consumes the step output with a FULL-matrix add,
    deliberately: for arbitrary steps (xla-mode SUMMA, plain matmul chains)
    a one-element coupling would let the algebraic simplifier legitimately
    narrow slices into the producing ops and shrink the measured work.
    bench.py's flagship loop uses the cheaper element coupling only because
    its outputs come through chains of aliased pallas custom calls XLA
    cannot slice through (verified on-device — see the comment there).
    The cost: up to ~4 extra HBM passes of harness overhead per iteration,
    so suite/autotune numbers are slightly conservative.

    coupling='elem' opts a driver into the one-element carry
    (`carry[0,0] += eps·out[0,0]`): ONLY valid when the step's output
    arrives through ops XLA cannot narrow a slice into — pallas custom
    calls, full-input consumers like cholesky.  The cacqr pallas driver
    qualifies (Q is a pallas kernel output; R rides potrf, whose input
    gram is consumed whole) and its tall Q-sized full-add was ~5 ms/iter
    of pure harness overhead at the 1M x 1024 BASELINE proxy.
    """

    loop = loop or _make_loop(step, coupling)
    eps = jnp.asarray(0.0, jnp.float32)

    def run(k: int) -> float:
        t0 = time.perf_counter()
        float(loop(operand, eps, k))  # host transfer = real sync
        return time.perf_counter() - t0

    run(1)  # compile (dynamic trip count -> one executable reused for both k)
    t, delta = paired_median_delta(run, iters, repeats + 2, samples_out)
    # Escalate the trip count until the DELTA clears the noise band: a
    # positive but small delta is still mostly noise (a ~2ms step was
    # observed reporting 13ms when the total delta sat at ~40ms).
    noise = noise_band_seconds()
    if delta < noise:
        if samples_out is not None:
            samples_out.clear()  # below-noise samples from the first pass
        t, delta, k = _resolve_delta(run, iters, 4096, repeats, noise,
                                     samples_out)
    else:
        k = iters
    if t <= 0.0 or delta < noise:
        # never resolved: refuse to return a fake number (a silent floor
        # once let a noise artifact win an autotune sweep; a positive delta
        # still inside the noise band at the trip-count cap is the same
        # artifact with extra steps)
        raise MeasurementUnresolved(
            f"timed_loop could not resolve a per-iteration time (delta "
            f"{delta:.3e}s after {k} iterations is inside the "
            f"{noise:.0e}s dispatch-noise band)"
        )
    return t


def timed_oneshot(
    gen: Callable[[jnp.ndarray], jnp.ndarray],
    step: Callable[[jnp.ndarray], jnp.ndarray],
    iters: int = 3,
    repeats: int = 8,
    device_check: bool = False,
) -> tuple[float, float, dict]:
    """The one-shot protocol (bench.py's large-n flagship discipline, made
    reusable): the operand is REGENERATED inside the loop each iteration by
    `gen(i)` (a fused elementwise program of the loop index — no persistent
    operand carry, so peak memory excludes it) and `step(a)` must return a
    scalar coupling value riding ops XLA cannot narrow (pallas chains /
    whole-input consumers — the caller asserts this, e.g.
    qr.pallas_coupled).  A regen-only loop is measured separately and
    subtracted; the subtracted time must clear the noise band on its own.
    Returns (net seconds/iter, regen seconds/iter, extras) — extras carries
    the drift-guard fields (device_ms, wall_ms_below_floor) when
    device_check measures a device floor for the net time."""

    def make_loop(consume):
        @jax.jit
        def loop(eps, k):
            def body(i, c):
                a = jax.lax.optimization_barrier(gen(i))
                return c + eps * consume(a)

            return jax.lax.fori_loop(0, k, body, jnp.float32(0.0))

        return loop

    full = make_loop(lambda a: step(a).astype(jnp.float32))
    regen = make_loop(lambda a: a[0, 0].astype(jnp.float32))
    eps = jnp.asarray(0.0, jnp.float32)

    def run(loop, k):
        t0 = time.perf_counter()
        float(loop(eps, k))
        return time.perf_counter() - t0

    run(full, 1), run(full, 1)  # compile + settle
    noise = noise_band_seconds()
    t, delta, iters = _resolve_delta(
        lambda k: run(full, k), iters, 512, repeats, noise
    )
    if t <= 0.0 or delta < noise:
        raise MeasurementUnresolved(
            f"one-shot full loop unresolved (delta {delta:.3e}s at {iters})"
        )
    run(regen, 1)
    tr, dr, kr = _resolve_delta(
        lambda k: run(regen, k), max(iters, 16), 4096, repeats, noise
    )
    if dr < noise:
        raise MeasurementUnresolved(
            f"one-shot regen loop unresolved (delta {dr:.3e}s at {kr})"
        )
    net = t - tr
    if net <= 0.0 or net * iters < noise:
        raise MeasurementUnresolved(
            f"one-shot net time {net:.3e}s/iter inside the noise band"
        )
    if device_check:
        # the drift guard for the one-shot protocol: the NET device floor
        # is the paired-delta difference of the two loops' device-op
        # totals (same discipline as the walls); a net wall below it is
        # re-measured, then floored — mirrors drivers._timed
        from capital_tpu.bench import trace as trace_mod

        def dev_total(loop, k):
            budget = trace_mod.device_budget(lambda: float(loop(eps, k)))
            budget.pop("async (overlapped)", None)
            return sum(budget.values()) / 1e3  # ms -> s

        try:
            dfull = dev_total(full, iters + 1) - dev_total(full, 1)
            dregen = dev_total(regen, iters + 1) - dev_total(regen, 1)
            dnet = max(0.0, (dfull - dregen) / iters)
        except TRACE_FLOOR_ERRORS as e:
            if isinstance(e, jax.errors.JaxRuntimeError):
                raise  # device-side failure of the measured program
            _warn(
                f"one-shot device floor unavailable ({type(e).__name__}: "
                f"{e}); wall stands unfloored"
            )
            dnet = 0.0
        if dnet > 0.0:
            tries = 0
            while net < dnet and tries < 2:
                t2, d2, _ = _resolve_delta(
                    lambda k: run(full, k), iters, 512, repeats, noise
                )
                if d2 >= noise:
                    net = t2 - tr
                tries += 1
            if net < dnet:
                return dnet, tr, {"device_ms": round(dnet * 1e3, 3),
                                  "wall_ms_below_floor": round(net * 1e3, 3)}
            return net, tr, {"device_ms": round(dnet * 1e3, 3)}
    return net, tr, {}


def report(
    metric: str,
    seconds: float,
    flops: float,
    dtype,
    device=None,
    **context,
) -> dict:
    """Print + return the one-line JSON record.

    schema_version/device/platform make the line self-identifying so
    ``obs diff`` can refuse to compare records from incompatible schemas
    or different chips (docs/OBSERVABILITY.md)."""
    from capital_tpu.obs.ledger import SCHEMA_VERSION

    device = device or jax.devices()[0]
    tflops = flops / seconds / 1e12
    target = 0.9 * peak_tflops(device, dtype)
    rec = {
        "metric": metric,
        "schema_version": SCHEMA_VERSION,
        "value": round(tflops, 3),
        "unit": "TFLOP/s",
        "vs_baseline": round(tflops / target, 4),
        "seconds": round(seconds, 5),
        "dtype": str(jnp.dtype(dtype)),
        "device": device.device_kind,
        "platform": jax.default_backend(),
        "target_tflops": round(target, 1),
        **context,
    }
    print(json.dumps(rec))
    return rec
