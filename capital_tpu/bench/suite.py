"""The BASELINE.json benchmark suite — the reference's de-facto config set.

Five configs (BASELINE.md "Benchmark configurations"):
  1. single-device blocked Cholesky, N=4096
  2. single-device CQR2 tall-skinny QR, 65536 x 512
  3. recursive comm-avoiding Cholesky on a 2x2 grid face, N=16384
  4. CQR2 across 8 devices, tall-skinny 2M x 1024
  5. SPD inverse via Cholesky (+ the autotune sweep lives in
     capital_tpu.autotune, run separately)

Multi-device configs run when the platform has enough devices (real chips,
or a CPU mesh under --xla_force_host_platform_device_count); otherwise they
fall back to all available devices and say so.  --scale divides the problem
sizes for smoke runs on the test rig.

Every row inherits the base argv via _args, so ``--ledger PATH`` on the
suite invocation makes each driver append its unified obs ledger record
(manifest + model costs + program audit + measured + residuals) — one
``python -m capital_tpu.bench suite --ledger runs.jsonl`` captures the
whole BASELINE set for later ``python -m capital_tpu.obs diff``.
"""

from __future__ import annotations

import argparse

import jax


def _args(base: argparse.Namespace, **over) -> argparse.Namespace:
    ns = argparse.Namespace(**vars(base))
    for k, v in over.items():
        setattr(ns, k, v)
    return ns


def run(base: argparse.Namespace, scale: int = 1) -> list[dict]:
    from capital_tpu.bench import drivers

    scale = getattr(base, "scale", scale) or scale
    ndev = len(jax.devices())
    # drift guard on by default where drift exists (the TPU tunnel):
    # suite rows carry device_ms and a wall may never undercut it
    # (VERDICT r2 weak #4; harmless no-op on CPU rigs — no device plane)
    if jax.default_backend() == "tpu":
        base.device_check = True
    out = []

    def go(name, fn, **over):
        print(f"# suite: {name}")
        out.append(fn(_args(base, **over)))

    go("cholesky N=4096 single-device", drivers.cholinv,
       n=max(256, 4096 // scale), devices=1)
    go("cacqr2 65536x512 single-device", drivers.cacqr,
       m=max(1024, 65536 // scale), n=max(64, 512 // scale), devices=1,
       variant=2)
    d4 = 4 if ndev >= 4 else 1
    go(f"recursive cholesky N=16384 2x2 grid ({d4} devices)", drivers.cholinv,
       n=max(512, 16384 // scale), devices=d4, c=1)
    d8 = 8 if ndev >= 8 else ndev
    # the 2M x 1024 row is the BASELINE 8-rank configuration; since round 3
    # it runs at FULL m even on one chip — but ONLY when the driver's
    # one-shot regen protocol can engage (single device, pallas-coupled
    # shapes; the carry loop needs ~4 Q-sized buffers — measured "Used
    # 16.01G of 15.75G").  Non-eligible configs (xla/explicit mode, scaled
    # n without the g=2 split, 1 < devices < 8) keep the per-device-scaled
    # m of rounds 1-2 rather than walking into the known OOM.
    from capital_tpu.models import qr as _qr
    from capital_tpu.parallel.topology import Grid as _Grid

    n8 = max(128, 1024 // scale)
    if d8 == 1:
        g1 = _Grid.square(c=1, devices=jax.devices()[:1])
        mode8 = drivers._resolve_mode(base.mode, g1)
        full_ok = _qr.pallas_coupled(g1, n8, mode8)
    else:
        full_ok = d8 >= 8  # 8 devices shard the carry; odd counts scale
    m8 = max(2048, (2**21 if full_ok else 2**21 * d8 // 8) // scale)
    go(f"cacqr2 2Mx1024 tree ({d8} devices, m={m8})", drivers.cacqr,
       m=m8, n=n8, devices=d8, variant=2)
    go("spd inverse via cholesky", drivers.spd_inverse,
       n=max(256, 4096 // scale))
    return out


def main(argv=None) -> None:
    from capital_tpu.bench import drivers

    args = drivers.build_parser().parse_args(argv or ["suite"])
    run(args)


if __name__ == "__main__":
    main()
