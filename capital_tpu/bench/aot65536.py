"""AOT witness for the N=65536 / v5e-8 BASELINE north-star row.

The rig has ONE v5e chip behind a tunnel; the BASELINE.md target names
"Cholesky & QR throughput, N=65536 ... TPU v5e-8".  What CAN be produced
without 8 chips (VERDICT r3 #2) is the real 8-chip program, compiled by the
real TPU toolchain: `jax.experimental.topologies.get_topology_desc` builds
a deviceless v5e-8 topology, the full distributed cholinv factor step
(explicit shard_map SUMMA schedule, tile-cyclic balancing, in-place Schur)
is jitted against it, and XLA's memory analysis + the emitted collective
schedule are committed as the artifact — per-chip peak HBM, argument/
output/temp footprints, and the collective op census, plus the cost-model
step-time projection against measured single-chip kernel rates.

CLI::

    python -m capital_tpu.bench.aot65536 [--n 65536] [--bc 512] [--c 2]
        [--out docs/N65536_V5E8.md]

Reference: the 8-rank schedule this witnesses is the reference's
cholinv.hpp:87-165 recursion over a d x d x c topology (topology.h:77-94).
"""

from __future__ import annotations

import argparse
import collections
import json
import re

import jax
import jax.numpy as jnp


def build(n: int, bc: int, c: int, balance: str, schur_in_place: bool):
    from jax.experimental import topologies

    from capital_tpu.models import cholesky
    from capital_tpu.parallel.topology import Grid

    topo = topologies.get_topology_desc(platform="tpu", topology_name="v5e:2x4")
    devs = topo.devices
    grid = Grid.square(c=c, devices=devs)
    cfg = cholesky.CholinvConfig(
        base_case_dim=bc, split=1, mode="explicit", balance=balance,
        schur_in_place=schur_in_place,
    )

    def fn(A):
        return cholesky.factor(grid, A, cfg)

    shape = jax.ShapeDtypeStruct((n, n), jnp.bfloat16, sharding=grid.face_sharding())
    return grid, cfg, fn, shape


def build_cacqr(m: int, n: int, bc: int):
    """The 8-rank CQR2 program for BASELINE's QR north-star row (2M x 1024
    "8-rank" configuration): tall-skinny X row-sharded over all 8 chips of
    the deviceless v5e-8 topology, the 1d tree regime (reference
    cacqr.hpp:103's panel pipeline over the flat communicator)."""
    from jax.experimental import topologies

    from capital_tpu.models import cholesky, qr
    from capital_tpu.parallel.topology import Grid

    topo = topologies.get_topology_desc(platform="tpu", topology_name="v5e:2x4")
    grid = Grid.flat(devices=topo.devices)
    # mode='pallas': the fused tall-pass kernels run PER SHARD inside the
    # shard_map pipeline (qr._cqr2_fused_sharded) — this witness is the
    # compile certificate that Mosaic custom calls work under the manual
    # partitioning (round-5; the GSPMD path cannot partition them)
    cfg = qr.CacqrConfig(
        num_iter=2, regime="1d", mode="pallas",
        cholinv=cholesky.CholinvConfig(base_case_dim=bc),
    )

    def fn(X):
        Q, R = qr.factor(grid, X, cfg)
        return Q, R

    shape = jax.ShapeDtypeStruct((m, n), jnp.bfloat16, sharding=grid.rows_sharding())
    return grid, cfg, fn, shape


def collective_census(text: str) -> dict[str, int]:
    """Count collective HLO *instructions* in the compiled module text.

    Only opcode positions count: the token right after the `=` of an
    instruction definition (`%all-gather.1 = bf16[...] all-gather(...)`
    names the instruction after its opcode, and operand references repeat
    the name — matching bare words over-counted every collective 2-3x,
    round-4 review finding).  Async pairs count once, at -start."""
    pat = re.compile(
        r"= *[^=\n]*?\b(all-gather|all-reduce|reduce-scatter|"
        r"collective-permute|all-to-all|collective-broadcast)"
        r"(-start)?\("
    )
    counts: collections.Counter = collections.Counter()
    for line in text.splitlines():
        m = pat.search(line)
        if m:
            counts[m.group(1)] += 1
    return dict(counts)


def cost_projection(grid, fn, shape, n: int, useful_flops: float | None = None) -> dict:
    """Trace-time cost-model projection: per-chip executed flops and comm
    bytes from the tracing Recorder, turned into a step-time band with the
    measured kernel rates (docs/PERF.md: 169-186 TF/s sustained executed on
    the balanced kernels) and the framework's own DeviceSpec ICI figure
    (utils/tracing.py — the same constant every other cost table uses)."""
    from capital_tpu.utils import tracing

    with tracing.Recorder() as rec:
        jax.eval_shape(fn, shape)
    # the cost model (tracing.gemm_cost etc.) emits PER-DEVICE flops and
    # comm bytes — the Recorder totals are already per-chip
    per_chip_flops = sum(s.flops for s in rec.stats.values())
    per_chip_comm = sum(s.comm_bytes for s in rec.stats.values())
    ncoll = sum(s.collectives for s in rec.stats.values())
    lo, hi = 169e12, 186e12  # measured sustained executed TF/s band
    ici = tracing.device_spec().ici_gbps * 1e9
    comp_ms = (per_chip_flops / hi * 1e3, per_chip_flops / lo * 1e3)
    comm_ms = per_chip_comm / ici * 1e3
    useful = useful_flops if useful_flops is not None else 2.0 * n**3 / 3.0
    return {
        "useful_flops": useful,
        "per_chip_executed_tflop": per_chip_flops / 1e12,
        "per_chip_comm_bytes": per_chip_comm,
        "collective_calls_modeled": ncoll,
        "comp_ms_band": [round(comp_ms[0], 1), round(comp_ms[1], 1)],
        "comm_ms": round(comm_ms, 1),
        "step_ms_band": [
            round(comp_ms[0] + comm_ms, 1),
            round(comp_ms[1] + comm_ms, 1),
        ],
        "useful_tflops_per_chip_band": [
            round(useful / grid.num_devices / (comp_ms[1] + comm_ms) / 1e9, 1),
            round(useful / grid.num_devices / (comp_ms[0] + comm_ms) / 1e9, 1),
        ],
    }


def _compile_and_measure(fn, shape):
    """lower -> compile -> per-chip memory analysis -> collective census:
    the one copy of the compile-and-measure sequence both witness paths
    share."""
    lowered = jax.jit(fn).lower(shape)
    print("# lowered OK")
    compiled = lowered.compile()
    print("# compiled OK (real XLA:TPU codegen for the 8-chip program)")
    ma = compiled.memory_analysis()
    mem = {
        "argument_bytes": ma.argument_size_in_bytes,
        "output_bytes": ma.output_size_in_bytes,
        "temp_bytes": ma.temp_size_in_bytes,
        "alias_bytes": ma.alias_size_in_bytes,
        "peak_memory_bytes": ma.peak_memory_in_bytes,
        "generated_code_bytes": ma.generated_code_size_in_bytes,
    }
    print("# per-chip memory:", json.dumps(mem))
    census = collective_census(compiled.as_text())
    print("# collective census:", json.dumps(census))
    return mem, census


# XLA's per-chip byte limit on v5e as it reports it (decimal GB: the
# round-3 OOM messages read "Used 16.01G of 15.75G")
HBM_V5E = 15.75e9


def _gib(b):
    return b / 1e9


def _mem_table(mem, arg_label, out_label):
    """The per-chip memory markdown table both witness artifacts share."""
    return (
        "| quantity | bytes | GB |\n"
        "|---|---|---|\n"
        f"| arguments ({arg_label}) | {mem['argument_bytes']} | {_gib(mem['argument_bytes']):.2f} |\n"
        f"| outputs ({out_label}) | {mem['output_bytes']} | {_gib(mem['output_bytes']):.2f} |\n"
        f"| temporaries | {mem['temp_bytes']} | {_gib(mem['temp_bytes']):.2f} |\n"
        f"| **peak HBM** | **{mem['peak_memory_bytes']}** | **{_gib(mem['peak_memory_bytes']):.2f}** |"
    )


def main(argv=None):
    p = argparse.ArgumentParser(prog="capital_tpu.bench.aot65536")
    p.add_argument("--alg", choices=["cholinv", "cacqr"], default="cholinv")
    p.add_argument("--n", type=int, default=None,
                   help="65536 for cholinv, 1024 for cacqr unless set")
    p.add_argument("--m", type=int, default=1 << 21, help="cacqr: rows")
    p.add_argument("--bc", type=int, default=512)
    p.add_argument("--c", type=int, default=2)
    p.add_argument("--balance", default="tile_cyclic")
    p.add_argument("--no-schur-in-place", action="store_true")
    p.add_argument("--out", default=None, help="write the markdown artifact here")
    args = p.parse_args(argv)

    if args.alg == "cacqr":
        n = args.n or 1024
        grid, cfg, fn, shape = build_cacqr(args.m, n, min(args.bc, n // 2))
        print(f"# grid {grid} over deviceless v5e-8 topology; m={args.m} n={n}")
        useful = 2.0 * args.m * n * n * cfg.num_iter
        proj = cost_projection(grid, fn, shape, n, useful_flops=useful)
        return _run_aot(args, grid, cfg, fn, shape, proj, n)

    args.n = args.n or 65536
    grid, cfg, fn, shape = build(
        args.n, args.bc, args.c, args.balance, not args.no_schur_in_place
    )
    print(f"# grid {grid} over deviceless v5e-8 topology; n={args.n} bc={args.bc}")

    proj = cost_projection(grid, fn, shape, args.n)
    print("# cost projection:", json.dumps(proj))

    return _run_cholinv_tail(args, grid, cfg, fn, shape, proj)


def _run_aot(args, grid, cfg, fn, shape, proj, n):
    """Compile the cacqr 8-chip program and write its witness artifact."""
    print("# cost projection:", json.dumps(proj))
    mem, census = _compile_and_measure(fn, shape)
    rec = {
        "metric": "aot_v5e8_cacqr",
        "m": args.m, "n": n, "grid": repr(grid), "regime": cfg.regime,
        "per_chip": mem, "collectives": census, "projection": proj,
    }
    print(json.dumps(rec))
    if args.out:
        hbm = HBM_V5E
        with open(args.out, "w") as f:
            f.write(
                f"""# CQR2 {args.m}x{n} on v5e-8 — AOT-compiled witness (round 4)

BASELINE.md's QR north-star row ("2M x 1024, 8 ranks") cannot be
*executed* on this one-chip rig; the single-chip one-shot row
(160.0-160.5 TF/s, docs/BENCH_SUITE_v5e.md) bounds the kernels, and
this artifact witnesses the DISTRIBUTED program: the full 8-chip CQR2,
compiled by the real XLA:TPU toolchain against a deviceless v5e-8
topology, with XLA's per-chip memory analysis and the emitted
collective schedule.

Reproduce: `python -m capital_tpu.bench.aot65536 --alg cacqr --out {args.out}`

## Program

CholeskyQR2, X {args.m} x {n} bf16 row-sharded over {grid!r} (the flat
8-rank topology the reference's cacqr tree runs on, cacqr.hpp:103),
regime='1d', num_iter=2.

## Per-chip memory (XLA buffer assignment, bytes are PER CHIP)

{_mem_table(mem, "X block", "Q block, R")}

Peak = {100 * mem['peak_memory_bytes'] / hbm:.0f}% of a v5e chip's
15.75 GB XLA byte limit — the 8-chip row fits with room to spare (the
single-chip run needed the one-shot regen protocol precisely because
~4 Q-sized buffers did NOT fit one chip).

## Collective schedule (compiled HLO census, per-step)

```json
{json.dumps(census, indent=2)}
```

The all-reduces are the gram-tree merges (the reference's
MPI_Allreduce over the flat communicator, cacqr.hpp:118-131); Q stays
row-local end to end.

## Cost-model projection (measured single-chip constants)

```json
{json.dumps(proj, indent=2)}
```

The program is the PER-SHARD FUSED pipeline (round 5, VERDICT r4 #2):
every chip runs the Mosaic tall-pass kernels on its own m/8 rows
inside one shard_map — Mosaic custom calls cannot be GSPMD-partitioned
(the round-4 AOT finding), but under shard_map's manual partitioning
they compile, and this artifact IS that compile certificate.  The
projection prices the emitted schedule's executed flops (the fused
(g+1)/2g column-split saving on every chip) with the measured
single-chip sustained band; round 4's unfused projection was
96.3-105.9 TF/s/chip — the per-shard kernels close the gap to the
single-chip one-shot row (160 TF/s).
"""
            )
        print(f"# wrote {args.out}")


def _run_cholinv_tail(args, grid, cfg, fn, shape, proj):
    mem, census = _compile_and_measure(fn, shape)
    rec = {
        "metric": "aot_v5e8_cholinv",
        "n": args.n,
        "bc": args.bc,
        "grid": repr(grid),
        "mode": cfg.mode,
        "balance": cfg.balance,
        "schur_in_place": cfg.schur_in_place,
        "per_chip": mem,
        "collectives": census,
        "projection": proj,
    }
    print(json.dumps(rec))
    if args.out:
        hbm = HBM_V5E
        with open(args.out, "w") as f:
            f.write(
                f"""# N=65536 on v5e-8 — AOT-compiled witness (round 4)

BASELINE.md's north star ("Cholesky & QR throughput, N=65536 ... TPU
v5e-8") cannot be *executed* on this rig (one chip behind the axon
tunnel).  This artifact is the strongest producible witness short of
execution: the **full 8-chip program, compiled by the real XLA:TPU
toolchain** against a deviceless v5e-8 topology
(`jax.experimental.topologies.get_topology_desc('v5e:2x4')`), with XLA's
own per-chip memory analysis and the emitted collective schedule.

Reproduce: `python -m capital_tpu.bench.aot65536 --out {args.out}`

## Program

cholinv factor, n={args.n} bf16, grid {grid!r} (2x2 face, c={args.c}
replication — the 8-chip BASELINE topology), mode='explicit' (shard_map
SUMMA schedule), balance='{cfg.balance}', schur_in_place={cfg.schur_in_place},
bc={args.bc}, split=1.  This is the same configuration family the
single-chip flagship runs, distributed.

## Per-chip memory (XLA buffer assignment, bytes are PER CHIP)

{_mem_table(mem, "A block", "R, R⁻¹ blocks")}

Peak = {100 * mem['peak_memory_bytes'] / hbm:.0f}% of a v5e chip's
15.75 GB XLA byte limit — the program **fits**; the single-chip wall
(3 x n² buffers = 25.8 GB at n=65536, docs/PERF.md) falls to the 8-chip
distribution exactly as designed.

## Collective schedule (compiled HLO census, per-step)

```json
{json.dumps(census, indent=2)}
```

The schedule is the explicit-mode SUMMA pipeline: all-gathers ride the
row/column axes (the reference's MPI_Bcast distribute, summa.hpp:185-193),
all-reduces the depth axis (the collect, summa.hpp:236), and
collective-permutes the grid transposes (util.hpp:232-247's
MPI_Sendrecv_replace pairs).

## Cost-model projection (measured single-chip constants)

```json
{json.dumps(proj, indent=2)}
```

Projected step time {proj['step_ms_band'][0]}-{proj['step_ms_band'][1]} ms
-> **{proj['useful_tflops_per_chip_band'][0]}-{proj['useful_tflops_per_chip_band'][1]}
useful TF/s/chip** against the 177.3 TF/s/chip target (90% of v5e bf16
peak).  Constants: 169-186 TF/s sustained executed kernel rate (the
measured flagship band, docs/PERF.md), DeviceSpec ICI bandwidth
(utils/tracing.py — the same constant every cost table uses).  The
projection prices the same schedule family the compiled HLO above emits
(tests/test_collective_audit.py pins emission = cost model on the CPU
mesh).
"""
            )
        print(f"# wrote {args.out}")


if __name__ == "__main__":
    main()
