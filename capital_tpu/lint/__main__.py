"""CLI: ``python -m capital_tpu.lint {program,source} ...``

``program`` builds the flagship targets (cholinv / cacqr / serve buckets),
runs every sanitizer rule, and gates; ``source`` AST-lints a tree.  Both
apply the checked-in baseline (``lint_baseline.jsonl``) unless
``--no-baseline``, can regenerate it with ``--update-baseline``, and append
ONE schema-tagged ``lint:report`` ledger record with ``--ledger`` — the
record ``obs lint-report`` reads with serve-report-style exit semantics.

Exit codes: 0 clean (or only findings below --fail-on), 1 gate failure.

Examples::

    python -m capital_tpu.lint source capital_tpu
    python -m capital_tpu.lint program --platform cpu --ledger lint.jsonl
    python -m capital_tpu.lint source capital_tpu --no-baseline
    python -m capital_tpu.lint source capital_tpu --update-baseline
"""

from __future__ import annotations

import argparse
import sys

from capital_tpu.lint import baseline as baseline_mod
from capital_tpu.lint import rules


def _report(pass_name: str, findings, args) -> rules.Report:
    if args.no_baseline:
        fresh, suppressed, bl_path = list(findings), [], None
    else:
        bl_path = args.baseline
        fresh, suppressed = baseline_mod.apply(
            findings, baseline_mod.load(bl_path))
    return rules.Report(pass_name=pass_name, fresh=fresh,
                        suppressed=suppressed, baseline_path=bl_path)


def _finish(pass_name: str, findings, args) -> int:
    if args.update_baseline:
        n = baseline_mod.write(args.baseline, findings)
        print(f"# wrote {n} baseline record(s) to {args.baseline}")
        return 0
    rep = _report(pass_name, findings, args)
    for f in rules.sort_findings(rep.fresh):
        print(f.render())
    counts = rep.counts()
    ok = rep.ok(args.fail_on)
    print(
        f"# lint {pass_name}: {counts['error']} error(s), "
        f"{counts['warn']} warn(s), {counts['info']} info, "
        f"{len(rep.suppressed)} baseline-suppressed "
        f"[fail-on={args.fail_on}] -> {'OK' if ok else 'FAIL'}"
    )
    if args.ledger:
        from capital_tpu.obs import ledger

        ledger.append(args.ledger, ledger.record(
            "lint:report", ledger.manifest(),
            lint_report=rep.block(args.fail_on),
        ))
    return 0 if ok else 1


def _program(args) -> int:
    import jax

    if args.platform:
        jax.config.update("jax_platforms", args.platform)
    from capital_tpu.lint import program, targets

    try:
        tgts = targets.flagship_targets(args.targets or None)
    except ValueError as e:
        raise SystemExit(str(e))
    findings = []
    for tgt in tgts:
        print(f"# sanitizing {tgt.target} "
              f"(donate={tgt.donate_argnums or '()'})")
        findings.extend(program.sanitize(
            tgt, tol_ratio=args.tol_ratio, slack=args.slack,
            flops_tol_ratio=args.flops_tol,
            compile_program=not args.no_compile,
        ))
    return _finish("program", findings, args)


def _source(args) -> int:
    from capital_tpu.lint import source

    findings = []
    for path in args.paths or ["capital_tpu"]:
        findings.extend(source.lint_tree(path))
    return _finish("source", findings, args)


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="capital_tpu.lint")
    sub = p.add_subparsers(dest="cmd", required=True)

    def common(sp):
        sp.add_argument("--fail-on", default="error",
                        choices=["warn", "error"],
                        help="lowest severity that fails the gate")
        sp.add_argument("--baseline", default=baseline_mod.DEFAULT_PATH,
                        help="suppression file (JSONL of fingerprints)")
        sp.add_argument("--no-baseline", action="store_true",
                        help="ignore the baseline: report the full debt")
        sp.add_argument("--update-baseline", action="store_true",
                        help="rewrite the baseline from current findings "
                             "and exit 0")
        sp.add_argument("--ledger", default=None,
                        help="append one lint:report record to this JSONL "
                             "ledger")

    g = sub.add_parser("program",
                       help="jaxpr/HLO sanitizer over flagship entry points")
    g.add_argument("targets", nargs="*",
                   help="target families: cholinv cacqr serve "
                        "(default: all)")
    g.add_argument("--platform", default=None,
                   help="jax platform override (e.g. cpu for the CI gate)")
    g.add_argument("--tol-ratio", type=float, default=4.0,
                   help="collective-budget per-phase compiled/model ratio")
    g.add_argument("--slack", type=int, default=8,
                   help="collective-budget absolute per-phase allowance")
    g.add_argument("--flops-tol", type=float, default=2.0,
                   help="collective-budget whole-program flops ratio")
    g.add_argument("--no-compile", action="store_true",
                   help="trace-side rules only (skip donation + "
                        "collective-budget)")
    common(g)
    g.set_defaults(fn=_program)

    s = sub.add_parser("source", help="AST lint over source trees")
    s.add_argument("paths", nargs="*",
                   help="files or directories (default: capital_tpu)")
    common(s)
    s.set_defaults(fn=_source)
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
