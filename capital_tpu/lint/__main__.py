"""CLI: ``python -m capital_tpu.lint {program,source,concurrency} ...``

``program`` builds the flagship targets (cholinv / cacqr / serve buckets),
runs every sanitizer rule, and gates; ``source`` AST-lints a tree;
``concurrency`` runs the serve-plane concurrency sanitizer — the
guarded-by/lock-order static pass (lint/concurrency.py), the seeded
interleaving explorer (lint/schedule.py), and a self-check against the
committed broken fixture that proves the gate is alive.  All apply the
checked-in baseline (``lint_baseline.jsonl``) unless ``--no-baseline``,
can regenerate it with ``--update-baseline``, and append ONE
schema-tagged ``lint:report`` ledger record with ``--ledger`` — the
record ``obs lint-report`` reads with serve-report-style exit semantics.

Exit codes: 0 clean (or only findings below --fail-on), 1 gate failure,
2 malformed invocation (bad scenario name, non-positive --schedules;
argparse errors exit 2 as well).

Examples::

    python -m capital_tpu.lint source capital_tpu
    python -m capital_tpu.lint program --platform cpu --ledger lint.jsonl
    python -m capital_tpu.lint source capital_tpu --no-baseline
    python -m capital_tpu.lint concurrency --schedules 200 --ledger lint.jsonl
    python -m capital_tpu.lint concurrency --static-only capital_tpu/serve
"""

from __future__ import annotations

import argparse
import sys

from capital_tpu.lint import baseline as baseline_mod
from capital_tpu.lint import rules


def _report(pass_name: str, findings, args) -> rules.Report:
    if args.no_baseline:
        fresh, suppressed, bl_path = list(findings), [], None
    else:
        bl_path = args.baseline
        fresh, suppressed = baseline_mod.apply(
            findings, baseline_mod.load(bl_path))
    return rules.Report(pass_name=pass_name, fresh=fresh,
                        suppressed=suppressed, baseline_path=bl_path)


def _finish(pass_name: str, findings, args) -> int:
    if args.update_baseline:
        n = baseline_mod.write(args.baseline, findings)
        print(f"# wrote {n} baseline record(s) to {args.baseline}")
        return 0
    rep = _report(pass_name, findings, args)
    for f in rules.sort_findings(rep.fresh):
        print(f.render())
    counts = rep.counts()
    ok = rep.ok(args.fail_on)
    print(
        f"# lint {pass_name}: {counts['error']} error(s), "
        f"{counts['warn']} warn(s), {counts['info']} info, "
        f"{len(rep.suppressed)} baseline-suppressed "
        f"[fail-on={args.fail_on}] -> {'OK' if ok else 'FAIL'}"
    )
    if args.ledger:
        from capital_tpu.obs import ledger

        ledger.append(args.ledger, ledger.record(
            "lint:report", ledger.manifest(),
            lint_report=rep.block(args.fail_on),
        ))
    return 0 if ok else 1


def _program(args) -> int:
    import jax

    if args.platform:
        jax.config.update("jax_platforms", args.platform)
    from capital_tpu.lint import program, targets

    try:
        tgts = targets.flagship_targets(args.targets or None)
    except ValueError as e:
        raise SystemExit(str(e))
    findings = []
    for tgt in tgts:
        print(f"# sanitizing {tgt.target} "
              f"(donate={tgt.donate_argnums or '()'})")
        findings.extend(program.sanitize(
            tgt, tol_ratio=args.tol_ratio, slack=args.slack,
            flops_tol_ratio=args.flops_tol,
            compile_program=not args.no_compile,
        ))
    return _finish("program", findings, args)


def _source(args) -> int:
    from capital_tpu.lint import source

    findings = []
    for path in args.paths or ["capital_tpu"]:
        findings.extend(source.lint_tree(path))
    return _finish("source", findings, args)


def _fixture_path() -> str:
    """tests/fixtures/concurrency_fault.py, located relative to the
    package so the self-check works from any cwd inside a checkout."""
    import os

    import capital_tpu

    root = os.path.dirname(os.path.dirname(os.path.abspath(
        capital_tpu.__file__)))
    return os.path.join(root, "tests", "fixtures", "concurrency_fault.py")


def _self_check(args) -> list:
    """Dead-gate discipline: the sanitizer must flag the committed
    broken fixture on BOTH layers, every run.  A sanitizer that stops
    flagging it gets a loud ``self-check-dead`` error, not a green."""
    import importlib.util
    import os

    from capital_tpu.lint import concurrency, schedule

    fix = _fixture_path()
    if not os.path.exists(fix):
        return [rules.make(
            "self-check-dead", rules.ERROR, fix,
            "seeded-fault fixture missing — the gate cannot prove it is "
            "alive (restore tests/fixtures/concurrency_fault.py)")]
    out = []
    static = concurrency.lint_concurrency_source(fix)
    got = {f.rule for f in static}
    for want in (concurrency.GUARDED_BY, concurrency.LOCK_ORDER_CYCLE):
        if want not in got:
            out.append(rules.make(
                "self-check-dead", rules.ERROR, fix,
                f"static layer no longer emits {want!r} on the seeded "
                f"fault (got {sorted(got) or 'nothing'}) — the rule is "
                "dead"))
    spec = importlib.util.spec_from_file_location("concurrency_fault", fix)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    failing, runs = schedule.explore(
        schedule.fault_scenario(mod), min(args.schedules, 50),
        seed=args.seed)
    if failing is None or not failing.trace:
        out.append(rules.make(
            "self-check-dead", rules.ERROR, fix,
            f"interleaving explorer swept {runs} schedules without "
            "reproducing the seeded lost update — the explorer is dead"))
    if not out:
        out.append(rules.make(
            "self-check", rules.INFO, fix,
            "seeded fault flagged on both layers "
            f"({len(static)} static finding(s); lost update reproduced "
            f"in {runs} schedule(s), minimal trace {len(failing.trace)} "
            "step(s))"))
    return out


def _concurrency(args) -> int:
    from capital_tpu.lint import concurrency, schedule

    if args.schedules < 1:
        print("--schedules must be >= 1", file=sys.stderr)
        return 2
    findings = []
    if not args.dynamic_only:
        findings.extend(concurrency.lint_tree(args.paths or None))
    if not args.static_only:
        scenarios = schedule.SCENARIOS
        if args.scenario:
            byname = {s.name: s for s in schedule.SCENARIOS}
            unknown = [n for n in args.scenario if n not in byname]
            if unknown:
                print(f"unknown scenario(s) {unknown}; known: "
                      f"{sorted(byname)}", file=sys.stderr)
                return 2
            scenarios = tuple(byname[n] for n in args.scenario)
        print(f"# exploring {len(scenarios)} scenario(s) x "
              f"{args.schedules} seeded schedule(s)")
        findings.extend(schedule.lint_schedules(
            args.schedules, seed=args.seed, scenarios=scenarios))
    if not args.no_self_check:
        findings.extend(_self_check(args))
    return _finish("concurrency", findings, args)


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="capital_tpu.lint")
    sub = p.add_subparsers(dest="cmd", required=True)

    def common(sp):
        sp.add_argument("--fail-on", default="error",
                        choices=["warn", "error"],
                        help="lowest severity that fails the gate")
        sp.add_argument("--baseline", default=baseline_mod.DEFAULT_PATH,
                        help="suppression file (JSONL of fingerprints)")
        sp.add_argument("--no-baseline", action="store_true",
                        help="ignore the baseline: report the full debt")
        sp.add_argument("--update-baseline", action="store_true",
                        help="rewrite the baseline from current findings "
                             "and exit 0")
        sp.add_argument("--ledger", default=None,
                        help="append one lint:report record to this JSONL "
                             "ledger")

    g = sub.add_parser("program",
                       help="jaxpr/HLO sanitizer over flagship entry points")
    g.add_argument("targets", nargs="*",
                   help="target families: cholinv cacqr serve "
                        "(default: all)")
    g.add_argument("--platform", default=None,
                   help="jax platform override (e.g. cpu for the CI gate)")
    g.add_argument("--tol-ratio", type=float, default=4.0,
                   help="collective-budget per-phase compiled/model ratio")
    g.add_argument("--slack", type=int, default=8,
                   help="collective-budget absolute per-phase allowance")
    g.add_argument("--flops-tol", type=float, default=2.0,
                   help="collective-budget whole-program flops ratio")
    g.add_argument("--no-compile", action="store_true",
                   help="trace-side rules only (skip donation + "
                        "collective-budget)")
    common(g)
    g.set_defaults(fn=_program)

    s = sub.add_parser("source", help="AST lint over source trees")
    s.add_argument("paths", nargs="*",
                   help="files or directories (default: capital_tpu)")
    common(s)
    s.set_defaults(fn=_source)

    c = sub.add_parser(
        "concurrency",
        help="serve-plane concurrency sanitizer: guarded-by lint, "
             "lock-order graph, seeded interleaving explorer")
    c.add_argument("paths", nargs="*",
                   help="files/dirs for the static layer (default: "
                        "capital_tpu/serve + obs/spans.py)")
    c.add_argument("--static-only", action="store_true",
                   help="skip the interleaving explorer")
    c.add_argument("--dynamic-only", action="store_true",
                   help="skip the static guarded-by/lock-order pass")
    c.add_argument("--schedules", type=int, default=200,
                   help="seeded schedules per scenario (default 200)")
    c.add_argument("--seed", type=int, default=0,
                   help="base seed for the schedule sweep")
    c.add_argument("--scenario", action="append", default=None,
                   help="run only this scenario (repeatable)")
    c.add_argument("--no-self-check", action="store_true",
                   help="skip the seeded-fault dead-gate self-check")
    common(c)
    c.set_defaults(fn=_concurrency)
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
