"""The serve-plane invariant registry: the formal ledger identities.

Every stats block the serve tier exports carries internal identities the
tests have so far asserted only at the END of whichever interleavings
pytest happened to produce — the router's no-drop identity, the
FactorCache byte ledger, the window coherence sum, the session manager's
miss/eviction pairing.  This module states each identity ONCE, as a
checkable function over the exported block, so three consumers share one
definition:

* the deterministic interleaving explorer (`lint/schedule.py`) checks
  every registered invariant after every scheduling step of every
  scripted scenario — an invariant that only holds at quiescence but
  breaks mid-schedule is exactly the bug class the explorer exists for;
* `tests/test_concurrency.py` unit-tests each identity against both the
  real objects and doctored blocks;
* humans read the registry as the serve tier's concurrency contract
  (docs/SERVING.md "The locking model").

Each check takes the SAME dict the production code already exports
(`Router.counters()`, `FactorCache.stats()`, a closed `serve:window`
block, `SessionManager.stats()`) — no shadow state, so the invariant can
never drift from what the ledger records actually claim.  A check
returns None when the identity holds and a human-readable violation
string when it does not (the obs.ledger validator convention).

Host-only module: pure stdlib, imports nothing from serve/ — the
explorer hands it exported dicts, never live objects.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Optional

#: Subject keys: the explorer probes map subject -> exported block.
ROUTER = "router"
FACTOR_CACHE = "factor_cache"
SERVE_WINDOW = "serve_window"
SESSIONS = "sessions"

SUBJECTS = (ROUTER, FACTOR_CACHE, SERVE_WINDOW, SESSIONS)


@dataclasses.dataclass(frozen=True)
class Invariant:
    """One formal identity over one exported stats block."""

    name: str
    subject: str
    description: str
    check: Callable[[dict], Optional[str]]

    def __post_init__(self):
        if self.subject not in SUBJECTS:
            raise ValueError(
                f"unknown invariant subject {self.subject!r}; "
                f"use one of {SUBJECTS}")


def _router_no_drop(c: dict) -> Optional[str]:
    """completed + parked + outstanding == dispatched (distinct requests).

    The router's whole fault story is this identity: a result lands
    (completed), waits for a healthy replica (parked), or is in flight on
    one (outstanding) — never silently gone.  `dispatched` counts
    DISTINCT requests; re-sends ride `redispatched` and crash-race second
    answers ride `duplicates`, so neither perturbs the sum."""
    outstanding = sum(per["outstanding"]
                      for per in c.get("per_replica", {}).values())
    lhs = c["completed"] + c["parked"] + outstanding
    if lhs != c["dispatched"]:
        return (f"no-drop identity broken: completed={c['completed']} + "
                f"parked={c['parked']} + outstanding={outstanding} = {lhs} "
                f"!= dispatched={c['dispatched']}")
    return None


def _router_counter_sanity(c: dict) -> Optional[str]:
    """All router counters are non-negative and per-replica completions
    never exceed per-replica dispatches (first-result-wins accounting)."""
    for k in ("dispatched", "completed", "redispatched", "duplicates",
              "failed_replicas", "parked"):
        if c[k] < 0:
            return f"router counter {k}={c[k]} went negative"
    for rid, per in c.get("per_replica", {}).items():
        if per["completed"] > per["dispatched"]:
            return (f"replica {rid!r} completed {per['completed']} > "
                    f"dispatched {per['dispatched']}")
    return None


def _cache_byte_ledger(s: dict) -> Optional[str]:
    """The per-entry byte ledger sums to the pool total, and the pool
    respects the budget except for the single-oversized-entry carve-out
    (put() keeps the newest entry even when it alone exceeds the
    budget)."""
    entry_sum = sum(s["entry_bytes"].values())
    if entry_sum != s["bytes"]:
        return (f"entry_bytes ledger sums to {entry_sum} but the pool "
                f"reports bytes={s['bytes']}")
    if len(s["entry_bytes"]) != s["entries"]:
        return (f"entry_bytes lists {len(s['entry_bytes'])} tokens but "
                f"entries={s['entries']}")
    if s["bytes"] > s["budget_bytes"] and s["entries"] > 1:
        return (f"pool holds {s['bytes']} bytes > budget "
                f"{s['budget_bytes']} with {s['entries']} entries — "
                "eviction must run until one entry remains")
    return None


def _cache_counter_conservation(s: dict) -> Optional[str]:
    """Counter conservation: every resident entry was installed and not
    yet evicted or released (overwrites re-install without adding an
    entry, hence the inequality), and the eviction-age histogram counts
    exactly the evictions."""
    for k in ("hits", "misses", "evictions", "installs", "released",
              "entries"):
        if s[k] < 0:
            return f"cache counter {k}={s[k]} went negative"
    if s["entries"] > s["installs"] - s["evictions"] - s["released"]:
        return (f"entries={s['entries']} exceeds installs="
                f"{s['installs']} - evictions={s['evictions']} - "
                f"released={s['released']} — an entry appeared without "
                "an install, or an eviction went uncounted")
    hist_total = sum(s["eviction_age_hist"].values())
    if hist_total != s["evictions"]:
        return (f"eviction_age_hist counts {hist_total} evictions but "
                f"the counter says {s['evictions']}")
    return None


def _window_coherence(w: dict) -> Optional[str]:
    """ok + failed + shed == requests, and the latency histogram covers
    exactly the requests that ran (shed requests never ran, so they
    carry no latency sample)."""
    total = w["ok"] + w["failed"] + w["shed"]
    if total != w["requests"]:
        return (f"window outcome split ok={w['ok']} + failed={w['failed']} "
                f"+ shed={w['shed']} = {total} != requests={w['requests']}")
    ran = w["ok"] + w["failed"]
    hist_total = sum(w["hist_ms"]["counts"])
    if hist_total != ran:
        return (f"latency histogram counts {hist_total} samples but "
                f"ok+failed={ran} requests ran")
    if w["sampled"] > ran:
        return (f"reservoir reports {w['sampled']} samples > {ran} "
                "requests that ran")
    return None


def _session_ledger(s: dict) -> Optional[str]:
    """misses == evicted_failures (the only miss is an evicted factor),
    reseeds <= opens (every reseed IS an open), hits == appends + solves
    + contracts (each resident-op success counts exactly one hit), and
    the window can't drop more blocks than were ever appended."""
    if s["misses"] != s["evicted_failures"]:
        return (f"misses={s['misses']} != evicted_failures="
                f"{s['evicted_failures']} — a miss that wasn't an "
                "eviction (or an uncounted eviction)")
    if s["reseeds"] > s["opens"]:
        return f"reseeds={s['reseeds']} > opens={s['opens']}"
    resident_ok = s["appends"] + s["solves"] + s["contracts"]
    if s["hits"] != resident_ok:
        return (f"hits={s['hits']} != appends={s['appends']} + solves="
                f"{s['solves']} + contracts={s['contracts']} = "
                f"{resident_ok}")
    if s["blocks_dropped"] > s["blocks_appended"]:
        return (f"blocks_dropped={s['blocks_dropped']} > blocks_appended="
                f"{s['blocks_appended']}")
    return None


#: The registry.  Order is stable (reports render in this order); names
#: are the rule-message vocabulary the explorer and the docs share.
REGISTRY: tuple[Invariant, ...] = (
    Invariant("router-no-drop", ROUTER,
              "completed + parked + outstanding == dispatched",
              _router_no_drop),
    Invariant("router-counter-sanity", ROUTER,
              "router counters non-negative; per-replica completed <= "
              "dispatched", _router_counter_sanity),
    Invariant("cache-byte-ledger", FACTOR_CACHE,
              "sum(entry_bytes) == bytes; bytes <= budget unless a single "
              "oversized entry", _cache_byte_ledger),
    Invariant("cache-counter-conservation", FACTOR_CACHE,
              "entries <= installs - evictions - released; eviction-age "
              "histogram counts == evictions", _cache_counter_conservation),
    Invariant("window-coherence", SERVE_WINDOW,
              "ok + failed + shed == requests; histogram covers exactly "
              "the ran population", _window_coherence),
    Invariant("session-ledger", SESSIONS,
              "misses == evicted_failures; reseeds <= opens; hits == "
              "appends + solves + contracts", _session_ledger),
)


def by_subject(subject: str) -> tuple[Invariant, ...]:
    return tuple(inv for inv in REGISTRY if inv.subject == subject)


def check(states: dict[str, dict]) -> list[str]:
    """Run every registered invariant whose subject appears in `states`
    (subject key -> exported block).  Returns violation strings prefixed
    with the invariant name, [] when everything holds.  A check that
    cannot even read its block (missing key) is itself a violation —
    a malformed block must never read as a passing one."""
    violations: list[str] = []
    for inv in REGISTRY:
        block = states.get(inv.subject)
        if block is None:
            continue
        try:
            msg = inv.check(block)
        except (KeyError, TypeError) as e:
            msg = f"block malformed for this invariant ({e!r})"
        if msg is not None:
            violations.append(f"{inv.name}: {msg}")
    return violations
