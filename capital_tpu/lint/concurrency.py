"""The concurrency sanitizer, static layer: guarded-by contracts and the
lock-acquisition graph over the serve host plane.

The serve tier's threading model (docs/SERVING.md "The locking model")
is simple by design — ONE real lock (the Router's RLock) plus a fleet of
single-owner classes that ride one dispatch loop — but nothing enforced
it: a new method touching ``router._parked`` outside the lock, or a
second lock acquired in the wrong order, would compile, pass the lucky
interleavings pytest produces, and ship.  This pass makes the model a
checked contract:

* **guarded-by** — every shared attribute of the registered serve
  classes carries a ``# guarded-by: <guard>`` annotation at its
  ``__init__`` assignment; the pass errors on any attribute access that
  violates the guard's discipline (and on registered classes whose
  annotations are not exhaustive — a contract with holes is not a
  contract).
* **lock-order-cycle** — the static lock-acquisition graph (lexical
  ``with``-nesting plus the intra-class call graph) must be acyclic; a
  cycle is a potential deadlock no test will reliably reproduce.
* **blocking-under-lock** — a blocking call (pipe/queue roundtrips,
  ``Event.wait``, ``join``, ``sleep``) made while lexically holding a
  lock stalls every thread contending for it; the three deliberate
  router roundtrip sites carry visible inline suppressions with reasons
  (same discipline as ``# lint: allow-broad-except``).

Annotation grammar (trailing comment on the ``__init__`` assignment)::

    self._states = {}          # guarded-by: self._lock
    self._lock = RLock()       # guarded-by: <lock>          (a guard itself)
    self.cfg = cfg             # guarded-by: <frozen>        (set once)
    self._entries = {}         # guarded-by: <owner-thread>  (single owner)
    self._stop = Event()       # guarded-by: <self-sync>     (primitive)
    self.attempts = 0          # guarded-by: <router-lock>   (owner's lock)
    self.response = None       # guarded-by: <published-by: self._event>

Enforcement per guard: ``self.<lock>`` — every access outside
``__init__`` must sit lexically inside ``with self.<lock>:`` or in a
method marked ``# lock-held: self.<lock>`` on its ``def`` line;
``<frozen>`` — no writes outside ``__init__``; the contract guards
(``<owner-thread>``, ``<self-sync>``, ``<router-lock>``,
``<published-by: ...>``) document an ownership discipline the dynamic
layer (lint/schedule.py) exercises instead of a lexical scope.  Methods
marked lock-held are themselves checked at their call sites: calling one
without holding its lock is the same bug as touching the attribute.

Pure stdlib ``ast`` + source-line comment scans — nothing is imported,
so the pass lints the deliberately broken self-check fixture safely.
Findings reuse the PR 5 rules engine verbatim (lint/rules.py:
fingerprints, severities, baseline, Report.block).
"""

from __future__ import annotations

import ast
import os
import re
from typing import Optional

from capital_tpu.lint import rules

GUARDED_BY = "guarded-by"
GUARDED_BY_MISSING = "guarded-by-missing"
GUARDED_BY_GRAMMAR = "guarded-by-grammar"
GUARDED_BY_FROZEN = "guarded-by-frozen"
LOCK_HELD_CALL = "lock-held-call"
LOCK_ORDER_CYCLE = "lock-order-cycle"
BLOCKING_UNDER_LOCK = "blocking-under-lock"

CONCURRENCY_RULES = (
    GUARDED_BY, GUARDED_BY_MISSING, GUARDED_BY_GRAMMAR, GUARDED_BY_FROZEN,
    LOCK_HELD_CALL, LOCK_ORDER_CYCLE, BLOCKING_UNDER_LOCK,
)

#: Classes whose annotation coverage must be exhaustive: the shared state
#: of the serve host plane (path suffix, class name).  Any OTHER class
#: that carries at least one guarded-by annotation opts into the same
#: exhaustiveness contract (the self-check fixture does).
REGISTERED_CLASSES = frozenset({
    (os.path.join("serve", "router.py"), "Router"),
    (os.path.join("serve", "router.py"), "RouterTicket"),
    (os.path.join("serve", "router.py"), "_ReplicaState"),
    (os.path.join("serve", "scheduler.py"), "Scheduler"),
    (os.path.join("serve", "factorcache.py"), "FactorCache"),
    (os.path.join("serve", "sessions.py"), "SessionManager"),
    (os.path.join("serve", "telemetry.py"), "WindowAggregator"),
    (os.path.join("serve", "telemetry.py"), "_Window"),
    (os.path.join("serve", "engine.py"), "SolveEngine"),
    (os.path.join("obs", "spans.py"), "TraceLog"),
    (os.path.join("obs", "spans.py"), "RequestTrace"),
})

#: Contract guards: documented ownership disciplines with no lexical
#: scope to check (the dynamic layer exercises them instead).
CONTRACT_GUARDS = ("<owner-thread>", "<self-sync>", "<router-lock>",
                   "<frozen>", "<lock>")

#: Call names that block the calling thread: sync transport roundtrips
#: (drain / warmup / request_stats / ping / stop ride _roundtrip),
#: primitive waits, thread joins, sleeps.  Deliberate sites suppress
#: inline with a reason.
BLOCKING_NAMES = frozenset({
    "wait", "join", "sleep", "drain", "warmup", "request_stats", "ping",
    "stop", "_roundtrip", "_await", "recv",
})

#: Inline suppression markers (on the offending line, with a reason).
_SUPPRESS_MARKERS = ("noqa", "lint: allow-blocking-under-lock",
                     "lint: allow-unguarded")

_ANNOT_RE = re.compile(r"guarded-by:\s*(<[^>]+>|self\.\w+)")
_LOCK_HELD_RE = re.compile(r"lock-held:\s*(self\.\w+)")


def _attr_chain(node: ast.AST) -> list[str]:
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return list(reversed(parts))
    return []


def _self_attr(node: ast.AST) -> Optional[str]:
    """'X' when node is exactly ``self.X``; None otherwise."""
    if isinstance(node, ast.Attribute) and \
            isinstance(node.value, ast.Name) and node.value.id == "self":
        return node.attr
    return None


def _is_lock_ctor(node: ast.AST) -> bool:
    """True for ``threading.Lock()`` / ``threading.RLock()`` / bare
    ``Lock()`` / ``RLock()`` value expressions."""
    if not isinstance(node, ast.Call):
        return False
    chain = _attr_chain(node.func)
    return bool(chain) and chain[-1] in ("Lock", "RLock")


class _ClassInfo:
    """Everything the checks need about one class: annotations, lock
    attributes, lock-held method markers, and the per-method acquisition
    facts feeding the global lock graph."""

    def __init__(self, name: str):
        self.name = name
        self.guards: dict[str, str] = {}       # attr -> guard string
        self.annot_lines: dict[str, int] = {}  # attr -> annotation lineno
        self.init_attrs: dict[str, int] = {}   # __init__ self.X -> lineno
        self.locks: set[str] = set()           # attrs that ARE locks
        self.lock_held: dict[str, str] = {}    # method -> lock attr it needs
        # method -> set of lock attrs it acquires directly (lexically)
        self.direct_acquires: dict[str, set[str]] = {}
        # method -> set of self-method names it calls
        self.self_calls: dict[str, set[str]] = {}
        # (held lock attr, acquired-or-called, lineno) acquisition events;
        # 'acquired' entries are lock attrs, 'called' entries method names
        self.nested_acquires: list[tuple[str, str, int]] = []
        self.calls_under_lock: list[tuple[str, str, int]] = []

    def lock_id(self, attr: str) -> str:
        return f"{self.name}.{attr}"


def _collect_class(cls: ast.ClassDef, lines: list[str]) -> _ClassInfo:
    info = _ClassInfo(cls.name)

    def line(n: int) -> str:
        return lines[n - 1] if 0 < n <= len(lines) else ""

    for item in cls.body:
        if not isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        m = _LOCK_HELD_RE.search(line(item.lineno))
        if m:
            info.lock_held[item.name] = m.group(1).split(".", 1)[1]
        if item.name != "__init__":
            continue
        for node in ast.walk(item):
            targets: list[ast.AST] = []
            value: Optional[ast.AST] = None
            if isinstance(node, ast.Assign):
                targets, value = node.targets, node.value
            elif isinstance(node, ast.AnnAssign) and node.value is not None:
                targets, value = [node.target], node.value
            for tgt in targets:
                attr = _self_attr(tgt)
                if attr is None:
                    continue
                info.init_attrs.setdefault(attr, node.lineno)
                m = _ANNOT_RE.search(line(node.lineno))
                if m:
                    info.guards.setdefault(attr, m.group(1))
                    info.annot_lines.setdefault(attr, node.lineno)
                if value is not None and _is_lock_ctor(value):
                    info.locks.add(attr)
    for attr, guard in info.guards.items():
        if guard == "<lock>":
            info.locks.add(attr)
    return info


def _registered(path: str, info: _ClassInfo) -> bool:
    norm = os.path.normpath(path)
    if any(norm.endswith(sfx) and cname == info.name
           for sfx, cname in REGISTERED_CLASSES):
        return True
    return bool(info.guards)


def _check_class(path: str, cls: ast.ClassDef, info: _ClassInfo,
                 lines: list[str], findings: list[rules.Finding], *,
                 exhaustive: bool = True) -> None:
    def line(n: int) -> str:
        return lines[n - 1] if 0 < n <= len(lines) else ""

    def suppressed(n: int) -> bool:
        return any(mk in line(n) for mk in _SUPPRESS_MARKERS)

    # -- annotation exhaustiveness + grammar -------------------------------
    for attr, lineno in sorted(info.init_attrs.items()):
        guard = info.guards.get(attr)
        if guard is None:
            if exhaustive:
                findings.append(rules.make(
                    GUARDED_BY_MISSING, rules.ERROR, path,
                    f"{info.name}.{attr} has no guarded-by annotation — "
                    "the registry must be exhaustive (annotate the "
                    "__init__ assignment: # guarded-by: self.<lock> | "
                    "<frozen> | <owner-thread> | <self-sync> | <lock> "
                    "| ...)",
                    line=lineno,
                ))
            continue
        if guard.startswith("self."):
            lock_attr = guard.split(".", 1)[1]
            if lock_attr not in info.locks:
                findings.append(rules.make(
                    GUARDED_BY_GRAMMAR, rules.ERROR, path,
                    f"{info.name}.{attr} names guard {guard!r} but "
                    f"{info.name}.{lock_attr} is not a lock of this class "
                    "(no Lock()/RLock() assignment or <lock> annotation)",
                    line=info.annot_lines[attr],
                ))
        elif guard not in CONTRACT_GUARDS \
                and not guard.startswith("<published-by:"):
            findings.append(rules.make(
                GUARDED_BY_GRAMMAR, rules.ERROR, path,
                f"{info.name}.{attr} carries unknown guard {guard!r} — "
                f"use self.<lock>, <published-by: ...>, or one of "
                f"{CONTRACT_GUARDS}",
                line=info.annot_lines[attr],
            ))

    lock_guarded = {a: g.split(".", 1)[1] for a, g in info.guards.items()
                    if g.startswith("self.")}
    frozen = {a for a, g in info.guards.items() if g == "<frozen>"}

    # -- per-method coverage walk ------------------------------------------
    for item in cls.body:
        if not isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        method = item.name
        held0 = frozenset(
            {info.lock_held[method]} if method in info.lock_held else ())
        info.direct_acquires.setdefault(method, set())
        info.self_calls.setdefault(method, set())

        def visit(node: ast.AST, held: frozenset, in_closure: bool,
                  method: str = method) -> None:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)) and node is not item:
                # a nested def/lambda runs later, NOT under the lexically
                # enclosing lock (the router's pump-loop closure)
                body = node.body if isinstance(node.body, list) \
                    else [node.body]
                for child in body:
                    visit(child, frozenset(), True)
                return
            if isinstance(node, ast.With):
                acquired = []
                for w in node.items:
                    attr = _self_attr(w.context_expr)
                    if attr is not None and attr in info.locks:
                        acquired.append((attr, w.context_expr.lineno))
                for w in node.items:
                    visit(w.context_expr, held, in_closure)
                for attr, lineno in acquired:
                    for h in held:
                        if h != attr:
                            info.nested_acquires.append((h, attr, lineno))
                    if not in_closure:
                        info.direct_acquires[method].add(attr)
                    held = held | {attr}
                for child in node.body:
                    visit(child, held, in_closure)
                return
            if isinstance(node, ast.Call):
                chain = _attr_chain(node.func)
                callee = _self_attr(node.func)
                if callee is not None and not in_closure:
                    info.self_calls[method].add(callee)
                    for h in held:
                        info.calls_under_lock.append(
                            (h, callee, node.lineno))
                if callee is not None and callee in info.lock_held:
                    need = info.lock_held[callee]
                    if need not in held and not suppressed(node.lineno):
                        findings.append(rules.make(
                            LOCK_HELD_CALL, rules.ERROR, path,
                            f"{info.name}.{method} calls lock-held method "
                            f"{callee}() without holding self.{need}",
                            line=node.lineno,
                        ))
                if held and chain and chain[-1] in BLOCKING_NAMES \
                        and not suppressed(node.lineno):
                    findings.append(rules.make(
                        BLOCKING_UNDER_LOCK, rules.ERROR, path,
                        f"{info.name}.{method} calls blocking "
                        f"`{'.'.join(chain)}` while holding "
                        f"{', '.join(f'self.{h}' for h in sorted(held))} — "
                        "every contending thread stalls for the call's "
                        "full duration (suppress inline with a reason if "
                        "deliberate: # lint: allow-blocking-under-lock)",
                        line=node.lineno,
                    ))
            attr = _self_attr(node)
            if attr is not None and method != "__init__":
                if attr in lock_guarded:
                    need = lock_guarded[attr]
                    if need not in held and not suppressed(node.lineno):
                        rw = ("write" if isinstance(
                            getattr(node, "ctx", None),
                            (ast.Store, ast.Del)) else "read")
                        findings.append(rules.make(
                            GUARDED_BY, rules.ERROR, path,
                            f"{info.name}.{method} {rw}s self.{attr} "
                            f"(guarded-by self.{need}) outside the lock — "
                            f"wrap in `with self.{need}:` or mark the "
                            f"method `# lock-held: self.{need}`",
                            line=node.lineno,
                        ))
                elif attr in frozen and isinstance(
                        getattr(node, "ctx", None), (ast.Store, ast.Del)) \
                        and not suppressed(node.lineno):
                    findings.append(rules.make(
                        GUARDED_BY_FROZEN, rules.ERROR, path,
                        f"{info.name}.{method} writes self.{attr}, "
                        "annotated <frozen> (set once in __init__, "
                        "immutable after publication)",
                        line=node.lineno,
                    ))
            for child in ast.iter_child_nodes(node):
                visit(child, held, in_closure)

        visit(item, held0, False)


def _lock_graph_edges(infos: dict[str, _ClassInfo]
                      ) -> list[tuple[str, str, str, int]]:
    """Directed (held-lock-id, acquired-lock-id, path, lineno) edges:
    lexical nesting plus one level of intra-class call propagation
    (a call made under lock L to a method that eventually acquires M
    adds L -> M)."""
    edges: list[tuple[str, str, str, int]] = []
    for path, info in infos.items():
        # transitive closure of locks each method eventually acquires
        eventual: dict[str, set[str]] = {
            m: set(acq) for m, acq in info.direct_acquires.items()}
        changed = True
        while changed:
            changed = False
            for m, callees in info.self_calls.items():
                for c in callees:
                    extra = eventual.get(c, set()) - eventual.setdefault(
                        m, set())
                    if extra:
                        eventual[m].update(extra)
                        changed = True
        for held, acquired, lineno in info.nested_acquires:
            edges.append((info.lock_id(held), info.lock_id(acquired),
                          path, lineno))
        for held, callee, lineno in info.calls_under_lock:
            for acq in sorted(eventual.get(callee, ())):
                if acq != held:
                    edges.append((info.lock_id(held), info.lock_id(acq),
                                  path, lineno))
    return edges


def _find_cycles(edges: list[tuple[str, str, str, int]]
                 ) -> list[tuple[tuple[str, ...], str, int]]:
    """Canonical cycles in the lock graph: each reported once, rotated to
    start at its lexicographically smallest lock, with a witness site."""
    graph: dict[str, set[str]] = {}
    site: dict[tuple[str, str], tuple[str, int]] = {}
    for a, b, path, lineno in edges:
        graph.setdefault(a, set()).add(b)
        site.setdefault((a, b), (path, lineno))
    cycles: dict[tuple[str, ...], tuple[str, int]] = {}

    def dfs(start: str, node: str, trail: list[str]) -> None:
        for nxt in sorted(graph.get(node, ())):
            if nxt == start:
                cyc = trail + [node]
                i = cyc.index(min(cyc))
                canon = tuple(cyc[i:] + cyc[:i])
                cycles.setdefault(canon, site[(node, start)])
            elif nxt not in trail + [node] and len(trail) < 8:
                dfs(start, nxt, trail + [node])

    for start in sorted(graph):
        dfs(start, start, [])
    return [(c, p, ln) for c, (p, ln) in sorted(cycles.items())]


def lint_concurrency_source(path: str, text: Optional[str] = None,
                            _graph_sink: Optional[dict] = None
                            ) -> list[rules.Finding]:
    """Every per-file concurrency finding (guarded-by family + blocking
    under lock).  Lock-graph facts accumulate into `_graph_sink` when
    given (lint_tree passes one and runs the cycle check globally);
    standalone calls get their cycles checked file-locally."""
    if text is None:
        with open(path) as f:
            text = f.read()
    try:
        tree = ast.parse(text, filename=path)
    except SyntaxError as e:
        return [rules.make(
            "syntax", rules.ERROR, path,
            f"not parseable: {e.msg}", line=e.lineno or 0)]
    lines = text.splitlines()
    findings: list[rules.Finding] = []
    infos: dict[str, _ClassInfo] = {}
    for node in ast.walk(tree):
        if not isinstance(node, ast.ClassDef):
            continue
        info = _collect_class(node, lines)
        registered = _registered(path, info)
        if not registered and not info.locks:
            continue
        # unregistered lock-owning classes still feed the lock graph and
        # the blocking-under-lock check; only the guarded-by family and
        # the exhaustiveness contract are registry-scoped
        _check_class(path, node, info, lines, findings,
                     exhaustive=registered)
        infos[f"{path}::{node.name}"] = info
    if _graph_sink is not None:
        _graph_sink.update(infos)
    else:
        findings.extend(cycle_findings(infos))
    return rules.sort_findings(findings)


def cycle_findings(infos: dict[str, _ClassInfo]) -> list[rules.Finding]:
    """lock-order-cycle findings over an accumulated lock graph (keys are
    'path::Class', values the per-class acquisition facts)."""
    edges: list[tuple[str, str, str, int]] = []
    for key, info in infos.items():
        path = key.split("::", 1)[0]
        edges.extend(_lock_graph_edges({path: info}))
    findings = []
    for cycle, path, lineno in _find_cycles(edges):
        findings.append(rules.make(
            LOCK_ORDER_CYCLE, rules.ERROR, path,
            "lock-acquisition cycle (potential deadlock): "
            + " -> ".join(cycle + (cycle[0],))
            + " — impose one global acquisition order",
            line=lineno,
        ))
    return findings


def default_paths() -> list[str]:
    """The serve host plane: every module under serve/ plus the shared
    span accumulator (obs/spans.py) — paths relative to the cwd when
    possible so fingerprints are stable across checkouts."""
    import capital_tpu

    pkg = os.path.dirname(os.path.abspath(capital_tpu.__file__))
    paths = []
    serve = os.path.join(pkg, "serve")
    for fn in sorted(os.listdir(serve)):
        if fn.endswith(".py"):
            paths.append(os.path.join(serve, fn))
    paths.append(os.path.join(pkg, "obs", "spans.py"))
    out = []
    for p in paths:
        rel = os.path.relpath(p)
        out.append(rel if not rel.startswith("..") else p)
    return out


def lint_tree(paths: Optional[list[str]] = None) -> list[rules.Finding]:
    """The static layer over `paths` (default: the serve plane), with the
    lock-acquisition graph assembled ACROSS files before the cycle
    check — a deadlock between two modules' locks is the case that
    matters for ROADMAP 3's multi-transport fleet."""
    findings: list[rules.Finding] = []
    graph: dict[str, _ClassInfo] = {}
    for path in (paths if paths is not None else default_paths()):
        if os.path.isdir(path):
            for dirpath, dirnames, filenames in os.walk(path):
                dirnames[:] = [d for d in dirnames if d != "__pycache__"]
                for fn in sorted(filenames):
                    if fn.endswith(".py"):
                        findings.extend(lint_concurrency_source(
                            os.path.join(dirpath, fn), _graph_sink=graph))
        else:
            findings.extend(lint_concurrency_source(path,
                                                    _graph_sink=graph))
    findings.extend(cycle_findings(graph))
    return rules.sort_findings(findings)
