"""capital_tpu.lint — jaxpr/HLO program sanitizer and repo source lint.

Two passes over one rules engine (docs/STATIC_ANALYSIS.md):

* ``lint.program`` — trace/compile any model or serve entry point and
  verify the repo's runtime invariants statically: phase coverage, honored
  donation, AOT-cache key hygiene, no host sync in hot paths, no dtype
  drift, collective counts within the obs drift envelope.
* ``lint.source`` — AST rules over the package source: no bare/broad
  excepts, no FLOP-bearing compute outside tracing scopes in
  models/parallel/ops, no unregistered phase-tag literals.

CLI: ``python -m capital_tpu.lint {program,source}`` (``make lint``), with
the checked-in ``lint_baseline.jsonl`` suppressing accepted pre-existing
findings and ``lint:report`` ledger records feeding ``obs lint-report``.
"""

from capital_tpu.lint.rules import (  # noqa: F401
    ERROR, INFO, WARN, Finding, Report, gate, sort_findings, summarize,
)
from capital_tpu.lint import baseline, program, rules, source  # noqa: F401
